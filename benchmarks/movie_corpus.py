"""Deterministic movie-style corpus generator (the 1million analog).

Mirrors the shape of the reference's benchmark dataset
(systest/1million/1million_test.go, benchmarks repo 1million.rdf.gz):
directors direct films, films carry genres and release dates, actors
star in films; names are exact/term-indexed strings.

The generator returns BOTH the RDF stream and a plain-Python graph model,
so conformance goldens are DERIVED independently of the engine
(VERDICT r1 next-round #4: no hand-typed goldens) — any query the suite
runs is answered twice: once by the engine, once by direct dict walks
here, and the two must agree.

Scale knob = target edge count; 1M edges ≈ 30k films / 6k directors /
60k actors at the default fan-outs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Set, Tuple

import numpy as np

GENRES = [
    "Action", "Comedy", "Drama", "Horror", "Romance", "Thriller",
    "Documentary", "Animation", "Crime", "Fantasy", "Mystery", "Western",
]

SCHEMA = """
name: string @index(exact, term) .
initial_release_date: datetime @index(year) .
genre: [uid] @reverse .
director.film: [uid] @reverse @count .
starring: [uid] @reverse .
rating: float @index(float) .
"""


@dataclass
class Corpus:
    # uid maps
    genres: Dict[str, int] = field(default_factory=dict)
    directors: Dict[int, str] = field(default_factory=dict)
    films: Dict[int, str] = field(default_factory=dict)
    actors: Dict[int, str] = field(default_factory=dict)
    # edges
    film_genres: Dict[int, List[int]] = field(default_factory=dict)
    director_films: Dict[int, List[int]] = field(default_factory=dict)
    actor_films: Dict[int, List[int]] = field(default_factory=dict)
    film_year: Dict[int, int] = field(default_factory=dict)
    film_rating: Dict[int, float] = field(default_factory=dict)
    n_edges: int = 0

    # -- derived goldens (independent of the engine) ----------------------

    def films_of_genre(self, genre: str) -> List[int]:
        g = self.genres[genre]
        return sorted(
            f for f, gs in self.film_genres.items() if g in gs
        )

    def directors_of_genre(self, genre: str) -> List[int]:
        """Directors with at least one film in the genre (2-hop)."""
        films = set(self.films_of_genre(genre))
        return sorted(
            d
            for d, fs in self.director_films.items()
            if films.intersection(fs)
        )

    def films_in_year(self, year: int) -> List[int]:
        return sorted(f for f, y in self.film_year.items() if y == year)

    def costars(self, actor_uid: int) -> List[int]:
        """Actors sharing a film with the given actor (2-hop via reverse)."""
        films = set(self.actor_films.get(actor_uid, []))
        out: Set[int] = set()
        for a, fs in self.actor_films.items():
            if a != actor_uid and films.intersection(fs):
                out.add(a)
        return sorted(out)

    def actors_of_director(self, d: int) -> List[int]:
        """3-hop: director -> films -> starring actors."""
        films = set(self.director_films.get(d, []))
        out: Set[int] = set()
        for a, fs in self.actor_films.items():
            if films.intersection(fs):
                out.add(a)
        return sorted(out)

    def genres_by_film_count(self) -> List[tuple]:
        """(genre uid, #films) sorted by count desc then uid."""
        counts = {g: 0 for g in self.genres.values()}
        for gs in self.film_genres.values():
            for g in gs:
                counts[g] += 1
        return sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))

    def prolific_directors(self, min_films: int) -> List[int]:
        return sorted(
            d for d, fs in self.director_films.items() if len(fs) >= min_films
        )

    def top_rated(self, n: int) -> List[int]:
        return [
            f
            for f, _ in sorted(
                self.film_rating.items(), key=lambda kv: (-kv[1], kv[0])
            )[:n]
        ]


def generate(target_edges: int = 1_000_000, seed: int = 42) -> Tuple[Corpus, List[str]]:
    """Returns (corpus model, rdf lines). Edge count ≈ target_edges."""
    rng = np.random.default_rng(seed)
    c = Corpus()
    rdf: List[str] = []
    uid = 0x1000

    def nxt() -> int:
        nonlocal uid
        uid += 1
        return uid

    for g in GENRES:
        u = nxt()
        c.genres[g] = u
        rdf.append(f'<0x{u:x}> <name> "{g}" .')
        c.n_edges += 1

    # fan-outs: each film -> ~2 genres + 1 date + 1 rating + 1 name = ~5
    # each director -> ~5 films; each actor -> ~3 films
    # edges per film ≈ 5 + (1/5 dir name) + 2 starring + ...; solve approx:
    n_films = max(10, target_edges // 9)
    n_directors = max(3, n_films // 5)
    n_actors = max(5, n_films * 2 // 3)

    for i in range(n_directors):
        u = nxt()
        c.directors[u] = f"Director {i}"
        rdf.append(f'<0x{u:x}> <name> "Director {i}" .')
        c.director_films[u] = []
        c.n_edges += 1

    for i in range(n_actors):
        u = nxt()
        c.actors[u] = f"Actor {i}"
        rdf.append(f'<0x{u:x}> <name> "Actor {i}" .')
        c.actor_films[u] = []
        c.n_edges += 1

    dirs = list(c.directors)
    actors = list(c.actors)
    genre_uids = list(c.genres.values())

    for i in range(n_films):
        u = nxt()
        title = f"Film {i} of the {GENRES[i % len(GENRES)]}"
        c.films[u] = title
        rdf.append(f'<0x{u:x}> <name> "{title}" .')
        year = 1950 + int(rng.integers(0, 75))
        c.film_year[u] = year
        rdf.append(
            f'<0x{u:x}> <initial_release_date> '
            f'"{year}-{1 + int(rng.integers(0, 12)):02d}-01" .'
        )
        rating = round(float(rng.uniform(1.0, 10.0)), 2)
        c.film_rating[u] = rating
        rdf.append(f'<0x{u:x}> <rating> "{rating}"^^<xs:float> .')
        c.n_edges += 3
        gs = rng.choice(genre_uids, size=1 + int(rng.integers(0, 2)), replace=False)
        c.film_genres[u] = [int(g) for g in gs]
        for g in gs:
            rdf.append(f"<0x{u:x}> <genre> <0x{int(g):x}> .")
            c.n_edges += 1
        d = int(dirs[int(rng.integers(0, len(dirs)))])
        c.director_films[d].append(u)
        rdf.append(f"<0x{d:x}> <director.film> <0x{u:x}> .")
        c.n_edges += 1
        stars = rng.choice(len(actors), size=2, replace=False)
        for si in stars:
            a = int(actors[int(si)])
            c.actor_films[a].append(u)
            rdf.append(f"<0x{a:x}> <starring> <0x{u:x}> .")
            c.n_edges += 1

    return c, rdf

"""Closed-loop multi-client QPS harness for the serving front.

Models the north-star workload — thousands of concurrent *small*
queries — against one in-process engine: C closed-loop clients each
issue the next query the moment the previous one returns (offered load
rises with C), over a small pool of hot query shapes with rotating
literals (the plan cache's serving regime). Each point reports achieved
QPS, p50/p99 latency of accepted executions, and the serving-front
counters (coalesced tasks, plan-cache hits, sheds, degrades).

Read literals draw from a bounded Zipfian distribution (--zipf-s, 0 =
the old uniform rotation): production traffic from millions of users
repeats a few hot bindings far more than the tail, and only that regime
exercises the plan cache's per-shape variant LRU (uniform rotation over
256 literals blows the 16-variant LRU and pins plan_cache_hit at ~0).

Modes swept per client count:

  batch_off  — BATCH_WINDOW_US=0, ADMISSION off: the pre-serving-front
               path (PR 2/6 per-query machinery only).
  batch_on   — the micro-batcher coalescing cross-query level tasks.
  admission  — batching + admission control with a deliberately small
               in-flight budget, driven PAST saturation: sheds are
               retried client-side with backoff (conn/retry
               .retrying_call); p99 of accepted work must stay bounded
               instead of collapsing with the queue.

Mixed read/write mode (--mix): each client flips a seeded coin per
operation (write ratios 10% and 50%) — reads are the Zipfian hot-shape
stream, writes insert a fresh entity with indexed fields plus a uid
edge into the existing graph (the live-ingest shape: exact + int index
maintenance, a @reverse edge, one commit per txn). Reported per point:
sustainable mutation QPS, write p50/p99, read QPS/percentiles, and the
write-path counters (group_commit batches, sheds). A/B rides
DGRAPH_TPU_GROUP_COMMIT (group_on vs group_off = today's serial
commits); --baseline runs one unmodified-engine mode for the
pre-change capture the ROADMAP requires.

Usage:
  python benchmarks/qps_loadgen.py                 # read sweep -> BENCH_QPS.json
  python benchmarks/qps_loadgen.py --mix           # mixed sweep -> BENCH_QPS.json
  python benchmarks/qps_loadgen.py --mix --baseline  # pre-change capture
  python benchmarks/qps_loadgen.py --sanity        # ~5s smoke (CI gate)
  python benchmarks/qps_loadgen.py --write-sanity  # ~5s write-path smoke
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

# Mixed native/Python thread pools convoy badly at CPython's default
# 5ms GIL switch interval: whichever pool makes more GIL-releasing FFI
# calls (the query side, with its numpy/ctypes kernels) re-queues
# behind a CPU-bound peer at every call and pays the full interval
# each time — measured starving readers to ~1 qps beside one hot
# writer. 1ms keeps both pools live; applied to EVERY mode (and to the
# baseline capture), so no A/B arm is favored.
sys.setswitchinterval(0.001)

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import stamp  # noqa: E402

N_ENTITIES = 4000
HOT_LITERALS = 256  # entity names the clients rotate over


def build_server(memlayer_entries: int = 512, n_entities: int = N_ENTITIES):
    """In-process engine in the at-scale serving regime: the working
    set deliberately EXCEEDS the decoded-list cache (MEMLAYER_ENTRIES),
    so level reads pay real decode work per dispatch — a store serving
    millions of users never has every posting list decoded in RAM. A
    fully cache-resident store makes level reads ~µs and cross-query
    batching rationally a no-op (the behind-running batcher adds no
    idle latency there, but has nothing to win either)."""
    from dgraph_tpu.api.server import Server
    from dgraph_tpu.x import config

    if memlayer_entries:
        config.set_env("MEMLAYER_ENTRIES", memlayer_entries)
    s = Server()
    s.alter(
        "name: string @index(exact) .\n"
        "age: int @index(int) .\n"
        "knows: [uid] @reverse .\n"
        "city: string .\n"
    )
    lines = []
    for u in range(1, n_entities + 1):
        # unique names: each query roots at ONE entity — the small-query
        # serving regime the front exists for (thousands of concurrent
        # point-ish queries, not a handful of giant scans)
        lines.append(f'<{hex(u)}> <name> "user{u}" .')
        lines.append(f'<{hex(u)}> <age> "{u % 70}"^^<xs:int> .')
        lines.append(f'<{hex(u)}> <city> "city{u % 12}" .')
        for k in range(1, 5):
            v = (u * 7 + k * 131) % n_entities + 1
            if v != u:
                lines.append(f"<{hex(u)}> <knows> <{hex(v)}> .")
    t = s.new_txn()
    t.mutate_rdf(set_rdf="\n".join(lines), commit_now=True)
    return s


QUERY_SHAPES = [
    # 2-level expansion off an exact-index root: the hot serving shape
    '{{ q(func: eq(name, "user{i}")) {{ name age knows {{ name }} }} }}',
    # 3-level traversal
    '{{ q(func: eq(name, "user{i}")) '
    "{{ name knows {{ name knows {{ name }} }} }} }}",
    # filter + count
    '{{ q(func: eq(name, "user{i}")) @filter(lt(age, 50)) '
    "{{ name cnt: count(knows) }} }}",
    # multi-arm AND with a verify-heavy arm declared FIRST: the
    # planner's chain-reorder site (cheap lt arm runs first, the
    # regexp verify sees the narrowed set) — makes planner_reorders
    # deltas non-zero in every read row
    '{{ q(func: eq(name, "user{i}")) '
    "@filter(regexp(name, /user.*/) AND lt(age, 60)) "
    "{{ name age }} }}",
]


def _zipf_picks(rng_state: int, n: int, s: float, count: int = 4096):
    """Deterministic bounded-Zipf literal indices for one client:
    p(k) ~ 1/k^s over ranks 1..n, rank->literal shuffled per client so
    clients don't all hammer literal 1 in lockstep."""
    import numpy as np

    rng = np.random.default_rng(1_000_003 + rng_state)
    ranks = np.arange(1, n + 1, dtype=np.float64)
    p = ranks ** (-s)
    p /= p.sum()
    perm = np.random.default_rng(7).permutation(n)  # shared literal map
    return [int(perm[i]) + 1 for i in rng.choice(n, size=count, p=p)]


def client_queries(rng_state: int, zipf_s: float = 0.0):
    """Deterministic per-client query stream over the hot shapes.
    zipf_s > 0 draws literals Zipfian (the repeated-binding regime the
    plan cache serves); 0 keeps the legacy uniform rotation."""
    i = rng_state
    picks = _zipf_picks(rng_state, HOT_LITERALS, zipf_s) if zipf_s else None
    while True:
        shape = QUERY_SHAPES[i % len(QUERY_SHAPES)]
        if picks is not None:
            lit = picks[i % len(picks)]
        else:
            lit = (i * 13 + rng_state) % HOT_LITERALS + 1
        yield shape.format(i=lit)
        i += 1


# every BENCH_QPS row stamps these per-run deltas so rows are
# self-describing: what the serving front actually did during the
# measurement window, not just the latency it produced. The PR 15
# additions (result_cache_*, planner_*, pushdown_*) make cache and
# planner efficacy self-describing per row, read AND mixed sweeps.
_ROW_COUNTERS = (
    "admission_shed_total", "admission_degraded_total",
    "degraded_queries_total", "batch_coalesced_total",
    "plan_cache_hit_total", "plan_cache_miss_total",
    "result_cache_hit_total", "result_cache_miss_total",
    "planner_reorders_total", "pushdown_applied_total",
    "group_commit_total", "group_commit_txns_total",
    "mutation_edges_total", "num_commits",
    # PR 16: columnar batch-apply coverage + the commit-phase
    # wall-time split (oracle verdict / encode+propose / apply
    # barrier) — the write path's residual-bound breakdown per row
    "mutation_batch_apply_edges_total", "mutation_native_fallback_total",
    "commit_oracle_ns_total", "commit_propose_ns_total",
    "commit_apply_ns_total",
    # PR 17: multi-process apply plane + adaptive group-commit bypass —
    # how many batches crossed the process boundary, how long the
    # shared-memory round trips took, and whether anything fell back
    "apply_shard_batches_total", "apply_shard_fallback_total",
    "apply_shard_ipc_seconds", "group_commit_bypass_total",
)


def metric_base() -> dict:
    """Counter + batch-width-histogram snapshot before a measurement
    window (pair with stamp_metric_deltas)."""
    from dgraph_tpu.serving.digest import DIGESTS
    from dgraph_tpu.utils.observe import METRICS

    base = {k: METRICS.value(k) for k in _ROW_COUNTERS}
    base["_gc_sum"], base["_gc_count"] = METRICS.hist_stats(
        "group_commit_batch_size"
    )
    # digest-store totals: every BENCH_QPS row reports how many calls
    # the flight recorder aggregated during its window plus the top
    # shape's latency share (skew visibility per point)
    dt = DIGESTS.totals()
    base["_digest_calls"] = dt["calls"]
    base["_digest_errors"] = dt["errors"]
    return base


def stamp_metric_deltas(row: dict, base: dict) -> dict:
    """Fold the window's metric deltas into a bench row: raw counter
    deltas (minus the _total suffix), the plan-cache hit RATE, and the
    REALIZED group-commit batch width (histogram sum/count delta)."""
    from dgraph_tpu.utils.observe import METRICS

    for k in _ROW_COUNTERS:
        row[k.replace("_total", "")] = int(METRICS.value(k) - base[k])
    # the IPC counter is float seconds; the generic int() delta would
    # truncate every sub-second window to 0 — stamp it as ns instead
    row["apply_shard_ipc_ns"] = int(
        (METRICS.value("apply_shard_ipc_seconds")
         - base["apply_shard_ipc_seconds"]) * 1e9
    )
    row.pop("apply_shard_ipc_seconds", None)
    looked = row["plan_cache_hit"] + row["plan_cache_miss"]
    row["plan_cache_hit_rate"] = (
        round(row["plan_cache_hit"] / looked, 4) if looked else 0.0
    )
    rlooked = row["result_cache_hit"] + row["result_cache_miss"]
    row["result_cache_hit_rate"] = (
        round(row["result_cache_hit"] / rlooked, 4) if rlooked else 0.0
    )
    s, c = METRICS.hist_stats("group_commit_batch_size")
    dc = c - base["_gc_count"]
    row["group_commit_batch_width"] = (
        round((s - base["_gc_sum"]) / dc, 2) if dc else 0.0
    )
    from dgraph_tpu.serving.digest import DIGESTS

    dt = DIGESTS.totals()
    row["digest_calls"] = int(dt["calls"] - base["_digest_calls"])
    row["digest_errors"] = int(dt["errors"] - base["_digest_errors"])
    row["digest_shapes"] = int(dt["shapes"])
    row["digest_top_shape_lat_share"] = round(
        dt["top_shape_lat_share"], 4
    )
    return row


def run_point(server, clients: int, seconds: float, warmup: float,
              zipf_s: float = 0.0):
    """One closed-loop measurement point. Returns the row dict."""
    from dgraph_tpu.conn.retry import RetryPolicy, retrying_call
    from dgraph_tpu.serving import TooManyRequestsError
    lat_lock = threading.Lock()
    lats: list = []
    sheds = [0]
    stop = threading.Event()
    go = threading.Event()
    started = threading.Barrier(clients + 1)

    def client(cid: int):
        stream = client_queries(cid, zipf_s)
        started.wait()
        go.wait()
        policy = RetryPolicy(base=0.002, cap=0.05, max_attempts=6)
        while not stop.is_set():
            q = next(stream)
            t0 = time.perf_counter()

            def attempt():
                try:
                    return server.query(q)
                except TooManyRequestsError:
                    sheds[0] += 1
                    t_shed = time.perf_counter()  # restart the clock:
                    # p50/p99 measure ACCEPTED executions; the shed
                    # count reports refused offered load separately
                    nonlocal_t0[0] = t_shed
                    raise

            nonlocal_t0 = [t0]
            try:
                retrying_call(
                    attempt, policy=policy,
                    retryable=(TooManyRequestsError,),
                )
            except TooManyRequestsError:
                continue  # retries exhausted: offered load refused
            except Exception:
                continue
            took = (time.perf_counter() - nonlocal_t0[0]) * 1e3
            with lat_lock:
                lats.append(took)

    threads = [
        threading.Thread(target=client, args=(c,)) for c in range(clients)
    ]
    for th in threads:
        th.start()
    started.wait()
    go.set()
    time.sleep(warmup)
    with lat_lock:
        lats.clear()
    base = metric_base()
    shed0 = sheds[0]
    t_start = time.perf_counter()
    time.sleep(seconds)
    stop.set()
    elapsed = time.perf_counter() - t_start
    for th in threads:
        th.join()
    with lat_lock:
        done = sorted(lats)
    row = {
        "clients": clients,
        "completed": len(done),
        "qps": round(len(done) / elapsed, 1),
        "p50_ms": round(done[len(done) // 2], 3) if done else None,
        "p99_ms": (
            round(done[min(len(done) - 1, int(len(done) * 0.99))], 3)
            if done
            else None
        ),
        "shed": sheds[0] - shed0,
    }
    return stamp_metric_deltas(row, base)


def _pct(done, q):
    if not done:
        return None
    return round(done[min(len(done) - 1, int(len(done) * q))], 3)


_WRITE_SEQ = [0]  # process-global: entity names stay unique across points


def run_mixed_point(server, clients: int, seconds: float, warmup: float,
                    write_ratio: float, zipf_s: float,
                    write_entities: int = 4,
                    n_entities: int = N_ENTITIES):
    """One closed-loop mixed read/write point: `clients` splits into a
    writer pool and a reader pool at `write_ratio` (50/50 = half the
    closed-loop clients are live writers — the mixed-traffic regime a
    write-path change must be measured in, since a coin-flip mix would
    only ever measure the read latency the writes ride behind). Writers
    ingest live-loader-shaped batches: `write_entities` fresh entities
    per txn, each with exact + int indexed fields and a @reverse uid
    edge into the existing graph, one commit per txn through the public
    txn API. Readers run the Zipfian hot-shape stream. Returns the row
    dict with read/write stats split out."""
    from dgraph_tpu.zero.zero import TxnConflictError

    writers = min(max(1, round(clients * write_ratio)), clients - 1)
    lat_lock = threading.Lock()
    rlats: list = []
    wlats: list = []
    errors = [0]
    stop = threading.Event()
    go = threading.Event()
    started = threading.Barrier(clients + 1)
    with _WRITE_SEQ_LOCK:
        seq_base = _WRITE_SEQ[0]
        _WRITE_SEQ[0] += 100_000_000

    def writer(cid: int):
        seq = seq_base + cid * 10_000_000
        started.wait()
        go.wait()
        while not stop.is_set():
            t0 = time.perf_counter()
            try:
                objs = []
                for _ in range(write_entities):
                    seq += 1
                    objs.append({
                        "uid": f"_:w{seq}",
                        "name": f"wuser{seq}",
                        "age": int(seq % 70),
                        "city": f"city{seq % 12}",
                        "knows": [{"uid": hex(seq % n_entities + 1)}],
                    })
                t = server.new_txn()
                t.mutate_json(set_obj=objs, commit_now=True)
            except TxnConflictError:
                continue  # retryable; fresh inserts shouldn't conflict
            except Exception:
                errors[0] += 1
                continue
            took = (time.perf_counter() - t0) * 1e3
            with lat_lock:
                wlats.append(took)

    def reader(cid: int):
        stream = client_queries(cid, zipf_s)
        started.wait()
        go.wait()
        while not stop.is_set():
            t0 = time.perf_counter()
            try:
                server.query(next(stream))
            except Exception:
                errors[0] += 1
                continue
            took = (time.perf_counter() - t0) * 1e3
            with lat_lock:
                rlats.append(took)

    threads = [
        threading.Thread(
            target=writer if c < writers else reader, args=(c,)
        )
        for c in range(clients)
    ]
    for th in threads:
        th.start()
    started.wait()
    go.set()
    time.sleep(warmup)
    with lat_lock:
        rlats.clear()
        wlats.clear()
    base = metric_base()
    t_start = time.perf_counter()
    time.sleep(seconds)
    stop.set()
    elapsed = time.perf_counter() - t_start
    for th in threads:
        th.join()
    with lat_lock:
        rd, wd = sorted(rlats), sorted(wlats)
    row = {
        "clients": clients,
        "writers": writers,
        "write_ratio": write_ratio,
        "write_entities": write_entities,
        "mutation_qps": round(len(wd) / elapsed, 1),
        "mutation_edges_qps": round(
            len(wd) * write_entities * 5 / elapsed, 1
        ),
        "write_p50_ms": _pct(wd, 0.50),
        "write_p99_ms": _pct(wd, 0.99),
        "read_qps": round(len(rd) / elapsed, 1),
        "read_p50_ms": _pct(rd, 0.50),
        "read_p99_ms": _pct(rd, 0.99),
        "errors": errors[0],
    }
    return stamp_metric_deltas(row, base)


_WRITE_SEQ_LOCK = threading.Lock()


def _assert_write_byte_identity(args) -> None:
    """In-capture guard for the mixed A/B: every write-pipeline arm —
    columnar batch apply, the multi-process apply plane (APPLY_PROCS
    forced to 2), and the adaptive group-commit bypass — must leave a
    byte-identical store to the serial per-edge arm over the loadgen's
    own writer corpus (a speedup is only admissible as the SAME write
    work done faster), and each arm must demonstrably take its path
    (counter gates), not silently fall back to the one being measured
    against. Runs on small throwaway engines before the measured
    sweep; raises on any divergence."""
    from dgraph_tpu.utils.observe import METRICS
    from dgraph_tpu.worker import applyshard
    from dgraph_tpu.x import config

    def capture(env):
        for k, v in env.items():
            config.set_env(k, v)
        try:
            s = build_server(0, 64)
            t = s.new_txn()
            objs = []
            for seq in range(200):
                objs.append({
                    "uid": f"_:w{seq}",
                    "name": f"wuser{seq}",
                    "age": int(seq % 70),
                    "city": f"city{seq % 12}",
                    "knows": [{"uid": hex(seq % 64 + 1)}],
                })
            t.mutate_json(set_obj=objs, commit_now=True)
            return {k: list(v) for k, v in s.kv._data.items()}
        finally:
            for k in env:
                config.unset_env(k)
            applyshard.shutdown()

    arms = [
        ("serial", {"BATCH_APPLY": 0}, None),
        ("batch", {"BATCH_APPLY": 1, "APPLY_PROCS": 0},
         "mutation_batch_apply_total"),
        ("proc_shard", {"BATCH_APPLY": 1, "APPLY_PROCS": 2},
         "apply_shard_batches_total"),
        ("bypass",
         {"BATCH_APPLY": 1, "GROUP_COMMIT": 1, "GROUP_COMMIT_BYPASS": 1},
         "group_commit_bypass_total"),
    ]
    dumps = {}
    fb_before = METRICS.value("apply_shard_fallback_total")
    for name, env, gate in arms:
        before = METRICS.value(gate) if gate else 0
        dumps[name] = capture(env)
        if gate:
            assert METRICS.value(gate) > before, (
                f"write-sanity {name} arm never took its path "
                f"({gate} unchanged)"
            )
    assert METRICS.value("apply_shard_fallback_total") == fb_before, (
        "proc-shard arm fell back during the byte-identity corpus"
    )
    ref = dumps["serial"]
    for name, _, _ in arms[1:]:
        a = dumps[name]
        assert a == ref, (
            f"{name} arm diverged from the serial arm: "
            f"{len(a)} vs {len(ref)} keys, "
            f"{sum(1 for k in a.keys() & ref.keys() if a[k] != ref[k])} "
            "mismatched"
        )
    print("write byte-identity: OK "
          f"({len(ref)} keys identical across {len(arms)} arms)",
          flush=True)


def mixed_sweep(args) -> dict:
    """The live-write capture: ratios x client counts x commit modes,
    modes interleaved per point and medianed across reps (same
    same-weather discipline as the read sweep). --baseline runs ONE
    unmodified-engine mode (the pre-change capture); otherwise group_on
    vs group_off ride DGRAPH_TPU_GROUP_COMMIT in the same run."""
    import statistics

    from dgraph_tpu.x import config

    server = build_server(args.memlayer_entries, args.entities)
    for q in (s.format(i=1) for s in QUERY_SHAPES):
        server.query(q)
    if args.baseline:
        # --baseline exists to run on a PRE-change checkout (where the
        # GROUP_COMMIT/BATCH_APPLY knobs are unregistered and must not
        # be set); on a post-change tree it pins the serial escape
        # hatches so the rows can never silently measure the new paths
        env = {
            k: 0
            for k in ("GROUP_COMMIT", "BATCH_APPLY")
            if k in config.REGISTRY
        }
        modes = [("serial", env)]
    else:
        # group_on = the full in-process write pipeline (group commit +
        # columnar native batch apply, APPLY_PROCS pinned 0 so the arm
        # is a stable reference on any box); procs_on = the same
        # pipeline with the multi-process apply plane forced on (cores-1
        # shard workers, min 1 — "auto" resolves to 0 on small boxes
        # and would silently measure the same arm twice); group_off =
        # the pre-PR-11 serial per-edge baseline. procs_on/group_on is
        # the same-run APPLY_PROCS on/off A/B the headline reads.
        nprocs = max(1, (os.cpu_count() or 2) - 1)
        modes = [
            ("group_on",
             {"GROUP_COMMIT": 1, "BATCH_APPLY": 1, "APPLY_PROCS": 0}),
            ("procs_on",
             {"GROUP_COMMIT": 1, "BATCH_APPLY": 1,
              "APPLY_PROCS": nprocs}),
            ("group_off", {"GROUP_COMMIT": 0, "BATCH_APPLY": 0}),
        ]
        _assert_write_byte_identity(args)
    ratios = args.write_ratios
    samples = {
        name: {(r, c): [] for r in ratios for c in args.clients}
        for name, _ in modes
    }
    for rep in range(args.reps):
        for ratio in ratios:
            for clients in args.clients:
                for name, env in modes:
                    for k, v in env.items():
                        config.set_env(k, v)
                    row = run_mixed_point(
                        server, clients, args.seconds, args.warmup,
                        ratio, args.zipf_s, args.write_entities,
                        n_entities=args.entities,
                    )
                    for k in env:
                        config.unset_env(k)
                    samples[name][(ratio, clients)].append(row)
                    print(
                        f"[rep{rep} {name}] mix={ratio} c={clients:3d} "
                        f"mut_qps={row['mutation_qps']:8.1f} "
                        f"wp50={row['write_p50_ms']}ms "
                        f"wp99={row['write_p99_ms']}ms "
                        f"read_qps={row['read_qps']:8.1f} "
                        f"plan_hit={row['plan_cache_hit']} "
                        f"batches={row['group_commit']}",
                        flush=True,
                    )

    def median_row(rows):
        out = dict(rows[0])
        for k, v in rows[0].items():
            if isinstance(v, (int, float)) and k not in (
                "clients", "writers", "write_ratio", "write_entities"
            ):
                vals = [r[k] for r in rows if r[k] is not None]
                out[k] = (
                    round(statistics.median(vals), 3) if vals else None
                )
        out["reps"] = len(rows)
        return out

    results: dict = {}
    for name, _ in modes:
        for ratio in ratios:
            key = f"mix_{int(ratio * 100)}"
            results.setdefault(key, {})[name] = [
                median_row(samples[name][(ratio, c)])
                for c in args.clients
            ]

    headline: dict = {"zipf_s": args.zipf_s, "clients": args.clients}
    for ratio in ratios:
        key = f"mix_{int(ratio * 100)}"
        for name, _ in modes:
            rows = results[key][name]
            best = max(rows, key=lambda r: r["mutation_qps"] or 0)
            headline[f"{key}_{name}_mutation_qps"] = best["mutation_qps"]
            headline[f"{key}_{name}_write_p99_ms"] = best["write_p99_ms"]
            headline[f"{key}_{name}_clients"] = best["clients"]
    if not args.baseline:
        for ratio in ratios:
            key = f"mix_{int(ratio * 100)}"
            off = headline.get(f"{key}_group_off_mutation_qps") or 0
            on = headline.get(f"{key}_group_on_mutation_qps") or 0
            procs = headline.get(f"{key}_procs_on_mutation_qps") or 0
            headline[f"{key}_speedup_x"] = (
                round(on / off, 2) if off else None
            )
            # the APPLY_PROCS on/off A/B, same run, same weather
            headline[f"{key}_procs_speedup_x"] = (
                round(procs / on, 2) if on else None
            )
    return {"rows": results, "headline": headline}


def stamp_vs_baseline(out: dict, merged: dict) -> None:
    """Stamp the cross-capture headline: best live arm vs the recorded
    pre-change mixed_baseline (serial single-mode capture), overall and
    per client count. Mutates out['headline'] in place; silently a
    no-op when no baseline capture exists in the artifact."""
    base = (merged.get("mixed_baseline") or {})
    bhead = base.get("headline") or {}
    brows = base.get("rows") or {}
    head = out["headline"]
    for key in out["rows"]:
        bqps = bhead.get(f"{key}_serial_mutation_qps")
        if not bqps:
            continue
        head[f"{key}_baseline_mutation_qps"] = bqps
        live = max(
            (head.get(f"{key}_{arm}_mutation_qps") or 0)
            for arm in ("group_on", "procs_on")
        )
        head[f"{key}_vs_baseline_x"] = round(live / bqps, 2)
        bby = {
            r["clients"]: r["mutation_qps"]
            for r in (brows.get(key, {}).get("serial") or [])
            if r.get("mutation_qps")
        }
        by = {}
        for arm in ("group_on", "procs_on"):
            for r in out["rows"][key].get(arm, []):
                c = r["clients"]
                if c in bby and r.get("mutation_qps"):
                    by[c] = max(
                        by.get(c, 0),
                        round(r["mutation_qps"] / bby[c], 2),
                    )
        if by:
            head[f"{key}_vs_baseline_by_clients_x"] = {
                str(c): v for c, v in sorted(by.items())
            }


def _reuse_modes(args):
    """The PR 15 A/B arms: baseline (planner + result cache OFF) first,
    then the reuse plane on — same build, knobs only."""
    return [
        ("reuse_off", {"RESULT_CACHE_SIZE": 0, "QUERY_PLANNER": 0}),
        (
            "reuse_on",
            {
                "RESULT_CACHE_SIZE": args.result_cache_size,
                "QUERY_PLANNER": 1,
            },
        ),
    ]


def _assert_byte_identity(server, args) -> int:
    """In-capture correctness gate: a sample of every shape's hot
    literals must produce byte-identical responses with the reuse
    plane off, on (populating miss), and on again (the actual HIT).
    Returns the number of (query, run) comparisons made; raises on any
    mismatch — a capture must never advertise a speedup over wrong
    bytes."""
    from dgraph_tpu.x import config

    def raw(q):
        return bytes(server.query(q, want="raw")["data"].raw)

    checked = 0
    for shape in QUERY_SHAPES:
        for lit in (1, 2, 3, 17, 101):
            q = shape.format(i=lit)
            for k, v in _reuse_modes(args)[0][1].items():
                config.set_env(k, v)
            base = raw(q)
            for k, v in _reuse_modes(args)[1][1].items():
                config.set_env(k, v)
            first, second = raw(q), raw(q)
            for k in _reuse_modes(args)[1][1]:
                config.unset_env(k)
            assert first == base and second == base, (
                f"reuse plane changed response bytes for {q!r}"
            )
            checked += 2
    return checked


def reuse_sweep(args) -> dict:
    """Planner + result-cache A/B over the Zipfian repeated-shape read
    mix (the ROADMAP item 2 payoff capture): same-run A/B with the
    baseline arm FIRST at every point, byte-identity asserted
    in-capture, and the reuse counters stamped into every row so each
    row is self-describing."""
    import statistics

    from dgraph_tpu.x import config

    server = build_server(args.memlayer_entries, args.entities)
    for q in (s.format(i=1) for s in QUERY_SHAPES):
        server.query(q)
    byte_checks = _assert_byte_identity(server, args)
    # drop the probe's cached entries so the measured arms start cold
    server.serving.results.clear()

    modes = _reuse_modes(args)
    samples = {name: {c: [] for c in args.clients} for name, _ in modes}
    for rep in range(args.reps):
        for clients in args.clients:
            for name, env in modes:  # baseline first within each point
                for k, v in env.items():
                    config.set_env(k, v)
                row = run_point(
                    server, clients, args.seconds, args.warmup,
                    args.zipf_s,
                )
                for k in env:
                    config.unset_env(k)
                samples[name][clients].append(row)
                print(
                    f"[rep{rep} {name}] c={clients:3d} "
                    f"qps={row['qps']:8.1f} p50={row['p50_ms']}ms "
                    f"p99={row['p99_ms']}ms "
                    f"rc_hit={row['result_cache_hit']} "
                    f"plan_hit={row['plan_cache_hit']} "
                    f"reorders={row['planner_reorders']}",
                    flush=True,
                )

    def median_row(rows):
        out = dict(rows[0])
        for k, v in rows[0].items():
            if isinstance(v, (int, float)) and k != "clients":
                vals = [r[k] for r in rows if r[k] is not None]
                out[k] = (
                    round(statistics.median(vals), 3) if vals else None
                )
        out["reps"] = len(rows)
        return out

    results = {}
    for name, _ in modes:
        rows = []
        for clients in args.clients:
            row = median_row(samples[name][clients])
            row["mode"] = name
            rows.append(row)
        results[name] = rows

    def at(m, c):
        return next(r for r in results[m] if r["clients"] == c)

    multi = [r for r in results["reuse_on"] if r["clients"] > 1]
    knee = (
        max(multi, key=lambda r: r["qps"])["clients"]
        if multi
        else args.clients[-1]
    )
    on, off = at("reuse_on", knee), at("reuse_off", knee)
    headline = {
        "zipf_s": args.zipf_s,
        "knee_clients": knee,
        "qps_reuse_off_at_knee": off["qps"],
        "qps_reuse_on_at_knee": on["qps"],
        "reuse_speedup_x": (
            round(on["qps"] / off["qps"], 2) if off["qps"] else None
        ),
        "p99_reuse_off_at_knee_ms": off["p99_ms"],
        "p99_reuse_on_at_knee_ms": on["p99_ms"],
        "result_cache_hit_at_knee": on["result_cache_hit"],
        "result_cache_hit_rate_at_knee": on["result_cache_hit_rate"],
        "plan_cache_hit_at_knee": on["plan_cache_hit"],
        "byte_identity_checks": byte_checks,
        "result_cache_size": args.result_cache_size,
    }
    return {"rows": results, "headline": headline}


def sweep(args) -> dict:
    from dgraph_tpu.x import config

    server = build_server(args.memlayer_entries, args.entities)
    # prime caches/JIT so mode points compare steady states
    for q in (s.format(i=0) for s in QUERY_SHAPES):
        server.query(q)

    modes = [
        ("batch_off", {"BATCH_WINDOW_US": 0, "ADMISSION": 0}),
        (
            "batch_on",
            {"BATCH_WINDOW_US": args.window_us, "ADMISSION": 0},
        ),
        (
            "admission",
            {
                "BATCH_WINDOW_US": args.window_us,
                "ADMISSION": 1,
                "MAX_INFLIGHT": args.max_inflight,
            },
        ),
    ]
    # modes INTERLEAVED per point and medianed over repetitions: this
    # box shows minute-scale load variance far larger than the effects
    # under test, so sequential per-mode sweeps compare weather, not
    # code. Interleaving puts every mode in the same weather.
    import statistics

    samples = {name: {c: [] for c in args.clients} for name, _ in modes}
    for rep in range(args.reps):
        for clients in args.clients:
            for name, env in modes:
                for k, v in env.items():
                    config.set_env(k, v)
                row = run_point(
                    server, clients, args.seconds, args.warmup,
                    args.zipf_s,
                )
                for k in env:
                    config.unset_env(k)
                samples[name][clients].append(row)
                print(
                    f"[rep{rep} {name}] c={clients:3d} "
                    f"qps={row['qps']:8.1f} p50={row['p50_ms']}ms "
                    f"p99={row['p99_ms']}ms shed={row['shed']} "
                    f"coalesced={row['batch_coalesced']}",
                    flush=True,
                )

    def median_row(rows):
        out = dict(rows[0])
        for k in ("qps", "p50_ms", "p99_ms"):
            vals = [r[k] for r in rows if r[k] is not None]
            out[k] = round(statistics.median(vals), 3) if vals else None
        for k in rows[0]:
            if k.endswith("_rate"):
                out[k] = round(
                    statistics.median([r[k] for r in rows]), 4
                )
            elif k.startswith(
                ("batch_", "plan_", "admission_", "result_",
                 "planner_", "pushdown_")
            ) or k in ("shed", "completed"):
                out[k] = int(statistics.median([r[k] for r in rows]))
        out["reps"] = len(rows)
        return out

    results = {}
    for name, _ in modes:
        rows = []
        for clients in args.clients:
            row = median_row(samples[name][clients])
            row["mode"] = name
            rows.append(row)
        results[name] = rows

    # headline: the KNEE is the highest sustainable offered load — the
    # concurrency point where batching-on throughput peaks; beyond it
    # closed-loop clients only oversubscribe the scheduler (on a 1-core
    # box, thread-scheduling luck dominates both modes there, and
    # admission — not batching — is what keeps p99 bounded). The top
    # (most oversubscribed) point is reported alongside.
    top = args.clients[-1]

    def at(m, c):
        return next(r for r in results[m] if r["clients"] == c)

    multi = [r for r in results["batch_on"] if r["clients"] > 1]
    knee = (
        max(multi, key=lambda r: r["qps"])["clients"] if multi else top
    )
    headline = {
        "knee_clients": knee,
        "qps_batch_off_at_knee": at("batch_off", knee)["qps"],
        "qps_batch_on_at_knee": at("batch_on", knee)["qps"],
        "p99_batch_off_at_knee_ms": at("batch_off", knee)["p99_ms"],
        "p99_batch_on_at_knee_ms": at("batch_on", knee)["p99_ms"],
        "clients_at_top": top,
        "p99_batch_off_at_top_ms": at("batch_off", top)["p99_ms"],
        "p99_batch_on_at_top_ms": at("batch_on", top)["p99_ms"],
        "p99_admission_at_top_ms": at("admission", top)["p99_ms"],
        "shed_at_top_admission": at("admission", top)["shed"],
        "window_us": args.window_us,
    }
    return {"rows": results, "headline": headline}


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--seconds", type=float, default=3.0)
    ap.add_argument("--warmup", type=float, default=0.5)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--window-us", type=int, default=500)
    ap.add_argument("--max-inflight", type=int, default=8)
    ap.add_argument(
        "--memlayer-entries", type=int, default=512,
        help="decoded-list cache bound; the default keeps the working "
        "set larger than the cache (the at-scale regime; 0 = engine "
        "default)",
    )
    ap.add_argument(
        "--clients", type=int, nargs="+", default=None,
        help="client counts (default: 1 4 8 16 read sweep; 2 4 8 "
        "mixed — a mixed point needs at least one of each pool)",
    )
    ap.add_argument("--entities", type=int, default=N_ENTITIES)
    ap.add_argument("--out", default=None)
    ap.add_argument(
        "--zipf-s", type=float, default=1.1,
        help="Zipf exponent for read literals (0 = legacy uniform "
        "rotation); the repeated-binding regime the plan cache serves",
    )
    ap.add_argument(
        "--mix", action="store_true",
        help="mixed read/write sweep (write ratios via --write-ratios) "
        "instead of the read-only sweep",
    )
    ap.add_argument(
        "--reuse", action="store_true",
        help="planner + result-cache A/B over the Zipfian "
        "repeated-shape read mix (baseline arm first, byte-identity "
        "asserted in-capture) -> the 'reuse' key of BENCH_QPS.json",
    )
    ap.add_argument(
        "--result-cache-size", type=int, default=8192,
        help="RESULT_CACHE_SIZE for the reuse_on arm (entries; must "
        "cover shapes x hot literals or the LRU thrashes)",
    )
    ap.add_argument(
        "--write-ratios", type=float, nargs="+", default=[0.1, 0.5],
    )
    ap.add_argument(
        "--write-entities", type=int, default=4,
        help="fresh entities per write txn (the live-loader ingest "
        "batch shape; 5 edges each incl. the @reverse uid edge)",
    )
    ap.add_argument(
        "--baseline", action="store_true",
        help="with --mix: run ONE unmodified-engine mode (the "
        "pre-change live-write baseline capture) instead of the "
        "group_on/group_off A/B",
    )
    ap.add_argument(
        "--sanity", action="store_true",
        help="~5s smoke run (CI gate): no artifact written",
    )
    ap.add_argument(
        "--write-sanity", action="store_true",
        help="~5s mixed read/write smoke (CI gate): no artifact written",
    )
    args = ap.parse_args(argv)
    if args.clients is None:
        args.clients = [2, 4, 8] if (args.mix or args.write_sanity) \
            else [1, 4, 8, 16]
    if args.sanity or args.write_sanity:
        args.seconds, args.warmup, args.reps = 0.6, 0.15, 1
        args.clients = [2, 4]
        args.entities = 600
    if args.write_sanity:
        args.mix = True
        args.write_ratios = [0.5]
    if args.mix:
        out = mixed_sweep(args)
    elif args.reuse:
        out = reuse_sweep(args)
    else:
        out = sweep(args)
    if args.write_sanity:
        rows = [
            r
            for modes in out["rows"].values()
            for rws in modes.values()
            for r in rws
        ]
        ok = all(
            r["mutation_qps"] > 0 and r["read_qps"] > 0 and
            r["errors"] == 0
            for r in rows
        )
        # the A arm must actually exercise the native columnar path:
        # a silently-always-falling-back kernel would "pass" the QPS
        # checks while measuring nothing new
        on_rows = [
            r
            for modes in out["rows"].values()
            for name, rws in modes.items()
            if name == "group_on"
            for r in rws
        ]
        batch_ok = any(
            r.get("mutation_batch_apply_edges", 0) > 0 for r in on_rows
        )
        ok = ok and (batch_ok or not on_rows)
        if on_rows and not batch_ok:
            print("write-sanity: native batch-apply counter stayed "
                  "zero in the group_on arm")
        # the proc arm must actually cross the process boundary
        proc_rows = [
            r
            for modes in out["rows"].values()
            for name, rws in modes.items()
            if name == "procs_on"
            for r in rws
        ]
        proc_ok = any(
            r.get("apply_shard_batches", 0) > 0 for r in proc_rows
        )
        ok = ok and (proc_ok or not proc_rows)
        if proc_rows and not proc_ok:
            print("write-sanity: shard-process kernel counter stayed "
                  "zero in the procs_on arm")
        print(f"write-sanity: {'OK' if ok else 'FAIL'} {out['headline']}")
        return 0 if ok else 1
    if args.sanity:
        top = out["headline"]
        ok = (
            all(r["completed"] > 0 for rows in out["rows"].values()
                for r in rows)
        )
        print(f"sanity: {'OK' if ok else 'FAIL'} {top}")
        return 0 if ok else 1
    import jax

    path = args.out or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BENCH_QPS.json",
    )
    # every sweep kind lands in ONE artifact: merge into the existing
    # BENCH_QPS.json keys instead of clobbering (a read-sweep rerun
    # must not silently drop the mixed/mixed_baseline captures)
    out_keys = (
        {"mixed_baseline": out} if (args.mix and args.baseline)
        else {"mixed": out} if args.mix
        else {"reuse": out} if args.reuse
        else out
    )
    merged = out_keys
    if os.path.exists(path):
        try:
            with open(path) as f:
                merged = json.load(f)
            merged.pop("provenance", None)
            if args.mix and not args.baseline:
                stamp_vs_baseline(out, merged)
            merged.update(out_keys)
        except Exception:
            merged = out_keys
    written = stamp.guarded_write(path, merged, jax.default_backend())
    print(f"wrote {written}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

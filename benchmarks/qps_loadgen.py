"""Closed-loop multi-client QPS harness for the serving front.

Models the north-star workload — thousands of concurrent *small*
queries — against one in-process engine: C closed-loop clients each
issue the next query the moment the previous one returns (offered load
rises with C), over a small pool of hot query shapes with rotating
literals (the plan cache's serving regime). Each point reports achieved
QPS, p50/p99 latency of accepted executions, and the serving-front
counters (coalesced tasks, plan-cache hits, sheds, degrades).

Modes swept per client count:

  batch_off  — BATCH_WINDOW_US=0, ADMISSION off: the pre-serving-front
               path (PR 2/6 per-query machinery only).
  batch_on   — the micro-batcher coalescing cross-query level tasks.
  admission  — batching + admission control with a deliberately small
               in-flight budget, driven PAST saturation: sheds are
               retried client-side with backoff (conn/retry
               .retrying_call); p99 of accepted work must stay bounded
               instead of collapsing with the queue.

Usage:
  python benchmarks/qps_loadgen.py                 # full sweep -> BENCH_QPS.json
  python benchmarks/qps_loadgen.py --seconds 5
  python benchmarks/qps_loadgen.py --sanity        # ~5s smoke (CI gate)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import stamp  # noqa: E402

N_ENTITIES = 4000
HOT_LITERALS = 256  # entity names the clients rotate over


def build_server(memlayer_entries: int = 512, n_entities: int = N_ENTITIES):
    """In-process engine in the at-scale serving regime: the working
    set deliberately EXCEEDS the decoded-list cache (MEMLAYER_ENTRIES),
    so level reads pay real decode work per dispatch — a store serving
    millions of users never has every posting list decoded in RAM. A
    fully cache-resident store makes level reads ~µs and cross-query
    batching rationally a no-op (the behind-running batcher adds no
    idle latency there, but has nothing to win either)."""
    from dgraph_tpu.api.server import Server
    from dgraph_tpu.x import config

    if memlayer_entries:
        config.set_env("MEMLAYER_ENTRIES", memlayer_entries)
    s = Server()
    s.alter(
        "name: string @index(exact) .\n"
        "age: int @index(int) .\n"
        "knows: [uid] @reverse .\n"
        "city: string .\n"
    )
    lines = []
    for u in range(1, n_entities + 1):
        # unique names: each query roots at ONE entity — the small-query
        # serving regime the front exists for (thousands of concurrent
        # point-ish queries, not a handful of giant scans)
        lines.append(f'<{hex(u)}> <name> "user{u}" .')
        lines.append(f'<{hex(u)}> <age> "{u % 70}"^^<xs:int> .')
        lines.append(f'<{hex(u)}> <city> "city{u % 12}" .')
        for k in range(1, 5):
            v = (u * 7 + k * 131) % n_entities + 1
            if v != u:
                lines.append(f"<{hex(u)}> <knows> <{hex(v)}> .")
    t = s.new_txn()
    t.mutate_rdf(set_rdf="\n".join(lines), commit_now=True)
    return s


QUERY_SHAPES = [
    # 2-level expansion off an exact-index root: the hot serving shape
    '{{ q(func: eq(name, "user{i}")) {{ name age knows {{ name }} }} }}',
    # 3-level traversal
    '{{ q(func: eq(name, "user{i}")) '
    "{{ name knows {{ name knows {{ name }} }} }} }}",
    # filter + count
    '{{ q(func: eq(name, "user{i}")) @filter(lt(age, 50)) '
    "{{ name cnt: count(knows) }} }}",
]


def client_queries(rng_state: int):
    """Deterministic per-client query stream over the hot shapes."""
    i = rng_state
    while True:
        shape = QUERY_SHAPES[i % len(QUERY_SHAPES)]
        yield shape.format(i=(i * 13 + rng_state) % HOT_LITERALS + 1)
        i += 1


def run_point(server, clients: int, seconds: float, warmup: float):
    """One closed-loop measurement point. Returns the row dict."""
    from dgraph_tpu.conn.retry import RetryPolicy, retrying_call
    from dgraph_tpu.serving import TooManyRequestsError
    from dgraph_tpu.utils.observe import METRICS

    counters = (
        "batch_coalesced_total", "plan_cache_hit_total",
        "plan_cache_miss_total", "admission_shed_total",
        "admission_degraded_total",
    )
    lat_lock = threading.Lock()
    lats: list = []
    sheds = [0]
    stop = threading.Event()
    go = threading.Event()
    started = threading.Barrier(clients + 1)

    def client(cid: int):
        stream = client_queries(cid)
        started.wait()
        go.wait()
        policy = RetryPolicy(base=0.002, cap=0.05, max_attempts=6)
        while not stop.is_set():
            q = next(stream)
            t0 = time.perf_counter()

            def attempt():
                try:
                    return server.query(q)
                except TooManyRequestsError:
                    sheds[0] += 1
                    t_shed = time.perf_counter()  # restart the clock:
                    # p50/p99 measure ACCEPTED executions; the shed
                    # count reports refused offered load separately
                    nonlocal_t0[0] = t_shed
                    raise

            nonlocal_t0 = [t0]
            try:
                retrying_call(
                    attempt, policy=policy,
                    retryable=(TooManyRequestsError,),
                )
            except TooManyRequestsError:
                continue  # retries exhausted: offered load refused
            except Exception:
                continue
            took = (time.perf_counter() - nonlocal_t0[0]) * 1e3
            with lat_lock:
                lats.append(took)

    threads = [
        threading.Thread(target=client, args=(c,)) for c in range(clients)
    ]
    for th in threads:
        th.start()
    started.wait()
    go.set()
    time.sleep(warmup)
    with lat_lock:
        lats.clear()
    base = {k: METRICS.value(k) for k in counters}
    shed0 = sheds[0]
    t_start = time.perf_counter()
    time.sleep(seconds)
    stop.set()
    elapsed = time.perf_counter() - t_start
    for th in threads:
        th.join()
    with lat_lock:
        done = sorted(lats)
    row = {
        "clients": clients,
        "completed": len(done),
        "qps": round(len(done) / elapsed, 1),
        "p50_ms": round(done[len(done) // 2], 3) if done else None,
        "p99_ms": (
            round(done[min(len(done) - 1, int(len(done) * 0.99))], 3)
            if done
            else None
        ),
        "shed": sheds[0] - shed0,
    }
    for k in counters:
        row[k.replace("_total", "")] = int(METRICS.value(k) - base[k])
    return row


def sweep(args) -> dict:
    from dgraph_tpu.x import config

    server = build_server(args.memlayer_entries, args.entities)
    # prime caches/JIT so mode points compare steady states
    for q in (s.format(i=0) for s in QUERY_SHAPES):
        server.query(q)

    modes = [
        ("batch_off", {"BATCH_WINDOW_US": 0, "ADMISSION": 0}),
        (
            "batch_on",
            {"BATCH_WINDOW_US": args.window_us, "ADMISSION": 0},
        ),
        (
            "admission",
            {
                "BATCH_WINDOW_US": args.window_us,
                "ADMISSION": 1,
                "MAX_INFLIGHT": args.max_inflight,
            },
        ),
    ]
    # modes INTERLEAVED per point and medianed over repetitions: this
    # box shows minute-scale load variance far larger than the effects
    # under test, so sequential per-mode sweeps compare weather, not
    # code. Interleaving puts every mode in the same weather.
    import statistics

    samples = {name: {c: [] for c in args.clients} for name, _ in modes}
    for rep in range(args.reps):
        for clients in args.clients:
            for name, env in modes:
                for k, v in env.items():
                    config.set_env(k, v)
                row = run_point(
                    server, clients, args.seconds, args.warmup
                )
                for k in env:
                    config.unset_env(k)
                samples[name][clients].append(row)
                print(
                    f"[rep{rep} {name}] c={clients:3d} "
                    f"qps={row['qps']:8.1f} p50={row['p50_ms']}ms "
                    f"p99={row['p99_ms']}ms shed={row['shed']} "
                    f"coalesced={row['batch_coalesced']}",
                    flush=True,
                )

    def median_row(rows):
        out = dict(rows[0])
        for k in ("qps", "p50_ms", "p99_ms"):
            vals = [r[k] for r in rows if r[k] is not None]
            out[k] = round(statistics.median(vals), 3) if vals else None
        for k in rows[0]:
            if k.startswith(("batch_", "plan_", "admission_")) or k in (
                "shed", "completed"
            ):
                out[k] = int(statistics.median([r[k] for r in rows]))
        out["reps"] = len(rows)
        return out

    results = {}
    for name, _ in modes:
        rows = []
        for clients in args.clients:
            row = median_row(samples[name][clients])
            row["mode"] = name
            rows.append(row)
        results[name] = rows

    # headline: the KNEE is the highest sustainable offered load — the
    # concurrency point where batching-on throughput peaks; beyond it
    # closed-loop clients only oversubscribe the scheduler (on a 1-core
    # box, thread-scheduling luck dominates both modes there, and
    # admission — not batching — is what keeps p99 bounded). The top
    # (most oversubscribed) point is reported alongside.
    top = args.clients[-1]

    def at(m, c):
        return next(r for r in results[m] if r["clients"] == c)

    multi = [r for r in results["batch_on"] if r["clients"] > 1]
    knee = (
        max(multi, key=lambda r: r["qps"])["clients"] if multi else top
    )
    headline = {
        "knee_clients": knee,
        "qps_batch_off_at_knee": at("batch_off", knee)["qps"],
        "qps_batch_on_at_knee": at("batch_on", knee)["qps"],
        "p99_batch_off_at_knee_ms": at("batch_off", knee)["p99_ms"],
        "p99_batch_on_at_knee_ms": at("batch_on", knee)["p99_ms"],
        "clients_at_top": top,
        "p99_batch_off_at_top_ms": at("batch_off", top)["p99_ms"],
        "p99_batch_on_at_top_ms": at("batch_on", top)["p99_ms"],
        "p99_admission_at_top_ms": at("admission", top)["p99_ms"],
        "shed_at_top_admission": at("admission", top)["shed"],
        "window_us": args.window_us,
    }
    return {"rows": results, "headline": headline}


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--seconds", type=float, default=3.0)
    ap.add_argument("--warmup", type=float, default=0.5)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--window-us", type=int, default=500)
    ap.add_argument("--max-inflight", type=int, default=8)
    ap.add_argument(
        "--memlayer-entries", type=int, default=512,
        help="decoded-list cache bound; the default keeps the working "
        "set larger than the cache (the at-scale regime; 0 = engine "
        "default)",
    )
    ap.add_argument(
        "--clients", type=int, nargs="+", default=[1, 4, 8, 16]
    )
    ap.add_argument("--entities", type=int, default=N_ENTITIES)
    ap.add_argument("--out", default=None)
    ap.add_argument(
        "--sanity", action="store_true",
        help="~5s smoke run (CI gate): no artifact written",
    )
    args = ap.parse_args(argv)
    if args.sanity:
        args.seconds, args.warmup, args.reps = 0.6, 0.15, 1
        args.clients = [2, 4]
        args.entities = 600
    out = sweep(args)
    if args.sanity:
        top = out["headline"]
        ok = (
            all(r["completed"] > 0 for rows in out["rows"].values()
                for r in rows)
        )
        print(f"sanity: {'OK' if ok else 'FAIL'} {top}")
        return 0 if ok else 1
    import jax

    path = args.out or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BENCH_QPS.json",
    )
    written = stamp.guarded_write(path, out, jax.default_backend())
    print(f"wrote {written}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Benchmark artifact provenance + overwrite protection (VERDICT r4 #2).

Every benchmark JSON this repo writes carries a `provenance` block (git
SHA, UTC timestamp, platform) so a number on disk can always be traced
to the commit and backend that produced it — and a TPU-captured
artifact can never be silently clobbered by a cpu_fallback rerun.
"""

import json
import os
import subprocess
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def provenance(platform: str) -> dict:
    try:
        sha = (
            subprocess.run(
                ["git", "rev-parse", "HEAD"],
                cwd=REPO, capture_output=True, text=True, timeout=10,
            ).stdout.strip()
            or "unknown"
        )
        dirty = bool(
            subprocess.run(
                ["git", "status", "--porcelain"],
                cwd=REPO, capture_output=True, text=True, timeout=10,
            ).stdout.strip()
        )
    except Exception:
        sha, dirty = "unknown", False
    return {
        "git_sha": sha,
        "git_dirty": dirty,
        "timestamp_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "platform": platform,
        "jax_platforms_env": os.environ.get("JAX_PLATFORMS", ""),
    }


def is_tpu(platform: str) -> bool:
    p = (platform or "").lower()
    return "tpu" in p or "axon" in p


def guarded_write(path: str, obj: dict, platform: str) -> str:
    """Write obj+provenance to path — unless path already holds a
    TPU-platform artifact and this run is a CPU fallback, in which case
    the new data lands at `<path>.cpu.json` and the TPU capture stays.
    Returns the path actually written."""
    obj = dict(obj)
    obj["provenance"] = provenance(platform)
    if os.path.exists(path) and not is_tpu(platform):
        try:
            old = json.load(open(path))
            if is_tpu(
                (old.get("provenance") or {}).get("platform", "")
            ):
                alt = path + ".cpu.json"
                with open(alt, "w") as f:
                    json.dump(obj, f, indent=1)
                print(
                    f"[stamp] {path} holds a TPU capture; cpu_fallback "
                    f"written to {alt}"
                )
                return alt
        except Exception:
            pass
    with open(path, "w") as f:
        json.dump(obj, f, indent=1)
    return path

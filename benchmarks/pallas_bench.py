"""Pallas compare-all sweep vs XLA searchsorted on the live backend.

The dispatcher's small-side intersect path has two device formulations:
  - setops.intersect: searchsorted (binary search + gather)
  - pallas_setops.intersect: compare-all VPU sweep (ops/pallas_setops.py)

This benchmark runs both COMPILED on whatever backend is live (TPU when
the tunnel is up) over the reference's ratio ladder
(/root/reference/algo/benchmarks shapes: small=10..128 vs big=10k..4M)
and reports per-op ns for a 128-wide vmapped batch, so the dispatcher's
_USE_PALLAS default can be set from data instead of a guess.

Usage: python benchmarks/pallas_bench.py [--json out]
"""

import sys as _sys

_sys.path.insert(0, "/root/repo") if "/root/repo" not in _sys.path else None

import argparse
import json
import time

import numpy as np


def _bench(fn, args, iters=30):
    # warmup + compile
    out = fn(*args)
    _block(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    _block(out)
    return (time.perf_counter() - t0) / iters


def _block(out):
    import jax

    jax.tree_util.tree_map(
        lambda x: x.block_until_ready() if hasattr(x, "block_until_ready") else x,
        out,
    )


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default=None)
    ap.add_argument("--batch", type=int, default=128)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from dgraph_tpu.ops import setops, pallas_setops

    backend = jax.default_backend()
    interpret = backend != "tpu"
    rng = np.random.default_rng(7)
    batch = args.batch

    rows = []
    for small, big in [
        (10, 10_000),
        (10, 100_000),
        (10, 1_000_000),
        (128, 100_000),
        (128, 1_000_000),
        (128, 4_000_000),
    ]:
        pa = max(8, 1 << (small - 1).bit_length())
        pb = 1 << (big - 1).bit_length() if big & (big - 1) == 0 else 1 << big.bit_length()
        B = np.full((batch, pb), setops.UINT32_MAX, np.uint32)
        A = np.full((batch, pa), setops.UINT32_MAX, np.uint32)
        for i in range(batch):
            b = np.sort(
                rng.choice(np.uint32(1) << np.uint32(31), size=big, replace=False)
            ).astype(np.uint32)
            a = np.sort(rng.choice(b, size=small, replace=False)).astype(np.uint32)
            B[i, :big] = b
            A[i, :small] = a
        LA = np.full((batch,), small, np.int32)
        LB = np.full((batch,), big, np.int32)
        Ad, Bd = jnp.asarray(A), jnp.asarray(B)
        LAd, LBd = jnp.asarray(LA), jnp.asarray(LB)

        xla_fn = jax.jit(jax.vmap(setops.intersect))
        t_xla = _bench(xla_fn, (Ad, LAd, Bd, LBd))

        t_pallas = None
        if small <= 128:
            def pl_batch(A_, LA_, B_, LB_):
                return pallas_setops.intersect_batch(
                    A_, LA_, B_, LB_, interpret=interpret
                )

            pl_fn = jax.jit(pl_batch)
            try:
                t_pallas = _bench(pl_fn, (Ad, LAd, Bd, LBd))
            except Exception as e:  # pragma: no cover - hardware-specific
                t_pallas = None
                print(f"pallas failed at {small}v{big}: {e}", file=_sys.stderr)

        row = {
            "small": small,
            "big": big,
            "batch": batch,
            "xla_ns_per_op": round(t_xla / batch * 1e9, 1),
            "pallas_ns_per_op": (
                round(t_pallas / batch * 1e9, 1) if t_pallas is not None else None
            ),
        }
        rows.append(row)
        print(json.dumps(row), flush=True)

    result = {"backend": backend, "interpret": interpret, "rows": rows}
    if args.json:
        with open(args.json, "w") as f:
            json.dump(result, f, indent=1)
    print(json.dumps({"summary": result}, indent=1))


if __name__ == "__main__":
    main()

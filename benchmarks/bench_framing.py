"""Inter-node data-plane framing: JSON+b64 (old) vs binary multipart (new).

Measures the two costs VERDICT r2 weak #8 calls out for bulk transfers
(raft snapshot install, predicate-move streams): encode+decode CPU time
and bytes on the wire, on a realistic tablet payload (posting-list
records: binary keys + pack bytes). Then times a real cross-process
predicate move in a ProcCluster with the live codec.

Usage: python benchmarks/bench_framing.py [--json out] [--move-edges N]
"""

import sys as _sys

_sys.path.insert(0, "/root/repo") if "/root/repo" not in _sys.path else None

from dgraph_tpu.devsetup import force_cpu

force_cpu()

import argparse
import base64
import json
import time

import numpy as np

from dgraph_tpu.conn.frame import pack_body, unpack_body


def _old_jsonize(obj):
    if isinstance(obj, bytes):
        return {"__b64__": base64.b64encode(obj).decode()}
    if isinstance(obj, (list, tuple)):
        return [_old_jsonize(x) for x in obj]
    if isinstance(obj, dict):
        return {k: _old_jsonize(v) for k, v in obj.items()}
    return obj


def _old_unjsonize(obj):
    if isinstance(obj, dict):
        if set(obj.keys()) == {"__b64__"}:
            return base64.b64decode(obj["__b64__"])
        return {k: _old_unjsonize(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_old_unjsonize(x) for x in obj]
    return obj


def tablet_payload(n_keys: int, val_bytes: int) -> dict:
    """A predicate-move stream chunk: [key, ts, record] triples with
    pack-like values (bit-packed uid blocks: structured, compressible)."""
    rng = np.random.default_rng(0)
    rows = []
    for i in range(n_keys):
        key = b"\x00\x00dgraph.movie.film" + i.to_bytes(8, "big")
        uids = np.sort(
            rng.choice(1 << 24, val_bytes // 4, replace=False)
        ).astype(np.uint32)
        rows.append([key, 7, np.diff(uids, prepend=uids[:1]).tobytes()])
    return {"rows": rows}


def bench_codec(payload: dict) -> dict:
    t0 = time.perf_counter()
    old_body = json.dumps(_old_jsonize(payload)).encode()
    t_old_enc = time.perf_counter() - t0
    t0 = time.perf_counter()
    _old_unjsonize(json.loads(old_body))
    t_old_dec = time.perf_counter() - t0

    t0 = time.perf_counter()
    new_body = pack_body(payload)
    t_new_enc = time.perf_counter() - t0
    t0 = time.perf_counter()
    unpack_body(new_body)
    t_new_dec = time.perf_counter() - t0

    return {
        "payload_mb": round(
            sum(len(r[0]) + len(r[2]) for r in payload["rows"]) / 1e6, 1
        ),
        "old_wire_mb": round(len(old_body) / 1e6, 2),
        "new_wire_mb": round(len(new_body) / 1e6, 2),
        "old_enc_s": round(t_old_enc, 3),
        "old_dec_s": round(t_old_dec, 3),
        "new_enc_s": round(t_new_enc, 3),
        "new_dec_s": round(t_new_dec, 3),
        "wire_ratio": round(len(old_body) / len(new_body), 2),
        "cpu_speedup": round(
            (t_old_enc + t_old_dec) / (t_new_enc + t_new_dec), 2
        ),
    }


def bench_typed(payload: dict) -> dict:
    """Typed KVList (conn/messages.py, pb wire format) vs the legacy
    JSON+b64 body for the same record batch — the VERDICT r4 #6 metric:
    small-record wire_ratio must exceed 1.0 (typed bytes < JSON bytes)."""
    from dgraph_tpu.conn.messages import KV, KVList

    t0 = time.perf_counter()
    old_body = json.dumps(_old_jsonize(payload)).encode()
    t_old_enc = time.perf_counter() - t0
    t0 = time.perf_counter()
    _old_unjsonize(json.loads(old_body))
    t_old_dec = time.perf_counter() - t0

    t0 = time.perf_counter()
    msg = KVList(
        kv=[KV(key=k, ts=ts, value=v) for k, ts, v in payload["rows"]]
    )
    typed_body = msg.encode()
    t_new_enc = time.perf_counter() - t0
    t0 = time.perf_counter()
    back = KVList.decode(typed_body)
    t_new_dec = time.perf_counter() - t0
    assert len(back.kv) == len(payload["rows"])

    return {
        "payload_mb": round(
            sum(len(r[0]) + len(r[2]) for r in payload["rows"]) / 1e6, 1
        ),
        "old_wire_mb": round(len(old_body) / 1e6, 2),
        "typed_wire_mb": round(len(typed_body) / 1e6, 2),
        "old_enc_s": round(t_old_enc, 3),
        "old_dec_s": round(t_old_dec, 3),
        "typed_enc_s": round(t_new_enc, 3),
        "typed_dec_s": round(t_new_dec, 3),
        "wire_ratio": round(len(old_body) / len(typed_body), 2),
        "cpu_speedup": round(
            (t_old_enc + t_old_dec) / (t_new_enc + t_new_dec), 2
        ),
    }


def bench_proc_move(n_edges: int) -> dict:
    """A real cross-process predicate move over the live RPC framing."""
    import tempfile

    from dgraph_tpu.worker.harness import ProcCluster

    with tempfile.TemporaryDirectory(prefix="framing_move_") as td:
        pc = ProcCluster(n_groups=2, replicas=1, data_dir=td)
        try:
            pc.alter("name: string .\nfollow: [uid] .")
            rng = np.random.default_rng(3)
            batch = []
            t0 = time.time()
            for i in range(1, n_edges + 1):
                s, o = int(rng.integers(1, 5000)), int(rng.integers(1, 5000))
                batch.append(f"<0x{s:x}> <follow> <0x{o:x}> .")
                if len(batch) >= 2000:
                    t = pc.new_txn()
                    t.mutate_rdf(set_rdf="\n".join(batch), commit_now=True)
                    batch = []
            if batch:
                t = pc.new_txn()
                t.mutate_rdf(set_rdf="\n".join(batch), commit_now=True)
            load_s = time.time() - t0

            src = pc.zero.belongs_to("follow")
            dst = 2 if src == 1 else 1
            t0 = time.time()
            pc.move_tablet("follow", dst)
            move_s = time.time() - t0
            return {
                "edges": n_edges,
                "load_s": round(load_s, 2),
                "move_s": round(move_s, 2),
                "from_group": src,
                "to_group": dst,
            }
        finally:
            pc.close()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default=None)
    ap.add_argument("--move-edges", type=int, default=30_000)
    args = ap.parse_args()

    from dgraph_tpu.conn import frame

    big = tablet_payload(200, 1 << 18)
    frame._COMPRESS = True
    compressed = bench_codec(big)
    frame._COMPRESS = False
    out = {
        # ~50MB tablet stream: 200 keys x 256KB packs (default raw mode)
        "codec_50mb_raw": bench_codec(big),
        # same payload with DGRAPH_TPU_WIRE_COMPRESS=1 (DCN-class links)
        "codec_50mb_zlib": compressed,
        # many-small-records shape (index keys)
        "codec_small_records": bench_codec(tablet_payload(20_000, 64)),
        # typed control-plane messages (conn/messages.py): the shape
        # RemoteKV/tablet-move streams actually use now
        "typed_small_records": bench_typed(tablet_payload(20_000, 64)),
        "typed_large_records": bench_typed(tablet_payload(2_000, 4096)),
    }
    print(json.dumps(out, indent=1), flush=True)
    if args.move_edges:
        out["proc_move"] = bench_proc_move(args.move_edges)
    blob = json.dumps(out, indent=1)
    print(blob)
    if args.json:
        with open(args.json, "w") as f:
            f.write(blob)


if __name__ == "__main__":
    main()

"""North-star metric: LDBC SNB 2-hop friends-of-friends edges/sec.

BASELINE.json's headline config — "systest/ldbc SNB interactive short
reads" / "friends-of-friends 2-hop traversal (batched UID intersect)",
target >=5x CPU on TPU. The real SNB dataset is CI-fetched and not
available here; benchmarks/ldbc_corpus.py generates the same shape at a
configurable scale.

Measures, through the FULL engine (parse -> plan -> dispatch -> merge):
  - 2-hop FoF queries from a batch of person roots (var block + uid()
    expansion + NOT-filters, the IS-style traversal),
  - edges traversed per second (knows edges touched at both hops),
  - per-query latency.

Usage: python benchmarks/ldbc_bench.py [--persons 20000] [--roots 64]
                                       [--json out]
"""

import sys as _sys

_sys.path.insert(0, "/root/repo") if "/root/repo" not in _sys.path else None
from dgraph_tpu.devsetup import maybe_force_cpu

maybe_force_cpu()

import argparse
import json
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--persons", type=int, default=20_000)
    ap.add_argument("--roots", type=int, default=64)
    ap.add_argument("--json", default=None)
    args = ap.parse_args()

    import jax

    from benchmarks.ldbc_corpus import generate, SCHEMA
    from dgraph_tpu.api.server import Server
    from dgraph_tpu.loaders.bulk2 import ParallelBulkLoader

    rng = np.random.default_rng(11)
    t0 = time.time()
    corpus, rdf = generate(
        n_persons=args.persons,
        n_posts=args.persons // 4,
        n_comments=args.persons // 4,
    )
    gen_s = time.time() - t0

    s = Server()
    s.alter(SCHEMA)
    t0 = time.time()
    ParallelBulkLoader(s).load_text("\n".join(rdf))
    load_s = time.time() - t0

    person_uids = list(corpus.persons)
    roots = [
        person_uids[int(rng.integers(len(person_uids)))]
        for _ in range(args.roots)
    ]

    def fof_query(pu):
        sid = corpus.persons[pu].sid
        return (
            f'{{ me as var(func: eq(fqid, "person_{sid}")) {{ f as knows }} '
            "q(func: uid(f)) { fof as knows @filter(NOT uid(me) AND NOT uid(f)) } "
            "res(func: uid(fof)) { count(uid) } }"
        )

    # warm (compiles)
    s.query(fof_query(roots[0]))

    # edge accounting OUTSIDE the timed loop (round 3 timed this O(E)
    # model scan per root and recorded it as engine latency)
    corpus.adjacency()
    per_root_edges = {}
    for pu in roots:
        direct = {f for f, _ in corpus.knows_of(pu)}
        per_root_edges[pu] = len(direct) + sum(
            len(corpus.knows_of(f)) for f in direct
        )
    edges = sum(per_root_edges[pu] for pu in roots)

    queries = [fof_query(pu) for pu in roots]
    t0 = time.time()
    for q in queries:
        out = s.query(q)
        assert "errors" not in out, out
    wall = time.time() - t0

    # batched-roots variant: every root in ONE uid() block — the
    # "batched UID intersect" shape the north star describes. One parse
    # + one level-batched dispatch per hop for all roots together.
    # Edge accounting matches the batched semantics: roots dedupe in
    # eq(fqid, [...]), and each unique friend's knows list is traversed
    # once for the whole batch (NOT once per root as in the loop above).
    uroots = sorted(set(roots))
    union_friends = {
        f for r in uroots for f, _ in corpus.knows_of(r)
    }
    batched_edges = sum(len(corpus.knows_of(r)) for r in uroots) + sum(
        len(corpus.knows_of(f)) for f in union_friends
    )
    # model golden for the global exclusion semantics:
    # fof = (union of friends' knows) - me - f
    want_fof = {
        g for f in union_friends for g, _ in corpus.knows_of(f)
    } - set(uroots) - union_friends
    all_sids = ", ".join(f'"person_{corpus.persons[pu].sid}"' for pu in uroots)
    batched_q = (
        f"{{ me as var(func: eq(fqid, [{all_sids}])) {{ f as knows }} "
        "q(func: uid(f)) { fof as knows @filter(NOT uid(me) AND NOT uid(f)) } "
        "res(func: uid(fof)) { count(uid) } }"
    )
    out = s.query(batched_q)  # warm + validate against the model
    assert "errors" not in out, out
    got_count = out["data"]["res"][0]["count"]
    batched_ok = got_count == len(want_fof)
    reps = 5
    t0 = time.time()
    for _ in range(reps):
        out = s.query(batched_q)
        assert "errors" not in out, out
    batched_wall = (time.time() - t0) / reps

    # correctness spot-check vs the model
    pu = roots[0]
    out = s.query(fof_query(pu).replace("count(uid)", "id"))
    got = sorted(r["id"] for r in out["data"]["res"])
    want = sorted(corpus.persons[u].sid for u in corpus.friends_of_friends(pu))
    ok = got == want

    result = {
        "persons": args.persons,
        "knows_edges": 2 * len(corpus.knows),
        "gen_seconds": round(gen_s, 1),
        "load_seconds": round(load_s, 1),
        "load_edges_per_sec": round(corpus.n_edges / load_s),
        "roots": args.roots,
        "fof_edges_per_sec": round(edges / wall),
        "latency_ms_per_query": round(wall / args.roots * 1e3, 2),
        "batched_fof_edges_per_sec": round(batched_edges / batched_wall),
        "batched_latency_ms": round(batched_wall * 1e3, 2),
        "batched_conformant": batched_ok,
        "conformant": ok,
        "device": str(jax.devices()[0]),
    }
    text = json.dumps(result, indent=1)
    print(text)
    if args.json:
        with open(args.json, "w") as f:
            f.write(text)


if __name__ == "__main__":
    main()

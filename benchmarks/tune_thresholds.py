"""Measure the host-vs-device break-even for the dispatcher thresholds.

_DEVICE_MIN_TOTAL (query/dispatch.py) decides when a batch of set ops is
worth a device dispatch instead of host numpy/C++. It shipped as a guess
(32k); this script measures, on the LIVE backend:

  - host path latency (the dispatcher's vectorized searchsorted fallback
    + native C++ loops) across total-work sizes,
  - device round-trip latency for the same batches (upload, vmapped
    kernel, download),

and reports the crossover total. Run with the TPU tunnel up to tune for
real dispatch latency; the recommended value is printed and can be
pinned via DGRAPH_TPU_DEVICE_MIN_TOTAL.

Usage: python benchmarks/tune_thresholds.py [--json out]
"""

import sys as _sys

_sys.path.insert(0, "/root/repo") if "/root/repo" not in _sys.path else None

import argparse
import json
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default=None)
    args = ap.parse_args()

    import jax

    from dgraph_tpu.query.dispatch import SetOpDispatcher

    backend = jax.default_backend()
    rng = np.random.default_rng(3)

    rows = []
    crossover = None
    # batch of 32 rows vs one shared big operand — the dominant query shape
    for big in [1 << k for k in range(10, 23)]:
        b = np.sort(
            rng.choice(np.uint64(1) << np.uint64(33), size=big, replace=False)
        ).astype(np.uint64)
        rws = [np.sort(rng.choice(b, size=16)).astype(np.uint64) for _ in range(32)]
        total = sum(len(r) for r in rws) + len(b)

        d = SetOpDispatcher()
        # host path: force the threshold above total
        import dgraph_tpu.query.dispatch as dmod

        old_min, old_force = dmod._DEVICE_MIN_TOTAL, dmod._FORCE_DEVICE
        try:
            dmod._DEVICE_MIN_TOTAL, dmod._FORCE_DEVICE = 1 << 62, False
            d.run_rows_vs_one("intersect", rws, b)  # warm
            t0 = time.perf_counter()
            for _ in range(10):
                d.run_rows_vs_one("intersect", rws, b)
            t_host = (time.perf_counter() - t0) / 10

            dmod._DEVICE_MIN_TOTAL, dmod._FORCE_DEVICE = 0, True
            d.run_rows_vs_one("intersect", rws, b)  # warm/compile
            t0 = time.perf_counter()
            for _ in range(10):
                d.run_rows_vs_one("intersect", rws, b)
            t_dev = (time.perf_counter() - t0) / 10
        finally:
            dmod._DEVICE_MIN_TOTAL, dmod._FORCE_DEVICE = old_min, old_force

        row = {
            "total": total,
            "big": big,
            "host_us": round(t_host * 1e6, 1),
            "device_us": round(t_dev * 1e6, 1),
        }
        rows.append(row)
        print(json.dumps(row), flush=True)
        if crossover is None and t_dev < t_host:
            crossover = total

    rec = crossover if crossover is not None else 1 << 62
    result = {
        "backend": backend,
        "rows": rows,
        "crossover_total": crossover,
        "recommended_DEVICE_MIN_TOTAL": rec,
    }
    if args.json:
        with open(args.json, "w") as f:
            json.dump(result, f, indent=1)
    print(json.dumps(result, indent=1))


if __name__ == "__main__":
    main()

"""Measure the host-vs-device break-even for the dispatcher thresholds.

_DEVICE_MIN_TOTAL (query/dispatch.py) decides when a batch of set ops is
worth a device dispatch instead of host numpy/C++. It shipped as a guess
(32k); this script measures, on the LIVE backend:

  - host path latency (the dispatcher's vectorized searchsorted fallback
    + native C++ loops) across total-work sizes,
  - device round-trip latency for the same batches (upload, vmapped
    kernel, download),

and reports the crossover total. Run with the TPU tunnel up to tune for
real dispatch latency; the recommended value is printed and can be
pinned via DGRAPH_TPU_DEVICE_MIN_TOTAL.

It also sweeps the packed-vs-decode crossover (--packed-only for just that
sweep; it runs after the device sweep by default):
the compressed-domain block-skip ops (ops/packed_setops.py) win when the
big operand is selective relative to the small one; below the crossover
ratio, one full decode + the dense kernels win. The recommended ratio is
printed and pinned the same way dispatch._min_total is — via
DGRAPH_TPU_PACKED_MIN_RATIO (default in query/dispatch.py).

Usage: python benchmarks/tune_thresholds.py [--json out] [--packed-json out]
"""

import sys as _sys

_sys.path.insert(0, "/root/repo") if "/root/repo" not in _sys.path else None

import argparse
import json
import time

import numpy as np


def sweep_packed(out_json=None):
    """Measure the packed-vs-decode crossover RATIO (|big| / |small|) on
    the live host kernels (run via --packed-only), in BOTH operand shapes
    the dispatcher sees:

      rows       array x pack (materialized small side): t_packed =
                 adaptive stream engine (or candidate-block decode
                 without the native lib) vs t_decoded = full decode +
                 intersect. The crossover here pins PACKED_MIN_RATIO.
      pair_rows  pack x pack (both sides compressed, the posting-list
                 vs posting-list shape): the per-block pair engine vs
                 decoding BOTH operands. With the bitmap/packed hybrid
                 kernels this wins at every ratio (crossover 1), which
                 is why the dispatcher runs both-packed pairs through
                 the engine unconditionally.

    A fresh pack per ratio row; one warmup call builds the pack's skip
    metadata (block_maxes + bitmap sidecars + cached ctypes pointers)
    before timing — that matches production, where a pack's metadata
    persists across queries while the decode itself re-runs per commit
    epoch (the decoded side here pays full decode every rep as the
    first-touch proxy)."""
    import time

    import numpy as np

    from dgraph_tpu import native
    from dgraph_tpu.codec import uidpack
    from dgraph_tpu.ops import packed_setops

    rng = np.random.default_rng(7)
    big_n = 1_000_000
    b = np.unique(
        rng.integers(1, 1 << 33, big_n + big_n // 8, dtype=np.uint64)
    )[:big_n]
    rows = []
    pair_rows = []
    for ratio in [1, 2, 4, 8, 16, 64, 256, 1024, 10_000, 100_000]:
        pack = uidpack.encode(b)  # fresh pack: no metadata carry-over
        small_n = max(1, big_n // ratio)
        a = np.sort(rng.choice(b, small_n, replace=False))
        reps = 5 if small_n > 10_000 else 20

        def best_of(fn, n):
            # best-of timing: robust to scheduler noise on shared boxes
            best, got = float("inf"), None
            for _ in range(n):
                t0 = time.perf_counter()
                got = fn()
                best = min(best, time.perf_counter() - t0)
            return best, got

        packed_setops.intersect_packed(a, pack)  # warm skip metadata
        t_packed, got_p = best_of(
            lambda: packed_setops.intersect_packed(a, pack), reps
        )
        t_decoded, got_d = best_of(
            lambda: native.intersect(uidpack.decode(pack), a), reps
        )
        np.testing.assert_array_equal(got_p, np.sort(got_d))

        row = {
            "ratio": ratio,
            "small": small_n,
            "packed_us": round(t_packed * 1e6, 1),
            "decoded_us": round(t_decoded * 1e6, 1),
        }
        rows.append(row)
        print(json.dumps(row), flush=True)

        # pack x pack: both operands compressed through the pair engine
        pa = uidpack.encode(a)
        packed_setops.intersect_packed(pa, pack)  # warm sidecars
        t_pair, got_pp = best_of(
            lambda: packed_setops.intersect_packed(pa, pack), reps
        )
        t_both, got_dd = best_of(
            lambda: native.intersect(
                uidpack.decode(pa), uidpack.decode(pack)
            ),
            reps,
        )
        np.testing.assert_array_equal(got_pp, got_dd)
        prow = {
            "ratio": ratio,
            "small": small_n,
            "pair_engine_us": round(t_pair * 1e6, 1),
            "decode_both_us": round(t_both * 1e6, 1),
        }
        pair_rows.append(prow)
        print(json.dumps(prow), flush=True)

    # robust crossover: smallest ratio from which packed wins (within 5%
    # noise) at EVERY larger ratio — a single noisy win must not pin a
    # too-aggressive threshold
    def _crossover(rs, pk, dk):
        for row in rs:
            if all(
                r[pk] <= r[dk] * 1.05 for r in rs if r["ratio"] >= row["ratio"]
            ):
                return row["ratio"]
        return None

    crossover = _crossover(rows, "packed_us", "decoded_us")
    pair_crossover = _crossover(pair_rows, "pair_engine_us", "decode_both_us")
    result = {
        "big": big_n,
        "rows": rows,
        "pair_rows": pair_rows,
        "crossover_ratio": crossover,
        "pair_crossover_ratio": pair_crossover,
        "recommended_PACKED_MIN_RATIO": crossover if crossover else 1 << 30,
    }
    if out_json:
        from benchmarks import stamp

        import jax

        stamp.guarded_write(out_json, result, jax.default_backend())
    print(json.dumps(result, indent=1))
    return result


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default=None)
    ap.add_argument("--packed-json", default=None)
    ap.add_argument(
        "--packed-only", action="store_true",
        help="run only the packed-vs-decode crossover sweep",
    )
    args = ap.parse_args()

    if args.packed_only:
        sweep_packed(args.packed_json)
        return

    import jax

    from dgraph_tpu.query.dispatch import SetOpDispatcher

    backend = jax.default_backend()
    rng = np.random.default_rng(3)

    rows = []
    crossover = None
    # batch of 32 rows vs one shared big operand — the dominant query shape
    for big in [1 << k for k in range(10, 23)]:
        b = np.sort(
            rng.choice(np.uint64(1) << np.uint64(33), size=big, replace=False)
        ).astype(np.uint64)
        rws = [np.sort(rng.choice(b, size=16)).astype(np.uint64) for _ in range(32)]
        total = sum(len(r) for r in rws) + len(b)

        d = SetOpDispatcher()
        # host path: force the threshold above total
        import dgraph_tpu.query.dispatch as dmod

        old_min, old_force = dmod._DEVICE_MIN_TOTAL, dmod._FORCE_DEVICE
        try:
            dmod._DEVICE_MIN_TOTAL, dmod._FORCE_DEVICE = 1 << 62, False
            d.run_rows_vs_one("intersect", rws, b)  # warm
            t0 = time.perf_counter()
            for _ in range(10):
                d.run_rows_vs_one("intersect", rws, b)
            t_host = (time.perf_counter() - t0) / 10

            dmod._DEVICE_MIN_TOTAL, dmod._FORCE_DEVICE = 0, True
            d.run_rows_vs_one("intersect", rws, b)  # warm/compile
            t0 = time.perf_counter()
            for _ in range(10):
                d.run_rows_vs_one("intersect", rws, b)
            t_dev = (time.perf_counter() - t0) / 10
        finally:
            dmod._DEVICE_MIN_TOTAL, dmod._FORCE_DEVICE = old_min, old_force

        row = {
            "total": total,
            "big": big,
            "host_us": round(t_host * 1e6, 1),
            "device_us": round(t_dev * 1e6, 1),
        }
        rows.append(row)
        print(json.dumps(row), flush=True)
        if crossover is None and t_dev < t_host:
            crossover = total

    rec = crossover if crossover is not None else 1 << 62
    result = {
        "backend": backend,
        "rows": rows,
        "crossover_total": crossover,
        "recommended_DEVICE_MIN_TOTAL": rec,
    }
    if args.json:
        with open(args.json, "w") as f:
            json.dump(result, f, indent=1)
    print(json.dumps(result, indent=1))
    sweep_packed(args.packed_json)


if __name__ == "__main__":
    main()

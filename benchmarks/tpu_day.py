"""One-command TPU benchmark day (VERDICT r2 next #4).

When the axon tunnel is up, this converts it into the full set of
hardware numbers in one run, each stage a separate subprocess so a
single stage failing (or the tunnel dropping mid-run) still leaves the
others' JSON on disk:

  1. bench.py               — headline batched 10v1M intersect + ratio sweep
  2. pallas_bench.py        — Pallas compare-all sweep vs XLA searchsorted, compiled
  3. tune_thresholds.py     — host/device crossover for _DEVICE_MIN_TOTAL
  4. bench_suite.py         — 2-hop engine traversal + vector QPS (brute/IVF)
  5. scale_suite.py         — 1M-edge corpus, 11 golden queries, device on

Usage:
  python benchmarks/tpu_day.py [--out TPU_DAY.json] [--scale small|full]
                               [--edges 1000000] [--skip stage,...]

Emits ONE combined JSON at --out. Designed to run end-to-end on the CPU
fallback too (stages detect the backend themselves).
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # `python benchmarks/tpu_day.py` puts only
    sys.path.insert(0, REPO)  # benchmarks/ on sys.path


def run_stage(name, argv, timeout_s, out):
    print(f"=== stage {name}: {' '.join(argv)}", flush=True)
    t0 = time.time()
    try:
        p = subprocess.run(
            argv,
            cwd=REPO,
            timeout=timeout_s,
            capture_output=True,
            text=True,
        )
        out[name] = {
            "rc": p.returncode,
            "wall_s": round(time.time() - t0, 1),
        }
        if p.returncode != 0:
            out[name]["stderr_tail"] = p.stderr[-2000:]
        return p
    except subprocess.TimeoutExpired:
        out[name] = {"rc": -1, "error": f"timeout after {timeout_s}s"}
        return None


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(REPO, "TPU_DAY.json"))
    ap.add_argument("--scale", choices=["small", "full"], default="small")
    ap.add_argument("--edges", type=int, default=1_000_000)
    ap.add_argument("--skip", default="")
    args = ap.parse_args()
    skip = set(filter(None, args.skip.split(",")))

    tmp = tempfile.mkdtemp(prefix="tpu_day_")
    results = {"started": time.strftime("%Y-%m-%dT%H:%M:%S"), "stages": {}}
    st = results["stages"]
    py = sys.executable

    if "bench" not in skip:
        p = run_stage("bench", [py, "bench.py"], 900, st)
        if p and p.returncode == 0:
            try:
                # bench.py emits one JSON line per metric (headline +
                # packed + decode-bytes ladder) — keep them all, with the
                # headline under the historical "result" key
                recs = [
                    json.loads(l)
                    for l in p.stdout.strip().splitlines()
                    if l.startswith("{")
                ]
                st["bench"]["result"] = next(
                    (
                        r
                        for r in recs
                        if r.get("metric") == "intersect_10v1M_batch256"
                    ),
                    recs[-1],
                )
                st["bench"]["all_metrics"] = recs
                st["bench"]["sweep_stderr"] = p.stderr[-1500:]
            except Exception:
                st["bench"]["raw"] = p.stdout[-1000:]

    if "pallas" not in skip:
        j = os.path.join(tmp, "pallas.json")
        p = run_stage(
            "pallas", [py, "benchmarks/pallas_bench.py", "--json", j], 1200, st
        )
        if os.path.exists(j):
            st["pallas"]["result"] = json.load(open(j))

    if "thresholds" not in skip:
        j = os.path.join(tmp, "thr.json")
        pj = os.path.join(tmp, "thr_packed.json")
        # 2400s: the device sweep AND the packed-crossover sweep both run;
        # the packed capture is what re-pins DGRAPH_TPU_PACKED_MIN_RATIO
        # on TPU (NOTES_NEXT_ROUND §1)
        p = run_stage(
            "thresholds",
            [
                py, "benchmarks/tune_thresholds.py",
                "--json", j, "--packed-json", pj,
            ],
            2400,
            st,
        )
        if os.path.exists(j):
            st["thresholds"]["result"] = json.load(open(j))
        if os.path.exists(pj):
            st["thresholds"]["packed"] = json.load(open(pj))

    if "suite" not in skip:
        j = os.path.join(tmp, "suite.json")
        p = run_stage(
            "suite",
            [py, "benchmarks/bench_suite.py", "--scale", args.scale, "--json", j],
            5400,
            st,
        )
        if os.path.exists(j):
            st["suite"]["result"] = json.load(open(j))

    if "scale" not in skip:
        j = os.path.join(tmp, "scale.json")
        p = run_stage(
            "scale",
            [py, "benchmarks/scale_suite.py", "--edges", str(args.edges), "--json", j],
            7200,
            st,
        )
        if os.path.exists(j):
            st["scale"]["result"] = json.load(open(j))

    results["finished"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    # provenance + TPU-artifact overwrite guard (VERDICT r4 #2): the
    # platform is whatever the bench stage actually detected
    from benchmarks.stamp import guarded_write

    platform = "unknown"
    bench_res = st.get("bench", {}).get("result") or {}
    if bench_res.get("platform"):
        platform = bench_res["platform"]
    elif "cpu_fallback" in json.dumps(bench_res):
        platform = "cpu_fallback"
    wrote = guarded_write(args.out, results, platform)
    print(json.dumps({"out": wrote, "stages": list(st)}, indent=1))


if __name__ == "__main__":
    main()

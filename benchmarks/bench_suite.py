"""Extended benchmark suite: the BASELINE.md north-star configs.

Measures (sized by --scale to fit the machine):
  1. 2-hop friends-of-friends traversal through the full engine
     (BASELINE.md: systest/1million 2-hop, metric = edges/sec)
  2. vector top-k QPS (BASELINE.md: 1M x 768 f32 top-10; scaled variant
     on small machines), brute-force exact + IVF@recall
  3. batched intersect throughput (algo/benchmarks shapes)

Usage: python benchmarks/bench_suite.py [--scale small|full] [--json out]
Prints one JSON object with all results (bench.py stays the single-line
driver contract; this is the detailed harness).
"""

import sys as _sys

_sys.path.insert(0, "/root/repo") if "/root/repo" not in _sys.path else None
from dgraph_tpu.devsetup import maybe_force_cpu

maybe_force_cpu()  # JAX_PLATFORMS=cpu must also unregister the axon plugin


import argparse
import json
import sys
import time

import numpy as np


def bench_2hop(scale: str) -> dict:
    from dgraph_tpu.api.server import Server
    from dgraph_tpu.loaders.bulk import BulkLoader
    from dgraph_tpu.loaders.rdf import NQuad

    n_users = 20_000 if scale == "small" else 200_000
    deg = 20
    rng = np.random.default_rng(0)

    s = Server()
    s.alter("name: string @index(exact) .\nfriend: [uid] @reverse @count .")
    loader = BulkLoader(s)
    t0 = time.time()
    for u in range(1, n_users + 1):
        loader.add_nquad(NQuad(subject=hex(u), predicate="name",
                               object_value=_val(f"user{u}")))
        for v in rng.integers(1, n_users + 1, deg):
            if int(v) != u:
                loader.add_nquad(
                    NQuad(subject=hex(u), predicate="friend",
                          object_id=hex(int(v)))
                )
    loader.finish()
    load_s = time.time() - t0

    # 2-hop expansion from a batch of roots; count traversed edges
    roots = rng.integers(1, n_users + 1, 64)
    t0 = time.time()
    edges = 0
    for r in roots:
        res = s.query(
            "{ q(func: uid(%s)) { friend { friend { uid } } } }" % hex(int(r))
        )["data"]
        for f1 in res["q"][0].get("friend", []):
            edges += 1 + len(f1.get("friend", []))
    dt = time.time() - t0
    return {
        "n_users": n_users,
        "avg_degree": deg,
        "load_seconds": round(load_s, 2),
        "queries": len(roots),
        "edges_traversed": edges,
        "edges_per_sec": round(edges / dt, 1),
        "latency_ms_per_query": round(dt / len(roots) * 1e3, 2),
    }


def bench_vector(scale: str) -> dict:
    """Vector QPS, measured the way ANN benches are: a query batch per
    dispatch (search_batch — one device round trip per 64 queries) plus
    an honest single-query latency. recall@10 for IVF is computed against
    the brute tier's exact results over ALL timed queries."""
    import gc

    import jax

    from dgraph_tpu.models.vector import VectorIndex

    n, d = (100_000, 256) if scale == "small" else (1_000_000, 768)
    k = 10
    qb, nq = 64, 256
    rng = np.random.default_rng(1)
    # mixture-of-gaussians corpus: real embedding sets cluster; pure
    # isotropic gaussian is IVF's pathological worst case (distance
    # concentration) and misrepresents production recall
    n_clusters = 256
    centers = rng.standard_normal((n_clusters, d)).astype(np.float32) * 4.0
    assign = rng.integers(0, n_clusters, n)
    V = centers[assign] + rng.standard_normal((n, d)).astype(np.float32)
    Qs = (
        centers[rng.integers(0, n_clusters, nq)]
        + rng.standard_normal((nq, d))
    ).astype(np.float32)

    uids = np.arange(1, n + 1, dtype=np.uint64)

    idx = VectorIndex("emb", ivf_threshold=1 << 62)  # brute force tier
    idx.bulk_load(uids, V)

    idx.search_batch(Qs[:qb], k)  # compile + upload
    t0 = time.time()
    exact = [idx.search_batch(Qs[i : i + qb], k) for i in range(0, nq, qb)]
    brute_qps = nq / (time.time() - t0)
    exact = np.concatenate(exact, axis=0)

    idx.search(Qs[0], k)  # warm the single-query jit before timing
    t0 = time.time()
    for q in Qs[:10]:
        idx.search(q, k)
    brute_ms_single = (time.time() - t0) / 10 * 1e3

    # free the brute tier's device arrays before the IVF build: at
    # 1Mx768 both tiers together would not fit a 16GB chip
    idx._device = None
    del idx
    gc.collect()

    idx2 = VectorIndex("emb2", ivf_threshold=1)  # auto nprobe
    idx2.bulk_load(uids, V)
    t0 = time.time()
    if idx2._use_quant():
        idx2._quant_view()  # quantize + centroid train + cell assignment
    else:
        idx2._sync_device()  # corpus device upload + slab IVF train
    ivf_sync_build_s = time.time() - t0

    idx2.search_batch(Qs[:qb], k)  # compile
    t0 = time.time()
    got = [idx2.search_batch(Qs[i : i + qb], k) for i in range(0, nq, qb)]
    ivf_qps = nq / (time.time() - t0)
    got = np.concatenate(got, axis=0)

    idx2.search(Qs[0], k)  # warm the single-query jit before timing
    t0 = time.time()
    for q in Qs[:10]:
        idx2.search(q, k)
    ivf_ms_single = (time.time() - t0) / 10 * 1e3

    hits = sum(
        len(set(map(int, got[i])) & set(map(int, exact[i])))
        for i in range(nq)
    )
    return {
        "n_vectors": n,
        "dim": d,
        "query_batch": qb,
        "brute_force_qps": round(brute_qps, 1),
        "brute_latency_ms_single": round(brute_ms_single, 2),
        "ivf_qps": round(ivf_qps, 1),
        "ivf_latency_ms_single": round(ivf_ms_single, 2),
        "ivf_sync_build_seconds": round(ivf_sync_build_s, 1),
        "ivf_recall_at_10": round(hits / (nq * k), 3),
        "device": str(jax.devices()[0]),
    }


def bench_intersect() -> dict:
    import jax
    import jax.numpy as jnp

    from dgraph_tpu.ops import setops

    rng = np.random.default_rng(0)
    big = np.unique(rng.integers(0, 1 << 31, 1_200_000, dtype=np.uint64)).astype(
        np.uint32
    )[: 1 << 20]
    out = {}
    for batch, small_n in ((256, 10), (64, 1000)):
        A = np.full((batch, max(16, 1 << (small_n - 1).bit_length())), 0xFFFFFFFF, np.uint32)
        LA = np.zeros((batch,), np.int32)
        for i in range(batch):
            a = np.sort(rng.choice(big, small_n, replace=False))
            A[i, : len(a)] = a
            LA[i] = len(a)
        fn = jax.jit(jax.vmap(setops.intersect, in_axes=(0, 0, None, None)))
        # device arrays made ONCE: re-converting per call ships the
        # operands through the device tunnel every iteration and measures
        # transfer, not the kernel
        Ad, LAd = jnp.asarray(A), jnp.asarray(LA)
        Bd, LBd = jnp.asarray(big), np.int32(big.size)
        r = fn(Ad, LAd, Bd, LBd)
        jax.block_until_ready(r)
        t0 = time.time()
        for _ in range(5):
            r = fn(Ad, LAd, Bd, LBd)
            jax.block_until_ready(r)
        dt = (time.time() - t0) / 5
        out[f"batch{batch}_{small_n}v1M_ns_per_op"] = round(dt / batch * 1e9, 1)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", choices=["small", "full"], default="small")
    ap.add_argument("--json", default=None)
    args = ap.parse_args()

    results = {}
    for name, fn in (
        ("two_hop", lambda: bench_2hop(args.scale)),
        ("vector", lambda: bench_vector(args.scale)),
        ("intersect", bench_intersect),
    ):
        print(f"running {name}...", file=sys.stderr)
        t0 = time.time()
        results[name] = fn()
        print(f"  {name} done in {time.time()-t0:.1f}s", file=sys.stderr)

    blob = json.dumps(results, indent=2)
    print(blob)
    if args.json:
        with open(args.json, "w") as f:
            f.write(blob)


def _val(s):
    from dgraph_tpu.types.types import TypeID, Val

    return Val(TypeID.STRING, s)


if __name__ == "__main__":
    main()

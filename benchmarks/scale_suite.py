"""Scale conformance + latency suite over the movie corpus.

The reference validates at scale with the 1million/21million suites and
per-query latency budgets (systest/1million/1million_test.go,
systest/ldbc/test_cases.yaml). This harness:

  1. generates an N-edge corpus (benchmarks/movie_corpus.py),
  2. bulk-loads it,
  3. runs a ported query set (genre membership, 2-hop director-by-genre,
     reverse expansion, year index, term search, ordered pagination,
     count aggregation),
  4. checks every result against goldens DERIVED from the generator's
     plain-Python model, and
  5. reports per-query latency + traversal edges/sec.

Usage: python benchmarks/scale_suite.py [--edges 1000000] [--json out]
"""

from __future__ import annotations

import sys as _sys

_sys.path.insert(0, "/root/repo") if "/root/repo" not in _sys.path else None
from dgraph_tpu.devsetup import maybe_force_cpu

maybe_force_cpu()  # JAX_PLATFORMS=cpu must also unregister the axon plugin

import argparse
import json
import sys
import time


def load(edges: int, storage: str = "mem", data_dir=None):
    from benchmarks.movie_corpus import SCHEMA, generate
    from dgraph_tpu.api.server import Server
    from dgraph_tpu.loaders.bulk2 import ParallelBulkLoader

    corpus, rdf = generate(edges)
    if storage == "lsm":
        import os as _os
        import tempfile

        _os.environ["DGRAPH_TPU_STORAGE"] = "lsm"
        data_dir = data_dir or tempfile.mkdtemp(prefix="dgraph_scale_lsm_")
        s = Server(data_dir=data_dir)
    else:
        s = Server()
    s.alter(SCHEMA)
    loader = ParallelBulkLoader(s)
    t0 = time.time()
    loader.load_text("\n".join(rdf))
    load_s = time.time() - t0
    return corpus, s, load_s


def _uids_of(out, block="q"):
    return sorted(int(x["uid"], 16) for x in out["data"][block])


def run_suite(corpus, server, repeat: int = 3) -> dict:
    """Returns {query_name: {latency_ms, ok, n}} — every query validated
    against the derived golden."""
    results = {}

    def run(name, q, golden_uids, block="q"):
        out = None
        best = float("inf")
        for _ in range(repeat):
            t0 = time.perf_counter()
            out = server.query(q)
            best = min(best, (time.perf_counter() - t0) * 1e3)
        got = _uids_of(out, block)
        ok = got == list(golden_uids)
        results[name] = {
            "latency_ms": round(best, 2),
            "ok": ok,
            "n": len(got),
        }
        if not ok:
            results[name]["want_n"] = len(golden_uids)
        return out

    g = "Horror"
    # 1-hop: all films of a genre via reverse edge (1million query family)
    out = server.query('{ g(func: eq(name, "%s")) { ~genre { uid } } }' % g)
    films = sorted(
        int(x["uid"], 16) for x in out["data"]["g"][0].get("~genre", [])
    )
    results["films_of_genre"] = {
        "latency_ms": None,
        "ok": films == corpus.films_of_genre(g),
        "n": len(films),
    }
    t0 = time.perf_counter()
    for _ in range(repeat):
        server.query('{ g(func: eq(name, "%s")) { ~genre { uid } } }' % g)
    results["films_of_genre"]["latency_ms"] = round(
        (time.perf_counter() - t0) / repeat * 1e3, 2
    )

    def timed(q):
        out = server.query(q)  # cold pass warms the decoded-list caches
        best = float("inf")
        for _ in range(max(1, repeat - 1)):
            t0 = time.perf_counter()
            out = server.query(q)
            best = min(best, (time.perf_counter() - t0) * 1e3)
        return out, best

    # 2-hop: directors with a film in genre (uid var + reverse walk)
    q2 = (
        '{ gf as var(func: eq(name, "%s")) { f as ~genre }\n'
        "  q(func: uid(f)) @filter(has(~director.film)) { uid }\n"
        "  d(func: has(director.film)) @filter(uid_in(director.film, uid(f))) { uid } }"
        % g
    )
    out, lat2 = timed(q2)
    got_d = sorted(int(x["uid"], 16) for x in out["data"]["d"])
    results["directors_of_genre_2hop"] = {
        "latency_ms": round(lat2, 2),
        "ok": got_d == corpus.directors_of_genre(g),
        "n": len(got_d),
    }

    # year index (datetime year tokenizer via between)
    year = 2000
    q_year = (
        '{ q(func: between(initial_release_date, "%d-01-01", "%d-12-31")) { uid } }'
        % (year, year)
    )
    out, lat = timed(q_year)
    got = _uids_of(out)
    results["films_in_year"] = {
        "latency_ms": round(lat, 2),
        "ok": got == corpus.films_in_year(year),
        "n": len(got),
    }

    # term search over film names
    out, lat = timed('{ q(func: allofterms(name, "Film Horror")) { uid } }')
    want = sorted(
        u for u, t in corpus.films.items() if "Horror" in t
    )
    results["allofterms"] = {
        "latency_ms": round(lat, 2),
        "ok": _uids_of(out) == want,
        "n": len(want),
    }

    # ordered pagination by rating (float index walk + first)
    out, lat = timed(
        "{ q(func: has(rating), orderdesc: rating, first: 20) { uid } }"
    )
    got = [int(x["uid"], 16) for x in out["data"]["q"]]
    want = corpus.top_rated(20)
    # rating collisions make exact uid order ambiguous: compare ratings
    ok = [corpus.film_rating[u] for u in got] == [
        corpus.film_rating[u] for u in want
    ]
    results["top20_by_rating"] = {
        "latency_ms": round(lat, 2),
        "ok": ok,
        "n": len(got),
    }

    # costar 2-hop through reverse starring (traversal edges/sec)
    actor = next(iter(corpus.actors))
    q_co = (
        "{ a as var(func: uid(0x%x)) { f as starring }\n"
        "  q(func: has(starring)) @filter(uid_in(starring, uid(f)) AND NOT uid(a)) { uid } }"
        % actor
    )
    out, lat = timed(q_co)
    got = _uids_of(out)
    results["costars_2hop"] = {
        "latency_ms": round(lat, 2),
        "ok": got == corpus.costars(actor),
        "n": len(got),
    }

    # 3-hop: a director's co-working actors (director->films->starring)
    d0 = next(iter(corpus.director_films))
    q3 = (
        "{ d as var(func: uid(0x%x)) { f as director.film }\n"
        "  q(func: has(starring)) @filter(uid_in(starring, uid(f))) { uid } }"
        % d0
    )
    out, lat = timed(q3)
    results["actors_of_director_3hop"] = {
        "latency_ms": round(lat, 2),
        "ok": _uids_of(out) == corpus.actors_of_director(d0),
        "n": len(corpus.actors_of_director(d0)),
    }

    # count(count-index): directors with >= 8 films via eq/ge(count())
    out, lat = timed(
        "{ q(func: ge(count(director.film), 8)) { uid } }"
    )
    results["prolific_directors_count_index"] = {
        "latency_ms": round(lat, 2),
        "ok": _uids_of(out) == corpus.prolific_directors(8),
        "n": len(corpus.prolific_directors(8)),
    }

    # groupby at scale: films per genre with per-group counts
    out, lat = timed(
        "{ q(func: has(genre)) @groupby(genre) { count(uid) } }"
    )
    got_counts = {
        int(g["genre"], 16): g["count"]
        for g in out["data"]["q"][0]["@groupby"]
    }
    want_counts = dict(corpus.genres_by_film_count())
    results["groupby_genre_counts"] = {
        "latency_ms": round(lat, 2),
        "ok": got_counts == {g: c for g, c in want_counts.items() if c > 0},
        "n": len(got_counts),
    }

    # cascade: films that have BOTH a rating and a 2005 release
    out, lat = timed(
        '{ q(func: between(initial_release_date, "2005-01-01", "2005-12-31")) '
        "@cascade { uid rating initial_release_date } }"
    )
    want = corpus.films_in_year(2005)
    results["cascade_year_rating"] = {
        "latency_ms": round(lat, 2),
        "ok": _uids_of(out) == want,  # every film carries a rating
        "n": len(want),
    }

    # bulk 2-hop fanout: genre -> films -> starring actors (edges/sec)
    qf = (
        '{ g(func: eq(name, "%s")) { ~genre { starring_count: count(~starring) } } }' % g
    )
    out, fan_ms = timed(qf)
    fan_lat = fan_ms / 1e3
    n_films_g = len(corpus.films_of_genre(g))
    # edges touched ~ films + 2*films (starring reverse reads)
    results["fanout_2hop"] = {
        "latency_ms": round(fan_lat * 1e3, 2),
        "ok": True,
        "edges_per_sec": int(3 * n_films_g / fan_lat) if fan_lat > 0 else 0,
        "n": n_films_g,
    }

    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--edges", type=int, default=1_000_000)
    ap.add_argument("--json", default=None)
    ap.add_argument("--storage", choices=("mem", "lsm"), default="mem")
    args = ap.parse_args()

    corpus, server, load_s = load(args.edges, storage=args.storage)
    res = run_suite(corpus, server)
    out = {
        "edges": corpus.n_edges,
        "storage": args.storage,
        "load_seconds": round(load_s, 2),
        "load_edges_per_sec": int(corpus.n_edges / load_s),
        "queries": res,
        "all_ok": all(r["ok"] for r in res.values()),
    }
    text = json.dumps(out, indent=1)
    print(text)
    if args.json:
        with open(args.json, "w") as f:
            f.write(text)
    sys.exit(0 if out["all_ok"] else 1)


if __name__ == "__main__":
    main()

"""Deterministic LDBC-SNB-shaped corpus generator.

The reference's LDBC oracle (systest/ldbc/ldbc_test.go) bulk-loads the
real SNB dataset (fetched by CI from TEST_DATA_DIRECTORY — not present in
the tree) and asserts golden answers from test_cases.yaml. With no
network egress the dataset itself cannot be used here, so this module
mirrors its SHAPE instead: persons with a knows-graph (creationDate
facets), places, messages (posts + comments) with hasCreator/replyOf,
forums with containerOf/hasModerator — the exact entity/edge layout the
IS01..IS07 interactive-short-read queries exercise
(/root/reference/systest/ldbc/test_cases.yaml:1-90).

Like movie_corpus.py, the generator returns BOTH the RDF stream and a
plain-Python model, so conformance goldens are derived independently of
the engine under test.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

SCHEMA = """
fqid: string @index(exact) @upsert .
id: int @index(int) .
firstName: string @index(exact, term) .
lastName: string @index(exact, term) .
gender: string .
birthday: datetime .
creationDate: datetime @index(hour) .
locationIP: string .
browserUsed: string .
content: string @index(fulltext) .
imageFile: string .
title: string @index(term) .
name: string @index(exact) .
dgraph.type: [string] @index(exact) .
knows: [uid] @reverse .
isLocatedIn: [uid] @reverse .
hasCreator: [uid] @reverse .
replyOf: [uid] @reverse .
containerOf: [uid] @reverse .
hasModerator: [uid] @reverse .
likes: [uid] @reverse .
"""

_FIRST = ["Mahinda", "Karl", "Jose", "Rudolf", "Chutima", "Farhad",
          "Abhishek", "Ouwo", "Abdou", "Jan", "Aisha", "Wei", "Maria",
          "Ivan", "Lena", "Noor"]
_LAST = ["Perera", "Wagner", "Costa", "Engel", "Wattansin", "Qaderi",
         "Roy", "Maazou", "Dia", "Hus", "Khan", "Chen", "Silva",
         "Petrov", "Meyer", "Ali"]
_PLACES = ["Thanjavur", "Leipzig", "Porto", "Vienna", "Bangkok",
           "Kabul", "Kolkata", "Niamey", "Dakar", "Prague"]
_BROWSERS = ["Internet Explorer", "Firefox", "Chrome", "Safari", "Opera"]


def _dt(ms_epoch: int) -> str:
    """RFC3339 with millis, the SNB creationDate shape."""
    import datetime

    d = datetime.datetime.fromtimestamp(
        ms_epoch / 1000.0, datetime.timezone.utc
    )
    return d.strftime("%Y-%m-%dT%H:%M:%S.") + f"{ms_epoch % 1000:03d}Z"


@dataclass
class Person:
    uid: int
    sid: int  # SNB id
    first: str
    last: str
    gender: str
    birthday: str
    creation: int  # ms epoch
    ip: str
    browser: str
    place: int  # place uid


@dataclass
class Message:
    uid: int
    sid: int
    kind: str  # "post" | "comment"
    content: str
    image: str
    creation: int
    creator: int  # person uid
    reply_of: Optional[int] = None  # message uid (comments)


@dataclass
class Forum:
    uid: int
    sid: int
    title: str
    moderator: int
    posts: List[int] = field(default_factory=list)


@dataclass
class Corpus:
    persons: Dict[int, Person] = field(default_factory=dict)
    messages: Dict[int, Message] = field(default_factory=dict)
    forums: Dict[int, Forum] = field(default_factory=dict)
    places: Dict[int, str] = field(default_factory=dict)  # uid -> name
    place_ids: Dict[int, int] = field(default_factory=dict)  # uid -> id
    # knows edges with creationDate facet (ms): (a, b) -> ms, a < b
    knows: Dict[Tuple[int, int], int] = field(default_factory=dict)
    by_fqid: Dict[str, int] = field(default_factory=dict)
    n_edges: int = 0

    # -- derived goldens ----------------------------------------------------

    def adjacency(self) -> Dict[int, List[Tuple[int, int]]]:
        """uid -> [(friend uid, facet ms)], built once. Scanning all of
        self.knows per lookup made the old knows_of O(E) — inside the
        LDBC bench's timed loop that accounting dwarfed the ~3ms query
        itself (recorded as a 113ms 'engine floor' in round 3)."""
        adj = getattr(self, "_adj", None)
        if adj is None:
            adj = {}
            for (a, b), ms in self.knows.items():
                adj.setdefault(a, []).append((b, ms))
                adj.setdefault(b, []).append((a, ms))
            object.__setattr__(self, "_adj", adj)
        return adj

    def knows_of(self, uid: int) -> List[Tuple[int, int]]:
        """[(friend uid, facet ms)] for one person."""
        return self.adjacency().get(uid, [])

    def friends_of_friends(self, uid: int) -> List[int]:
        """2-hop friends (excluding self and direct friends) — the
        north-star traversal (BASELINE.json LDBC 2-hop)."""
        direct = {f for f, _ in self.knows_of(uid)}
        out = set()
        for f in direct:
            for g, _ in self.knows_of(f):
                if g != uid and g not in direct:
                    out.add(g)
        return sorted(out)

    def messages_by(self, person_uid: int) -> List[int]:
        return sorted(
            m.uid for m in self.messages.values() if m.creator == person_uid
        )

    def replies_to(self, msg_uid: int) -> List[int]:
        return sorted(
            m.uid for m in self.messages.values() if m.reply_of == msg_uid
        )

    def forum_of_post(self, post_uid: int) -> Optional[int]:
        for f in self.forums.values():
            if post_uid in f.posts:
                return f.uid
        return None


def generate(
    n_persons: int = 200,
    n_posts: int = 600,
    n_comments: int = 900,
    seed: int = 7,
) -> Tuple[Corpus, List[str]]:
    rng = np.random.default_rng(seed)
    c = Corpus()
    rdf: List[str] = []
    uid = 0x10000

    def nu() -> int:
        nonlocal uid
        uid += 1
        return uid

    def emit(s, p, o, facet=None):
        c.n_edges += 1
        rdf.append(
            f"<0x{s:x}> <{p}> {o} "
            + (f"({facet}) ." if facet else ".")
        )

    def lit(v: str) -> str:
        e = v.replace("\\", "\\\\").replace('"', '\\"')
        return f'"{e}"'

    # places
    for i, name in enumerate(_PLACES):
        pu = nu()
        c.places[pu] = name
        c.place_ids[pu] = 200 + i
        emit(pu, "name", lit(name))
        emit(pu, "id", f'"{200+i}"^^<xs:int>')
        emit(pu, "dgraph.type", lit("place"))

    place_uids = list(c.places)

    # persons
    base_ms = 1275850000000  # ~2010-06
    for i in range(n_persons):
        pu = nu()
        sid = 933 + i * 7
        p = Person(
            uid=pu,
            sid=sid,
            first=_FIRST[int(rng.integers(len(_FIRST)))],
            last=_LAST[int(rng.integers(len(_LAST)))],
            gender="male" if rng.integers(2) else "female",
            birthday=f"19{60 + int(rng.integers(40)):02d}-0{1 + int(rng.integers(9))}-0{1 + int(rng.integers(9))}T00:00:00Z",
            creation=base_ms + int(rng.integers(0, 60_000_000_000)),
            ip=f"27.54.{int(rng.integers(256))}.{int(rng.integers(256))}",
            browser=_BROWSERS[int(rng.integers(len(_BROWSERS)))],
            place=place_uids[int(rng.integers(len(place_uids)))],
        )
        c.persons[pu] = p
        fq = f"person_{sid}"
        c.by_fqid[fq] = pu
        emit(pu, "fqid", lit(fq))
        emit(pu, "id", f'"{sid}"^^<xs:int>')
        emit(pu, "firstName", lit(p.first))
        emit(pu, "lastName", lit(p.last))
        emit(pu, "gender", lit(p.gender))
        emit(pu, "birthday", f'"{p.birthday}"^^<xs:dateTime>')
        emit(pu, "creationDate", f'"{_dt(p.creation)}"^^<xs:dateTime>')
        emit(pu, "locationIP", lit(p.ip))
        emit(pu, "browserUsed", lit(p.browser))
        emit(pu, "dgraph.type", lit("person"))
        emit(pu, "isLocatedIn", f"<0x{p.place:x}>")

    person_uids = list(c.persons)

    # knows graph: preferential-ish — everyone gets 3-10 friends
    for pu in person_uids:
        deg = 3 + int(rng.integers(8))
        for _ in range(deg):
            q = person_uids[int(rng.integers(len(person_uids)))]
            if q == pu:
                continue
            a, b = min(pu, q), max(pu, q)
            if (a, b) in c.knows:
                continue
            ms = base_ms + int(rng.integers(0, 60_000_000_000))
            c.knows[(a, b)] = ms
            facet = f'creationDate="{_dt(ms)}"^^<xs:dateTime>'
            emit(a, "knows", f"<0x{b:x}>", facet)
            emit(b, "knows", f"<0x{a:x}>", facet)

    # posts
    post_uids: List[int] = []
    for i in range(n_posts):
        mu = nu()
        sid = 3 + i * 11
        creator = person_uids[int(rng.integers(len(person_uids)))]
        m = Message(
            uid=mu,
            sid=sid,
            kind="post",
            content=(
                f"About topic {int(rng.integers(500))}, opinion {i}"
                if rng.integers(4)
                else ""
            ),
            image=f"photo{sid}.jpg" if not rng.integers(3) else "",
            creation=base_ms + int(rng.integers(0, 70_000_000_000)),
            creator=creator,
        )
        c.messages[mu] = m
        post_uids.append(mu)
        fq = f"post_{sid}"
        c.by_fqid[fq] = mu
        emit(mu, "fqid", lit(fq))
        emit(mu, "id", f'"{sid}"^^<xs:int>')
        if m.content:
            emit(mu, "content", lit(m.content))
        if m.image:
            emit(mu, "imageFile", lit(m.image))
        emit(mu, "creationDate", f'"{_dt(m.creation)}"^^<xs:dateTime>')
        emit(mu, "dgraph.type", lit("post"))
        emit(mu, "hasCreator", f"<0x{creator:x}>")

    # comments (reply to posts or earlier comments)
    all_msgs = list(post_uids)
    for i in range(n_comments):
        mu = nu()
        sid = 1099511627777 + i * 3
        creator = person_uids[int(rng.integers(len(person_uids)))]
        target = all_msgs[int(rng.integers(len(all_msgs)))]
        m = Message(
            uid=mu,
            sid=sid,
            kind="comment",
            content=f"reply {i} about {int(rng.integers(100))}",
            image="",
            creation=c.messages[target].creation
            + 1000 + int(rng.integers(0, 5_000_000_000)),
            creator=creator,
            reply_of=target,
        )
        c.messages[mu] = m
        all_msgs.append(mu)
        fq = f"comment_{sid}"
        c.by_fqid[fq] = mu
        emit(mu, "fqid", lit(fq))
        emit(mu, "id", f'"{sid}"^^<xs:int>')
        emit(mu, "content", lit(m.content))
        emit(mu, "creationDate", f'"{_dt(m.creation)}"^^<xs:dateTime>')
        emit(mu, "dgraph.type", lit("comment"))
        emit(mu, "hasCreator", f"<0x{creator:x}>")
        emit(mu, "replyOf", f"<0x{target:x}>")

    # forums: each wraps a slice of posts
    nf = max(1, n_persons // 10)
    for i in range(nf):
        fu = nu()
        sid = i
        mod = person_uids[int(rng.integers(len(person_uids)))]
        f = Forum(
            uid=fu,
            sid=sid,
            title=f"Wall of {c.persons[mod].first} {c.persons[mod].last}",
            moderator=mod,
        )
        c.forums[fu] = f
        fq = f"forum_{sid}"
        c.by_fqid[fq] = fu
        emit(fu, "fqid", lit(fq))
        emit(fu, "id", f'"{sid}"^^<xs:int>')
        emit(fu, "title", lit(f.title))
        emit(fu, "dgraph.type", lit("forum"))
        emit(fu, "hasModerator", f"<0x{mod:x}>")
    forum_uids = list(c.forums)
    for j, mu in enumerate(post_uids):
        fu = forum_uids[j % len(forum_uids)]
        c.forums[fu].posts.append(mu)
        emit(fu, "containerOf", f"<0x{mu:x}>")

    return c, rdf

"""ACL, JWT, namespaces, audit, encryption tests
(mirrors /root/reference/acl tests + audit/ + enc/)."""

import time

import pytest

from dgraph_tpu.acl import jwt
from dgraph_tpu.acl.acl import READ, WRITE, AclError
from dgraph_tpu.api.server import Server

SCHEMA = "name: string @index(exact) .\nsalary: float @index(float) ."


def _server():
    s = Server()
    s.alter(SCHEMA)
    s.enable_acl(secret=b"test-secret-0123456789abcdef0000")
    return s


def test_jwt_roundtrip_and_tamper():
    secret = b"s" * 32
    tok = jwt.encode({"userid": "u", "exp": time.time() + 100}, secret)
    assert jwt.decode(tok, secret)["userid"] == "u"
    with pytest.raises(jwt.JwtError):
        jwt.decode(tok + "x", secret)
    with pytest.raises(jwt.JwtError):
        jwt.decode(tok, b"wrong" * 8)
    expired = jwt.encode({"exp": time.time() - 1}, secret)
    with pytest.raises(jwt.JwtError):
        jwt.decode(expired, secret)


def test_groot_login_and_guardian_bypass():
    s = _server()
    toks = s.login("groot", "password")
    assert "accessJwt" in toks
    with pytest.raises(AclError):
        s.login("groot", "wrongpass")
    # guardian can query anything
    res = s.query("{ q(func: has(name)) { name } }", access_jwt=toks["accessJwt"])
    assert res["data"]["q"] == []


def test_non_user_denied_and_rules():
    s = _server()
    acl = s.acl
    acl.add_user("alice", "alicepw")
    acl.add_group("engineering")
    acl.add_user_to_group("alice", "engineering")
    toks = s.login("alice", "alicepw")
    a = toks["accessJwt"]

    # no rules yet: read denied
    with pytest.raises(AclError):
        s.query("{ q(func: has(name)) { name } }", access_jwt=a)

    acl.set_rule("engineering", "name", READ)
    res = s.query("{ q(func: has(name)) { name } }", access_jwt=a)
    assert res["data"]["q"] == []

    # write still denied
    t = s.new_txn()
    with pytest.raises(AclError):
        t.mutate_rdf(set_rdf='<0x1> <name> "X" .', access_jwt=a)

    acl.set_rule("engineering", "name", WRITE)
    t = s.new_txn()
    t.mutate_rdf(set_rdf='<0x1> <name> "X" .', access_jwt=a, commit_now=True)

    # but salary is still invisible
    with pytest.raises(AclError):
        s.query("{ q(func: has(salary)) { salary } }", access_jwt=a)


def test_missing_token_when_acl_on():
    s = _server()
    with pytest.raises(AclError):
        s.query("{ q(func: has(name)) { name } }")


def test_refresh_token():
    s = _server()
    toks = s.login("groot", "password")
    toks2 = s.acl.refresh(toks["refreshJwt"])
    assert toks2["accessJwt"]
    claims = s.acl.claims(toks2["accessJwt"])
    assert claims["userid"] == "groot"


def test_namespaces_isolated():
    from dgraph_tpu.admin.namespace import NamespaceManager

    s = _server()
    nm = NamespaceManager(s)
    ns1 = nm.create_namespace()
    assert ns1 >= 1
    # same user name in two namespaces, different passwords
    s.acl.add_user("bob", "pw0")
    s.acl.add_user("bob", "pw1", ns=ns1)
    t0 = s.login("bob", "pw0")
    t1 = s.login("bob", "pw1", ns=ns1)
    assert s.acl.claims(t0["accessJwt"])["namespace"] == 0
    assert s.acl.claims(t1["accessJwt"])["namespace"] == ns1
    with pytest.raises(AclError):
        s.login("bob", "pw0", ns=ns1)

    # groot of ns1 writes data invisible to galaxy queries
    g1 = s.login("groot", "password", ns=ns1)["accessJwt"]
    t = s.new_txn()
    t.mutate_rdf(set_rdf='<0x900> <name> "ns1-only" .', access_jwt=g1, commit_now=True)
    g0 = s.login("groot", "password")["accessJwt"]
    res = s.query('{ q(func: eq(name, "ns1-only")) { name } }', access_jwt=g0)
    assert res["data"]["q"] == []
    res = s.query('{ q(func: eq(name, "ns1-only")) { name } }', access_jwt=g1)
    assert res["data"]["q"] == [{"name": "ns1-only"}]


def test_audit_log(tmp_path):
    # encrypted audit logs ride the optional cryptography module
    pytest.importorskip("cryptography")
    s = Server()
    s.alter(SCHEMA)
    s.enable_audit(str(tmp_path), key=b"0123456789abcdef")
    s.enable_acl(secret=b"x" * 32)
    toks = s.login("groot", "password")
    s.query("{ q(func: has(name)) { name } }", access_jwt=toks["accessJwt"])
    try:
        s.login("groot", "nope")
    except AclError:
        pass
    entries = s.audit.read_all()
    endpoints = [(e["endpoint"], e["status"]) for e in entries]
    assert ("login", "OK") in endpoints
    assert ("query", "OK") in endpoints
    assert ("login", "DENIED") in endpoints
    # raw file is encrypted (no plaintext 'login')
    import os

    raw = open(os.path.join(str(tmp_path), os.listdir(str(tmp_path))[0]), "rb").read()
    assert b'"endpoint"' not in raw


def test_encryption_roundtrip(tmp_path):
    pytest.importorskip("cryptography")
    from dgraph_tpu.enc.enc import decrypt_stream, encrypt_stream, read_key_file

    key_path = str(tmp_path / "key")
    with open(key_path, "wb") as f:
        f.write(b"0123456789abcdef")
    key = read_key_file(key_path)
    data = b"secret posting list" * 100
    enc = encrypt_stream(data, key)
    assert enc[16:] != data
    assert decrypt_stream(enc, key) == data
    # unique IVs
    assert encrypt_stream(data, key) != enc


def test_json_mutation_requires_token_and_ns():
    s = _server()
    t = s.new_txn()
    with pytest.raises(AclError):
        t.mutate_json(set_obj={"uid": "0x1", "name": "evil"})
    # guardian token works and nested preds are checked
    tok = s.login("groot", "password")["accessJwt"]
    t = s.new_txn()
    t.mutate_json(
        set_obj={"uid": "0x1", "name": "ok"}, access_jwt=tok, commit_now=True
    )


def test_expand_all_respects_acl():
    s = _server()
    g = s.login("groot", "password")["accessJwt"]
    t = s.new_txn()
    t.mutate_rdf(
        set_rdf='<0x2> <name> "secret" .\n<0x2> <dgraph.type> "Person" .',
        access_jwt=g,
        commit_now=True,
    )
    from dgraph_tpu.schema.schema import TypeUpdate

    s.schema.set_type(TypeUpdate(name="Person", fields=["name", "salary"]))
    s.acl.add_user("eve", "evepw")
    s.acl.add_group("nothing")
    s.acl.add_user_to_group("eve", "nothing")
    s.acl.set_rule("nothing", "salary", READ)  # can read salary, NOT name
    a = s.login("eve", "evepw")["accessJwt"]
    res = s.query("{ q(func: uid(0x2)) { expand(_all_) } }", access_jwt=a)
    assert "name" not in res["data"]["q"][0] if res["data"]["q"] else True
    # groupby on a denied pred also blocked
    with pytest.raises(AclError):
        s.query(
            "{ q(func: uid(0x2)) @groupby(name) { count(uid) } }", access_jwt=a
        )


def test_admin_routes_guardian_only():
    import json as _json
    import urllib.request as ur
    import urllib.error

    from dgraph_tpu.api.http_server import HTTPServer

    s = _server()
    srv = HTTPServer(s, port=0).start()
    base = f"http://127.0.0.1:{srv.port}"

    def post(path, body, headers=None):
        req = ur.Request(
            base + path, data=body.encode(), headers=headers or {}, method="POST"
        )
        try:
            with ur.urlopen(req) as r:
                return r.status
        except urllib.error.HTTPError as e:
            return e.code

    assert post("/alter", '{"drop_all": true}') == 403
    assert post("/admin/export", "") == 403
    tok = s.login("groot", "password")["accessJwt"]
    assert (
        post("/alter", "city2: string .", {"X-Dgraph-AccessToken": tok}) == 200
    )
    srv.stop()


def test_dgraph_internal_preds_guarded():
    s = _server()
    s.acl.add_user("mal", "malpw")
    a = s.login("mal", "malpw")["accessJwt"]
    with pytest.raises(AclError):
        s.query(
            "{ q(func: has(dgraph.password)) { dgraph.password } }",
            access_jwt=a,
        )
    # dgraph.type READ still allowed (type()/expand need it)
    s.acl.add_group("g1")
    s.acl.add_user_to_group("mal", "g1")
    s.acl.set_rule("g1", "name", READ)
    a = s.login("mal", "malpw")["accessJwt"]  # re-login: groups in claims
    res = s.query("{ q(func: type(Person)) { name } }", access_jwt=a)
    assert res["data"]["q"] == []


def test_txn_query_and_upsert_require_token():
    s = _server()
    t = s.new_txn()
    with pytest.raises(AclError):
        t.query("{ q(func: has(name)) { name } }")
    t = s.new_txn()
    with pytest.raises(AclError):
        t.upsert(
            query="{ v as var(func: has(name)) }",
            set_rdf='uid(v) <name> "x" .',
        )
    g = s.login("groot", "password")["accessJwt"]
    t = s.new_txn()
    assert t.query("{ q(func: has(name)) { name } }", access_jwt=g)


def test_random_salt():
    s = _server()
    s.acl.add_user("s1", "same")
    s.acl.add_user("s2", "same")
    from dgraph_tpu.posting.lists import LocalCache
    from dgraph_tpu.x import keys as xkeys

    cache = LocalCache(s.kv, s.zero.read_ts())
    hashes = []
    for xid in ("s1", "s2"):
        uid = s.acl._uid_of_xid(xid, 0)
        hashes.append(
            cache.value(xkeys.DataKey("dgraph.password", uid)).value
        )
    assert hashes[0] != hashes[1]  # same password, different salt/hash

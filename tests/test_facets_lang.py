"""Edge facets (projection/filter/order) and language preference chains
(mirrors /root/reference/query facets tests + lang list semantics)."""

import pytest

from dgraph_tpu.api.server import Server

SCHEMA = """
name: string @index(exact) @lang .
friend: [uid] @reverse .
"""

RDF = """
<0x1> <name> "Center" .
<0x1> <friend> <0x2> (since=2004, close=true) .
<0x1> <friend> <0x3> (since=2010, close=false) .
<0x1> <friend> <0x4> (since=2001) .
<0x2> <name> "Two" .
<0x3> <name> "Three" .
<0x4> <name> "Four" .
<0x5> <name> "Olá"@pt .
<0x5> <name> "Hello"@en .
<0x5> <name> "plain" .
<0x6> <name> "nur deutsch"@de .
"""


@pytest.fixture()
def server():
    s = Server()
    s.alter(SCHEMA)
    t = s.new_txn()
    t.mutate_rdf(set_rdf=RDF, commit_now=True)
    return s


def test_facet_projection(server):
    res = server.query(
        '{ q(func: uid(0x1)) { friend @facets(since) { name } } }'
    )["data"]
    by_name = {o["name"]: o.get("friend|since") for o in res["q"][0]["friend"]}
    assert by_name == {"Two": 2004, "Three": 2010, "Four": 2001}


def test_facet_filter(server):
    res = server.query(
        '{ q(func: uid(0x1)) { friend @facets(gt(since, 2003)) { name } } }'
    )["data"]
    names = {o["name"] for o in res["q"][0]["friend"]}
    assert names == {"Two", "Three"}
    res = server.query(
        '{ q(func: uid(0x1)) { friend @facets(eq(close, true)) { name } } }'
    )["data"]
    assert {o["name"] for o in res["q"][0]["friend"]} == {"Two"}


def test_facet_order(server):
    res = server.query(
        '{ q(func: uid(0x1)) { friend @facets(orderasc: since) { name } } }'
    )["data"]
    assert [o["name"] for o in res["q"][0]["friend"]] == [
        "Four",
        "Two",
        "Three",
    ]


def test_facets_survive_rollup(server):
    from dgraph_tpu.posting.rollup import rollup_all

    assert rollup_all(server, min_deltas=1) > 0
    res = server.query(
        '{ q(func: uid(0x1)) { friend @facets(since) { name } } }'
    )["data"]
    by_name = {o["name"]: o.get("friend|since") for o in res["q"][0]["friend"]}
    assert by_name["Two"] == 2004


def test_lang_chain(server):
    res = server.query('{ q(func: uid(0x5)) { name@en } }')["data"]
    assert res["q"] == [{"name@en": "Hello"}]
    res = server.query('{ q(func: uid(0x5)) { name@fr:pt } }')["data"]
    assert res["q"] == [{"name@fr:pt": "Olá"}]
    # '.' = any language
    res = server.query('{ q(func: uid(0x6)) { name@fr:. } }')["data"]
    assert res["q"] == [{"name@fr:.": "nur deutsch"}]
    # untagged read gets untagged value
    res = server.query('{ q(func: uid(0x5)) { name } }')["data"]
    assert res["q"] == [{"name": "plain"}]
    # missing language entirely -> absent field
    res = server.query('{ q(func: uid(0x6)) { name@fr } }')["data"]
    assert res["q"] == []


def test_facet_value_vars():
    """`@facets(w as weight)` binds target-uid -> facet value into a
    value var usable by later blocks (ref facet var bindings)."""
    from dgraph_tpu.api.server import Server

    s = Server()
    s.alter("name: string @index(exact) .\nfollows: [uid] .")
    t = s.new_txn()
    t.mutate_rdf(
        set_rdf=(
            '<0x1> <name> "hub" .\n'
            "<0x1> <follows> <0x2> (weight=0.9) .\n"
            "<0x1> <follows> <0x3> (weight=0.1) .\n"
            '<0x2> <name> "heavy" .\n'
            '<0x3> <name> "light" .'
        ),
        commit_now=True,
    )
    out = s.query(
        """{
          var(func: eq(name, "hub")) { follows @facets(w as weight) }
          q(func: uid(w), orderdesc: val(w)) { name score: val(w) }
        }"""
    )
    q = out["data"]["q"]
    assert [x["name"] for x in q] == ["heavy", "light"]
    assert q[0]["score"] == 0.9

"""Bulk/live loaders, export, backup/restore, restart persistence."""

import gzip
import json
import os

import numpy as np
import pytest

from dgraph_tpu.api.server import Server
from dgraph_tpu.loaders.bulk import bulk_load_rdf
from dgraph_tpu.loaders.live import LiveLoader
from dgraph_tpu.admin.export import export
from dgraph_tpu.admin.backup import backup, restore

SCHEMA = """
name: string @index(term, exact) .
age: int @index(int) .
friend: [uid] @reverse @count .
embedding: float32vector @index(hnsw(metric:"euclidean")) .
"""

RDF = """
_:a <name> "Ann" .
_:a <age> "30"^^<xs:int> .
_:a <friend> _:b .
_:a <embedding> "[1.0, 2.0]"^^<float32vector> .
_:b <name> "Ben" .
_:b <age> "40"^^<xs:int> .
_:b <friend> _:a .
"""


def test_bulk_load_and_query():
    s = Server()
    s.alter(SCHEMA)
    bulk_load_rdf(s, RDF)
    res = s.query('{ q(func: eq(name, "Ann")) { name age friend { name } } }')[
        "data"
    ]
    assert res["q"][0]["name"] == "Ann"
    assert res["q"][0]["friend"][0]["name"] == "Ben"
    # reverse + count from bulk path
    res = s.query('{ q(func: eq(name, "Ben")) { c: count(~friend) } }')["data"]
    assert res["q"][0]["c"] == 1
    # vector present
    res = s.query('{ v(func: similar_to(embedding, 1, "[1.0,2.0]")) { name } }')[
        "data"
    ]
    assert res["v"][0]["name"] == "Ann"


def test_bulk_equals_live():
    sb, sl = Server(), Server()
    sb.alter(SCHEMA)
    sl.alter(SCHEMA)
    bulk_load_rdf(sb, RDF)
    LiveLoader(sl, batch_size=2).load_rdf(RDF)
    q = '{ q(func: has(name), orderasc: name) { name age c: count(friend) } }'
    assert sb.query(q)["data"] == sl.query(q)["data"]


def test_live_loader_stats():
    s = Server()
    s.alter(SCHEMA)
    ll = LiveLoader(s, batch_size=3)
    ll.load_rdf(RDF)
    assert ll.nquads_loaded == 7
    assert ll.txns_committed >= 2


def test_export_rdf_roundtrip(tmp_path):
    s = Server()
    s.alter(SCHEMA)
    bulk_load_rdf(s, RDF)
    out = export(s, str(tmp_path), fmt="rdf")
    assert out["nquads"] >= 7

    # re-import the export into a fresh server: same query results
    with gzip.open(out["data"], "rt") as f:
        rdf = f.read()
    with gzip.open(out["schema"], "rt") as f:
        schema_text = f.read()
    s2 = Server()
    s2.alter(schema_text)
    bulk_load_rdf(s2, rdf)
    q = '{ q(func: has(name), orderasc: name) { name age friend { name } } }'
    assert s.query(q)["data"] == s2.query(q)["data"]


def test_export_json(tmp_path):
    s = Server()
    s.alter(SCHEMA)
    bulk_load_rdf(s, RDF)
    out = export(s, str(tmp_path), fmt="json", compress=False)
    with open(out["data"]) as f:
        rows = json.load(f)
    names = {r.get("name") for r in rows if "name" in r}
    assert names == {"Ann", "Ben"}


def test_backup_restore_full_and_incremental(tmp_path):
    bdir = str(tmp_path / "backups")
    s = Server()
    s.alter(SCHEMA)
    bulk_load_rdf(s, RDF)
    e1 = backup(s, bdir)
    assert e1["type"] == "full"

    t = s.new_txn()
    t.mutate_rdf(set_rdf='<0x100> <name> "Cid" .', commit_now=True)
    e2 = backup(s, bdir)
    assert e2["type"] == "incremental"
    assert e2["since"] == e1["read_ts"]

    s2 = Server()
    s2.alter(SCHEMA)
    n = restore(s2, bdir)
    assert n > 0
    q = '{ q(func: has(name), orderasc: name) { name } }'
    assert s2.query(q)["data"] == s.query(q)["data"]


def test_restart_persistence(tmp_path):
    d = str(tmp_path / "data")
    s = Server(data_dir=d)
    s.alter(SCHEMA)
    t = s.new_txn()
    t.mutate_rdf(
        set_rdf='<0x1> <name> "Zed" .\n'
        '<0x1> <embedding> "[0.5, 0.5]"^^<float32vector> .',
        commit_now=True,
    )
    s.kv.close()

    s2 = Server(data_dir=d)
    # schema recovered
    assert s2.schema.get("name").tokenizers == ["term", "exact"]
    res = s2.query('{ q(func: eq(name, "Zed")) { name } }')["data"]
    assert res["q"] == [{"name": "Zed"}]
    # vector index rebuilt
    res = s2.query('{ v(func: similar_to(embedding, 1, "[0.5,0.5]")) { uid } }')[
        "data"
    ]
    assert res["v"] == [{"uid": "0x1"}]
    # new writes still work at advanced ts
    t = s2.new_txn()
    t.mutate_rdf(set_rdf='<0x2> <name> "Yao" .', commit_now=True)
    res = s2.query('{ q(func: has(name), orderasc: name) { name } }')["data"]
    assert [o["name"] for o in res["q"]] == ["Yao", "Zed"]
    s2.kv.close()


def test_restart_uid_lease_no_reuse(tmp_path):
    # review regression: blank nodes after restart must not reuse uids
    d = str(tmp_path / "lease")
    s = Server(data_dir=d)
    s.alter("name: string @index(exact) .")
    t = s.new_txn()
    t.mutate_rdf(set_rdf='_:a <name> "Alice" .', commit_now=True)
    s.kv.close()
    s2 = Server(data_dir=d)
    t = s2.new_txn()
    t.mutate_rdf(set_rdf='_:b <name> "Bob" .', commit_now=True)
    res = s2.query('{ q(func: has(name), orderasc: name) { name } }')["data"]
    assert [o["name"] for o in res["q"]] == ["Alice", "Bob"]
    s2.kv.close()


def test_drop_attr_survives_restart(tmp_path):
    d = str(tmp_path / "drop")
    s = Server(data_dir=d)
    s.alter("name: string @index(exact) .\ncity: string .")
    s.alter(drop_attr="name")
    s.kv.close()
    s2 = Server(data_dir=d)
    assert s2.schema.get("name") is None
    assert s2.schema.get("city") is not None
    s2.kv.close()


def test_rdf_iri_fragments_and_multistatement():
    from dgraph_tpu.loaders.rdf import parse_rdf

    nqs = parse_rdf(
        '<0x1> <http://schema.org#name> "Alice" . <0x2> <age> "3"^^<xs:int> .'
    )
    assert len(nqs) == 2
    assert nqs[0].predicate == "http://schema.org#name"
    # comments still stripped
    nqs = parse_rdf('# a comment\n<0x1> <name> "A" .')
    assert len(nqs) == 1


def test_loaders_accept_multistatement_lines():
    s = Server()
    s.alter("name: string @index(exact) .")
    bulk_load_rdf(s, '_:a <name> "X" . _:b <name> "Y" .')
    res = s.query('{ q(func: has(name)) { name } }')["data"]
    assert {o["name"] for o in res["q"]} == {"X", "Y"}


def test_rdf_dot_abutting_and_export_geo_roundtrip(tmp_path):
    from dgraph_tpu.loaders.rdf import parse_rdf

    nqs = parse_rdf('<0x1> <name> "Alice".\n<0x2> <name> "Bob".')
    assert len(nqs) == 2
    # geo export lines re-parse (escaped inner quotes)
    s = Server()
    s.alter("loc: geo @index(geo) .")
    t = s.new_txn()
    t.mutate_rdf(
        set_rdf='<0x1> <loc> "{\\"type\\":\\"Point\\",\\"coordinates\\":[1.0,2.0]}"^^<geo:geojson> .',
        commit_now=True,
    )
    out = export(s, str(tmp_path), fmt="rdf", compress=False)
    with open(out["data"]) as f:
        rdf = f.read()
    s2 = Server()
    s2.alter("loc: geo @index(geo) .")
    bulk_load_rdf(s2, rdf)
    res = s2.query("{ q(func: uid(0x1)) { loc } }")["data"]
    assert res["q"][0]["loc"]["type"] == "Point"


def test_restore_into_fresh_server_recovers_schema(tmp_path):
    bdir = str(tmp_path / "b2")
    s = Server()
    s.alter(SCHEMA)
    bulk_load_rdf(s, RDF)
    backup(s, bdir)
    s2 = Server()  # NO alter — schema must come from the backup
    restore(s2, bdir)
    assert s2.schema.get("name").tokenizers == ["term", "exact"]
    res = s2.query('{ q(func: eq(name, "Ann")) { name } }')["data"]
    assert res["q"] == [{"name": "Ann"}]
    res = s2.query('{ v(func: similar_to(embedding, 1, "[1.0,2.0]")) { name } }')[
        "data"
    ]
    assert res["v"][0]["name"] == "Ann"


def test_online_restore_into_cluster(tmp_path):
    """Backups restore into a LIVE distributed cluster via raft proposals
    (ref worker/online_restore.go)."""
    from dgraph_tpu.admin.backup import backup, restore_to_cluster
    from dgraph_tpu.api.server import Server
    from dgraph_tpu.worker.groups import DistributedCluster

    src = Server()
    src.alter("name: string @index(exact) .\nfollows: [uid] .")
    t = src.new_txn()
    t.mutate_rdf(
        set_rdf='<0x1> <name> "or-alice" .\n<0x2> <name> "or-bob" .\n'
        "<0x1> <follows> <0x2> .",
        commit_now=True,
    )
    bdir = str(tmp_path / "bk")
    backup(src, bdir)

    c = DistributedCluster(n_groups=2, replicas=3)
    try:
        n = restore_to_cluster(c, bdir)
        assert n > 0
        out = c.query('{ q(func: eq(name, "or-alice")) { name follows { name } } }')
        assert out["data"]["q"][0]["follows"][0]["name"] == "or-bob"
        # leases advanced: new writes don't collide with restored uids
        c.new_txn().mutate_rdf(set_rdf='_:n <name> "or-new" .', commit_now=True)
        out = c.query('{ q(func: eq(name, "or-new")) { uid name } }')
        assert out["data"]["q"][0]["name"] == "or-new"
        assert int(out["data"]["q"][0]["uid"], 16) > 2
    finally:
        c.close()


def test_parallel_bulk_loader_spill_and_ingest(tmp_path, monkeypatch):
    """Out-of-core loader (ref dgraph/cmd/bulk mapStage/reduceStage): tiny
    spill threshold forces multiple sorted runs + k-way merge; LSM backend
    takes the direct-SSTable ingest path; result matches the live path."""
    from dgraph_tpu.api.server import Server
    from dgraph_tpu.loaders.bulk2 import ParallelBulkLoader

    rdf = []
    for i in range(500):
        rdf.append(f'<0x{i+1:x}> <name> "n{i:03d}" .')
        rdf.append(f"<0x{i+1:x}> <follows> <0x{(i % 250) + 1:x}> .")
    rdf.append('_:blank <name> "from-xid" .')
    text = "\n".join(rdf)
    schema = "name: string @index(exact) .\nfollows: [uid] @reverse @count ."

    monkeypatch.setenv("DGRAPH_TPU_STORAGE", "lsm")
    s = Server(data_dir=str(tmp_path / "l"))
    s.alter(schema)
    ld = ParallelBulkLoader(
        s, workdir=str(tmp_path / "w"), workers=1, spill_entries=200
    )
    ld.load_text(text)
    assert ld.nquads == 1001
    out = s.query('{ q(func: eq(name, "n007")) { name follows { name } } }')
    assert out["data"]["q"][0]["follows"][0]["name"] == "n007"
    # reverse index + count index built in the same pass
    out = s.query('{ q(func: eq(name, "n003")) { c: count(~follows) } }')
    assert out["data"]["q"][0]["c"] == 2
    out = s.query('{ q(func: eq(name, "from-xid")) { name } }')
    assert out["data"]["q"][0]["name"] == "from-xid"
    s.kv.close()


def test_parallel_bulk_loader_vectors(tmp_path):
    """Bulk-loaded float32vector predicates must land in the similarity
    engine without a restart (parity with loaders.bulk's vector path)."""
    from dgraph_tpu.api.server import Server
    from dgraph_tpu.loaders.bulk2 import ParallelBulkLoader

    s = Server()
    s.alter(
        'emb: float32vector @index(hnsw(metric:"euclidean")) .\n'
        "name: string @index(exact) ."
    )
    rdf = []
    for i in range(8):
        vec = f"[{float(i)}, {float(i)}]"
        rdf.append(f'<0x{i+1:x}> <emb> "{vec}"^^<xs:float32vector> .')
        rdf.append(f'<0x{i+1:x}> <name> "v{i}" .')
    ld = ParallelBulkLoader(s, workdir=str(tmp_path / "w"), workers=1)
    ld.load_text("\n".join(rdf))
    out = s.query(
        '{ q(func: similar_to(emb, 2, "[3.1, 3.1]")) { name } }'
    )
    names = [r["name"] for r in out["data"]["q"]]
    assert names == ["v3", "v4"]


def test_parallel_bulk_loader_type_inference_chunk_independent(tmp_path):
    """Undeclared-predicate types are decided by first occurrence in input
    order regardless of worker chunking; later conflicting values convert
    to the decided type at reduce (review finding: per-worker inference)."""
    from dgraph_tpu.api.server import Server
    from dgraph_tpu.loaders.bulk2 import ParallelBulkLoader

    lines = ['<0x1> <age> "25"^^<xs:int> .']
    lines += [f'<0x{i:x}> <age> "{i}"^^<xs:int> .' for i in range(2, 40)]
    s = Server()
    ld = ParallelBulkLoader(s, workdir=str(tmp_path / "w"), workers=2)
    ld.load_text("\n".join(lines))
    su = s.schema.get("age")
    from dgraph_tpu.types.types import TypeID

    assert su is not None and su.value_type == TypeID.INT
    out = s.query('{ q(func: eq(age, 25)) { age } }')
    assert out["data"]["q"][0]["age"] == 25

"""Bundled pydgraph-style gRPC client against the api.Dgraph server."""

import pytest

from dgraph_tpu.api.grpc_server import serve
from dgraph_tpu.api.server import Server
from dgraph_tpu.client_grpc import DgraphClient, DgraphClientStub


@pytest.fixture(scope="module")
def client():
    engine = Server()
    gs, port = serve(engine)
    stub = DgraphClientStub(f"127.0.0.1:{port}")
    c = DgraphClient(stub)
    yield c
    stub.close()
    gs.stop(0)


def test_client_lifecycle(client):
    assert client.check_version() == "dgraph-tpu"
    client.alter(schema="name: string @index(exact) .\nage: int .")

    txn = client.txn()
    uids = txn.mutate(set_nquads='_:a <name> "cg-alice" .\n_:a <age> "30"^^<xs:int> .')
    assert "a" in uids
    # visible inside the txn, not outside
    assert txn.query('{ q(func: eq(name, "cg-alice")) { age } }')["q"][0]["age"] == 30
    ro = client.txn(read_only=True)
    assert ro.query('{ q(func: eq(name, "cg-alice")) { uid } }')["q"] == []
    assert txn.commit() > 0
    assert (
        client.txn(read_only=True)
        .query('{ q(func: eq(name, "cg-alice")) { age } }')["q"][0]["age"]
        == 30
    )


def test_client_commit_now_and_json(client):
    txn = client.txn()
    txn.mutate(set_obj={"uid": "_:j", "name": "cg-json"}, commit_now=True)
    got = client.txn(read_only=True).query(
        '{ q(func: eq(name, "cg-json")) { name } }'
    )
    assert got["q"][0]["name"] == "cg-json"


def test_client_discard(client):
    txn = client.txn()
    txn.mutate(set_nquads='_:g <name> "cg-ghost" .')
    txn.discard()
    got = client.txn(read_only=True).query(
        '{ q(func: eq(name, "cg-ghost")) { uid } }'
    )
    assert got["q"] == []


def test_client_upsert_do_request(client):
    client.txn().mutate(
        set_nquads='_:e <name> "cg-upsertee" .', commit_now=True
    )
    out = client.txn().do_request(
        '{ u as var(func: eq(name, "cg-upsertee")) }',
        [('uid(u) <age> "44"^^<xs:int> .', None)],
    )
    got = client.txn(read_only=True).query(
        '{ q(func: eq(name, "cg-upsertee")) { age } }'
    )
    assert got["q"][0]["age"] == 44

"""alpha CLI smoke tests: single-node and --cluster serving modes."""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _wait_http(port, timeout=60):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/health", timeout=1
            ) as r:
                return json.loads(r.read())
        except Exception:
            time.sleep(0.3)
    raise TimeoutError("alpha never became healthy")


def _spawn_alpha(*extra):
    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "dgraph_tpu", "alpha",
            "--port", str(port), "--grpc_port", "0", *extra,
        ],
        env=env,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    return proc, port


def _post(port, path, body, ctype="application/rdf"):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=body.encode(),
        headers={"Content-Type": ctype},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())


@pytest.mark.parametrize(
    "extra",
    [
        (),
        ("--cluster", "groups=2; replicas=3"),
    ],
    ids=["single-node", "cluster"],
)
def test_alpha_cli_serves(extra):
    proc, port = _spawn_alpha(*extra)
    try:
        health = _wait_http(port)
        assert health[0]["status"] == "healthy"
        out = _post(port, "/alter", "name: string @index(exact) .")
        assert out["data"]["code"] == "Success"
        out = _post(
            port, "/mutate?commitNow=true",
            '{ set { _:x <name> "cli-alice" . } }',
        )
        assert out["data"]["code"] == "Success"
        res = _post(port, "/query", '{ q(func: eq(name, "cli-alice")) { name } }')
        assert res["data"]["q"] == [{"name": "cli-alice"}]
    finally:
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=5)

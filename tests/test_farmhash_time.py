"""go_time_binary edge cases (ADVICE r3: UTC vs zero-offset-non-UTC,
fractional-minute offsets). Mirrors Go time.Time.MarshalBinary v1."""

import datetime as dt
import struct

import pytest

from dgraph_tpu.utils.farmhash import go_time_binary


def _off_min(b: bytes) -> int:
    return struct.unpack(">h", b[-2:])[0]


def test_utc_marshals_minus_one():
    t = dt.datetime(2020, 5, 1, 12, 0, 0, tzinfo=dt.timezone.utc)
    assert _off_min(go_time_binary(t)) == -1


def test_plus_zero_offset_is_utc_singleton():
    # RFC3339 "+00:00" parses to the UTC singleton in python like Go
    t = dt.datetime.fromisoformat("2020-05-01T12:00:00+00:00")
    assert t.tzinfo is dt.timezone.utc
    assert _off_min(go_time_binary(t)) == -1


def test_non_utc_zero_offset_zone_writes_zero():
    class ZeroZone(dt.tzinfo):
        def utcoffset(self, _):
            return dt.timedelta(0)

        def dst(self, _):
            return dt.timedelta(0)

    t = dt.datetime(2020, 5, 1, 12, 0, 0, tzinfo=ZeroZone())
    assert _off_min(go_time_binary(t)) == 0


def test_positive_offset_minutes():
    t = dt.datetime(
        2020, 5, 1, 12, 0, 0, tzinfo=dt.timezone(dt.timedelta(hours=5, minutes=30))
    )
    assert _off_min(go_time_binary(t)) == 330


def test_fractional_minute_offset_raises():
    tz = dt.timezone(dt.timedelta(seconds=90))
    t = dt.datetime(2020, 5, 1, tzinfo=tz)
    with pytest.raises(ValueError):
        go_time_binary(t)


def test_zoneinfo_utc_marshals_minus_one():
    from zoneinfo import ZoneInfo

    t = dt.datetime(2020, 5, 1, 12, tzinfo=ZoneInfo("UTC"))
    assert _off_min(go_time_binary(t)) == -1


def test_named_gmt_zero_offset_writes_zero():
    t = dt.datetime(2020, 5, 1, tzinfo=dt.timezone(dt.timedelta(0), "GMT"))
    assert _off_min(go_time_binary(t)) == 0


def test_subsecond_offset_raises():
    class SubSec(dt.tzinfo):
        def utcoffset(self, _):
            return dt.timedelta(microseconds=500000)

        def dst(self, _):
            return dt.timedelta(0)

    with pytest.raises(ValueError):
        go_time_binary(dt.datetime(2020, 5, 1, tzinfo=SubSec()))

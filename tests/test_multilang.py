"""Multi-language fulltext stemming (ref tok.go FullTextTokenizer{lang},
bleve per-language analyzers).
"""

import pytest

from dgraph_tpu.api.server import Server
from dgraph_tpu.tok.stemmers import REGISTRY, lang_base
from dgraph_tpu.tok.tok import FulltextTokenizer
from dgraph_tpu.types.types import TypeID, Val


def _toks(text, lang=""):
    t = FulltextTokenizer()
    return {b[1:].decode() for b in t.tokens(Val(TypeID.STRING, text), lang)}


def test_lang_base():
    assert lang_base("fr-CA") == "fr"
    assert lang_base("pt_BR") == "pt"
    assert lang_base("") == ""


def test_spanish_stems_and_stopwords():
    got = _toks("las bibliotecas nacionales", "es")
    # stopword 'las' dropped; plural endings stripped
    assert "las" not in got
    assert _toks("biblioteca nacional", "es") & got


def test_french_stems():
    a = _toks("les nations européennes", "fr")
    b = _toks("nation européenne", "fr")
    assert "les" not in a
    assert a & b


def test_german_stems():
    a = _toks("die Bibliotheken", "de")
    b = _toks("Bibliothek", "de")
    assert a & b


def test_russian_stopwords():
    got = _toks("и все книги", "ru")
    assert "и" not in got


def test_unknown_lang_falls_back():
    # no stemmer: words tokenize as-is through the EN pipeline
    assert _toks("running waters", "xx")


def test_engine_lang_aware_fulltext():
    s = Server()
    s.alter("bio: string @index(fulltext) @lang .")
    t = s.new_txn()
    t.mutate_rdf(
        set_rdf=(
            '<0x1> <bio> "las bibliotecas nacionales"@es .\n'
            '<0x2> <bio> "national libraries"@en .'
        ),
        commit_now=True,
    )
    # Spanish query form matches the Spanish-stemmed document
    out = s.query('{ q(func: alloftext(bio@es, "biblioteca nacional")) { uid } }')
    assert [x["uid"] for x in out["data"]["q"]] == ["0x1"]
    out = s.query('{ q(func: alloftext(bio@en, "library national")) { uid } }')
    assert [x["uid"] for x in out["data"]["q"]] == ["0x2"]


def test_cjk_fulltext_bigrams():
    """CJK analyzer (ref tok.go bleve cjk analyzer for zh/ja/ko —
    thrice-carried VERDICT item): ideograph runs index as overlapping
    bigrams, searchable via alloftext with @lang."""
    from dgraph_tpu.api.server import Server

    s = Server()
    s.alter("title: string @index(fulltext) @lang .")
    t = s.new_txn()
    t.mutate_rdf(set_rdf='''
        <0x1> <title> "数据库系统"@zh .
        <0x2> <title> "分布式计算"@zh .
        <0x3> <title> "データベース"@ja .
    ''')
    t.commit()
    out = s.query('{ q(func: alloftext(title@zh, "数据")) { uid } }')
    assert [r["uid"] for r in out["data"]["q"]] == ["0x1"]
    out = s.query('{ q(func: alloftext(title@zh, "计算")) { uid } }')
    assert [r["uid"] for r in out["data"]["q"]] == ["0x2"]
    out = s.query('{ q(func: alloftext(title@ja, "データ")) { uid } }')
    assert [r["uid"] for r in out["data"]["q"]] == ["0x3"]
    # a bigram that spans nothing stored must not match
    out = s.query('{ q(func: alloftext(title@zh, "系统计算")) { uid } }')
    assert out["data"]["q"] == []


def test_decrypt_cli_roundtrip(tmp_path):
    """dgraph decrypt (ref dgraph/cmd/decrypt/decrypt.go:47)."""
    pytest.importorskip("cryptography")
    import gzip
    import os

    from dgraph_tpu.cli import main as cli_main
    from dgraph_tpu.enc import enc

    key = os.urandom(32)
    kf = tmp_path / "key"
    kf.write_bytes(key)
    plain = b"<0x1> <name> \"secret export\" .\n" * 50
    encf = tmp_path / "export.rdf"
    encf.write_bytes(enc.encrypt_stream(plain, key))
    outf = tmp_path / "out.rdf.gz"
    cli_main([
        "decrypt", "-f", str(encf), "-o", str(outf),
        "--encryption-key-file", str(kf),
    ])
    assert gzip.decompress(outf.read_bytes()) == plain

"""Multi-language fulltext stemming (ref tok.go FullTextTokenizer{lang},
bleve per-language analyzers).
"""

import pytest

from dgraph_tpu.api.server import Server
from dgraph_tpu.tok.stemmers import REGISTRY, lang_base
from dgraph_tpu.tok.tok import FulltextTokenizer
from dgraph_tpu.types.types import TypeID, Val


def _toks(text, lang=""):
    t = FulltextTokenizer()
    return {b[1:].decode() for b in t.tokens(Val(TypeID.STRING, text), lang)}


def test_lang_base():
    assert lang_base("fr-CA") == "fr"
    assert lang_base("pt_BR") == "pt"
    assert lang_base("") == ""


def test_spanish_stems_and_stopwords():
    got = _toks("las bibliotecas nacionales", "es")
    # stopword 'las' dropped; plural endings stripped
    assert "las" not in got
    assert _toks("biblioteca nacional", "es") & got


def test_french_stems():
    a = _toks("les nations européennes", "fr")
    b = _toks("nation européenne", "fr")
    assert "les" not in a
    assert a & b


def test_german_stems():
    a = _toks("die Bibliotheken", "de")
    b = _toks("Bibliothek", "de")
    assert a & b


def test_russian_stopwords():
    got = _toks("и все книги", "ru")
    assert "и" not in got


def test_unknown_lang_falls_back():
    # no stemmer: words tokenize as-is through the EN pipeline
    assert _toks("running waters", "xx")


def test_engine_lang_aware_fulltext():
    s = Server()
    s.alter("bio: string @index(fulltext) @lang .")
    t = s.new_txn()
    t.mutate_rdf(
        set_rdf=(
            '<0x1> <bio> "las bibliotecas nacionales"@es .\n'
            '<0x2> <bio> "national libraries"@en .'
        ),
        commit_now=True,
    )
    # Spanish query form matches the Spanish-stemmed document
    out = s.query('{ q(func: alloftext(bio@es, "biblioteca nacional")) { uid } }')
    assert [x["uid"] for x in out["data"]["q"]] == ["0x1"]
    out = s.query('{ q(func: alloftext(bio@en, "library national")) { uid } }')
    assert [x["uid"] for x in out["data"]["q"]] == ["0x2"]

"""Index-assisted and device top-k ordering (VERDICT r1 next-round #9;
ref worker/sort.go:189 sortWithIndex, :245 sortWithoutIndex).
"""

import numpy as np
import pytest

from dgraph_tpu.api.server import Server

SCHEMA = """
name: string @index(exact) .
age: int @index(int) .
score: float @index(float) .
"""


@pytest.fixture(scope="module")
def server():
    s = Server()
    s.alter(SCHEMA)
    t = s.new_txn()
    rdf = []
    # ages 1..60 shuffled across uids; floats with sub-integer parts to
    # exercise lossy-bucket tiebreaks (float indexes at int granularity)
    rng = np.random.default_rng(5)
    ages = rng.permutation(np.arange(1, 61))
    for i, age in enumerate(ages, start=1):
        rdf.append(f'<0x{i:x}> <name> "p{i}" .')
        rdf.append(f'<0x{i:x}> <age> "{age}"^^<xs:int> .')
        rdf.append(f'<0x{i:x}> <score> "{age + (i % 10) / 10.0}"^^<xs:float> .')
    # one uid with no age: must sink to the end
    rdf.append('<0xff> <name> "ageless" .')
    t.mutate_rdf(set_rdf="\n".join(rdf), commit_now=True)
    return s


def _ages(out):
    return [x["age"] for x in out["data"]["q"] if "age" in x]


def test_orderasc_int_index_walk(server):
    out = server.query('{ q(func: has(name), orderasc: age) { name age } }')
    ages = _ages(out)
    assert ages == sorted(ages) and len(ages) == 60
    # nodes missing the sort value sort AFTER every valued one (golden
    # TestNegativeOffset pins keep-missing-last for predicate sorts)
    assert out["data"]["q"][-1]["name"] == "ageless"
    assert len(out["data"]["q"]) == 61


def test_orderdesc_with_first_early_stop(server):
    out = server.query(
        '{ q(func: has(age), orderdesc: age, first: 5) { age } }'
    )
    assert _ages(out) == [60, 59, 58, 57, 56]


def test_order_offset_window(server):
    out = server.query(
        '{ q(func: has(age), orderasc: age, offset: 10, first: 3) { age } }'
    )
    assert _ages(out) == [11, 12, 13]


def test_lossy_float_bucket_inner_sort(server):
    out = server.query('{ q(func: has(age), orderasc: score) { score } }')
    scores = [x["score"] for x in out["data"]["q"]]
    assert scores == sorted(scores)


def test_device_topk_val_var_first():
    s = Server()
    s.alter("name: string @index(exact) .\nrank: int @index(int) .")
    t = s.new_txn()
    n = 6000  # above the 4096 device-top-k threshold
    rng = np.random.default_rng(11)
    ranks = rng.permutation(n) + 1
    rdf = []
    for i in range(1, n + 1):
        rdf.append(f'<0x{i:x}> <name> "u{i}" .')
        rdf.append(f'<0x{i:x}> <rank> "{ranks[i-1]}"^^<xs:int> .')
    t.mutate_rdf(set_rdf="\n".join(rdf), commit_now=True)
    out = s.query(
        """{
          v as var(func: has(rank)) { r as rank }
          q(func: uid(v), orderdesc: val(r), first: 4) { rank }
        }"""
    )
    got = [x["rank"] for x in out["data"]["q"]]
    assert got == [n, n - 1, n - 2, n - 3]
    out = s.query(
        """{
          v as var(func: has(rank)) { r as rank }
          q(func: uid(v), orderasc: val(r), first: 3) { rank }
        }"""
    )
    assert [x["rank"] for x in out["data"]["q"]] == [1, 2, 3]

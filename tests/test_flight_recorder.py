"""The flight recorder: query digest store (shape-keyed aggregates,
LRU eviction into `other`, cluster merge), the metrics history ring
(in-memory + on-disk AppendLog with torn-tail truncation), per-tenant
SLO slices, the wall-clock sampling profiler (on-demand + sustained-
burn auto-trigger), the debug HTTP surfaces, and the one-command
debug bundle.
"""

import json
import os
import tarfile
import threading
import time
import urllib.error
import urllib.request

import pytest

from dgraph_tpu.serving.digest import (
    DIGESTS,
    OTHER_SHAPE,
    DigestStore,
    merge_rows,
)
from dgraph_tpu.utils import observe
from dgraph_tpu.utils.observe import METRICS, HistoryLog, MetricsHistory


# ---------------------------------------------------------------------------
# digest store
# ---------------------------------------------------------------------------


def test_digest_record_snapshot_and_totals():
    d = DigestStore(capacity=8)
    d.record("0", "{ q ( func : has ( ? ) ) { ? } }", 0.010,
             rows=3, nbytes=120, plan_hit=True)
    d.record("0", "{ q ( func : has ( ? ) ) { ? } }", 0.030,
             rows=3, nbytes=120, result_hit=True)
    d.record("0", None, 0.001, error=True)  # unlexable -> `other`
    rows = {(r["ns"], r["shape"]): r for r in d.snapshot()}
    agg = rows[("0", "{ q ( func : has ( ? ) ) { ? } }")]
    assert agg["calls"] == 2 and agg["errors"] == 0
    assert agg["rows"] == 6 and agg["bytes"] == 240
    assert agg["plan_hits"] == 1 and agg["result_hits"] == 1
    assert abs(agg["lat_sum"] - 0.040) < 1e-9
    assert sum(agg["lat_counts"]) == 2
    other = rows[("0", OTHER_SHAPE)]
    assert other["calls"] == 1 and other["errors"] == 1
    t = d.totals()
    assert t["calls"] == 3 and t["errors"] == 1
    assert 0.0 < t["top_shape_lat_share"] <= 1.0


def test_digest_lru_eviction_folds_into_other_conserving_calls():
    d = DigestStore(capacity=2)
    before = METRICS.value("digest_evicted_total")
    for i in range(5):
        d.record("0", f"{{ shape {i} }}", 0.001 * (i + 1))
    rows = d.snapshot()
    assert len(rows) <= 2
    # eviction folded, never dropped: total calls conserved
    assert sum(r["calls"] for r in rows) == 5
    other = [r for r in rows if r["shape"] == OTHER_SHAPE]
    assert other and other[0]["calls"] >= 3
    assert METRICS.value("digest_evicted_total") > before


def test_digest_other_sink_never_evicts_itself():
    d = DigestStore(capacity=2)
    d.record("0", None, 0.001)  # `other` becomes the coldest row
    for i in range(6):
        d.record("0", f"{{ s {i} }}", 0.001)
    rows = d.snapshot()
    assert sum(r["calls"] for r in rows) == 7
    assert any(r["shape"] == OTHER_SHAPE for r in rows)


def test_digest_knob_off_disables_recording(monkeypatch):
    monkeypatch.setenv("DGRAPH_TPU_DIGEST", "0")
    d = DigestStore(capacity=8)
    d.record("0", "{ q }", 0.001)
    assert d.snapshot() == []


def test_digest_merge_rows_sums_per_key_and_bucketwise():
    d = DigestStore(capacity=8)
    d.record("0", "{ a }", 0.010, rows=1)
    d.record("0", "{ a }", 0.020, rows=2)
    d.record("1", "{ a }", 0.005)
    snap = d.snapshot()
    merged = merge_rows([snap, snap])
    by_key = {(r["ns"], r["shape"]): r for r in merged}
    # the cluster-merge contract: merged counts == sum of scrapes
    assert by_key[("0", "{ a }")]["calls"] == 4
    assert by_key[("1", "{ a }")]["calls"] == 2
    one = next(r for r in snap if r["ns"] == "0")
    two = by_key[("0", "{ a }")]
    assert two["lat_counts"] == [c * 2 for c in one["lat_counts"]]
    assert abs(two["lat_sum"] - 2 * one["lat_sum"]) < 1e-9


def test_server_queries_feed_digest_with_normalized_shape():
    from dgraph_tpu.api.server import Server

    DIGESTS.reset()
    s = Server()
    s.alter("fname: string @index(exact) .")
    s.new_txn().mutate_rdf(
        set_rdf='<0x1> <fname> "A" .\n<0x2> <fname> "B" .',
        commit_now=True,
    )
    # two literals, one shape: digest keys on the normalized form
    s.query('{ q(func: eq(fname, "A")) { fname } }')
    s.query('{ q(func: eq(fname, "B")) { fname } }')
    rows = [r for r in DIGESTS.snapshot() if r["shape"] != OTHER_SHAPE]
    assert len(rows) == 1, rows
    r = rows[0]
    assert r["calls"] == 2 and r["errors"] == 0
    assert "?" in r["shape"] and '"A"' not in r["shape"]
    assert r["rows"] == 2 and r["bytes"] > 0
    # a failing query still accrues (as an error) — never silently lost
    with pytest.raises(Exception):
        s.query("{ q(func: eq(nosuchpred")
    total = DIGESTS.totals()
    assert total["errors"] >= 1


def test_slow_query_log_records_digest_shape(tmp_path, monkeypatch):
    from dgraph_tpu.api.server import Server

    log = tmp_path / "slow.jsonl"
    monkeypatch.setenv("DGRAPH_TPU_SLOW_QUERY_LOG", str(log))
    monkeypatch.setenv("DGRAPH_TPU_SLOW_QUERY_MS", "0.0")
    s = Server()
    s.alter("sqname: string .")
    s.new_txn().mutate_rdf(
        set_rdf='<0x1> <sqname> "A" .', commit_now=True
    )
    s.query('{ q(func: has(sqname)) { sqname } }')
    rec = json.loads(log.read_text().splitlines()[-1])
    assert "shape" in rec and "sqname" in rec["shape"], rec
    assert rec.get("ns") is not None


def test_recorder_on_off_byte_identity():
    """Spot check of the --obs-sanity gate's property: the recorder
    never changes response bytes."""
    from dgraph_tpu.api.server import Server
    from dgraph_tpu.x import config

    s = Server()
    s.alter("biname: string @index(exact) .")
    s.new_txn().mutate_rdf(
        set_rdf='<0x1> <biname> "A" .', commit_now=True
    )
    q = '{ q(func: eq(biname, "A")) { biname } }'

    def run():
        d = s.query(q, want="raw")["data"]
        raw = getattr(d, "raw", None)
        return bytes(raw) if raw is not None else json.dumps(
            d, sort_keys=True
        ).encode()

    config.set_env("DIGEST", 0)
    config.set_env("HISTORY", 0)
    try:
        off = run()
    finally:
        config.unset_env("DIGEST")
        config.unset_env("HISTORY")
    assert run() == off


# ---------------------------------------------------------------------------
# per-tenant SLO slices
# ---------------------------------------------------------------------------


def test_tenant_slices_report_and_healthz(monkeypatch):
    monkeypatch.setattr(observe, "_TENANT_SLO", {})
    observe.note_tenant("query", 7, 0.001)
    observe.note_tenant("query", 7, 0.002)
    observe.note_tenant("commit", 0, 0.001)
    rep = observe.tenant_slo_report()
    assert rep["query"]["7"]["windows"]["60s"]["total"] == 2
    assert rep["commit"]["0"]["windows"]["60s"]["total"] == 1
    h = observe.healthz()
    assert h["tenants"]["slo"]["query"]["7"]["windows"]["60s"]["total"] == 2
    assert "traffic" in h["tenants"]


def test_tenant_slices_bounded(monkeypatch):
    monkeypatch.setattr(observe, "_TENANT_SLO", {})
    for i in range(observe._TENANT_CAP + 16):
        observe.note_tenant("query", i, 0.001)
    assert len(observe._TENANT_SLO) <= observe._TENANT_CAP


# ---------------------------------------------------------------------------
# metrics history ring
# ---------------------------------------------------------------------------


def test_history_report_windowed_deltas():
    h = MetricsHistory(retention=16)
    h.record_now()
    METRICS.inc("num_queries", 3)
    h.record_now()
    rep = h.report(window_s=3600.0)
    assert rep["samples"] >= 2 and rep["retained"] >= 2
    assert rep["deltas"].get("num_queries") == 3.0
    assert rep["to_ts"] >= rep["from_ts"]
    # zero-delta metrics are dropped from the payload
    assert all(v for v in rep["deltas"].values())


def test_history_retention_bounds_ring():
    h = MetricsHistory(retention=4)
    for _ in range(9):
        h.record_now()
    assert len(h.snapshots()) == 4
    h.reset()
    assert h.snapshots() == []


def test_history_disk_roundtrip_survives_restart(tmp_path, monkeypatch):
    monkeypatch.setenv("DGRAPH_TPU_HISTORY_DIR", str(tmp_path))
    h = MetricsHistory(retention=8)
    h.set_label("t-restart")
    for _ in range(3):
        h.record_now()
    # a fresh process: empty ring, replayed from the same on-disk file
    h2 = MetricsHistory(retention=8)
    h2.set_label("t-restart")
    assert h2.load_disk() == 3
    assert len(h2.snapshots()) == 3
    # load_disk never clobbers a live ring
    assert h2.load_disk() == 0


def test_history_disk_rotation_keeps_newest_half(tmp_path, monkeypatch):
    monkeypatch.setenv("DGRAPH_TPU_HISTORY_DISK_MAX_BYTES", "4096")
    log = HistoryLog(str(tmp_path / "ring.log"))
    pad = "x" * 256
    rotations = 0
    for i in range(64):
        rotations += log.append({"i": i, "pad": pad})
    assert rotations >= 1
    snaps = log.scan()
    assert snaps, "rotation emptied the ring"
    # newest records survive; the oldest were dropped
    assert snaps[-1]["i"] == 63
    assert snaps[0]["i"] > 0
    assert os.path.getsize(log.path) <= 2 * 4096
    log.close()


def test_history_log_torn_tail_every_byte_boundary(tmp_path):
    """A crash mid-append leaves a torn tail: reopening folds to the
    last COMPLETE snapshot and physically truncates the garbage (the
    AppendLog WAL-crash contract, exercised at every byte boundary)."""
    from dgraph_tpu.worker.tabletmove import AppendLog

    seed = tmp_path / "seed.log"
    log = HistoryLog(str(seed))
    for i in range(3):
        log.append({"i": i, "values": {"m": float(i)}})
    log.close()
    blob = seed.read_bytes()
    offsets, pos = [], 0
    while pos < len(blob):
        _, plen = AppendLog._HDR.unpack_from(blob, pos)
        offsets.append(pos)
        pos += AppendLog._HDR.size + plen
    assert pos == len(blob) and len(offsets) == 3
    last = offsets[-1]
    for cut in range(last, len(blob)):
        p = tmp_path / f"cut_{cut}.log"
        p.write_bytes(blob[:cut])
        lr = HistoryLog(str(p))
        snaps = lr.scan()
        assert [s["i"] for s in snaps] == [0, 1], cut
        assert os.path.getsize(p) == last, cut  # tail truncated
        # appends after repair land on a clean boundary
        lr.append({"i": 99})
        lr.close()
        lr2 = HistoryLog(str(p))
        assert [s["i"] for s in lr2.scan()] == [0, 1, 99], cut
        lr2.close()


def test_history_sampler_thread_ticks(monkeypatch):
    monkeypatch.setenv("DGRAPH_TPU_HISTORY_INTERVAL_S", "0.05")
    h = MetricsHistory(retention=64)
    h.start()
    try:
        deadline = time.monotonic() + 5.0
        while len(h.snapshots()) < 2 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert len(h.snapshots()) >= 2
    finally:
        h.stop()


# ---------------------------------------------------------------------------
# sampling profiler
# ---------------------------------------------------------------------------


def _burn(stop):
    while not stop.is_set():
        sum(i * i for i in range(500))


def test_profiler_folds_busy_thread_stacks():
    from dgraph_tpu.utils.profiler import PROFILER

    before = METRICS.value("profiler_samples_total")
    stop = threading.Event()
    t = threading.Thread(target=_burn, args=(stop,), daemon=True)
    t.start()
    try:
        folded = PROFILER.profile(0.3, hz=200)
    finally:
        stop.set()
        t.join()
    assert folded.strip(), "no stacks sampled"
    assert "_burn" in folded
    # folded format: `frame;frame;... count`, counts descending
    counts = [int(line.rsplit(" ", 1)[1])
              for line in folded.strip().splitlines()]
    assert counts == sorted(counts, reverse=True)
    assert METRICS.value("profiler_samples_total") > before
    assert METRICS.value("profiler_active") == 0.0


def test_auto_profiler_triggers_on_burn_with_cooldown(monkeypatch):
    from dgraph_tpu.utils import profiler as profmod

    monkeypatch.setenv("DGRAPH_TPU_PROFILE_AUTO_S", "0.1")
    monkeypatch.setenv("DGRAPH_TPU_PROFILE_BURN", "2.0")
    auto = profmod.AutoProfiler()
    monkeypatch.setattr(
        auto, "_query_burn_300s", staticmethod(lambda: 9.0)
    )
    before = METRICS.value("profiler_auto_triggers_total")
    stop = threading.Event()
    t = threading.Thread(target=_burn, args=(stop,), daemon=True)
    t.start()
    try:
        assert auto.check() is True
        # cooldown: a second sustained-burn tick does NOT re-trigger
        assert auto.check() is False
        deadline = time.monotonic() + 5.0
        while auto.last_info() is None and time.monotonic() < deadline:
            time.sleep(0.02)
    finally:
        stop.set()
        t.join()
    info = auto.last_info()
    assert info and info["burn"] == 9.0
    assert auto.last(), "auto-capture retained no folded stacks"
    assert METRICS.value("profiler_auto_triggers_total") == before + 1


def test_auto_profiler_quiet_below_burn(monkeypatch):
    from dgraph_tpu.utils import profiler as profmod

    monkeypatch.setenv("DGRAPH_TPU_PROFILE_BURN", "2.0")
    auto = profmod.AutoProfiler()
    monkeypatch.setattr(
        auto, "_query_burn_300s", staticmethod(lambda: 1.0)
    )
    assert auto.check() is False
    monkeypatch.setenv("DGRAPH_TPU_PROFILE_AUTO", "0")
    monkeypatch.setattr(
        auto, "_query_burn_300s", staticmethod(lambda: 99.0)
    )
    assert auto.check() is False


# ---------------------------------------------------------------------------
# debug HTTP surfaces + CLI
# ---------------------------------------------------------------------------


@pytest.fixture()
def http_server():
    from dgraph_tpu.api.http_server import HTTPServer
    from dgraph_tpu.api.server import Server

    engine = Server()
    engine.alter("hname: string @index(exact) .")
    engine.new_txn().mutate_rdf(
        set_rdf='<0x1> <hname> "A" .', commit_now=True
    )
    srv = HTTPServer(engine, port=0).start()
    yield engine, srv
    srv.stop()


def _get(srv, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{srv.port}{path}", timeout=10
    ) as r:
        return r.read()


def test_debug_http_flight_recorder_routes(http_server):
    engine, srv = http_server
    DIGESTS.reset()
    engine.query("{ q(func: has(hname)) { hname } }")
    body = json.loads(_get(srv, "/debug/digests"))
    assert body["digests"] and body["digests"][0]["calls"] >= 1
    hist = json.loads(_get(srv, "/debug/history?window=60"))
    assert "samples" in hist and "retained" in hist
    cfg = json.loads(_get(srv, "/debug/config"))
    assert cfg["DIGEST"]["env"] == "DGRAPH_TPU_DIGEST"
    assert "value" in cfg["HISTORY_INTERVAL_S"]
    stop = threading.Event()
    t = threading.Thread(target=_burn, args=(stop,), daemon=True)
    t.start()
    try:
        folded = _get(srv, "/debug/profile?seconds=0.1")
    finally:
        stop.set()
        t.join()
    assert b"_burn" in folded
    # no auto-capture yet -> 404 on ?last=1
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(srv, "/debug/profile?last=1")
    assert ei.value.code == 404
    assert _get(srv, "/debug/slowlog") is not None


def test_cli_top_renders_digest_rows(http_server, capsys):
    from dgraph_tpu import cli

    engine, srv = http_server
    DIGESTS.reset()
    engine.query('{ q(func: eq(hname, "A")) { hname } }')
    rc = cli.main([
        "top", "--addr", f"http://127.0.0.1:{srv.port}", "-n", "5",
    ])
    assert rc in (0, None)
    out = capsys.readouterr().out
    assert "CALLS" in out and "SHAPE" in out
    assert "hname" in out
    rc = cli.main([
        "top", "--addr", f"http://127.0.0.1:{srv.port}", "--json",
    ])
    assert rc in (0, None)
    body = json.loads(capsys.readouterr().out)
    assert body["digests"]


def test_cli_debug_bundle_against_live_server(http_server, tmp_path,
                                              capsys):
    from dgraph_tpu import cli

    engine, srv = http_server
    engine.query("{ q(func: has(hname)) { hname } }")
    out = tmp_path / "bundle.tar.gz"
    rc = cli.main([
        "debug-bundle",
        "--addr", f"http://127.0.0.1:{srv.port}",
        "-o", str(out),
    ])
    assert rc in (0, None)
    assert "wrote" in capsys.readouterr().out
    with tarfile.open(out) as tar:
        names = {m.name for m in tar.getmembers()}
        for want in (
            "debug-bundle/MANIFEST.json",
            "debug-bundle/metrics.prom",
            "debug-bundle/digests.json",
            "debug-bundle/history.json",
            "debug-bundle/health.json",
            "debug-bundle/config.json",
            "debug-bundle/lockgraph.json",
        ):
            assert want in names, want
        manifest = json.load(
            tar.extractfile("debug-bundle/MANIFEST.json")
        )
        assert all(
            f.get("ok") for f in manifest["files"].values()
        ), manifest["files"]
        digests = json.load(
            tar.extractfile("debug-bundle/digests.json")
        )
        assert digests["digests"]
        lg = json.load(tar.extractfile("debug-bundle/lockgraph.json"))
        assert lg["edges"] and {"outer", "inner", "path"} <= set(
            lg["edges"][0]
        )


def test_cli_debug_bundle_partial_when_endpoint_dead(tmp_path, capsys):
    """Every endpoint down (no server at all) still yields a readable
    bundle: locally-computed sections present, failures in MANIFEST."""
    import socket

    from dgraph_tpu import cli

    with socket.socket() as sk:
        sk.bind(("127.0.0.1", 0))
        dead_port = sk.getsockname()[1]
    out = tmp_path / "partial.tar.gz"
    rc = cli.main([
        "debug-bundle",
        "--addr", f"http://127.0.0.1:{dead_port}",
        "-o", str(out), "--timeout", "0.5",
    ])
    assert rc in (0, None)
    assert "PARTIAL" in capsys.readouterr().out
    with tarfile.open(out) as tar:
        names = {m.name for m in tar.getmembers()}
        assert "debug-bundle/MANIFEST.json" in names
        assert "debug-bundle/lockgraph.json" in names
        assert "debug-bundle/config.json" in names  # local fallback
        manifest = json.load(
            tar.extractfile("debug-bundle/MANIFEST.json")
        )
        assert not manifest["files"]["metrics.prom"]["ok"]
        assert manifest["files"]["config.json"].get("local")

"""GraphQL conformance against the reference's own oracles (VERDICT r3 #4).

Two tiers, mirroring how tests/test_ref_golden.py gave DQL its oracle:

Tier A — e2e response goldens: cases extracted from
/root/reference/graphql/e2e/common/query.go (extract_goldens.py) run
over the normal-suite fixture (e2e_schema.graphql + e2e_data.json,
copied from /root/reference/graphql/e2e/normal/) and compared with
testify-JSONEq / testutil-CompareJSON semantics.

Tier B — translation-equivalence goldens: the 167 cases of
/root/reference/graphql/resolve/query_test.yaml each pair a GraphQL
query with the DQL the reference rewrites it to. Both run against the
SAME store here: the GraphQL query through our graphql layer, the
reference-blessed dgquery through our DQL engine (itself 535/535
conformant to the reference query suites) — results must agree after
alias normalization. This checks our GraphQL semantics against the
reference's rewriter without requiring byte-identical internal DQL.

Failures are tracked in known_fails_{e2e,resolve}.json (strict xfail —
a fixed case must be removed); shrinking them is the metric.
"""

import json
import os

import pytest

HERE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "ref_golden_graphql"
)

E2E_CASES = json.load(open(os.path.join(HERE, "cases.json")))
RESOLVE_CASES = json.load(open(os.path.join(HERE, "resolve_cases.json")))


def _load(name):
    p = os.path.join(HERE, name)
    return set(json.load(open(p))) if os.path.exists(p) else set()


KNOWN_E2E = _load("known_fails_e2e.json")
KNOWN_RESOLVE = _load("known_fails_resolve.json")


# ---------------------------------------------------------------------------
# Fixtures
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def e2e():
    from dgraph_tpu.api.server import Server
    from dgraph_tpu.graphql import GraphQLServer

    s = Server()
    gql = GraphQLServer(
        s, open(os.path.join(HERE, "e2e_schema.graphql")).read()
    )
    data = json.load(open(os.path.join(HERE, "e2e_data.json")))
    t = s.new_txn()
    t.mutate_json(set_obj=data)
    t.commit()
    return gql


@pytest.fixture(scope="module")
def e2e_directives():
    """The SAME e2e cases over the directives fixture (ref
    graphql/e2e/directives: @dgraph(type:/pred:) storage mappings +
    reverse-edge preds) — the reference's RunAll exercises both
    clusters; so do we."""
    from dgraph_tpu.api.server import Server
    from dgraph_tpu.graphql import GraphQLServer

    s = Server()
    gql = GraphQLServer(
        s, open(os.path.join(HERE, "e2e_directives_schema.graphql")).read()
    )
    data = json.load(
        open(os.path.join(HERE, "e2e_directives_data.json"))
    )
    t = s.new_txn()
    t.mutate_json(set_obj=data)
    t.commit()
    return gql


@pytest.fixture(scope="module")
def resolve_world():
    from dgraph_tpu.api.server import Server
    from dgraph_tpu.graphql import GraphQLServer

    s = Server()
    gql = GraphQLServer(
        s, open(os.path.join(HERE, "resolve_schema.graphql")).read()
    )

    def mut(q, variables=None):
        res = gql.execute(q, variables=variables)
        assert "errors" not in res or not res["errors"], res
        return res

    # a small world covering the resolve schema's main types, seeded
    # through our own GraphQL mutations so every query has data to hit
    mut(
        """
        mutation {
          addCountry(input: [
            {name: "Ruritania", states: [
              {code: "RU-N", name: "North", capital: "Nordberg"},
              {code: "RU-S", name: "South"}]},
            {name: "Elbonia", states: [{code: "EL-1", name: "Mud"}]}
          ]) { numUids }
        }
        """
    )
    mut(
        """
        mutation {
          addAuthor(input: [
            {name: "A. N. Author", dob: "2000-01-01", reputation: 6.6,
             posts: [
               {title: "GraphQL doco", text: "types and queries",
                tags: ["graphql", "docs"], numLikes: 100,
                isPublished: true, postType: [Fact]},
               {title: "Random post", text: "this is random",
                tags: ["random"], numLikes: 2, isPublished: false,
                postType: [Opinion]}
             ]},
            {name: "Other Author", dob: "1988-01-01", reputation: 8.9,
             posts: [{title: "Another post", text: "words",
                      tags: ["docs"], numLikes: 10, isPublished: true,
                      postType: [Question]}]}
          ]) { numUids }
        }
        """
    )
    mut(
        """
        mutation {
          addEditor(input: [{code: "ed1", name: "E. Ditor"}]) { numUids }
        }
        """
    )
    mut(
        """
        mutation {
          addHuman(input: [
            {name: "Bob", ename: "bob-emp", dob: "2000-01-01",
             female: false}
          ]) { numUids }
        }
        """
    )
    mut(
        """
        mutation {
          addUser(input: [{name: "user1", pwd: "Password"}]) { numUids }
        }
        """
    )
    mut(
        """
        mutation {
          addAstronaut(input: [
            {id: "0x1", missions: [{id: "m1", designation: "Apollo"}]},
            {id: "0x2", missions: [{id: "m2", designation: "Artemis"}]}
          ]) { numUids }
          addSpaceShip(input: [
            {id: "0x1", missions: [{id: "m3", designation: "Falcon"}]}
          ]) { numUids }
        }
        """
    )
    mut(
        """
        mutation {
          addVerification(input: [
            {name: "v1", status: [ACTIVE], prevStatus: INACTIVE},
            {name: "v2", status: [INACTIVE, DEACTIVATED],
             prevStatus: ACTIVE}
          ]) { numUids }
        }
        """
    )
    return gql, s


# ---------------------------------------------------------------------------
# Comparison helpers
# ---------------------------------------------------------------------------


def _canon(x):
    if isinstance(x, dict):
        return {k: _canon(v) for k, v in x.items()}
    if isinstance(x, list):
        return [_canon(v) for v in x]
    if isinstance(x, bool):
        return x
    if isinstance(x, (int, float)):
        return float(x)
    return x


def _sorted_lists(x):
    """testutil.CompareJSON semantics: arrays compare order-insensitively
    at every depth."""
    if isinstance(x, dict):
        return {k: _sorted_lists(v) for k, v in x.items()}
    if isinstance(x, list):
        return sorted(
            (_sorted_lists(v) for v in x),
            key=lambda v: json.dumps(v, sort_keys=True),
        )
    return x


_CHILD_AGG_RE = None


def _strip_ref(x):
    """Normalize a dgquery response: 'Type.field' aliases -> 'field',
    drop dgraph.uid / dgraph.type (the rewriter injects both), and fold
    the rewriter's flat child-aggregate aliases
    ('AggRes.cnt_Country.ag': N -> {'ag': {'cnt': N}})."""
    global _CHILD_AGG_RE
    import re

    if _CHILD_AGG_RE is None:
        _CHILD_AGG_RE = re.compile(r"^(\w+)_[A-Z]\w*\.(\w+)$")
    if isinstance(x, dict):
        out = {}
        folded = {}
        for k, v in x.items():
            if k in ("dgraph.uid", "dgraph.type"):
                continue
            # 'AggRes.cnt_Country.ag' -> strip the alias-type prefix,
            # leaving 'cnt_Country.ag' for the fold below
            k = k.split(".", 1)[1] if "." in k else k
            m = _CHILD_AGG_RE.match(k)
            if m:
                folded.setdefault(m.group(2), {})[m.group(1)] = _strip_ref(v)
            else:
                out[k] = _strip_ref(v)
        out.update(folded)
        return out
    if isinstance(x, list):
        return [_strip_ref(v) for v in x]
    return x


def _strip_ours(x):
    """Normalize our GraphQL response for DQL comparison: drop
    requested-but-missing fields (GraphQL nulls / empty lists — DQL
    omits them) and __typename (no DQL counterpart)."""
    if isinstance(x, dict):
        out = {}
        for k, v in x.items():
            if v is None or v == [] or k == "__typename":
                continue
            sv = _strip_ours(v)
            if sv == {}:
                # an all-null child aggregate strips to {}; DQL omits
                # the block entirely
                continue
            out[k] = sv
        return out
    if isinstance(x, list):
        return [_strip_ours(v) for v in x]
    return x


# ---------------------------------------------------------------------------
# Tier A: e2e response goldens
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "case",
    [
        pytest.param(
            c,
            marks=(
                [pytest.mark.xfail(strict=True, reason="tracked gap")]
                if c["id"] in KNOWN_E2E
                else []
            ),
        )
        for c in E2E_CASES
    ],
    ids=[c["id"] for c in E2E_CASES],
)
def test_graphql_e2e_golden(case, e2e):
    res = e2e.execute(case["query"], variables=case.get("variables"))
    assert "errors" not in res or not res["errors"], res
    got = _canon(res["data"])
    want = _canon(json.loads(case["expected"]))
    if case.get("unordered"):
        got, want = _sorted_lists(got), _sorted_lists(want)
    assert got == want


KNOWN_DIRECTIVES = _load("known_fails_directives.json")


@pytest.mark.parametrize(
    "case",
    [
        pytest.param(
            c,
            marks=(
                [pytest.mark.xfail(strict=True, reason="tracked gap")]
                if c["id"] in KNOWN_DIRECTIVES
                else []
            ),
        )
        for c in E2E_CASES
    ],
    ids=[f"dir-{c['id']}" for c in E2E_CASES],
)
def test_graphql_e2e_golden_directives(case, e2e_directives):
    """Same goldens over @dgraph-mapped storage (type renames, custom
    predicate names, reverse-edge mappings)."""
    res = e2e_directives.execute(
        case["query"], variables=case.get("variables")
    )
    assert "errors" not in res or not res["errors"], res
    got = _canon(res["data"])
    want = _canon(json.loads(case["expected"]))
    if case.get("unordered"):
        got, want = _sorted_lists(got), _sorted_lists(want)
    assert got == want


def _normalize_pair(ours_data, ref_data):
    """(got, want) ready to compare: our entities stripped of GraphQL
    nulls/empties (DQL omits them), ref aliases de-qualified, getX
    object results wrapped to lists, and root keys aligned (our
    response honors root aliases; the dgquery block keeps the
    generated operation name)."""
    got = {}
    for k, v in ours_data.items():
        if not isinstance(v, list):
            v = [] if v is None else [v]
        got[k] = _strip_ours(v)
    want = _strip_ref(ref_data)
    # the reference rewriter injects val(distance) as vector_distance
    # even when the GraphQL query never selected it; drop it from the
    # dgquery side unless our response carries it too
    def _has_vd(x):
        if isinstance(x, dict):
            return "vector_distance" in x or any(
                _has_vd(v) for v in x.values()
            )
        if isinstance(x, list):
            return any(_has_vd(v) for v in x)
        return False

    def _drop_vd(x):
        if isinstance(x, dict):
            return {
                k: _drop_vd(v)
                for k, v in x.items()
                if k != "vector_distance"
            }
        if isinstance(x, list):
            return [_drop_vd(v) for v in x]
        return x

    if not _has_vd(got):
        want = _drop_vd(want)
    # rewriter helper blocks appear in the dgquery response but have no
    # GraphQL counterpart — an EXPLICIT allowlist only (VERDICT r4 #5:
    # a blanket subset-drop would also hide root fields our resolver
    # silently failed to return)
    _HELPER_KEYS = ("checkPwd",)
    for hk in _HELPER_KEYS:
        if hk in want and hk not in got:
            want = {k: v for k, v in want.items() if k != hk}
    # DQL encodes a root aggregate as one single-key object per
    # aggregate child; GraphQL completion merges them and turns a
    # missing count into 0 (ref completeAggregateValues). Apply the
    # same completion to the dgquery side before comparing.
    for k, v in list(want.items()):
        g = got.get(k)
        if (
            isinstance(v, list)
            and len(v) > 1
            and all(isinstance(e, dict) and len(e) <= 1 for e in v)
            and isinstance(g, list)
            and len(g) == 1
        ):
            merged = {}
            for e in v:
                merged.update(e)
            merged = {
                mk: (0 if mv is None and mk in g[0] and g[0][mk] == 0 else mv)
                for mk, mv in merged.items()
            }
            merged = {mk: mv for mk, mv in merged.items() if mv is not None}
            want[k] = [merged]
    if set(got) != set(want) and len(got) == len(want):
        # root alias: compare positionally (both sides preserve
        # selection order)
        got = {i: v for i, v in enumerate(got.values())}
        want = {i: v for i, v in enumerate(want.values())}
    return got, want



# ---------------------------------------------------------------------------
# Tier B: translation equivalence
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "case",
    [
        pytest.param(
            c,
            marks=(
                [pytest.mark.xfail(strict=True, reason="tracked gap")]
                if c["id"] in KNOWN_RESOLVE
                else []
            ),
        )
        for c in RESOLVE_CASES
    ],
    ids=[c["id"] for c in RESOLVE_CASES],
)
def test_graphql_resolve_equiv(case, resolve_world):
    gql, s = resolve_world
    ours = gql.execute(case["gqlquery"], variables=case.get("gqlvariables"))
    assert "errors" not in ours or not ours["errors"], ours
    ref = s.query(case["dgquery"], variables=case.get("dgvars"))["data"]
    got, want = _normalize_pair(ours["data"], ref)
    assert _canon(_sorted_lists(got)) == _canon(_sorted_lists(want))

"""Upserts, math expressions, @groupby (ref query/math.go, groupby.go,
edgraph upsert path)."""

import pytest

from dgraph_tpu.api.server import Server

SCHEMA = """
name: string @index(exact) @upsert .
email: string @index(exact) @upsert .
age: int @index(int) .
bonus: float .
friend: [uid] @reverse .
"""


def _server():
    s = Server()
    s.alter(SCHEMA)
    t = s.new_txn()
    t.mutate_rdf(
        set_rdf="""
        <0x1> <name> "Alice" .
        <0x1> <age> "30"^^<xs:int> .
        <0x1> <bonus> "2.5"^^<xs:float> .
        <0x2> <name> "Bob" .
        <0x2> <age> "25"^^<xs:int> .
        <0x2> <bonus> "1.5"^^<xs:float> .
        <0x3> <name> "Carol" .
        <0x3> <age> "25"^^<xs:int> .
        <0x1> <friend> <0x2> .
        <0x1> <friend> <0x3> .
        """,
        commit_now=True,
    )
    return s


def test_math_expr():
    s = _server()
    res = s.query(
        """
        {
          q(func: has(bonus)) {
            name
            a as age
            b as bonus
            total: math(a + b * 2)
          }
        }
        """
    )["data"]
    by = {o["name"]: o["total"] for o in res["q"]}
    assert by == {"Alice": 35.0, "Bob": 28.0}


def test_math_var_reuse_and_order():
    s = _server()
    res = s.query(
        """
        {
          var(func: has(age)) {
            a as age
            double as math(a * 2)
          }
          q(func: uid(double), orderdesc: val(double)) {
            name
            val(double)
          }
        }
        """
    )["data"]
    assert [o["name"] for o in res["q"]][0] == "Alice"
    assert res["q"][0]["val(double)"] == 60


def test_groupby_value_pred():
    s = _server()
    res = s.query(
        """
        {
          q(func: uid(0x1)) {
            friend @groupby(age) {
              count(uid)
            }
          }
        }
        """
    )["data"]
    groups = res["q"][0]["friend"][0]["@groupby"]
    assert groups == [{"age": 25, "count": 2}]


def test_upsert_insert_then_update():
    s = _server()
    # first run: no match -> create via blank node
    t = s.new_txn()
    uids = t.upsert(
        query='{ v as var(func: eq(email, "x@y.z")) }',
        set_rdf='_:new <email> "x@y.z" .\n_:new <name> "Xavier" .',
        cond="@if(eq(len(v), 0))",
    )
    assert "new" in uids
    # second run: match -> cond fails, no new node
    t = s.new_txn()
    uids = t.upsert(
        query='{ v as var(func: eq(email, "x@y.z")) }',
        set_rdf='_:new <email> "x@y.z" .\n_:new <name> "DUPE" .',
        cond="@if(eq(len(v), 0))",
    )
    assert uids == {}
    res = s.query('{ q(func: eq(email, "x@y.z")) { name } }')["data"]
    assert res["q"] == [{"name": "Xavier"}]


def test_upsert_update_via_uid_var():
    s = _server()
    t = s.new_txn()
    t.upsert(
        query='{ v as var(func: eq(name, "Bob")) }',
        set_rdf='uid(v) <age> "26"^^<xs:int> .',
    )
    res = s.query('{ q(func: eq(name, "Bob")) { age } }')["data"]
    assert res["q"] == [{"age": 26}]


def test_upsert_val_var_copy():
    s = _server()
    t = s.new_txn()
    # copy each person's age into bonus via val(var)
    t.upsert(
        query="{ v as var(func: has(age)) { a as age } }",
        set_rdf="uid(v) <bonus> val(a) .",
    )
    res = s.query('{ q(func: eq(name, "Carol")) { bonus } }')["data"]
    assert res["q"] == [{"bonus": 25.0}]


def test_negative_numbers_in_args():
    s = _server()
    t = s.new_txn()
    t.mutate_rdf(set_rdf='<0x9> <age> "-5"^^<xs:int> .', commit_now=True)
    res = s.query("{ q(func: lt(age, -1)) { uid age } }")["data"]
    assert res["q"] == [{"uid": "0x9", "age": -5}]


def test_double_division_and_negative_first():
    s = _server()
    res = s.query(
        "{ q(func: has(age)) { a as age half: math(a / 2 / 1) } }"
    )["data"]
    assert any(o.get("half") == 15.0 for o in res["q"])
    res = s.query("{ q(func: has(age), first: -2, orderasc: age) { age } }")[
        "data"
    ]
    assert len(res["q"]) == 2


def test_upsert_self_pair_edges():
    s = _server()
    t = s.new_txn()
    t.upsert(
        query='{ v as var(func: eq(age, 25)) }',
        set_rdf="uid(v) <friend> uid(v) .",
    )
    # v = {Bob(0x2), Carol(0x3)}: cross product incl. self-pairs written
    # with correct subject->object orientation
    res = s.query("{ q(func: uid(0x2)) { friend { uid } } }")["data"]
    uids = {o["uid"] for o in res["q"][0]["friend"]}
    assert uids == {"0x2", "0x3"}


def test_count_index_root_funcs():
    s = Server()
    s.alter("name: string @index(exact) .\nfriend: [uid] @count .")
    t = s.new_txn()
    t.mutate_rdf(
        set_rdf="""
        <0x1> <friend> <0x10> .
        <0x1> <friend> <0x11> .
        <0x1> <friend> <0x12> .
        <0x2> <friend> <0x10> .
        <0x3> <name> "loner" .
        """,
        commit_now=True,
    )
    res = s.query("{ q(func: eq(count(friend), 3)) { uid } }")["data"]
    assert res["q"] == [{"uid": "0x1"}]
    res = s.query("{ q(func: ge(count(friend), 1)) { uid } }")["data"]
    assert {o["uid"] for o in res["q"]} == {"0x1", "0x2"}
    # as a filter over candidates
    res = s.query(
        "{ q(func: has(friend)) @filter(lt(count(friend), 2)) { uid } }"
    )["data"]
    assert res["q"] == [{"uid": "0x2"}]


def test_subscriptions():
    from dgraph_tpu.api.subscriptions import Subscriptions

    s = Server()
    s.alter("name: string @index(exact) .\ncity: string .")
    events = []
    subs = Subscriptions(s)
    sid = subs.subscribe(
        "{ q(func: has(name)) { name } }", lambda r: events.append(r)
    )
    assert len(events) == 1  # initial snapshot
    t = s.new_txn()
    t.mutate_rdf(set_rdf='<0x1> <name> "N" .', commit_now=True)
    assert len(events) == 2
    assert events[1]["data"]["q"] == [{"name": "N"}]
    # commit touching an unrelated pred does not refire
    t = s.new_txn()
    t.mutate_rdf(set_rdf='<0x2> <city> "Pune" .', commit_now=True)
    assert len(events) == 2
    subs.unsubscribe(sid)
    t = s.new_txn()
    t.mutate_rdf(set_rdf='<0x3> <name> "M" .', commit_now=True)
    assert len(events) == 2


def test_count_reverse_edges():
    s = Server()
    s.alter("friend: [uid] @reverse @count .")
    t = s.new_txn()
    t.mutate_rdf(
        set_rdf="<0x1> <friend> <0x9> .\n<0x2> <friend> <0x9> .\n"
        "<0x3> <friend> <0x9> .\n<0x1> <friend> <0x8> .",
        commit_now=True,
    )
    res = s.query("{ q(func: eq(count(~friend), 3)) { uid } }")["data"]
    assert res["q"] == [{"uid": "0x9"}]
    res = s.query("{ q(func: eq(count(~friend), 1)) { uid } }")["data"]
    assert res["q"] == [{"uid": "0x8"}]


def test_subscription_acl_safe():
    from dgraph_tpu.api.subscriptions import Subscriptions

    s = _server()
    s.enable_acl(secret=b"z" * 32)
    g = s.login("groot", "password")["accessJwt"]
    events = []
    subs = Subscriptions(s)
    subs.subscribe(
        "{ q(func: has(name)) { name } }",
        lambda r: events.append(r),
        access_jwt=g,
    )
    t = s.new_txn()
    # commit succeeds even though subscription re-evaluation runs under ACL
    t.mutate_rdf(set_rdf='<0x1> <name> "S" .', access_jwt=g, commit_now=True)
    assert len(events) == 2


def test_checkpwd_and_geo_within():
    s = Server()
    s.alter("pw: password .\nloc: geo @index(geo) .\nname: string @index(exact) .")
    t = s.new_txn()
    t.mutate_rdf(
        # clients write PLAINTEXT; the type conversion hashes at ingest
        set_rdf='<0x1> <pw> "s3cret"^^<xs:password> .\n'
        '<0x1> <name> "u1" .\n'
        '<0x2> <loc> "{\\"type\\":\\"Point\\",\\"coordinates\\":[10.0,10.0]}"^^<geo:geojson> .\n'
        '<0x3> <loc> "{\\"type\\":\\"Point\\",\\"coordinates\\":[50.0,50.0]}"^^<geo:geojson> .',
        commit_now=True,
    )
    res = s.query('{ q(func: uid(0x1)) @filter(checkpwd(pw, "s3cret")) { name } }')["data"]
    assert res["q"] == [{"name": "u1"}]
    res = s.query('{ q(func: uid(0x1)) @filter(checkpwd(pw, "wrong")) { name } }')["data"]
    assert res["q"] == []
    res = s.query(
        "{ q(func: within(loc, [[[5.0,5.0],[15.0,5.0],[15.0,15.0],[5.0,15.0]]])) { uid } }"
    )["data"]
    assert res["q"] == [{"uid": "0x2"}]


def test_parser_fuzz_no_crashes():
    import random

    from dgraph_tpu.dql.parser import ParseError, parse

    rng = random.Random(0)
    corpus = '{}()@:,"abcfunc eq uid name <x> 0x1 12 /re/ * - . ~f $v as val'
    pieces = corpus.split(" ") + list('{}()@:,"*-.')
    for _ in range(800):
        q = " ".join(rng.choice(pieces) for _ in range(rng.randint(1, 30)))
        try:
            parse(q)
        except ParseError:
            pass  # the only acceptable failure mode
        except RecursionError:
            pass  # deeply nested parens; acceptable guard


def test_dql_query_variables():
    s = _server()
    res = s.query(
        'query people($n: string, $min: int = 20) '
        "{ q(func: eq(name, $n)) @filter(ge(age, $min)) { name age } }",
        variables={"$n": "Alice"},
    )["data"]
    assert res["q"] == [{"name": "Alice", "age": 30}]
    # default value used
    res = s.query(
        'query v($lim: int = 1) { q(func: has(age), first: $lim) { uid } }'
    )["data"]
    assert len(res["q"]) == 1
    # missing required variable
    from dgraph_tpu.dql.parser import ParseError

    with pytest.raises(ParseError):
        s.query('query q($x: string) { q(func: eq(name, $x)) { uid } }')
    # type mismatch
    with pytest.raises(ParseError):
        s.query(
            'query q($x: int) { q(func: ge(age, $x)) { uid } }',
            variables={"$x": "notanint"},
        )


def test_query_vars_in_uid_depth_and_negative_default():
    s = _server()
    res = s.query(
        "query q($u: uid) { q(func: uid($u)) { name } }",
        variables={"$u": "0x1"},
    )["data"]
    assert res["q"] == [{"name": "Alice"}]
    res = s.query(
        "query q($d: int = -1) { q(func: has(age), first: $d) { uid } }"
    )["data"]
    assert len(res["q"]) == 1  # first: -1 = last one
    from dgraph_tpu.dql.parser import ParseError

    with pytest.raises(ParseError):
        s.query("query q($x: in) { q(func: ge(age, $x)) { uid } }",
                variables={"$x": "5"})


def test_upsert_cond_combinators():
    """@if with AND/OR/NOT + parens (ref conditional upsert semantics)."""
    from dgraph_tpu.api.server import _eval_cond

    uv = {"a": [1, 2], "b": []}
    assert _eval_cond("@if(eq(len(a), 2))", uv)
    assert _eval_cond("@if(eq(len(a), 2) AND eq(len(b), 0))", uv)
    assert not _eval_cond("@if(eq(len(a), 2) AND gt(len(b), 0))", uv)
    assert _eval_cond("@if(eq(len(a), 9) OR eq(len(b), 0))", uv)
    assert _eval_cond("@if(NOT eq(len(a), 9))", uv)
    assert _eval_cond("@if((eq(len(a), 9) OR eq(len(b), 0)) AND ge(len(a), 1))", uv)
    import pytest as _pytest

    with _pytest.raises(ValueError):
        _eval_cond("@if(bogus)", uv)


def test_upsert_cond_engine_path():
    from dgraph_tpu.api.server import Server

    s = Server()
    s.alter("email: string @index(exact) @upsert .\nname: string @index(exact) .")
    t = s.new_txn()
    # create only if absent AND the name isn't taken
    t.upsert(
        '{ u as var(func: eq(email, "a@x.io")) \n n as var(func: eq(name, "taken")) }',
        set_rdf='_:new <email> "a@x.io" .\n_:new <name> "fresh" .',
        cond="@if(eq(len(u), 0) AND eq(len(n), 0))",
    )
    out = s.query('{ q(func: eq(email, "a@x.io")) { name } }')
    assert out["data"]["q"][0]["name"] == "fresh"
    # second run: condition false, nothing added
    t2 = s.new_txn()
    t2.upsert(
        '{ u as var(func: eq(email, "a@x.io")) \n n as var(func: eq(name, "taken")) }',
        set_rdf='_:new <email> "a@x.io" .\n_:new <name> "dupe" .',
        cond="@if(eq(len(u), 0) AND eq(len(n), 0))",
    )
    out = s.query('{ q(func: eq(email, "a@x.io")) { name } }')
    assert len(out["data"]["q"]) == 1


def test_geo_contains_and_intersects():
    """contains(point-in-polygon) + intersects(polygon-polygon) over the
    quadtree geo index (ref types/geofilter.go QueryTypeContains/
    Intersects)."""
    import json

    from dgraph_tpu.api.server import Server

    s = Server()
    s.alter("area: geo @index(geo) .\nname: string @index(exact) .")
    square = {
        "type": "Polygon",
        "coordinates": [[[0, 0], [10, 0], [10, 10], [0, 10], [0, 0]]],
    }
    far = {
        "type": "Polygon",
        "coordinates": [[[50, 50], [60, 50], [60, 60], [50, 60], [50, 50]]],
    }
    pt = {"type": "Point", "coordinates": [5, 5]}
    t = s.new_txn()
    t.mutate_json(
        set_obj=[
            {"uid": "0x1", "name": "square", "area": json.dumps(square)},
            {"uid": "0x2", "name": "far", "area": json.dumps(far)},
            {"uid": "0x3", "name": "pt", "area": json.dumps(pt)},
        ],
        commit_now=True,
    )
    # the square (not 'far') contains (5,5)
    out = s.query("{ q(func: contains(area, [5.0, 5.0])) { name } }")
    assert [x["name"] for x in out["data"]["q"]] == ["square"]
    # a polygon overlapping the square intersects it and the inner point
    out = s.query(
        "{ q(func: intersects(area, [[[4.0,4.0],[12.0,4.0],[12.0,6.0],[4.0,6.0],[4.0,4.0]]])) { name } }"
    )
    assert sorted(x["name"] for x in out["data"]["q"]) == ["pt", "square"]
    # a disjoint polygon matches nothing
    out = s.query(
        "{ q(func: intersects(area, [[[80.0,80.0],[85.0,80.0],[85.0,85.0],[80.0,85.0],[80.0,80.0]]])) { name } }"
    )
    assert out["data"]["q"] == []


def test_groupby_aggregations_and_var():
    """@groupby with min/max/avg aggregates + the groupby-var pattern
    (x as count(uid) keyed by the grouped uid; ref query/groupby.go)."""
    from dgraph_tpu.api.server import Server

    s = Server()
    s.alter(
        "name: string @index(exact) .\nage: int .\nlives_in: uid .\n"
        "follows: [uid] ."
    )
    t = s.new_txn()
    rdf = ['<0x100> <name> "cityA" .', '<0x101> <name> "cityB" .']
    ages = {1: 20, 2: 30, 3: 40, 4: 50}
    city = {1: 0x100, 2: 0x100, 3: 0x101, 4: 0x101}
    rdf.append('<0x10> <name> "root" .')
    for u, a in ages.items():
        rdf.append(f'<0x{u:x}> <age> "{a}"^^<xs:int> .')
        rdf.append(f"<0x{u:x}> <lives_in> <0x{city[u]:x}> .")
        rdf.append(f"<0x10> <follows> <0x{u:x}> .")
    t.mutate_rdf(set_rdf="\n".join(rdf), commit_now=True)

    out = s.query(
        """{
          q(func: eq(name, "root")) {
            follows @groupby(lives_in) {
              count(uid)
              min(age)
              m: max(age)
              avg(age)
            }
          }
        }"""
    )
    groups = out["data"]["q"][0]["follows"][0]["@groupby"]
    by_city = {g["lives_in"]: g for g in groups}
    a = by_city["0x100"]
    assert a["count"] == 2 and a["min(age)"] == 20 and a["m"] == 30
    assert a["avg(age)"] == 25.0
    b = by_city["0x101"]
    assert b["count"] == 2 and b["min(age)"] == 40

    # groupby-var: per-city follower counts usable in a later block
    out = s.query(
        """{
          var(func: eq(name, "root")) {
            follows @groupby(lives_in) { c as count(uid) }
          }
          cities(func: uid(c), orderdesc: val(c)) { name total: val(c) }
        }"""
    )
    cities = out["data"]["cities"]
    assert {x["name"] for x in cities} == {"cityA", "cityB"}
    assert all(x["total"] == 2 for x in cities)


def test_root_groupby_with_pagination_matches_slow_path():
    """ADVICE r2 (medium): `has(X), first: N @groupby(X)` must apply root
    pagination before grouping (the reverse-index fast path would bucket
    the whole tablet)."""
    from dgraph_tpu.api.server import Server

    s = Server()
    s.alter("follows: [uid] @reverse .\nname: string .")
    t = s.new_txn()
    rdf = []
    # 6 followers: 4 follow 0x64, 2 follow 0x65
    for i, tgt in enumerate([0x64, 0x64, 0x64, 0x64, 0x65, 0x65]):
        rdf.append(f"<0x{i+1:x}> <follows> <0x{tgt:x}> .")
    t.mutate_rdf(set_rdf="\n".join(rdf), commit_now=True)

    full = s.query(
        "{ q(func: has(follows)) @groupby(follows) { count(uid) } }"
    )["data"]["q"][0]["@groupby"]
    assert sorted(g["count"] for g in full) == [2, 4]

    # first:2 takes the two lowest-uid followers (both follow 0x64)
    paged = s.query(
        "{ q(func: has(follows), first: 2) @groupby(follows) { count(uid) } }"
    )["data"]["q"][0]["@groupby"]
    assert [g["count"] for g in paged] == [2]

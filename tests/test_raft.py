"""Raft tests: election, replication, failover, partitions, log repair.

Correctness scenarios modeled on the jepsen workloads the reference uses
(/root/reference/contrib/jepsen) run against the in-proc network with a
virtual clock (deterministic — no sleeps)."""

from dgraph_tpu.raft.raft import LEADER, RaftCluster


def test_single_node_becomes_leader():
    c = RaftCluster(1)
    leader = c.elect()
    assert leader.id == 1


def test_election_three_nodes():
    c = RaftCluster(3)
    leader = c.elect()
    others = [n for n in c.nodes.values() if n.id != leader.id]
    # followers learn the leader from the first heartbeat
    assert c.run_until(
        lambda: all(n.leader_id == leader.id for n in others)
    )
    assert all(n.state != LEADER for n in others)


def test_replication_and_apply():
    c = RaftCluster(3)
    leader = c.elect()
    for i in range(5):
        assert leader.propose({"op": i})
    assert c.run_until(
        lambda: all(len(c.applied[i]) == 5 for i in c.nodes)
    )
    for i in c.nodes:
        assert [d["op"] for d in c.applied[i]] == [0, 1, 2, 3, 4]


def test_leader_failover_preserves_committed():
    c = RaftCluster(3)
    leader = c.elect()
    leader.propose("a")
    leader.propose("b")
    assert c.run_until(lambda: all(len(c.applied[i]) == 2 for i in c.nodes))
    # kill the leader
    c.net.down.add(leader.id)
    assert c.run_until(
        lambda: c.leader() is not None and c.leader().id != leader.id
    )
    new_leader = c.leader()
    new_leader.propose("c")
    alive = [i for i in c.nodes if i != leader.id]
    assert c.run_until(lambda: all(len(c.applied[i]) == 3 for i in alive))
    for i in alive:
        assert c.applied[i] == ["a", "b", "c"]


def test_minority_partition_cannot_commit():
    c = RaftCluster(3)
    leader = c.elect()
    others = [i for i in c.nodes if i != leader.id]
    # isolate the leader from both followers
    for o in others:
        c.net.partition(leader.id, o)
    leader.propose("lost")
    c.pump(10, 100)
    assert all(len(c.applied[i]) == 0 for i in c.nodes)
    # majority side elects a new leader and commits
    assert c.run_until(
        lambda: any(
            c.nodes[i].state == LEADER and c.nodes[i].term > leader.term
            for i in others
        )
    )
    new_leader = next(c.nodes[i] for i in others if c.nodes[i].state == LEADER)
    new_leader.propose("won")
    assert c.run_until(lambda: all(len(c.applied[i]) == 1 for i in others))
    # heal: old leader rejoins, uncommitted entry overwritten
    c.net.heal()
    assert c.run_until(lambda: len(c.applied[leader.id]) == 1)
    assert c.applied[leader.id] == ["won"]


def test_follower_catch_up_after_downtime():
    c = RaftCluster(3)
    leader = c.elect()
    victim = next(i for i in c.nodes if i != leader.id)
    c.net.down.add(victim)
    for i in range(10):
        leader.propose(i)
    alive = [i for i in c.nodes if i != victim]
    assert c.run_until(lambda: all(len(c.applied[i]) == 10 for i in alive))
    c.net.down.discard(victim)
    assert c.run_until(lambda: len(c.applied[victim]) == 10)
    assert c.applied[victim] == list(range(10))


def test_five_node_majority():
    c = RaftCluster(5)
    leader = c.elect()
    # two nodes down: still a majority
    downs = [i for i in c.nodes if i != leader.id][:2]
    for d in downs:
        c.net.down.add(d)
    leader.propose("x")
    alive = [i for i in c.nodes if i not in downs]
    assert c.run_until(lambda: all(len(c.applied[i]) == 1 for i in alive))


def test_raft_over_tcp_sockets():
    """3 nodes on real localhost sockets (each with its own endpoint, as
    separate processes would be) elect a leader and replicate."""
    import threading
    import time as _time

    from dgraph_tpu.raft.raft import RaftNode
    from dgraph_tpu.raft.tcp import TcpNetwork

    # reserve three ports
    import socket as _socket

    ports = []
    socks = []
    for _ in range(3):
        s = _socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    peers = {i + 1: ("127.0.0.1", ports[i]) for i in range(3)}

    nets, nodes, applied = [], {}, {1: [], 2: [], 3: []}
    for nid in (1, 2, 3):
        net = TcpNetwork(dict(peers))
        net.register(nid)
        nets.append(net)
        nodes[nid] = RaftNode(
            nid, [1, 2, 3], net,
            lambda idx, d, _n=nid: applied[_n].append(d), seed=nid,
        )

    stop = threading.Event()

    def tick_loop(node):
        now = 0
        while not stop.is_set():
            now += 50
            node.tick(now)
            _time.sleep(0.005)

    threads = [
        threading.Thread(target=tick_loop, args=(n,), daemon=True)
        for n in nodes.values()
    ]
    for t in threads:
        t.start()
    try:
        deadline = _time.time() + 15
        leader = None
        while _time.time() < deadline:
            leaders = [n for n in nodes.values() if n.is_leader()]
            if leaders:
                leader = max(leaders, key=lambda n: n.term)
                break
            _time.sleep(0.02)
        assert leader is not None, "no leader elected over TCP"
        for i in range(3):
            assert leader.propose({"n": i})
        deadline = _time.time() + 10
        while _time.time() < deadline:
            if all(len(applied[i]) == 3 for i in applied):
                break
            _time.sleep(0.02)
        assert all(
            [d["n"] for d in applied[i]] == [0, 1, 2] for i in applied
        ), applied
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=1)
        for net in nets:
            net.close()


def test_learner_replicates_but_never_votes_or_leads():
    """Non-voting learners (ref etcd raft learners): replicate + apply,
    excluded from quorum — commits proceed with a majority of VOTERS even
    when every learner is down."""
    from dgraph_tpu.raft.raft import RaftCluster

    c = RaftCluster(4, learner_ids={4})
    c.nodes[4].learner = True
    leader = c.elect()
    assert leader.id != 4
    assert leader.propose({"op": 1})
    assert c.run_until(lambda: all(len(c.applied[i]) == 1 for i in c.nodes))
    # kill the learner: quorum is 2/3 voters, commits continue
    c.net.down.add(4)
    assert leader.propose({"op": 2})
    assert c.run_until(
        lambda: all(len(c.applied[i]) == 2 for i in (1, 2, 3))
    )
    # kill one VOTER too (2/3 voters remain = still majority)
    dead_voter = next(i for i in (1, 2, 3) if i != leader.id)
    c.net.down.add(dead_voter)
    assert c.run_until(lambda: c.leader() is not None)
    lead2 = c.leader()
    assert lead2.propose({"op": 3})
    live_voter = next(i for i in (1, 2, 3) if i not in (dead_voter,))
    assert c.run_until(lambda: len(c.applied[lead2.id]) == 3)
    # learner rejoins and catches up without ever voting
    c.net.down.discard(4)
    assert c.run_until(lambda: len(c.applied[4]) == 3)
    assert c.nodes[4].state == "follower"


def test_cluster_learners_serve_reads():
    from dgraph_tpu.worker.groups import DistributedCluster

    c = DistributedCluster(n_groups=1, replicas=3, learners_per_group=1)
    try:
        c.alter("name: string @index(exact) .")
        c.new_txn().mutate_rdf(set_rdf='<0x1> <name> "lr" .', commit_now=True)
        learner = c.groups[1].nodes[-1]
        assert learner.raft.learner
        # the learner applied the committed delta and can serve the read
        import time

        deadline = time.time() + 5
        while time.time() < deadline:
            got = learner.kv.get(
                __import__("dgraph_tpu.x.keys", fromlist=["DataKey"]).DataKey(
                    "name", 1
                ),
                1 << 60,
            )
            if got is not None:
                break
            time.sleep(0.05)
        assert got is not None
        out = c.query('{ q(func: eq(name, "lr")) { name } }')
        assert out["data"]["q"][0]["name"] == "lr"
    finally:
        c.close()

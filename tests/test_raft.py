"""Raft tests: election, replication, failover, partitions, log repair.

Correctness scenarios modeled on the jepsen workloads the reference uses
(/root/reference/contrib/jepsen) run against the in-proc network with a
virtual clock (deterministic — no sleeps)."""

from dgraph_tpu.raft.raft import LEADER, RaftCluster


def test_single_node_becomes_leader():
    c = RaftCluster(1)
    leader = c.elect()
    assert leader.id == 1


def test_election_three_nodes():
    c = RaftCluster(3)
    leader = c.elect()
    others = [n for n in c.nodes.values() if n.id != leader.id]
    # followers learn the leader from the first heartbeat
    assert c.run_until(
        lambda: all(n.leader_id == leader.id for n in others)
    )
    assert all(n.state != LEADER for n in others)


def test_replication_and_apply():
    c = RaftCluster(3)
    leader = c.elect()
    for i in range(5):
        assert leader.propose({"op": i})
    assert c.run_until(
        lambda: all(len(c.applied[i]) == 5 for i in c.nodes)
    )
    for i in c.nodes:
        assert [d["op"] for d in c.applied[i]] == [0, 1, 2, 3, 4]


def test_leader_failover_preserves_committed():
    c = RaftCluster(3)
    leader = c.elect()
    leader.propose("a")
    leader.propose("b")
    assert c.run_until(lambda: all(len(c.applied[i]) == 2 for i in c.nodes))
    # kill the leader
    c.net.down.add(leader.id)
    assert c.run_until(
        lambda: c.leader() is not None and c.leader().id != leader.id
    )
    new_leader = c.leader()
    new_leader.propose("c")
    alive = [i for i in c.nodes if i != leader.id]
    assert c.run_until(lambda: all(len(c.applied[i]) == 3 for i in alive))
    for i in alive:
        assert c.applied[i] == ["a", "b", "c"]


def test_minority_partition_cannot_commit():
    c = RaftCluster(3)
    leader = c.elect()
    others = [i for i in c.nodes if i != leader.id]
    # isolate the leader from both followers
    for o in others:
        c.net.partition(leader.id, o)
    leader.propose("lost")
    c.pump(10, 100)
    assert all(len(c.applied[i]) == 0 for i in c.nodes)
    # majority side elects a new leader and commits
    assert c.run_until(
        lambda: any(
            c.nodes[i].state == LEADER and c.nodes[i].term > leader.term
            for i in others
        )
    )
    new_leader = next(c.nodes[i] for i in others if c.nodes[i].state == LEADER)
    new_leader.propose("won")
    assert c.run_until(lambda: all(len(c.applied[i]) == 1 for i in others))
    # heal: old leader rejoins, uncommitted entry overwritten
    c.net.heal()
    assert c.run_until(lambda: len(c.applied[leader.id]) == 1)
    assert c.applied[leader.id] == ["won"]


def test_follower_catch_up_after_downtime():
    c = RaftCluster(3)
    leader = c.elect()
    victim = next(i for i in c.nodes if i != leader.id)
    c.net.down.add(victim)
    for i in range(10):
        leader.propose(i)
    alive = [i for i in c.nodes if i != victim]
    assert c.run_until(lambda: all(len(c.applied[i]) == 10 for i in alive))
    c.net.down.discard(victim)
    assert c.run_until(lambda: len(c.applied[victim]) == 10)
    assert c.applied[victim] == list(range(10))


def test_five_node_majority():
    c = RaftCluster(5)
    leader = c.elect()
    # two nodes down: still a majority
    downs = [i for i in c.nodes if i != leader.id][:2]
    for d in downs:
        c.net.down.add(d)
    leader.propose("x")
    alive = [i for i in c.nodes if i not in downs]
    assert c.run_until(lambda: all(len(c.applied[i]) == 1 for i in alive))

"""RDF response encoding (ref query/outputrdf.go ToRDF; resp_format=RDF)."""

import json

import pytest

from dgraph_tpu.api.server import Server


@pytest.fixture(scope="module")
def server():
    s = Server()
    s.alter(
        "name: string @index(exact) .\nfriend: [uid] .\nage: int .\n"
        "alive: bool ."
    )
    t = s.new_txn()
    t.mutate_rdf(
        set_rdf=(
            '<0x1> <name> "Alice" .\n'
            '<0x1> <age> "30"^^<xs:int> .\n'
            '<0x1> <alive> "true"^^<xs:boolean> .\n'
            "<0x1> <friend> <0x2> .\n"
            '<0x2> <name> "Bob" .'
        ),
        commit_now=True,
    )
    return s


def test_query_rdf_triples(server):
    rdf = server.query_rdf(
        '{ q(func: eq(name, "Alice")) { name age alive friend { name } } }'
    )
    lines = set(rdf.strip().splitlines())
    assert '<0x1> <name> "Alice" .' in lines
    assert '<0x1> <age> "30"^^<xs:int> .' in lines
    assert '<0x1> <alive> "true"^^<xs:boolean> .' in lines
    assert "<0x1> <friend> <0x2> ." in lines
    assert '<0x2> <name> "Bob" .' in lines


def test_rdf_round_trips_through_loader(server):
    rdf = server.query_rdf(
        '{ q(func: eq(name, "Alice")) { name age friend { name } } }'
    )
    s2 = Server()
    s2.alter("name: string @index(exact) .\nfriend: [uid] .\nage: int .")
    s2.new_txn().mutate_rdf(set_rdf=rdf, commit_now=True)
    out = s2.query('{ q(func: eq(name, "Alice")) { age friend { name } } }')
    q = out["data"]["q"][0]
    assert q["age"] == 30 and q["friend"][0]["name"] == "Bob"


def test_rdf_shares_json_value_formats(server):
    """RDF literals come from the SAME valuefmt formatters the JSON
    encoders use — pin the golden forms so the copies can't drift
    again (before valuefmt, RDF printed naive datetimes without the Z
    suffix the JSON path emits, so an exported result re-imported with
    a shifted zone)."""
    t = server.new_txn()
    t.mutate_rdf(
        set_rdf=(
            '<0x7> <name> "Tick" .\n'
            '<0x7> <when> "1980-05-01T10:30:00Z"^^<xs:dateTime> .\n'
            '<0x7> <score> "2.5"^^<xs:float> .'
        ),
        commit_now=True,
    )
    rdf = server.query_rdf(
        '{ q(func: eq(name, "Tick")) { name when score } }'
    )
    lines = set(rdf.strip().splitlines())
    # naive-stored datetime prints RFC3339 with the Z suffix (JSON form)
    assert '<0x7> <when> "1980-05-01T10:30:00Z"^^<xs:dateTime> .' in lines
    assert '<0x7> <score> "2.5"^^<xs:float> .' in lines
    # and the JSON path emits the identical scalar text
    out = server.query('{ q(func: eq(name, "Tick")) { when score } }')
    assert out["data"].raw is not None
    assert b'"when":"1980-05-01T10:30:00Z"' in out["data"].raw
    assert b'"score":2.5' in out["data"].raw


def test_grpc_resp_format_rdf(server):
    from dgraph_tpu.api.grpc_server import pb, serve

    import grpc

    gs, port = serve(server)
    try:
        ch = grpc.insecure_channel(f"127.0.0.1:{port}")
        q = ch.unary_unary(
            "/api.Dgraph/Query",
            request_serializer=pb.Request.SerializeToString,
            response_deserializer=pb.Response.FromString,
        )
        resp = q(
            pb.Request(
                query='{ q(func: eq(name, "Alice")) { name } }',
                resp_format=pb.Request.RDF,
                read_only=True,
            )
        )
        assert b'<0x1> <name> "Alice" .' in resp.rdf
        assert not resp.json
    finally:
        gs.stop(0)


def test_http_resp_format_rdf(server):
    from dgraph_tpu.api.http_server import HTTPServer
    import urllib.request

    srv = HTTPServer(server, port=0).start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/query?respFormat=rdf",
            data=b'{ q(func: eq(name, "Alice")) { name } }',
            method="POST",
        )
        with urllib.request.urlopen(req) as r:
            body = r.read()
            assert r.headers["Content-Type"] == "application/n-quads"
        assert b'<0x1> <name> "Alice" .' in body
    finally:
        srv.stop()

"""Operator tools: cert/conv/migrate/debuginfo/upgrade (ref
dgraph/cmd/{cert,conv,migrate,debuginfo}, upgrade/upgrade.go).
"""

import json
import os

import pytest

from dgraph_tpu import tools


def test_cert_create_and_ls(tmp_path):
    d = str(tmp_path / "tls")
    made = tools.cert_create(d, nodes=["localhost"], client="alice")
    assert os.path.exists(os.path.join(d, "ca.crt"))
    assert os.path.exists(os.path.join(d, "node.crt"))
    assert os.path.exists(os.path.join(d, "client.alice.crt"))
    rows = tools.cert_ls(d)
    names = {r["file"] for r in rows}
    assert {"ca.crt", "node.crt", "client.alice.crt"} <= names
    assert any("dgraph-tpu CA" in r["info"] for r in rows)


def test_conv_geojson(tmp_path):
    p = tmp_path / "g.json"
    p.write_text(
        json.dumps(
            {
                "type": "FeatureCollection",
                "features": [
                    {
                        "geometry": {"type": "Point", "coordinates": [1, 2]},
                        "properties": {"name": "spot", "pop": 7},
                    }
                ],
            }
        )
    )
    rdf = tools.conv_geojson(str(p))
    assert any("<loc>" in line for line in rdf)
    assert any('<name> "spot"' in line for line in rdf)


def test_migrate_csv_roundtrip(tmp_path):
    users = tmp_path / "users.csv"
    users.write_text("id,name,age\n1,ann,30\n2,ben,25\n")
    orders = tmp_path / "orders.csv"
    orders.write_text("id,user_id,total\n10,1,99.5\n11,2,12.0\n")
    schema, rdf = tools.migrate_csv(
        {"users": str(users), "orders": str(orders)},
        fk={("orders", "user_id"): "users"},
    )
    assert "users.age: int @index(int) ." in schema
    assert "orders.user_id: [uid] ." in schema
    assert any("_:orders.10 <orders.user_id> _:users.1 ." == l for l in rdf)

    # the output loads into the engine and joins across the FK
    from dgraph_tpu.api.server import Server

    s = Server()
    s.alter(schema + "\ndgraph.type: [string] @index(exact) .")
    t = s.new_txn()
    t.mutate_rdf(set_rdf="\n".join(rdf), commit_now=True)
    out = s.query(
        '{ q(func: eq(users.name, "ann")) { users.name } }'
    )
    assert out["data"]["q"][0]["users.name"] == "ann"


def test_debuginfo_bundle(tmp_path):
    from dgraph_tpu.api.server import Server

    s = Server()
    s.alter("name: string .")
    bundle = tools.debuginfo(s, str(tmp_path))
    files = set(os.listdir(bundle))
    assert {
        "metrics.prom", "traces.json", "state.json", "schema.txt",
        "goroutines.txt",
    } <= files
    state = json.loads(open(os.path.join(bundle, "state.json")).read())
    assert "name" in state["predicates"]


def test_upgrade_layout(tmp_path):
    d = str(tmp_path / "p")
    os.makedirs(d)
    assert tools.layout_version(d) == 1
    applied = tools.upgrade(d)
    assert applied == [2]
    assert tools.layout_version(d) == tools.LAYOUT_VERSION
    assert tools.upgrade(d) == []  # idempotent

"""GraphQL @lambda / @lambdaOnMutate / websocket subscriptions
(ref graphql/schema/gqlschema.go:291-292 directives, resolve/webhook.go
payload shape, graphql/subscription/poller.go transport).
"""

import base64
import json
import os
import socket
import struct
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from dgraph_tpu.api.server import Server
from dgraph_tpu.graphql.resolve import GraphQLServer

RECEIVED = []


class _Lambda(BaseHTTPRequestHandler):
    """Stub lambda server: resolves by `resolver` key like dgraph-lambda."""

    def log_message(self, *a):
        pass

    def do_POST(self):
        n = int(self.headers.get("Content-Length", 0))
        body = json.loads(self.rfile.read(n))
        RECEIVED.append(body)
        res = body.get("resolver")
        if res == "Query.greet":
            out = f"hello {body['args']['name']}"
        elif res == "Person.fullName":
            out = [
                f"{p.get('firstName','')} {p.get('lastName','')}"
                for p in body["parents"]
            ]
        elif res == "$webhook":
            out = None
        else:
            out = None
        data = json.dumps(out).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)


@pytest.fixture(scope="module")
def lambda_port():
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _Lambda)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield srv.server_address[1]
    srv.shutdown()


SDL = """
type Person @lambdaOnMutate(add: true, delete: true) {
  id: ID!
  firstName: String @search(by: [exact])
  lastName: String
  fullName: String @lambda
}
type Query {
  greet(name: String!): String @lambda
}
"""


@pytest.fixture()
def gql(lambda_port):
    RECEIVED.clear()
    return GraphQLServer(
        Server(), SDL, lambda_url=f"http://127.0.0.1:{lambda_port}/graphql-worker"
    )


def test_lambda_query_root(gql):
    out = gql.execute('{ greet(name: "ada") }')
    assert out["data"]["greet"] == "hello ada"
    assert RECEIVED[-1]["resolver"] == "Query.greet"
    assert RECEIVED[-1]["args"] == {"name": "ada"}


def test_lambda_field_batch(gql):
    gql.execute(
        'mutation { addPerson(input: [{firstName: "Ada", lastName: "L"}, '
        '{firstName: "Alan", lastName: "T"}]) { numUids } }'
    )
    out = gql.execute(
        '{ queryPerson(order: {asc: firstName}) { firstName fullName } }'
    )
    rows = out["data"]["queryPerson"]
    assert [r["fullName"] for r in rows] == ["Ada L", "Alan T"]
    # BATCH shape: one POST with all parents incl. unselected scalars
    batch = [r for r in RECEIVED if r.get("resolver") == "Person.fullName"][-1]
    assert [p["lastName"] for p in batch["parents"]] == ["L", "T"]
    # hidden parent-only scalars never leak into the response
    assert all(not k.startswith("__lp_") for r in rows for k in r)


def test_lambda_on_mutate_webhook(gql):
    gql.execute('mutation { addPerson(input: [{firstName: "Eve"}]) { numUids } }')
    deadline = time.time() + 5
    while time.time() < deadline:
        hooks = [r for r in RECEIVED if r.get("resolver") == "$webhook"]
        if hooks:
            break
        time.sleep(0.05)
    assert hooks, "webhook never fired"
    ev = hooks[-1]["event"]
    assert ev["__typename"] == "Person"
    assert ev["operation"] == "add"
    assert ev["add"]["input"][0]["firstName"] == "Eve"
    # update not enabled -> no webhook
    before = len([r for r in RECEIVED if r.get("resolver") == "$webhook"])
    gql.execute(
        'mutation { updatePerson(input: {filter: {firstName: {eq: "Eve"}}, '
        'set: {lastName: "X"}}) { numUids } }'
    )
    time.sleep(0.3)
    after = len([r for r in RECEIVED if r.get("resolver") == "$webhook"])
    assert after == before


# -- websocket subscriptions -------------------------------------------------


def _ws_send(sock, obj):
    payload = json.dumps(obj).encode()
    mask = b"\x01\x02\x03\x04"
    n = len(payload)
    if n < 126:
        hdr = bytes([0x81, 0x80 | n])
    else:
        hdr = bytes([0x81, 0x80 | 126]) + struct.pack(">H", n)
    masked = bytes(c ^ mask[i % 4] for i, c in enumerate(payload))
    sock.sendall(hdr + mask + masked)


def _ws_recv(sock, timeout=10.0):
    sock.settimeout(timeout)

    def rd(n):
        buf = b""
        while len(buf) < n:
            got = sock.recv(n - len(buf))
            if not got:
                raise ConnectionError("closed")
            buf += got
        return buf

    b1, b2 = rd(2)
    ln = b2 & 0x7F
    if ln == 126:
        (ln,) = struct.unpack(">H", rd(2))
    elif ln == 127:
        (ln,) = struct.unpack(">Q", rd(8))
    return json.loads(rd(ln).decode())


def test_websocket_subscription(tmp_path):
    from dgraph_tpu.api.http_server import HTTPServer
    from dgraph_tpu.api.subscriptions import Subscriptions

    engine = Server()
    engine.graphql = GraphQLServer(engine, SDL, lambda_url="")
    Subscriptions(engine)
    srv = HTTPServer(engine, port=0).start()
    port = srv.port
    try:
        s = socket.create_connection(("127.0.0.1", port), timeout=10)
        key = base64.b64encode(os.urandom(16)).decode()
        s.sendall(
            (
                f"GET /graphql HTTP/1.1\r\nHost: 127.0.0.1:{port}\r\n"
                "Upgrade: websocket\r\nConnection: Upgrade\r\n"
                f"Sec-WebSocket-Key: {key}\r\nSec-WebSocket-Version: 13\r\n"
                "Sec-WebSocket-Protocol: graphql-transport-ws\r\n\r\n"
            ).encode()
        )
        # read the 101 response headers
        hdr = b""
        while b"\r\n\r\n" not in hdr:
            hdr += s.recv(1024)
        assert b"101" in hdr.split(b"\r\n", 1)[0]

        _ws_send(s, {"type": "connection_init"})
        assert _ws_recv(s)["type"] == "connection_ack"
        _ws_send(
            s,
            {
                "id": "1",
                "type": "subscribe",
                "payload": {
                    "query": "subscription { queryPerson { firstName } }"
                },
            },
        )
        first = _ws_recv(s)
        assert first["type"] == "next"
        assert first["payload"]["data"]["queryPerson"] == []

        # a mutation through the engine pushes an update frame
        engine.graphql.execute(
            'mutation { addPerson(input: [{firstName: "Zed"}]) { numUids } }'
        )
        nxt = _ws_recv(s)
        assert nxt["type"] == "next"
        assert nxt["payload"]["data"]["queryPerson"] == [{"firstName": "Zed"}]

        _ws_send(s, {"id": "1", "type": "complete"})
        s.close()
    finally:
        srv.stop()

"""Chaos suite: deterministic fault injection against the cluster stack.

Unit layer: FaultPlan determinism (same seed => same per-stream fault
sequence, byte-for-byte), RetryPolicy backoff/jitter/deadline math, the
RpcClient timeout-restore and frame-size-cap satellites, idempotency-key
dedup (no double-apply across reconnect-and-resend), the per-peer
circuit breaker, and hedged-read loser reaping.

Cluster layer (marked `chaos`): a fixed-seed fault schedule
(drop+delay+disconnect across the Zero quorum and an alpha group) runs
the bank workload on a real multi-process cluster with invariants
checked — balance sum conserved at every snapshot, acked transfers
applied exactly once (ledger-exact), read timestamps never going back in
time — plus the graceful-degradation contract: with one alpha group
fully partitioned, queries over healthy predicates still answer inside
their deadline and queries touching the dead group return a
`degraded`/`partial` response instead of hanging. Long randomized
schedules are additionally marked `slow` (out of tier-1).
"""

import io
import socket
import struct
import threading
import time

import pytest

from dgraph_tpu.conn import faults
from dgraph_tpu.conn.faults import FaultPlan
from dgraph_tpu.conn.frame import MAX_FRAME, FrameError
from dgraph_tpu.conn.messages import HealthInfo
from dgraph_tpu.conn.retry import (
    Deadline,
    RetryPolicy,
    current_deadline,
    deadline_scope,
)
from dgraph_tpu.conn.rpc import (
    PeerDownError,
    RpcClient,
    RpcError,
    RpcPool,
    RpcServer,
    _recv_frame,
)
from dgraph_tpu.utils.observe import METRICS


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _dead_addr():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    addr = s.getsockname()
    s.close()
    return addr


# ---------------------------------------------------------------------------
# FaultPlan determinism
# ---------------------------------------------------------------------------


def test_same_seed_reproduces_fault_sequence_byte_for_byte():
    rules = [
        dict(point="send", action="drop", p=0.2),
        dict(point="send", action="delay", p=0.3, delay_ms=5),
        dict(point="resp", action="drop", p=0.15),
    ]
    runs = []
    for _ in range(2):
        plan = FaultPlan(seed=42, rules=rules)
        seq = []
        for peer in (("a", 1), ("b", 2)):
            for point in ("send", "resp"):
                for _n in range(40):
                    r = plan.decide(point, peer, "m")
                    seq.append(r.action if r is not None else None)
        runs.append((seq, sorted(plan.trace().items())))
    assert runs[0] == runs[1]  # byte-for-byte identical schedules
    assert any(a for a in runs[0][0])  # and faults actually fired
    # replay is a pure function of (seed, stream, n): it reconstructs the
    # live decisions without consuming state
    plan = FaultPlan(seed=42, rules=rules)
    live = [
        (r.action if r is not None else None)
        for _ in range(40)
        for r in (plan.decide("send", ("a", 1), "m"),)
    ]
    assert live == plan.replay("send", ("a", 1), 40, "m")
    assert live == FaultPlan(seed=42, rules=rules).replay(
        "send", ("a", 1), 40, "m"
    )
    # a different seed yields a different schedule
    other = FaultPlan(seed=43, rules=rules).replay("send", ("a", 1), 40, "m")
    assert live != other


def test_fault_plan_streams_are_independent():
    """Interleaving order across streams cannot change a stream's own
    decisions — the determinism guarantee under thread scheduling."""
    rules = [dict(action="drop", p=0.25)]
    a = FaultPlan(seed=7, rules=rules)
    for _ in range(30):
        a.decide("send", "x")
    seq_x_alone = [n_act for n_act in a.trace().get(("send", "x"), [])]
    b = FaultPlan(seed=7, rules=rules)
    for i in range(30):  # interleave with another stream
        b.decide("send", "y")
        b.decide("send", "x")
    assert b.trace().get(("send", "x"), []) == seq_x_alone


def test_env_spec_and_partition(monkeypatch):
    import json

    monkeypatch.setenv(
        faults.ENV_VAR,
        json.dumps(
            {"seed": 5, "rules": [{"action": "drop", "p": 1.0, "max": 2}]}
        ),
    )
    plan = faults.init_from_env(force=True)
    assert plan is not None and plan.seed == 5
    assert plan.decide("send", "p").action == "drop"
    assert plan.decide("send", "p").action == "drop"
    assert plan.decide("send", "p") is None  # max=2 exhausted
    # directional partition blocks deterministically
    plan.partition(("10.0.0.1", 1), direction="to")
    assert plan.decide("send", ("10.0.0.1", 1)).action == "partition"
    assert plan.decide("recv", ("10.0.0.1", 1)) is None  # other direction
    plan.heal()
    assert plan.decide("send", ("10.0.0.1", 1)) is None
    faults.reset()


# ---------------------------------------------------------------------------
# RetryPolicy / Deadline
# ---------------------------------------------------------------------------


def test_retry_policy_full_jitter_and_cap():
    import random

    p = RetryPolicy(base=0.1, mult=2.0, cap=0.5, rng=random.Random(0))
    for attempt in range(1, 10):
        ceiling = min(0.5, 0.1 * 2 ** (attempt - 1))
        for _ in range(50):
            d = p.backoff(attempt)
            assert 0.0 <= d <= ceiling
    assert p.exhausted(3) is False
    assert RetryPolicy(max_attempts=3).exhausted(3) is True


def test_retry_sleep_never_outlives_deadline():
    p = RetryPolicy(base=5.0, cap=10.0)  # huge backoff...
    dl = Deadline.after(0.05)
    t0 = time.perf_counter()
    p.sleep(5, dl)  # ...must be clipped to the deadline
    assert time.perf_counter() - t0 < 0.2


def test_deadline_scope_nests_tighter_only():
    with deadline_scope(Deadline.after(10.0)) as outer:
        with deadline_scope(Deadline.after(99.0)) as inner:
            assert inner.at == outer.at  # cannot extend
        with deadline_scope(Deadline.after(0.5)) as inner2:
            assert inner2.at < outer.at  # may shrink
        assert current_deadline() is outer
    assert current_deadline() is None


# ---------------------------------------------------------------------------
# RPC satellites: timeout restore, frame cap
# ---------------------------------------------------------------------------


def test_per_call_timeout_restored_after_long_deadline_call():
    srv = RpcServer().start()
    try:
        c = RpcClient(srv.addr, timeout=1.5)
        c.call("ping", timeout=60.0)
        # the old code left the 60s timeout on the socket, slowing
        # failure detection for every later call
        assert c._sock.gettimeout() == 1.5
        c.close_conn()
    finally:
        srv.close()


def test_recv_frame_rejects_oversize_length_header():
    with pytest.raises(FrameError):
        _recv_frame(io.BytesIO(struct.Struct(">I").pack(MAX_FRAME + 1)))
    # and a server receiving one drops the connection cleanly
    srv = RpcServer().start()
    try:
        s = socket.create_connection(srv.addr)
        s.sendall(struct.Struct(">I").pack(1 << 31))
        s.settimeout(2.0)
        assert s.recv(64) == b""  # closed, no allocation attempted
        s.close()
        # the server keeps serving other connections
        assert RpcPool(timeout=1.0).call(srv.addr, "ping")["pong"]
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# idempotency: reconnect-and-resend cannot double-apply
# ---------------------------------------------------------------------------


def _counting_server():
    srv = RpcServer().start()
    applied = []

    def apply(a):
        applied.append(a.get("v"))
        return {"applied": len(applied)}

    srv.register("apply", apply)
    return srv, applied


def test_lost_ack_resend_applies_once():
    srv, applied = _counting_server()
    try:
        # the server applies, then the ack is lost — the classic
        # double-apply trap for reconnect-and-resend
        faults.install(
            FaultPlan(
                seed=1,
                rules=[
                    dict(point="resp", method="apply", action="drop",
                         p=1.0, max=2)
                ],
            )
        )
        c = RpcClient(srv.addr, timeout=0.25)
        h0 = METRICS.value("idem_hits_total")
        out = c.call(
            "apply", {"v": 7}, timeout=0.25,
            deadline=Deadline.after(5.0), idem=True,
        )
        assert out["applied"] == 1
        assert applied == [7]  # applied exactly once despite 2 resends
        assert METRICS.value("idem_hits_total") >= h0 + 1
        c.close_conn()
    finally:
        srv.close()


def test_duplicated_request_applies_once():
    srv, applied = _counting_server()
    try:
        faults.install(
            FaultPlan(
                seed=2,
                rules=[
                    dict(point="send", method="apply", action="dup",
                         p=1.0, max=1)
                ],
            )
        )
        c = RpcClient(srv.addr, timeout=1.0)
        out = c.call("apply", {"v": 1}, idem=True)
        assert out["applied"] == 1 and applied == [1]
        # the duplicate's extra response is skipped as stale by the
        # NEXT call on the same connection
        assert c.call("apply", {"v": 2}, idem=True)["applied"] == 2
        assert applied == [1, 2]
        c.close_conn()
    finally:
        srv.close()


def test_non_idem_call_still_works_plain():
    srv, applied = _counting_server()
    try:
        c = RpcClient(srv.addr)
        assert c.call("apply", {"v": 5})["applied"] == 1
        c.close_conn()
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------


def test_circuit_opens_then_fails_fast_then_halfopen_recovers():
    addr = _dead_addr()
    pool = RpcPool(timeout=0.3, heartbeat_s=0.4, max_misses=2)
    try:
        for _ in range(2):
            with pytest.raises(RpcError):
                pool.call(addr, "ping", timeout=0.2)
        assert not pool.healthy(addr)
        t0 = time.perf_counter()
        with pytest.raises(PeerDownError):
            pool.call(addr, "ping")
        assert time.perf_counter() - t0 < 0.05  # no connect/timeout cost
        # peer comes back: the next half-open probe closes the circuit
        srv = RpcServer(host=addr[0], port=addr[1]).start()
        try:
            time.sleep(0.45)
            assert pool.call(addr, "ping")["pong"]
            assert pool.healthy(addr)
        finally:
            srv.close()
    finally:
        pool.close()


# ---------------------------------------------------------------------------
# hedged reads
# ---------------------------------------------------------------------------


def test_hedge_backup_wins_and_loser_is_reaped():
    from dgraph_tpu.worker.remote import RemoteGroup

    slow = RpcServer().start()
    fast = RpcServer().start()
    slow.register(
        "health",
        lambda a: HealthInfo(ok=True, is_leader=True, node=1, group=1),
    )
    fast.register(
        "health",
        lambda a: HealthInfo(ok=True, is_leader=False, node=2, group=1),
    )

    def slow_get(a):
        time.sleep(0.5)
        return {"who": "slow"}

    slow.register("kv.get", slow_get)
    fast.register("kv.get", lambda a: {"who": "fast"})
    pool = RpcPool(timeout=2.0)
    try:
        g = RemoteGroup(1, [slow.addr, fast.addr], pool)
        w0 = METRICS.value("hedge_wins")
        out = g.read("kv.get", {}, hedge_after=0.05)
        assert out["who"] == "fast"  # the backup answered first
        assert METRICS.value("hedge_wins") >= w0 + 1
        j0 = METRICS.value("hedge_losses_joined")
        time.sleep(0.6)  # the slow loser finishes and is reaped
        assert METRICS.value("hedge_losses_joined") >= j0 + 1
    finally:
        pool.close()
        slow.close()
        fast.close()


def test_propose_respects_ambient_deadline_not_layer_default():
    """A down group must fail within the caller's stamped deadline, not
    the old hardwired 15s proposal budget."""
    from dgraph_tpu.worker.remote import RemoteGroup

    pool = RpcPool(timeout=0.3, max_misses=2)
    try:
        g = RemoteGroup(1, [_dead_addr(), _dead_addr()], pool)
        t0 = time.perf_counter()
        with deadline_scope(Deadline.after(0.8)):
            with pytest.raises((RpcError, TimeoutError)):
                g.propose(("delta", []))
        assert time.perf_counter() - t0 < 4.0
    finally:
        pool.close()


# ---------------------------------------------------------------------------
# cluster chaos (fixed-seed smoke — tier-1)
# ---------------------------------------------------------------------------

N_ACCOUNTS = 8
START_BAL = 100


def _seed_bank(c):
    c.alter("bal: int @upsert .\nacct: string @index(exact) @upsert .")
    rdf = []
    for i in range(1, N_ACCOUNTS + 1):
        rdf.append(f'<0x{i:x}> <acct> "a{i}" .')
        rdf.append(f'<0x{i:x}> <bal> "{START_BAL}"^^<xs:int> .')
    c.new_txn().mutate_rdf(set_rdf="\n".join(rdf), commit_now=True)


def _balances(c):
    out = c.query("{ q(func: has(bal)) { uid bal } }")
    # extensions now always carry server_latency/profile; degradation is
    # signalled by the `degraded` marker, not by extensions' presence
    assert not out["extensions"].get("degraded"), out["extensions"]
    return {int(x["uid"], 16): x["bal"] for x in out["data"]["q"]}


@pytest.mark.chaos
def test_chaos_bank_fixed_seed_smoke():
    """Seeded drop+delay+disconnect across the Zero quorum and an alpha
    group: balance sum conserved, acked transfers applied exactly once,
    read timestamps monotonic, schedule reproducible from the seed."""
    import numpy as np

    from dgraph_tpu.worker.harness import ProcCluster

    c = ProcCluster(
        n_groups=1, replicas=3, replicated_zero=True, zero_replicas=3
    )
    plan = None
    try:
        _seed_bank(c)
        plan = faults.install(
            FaultPlan(
                seed=1234,
                rules=[
                    dict(point="send", action="drop", p=0.05),
                    dict(point="send", action="delay", p=0.12, delay_ms=5),
                    dict(point="send", action="disconnect", p=0.03),
                ],
            )
        )
        rng = np.random.default_rng(0)
        ledger = {i: START_BAL for i in range(1, N_ACCOUNTS + 1)}
        ambiguous = 0
        last_ts = 0
        for step in range(10):
            frm, to = (
                int(x) + 1 for x in rng.choice(N_ACCOUNTS, 2, replace=False)
            )
            amt = int(rng.integers(1, 20))
            t = c.new_txn()
            try:
                t.mutate_rdf(
                    set_rdf=(
                        f'<0x{frm:x}> <bal> "{ledger[frm] - amt}"'
                        f"^^<xs:int> .\n"
                        f'<0x{to:x}> <bal> "{ledger[to] + amt}"^^<xs:int> .'
                    ),
                    commit_now=True,
                )
                ledger[frm] -= amt
                ledger[to] += amt
            except TimeoutError:
                ambiguous += 1  # may or may not have applied
            ts = c.zero.zero.read_ts()
            assert ts > last_ts, "linearizable reads went back in time"
            last_ts = ts
            if step % 3 == 0:
                bals = _balances(c)
                assert sum(bals.values()) == N_ACCOUNTS * START_BAL, bals
        faults.reset()
        bals = _balances(c)
        assert sum(bals.values()) == N_ACCOUNTS * START_BAL
        if ambiguous == 0:
            # every acked transfer applied exactly once — a duplicated
            # proposal would shift two accounts off the ledger
            assert bals == ledger
        # the schedule hit RPC streams and is reproducible from the seed
        trace = plan.trace()
        counts = plan.counts()
        assert sum(len(v) for v in trace.values()) >= 3
        zero_peers = {f"{h}:{p}" for h, p in c.zero.zero.addrs}
        alpha_peers = {
            f"{h}:{p}" for h, p in c.remote_groups[1].addrs
        }
        consulted = {peer for (_pt, peer) in counts}
        assert consulted & zero_peers and consulted & alpha_peers
        replayed = {
            stream: [
                (n, act)
                for n, act in enumerate(
                    plan.replay(stream[0], stream[1], counts[stream]), 1
                )
                if act is not None
            ]
            for stream in trace
        }
        # partitions are runtime state, not seeded draws; none were used
        assert replayed == trace
    finally:
        faults.reset()
        c.close()


@pytest.mark.chaos
def test_partitioned_group_degrades_instead_of_hanging():
    """With one alpha group fully partitioned: queries over healthy
    predicates answer within their deadline; queries touching the dead
    group come back `degraded`/`partial` (and fast, once the breaker
    opens) instead of stacking per-layer timeouts."""
    from dgraph_tpu.worker.harness import ProcCluster

    c = ProcCluster(n_groups=2, replicas=1)
    try:
        c.alter("pa: string @index(exact) .\npb: string @index(exact) .")
        ga, gb = c.zero.belongs_to("pa"), c.zero.belongs_to("pb")
        assert {ga, gb} == {1, 2}
        c.new_txn().mutate_rdf(
            set_rdf='<0x1> <pa> "ha" .\n<0x2> <pb> "hb" .', commit_now=True
        )
        plan = faults.install(FaultPlan(seed=9))
        for addr in c.remote_groups[gb].addrs:
            plan.partition(addr)  # full partition of group B

        t0 = time.perf_counter()
        out = c.query('{ q(func: eq(pa, "ha")) { pa } }')
        healthy_dt = time.perf_counter() - t0
        assert out["data"]["q"] == [{"pa": "ha"}]
        assert not out["extensions"].get("degraded")
        assert healthy_dt < 10.0  # well inside the query deadline

        t0 = time.perf_counter()
        out = c.query('{ q(func: eq(pb, "hb")) { pb } }')
        first_dt = time.perf_counter() - t0
        assert out["extensions"]["degraded"] is True
        assert out["extensions"]["partial"] is True
        assert out["extensions"]["unreachable_groups"] == [gb]
        assert out["data"]["q"] == []
        assert first_dt < 12.0  # not the stacked 5s/8s/15s ladder

        # breaker is open now: the dead group costs ~nothing per query
        t0 = time.perf_counter()
        out = c.query('{ q(func: eq(pb, "hb")) { pb } }')
        assert out["extensions"]["degraded"] is True
        assert time.perf_counter() - t0 < 2.0
        # and healthy-predicate queries were never impacted
        out = c.query('{ q(func: eq(pa, "ha")) { pa } }')
        assert out["data"]["q"] == [{"pa": "ha"}]

        # heal: the heartbeat's half-open probe closes the circuit and
        # full (non-degraded) answers resume
        plan.heal()
        deadline = time.time() + 10
        while time.time() < deadline:
            out = c.query('{ q(func: eq(pb, "hb")) { pb } }')
            if not out["extensions"].get("degraded") and out["data"]["q"]:
                break
            time.sleep(0.3)
        assert out["data"]["q"] == [{"pb": "hb"}]
        assert not out["extensions"].get("degraded")
    finally:
        faults.reset()
        c.close()


# ---------------------------------------------------------------------------
# long randomized schedules (out of tier-1)
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_long_schedule_with_raft_faults(tmp_path, monkeypatch):
    """Heavier seeded schedule, including raft-plane faults inside the
    replica processes (via DGRAPH_TPU_FAULT_PLAN inheritance), a
    concurrent bank workload, and a multi-level query corpus checked
    serial-vs-parallel identical under chaos."""
    import json
    import os

    import numpy as np

    from dgraph_tpu.worker.harness import ProcCluster
    from dgraph_tpu.zero.zero import TxnConflictError

    child_spec = {
        "seed": 77,
        "rules": [
            {"point": "raft_send", "action": "drop", "p": 0.03},
            {"point": "raft_send", "action": "delay", "p": 0.10,
             "delay_ms": 5},
            {"point": "raft_send", "action": "dup", "p": 0.05},
            {"point": "resp", "action": "drop", "p": 0.03},
        ],
    }
    monkeypatch.setenv(faults.ENV_VAR, json.dumps(child_spec))
    c = ProcCluster(
        n_groups=2, replicas=3, replicated_zero=True, zero_replicas=3,
        data_dir=str(tmp_path / "chaos"),
    )
    try:
        # children announced the inherited schedule (auditability)
        logs = [
            p for p in os.listdir(str(tmp_path / "chaos"))
            if p.endswith(".log")
        ]
        tagged = 0
        for p in logs:
            with open(tmp_path / "chaos" / p, "rb") as f:
                if b"[faults]" in f.read():
                    tagged += 1
        assert tagged >= 1, logs
        _seed_bank(c)
        c.alter("follows: [uid] .")
        c.new_txn().mutate_rdf(
            set_rdf="\n".join(
                f"<0x{i:x}> <follows> <0x{(i % N_ACCOUNTS) + 1:x}> ."
                for i in range(1, N_ACCOUNTS + 1)
            ),
            commit_now=True,
        )
        faults.install(
            FaultPlan(
                seed=4321,
                rules=[
                    dict(point="send", action="drop", p=0.08),
                    dict(point="send", action="delay", p=0.15, delay_ms=8),
                    dict(point="send", action="disconnect", p=0.05),
                ],
            )
        )
        stats = {"ok": 0, "conflict": 0, "ambiguous": 0}
        lock = threading.Lock()

        def worker(seed):
            rng = np.random.default_rng(seed)
            for _ in range(12):
                frm, to = (
                    int(x) + 1
                    for x in rng.choice(N_ACCOUNTS, 2, replace=False)
                )
                amt = int(rng.integers(1, 10))
                t = c.new_txn()
                try:
                    got = c.query(
                        "{ a(func: uid(0x%x)) { bal } "
                        "b(func: uid(0x%x)) { bal } }" % (frm, to)
                    )["data"]
                    if not got["a"] or not got["b"]:
                        continue  # degraded snapshot: skip the transfer
                    t.mutate_rdf(
                        set_rdf=(
                            f'<0x{frm:x}> <bal> '
                            f'"{got["a"][0]["bal"] - amt}"^^<xs:int> .\n'
                            f'<0x{to:x}> <bal> '
                            f'"{got["b"][0]["bal"] + amt}"^^<xs:int> .'
                        ),
                        commit_now=True,
                    )
                    with lock:
                        stats["ok"] += 1
                except TxnConflictError:
                    with lock:
                        stats["conflict"] += 1
                except (TimeoutError, RpcError, RuntimeError):
                    with lock:
                        stats["ambiguous"] += 1

        threads = [
            threading.Thread(target=worker, args=(s,)) for s in (1, 2)
        ]
        for th in threads:
            th.start()
        corpus = [
            "{ q(func: has(bal)) { uid bal } }",
            '{ q(func: eq(acct, "a1")) { acct bal '
            "follows { acct follows { acct } } } }",
            "{ q(func: has(acct), orderasc: acct) { acct } }",
        ]
        last_ts = 0
        while any(th.is_alive() for th in threads):
            out = c.query(corpus[0])
            if not out["extensions"].get("degraded"):
                bals = {
                    int(x["uid"], 16): x["bal"] for x in out["data"]["q"]
                }
                assert sum(bals.values()) == N_ACCOUNTS * START_BAL, bals
            ts = c.zero.zero.read_ts()
            assert ts > last_ts
            last_ts = ts
            time.sleep(0.05)
        for th in threads:
            th.join(timeout=30)
        assert stats["ok"] > 0, stats
        # final invariant after chaos quiesces on the coordinator side
        faults.reset()
        bals = _balances(c)
        assert sum(bals.values()) == N_ACCOUNTS * START_BAL, (bals, stats)

        # multi-level corpus: serial and parallel executors identical
        # (both non-degraded; raft-plane chaos continues in children)
        for q in corpus:
            monkeypatch.setenv("DGRAPH_TPU_EXEC_WORKERS", "1")
            serial = c.query(q)
            monkeypatch.setenv("DGRAPH_TPU_EXEC_WORKERS", "4")
            parallel = c.query(q)
            if serial["extensions"].get("degraded") or \
                    parallel["extensions"].get("degraded"):
                continue
            # extensions carry run-specific timings; data must be equal
            assert serial["data"] == parallel["data"], q
    finally:
        faults.reset()
        c.close()

"""EXPLAIN/ANALYZE query introspection (the telemetry plane's debug
surface).

The golden gate: response `data` bytes must be IDENTICAL with the
debug flag on vs off over the DQL golden corpus (smoke subset tier-1,
full 535-case sweep slow-marked) — plan capture is observation-only.
Every smoke query's plan tree must also be present and schema-valid.
Plus: the CLI renderer snapshot, the HTTP ?debug=true surface, the
capture hooks (plan cache, admission, micro-batch, set-op decisions),
and the ProcCluster entry point.
"""

import json
import os

import pytest

HERE = os.path.join(os.path.dirname(os.path.abspath(__file__)), "ref_golden")
CASES = json.load(open(os.path.join(HERE, "cases.json")))
SMOKE_CASES = CASES[::9]  # same stride as test_stream_encoder's smoke set

_NODE_FIELDS = {
    "attr": str,
    "level": int,
    "uids_in": int,
    "uids_out": int,
    "read": str,
    "wall_ns": int,
    "kernels": dict,
    "children": list,
}


def _validate_node(node, path="nodes"):
    for field, typ in _NODE_FIELDS.items():
        assert field in node, f"{path}: missing {field!r} in {node}"
        assert isinstance(node[field], typ), (path, field, node[field])
    assert node["level"] >= 0
    assert node["uids_in"] >= 0 and node["uids_out"] >= 0
    for i, c in enumerate(node["children"]):
        assert c["level"] > node["level"], (path, node, c)
        _validate_node(c, f"{path}.children[{i}]")


def validate_plan(plan):
    """The extensions.plan schema the CLI renderer and dashboards
    consume — every field the tentpole names."""
    assert isinstance(plan, dict)
    for key, typ in (
        ("nodes", list),
        ("setops", list),
        ("microbatch", dict),
        ("plan_cache", dict),
        ("admission", dict),
        ("cache", dict),
        ("planner", dict),
        ("result_cache", dict),
    ):
        assert key in plan and isinstance(plan[key], typ), key
    for node in plan["nodes"]:
        _validate_node(node)
    for s in plan["setops"]:
        assert s.get("verdict") in ("packed", "decoded", "pushdown"), s
        assert s.get("site") in (
            "pair", "index_intersect", "level_filter"
        ), s
    mb = plan["microbatch"]
    assert set(mb) == {"solo", "coalesced", "members_max"}
    assert {"cost", "degrade", "enabled"} <= set(plan["admission"])
    assert "wall_ns" in plan and plan["wall_ns"] >= 0


@pytest.fixture(scope="module")
def golden_server():
    from dgraph_tpu.api.server import Server

    s = Server()
    s.alter(open(os.path.join(HERE, "schema.txt")).read())
    for rdf in ("triples.rdf", "triples_facets.rdf"):
        t = s.new_txn()
        t.mutate_rdf(
            set_rdf=open(os.path.join(HERE, rdf)).read(), commit_now=True
        )
    return s


def _data_bytes(d):
    """Wire bytes of a response's data: the raw arena shell when the
    streaming path produced one, else a canonical dump (schema blocks
    return plain dicts on the raw path too)."""
    raw = getattr(d, "raw", None)
    if raw is not None:
        return bytes(raw)
    return json.dumps(d, sort_keys=True).encode()


def _two_ways(server, q):
    """(plain data bytes, debug data bytes, plan) — or the matching
    error reprs when the query fails either way."""
    try:
        plain = _data_bytes(server.query(q, want="raw")["data"])
    except Exception as exc:
        plain = f"{type(exc).__name__}: {exc}"
    try:
        res = server.query(q, want="raw", debug=True)
        dbg = _data_bytes(res["data"])
        plan = res["extensions"].get("plan")
    except Exception as exc:
        dbg = f"{type(exc).__name__}: {exc}"
        plan = None
    return plain, dbg, plan


@pytest.mark.parametrize(
    "case", SMOKE_CASES, ids=[c["id"] for c in SMOKE_CASES]
)
def test_golden_debug_byte_equality_smoke(golden_server, case):
    plain, dbg, plan = _two_ways(golden_server, case["query"])
    assert plain == dbg
    if isinstance(plain, bytes):  # executed cleanly both ways
        assert plan is not None
        validate_plan(plan)


@pytest.mark.slow
@pytest.mark.parametrize("case", CASES, ids=[c["id"] for c in CASES])
def test_golden_debug_byte_equality_full(golden_server, case):
    plain, dbg, _plan = _two_ways(golden_server, case["query"])
    assert plain == dbg


# ---------------------------------------------------------------------------
# capture hooks
# ---------------------------------------------------------------------------


@pytest.fixture()
def small_server():
    from dgraph_tpu.api.server import Server

    s = Server()
    s.alter("name: string @index(exact) .\nfriend: [uid] .")
    t = s.new_txn()
    t.mutate_rdf(
        set_rdf=(
            '<0x1> <name> "A" .\n<0x2> <name> "B" .\n<0x3> <name> "C" .\n'
            "<0x1> <friend> <0x2> .\n<0x1> <friend> <0x3> .\n"
            "<0x2> <friend> <0x3> ."
        ),
        commit_now=True,
    )
    return s


def test_plan_tree_shape_and_counts(small_server):
    q = '{ q(func: eq(name, "A")) { name friend { name } } }'
    res = small_server.query(q, debug=True)
    plan = res["extensions"]["plan"]
    validate_plan(plan)
    (root,) = plan["nodes"]
    assert root["read"] == "root" and root["func"] == "eq"
    assert root["uids_out"] == 1
    by_attr = {c["attr"]: c for c in root["children"]}
    assert by_attr["friend"]["uids_in"] == 1
    assert by_attr["friend"]["uids_out"] == 2
    assert by_attr["friend"]["level"] == 1
    (gname,) = by_attr["friend"]["children"]
    assert gname["attr"] == "name" and gname["level"] == 2
    assert gname["uids_in"] == 2 and gname["uids_out"] == 2
    # plan-cache outcome captured with the normalized shape key
    assert plan["plan_cache"]["shape"].startswith("{ q ( func : eq")
    # second run: the same shape must now report a hit
    res2 = small_server.query(q, debug=True)
    assert res2["extensions"]["plan"]["plan_cache"]["hit"] is True
    # cache tiers: the whole query read through the memlayer
    assert res2["extensions"]["plan"]["cache"]["batch_reads"] >= 1


def test_plan_captures_admission_decision(small_server, monkeypatch):
    monkeypatch.setenv("DGRAPH_TPU_ADMISSION", "1")
    res = small_server.query(
        '{ q(func: has(name)) { name } }', debug=True
    )
    adm = res["extensions"]["plan"]["admission"]
    assert adm["enabled"] is True
    assert adm["cost"] >= 1.0
    assert adm["degrade"] is False


def test_plan_captures_setop_decisions(small_server):
    # a root filter routes through _index_src_intersect (the
    # StatsHolder decision site)
    q = '{ q(func: has(name)) @filter(eq(name, "B")) { name } }'
    res = small_server.query(q, debug=True)
    plan = res["extensions"]["plan"]
    sites = {s["site"] for s in plan["setops"]}
    assert "index_intersect" in sites, plan["setops"]
    rec = next(
        s for s in plan["setops"] if s["site"] == "index_intersect"
    )
    assert rec["attr"] == "name"
    assert rec["verdict"] in ("packed", "decoded")
    assert rec["src"] >= 1 and rec["min_ratio"] >= 1


def test_no_plan_without_debug(small_server):
    res = small_server.query('{ q(func: has(name)) { name } }')
    assert "plan" not in res["extensions"]
    # and the capture hooks see no active plan outside a debug query
    from dgraph_tpu.utils.observe import current_plan

    assert current_plan() is None


def test_explain_counter_ticks(small_server):
    from dgraph_tpu.utils.observe import METRICS

    before = METRICS.value("explain_queries_total")
    small_server.query('{ q(func: has(name)) { name } }', debug=True)
    assert METRICS.value("explain_queries_total") == before + 1


# ---------------------------------------------------------------------------
# CLI renderer
# ---------------------------------------------------------------------------


def test_render_plan_snapshot(small_server):
    """The rendered plan is a stable contract (the --explain-sanity
    gate snapshots it too): one header, the decision lines, one
    indented line per node."""
    from dgraph_tpu.cli import render_plan

    res = small_server.query(
        '{ q(func: eq(name, "A")) { friend { uid } } }', debug=True
    )
    out = render_plan(res["extensions"]["plan"])
    lines = out.splitlines()
    assert lines[0].startswith("Query plan (wall ")
    assert any(l.startswith("  plan cache: ") for l in lines)
    assert any(l.startswith("  admission: ") for l in lines)
    assert any(l.startswith("  cache: ") for l in lines)
    assert "  q (root func=eq) -> 1 uids" in lines
    (friend_line,) = [
        l for l in lines if l.lstrip().startswith("friend level=")
    ]
    assert friend_line.startswith("    friend level=1 [batched] 1 -> 2 uids")


def test_cli_explain_local(small_server, tmp_path, capsys):
    """dgraph-tpu explain against a data dir renders a plan."""
    from dgraph_tpu.cli import main as cli_main

    d = str(tmp_path / "data")
    from dgraph_tpu.api.server import Server

    s = Server(data_dir=d)
    s.alter("name: string @index(exact) .")
    s.new_txn().mutate_rdf(
        set_rdf='<0x1> <name> "A" .', commit_now=True
    )
    s.kv.sync()
    rc = cli_main(
        ["explain", "-p", d, '{ q(func: has(name)) { name } }']
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert out.startswith("Query plan")
    assert "name level=1" in out


# ---------------------------------------------------------------------------
# transport surfaces
# ---------------------------------------------------------------------------


def test_http_debug_flag(small_server):
    import urllib.request

    from dgraph_tpu.api.http_server import HTTPServer

    srv = HTTPServer(small_server, port=0).start()
    try:
        url = f"http://127.0.0.1:{srv.port}/query"
        q = '{ q(func: has(name)) { name } }'

        def post(u):
            req = urllib.request.Request(
                u, data=q.encode(), method="POST",
                headers={"Content-Type": "application/dql"},
            )
            return json.loads(urllib.request.urlopen(req, timeout=10).read())

        plain = post(url)
        dbg = post(url + "?debug=true")
        assert plain["data"] == dbg["data"]
        assert "plan" not in plain.get("extensions", {})
        validate_plan(dbg["extensions"]["plan"])
        # JSON body spelling too
        req = urllib.request.Request(
            url,
            data=json.dumps({"query": q, "debug": True}).encode(),
            method="POST",
            headers={"Content-Type": "application/json"},
        )
        viajson = json.loads(
            urllib.request.urlopen(req, timeout=10).read()
        )
        assert viajson["data"] == plain["data"]
        assert "plan" in viajson["extensions"]
    finally:
        srv.stop()


def test_proc_cluster_debug_flag():
    from dgraph_tpu.worker.harness import ProcCluster

    c = ProcCluster(n_groups=1, replicas=1)
    try:
        c.alter("name: string @index(exact) .")
        c.new_txn().mutate_rdf(
            set_rdf='<0x1> <name> "A" .\n<0x2> <name> "B" .',
            commit_now=True,
        )
        q = '{ q(func: has(name)) { name } }'
        plain = c.query(q, want="raw")
        dbg = c.query(q, want="raw", debug=True)
        assert plain["data"].raw == dbg["data"].raw
        plan = dbg["extensions"]["plan"]
        validate_plan(plan)
        assert plan["nodes"], plan
        assert "plan" not in plain["extensions"]
    finally:
        c.close()

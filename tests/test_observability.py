"""Metrics histograms, spans, heartbeat pruning, size-based rebalance
(VERDICT r1 breadth tail; ref x/metrics.go, conn/pool.go:233,
zero/tablet.go:53).
"""

import time

from dgraph_tpu.utils.observe import Metrics, Tracer


def test_histogram_buckets_and_render():
    m = Metrics(prefix="t")
    m.inc("ops")
    m.inc("ops", 2)
    m.set_gauge("live", 3)
    for v in (0.0002, 0.002, 0.02, 0.2, 2.0, 20.0):
        m.observe("lat_seconds", v)
    text = m.render()
    assert "t_ops 3" in text
    assert "t_live 3" in text
    assert 't_lat_seconds_bucket{le="+Inf"} 6' in text
    assert "t_lat_seconds_count 6" in text
    # cumulative counts are monotone
    counts = [
        int(line.rsplit(" ", 1)[1])
        for line in text.splitlines()
        if line.startswith("t_lat_seconds_bucket")
    ]
    assert counts == sorted(counts)


def test_timer_contextmanager():
    m = Metrics()
    with m.timer("op_seconds"):
        time.sleep(0.005)
    assert m._hists["op_seconds"].total == 1
    assert m._hists["op_seconds"].sum >= 0.005


def test_spans_nest_and_record(tmp_path):
    tr = Tracer(sink_path=str(tmp_path / "spans.jsonl"))
    with tr.span("outer", op="query"):
        with tr.span("inner"):
            pass
    spans = tr.recent()
    assert [s["name"] for s in spans] == ["inner", "outer"]
    inner, outer = spans
    assert inner["trace_id"] == outer["trace_id"]
    assert inner["parent_id"] == outer["span_id"]
    assert outer["attrs"] == {"op": "query"}
    assert (tmp_path / "spans.jsonl").read_text().count("\n") == 2


def test_engine_emits_metrics_and_spans():
    from dgraph_tpu.api.server import Server
    from dgraph_tpu.utils.observe import METRICS, TRACER

    s = Server()
    s.alter("name: string @index(exact) .")
    s.new_txn().mutate_rdf(set_rdf='_:a <name> "m" .', commit_now=True)
    s.query('{ q(func: eq(name, "m")) { name } }')
    text = METRICS.render()
    assert "dgraph_tpu_num_queries" in text
    assert "dgraph_tpu_query_latency_seconds_bucket" in text
    assert "dgraph_tpu_commit_latency_seconds_count" in text
    names = {sp["name"] for sp in TRACER.recent()}
    assert {"query", "commit"} <= names


def test_membership_prune_and_size_rebalance():
    from dgraph_tpu.worker.groups import DistributedCluster

    c = DistributedCluster(n_groups=2, replicas=3)
    try:
        # all six members heartbeat via the pump loop
        time.sleep(0.3)
        assert len(c.zero.members) == 6
        c.kill_node(1)
        deadline = time.time() + 15
        while time.time() < deadline and 1 in c.zero.members:
            time.sleep(0.2)
        assert 1 not in c.zero.members  # pruned after missing heartbeats
        c.revive_node(1)

        # size-based rebalance: pile data onto one group's tablets
        c.alter("heavy: string .\nlight: string .")
        gid = c.zero.should_serve("heavy")
        # force both tablets onto the same group for the test
        c.zero.tablets["light"] = gid
        t = c.new_txn()
        rdf = [f'<0x{i:x}> <heavy> "{"x" * 200}" .' for i in range(1, 60)]
        rdf += [f'<0x{i:x}> <light> "s" .' for i in range(1, 10)]
        t.mutate_rdf(set_rdf="\n".join(rdf), commit_now=True)
        moved = c.rebalance_by_size(min_move_bytes=100)
        # moving `heavy` off the shared group narrows the byte gap
        assert moved == "heavy"
        assert c.zero.belongs_to(moved) != gid
        # data still readable after the move
        out = c.query("{ q(func: uid(0x1)) { heavy } }")
        assert out["data"]["q"][0]["heavy"].startswith("x")
    finally:
        c.close()

"""Metrics histograms, spans, heartbeat pruning, size-based rebalance
(VERDICT r1 breadth tail; ref x/metrics.go, conn/pool.go:233,
zero/tablet.go:53) + the distributed-observability primitives: random
span ids, traceparent context, exposition escaping/merge exactness,
OTLP shutdown flush, slow-query force-sampling.
"""

import json
import os
import subprocess
import sys
import time

import pytest

from dgraph_tpu.utils import observe
from dgraph_tpu.utils.observe import Metrics, Tracer


def test_histogram_buckets_and_render():
    m = Metrics(prefix="t")
    m.inc("ops")
    m.inc("ops", 2)
    m.set_gauge("live", 3)
    for v in (0.0002, 0.002, 0.02, 0.2, 2.0, 20.0):
        m.observe("lat_seconds", v)
    text = m.render()
    assert "t_ops 3" in text
    assert "t_live 3" in text
    assert 't_lat_seconds_bucket{le="+Inf"} 6' in text
    assert "t_lat_seconds_count 6" in text
    # cumulative counts are monotone
    counts = [
        int(line.rsplit(" ", 1)[1])
        for line in text.splitlines()
        if line.startswith("t_lat_seconds_bucket")
    ]
    assert counts == sorted(counts)


def test_timer_contextmanager():
    m = Metrics()
    with m.timer("op_seconds"):
        time.sleep(0.005)
    assert m._hists["op_seconds"].total == 1
    assert m._hists["op_seconds"].sum >= 0.005


def test_spans_nest_and_record(tmp_path):
    tr = Tracer(sink_path=str(tmp_path / "spans.jsonl"))
    with tr.span("outer", op="query"):
        with tr.span("inner"):
            pass
    spans = tr.recent()
    assert [s["name"] for s in spans] == ["inner", "outer"]
    inner, outer = spans
    assert inner["trace_id"] == outer["trace_id"]
    assert inner["parent_id"] == outer["span_id"]
    assert outer["attrs"] == {"op": "query"}
    assert (tmp_path / "spans.jsonl").read_text().count("\n") == 2


def test_engine_emits_metrics_and_spans():
    from dgraph_tpu.api.server import Server
    from dgraph_tpu.utils.observe import METRICS, TRACER

    s = Server()
    s.alter("name: string @index(exact) .")
    s.new_txn().mutate_rdf(set_rdf='_:a <name> "m" .', commit_now=True)
    s.query('{ q(func: eq(name, "m")) { name } }')
    text = METRICS.render()
    assert "dgraph_tpu_num_queries" in text
    assert "dgraph_tpu_query_latency_seconds_bucket" in text
    assert "dgraph_tpu_commit_latency_seconds_count" in text
    names = {sp["name"] for sp in TRACER.recent()}
    assert {"query", "commit"} <= names


def test_membership_prune_and_size_rebalance():
    from dgraph_tpu.worker.groups import DistributedCluster

    c = DistributedCluster(n_groups=2, replicas=3)
    try:
        # all six members heartbeat via the pump loop
        time.sleep(0.3)
        assert len(c.zero.members) == 6
        c.kill_node(1)
        deadline = time.time() + 15
        while time.time() < deadline and 1 in c.zero.members:
            time.sleep(0.2)
        assert 1 not in c.zero.members  # pruned after missing heartbeats
        c.revive_node(1)

        # size-based rebalance: pile data onto one group's tablets
        c.alter("heavy: string .\nlight: string .")
        gid = c.zero.should_serve("heavy")
        # force both tablets onto the same group for the test
        c.zero.tablets["light"] = gid
        t = c.new_txn()
        rdf = [f'<0x{i:x}> <heavy> "{"x" * 200}" .' for i in range(1, 60)]
        rdf += [f'<0x{i:x}> <light> "s" .' for i in range(1, 10)]
        t.mutate_rdf(set_rdf="\n".join(rdf), commit_now=True)
        moved = c.rebalance_by_size(min_move_bytes=100)
        # moving `heavy` off the shared group narrows the byte gap
        assert moved == "heavy"
        assert c.zero.belongs_to(moved) != gid
        # data still readable after the move
        out = c.query("{ q(func: uid(0x1)) { heavy } }")
        assert out["data"]["q"][0]["heavy"].startswith("x")
    finally:
        c.close()


def test_traceparent_roundtrip_and_attach():
    from dgraph_tpu.utils.observe import (
        SpanContext,
        format_traceparent,
        parse_traceparent,
    )

    ctx = SpanContext(0xDEADBEEF0123456789ABCDEF01234567, 0x1234ABCD, True)
    assert parse_traceparent(format_traceparent(ctx)) == ctx
    un = SpanContext(5, 7, False)
    assert parse_traceparent(format_traceparent(un)) == un
    for bad in ("", "garbage", "00-zz-yy-01", "01-0-0-00", None):
        assert parse_traceparent(bad) is None
    tr = Tracer()
    token = tr.attach(ctx)
    try:
        assert tr.current_traceparent() == format_traceparent(ctx)
        with tr.span("child") as sp:
            assert sp.trace_id == ctx.trace_id
            assert sp.parent_id == ctx.span_id
            assert sp.sampled
    finally:
        tr.detach(token)
    assert tr.current_context() is None


def test_span_ids_never_collide_across_processes():
    """Two separate interpreter processes must emit disjoint random
    span/trace ids (the old sequential per-process counter collided and
    corrupted merged traces)."""
    prog = (
        "from dgraph_tpu.utils.observe import Tracer\n"
        "import json\n"
        "tr = Tracer()\n"
        "ids = []\n"
        "for _ in range(100):\n"
        "    with tr.span('s') as sp:\n"
        "        ids.append([sp.trace_id, sp.span_id])\n"
        "print(json.dumps(ids))\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    runs = []
    for _ in range(2):
        got = subprocess.run(
            [sys.executable, "-c", prog],
            capture_output=True, text=True, env=env, timeout=120,
        )
        assert got.returncode == 0, got.stderr
        runs.append(json.loads(got.stdout))
    a_spans = {s for _, s in runs[0]}
    b_spans = {s for _, s in runs[1]}
    a_traces = {t for t, _ in runs[0]}
    b_traces = {t for t, _ in runs[1]}
    assert not a_spans & b_spans
    assert not a_traces & b_traces
    assert all(0 < s < 1 << 64 for s in a_spans | b_spans)
    assert all(0 < t < 1 << 128 for t in a_traces | b_traces)


def test_exposition_label_escaping_roundtrip():
    m = Metrics()
    m.inc("ops", 3)
    weird = 'inst"a\\b\nc'
    merged = observe.merge_expositions({weird: m.render()})
    parsed = observe.parse_exposition(merged)
    assert parsed["counter"]["dgraph_tpu_ops"] == 3
    labeled = [
        k for k in parsed["counter"] if k.startswith("dgraph_tpu_ops{")
    ]
    assert len(labeled) == 1
    assert parsed["counter"][labeled[0]] == 3
    inner = labeled[0][len("dgraph_tpu_ops{"):-1]
    assert observe._parse_labels(inner)["instance"] == weird


def test_parse_exposition_skips_malformed_lines():
    """A corrupt/foreign scrape (truncated line, OpenMetrics flavor,
    bare-word labels) must not crash the merge — malformed lines are
    skipped, well-formed ones still parse."""
    text = (
        "# TYPE x counter\n"
        "x{oops} 3\n"          # no '=' in labels
        "x{a=b} 3\n"           # unquoted label value
        'x{a="unterminated 3\n'
        "x notanumber\n"
        "x 2\n"
        'x{inst="ok"} 4\n'
    )
    p = observe.parse_exposition(text)
    assert p["counter"]["x"] == 2
    assert p["counter"]['x{inst="ok"}'] == 4
    # and a merge over a corrupt instance still succeeds
    merged = observe.merge_expositions({"a": text, "b": "x 1\n"})
    assert observe.parse_exposition(merged)["counter"]["x"] == 3


def test_merge_is_exact_for_counters_and_histograms():
    m1, m2 = Metrics(), Metrics()
    m1.inc("shared", 2)
    m2.inc("shared", 5)
    m1.inc("only_a", 1)
    m2.set_gauge("g", 4)
    for v in (0.0002, 0.03, 7.0):
        m1.observe("lat_seconds", v)
    for v in (0.0002, 0.2):
        m2.observe("lat_seconds", v)
    merged = observe.merge_expositions({"a": m1.render(), "b": m2.render()})
    p = observe.parse_exposition(merged)
    assert p["counter"]["dgraph_tpu_shared"] == 7
    assert p["counter"]['dgraph_tpu_shared{instance="a"}'] == 2
    assert p["counter"]['dgraph_tpu_shared{instance="b"}'] == 5
    assert p["counter"]["dgraph_tpu_only_a"] == 1
    assert p["gauge"]["dgraph_tpu_g"] == 4
    h = p["histogram"]["dgraph_tpu_lat_seconds"]
    assert h["count"] == 5
    assert h["sum"] == pytest.approx(7.2304)
    # exact bucket-merge on the shared cumulative grid
    assert h["buckets"]["0.0001"] == 0
    assert h["buckets"]["0.00025"] == 2  # one 0.0002 from each side
    assert h["buckets"]["0.05"] == 3     # + m1's 0.03
    assert h["buckets"]["0.25"] == 4     # + m2's 0.2
    assert h["buckets"]["10.0"] == 5     # + m1's 7.0
    assert h["buckets"]["+Inf"] == h["count"]
    # cumulative counts stay monotone in le order
    les = sorted(h["buckets"], key=observe._le_sortkey)
    cums = [h["buckets"][le] for le in les]
    assert cums == sorted(cums)


def test_slow_query_log_force_samples(tmp_path, monkeypatch):
    log = tmp_path / "slow.jsonl"
    monkeypatch.setenv("DGRAPH_TPU_SLOW_QUERY_MS", "0")
    monkeypatch.setenv("DGRAPH_TPU_SLOW_QUERY_LOG", str(log))
    monkeypatch.setenv("DGRAPH_TPU_SLOW_QUERY_LOG_MAX", "5")
    monkeypatch.setenv("DGRAPH_TPU_TRACE_SAMPLE", "0")  # unsampled trace
    from dgraph_tpu.api.server import Server

    s = Server()
    s.alter("name: string @index(exact) .")
    s.new_txn().mutate_rdf(set_rdf='_:a <name> "sl" .', commit_now=True)
    out = s.query('{ q(func: eq(name, "sl")) { name } }')
    recs = [json.loads(line) for line in open(log)]
    assert recs, "slow query not logged"
    rec = recs[-1]
    assert rec["kind"] == "query" and rec["took_ms"] > 0
    # the full local span tree rides along, force-sampled even though
    # the trace itself was unsampled
    assert rec["trace_id"] == out["extensions"]["trace_id"]
    names = {sp["name"] for sp in rec["spans"]}
    assert "query" in names and "level_task" in names
    roots = [sp for sp in rec["spans"] if sp["parent_id"] is None]
    assert len(roots) == 1
    # bounded: the log rewrites itself down to SLOW_QUERY_LOG_MAX
    for _ in range(12):
        s.query('{ q(func: eq(name, "sl")) { name } }')
    assert sum(1 for _ in open(log)) <= 5


def test_unsampled_spans_skip_export_but_feed_ring(tmp_path, monkeypatch):
    monkeypatch.setenv("DGRAPH_TPU_TRACE_SAMPLE", "0")
    sink = tmp_path / "sink.jsonl"
    tr = Tracer(sink_path=str(sink))
    with tr.span("root") as root:
        with tr.span("kid"):
            pass
    assert sink.read_text() == ""  # nothing exported
    assert {s["name"] for s in tr.recent()} == {"root", "kid"}
    # force-sampling retro-exports the buffered trace
    assert tr.force_sample(root.trace_id) == 2
    names = {json.loads(line)["name"] for line in open(sink)}
    assert names == {"root", "kid"}
    assert tr.force_sample(root.trace_id) == 0  # idempotent


def test_trace_disabled_is_a_noop(monkeypatch):
    monkeypatch.setenv("DGRAPH_TPU_TRACE", "0")
    tr = Tracer()
    with tr.span("off") as sp:
        assert sp.trace_id == 0
    assert tr.recent() == []


def test_otlp_flush_exports_spans_the_drainer_dequeued():
    """Shutdown path: spans the background drainer already moved into
    its working batch (but not yet posted — batch/interval not due)
    must still reach the collector via otlp_flush()."""
    import http.server
    import threading

    got = []

    class H(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers["Content-Length"])
            got.append(json.loads(self.rfile.read(n)))
            self.send_response(200)
            self.end_headers()
            self.wfile.write(b"{}")

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        tr = Tracer()
        # huge batch + long interval: the drainer dequeues but never
        # posts on its own within the test window
        tr.enable_otlp(
            f"http://127.0.0.1:{srv.server_port}",
            batch=10_000, flush_interval_s=600.0,
        )
        with tr.span("a"):
            pass
        with tr.span("b"):
            pass
        deadline = time.time() + 5
        while time.time() < deadline:
            with tr._otlp["lock"]:
                moved = len(tr._otlp["pending"])
            if moved == 2 and tr._otlp["q"].empty():
                break
            time.sleep(0.02)
        assert moved == 2, "drainer never dequeued the spans"
        assert not got, "spans posted prematurely (batching defeated)"
        tr.otlp_flush()
        names = {
            s["name"]
            for b in got
            for s in b["resourceSpans"][0]["scopeSpans"][0]["spans"]
        }
        assert names == {"a", "b"}
    finally:
        srv.shutdown()


def test_otlp_exporter_posts_spans():
    """OTLP/HTTP trace export (VERDICT carry: utils/observe.py seam)."""
    import http.server
    import json as _json
    import threading

    from dgraph_tpu.utils.observe import Tracer

    got = []

    class H(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers["Content-Length"])
            got.append((self.path, _json.loads(self.rfile.read(n))))
            self.send_response(200)
            self.end_headers()
            self.wfile.write(b"{}")

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), H)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        tr = Tracer()
        tr.enable_otlp(
            f"http://127.0.0.1:{srv.server_port}", batch=2,
            service_name="svc-x",
        )
        with tr.span("outer", q="abc"):
            with tr.span("inner"):
                pass
        tr.otlp_flush()  # exporting is async; force anything queued out
        import time as _time

        deadline = _time.time() + 5
        while not got and _time.time() < deadline:
            _time.sleep(0.02)  # drainer may hold the batch briefly
        assert got, "no OTLP batch received"
        while (
            sum(len(b["resourceSpans"][0]["scopeSpans"][0]["spans"]) for _, b in got) < 2
            and _time.time() < deadline
        ):
            _time.sleep(0.02)
        path, body = got[0]
        assert path == "/v1/traces"
        rs = body["resourceSpans"][0]
        attrs = {
            a["key"]: a["value"]["stringValue"]
            for a in rs["resource"]["attributes"]
        }
        assert attrs["service.name"] == "svc-x"
        # spans may arrive across one or two batches
        spans = [
            s
            for _, b in got
            for s in b["resourceSpans"][0]["scopeSpans"][0]["spans"]
        ]
        names = {s["name"] for s in spans}
        assert names == {"outer", "inner"}
        inner = next(s for s in spans if s["name"] == "inner")
        outer = next(s for s in spans if s["name"] == "outer")
        assert inner["parentSpanId"] == outer["spanId"]
        assert inner["traceId"] == outer["traceId"]
        assert int(outer["endTimeUnixNano"]) >= int(
            outer["startTimeUnixNano"]
        )
    finally:
        srv.shutdown()

"""Metrics histograms, spans, heartbeat pruning, size-based rebalance
(VERDICT r1 breadth tail; ref x/metrics.go, conn/pool.go:233,
zero/tablet.go:53).
"""

import time

from dgraph_tpu.utils.observe import Metrics, Tracer


def test_histogram_buckets_and_render():
    m = Metrics(prefix="t")
    m.inc("ops")
    m.inc("ops", 2)
    m.set_gauge("live", 3)
    for v in (0.0002, 0.002, 0.02, 0.2, 2.0, 20.0):
        m.observe("lat_seconds", v)
    text = m.render()
    assert "t_ops 3" in text
    assert "t_live 3" in text
    assert 't_lat_seconds_bucket{le="+Inf"} 6' in text
    assert "t_lat_seconds_count 6" in text
    # cumulative counts are monotone
    counts = [
        int(line.rsplit(" ", 1)[1])
        for line in text.splitlines()
        if line.startswith("t_lat_seconds_bucket")
    ]
    assert counts == sorted(counts)


def test_timer_contextmanager():
    m = Metrics()
    with m.timer("op_seconds"):
        time.sleep(0.005)
    assert m._hists["op_seconds"].total == 1
    assert m._hists["op_seconds"].sum >= 0.005


def test_spans_nest_and_record(tmp_path):
    tr = Tracer(sink_path=str(tmp_path / "spans.jsonl"))
    with tr.span("outer", op="query"):
        with tr.span("inner"):
            pass
    spans = tr.recent()
    assert [s["name"] for s in spans] == ["inner", "outer"]
    inner, outer = spans
    assert inner["trace_id"] == outer["trace_id"]
    assert inner["parent_id"] == outer["span_id"]
    assert outer["attrs"] == {"op": "query"}
    assert (tmp_path / "spans.jsonl").read_text().count("\n") == 2


def test_engine_emits_metrics_and_spans():
    from dgraph_tpu.api.server import Server
    from dgraph_tpu.utils.observe import METRICS, TRACER

    s = Server()
    s.alter("name: string @index(exact) .")
    s.new_txn().mutate_rdf(set_rdf='_:a <name> "m" .', commit_now=True)
    s.query('{ q(func: eq(name, "m")) { name } }')
    text = METRICS.render()
    assert "dgraph_tpu_num_queries" in text
    assert "dgraph_tpu_query_latency_seconds_bucket" in text
    assert "dgraph_tpu_commit_latency_seconds_count" in text
    names = {sp["name"] for sp in TRACER.recent()}
    assert {"query", "commit"} <= names


def test_membership_prune_and_size_rebalance():
    from dgraph_tpu.worker.groups import DistributedCluster

    c = DistributedCluster(n_groups=2, replicas=3)
    try:
        # all six members heartbeat via the pump loop
        time.sleep(0.3)
        assert len(c.zero.members) == 6
        c.kill_node(1)
        deadline = time.time() + 15
        while time.time() < deadline and 1 in c.zero.members:
            time.sleep(0.2)
        assert 1 not in c.zero.members  # pruned after missing heartbeats
        c.revive_node(1)

        # size-based rebalance: pile data onto one group's tablets
        c.alter("heavy: string .\nlight: string .")
        gid = c.zero.should_serve("heavy")
        # force both tablets onto the same group for the test
        c.zero.tablets["light"] = gid
        t = c.new_txn()
        rdf = [f'<0x{i:x}> <heavy> "{"x" * 200}" .' for i in range(1, 60)]
        rdf += [f'<0x{i:x}> <light> "s" .' for i in range(1, 10)]
        t.mutate_rdf(set_rdf="\n".join(rdf), commit_now=True)
        moved = c.rebalance_by_size(min_move_bytes=100)
        # moving `heavy` off the shared group narrows the byte gap
        assert moved == "heavy"
        assert c.zero.belongs_to(moved) != gid
        # data still readable after the move
        out = c.query("{ q(func: uid(0x1)) { heavy } }")
        assert out["data"]["q"][0]["heavy"].startswith("x")
    finally:
        c.close()


def test_otlp_exporter_posts_spans():
    """OTLP/HTTP trace export (VERDICT carry: utils/observe.py seam)."""
    import http.server
    import json as _json
    import threading

    from dgraph_tpu.utils.observe import Tracer

    got = []

    class H(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers["Content-Length"])
            got.append((self.path, _json.loads(self.rfile.read(n))))
            self.send_response(200)
            self.end_headers()
            self.wfile.write(b"{}")

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), H)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        tr = Tracer()
        tr.enable_otlp(
            f"http://127.0.0.1:{srv.server_port}", batch=2,
            service_name="svc-x",
        )
        with tr.span("outer", q="abc"):
            with tr.span("inner"):
                pass
        tr.otlp_flush()  # exporting is async; force anything queued out
        import time as _time

        deadline = _time.time() + 5
        while not got and _time.time() < deadline:
            _time.sleep(0.02)  # drainer may hold the batch briefly
        assert got, "no OTLP batch received"
        while (
            sum(len(b["resourceSpans"][0]["scopeSpans"][0]["spans"]) for _, b in got) < 2
            and _time.time() < deadline
        ):
            _time.sleep(0.02)
        path, body = got[0]
        assert path == "/v1/traces"
        rs = body["resourceSpans"][0]
        attrs = {
            a["key"]: a["value"]["stringValue"]
            for a in rs["resource"]["attributes"]
        }
        assert attrs["service.name"] == "svc-x"
        # spans may arrive across one or two batches
        spans = [
            s
            for _, b in got
            for s in b["resourceSpans"][0]["scopeSpans"][0]["spans"]
        ]
        names = {s["name"] for s in spans}
        assert names == {"outer", "inner"}
        inner = next(s for s in spans if s["name"] == "inner")
        outer = next(s for s in spans if s["name"] == "outer")
        assert inner["parentSpanId"] == outer["spanId"]
        assert inner["traceId"] == outer["traceId"]
        assert int(outer["endTimeUnixNano"]) >= int(
            outer["startTimeUnixNano"]
        )
    finally:
        srv.shutdown()

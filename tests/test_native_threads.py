"""Threaded stress corpus for the -pthread native kernels.

The three kernels that spin std::thread fan-outs internally —
vec_qi8_topk_lists (IVF probe batches), vec_qi8_quantize (row
quantizer), batch_apply (columnar group-commit apply) — are here
hammered from many *Python* threads at once, each call itself
multi-threaded, over shared read-only inputs. Two jobs:

  1. tier-1 (plain build): caller-concurrency determinism — every
     concurrent call must return bytes identical to the solo call
     (a race on shared input handling or a hidden global shows up as
     a divergent result);
  2. the TSan target corpus: `tools/check.sh --san-matrix` re-runs
     this module with DGRAPH_TPU_NATIVE_SAN=tsan, where any data race
     inside the fan-outs (or between concurrent callers) aborts the
     interpreter. TSan is the only tool that can see those races —
     the GIL is released for the entire native call.

batch_apply inputs are captured from a real seeded group-commit
workload (capture-and-replay), so the concurrent batches are exactly
the shapes production emits, not synthetic columns.
"""

import threading

import numpy as np
import pytest

from dgraph_tpu import native
from dgraph_tpu.models import vector
from dgraph_tpu.x import config

requires_native = pytest.mark.skipif(
    not native.NATIVE_AVAILABLE, reason="native codec library not built"
)

N_THREADS = 6
ITERS = 4


def _hammer(fn):
    """Run fn(thread_idx, iter_idx) from N_THREADS threads x ITERS
    iterations, barrier-aligned for maximal overlap; re-raise the
    first failure."""
    barrier = threading.Barrier(N_THREADS)
    errors = []

    def worker(t):
        try:
            barrier.wait(timeout=30)
            for i in range(ITERS):
                fn(t, i)
        except Exception as e:  # pragma: no cover - failure path
            errors.append(e)

    threads = [
        threading.Thread(target=worker, args=(t,), daemon=True)
        for t in range(N_THREADS)
    ]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=120)
    assert not any(th.is_alive() for th in threads), "stress worker hung"
    if errors:
        raise errors[0]


@requires_native
def test_topk_lists_concurrent_callers():
    rng = np.random.default_rng(31)
    n, d, nq, k = 2500, 32, 8, 8
    V = rng.standard_normal((n, d)).astype(np.float32)
    codes, scales, offsets, csums = vector._quantize(V)
    sqn = (V * V).sum(axis=1, dtype=np.float32)
    valid = np.ones((n,), np.uint8)
    valid[rng.choice(n, 250, replace=False)] = 0
    Q = rng.standard_normal((nq, d)).astype(np.float32)
    cand = [
        np.sort(
            rng.choice(n, int(rng.integers(1, 900)), replace=False)
        ).astype(np.int32)
        for _ in range(nq)
    ]
    cand[3] = np.zeros((0,), np.int32)  # empty slice
    cand[5] = cand[1]                    # aliased slice
    lens = np.array([c.size for c in cand], np.int64)
    ends = np.cumsum(lens)
    begs = ends - lens
    cat = np.concatenate(cand)
    qc, qs, qo, qcs, qstat = vector._quantize_queries(Q, "euclidean")
    mid = vector._METRIC_ID["euclidean"]

    def call():
        return native.vec_qi8_topk_lists(
            codes, scales, offsets, csums, sqn, valid,
            cat, begs, ends, qc, qs, qo, qcs, qstat, mid, k,
            nthreads=3,
        )

    want_idx, want_dist, want_scanned = call()

    def body(_t, _i):
        got_idx, got_dist, got_scanned = call()
        np.testing.assert_array_equal(got_idx, want_idx)
        np.testing.assert_array_equal(got_dist, want_dist)
        assert got_scanned == want_scanned

    _hammer(body)


@requires_native
def test_quantize_concurrent_callers():
    rng = np.random.default_rng(32)
    n, d = 900, 67  # odd dim: SIMD tail under thread splits
    V = rng.standard_normal((n, d)).astype(np.float32)
    V *= (10.0 ** rng.uniform(-5, 5, size=n)).astype(np.float32)[:, None]
    V[3] = 0.0

    def call():
        return native.vec_qi8_quantize(V, nthreads=2)

    want = call()
    assert want is not None

    def body(_t, _i):
        got = native.vec_qi8_quantize(V, nthreads=((_t % 3) + 1))
        for g, w in zip(got, want):
            np.testing.assert_array_equal(g, w)

    _hammer(body)


def _capture_batches():
    """Run a small seeded mutation workload with the columnar path
    forced on, capturing every batch_apply call's input columns (deep
    copies: the write sets recycle their buffers)."""
    from array import array

    from dgraph_tpu.api.server import Server

    captured = []
    real = native.batch_apply

    def spy(m_offs, shapes, entities, pred_ids, objects, vtypes, voffs,
            vblob, pp_blob, pp_offs, pflags, pidents):
        captured.append((
            m_offs[:], bytearray(shapes), entities[:], pred_ids[:],
            objects[:], bytearray(vtypes), voffs[:], bytearray(vblob),
            bytes(pp_blob), pp_offs[:], bytes(pflags), bytes(pidents),
        ))
        return real(m_offs, shapes, entities, pred_ids, objects, vtypes,
                    voffs, vblob, pp_blob, pp_offs, pflags, pidents)

    config.set_env("BATCH_APPLY", 1)
    native.batch_apply = spy
    try:
        rng = np.random.default_rng(33)
        s = Server()
        s.alter(
            "name: string @index(exact) .\n"
            "bio: string @index(term) .\n"
            "age: int @index(int) .\n"
            "knows: [uid] @reverse ."
        )
        auto = 0
        for _ in range(6):
            t = s.new_txn()
            objs = []
            for _ in range(int(rng.integers(2, 6))):
                auto += 1
                objs.append({
                    "uid": f"_:n{auto}",
                    "name": f"user{int(rng.integers(0, 30))}",
                    "bio": f"likes topic{int(rng.integers(0, 9))} daily",
                    "age": int(rng.integers(0, 99)),
                    "knows": [{"uid": hex(int(rng.integers(1, 16)))}],
                })
            t.mutate_json(set_obj=objs, commit_now=True)
    finally:
        native.batch_apply = real
        config.unset_env("BATCH_APPLY")
    assert isinstance(captured[0][0], array)  # shape sanity
    return captured


@requires_native
def test_batch_apply_concurrent_batches():
    batches = _capture_batches()
    assert batches, "columnar path never reached the kernel"
    want = [native.batch_apply(*b) for b in batches]

    def norm(res):
        n_pairs, keys, koffs, recs, roffs, member, pred, kinds, counts = res
        return (
            n_pairs, bytes(keys), list(koffs), bytes(recs), list(roffs),
            list(member), list(pred), list(kinds), list(counts),
        )

    want = [norm(w) for w in want]

    def body(t, i):
        b = batches[(t + i) % len(batches)]
        assert norm(native.batch_apply(*b)) == want[(t + i) % len(batches)]

    _hammer(body)

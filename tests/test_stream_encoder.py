"""Streaming arena encoder byte-identity (query/streamjson.py).

The streaming encoder — native kernels AND pure-Python fallback — must
be byte-identical to the dict encoder (``encode_blocks`` +
``json.dumps``) on every query: the DQL golden corpus (smoke subset in
tier-1, the full 535-case sweep slow-marked), plus the value shapes the
composer hand-formats or splices (RFC3339 datetimes, ±Inf→MaxFloat64,
base64 bytes, @normalize, facet keys, count(pred) forms). The
DGRAPH_TPU_STREAM_ENCODER escape hatch must route the whole response
path and the spliced response assembly must parse back to the dict
API's view.
"""

import json
import os

import numpy as np
import pytest

HERE = os.path.join(os.path.dirname(os.path.abspath(__file__)), "ref_golden")
CASES = json.load(open(os.path.join(HERE, "cases.json")))

# every ~9th case: wide coverage across the query0..4/facets/math suites
# without stalling tier-1 on the 1-core box
SMOKE_CASES = CASES[::9]


def _exec(server, q):
    """Run q through the executor once; encoding variants then compare
    over the SAME executed tree (isolates the encoder from any
    execution nondeterminism)."""
    from dgraph_tpu import dql
    from dgraph_tpu.posting.lists import LocalCache
    from dgraph_tpu.query.subgraph import Executor

    cache = LocalCache(server.kv, server.zero.read_ts(), mem=server.mem)
    ex = Executor(
        cache,
        server.schema,
        vector_indexes=server.vector_indexes,
        stats=server.stats,
    )
    nodes = ex.process(dql.parse(q))
    return nodes, ex


def _three_ways(server, q):
    """(dict-path bytes, streaming native bytes, streaming python
    bytes) for one query — or the error repr when execution fails
    (every encoder variant must then be unreachable the same way)."""
    from dgraph_tpu.query.streamjson import encode_data_bytes

    try:
        nodes, ex = _exec(server, q)
    except Exception as exc:
        e = f"{type(exc).__name__}: {exc}"
        return e, e, e
    kw = dict(val_vars=ex.val_vars, schema=server.schema)
    want = encode_data_bytes(nodes, stream=False, **kw).to_bytes()
    native = encode_data_bytes(
        nodes, stream=True, native_ok=True, **kw
    ).to_bytes()
    py = encode_data_bytes(
        nodes, stream=True, native_ok=False, **kw
    ).to_bytes()
    return want, native, py


@pytest.fixture(scope="module")
def golden_server():
    from dgraph_tpu.api.server import Server

    s = Server()
    s.alter(open(os.path.join(HERE, "schema.txt")).read())
    t = s.new_txn()
    t.mutate_rdf(
        set_rdf=open(os.path.join(HERE, "triples.rdf")).read(),
        commit_now=True,
    )
    t = s.new_txn()
    t.mutate_rdf(
        set_rdf=open(os.path.join(HERE, "triples_facets.rdf")).read(),
        commit_now=True,
    )
    return s


@pytest.mark.parametrize(
    "case", SMOKE_CASES, ids=[c["id"] for c in SMOKE_CASES]
)
def test_golden_corpus_smoke(golden_server, case):
    want, native, py = _three_ways(golden_server, case["query"])
    assert want == native
    assert want == py


@pytest.mark.slow
@pytest.mark.parametrize("case", CASES, ids=[c["id"] for c in CASES])
def test_golden_corpus_full(golden_server, case):
    want, native, py = _three_ways(golden_server, case["query"])
    assert want == native
    assert want == py


# ---------------------------------------------------------------------------
# Value shapes the streaming composer hand-formats or must fall back on.
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def shape_server():
    from dgraph_tpu.api.server import Server

    s = Server()
    s.alter(
        "name: string @index(exact) .\n"
        "friend: [uid] @count .\n"
        "boss: uid .\n"
        "dob: datetime .\n"
        "score: float .\n"
        "blob: binary .\n"
        "tags: [string] .\n"
    )
    t = s.new_txn()
    t.mutate_rdf(
        set_rdf=(
            '<0x1> <name> "Alice" .\n'
            '<0x1> <dob> "1910-01-01T07:30:00Z"^^<xs:dateTime> .\n'
            '<0x1> <score> "inf"^^<xs:float> .\n'
            '<0x2> <score> "-inf"^^<xs:float> .\n'
            '<0x1> <tags> "a" .\n'
            '<0x1> <tags> "b" .\n'
            '<0x2> <name> "Bob" .\n'
            '<0x3> <name> "Chan" .\n'
            "<0x1> <friend> <0x2> .\n"
            "<0x1> <friend> <0x3> .\n"
            "<0x2> <friend> <0x3> .\n"
            "<0x1> <boss> <0x2> (since=2006-01-02T15:04:05) .\n"
        ),
        commit_now=True,
    )
    return s


SHAPE_QUERIES = [
    # RFC3339 datetimes + ±Inf -> ±MaxFloat64 + string lists
    '{ q(func: has(name)) { name dob score tags } }',
    # count(pred) leaf per entity and count(uid) block form
    '{ q(func: has(name)) { name cnt: count(friend) } }',
    '{ q(func: has(name)) { count(uid) } }',
    # pure-uid child rows (the native enc_uid_objs shape)
    '{ q(func: has(name)) { friend { uid } } }',
    # count-object child rows under a uid pred
    '{ q(func: has(name)) { friend { c: count(friend) } } }',
    # non-list uid pred encodes as ONE object, with facet fallback
    '{ q(func: has(name)) { boss @facets { name } } }',
    '{ q(func: has(name)) { boss @facets(since) { name } } }',
    # @normalize falls back to the dict encoder for that block
    '{ q(func: has(name)) @normalize { n: name friend { fn: name } } }',
    # aggregates + math at block level
    '{ var(func: has(name)) { s as score } '
    '  q() { mx: max(val(s)) mn: min(val(s)) } }',
    # empty result block
    '{ q(func: eq(name, "Nobody")) { name } }',
]


@pytest.mark.parametrize("q", SHAPE_QUERIES)
def test_shape_identity(shape_server, q):
    want, native, py = _three_ways(shape_server, q)
    assert want == native
    assert want == py


def test_ordered_root_count_rows(shape_server):
    """Root orderasc/orderdesc reorders dest_uids by VALUE — the
    count-gather must not binary-search the now-unsorted level key
    vector (regression: searchsorted over value-ordered parents
    returned 0 for every row)."""
    for order in ("orderasc", "orderdesc"):
        q = (
            "{ q(func: has(name), %s: name) "
            "{ name c: count(friend) } }" % order
        )
        want, native, py = _three_ways(shape_server, q)
        assert want == native == py
        # the regression emitted 0 for EVERY row; Alice/Bob have friends
        assert b'"c":2' in want and b'"c":1' in want


def test_bytes_value_b64(shape_server):
    """binary values serialize base64 — through a JSON mutation (the
    RDF path has no binary literal form)."""
    t = shape_server.new_txn()
    t.mutate_json(
        set_obj={"uid": "0x4", "name": "Blobby", "blob": "aGVsbG8="},
        commit_now=True,
    )
    want, native, py = _three_ways(
        shape_server, '{ q(func: eq(name, "Blobby")) { blob } }'
    )
    # binary stores the literal value bytes; output re-base64s them
    assert b'"blob":"YUdWc2JHOD0="' in want
    assert want == native
    assert want == py


def test_inf_is_maxfloat(shape_server):
    """Go json marshals ±Inf as ±MaxFloat64 (ref outputnode floats) —
    pin the literal so both encoders keep matching it."""
    want, native, py = _three_ways(
        shape_server, '{ q(func: has(score), orderasc: score) { score } }'
    )
    assert b"1.7976931348623157e+308" in want
    assert b"-1.7976931348623157e+308" in want
    assert want == native == py


def test_datetime_rfc3339(shape_server):
    want, native, py = _three_ways(
        shape_server, '{ q(func: eq(name, "Alice")) { dob } }'
    )
    assert b'"1910-01-01T07:30:00Z"' in want
    assert want == native == py


# ---------------------------------------------------------------------------
# Bulk emitters at native width (> 32 rows triggers the kernels) and the
# response-path escape hatch.
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def wide_server():
    from dgraph_tpu.api.server import Server

    s = Server()
    s.alter("name: string @index(exact) .\nfollow: [uid] @count .")
    rows = ['<0x1> <name> "hub" .']
    for i in range(2, 203):
        rows.append(f"<0x1> <follow> <{hex(i)}> .")
        rows.append(f'<{hex(i)}> <name> "n{i}" .')
        rows.append(f"<{hex(i)}> <follow> <0x1> .")
    t = s.new_txn()
    t.mutate_rdf(set_rdf="\n".join(rows), commit_now=True)
    return s


def test_wide_uid_rows_native(wide_server):
    from dgraph_tpu.utils.observe import METRICS

    before = METRICS.value("stream_encode_native_bytes_total")
    want, native, py = _three_ways(
        wide_server, '{ q(func: eq(name, "hub")) { follow { uid } } }'
    )
    assert want == native == py
    assert want.count(b'"uid"') == 201
    from dgraph_tpu import native as native_mod

    if native_mod.NATIVE_AVAILABLE:
        assert (
            METRICS.value("stream_encode_native_bytes_total") > before
        )


def test_wide_count_rows_native(wide_server):
    want, native, py = _three_ways(
        wide_server,
        '{ q(func: eq(name, "hub")) { follow { c: count(follow) } } }',
    )
    assert want == native == py
    assert want.count(b'"c":') == 201


def test_escape_hatch_roundtrip(wide_server, monkeypatch):
    """DGRAPH_TPU_STREAM_ENCODER ∈ {0, 1} through the PUBLIC query
    path: identical dict view, identical raw bytes, and the spliced
    response envelope parses back to the same object."""
    from dgraph_tpu.query import streamjson

    q = '{ q(func: has(name), first: 40) { uid name follow { uid } } }'
    outs = {}
    for flag in ("0", "1"):
        monkeypatch.setenv("DGRAPH_TPU_STREAM_ENCODER", flag)
        res = wide_server.query(q)
        outs[flag] = res
        assert isinstance(res["data"], dict)  # dict API intact
        assert res["data"].raw is not None
        body = streamjson.response_bytes(res)
        parsed = json.loads(body)
        assert parsed["data"] == res["data"]
        assert res["extensions"]["server_latency"]["encoding_ns"] > 0
        enc_prof = res["extensions"]["profile"]["encode"]
        assert enc_prof["stream"] == int(flag)
        assert enc_prof["bytes"] == len(res["data"].raw)
    assert outs["0"]["data"] == outs["1"]["data"]
    assert outs["0"]["data"].raw == outs["1"]["data"].raw


def test_want_raw_skips_parse_back(wide_server):
    from dgraph_tpu.query.streamjson import RawJson

    res = wide_server.query(
        "{ q(func: has(name), first: 3) { uid } }", want="raw"
    )
    assert isinstance(res["data"], RawJson)
    assert json.loads(res["data"].raw) == {
        "q": [{"uid": "0x1"}, {"uid": "0x2"}, {"uid": "0x3"}]
    }
    assert "parse_ns" not in res["extensions"]["profile"]["encode"]


def test_fallback_counter_ticks(shape_server):
    from dgraph_tpu.utils.observe import METRICS

    before = METRICS.value("stream_encode_fallback_nodes_total")
    want, native, py = _three_ways(
        shape_server,
        '{ q(func: has(name)) @normalize { n: name } }',
    )
    assert want == native == py
    assert METRICS.value("stream_encode_fallback_nodes_total") > before


def test_arena_mark_truncate():
    from dgraph_tpu.query.streamjson import Arena

    a = Arena()
    a.write(b"abc")
    m = a.mark()
    a.write(b"defg")
    a.write(memoryview(b"hi"))
    assert a.length == 9
    a.truncate(m)
    assert a.to_bytes() == b"abc" and a.length == 3


def test_enc_kernels_match_python():
    """Native emitters vs the Python fallback formats, including the
    edge values the hex/decimal formatters hand-roll."""
    from dgraph_tpu import native

    if not native.NATIVE_AVAILABLE:
        pytest.skip("native lib unavailable")
    uids = np.array(
        [0, 1, 9, 15, 16, 255, 2**32 - 1, 2**63, 2**64 - 1], np.uint64
    )
    got = bytes(native.enc_uid_objs(uids, b'{"uid":"0x', b'"}'))
    want = b",".join(b'{"uid":"0x%x"}' % u for u in uids.tolist())
    assert got == want
    vals = np.array(
        [0, 1, -1, 10, -(2**63), 2**63 - 1, 12345678901234], np.int64
    )
    got = bytes(native.enc_int_objs(vals, b'{"c":', b"}"))
    want = b",".join(b'{"c":%d}' % v for v in vals.tolist())
    assert got == want

"""Cost-based planner + snapshot-keyed result cache (ROADMAP item 2).

The correctness gates:

  - Golden-corpus byte equivalence with the planner on vs off
    (DGRAPH_TPU_QUERY_PLANNER) — every ordering/narrowing/pushdown
    decision must be observation-equivalent. Smoke subset tier-1; the
    full 535-case sweep is slow-marked.
  - Golden-corpus byte equivalence with the result cache on vs off
    (DGRAPH_TPU_RESULT_CACHE_SIZE), including the repeat that actually
    HITS the cache.
  - No stale result is ever served past a watermark advance: the
    deterministic mutate-then-query check and a concurrent-writer
    monotonicity regression.

Plus unit tests for the planner's ordering/pushdown decisions, the
ResultCache LRU/TTL/key semantics, the EXPLAIN surfacing, and the
ProcCluster wiring.
"""

import json
import os
import threading

import numpy as np
import pytest

from dgraph_tpu.utils.observe import METRICS

HERE = os.path.join(os.path.dirname(os.path.abspath(__file__)), "ref_golden")
CASES = json.load(open(os.path.join(HERE, "cases.json")))
SMOKE_CASES = CASES[::9]  # same stride as test_explain/test_parallel_exec


@pytest.fixture(scope="module")
def golden_server():
    from dgraph_tpu.api.server import Server

    s = Server()
    s.alter(open(os.path.join(HERE, "schema.txt")).read())
    for rdf in ("triples.rdf", "triples_facets.rdf"):
        t = s.new_txn()
        t.mutate_rdf(
            set_rdf=open(os.path.join(HERE, rdf)).read(), commit_now=True
        )
    return s


def _data_bytes(server, q):
    """Wire bytes of the response data, or the error repr — both
    configurations must fail identically too."""
    try:
        d = server.query(q, want="raw")["data"]
        raw = getattr(d, "raw", None)
        if raw is not None:
            return bytes(raw)
        return json.dumps(d, sort_keys=True).encode()
    except Exception as exc:
        return f"{type(exc).__name__}: {exc}"


def _with_env(server, q, **env):
    saved = {}
    for k, v in env.items():
        name = f"DGRAPH_TPU_{k}"
        saved[name] = os.environ.get(name)
        os.environ[name] = str(v)
    try:
        return _data_bytes(server, q)
    finally:
        for name, old in saved.items():
            if old is None:
                os.environ.pop(name, None)
            else:
                os.environ[name] = old


# ---------------------------------------------------------------------------
# golden-corpus byte equivalence: planner on/off
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "case", SMOKE_CASES, ids=[c["id"] for c in SMOKE_CASES]
)
def test_golden_planner_byte_equality_smoke(golden_server, case):
    on = _with_env(golden_server, case["query"], QUERY_PLANNER=1)
    off = _with_env(golden_server, case["query"], QUERY_PLANNER=0)
    assert on == off


@pytest.mark.slow
@pytest.mark.parametrize("case", CASES, ids=[c["id"] for c in CASES])
def test_golden_planner_byte_equality_full(golden_server, case):
    on = _with_env(golden_server, case["query"], QUERY_PLANNER=1)
    off = _with_env(golden_server, case["query"], QUERY_PLANNER=0)
    assert on == off


# ---------------------------------------------------------------------------
# golden-corpus byte equivalence: result cache on/off (incl. the HIT)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "case", SMOKE_CASES, ids=[c["id"] for c in SMOKE_CASES]
)
def test_golden_result_cache_byte_equality_smoke(golden_server, case):
    q = case["query"]
    base = _with_env(golden_server, q, RESULT_CACHE_SIZE=0)
    first = _with_env(golden_server, q, RESULT_CACHE_SIZE=4096)
    second = _with_env(golden_server, q, RESULT_CACHE_SIZE=4096)
    assert first == base  # the populating miss
    assert second == base  # the hit (or a second miss) — never stale


@pytest.mark.slow
@pytest.mark.parametrize("case", CASES, ids=[c["id"] for c in CASES])
def test_golden_result_cache_byte_equality_full(golden_server, case):
    q = case["query"]
    base = _with_env(golden_server, q, RESULT_CACHE_SIZE=0)
    first = _with_env(golden_server, q, RESULT_CACHE_SIZE=4096)
    second = _with_env(golden_server, q, RESULT_CACHE_SIZE=4096)
    assert first == base and second == base


# ---------------------------------------------------------------------------
# planner decisions
# ---------------------------------------------------------------------------


@pytest.fixture()
def hub_server():
    """One hub entity with a wide friend fan-out — the level shape
    where the intersect-vs-filter (pushdown) choice matters."""
    from dgraph_tpu.api.server import Server

    s = Server()
    s.alter(
        "name: string @index(exact, trigram) .\n"
        "age: int @index(int) .\n"
        "friend: [uid] @reverse .\n"
    )
    lines = []
    for u in range(1, 301):
        lines.append(f'<{hex(u)}> <name> "n{u}" .')
        lines.append(f'<{hex(u)}> <age> "{u % 60}"^^<xs:int> .')
    for v in range(2, 252):
        lines.append(f"<0x1> <friend> <{hex(v)}> .")
    t = s.new_txn()
    t.mutate_rdf(set_rdf="\n".join(lines), commit_now=True)
    return s


def test_pushdown_fires_and_matches_filter_strategy(hub_server):
    q = (
        '{ q(func: eq(name, "n1")) '
        '{ name friend @filter(eq(name, "n17")) { name } } }'
    )
    p0 = METRICS.value("pushdown_applied_total")
    on = _with_env(hub_server, q, QUERY_PLANNER=1)
    assert METRICS.value("pushdown_applied_total") > p0, (
        "selective indexed filter over a 250-wide frontier must push down"
    )
    off = _with_env(hub_server, q, QUERY_PLANNER=0)
    assert on == off
    assert b"n17" in on


def test_pushdown_surfaces_in_explain(hub_server):
    q = (
        '{ q(func: eq(name, "n1")) '
        '{ name friend @filter(eq(name, "n17")) { name } } }'
    )
    hub_server.query(q)  # warm the CardBook/stats
    res = hub_server.query(q, debug=True)
    plan = res["extensions"]["plan"]
    assert plan["planner"]["enabled"] is True
    assert plan["planner"]["pushdowns"] >= 1
    recs = [s for s in plan["setops"] if s["site"] == "level_filter"]
    assert recs and recs[0]["verdict"] == "pushdown"
    assert recs[0]["frontier"] >= recs[0]["est"]
    # est-vs-actual cardinality on the friend node (CardBook warmed by
    # the first run)
    (root,) = plan["nodes"]
    friend = next(c for c in root["children"] if c["attr"] == "friend")
    assert "est_out" in friend and friend["est_out"] is not None


def test_and_chain_orders_cheap_arm_first(hub_server):
    """regexp (verify-heavy) declared BEFORE an indexed eq must still
    evaluate after it — and the narrowed chain is byte-identical."""
    q = (
        "{ q(func: has(age)) "
        '@filter(regexp(name, /n1.*/) AND eq(name, "n17")) { name } }'
    )
    r0 = METRICS.value("planner_reorders_total")
    on = _with_env(hub_server, q, QUERY_PLANNER=1)
    assert METRICS.value("planner_reorders_total") > r0
    off = _with_env(hub_server, q, QUERY_PLANNER=0)
    assert on == off


def test_and_chain_error_arms_still_raise(hub_server):
    """An arm whose schema checks would raise must raise with the
    planner on, even when a selective earlier arm empties the running
    intersection first (the early-exit would otherwise turn an error
    into an empty success)."""
    q = (
        '{ q(func: has(age)) '
        '@filter(eq(name, "no-such-name") AND near(name, [1,1], 10)) '
        "{ name } }"
    )
    on = _with_env(hub_server, q, QUERY_PLANNER=1)
    off = _with_env(hub_server, q, QUERY_PLANNER=0)
    assert on == off
    assert isinstance(on, str) and "QueryError" in on, on


def test_sibling_error_identity_under_reorder(hub_server):
    """When siblings are reordered, the error raised must still be the
    earliest-DECLARED failing sibling's — what the declaration-order
    path surfaces."""
    # ~name is invalid (reverse on a non-uid predicate) and scores as
    # an expensive uid fan-out, so the planner moves the cheap value
    # reads ahead of it; the response must still be ~name's error
    q = (
        '{ q(func: eq(name, "n1")) '
        "{ ~name { name } name age } }"
    )
    on = _with_env(hub_server, q, QUERY_PLANNER=1)
    off = _with_env(hub_server, q, QUERY_PLANNER=0)
    assert on == off
    assert isinstance(on, str) and "reverse" in on, on


def test_planner_order_and_unit():
    from dgraph_tpu.dql.parser import FilterTree, FuncSpec
    from dgraph_tpu.query.planner import Planner
    from dgraph_tpu.schema.schema import State

    pl = Planner(State(), None, 0)
    chain = FilterTree(
        op="and",
        children=[
            FilterTree(func=FuncSpec(name="regexp", attr="name", args=[])),
            FilterTree(func=FuncSpec(name="uid", attr="", args=[1, 2])),
            FilterTree(func=FuncSpec(name="has", attr="name", args=[])),
        ],
    )
    order = pl.order_and(chain.children, 1000)
    # uid (class 0) first, has (class 2) second, regexp (class 3) last
    assert order == [1, 2, 0]
    assert pl.reorders == 1


def test_planner_pushdown_gate_unit():
    from dgraph_tpu.dql.parser import FilterTree, FuncSpec
    from dgraph_tpu.query.planner import Planner
    from dgraph_tpu.schema.schema import State

    pl = Planner(State(), None, 0)
    ok = FilterTree(
        op="and",
        children=[
            FilterTree(func=FuncSpec(name="eq", attr="name", args=["x"])),
            FilterTree(func=FuncSpec(name="has", attr="age", args=[])),
        ],
    )
    assert pl.tree_pushdown_ok(ok)
    # NOT needs the frontier as its universe: never root-evaluable
    noted = FilterTree(op="not", children=[ok])
    assert not pl.tree_pushdown_ok(noted)
    assert not pl.tree_pushdown_ok(
        FilterTree(op="and", children=[ok, noted])
    )
    # similar_to is a top-k (impure): no narrowing for its subtree
    sim = FilterTree(
        op="and",
        children=[
            FilterTree(
                func=FuncSpec(name="similar_to", attr="v", args=[])
            ),
            ok,
        ],
    )
    assert not pl.tree_pure(sim)
    assert pl.tree_pure(ok)


def test_sibling_reorder_preserves_output_order(hub_server):
    """Cheap value predicates may EXECUTE before an expensive uid
    fan-out, but the response field order must stay declaration
    order."""
    q = (
        '{ q(func: eq(name, "n1")) '
        "{ friend { name } name age } }"
    )
    hub_server.query(q)  # warm CardBook so friend scores expensive
    on = json.loads(_with_env(hub_server, q, QUERY_PLANNER=1))
    off = json.loads(_with_env(hub_server, q, QUERY_PLANNER=0))
    assert on == off
    assert list(on["q"][0].keys()) == ["friend", "name", "age"]


# ---------------------------------------------------------------------------
# result cache semantics
# ---------------------------------------------------------------------------


def test_result_cache_lru_and_ttl_unit():
    from dgraph_tpu.serving.resultcache import ResultCache

    rc = ResultCache(size=2, ttl_s=0.0)
    k1 = rc.key("s", ("a",), None, 0, 7, epoch=1)
    k2 = rc.key("s", ("b",), None, 0, 7, epoch=1)
    k3 = rc.key("s", ("c",), None, 0, 7, epoch=1)
    rc.put(k1, b"1")
    rc.put(k2, b"2")
    assert rc.get(k1) == b"1"
    rc.put(k3, b"3")  # evicts k2 (k1 was refreshed by the get)
    assert rc.get(k2) is None
    assert rc.get(k1) == b"1" and rc.get(k3) == b"3"
    # byte bound: eviction honors RESULT_CACHE_BYTES, and a single
    # over-bound response never flushes the LRU (it just isn't cached)
    rcb = ResultCache(size=100, ttl_s=0.0, max_bytes=10)
    rcb.put(k1, b"aaaa")
    rcb.put(k2, b"bbbb")
    rcb.put(k3, b"cccc")  # 12 bytes total -> k1 evicted
    assert rcb.get(k1) is None
    assert rcb.get(k2) == b"bbbb" and rcb.get(k3) == b"cccc"
    assert rcb.stats()["bytes"] == 8
    rcb.put(rc.key("s", ("d",), None, 0, 7, 1), b"x" * 64)  # > bound
    assert rcb.get(k2) == b"bbbb"  # LRU untouched
    # TTL: an expired entry is a miss even at the same watermark
    rc2 = ResultCache(size=8, ttl_s=1e-9)
    rc2.put(k1, b"1")
    import time

    time.sleep(0.01)
    assert rc2.get(k1) is None
    # key separates watermarks, epochs, namespaces, and variables
    assert rc.key("s", ("a",), None, 0, 7, 1) != rc.key(
        "s", ("a",), None, 0, 8, 1
    )
    assert rc.key("s", ("a",), None, 0, 7, 1) != rc.key(
        "s", ("a",), None, 0, 7, 2
    )
    assert rc.key("s", ("a",), None, 1, 7, 1) != rc.key(
        "s", ("a",), None, 0, 7, 1
    )
    assert rc.key("s", ("a",), {"$x": "1"}, 0, 7, 1) != rc.key(
        "s", ("a",), {"$x": "2"}, 0, 7, 1
    )


def test_result_cache_never_stale_after_mutation(monkeypatch):
    from dgraph_tpu.api.server import Server

    monkeypatch.setenv("DGRAPH_TPU_RESULT_CACHE_SIZE", "64")
    s = Server()
    s.alter("v: int .\nname: string @index(exact) .")
    t = s.new_txn()
    t.mutate_rdf(
        set_rdf='<0x1> <name> "c" .\n<0x1> <v> "0"^^<xs:int> .',
        commit_now=True,
    )
    q = '{ q(func: eq(name, "c")) { v } }'
    assert s.query(q)["data"]["q"] == [{"v": 0}]
    # second read HITS
    h0 = METRICS.value("result_cache_hit_total")
    assert s.query(q)["data"]["q"] == [{"v": 0}]
    assert METRICS.value("result_cache_hit_total") == h0 + 1
    for i in range(1, 6):
        t = s.new_txn()
        t.mutate_rdf(
            set_rdf=f'<0x1> <v> "{i}"^^<xs:int> .', commit_now=True
        )
        assert s.query(q)["data"]["q"] == [{"v": i}], (
            "stale result served past a watermark advance"
        )


def test_result_cache_invalidation_under_concurrent_mutation(monkeypatch):
    """A writer advancing a counter races cached readers: observed
    values must be monotonically non-decreasing (a stale serve past a
    watermark advance would show as a decrease), and the final read
    must see the final committed value."""
    from dgraph_tpu.api.server import Server

    monkeypatch.setenv("DGRAPH_TPU_RESULT_CACHE_SIZE", "256")
    s = Server()
    s.alter("v: int .\nname: string @index(exact) .")
    t = s.new_txn()
    t.mutate_rdf(
        set_rdf='<0x1> <name> "c" .\n<0x1> <v> "0"^^<xs:int> .',
        commit_now=True,
    )
    q = '{ q(func: eq(name, "c")) { v } }'
    N = 60
    per_reader = [[], []]
    stop = threading.Event()

    def reader(idx):
        mine = per_reader[idx]
        while not stop.is_set():
            got = s.query(q)["data"]["q"]
            if got:
                mine.append(got[0]["v"])

    ths = [
        threading.Thread(target=reader, args=(i,)) for i in range(2)
    ]
    for th in ths:
        th.start()
    for i in range(1, N + 1):
        t = s.new_txn()
        t.mutate_rdf(
            set_rdf=f'<0x1> <v> "{i}"^^<xs:int> .', commit_now=True
        )
    stop.set()
    for th in ths:
        th.join()
    assert s.query(q)["data"]["q"] == [{"v": N}]
    # one reader's sequential reads ride a monotonically advancing
    # watermark: a stale serve past an advance would show as a value
    # DECREASE in that reader's sequence
    for mine in per_reader:
        assert all(
            a <= b for a, b in zip(mine, mine[1:])
        ), "stale cached result served past a watermark advance"
        assert all(0 <= v <= N for v in mine)


def test_result_cache_pinned_read_ts_never_caches(monkeypatch):
    from dgraph_tpu.api.server import Server

    monkeypatch.setenv("DGRAPH_TPU_RESULT_CACHE_SIZE", "64")
    s = Server()
    s.alter("name: string @index(exact) .")
    t = s.new_txn()
    t.mutate_rdf(set_rdf='<0x1> <name> "a" .', commit_now=True)
    q = '{ q(func: eq(name, "a")) { name } }'
    ts = s.zero.read_ts()
    m0 = METRICS.value("result_cache_miss_total")
    h0 = METRICS.value("result_cache_hit_total")
    s.query(q, read_ts=ts)
    s.query(q, read_ts=ts)
    assert METRICS.value("result_cache_miss_total") == m0
    assert METRICS.value("result_cache_hit_total") == h0


def test_result_cache_dict_hits_are_fresh_objects(monkeypatch):
    """A caller mutating a dict-API response must never poison the
    cache: hits rebuild from the immutable stored bytes."""
    from dgraph_tpu.api.server import Server

    monkeypatch.setenv("DGRAPH_TPU_RESULT_CACHE_SIZE", "64")
    s = Server()
    s.alter("name: string @index(exact) .")
    t = s.new_txn()
    t.mutate_rdf(set_rdf='<0x1> <name> "a" .', commit_now=True)
    q = '{ q(func: eq(name, "a")) { name } }'
    first = s.query(q)["data"]
    second = s.query(q)["data"]  # populate → hit
    second["q"][0]["name"] = "MUTATED"
    third = s.query(q)["data"]
    assert third["q"] == [{"name": "a"}]
    assert first["q"] == [{"name": "a"}]


# ---------------------------------------------------------------------------
# EXPLAIN renderer
# ---------------------------------------------------------------------------


def test_render_plan_planner_and_result_cache_lines(hub_server, monkeypatch):
    from dgraph_tpu.cli import render_plan

    monkeypatch.setenv("DGRAPH_TPU_RESULT_CACHE_SIZE", "64")
    q = (
        '{ q(func: eq(name, "n1")) '
        '{ name friend @filter(eq(name, "n17")) { name } } }'
    )
    hub_server.query(q)  # warm CardBook + populate the cache
    res = hub_server.query(q, debug=True)
    out = render_plan(res["extensions"]["plan"])
    lines = out.splitlines()
    assert any(l.startswith("  planner: on, ") for l in lines), out
    assert any(l.startswith("  result cache: ") for l in lines), out
    # the friend node carries est-vs-actual cardinality
    (friend_line,) = [
        l for l in lines if l.lstrip().startswith("friend level=")
    ]
    assert "(est " in friend_line, friend_line
    assert "pushdown" in out, out


# ---------------------------------------------------------------------------
# ProcCluster wiring
# ---------------------------------------------------------------------------


def test_proc_cluster_result_cache_and_planner(monkeypatch):
    from dgraph_tpu.worker.harness import ProcCluster

    monkeypatch.setenv("DGRAPH_TPU_RESULT_CACHE_SIZE", "64")
    c = ProcCluster(n_groups=1, replicas=1)
    try:
        c.alter("name: string @index(exact) .")
        c.new_txn().mutate_rdf(
            set_rdf='<0x1> <name> "A" .\n<0x2> <name> "B" .',
            commit_now=True,
        )
        q = '{ q(func: has(name)) { name } }'
        first = c.query(q, want="raw")
        h0 = METRICS.value("result_cache_hit_total")
        second = c.query(q, want="raw")
        assert METRICS.value("result_cache_hit_total") == h0 + 1
        assert first["data"].raw == second["data"].raw
        assert second["extensions"]["result_cache"]["hit"] is True
        # a commit advances the watermark: no stale serve
        c.new_txn().mutate_rdf(
            set_rdf='<0x3> <name> "C" .', commit_now=True
        )
        third = c.query(q)
        assert len(third["data"]["q"]) == 3
        # EXPLAIN surfaces both planes
        dbg = c.query(q, want="raw", debug=True)
        plan = dbg["extensions"]["plan"]
        assert plan["planner"].get("enabled") in (True, False)
        assert plan["result_cache"]["enabled"] is True
    finally:
        c.close()

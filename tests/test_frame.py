"""conn/frame.py binary multipart codec (the snappy-framing analog)."""

import json

import numpy as np
import pytest

from dgraph_tpu.conn.frame import MAGIC, pack_body, unpack_body


def test_small_message_stays_json():
    obj = {"id": 1, "m": "ping", "a": {"x": [1, 2, 3], "s": "hi"}}
    body = pack_body(obj)
    assert body[0] != MAGIC
    assert json.loads(body) == obj
    assert unpack_body(body) == obj


def test_small_bytes_inline_b64():
    obj = {"a": {"key": b"shortkey", "n": 7}}
    body = pack_body(obj)
    assert body[0] != MAGIC  # no blobs extracted
    assert unpack_body(body) == obj


def test_large_bytes_ride_as_blobs():
    rng = np.random.default_rng(0)
    big = rng.integers(0, 256, 100_000, dtype=np.uint8).tobytes()
    obj = {"r": [[b"k1", 5, big], [b"k2", 6, big[: 50_000]]]}
    body = pack_body(obj)
    assert body[0] == MAGIC
    got = unpack_body(body)
    assert got == {"r": [["k1".encode(), 5, big], [b"k2", 6, big[:50_000]]]}


def test_compressible_blob_shrinks_when_enabled(monkeypatch):
    from dgraph_tpu.conn import frame

    monkeypatch.setattr(frame, "_COMPRESS", True)
    big = b"abcdefgh" * 200_000  # 1.6MB, highly compressible
    body = pack_body({"d": big})
    assert body[0] == MAGIC
    assert len(body) < len(big) // 10
    assert unpack_body(body)["d"] == big


def test_default_mode_stores_raw():
    big = b"abcdefgh" * 200_000
    body = pack_body({"d": big})
    assert len(body) >= len(big)  # raw blob, no b64 inflation either
    assert unpack_body(body)["d"] == big


def test_incompressible_blob_stored_raw():
    rng = np.random.default_rng(1)
    big = rng.integers(0, 256, 1 << 20, dtype=np.uint8).tobytes()
    body = pack_body({"d": big})
    # raw + headers: no inflation beyond a few dozen bytes
    assert len(body) < len(big) + 128
    assert unpack_body(body)["d"] == big


def test_nested_structures_and_tuples():
    obj = {"p": ("delta", [(b"x" * 500, 1)], {"deep": [b"y" * 300]})}
    got = unpack_body(pack_body(obj))
    # tuples become lists on the wire (JSON), like the old codec
    assert got["p"][0] == "delta"
    assert got["p"][1][0][0] == b"x" * 500
    assert got["p"][2]["deep"][0] == b"y" * 300


def test_rpc_roundtrip_with_bulk_payload():
    from dgraph_tpu.conn.rpc import RpcClient, RpcServer

    srv = RpcServer().start()
    payload = [bytes([i % 251] * 2000) for i in range(50)]
    srv.register("bulk", lambda a: {"vals": payload, "n": len(a["keys"])})
    try:
        c = RpcClient(srv.addr)
        got = c.call("bulk", {"keys": [b"a" * 400, b"b" * 400]})
        assert got["n"] == 2
        assert got["vals"] == payload
        c.close_conn()
    finally:
        srv.close()

"""conn/frame.py binary multipart codec (the snappy-framing analog)."""

import json

import numpy as np
import pytest

from dgraph_tpu.conn.frame import MAGIC, pack_body, unpack_body


def test_small_message_stays_json():
    obj = {"id": 1, "m": "ping", "a": {"x": [1, 2, 3], "s": "hi"}}
    body = pack_body(obj)
    assert body[0] != MAGIC
    assert json.loads(body) == obj
    assert unpack_body(body) == obj


def test_small_bytes_inline_b64():
    obj = {"a": {"key": b"shortkey", "n": 7}}
    body = pack_body(obj)
    assert body[0] != MAGIC  # no blobs extracted
    assert unpack_body(body) == obj


def test_large_bytes_ride_as_blobs():
    rng = np.random.default_rng(0)
    big = rng.integers(0, 256, 100_000, dtype=np.uint8).tobytes()
    obj = {"r": [[b"k1", 5, big], [b"k2", 6, big[: 50_000]]]}
    body = pack_body(obj)
    assert body[0] == MAGIC
    got = unpack_body(body)
    assert got == {"r": [["k1".encode(), 5, big], [b"k2", 6, big[:50_000]]]}


def test_compressible_blob_shrinks_when_enabled(monkeypatch):
    from dgraph_tpu.conn import frame

    monkeypatch.setattr(frame, "_COMPRESS", True)
    big = b"abcdefgh" * 200_000  # 1.6MB, highly compressible
    body = pack_body({"d": big})
    assert body[0] == MAGIC
    assert len(body) < len(big) // 10
    assert unpack_body(body)["d"] == big


def test_default_mode_stores_raw():
    big = b"abcdefgh" * 200_000
    body = pack_body({"d": big})
    assert len(body) >= len(big)  # raw blob, no b64 inflation either
    assert unpack_body(body)["d"] == big


def test_incompressible_blob_stored_raw():
    rng = np.random.default_rng(1)
    big = rng.integers(0, 256, 1 << 20, dtype=np.uint8).tobytes()
    body = pack_body({"d": big})
    # raw + headers: no inflation beyond a few dozen bytes
    assert len(body) < len(big) + 128
    assert unpack_body(body)["d"] == big


def test_nested_structures_and_tuples():
    obj = {"p": ("delta", [(b"x" * 500, 1)], {"deep": [b"y" * 300]})}
    got = unpack_body(pack_body(obj))
    # tuples become lists on the wire (JSON), like the old codec
    assert got["p"][0] == "delta"
    assert got["p"][1][0][0] == b"x" * 500
    assert got["p"][2]["deep"][0] == b"y" * 300


def test_rpc_roundtrip_with_bulk_payload():
    from dgraph_tpu.conn.rpc import RpcClient, RpcServer

    srv = RpcServer().start()
    payload = [bytes([i % 251] * 2000) for i in range(50)]
    srv.register("bulk", lambda a: {"vals": payload, "n": len(a["keys"])})
    try:
        c = RpcClient(srv.addr)
        got = c.call("bulk", {"keys": [b"a" * 400, b"b" * 400]})
        assert got["n"] == 2
        assert got["vals"] == payload
        c.close_conn()
    finally:
        srv.close()


def test_sentinel_collision_dicts_roundtrip():
    # user payloads that look exactly like codec sentinels must survive
    big = b"x" * 1000
    obj = {
        "a": {"__blob__": 3},
        "b": {"__b64__": "not base64!"},
        "c": {"__esc__": {"__blob__": 0}},
        "d": {"__blob__": big},  # value itself is blob-sized bytes
        "e": big,  # a real blob alongside, indices must not collide
    }
    got = unpack_body(pack_body(obj))
    assert got == obj


def test_sentinel_collision_without_blobs_stays_consistent():
    obj = {"only": {"__b64__": 42}}
    assert unpack_body(pack_body(obj)) == obj


def test_decompression_bomb_rejected():
    import struct
    import zlib

    from dgraph_tpu.conn.frame import FrameError

    # hand-build a frame whose blob declares 100 bytes but inflates to 10MB
    bomb = zlib.compress(b"\x00" * (10 << 20), 1)
    payload = struct.pack(">I", 100) + bomb
    jb = json.dumps({"d": {"__blob__": 0}}).encode()
    body = (
        bytes([MAGIC])
        + struct.pack(">I", len(jb))
        + jb
        + struct.pack(">I", len(payload))
        + b"\x02"
        + payload
    )
    with pytest.raises(FrameError):
        unpack_body(body)


def test_compressed_roundtrip_with_rawlen_header(monkeypatch):
    from dgraph_tpu.conn import frame

    monkeypatch.setattr(frame, "_COMPRESS", True)
    big = b"pattern!" * 100_000
    body = pack_body({"d": big, "meta": {"__blob__": "user-key"}})
    got = unpack_body(body)
    assert got == {"d": big, "meta": {"__blob__": "user-key"}}


def test_declared_huge_rawlen_rejected():
    import struct
    import zlib

    from dgraph_tpu.conn import frame
    from dgraph_tpu.conn.frame import FrameError

    # blob declares 1GB (over the 256MB cap) — rejected before inflating
    comp = zlib.compress(b"\x00" * 1024, 1)
    payload = struct.pack(">I", 1 << 30) + comp
    jb = json.dumps({"d": {"__blob__": 0}}).encode()
    body = (
        bytes([MAGIC])
        + struct.pack(">I", len(jb))
        + jb
        + struct.pack(">I", len(payload))
        + b"\x02"
        + payload
    )
    with pytest.raises(FrameError):
        unpack_body(body)
    assert frame._MAX_INFLATE == 256 << 20


def test_truncated_zlib_trailer_rejected():
    import struct
    import zlib

    from dgraph_tpu.conn.frame import FrameError

    raw = b"checksum-me" * 100
    comp = zlib.compress(raw, 1)[:-4]  # cut the adler32 trailer
    payload = struct.pack(">I", len(raw)) + comp
    jb = json.dumps({"d": {"__blob__": 0}}).encode()
    body = (
        bytes([MAGIC])
        + struct.pack(">I", len(jb))
        + jb
        + struct.pack(">I", len(payload))
        + b"\x02"
        + payload
    )
    with pytest.raises(FrameError):
        unpack_body(body)


def test_malformed_esc_payload_raises_frameerror():
    from dgraph_tpu.conn.frame import FrameError

    with pytest.raises(FrameError):
        unpack_body(json.dumps({"x": {"__esc__": 5}}).encode())


def test_aggregate_inflation_budget_enforced():
    import struct
    import zlib

    from dgraph_tpu.conn import frame
    from dgraph_tpu.conn.frame import FrameError

    # three blobs each declaring 100MB (each under the 256MB cap, but
    # 300MB aggregate) — the frame budget must reject the third
    comp = zlib.compress(b"\x00" * (100 << 20), 1)
    payload = struct.pack(">I", 100 << 20) + comp
    jb = json.dumps({"d": [{"__blob__": i} for i in range(3)]}).encode()
    body = bytes([MAGIC]) + struct.pack(">I", len(jb)) + jb
    for _ in range(3):
        body += struct.pack(">I", len(payload)) + b"\x02" + payload
    with pytest.raises(FrameError):
        unpack_body(body)


def test_legacy_flag1_blob_still_decodes():
    import struct
    import zlib

    raw = b"legacy-data" * 1000
    comp = zlib.compress(raw, 1)
    jb = json.dumps({"d": {"__blob__": 0}}).encode()
    body = (
        bytes([MAGIC])
        + struct.pack(">I", len(jb))
        + jb
        + struct.pack(">I", len(comp))
        + b"\x01"
        + comp
    )
    assert unpack_body(body) == {"d": raw}


def test_bad_blob_ref_types_raise_frameerror():
    from dgraph_tpu.conn.frame import FrameError

    for payload in (
        {"x": {"__blob__": "0"}},  # string index
        {"x": {"__blob__": 0}},  # dangling (no blobs in plain JSON)
        {"x": {"__blob__": True}},  # bool index
        {"x": {"__b64__": 7}},  # non-string b64
    ):
        with pytest.raises(FrameError):
            unpack_body(json.dumps(payload).encode())


def test_trailing_bytes_after_stream_rejected():
    import struct
    import zlib

    from dgraph_tpu.conn.frame import FrameError

    raw = b"payload" * 100
    comp = zlib.compress(raw, 1) + b"JUNKJUNK"
    payload = struct.pack(">I", len(raw)) + comp
    jb = json.dumps({"d": {"__blob__": 0}}).encode()
    body = (
        bytes([MAGIC])
        + struct.pack(">I", len(jb))
        + jb
        + struct.pack(">I", len(payload))
        + b"\x02"
        + payload
    )
    with pytest.raises(FrameError):
        unpack_body(body)


def test_typed_messages_roundtrip():
    """conn/messages.py: pb-wire-format codec roundtrips every schema
    (the typed control plane of VERDICT r4 #6)."""
    from dgraph_tpu.conn import messages as M

    kvl = M.KVList(
        kv=[
            M.KV(key=b"\x00k1", ts=7, value=b"\xff" * 300),
            M.KV(key=b"k2", ts=1 << 40, value=b""),
        ]
    )
    back = M.KVList.decode(kvl.encode())
    assert back == kvl
    h = M.HealthInfo(ok=True, node=3, group=1, is_leader=True, term=9,
                     applied=12345)
    assert M.HealthInfo.decode(h.encode()) == h
    g = M.GetResponse(found=True, ts=5, value=b"v")
    assert M.GetResponse.decode(g.encode()) == g
    p = M.ProposalResponse(ok=False, error="not leader", leader_hint=2)
    assert M.ProposalResponse.decode(p.encode()) == p
    env = M.RaftEnvelope(kind="append_req", frm=1, to=2, term=3,
                         payload=b"\x01\x02\x00raw")
    assert M.RaftEnvelope.decode(env.encode()) == env
    # unknown fields are skipped (forward compat): append an extra field
    extra = h.encode() + bytes([15 << 3 | 0, 42])
    assert M.HealthInfo.decode(extra) == h


def test_typed_message_over_rpc():
    """A typed request/response crosses the socket as a typed message."""
    from dgraph_tpu.conn import messages as M
    from dgraph_tpu.conn.rpc import RpcClient, RpcServer

    srv = RpcServer()
    srv.register(
        "echo.kv",
        lambda a: M.KVList(kv=[M.KV(key=a.key, ts=a.ts, value=b"hit")]),
    )
    srv.start()
    try:
        c = RpcClient(srv.addr)
        out = c.call("echo.kv", M.GetRequest(key=b"K", ts=7))
        assert isinstance(out, M.KVList)
        assert out.kv[0].key == b"K" and out.kv[0].ts == 7
        assert out.kv[0].value == b"hit"
    finally:
        srv.close()

"""pydgraph-style client tests against a live HTTP server."""

import pytest

from dgraph_tpu.api.http_server import HTTPServer
from dgraph_tpu.api.server import Server
from dgraph_tpu.client import DgraphClient, DgraphClientError, RetriableError


@pytest.fixture()
def live():
    engine = Server()
    srv = HTTPServer(engine, port=0).start()
    yield engine, DgraphClient(f"http://127.0.0.1:{srv.port}")
    srv.stop()


def test_full_client_flow(live):
    engine, c = live
    c.alter(schema="name: string @index(exact) @upsert .\nfriend: [uid] .")
    txn = c.txn()
    out = txn.mutate(set_rdf='_:a <name> "Ada" . _:a <friend> _:b . _:b <name> "Bo" .')
    assert "a" in out["uids"]
    txn.commit()
    res = c.query('{ q(func: eq(name, "Ada")) { name friend { name } } }')
    assert res["data"]["q"][0]["friend"][0]["name"] == "Bo"
    # json mutation + discard leaves no trace
    txn = c.txn()
    txn.mutate(set_obj={"uid": "_:x", "name": "Ghost"})
    txn.discard()
    res = c.query('{ q(func: eq(name, "Ghost")) { uid } }')
    assert res["data"]["q"] == []
    # conflict maps to RetriableError
    t1, t2 = c.txn(), c.txn()
    t1.mutate(set_rdf='<0x1> <name> "A" .')
    t2.mutate(set_rdf='<0x1> <name> "B" .')
    t1.commit()
    with pytest.raises(RetriableError):
        t2.commit()
    assert c.health()[0]["status"] == "healthy"


def test_client_acl_login_and_refresh(live):
    engine, c = live
    engine.alter("name: string @index(exact) .")
    engine.enable_acl(secret=b"c" * 32)
    with pytest.raises(DgraphClientError):
        c.query("{ q(func: has(name)) { uid } }")
    c.login("groot", "password")
    assert c.query("{ q(func: has(name)) { uid } }")["data"]["q"] == []
    # expired access token: client refreshes transparently
    c._access = c._access[:-2] + "xx"  # corrupt -> 401 -> refresh path
    assert c.query("{ q(func: has(name)) { uid } }")["data"]["q"] == []


def test_client_graphql(live):
    engine, c = live
    c.set_graphql_schema("type Item { id: ID! sku: String! @search(by: [exact]) }")
    c.graphql('mutation { addItem(input: [{sku: "X1"}]) { numUids } }')
    out = c.graphql(
        "query q($s: String!) { queryItem(filter: {sku: {eq: $s}}) { sku } }",
        variables={"s": "X1"},
    )
    assert out["data"]["queryItem"] == [{"sku": "X1"}]


def test_discard_after_failed_commit_is_noop(live):
    engine, c = live
    c.alter(schema="v: string @index(exact) @upsert .")
    t1, t2 = c.txn(), c.txn()
    t1.mutate(set_rdf='<0x5> <v> "a" .')
    t2.mutate(set_rdf='<0x5> <v> "b" .')
    t1.commit()
    with pytest.raises(RetriableError):
        t2.commit()
    t2.discard()  # must not raise (canonical retry pattern)
    assert t2.finished

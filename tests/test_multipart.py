"""Multi-part posting lists + sharded giant-operand dispatch.

Covers VERDICT r1 next-round #3: split keys (x/keys.go:512 SplitKey
semantics), rollup-time re-split (posting/list.go:1590), and routing
oversized operands through the row-sharded mesh kernels.
"""

import numpy as np
import pytest

import jax

from dgraph_tpu.posting import pl as plmod
from dgraph_tpu.posting.pl import (
    OP_SET,
    Posting,
    PostingList,
    decode_record,
    encode_delta,
    rollup_writes,
)
from dgraph_tpu.posting.rollup import rollup_key
from dgraph_tpu.storage.kv import MemKV
from dgraph_tpu.x import keys


def test_split_key_roundtrip():
    base = keys.DataKey("friend", 42)
    sk = keys.SplitKey(base, 7)
    got_base, start = keys.base_of_split(sk)
    assert got_base == base and start == 7
    pk = keys.parse_key(sk)
    assert pk.tag == keys.TAG_SPLIT
    assert pk.attr == "friend" and pk.uid == 42 and pk.split_start == 7
    # split keys sort outside the data region
    assert not sk.startswith(keys.DataPrefix("friend"))
    assert sk.startswith(keys.SplitPredicatePrefix("friend"))


def test_rollup_splits_and_reads_back(monkeypatch):
    monkeypatch.setattr(plmod, "MAX_PART_UIDS", 100)
    kv = MemKV()
    key = keys.DataKey("follows", 1)
    uids = np.arange(1, 501, dtype=np.uint64)  # 500 uids > 100 threshold
    for ts, u in enumerate(uids, start=2):
        kv.put(key, ts, encode_delta([Posting(uid=int(u), op=OP_SET)]))
    assert rollup_key(kv, key, 1000)
    # main record now holds split starts, parts live under SplitKey
    _, rec = kv.get(key, 1000)
    kind, pack, posts, splits = decode_record(rec)
    assert len(splits) == 10  # 500 / (100//2)
    for st in splits:
        assert kv.get(keys.SplitKey(key, st), 1000) is not None
    pl2 = PostingList.from_versions(key, kv.versions(key, 1000), kv=kv, read_ts=1000)
    np.testing.assert_array_equal(pl2.uids(), uids)


def test_resplit_after_growth(monkeypatch):
    monkeypatch.setattr(plmod, "MAX_PART_UIDS", 100)
    kv = MemKV()
    key = keys.DataKey("follows", 2)
    ts = 1
    for u in range(1, 201):
        ts += 1
        kv.put(key, ts, encode_delta([Posting(uid=u, op=OP_SET)]))
    assert rollup_key(kv, key, 1000)
    _, rec = kv.get(key, 1000)
    _, _, _, splits1 = decode_record(rec)
    # grow the list, rollup again: re-split with more parts, old parts gone
    for u in range(201, 501):
        ts += 1
        kv.put(key, ts, encode_delta([Posting(uid=u, op=OP_SET)]))
    assert rollup_key(kv, key, 2000)
    _, rec = kv.get(key, 2000)
    _, _, _, splits2 = decode_record(rec)
    assert len(splits2) > len(splits1)
    pl2 = PostingList.from_versions(key, kv.versions(key, 2000), kv=kv, read_ts=2000)
    np.testing.assert_array_equal(pl2.uids(), np.arange(1, 501, dtype=np.uint64))


def test_shrink_merges_back(monkeypatch):
    monkeypatch.setattr(plmod, "MAX_PART_UIDS", 100)
    kv = MemKV()
    key = keys.DataKey("follows", 3)
    ts = 1
    for u in range(1, 301):
        ts += 1
        kv.put(key, ts, encode_delta([Posting(uid=u, op=OP_SET)]))
    assert rollup_key(kv, key, 1000)
    from dgraph_tpu.posting.pl import OP_DEL

    for u in range(51, 301):  # delete down to 50 uids
        ts += 1
        kv.put(key, ts, encode_delta([Posting(uid=u, op=OP_DEL)]))
    assert rollup_key(kv, key, 2000)
    _, rec = kv.get(key, 2000)
    _, pack, _, splits = decode_record(rec)
    assert splits == []  # merged back into a single record
    pl2 = PostingList.from_versions(key, kv.versions(key, 2000), kv=kv, read_ts=2000)
    np.testing.assert_array_equal(pl2.uids(), np.arange(1, 51, dtype=np.uint64))


def test_bulk_rollup_writes_split(monkeypatch):
    monkeypatch.setattr(plmod, "MAX_PART_UIDS", 64)
    kv = MemKV()
    key = keys.DataKey("x", 9)
    uids = np.arange(10, 400, dtype=np.uint64)
    for k, ts, rec in rollup_writes(key, uids, [], 5):
        kv.put(k, ts, rec)
    pl2 = PostingList.from_versions(key, kv.versions(key, 10), kv=kv, read_ts=10)
    np.testing.assert_array_equal(pl2.uids(), uids)


def test_engine_query_over_split_list(monkeypatch):
    """A predicate whose posting list is split must answer queries
    identically (expansion + filter intersect path)."""
    monkeypatch.setattr(plmod, "MAX_PART_UIDS", 50)
    from dgraph_tpu.api.server import Server
    from dgraph_tpu.posting.rollup import rollup_all

    s = Server()
    s.alter("name: string @index(exact) .\nfollows: [uid] .")
    t = s.new_txn()
    rdf = ['<0x1> <name> "hub" .']
    for i in range(2, 202):
        rdf.append(f"<0x1> <follows> <0x{i:x}> .")
        rdf.append(f'<0x{i:x}> <name> "n{i}" .')
    t.mutate_rdf(set_rdf="\n".join(rdf), commit_now=True)
    rollup_all(s, min_deltas=1)
    # split actually happened
    _, rec = s.kv.get(keys.DataKey("follows", 1), 1 << 60)
    _, _, _, splits = decode_record(rec)
    assert len(splits) >= 2
    out = s.query('{ q(func: eq(name, "hub")) { follows { name } } }')
    assert len(out["data"]["q"][0]["follows"]) == 200
    out = s.query(
        '{ q(func: eq(name, "hub")) { c: count(follows) } }'
    )
    assert out["data"]["q"][0]["c"] == 200


@pytest.mark.skipif(len(jax.devices()) < 2, reason="needs multi-device mesh")
def test_sharded_rows_membership_4m():
    """>4M-uid operand on the 8-device virtual mesh (VERDICT r1 #3 'done'
    criterion)."""
    from dgraph_tpu.parallel import mesh as pmesh
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    rng = np.random.default_rng(0)
    n_big = (1 << 22) + 12345  # > 4M
    big = np.sort(
        rng.choice(np.arange(1, 1 << 26, dtype=np.uint32), n_big, replace=False)
    )
    mesh = pmesh.make_mesh()
    ndev = mesh.devices.size
    tile = -(-n_big // ndev)
    tile = 1 << (tile - 1).bit_length()
    pb = tile * ndev
    from dgraph_tpu.ops import setops

    Bd = jax.device_put(
        jnp.asarray(setops.pad_sorted(big, pb)), NamedSharding(mesh, P("data"))
    )
    rows = np.full((4, 64), setops.UINT32_MAX, np.uint32)
    LA = np.zeros((4,), np.int32)
    for i in range(4):
        hits = rng.choice(big, 20, replace=False)
        misses = rng.integers(1 << 26, 1 << 27, 20, dtype=np.uint32)
        r = np.unique(np.concatenate([hits, misses]))
        rows[i, : len(r)] = r
        LA[i] = len(r)
    mask = np.asarray(
        pmesh.sharded_rows_membership(mesh, jnp.asarray(rows), LA, Bd, n_big)
    )
    bigset = set(big.tolist())
    for i in range(4):
        for j in range(LA[i]):
            assert mask[i, j] == (int(rows[i, j]) in bigset)


@pytest.mark.skipif(len(jax.devices()) < 2, reason="needs multi-device mesh")
def test_dispatcher_routes_giant_b_through_mesh(monkeypatch):
    from dgraph_tpu.query import dispatch

    monkeypatch.setattr(dispatch, "_SHARD_MIN_B", 1 << 16)
    d = dispatch.SetOpDispatcher()
    rng = np.random.default_rng(1)
    big = np.unique(rng.integers(1, 1 << 24, 1 << 17, dtype=np.uint64))
    rows = [
        np.unique(
            np.concatenate(
                [
                    rng.choice(big, 50, replace=False),
                    rng.integers(1 << 24, 1 << 25, 50, dtype=np.uint64),
                ]
            )
        )
        for _ in range(3)
    ]
    got = d.run_rows_vs_one("intersect", rows, big)
    want = [np.intersect1d(r, big, assume_unique=True) for r in rows]
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g, w)
    got = d.run_rows_vs_one("difference", rows, big)
    want = [np.setdiff1d(r, big, assume_unique=True) for r in rows]
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g, w)

"""Golden conformance suite on a deterministic movie graph.

The analog of /root/reference/systest/1million + query/query0-4_test.go:
a fixed film/director/genre graph loaded once, with golden DQL->JSON
assertions across the feature surface. Any engine change that shifts these
outputs is a conformance break.
"""

import json

import pytest

from dgraph_tpu.api.server import Server
from dgraph_tpu.loaders.bulk import bulk_load_rdf

SCHEMA = """
name: string @index(term, exact, trigram) @lang .
initial_release_date: datetime @index(year) .
genre: [uid] @reverse .
director.film: [uid] @reverse @count .
starring: [uid] @reverse .
rating: float @index(float) .
running_time: int @index(int) .
"""

RDF = """
<0x10> <name> "Ridley Scott" .
<0x10> <director.film> <0x100> .
<0x10> <director.film> <0x101> .
<0x10> <director.film> <0x102> .
<0x11> <name> "Denis Villeneuve" .
<0x11> <director.film> <0x103> .
<0x11> <director.film> <0x104> .
<0x12> <name> "George Miller" .
<0x12> <director.film> <0x105> .

<0x100> <name> "Alien" .
<0x100> <initial_release_date> "1979-05-25"^^<xs:dateTime> .
<0x100> <rating> "8.5"^^<xs:float> .
<0x100> <running_time> "117"^^<xs:int> .
<0x100> <genre> <0x200> .
<0x100> <genre> <0x201> .
<0x101> <name> "Blade Runner" .
<0x101> <initial_release_date> "1982-06-25"^^<xs:dateTime> .
<0x101> <rating> "8.1"^^<xs:float> .
<0x101> <running_time> "117"^^<xs:int> .
<0x101> <genre> <0x201> .
<0x102> <name> "The Martian" .
<0x102> <initial_release_date> "2015-10-02"^^<xs:dateTime> .
<0x102> <rating> "8.0"^^<xs:float> .
<0x102> <running_time> "144"^^<xs:int> .
<0x102> <genre> <0x201> .
<0x102> <starring> <0x300> .
<0x103> <name> "Arrival" .
<0x103> <initial_release_date> "2016-11-11"^^<xs:dateTime> .
<0x103> <rating> "7.9"^^<xs:float> .
<0x103> <running_time> "116"^^<xs:int> .
<0x103> <genre> <0x201> .
<0x104> <name> "Dune" .
<0x104> <initial_release_date> "2021-10-22"^^<xs:dateTime> .
<0x104> <rating> "8.0"^^<xs:float> .
<0x104> <running_time> "155"^^<xs:int> .
<0x104> <genre> <0x201> .
<0x104> <starring> <0x301> .
<0x105> <name> "Mad Max: Fury Road"@en .
<0x105> <name> "Mad Max"@de .
<0x105> <name> "Mad Max: Fury Road" .
<0x105> <initial_release_date> "2015-05-15"^^<xs:dateTime> .
<0x105> <rating> "8.1"^^<xs:float> .
<0x105> <running_time> "120"^^<xs:int> .
<0x105> <genre> <0x200> .
<0x105> <genre> <0x202> .

<0x200> <name> "Horror" .
<0x201> <name> "Science Fiction" .
<0x202> <name> "Action" .
<0x300> <name> "Matt Damon" .
<0x301> <name> "Timothee Chalamet" .
"""

GOLDEN = [
    (
        "director filmography ordered by release",
        """{ q(func: eq(name, "Ridley Scott")) {
             name
             director.film (orderasc: initial_release_date) { name }
        } }""",
        {"q": [{"name": "Ridley Scott", "director.film": [
            {"name": "Alien"}, {"name": "Blade Runner"}, {"name": "The Martian"}]}]},
    ),
    (
        "reverse edge: films per genre with counts",
        """{ q(func: eq(name, "Horror")) {
             name
             c: count(~genre)
             ~genre (orderasc: name) { name }
        } }""",
        {"q": [{"name": "Horror", "c": 2, "~genre": [
            {"name": "Alien"}, {"name": "Mad Max: Fury Road"}]}]},
    ),
    (
        "filter tree AND/OR/NOT over ratings and years",
        """{ q(func: has(rating), orderasc: name)
             @filter(
               (ge(rating, 8.1) OR ge(initial_release_date, "2020-01-01"))
               AND NOT eq(name, "Alien")
             ) { name } }""",
        {"q": [{"name": "Blade Runner"}, {"name": "Dune"},
               {"name": "Mad Max: Fury Road"}]},
    ),
    (
        "terms + inequality filter",
        """{ q(func: anyofterms(name, "dune arrival alien"), orderasc: name)
             @filter(ge(rating, 8.0)) { name rating } }""",
        {"q": [{"name": "Alien", "rating": 8.5},
               {"name": "Dune", "rating": 8.0}]},
    ),
    (
        "year index + between",
        """{ q(func: between(initial_release_date, "2015-01-01", "2017-01-01"),
              orderasc: name) { name } }""",
        {"q": [{"name": "Arrival"}, {"name": "Mad Max: Fury Road"},
               {"name": "The Martian"}]},
    ),
    (
        "count index at root",
        """{ q(func: eq(count(director.film), 3)) { name } }""",
        {"q": [{"name": "Ridley Scott"}]},
    ),
    (
        "var propagation + aggregation",
        """{
          var(func: eq(name, "Denis Villeneuve")) {
            director.film { r as rating }
          }
          stats(func: uid(r)) { avg: avg(val(r)) mx: max(val(r)) }
        }""",
        {"stats": [{"avg": 7.95}, {"mx": 8.0}]},
    ),
    (
        "2-hop with cascade",
        """{ q(func: eq(name, "Science Fiction")) {
             ~genre @filter(has(starring)) (orderasc: name) {
               name
               starring { name }
             }
        } }""",
        {"q": [{"~genre": [
            {"name": "Dune", "starring": [{"name": "Timothee Chalamet"}]},
            {"name": "The Martian", "starring": [{"name": "Matt Damon"}]}]}]},
    ),
    (
        "regexp + trigram",
        """{ q(func: regexp(name, /Blade.*/)) { name } }""",
        {"q": [{"name": "Blade Runner"}]},
    ),
    (
        "lang preference on film titles",
        """{ q(func: eq(name@de, "Mad Max")) { name@en name@de } }""",
        {"q": [{"name@en": "Mad Max: Fury Road", "name@de": "Mad Max"}]},
    ),
    (
        "normalize flattening",
        """{ q(func: eq(name, "George Miller")) @normalize {
             d: name
             director.film { f: name genre { g: name } }
        } }""",
        {"q": [
            {"d": "George Miller", "f": "Mad Max: Fury Road", "g": "Horror"},
            {"d": "George Miller", "f": "Mad Max: Fury Road", "g": "Action"},
        ]},
    ),
    (
        "groupby running time",
        """{ q(func: eq(name, "Ridley Scott")) {
             director.film @groupby(running_time) { count(uid) }
        } }""",
        # groups order by SIZE asc then key (ref groupby.go:385 groupLess)
        {"q": [{"director.film": [{"@groupby": [
            {"running_time": 144, "count": 1},
            {"running_time": 117, "count": 2}]}]}]},
    ),
]


@pytest.fixture(scope="module")
def server():
    s = Server()
    s.alter(SCHEMA)
    bulk_load_rdf(s, RDF)
    return s


@pytest.mark.parametrize(
    "name,query,want",
    [g for g in GOLDEN if g[2] is not None],
    ids=[g[0] for g in GOLDEN if g[2] is not None],
)
def test_golden(server, name, query, want):
    got = server.query(query)["data"]
    assert got == want, f"{name}:\n got: {json.dumps(got, indent=1)}"

"""Raft-replicated Zero (ref dgraph/cmd/zero: raft-backed coordinator —
leases, oracle commit decisions, tablet assignment via consensus).
"""

import time

import pytest

from dgraph_tpu.worker.groups import DistributedCluster
from dgraph_tpu.zero.zero import TxnConflictError


@pytest.fixture()
def cluster():
    c = DistributedCluster(n_groups=2, replicas=3, replicated_zero=True)
    yield c
    c.close()


def test_leases_unique_and_monotonic(cluster):
    z = cluster.zero.zero
    seen = set()
    for _ in range(300):  # crosses TS_BLOCK boundaries
        ts = z.next_ts()
        assert ts not in seen
        seen.add(ts)
    u1 = z.assign_uids(10)
    u2 = z.assign_uids(5)
    assert u2 >= u1 + 10


def test_end_to_end_txns_through_zero_quorum(cluster):
    cluster.alter("name: string @index(exact) .")
    t = cluster.new_txn()
    t.mutate_rdf(set_rdf='<0x1> <name> "rz-alice" .', commit_now=True)
    out = cluster.query('{ q(func: eq(name, "rz-alice")) { name } }')
    assert out["data"]["q"][0]["name"] == "rz-alice"
    # tablet decisions replicated to every zero node
    states = [
        z.sm.tablets.get("name")
        for z in cluster.zero_nodes
        if z.raft.last_applied >= cluster.zero_nodes[0].raft.last_applied
    ]
    assert any(s is not None for s in states)


def test_conflicts_decided_by_state_machine(cluster):
    cluster.alter("counter: int @upsert .")
    cluster.new_txn().mutate_rdf(
        set_rdf='<0x50> <counter> "1"^^<xs:int> .', commit_now=True
    )
    t1 = cluster.new_txn()
    t2 = cluster.new_txn()
    t1.mutate_rdf(set_rdf='<0x50> <counter> "2"^^<xs:int> .')
    t2.mutate_rdf(set_rdf='<0x50> <counter> "3"^^<xs:int> .')
    t1.commit()
    with pytest.raises(TxnConflictError):
        t2.commit()
    # every caught-up replica recorded the same abort
    lead = next(z for z in cluster.zero_nodes if z.raft.is_leader())
    assert t2.start_ts in lead.sm.aborted


def test_zero_leader_failover(cluster):
    cluster.alter("name: string @index(exact) .")
    lead = next(z for z in cluster.zero_nodes if z.raft.is_leader())
    cluster.net.down.add(lead.id)
    try:
        # remaining two re-elect; leases + commits keep working
        t = cluster.new_txn()
        t.mutate_rdf(set_rdf='<0x2> <name> "rz-bob" .', commit_now=True)
        out = cluster.query('{ q(func: eq(name, "rz-bob")) { name } }')
        assert out["data"]["q"][0]["name"] == "rz-bob"
    finally:
        cluster.net.down.discard(lead.id)


def test_replicated_zero_durable_restart(tmp_path):
    d = str(tmp_path / "rz")
    c = DistributedCluster(
        n_groups=1, replicas=3, data_dir=d, replicated_zero=True
    )
    c.alter("name: string @index(exact) .")
    c.new_txn().mutate_rdf(set_rdf='_:a <name> "rz-zoe" .', commit_now=True)
    max_ts_before = c.zero.zero.max_assigned
    c.close()

    c2 = DistributedCluster(
        n_groups=1, replicas=3, data_dir=d, replicated_zero=True
    )
    try:
        out = c2.query('{ q(func: eq(name, "rz-zoe")) { name } }')
        assert out["data"]["q"][0]["name"] == "rz-zoe"
        # leases recovered through the zero raft WAL: no ts reuse
        assert c2.zero.zero.next_ts() > max_ts_before
        # tablet map recovered from consensus state, not a side file
        assert c2.zero.belongs_to("name") is not None
        c2.new_txn().mutate_rdf(
            set_rdf='_:b <name> "rz-post" .', commit_now=True
        )
        out = c2.query('{ q(func: eq(name, "rz-post")) { name } }')
        assert out["data"]["q"][0]["name"] == "rz-post"
    finally:
        c2.close()


def test_commit_verdict_decided_exactly_once():
    """A commit op re-proposed with a fresh request id (the client
    retried through another server after a lost/timed-out ack) must
    return the ORIGINAL verdict — re-running conflict detection would
    flip commit into abort and burn a timestamp."""
    from dgraph_tpu.zero.replicated import ZeroStateMachine

    sm = ZeroStateMachine()
    sm.apply(("lease_ts", 9, 1, 10))
    v1 = sm.apply(("commit", 1, 1, 5, ["ck"]))
    assert v1 == ("commit", 11)
    # duplicate via a different (proposer, req_id): same verdict, no
    # extra timestamp
    assert sm.apply(("commit", 2, 9, 5, ["ck"])) == v1
    assert sm.max_ts == 11
    # a genuinely conflicting later txn still aborts, and ITS duplicate
    # replays the same abort
    v3 = sm.apply(("commit", 1, 2, 3, ["ck"]))
    assert v3 == ("abort", 11)
    assert sm.apply(("commit", 2, 7, 3, ["ck"])) == v3
    # late duplicate commit after an explicit abort stays aborted
    sm.apply(("abort", 1, 3, 100))
    assert sm.apply(("commit", 1, 4, 100, []))[0] == "abort"
    # verdicts survive snapshot round-trips (and old 6-field snapshots
    # still load)
    import pickle

    sm2 = ZeroStateMachine()
    sm2.load(sm.dump())
    assert sm2.txn_verdicts == sm.txn_verdicts
    sm2.load(pickle.dumps((1, 1, {}, set(), {}, 1)))
    assert sm2.txn_verdicts == {}

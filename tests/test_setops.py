"""Golden tests for device sorted-set kernels.

Mirrors the reference's algo/uidlist_test.go semantics: results must equal
numpy's exact sorted-set ops for random sorted inputs, including edge cases
(empty lists, full overlap, disjoint, sentinel-valued UIDs).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from dgraph_tpu.ops import setops


def _mk(rng, n, lo=0, hi=1 << 30):
    return np.unique(rng.integers(lo, hi, size=n, dtype=np.uint64)).astype(
        np.uint32
    )


def _pow2(n):
    return max(8, 1 << (max(1, n) - 1).bit_length())


def _pad(a, size):
    return jnp.asarray(setops.pad_sorted(a, size))


CASES = [
    (0, 0),
    (1, 0),
    (0, 1),
    (10, 10),
    (10, 1000),
    (1000, 10),
    (500, 500),
    (1024, 1024),
]


@pytest.mark.parametrize("na,nb", CASES)
def test_intersect(na, nb):
    rng = np.random.default_rng(na * 1000 + nb)
    a, b = _mk(rng, na), _mk(rng, nb)
    pa, pb = _pow2(len(a)), _pow2(len(b))
    out, n = setops.intersect(_pad(a, pa), len(a), _pad(b, pb), len(b))
    got = np.asarray(out)[: int(n)]
    want = np.intersect1d(a, b, assume_unique=True)
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("na,nb", CASES)
def test_difference(na, nb):
    rng = np.random.default_rng(na * 7 + nb)
    a, b = _mk(rng, na), _mk(rng, nb)
    pa, pb = _pow2(len(a)), _pow2(len(b))
    out, n = setops.difference(_pad(a, pa), len(a), _pad(b, pb), len(b))
    got = np.asarray(out)[: int(n)]
    want = np.setdiff1d(a, b, assume_unique=True)
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("na,nb", CASES)
def test_union(na, nb):
    rng = np.random.default_rng(na * 13 + nb)
    a, b = _mk(rng, na), _mk(rng, nb)
    pa, pb = _pow2(len(a)), _pow2(len(b))
    out, n = setops.union(_pad(a, pa), len(a), _pad(b, pb), len(b))
    got = np.asarray(out)[: int(n)]
    want = np.union1d(a, b)
    np.testing.assert_array_equal(got, want)


def test_sentinel_value_is_valid_uid():
    # 0xFFFFFFFF is a legal UID: validity is judged by length, not sentinel.
    a = np.array([5, 0xFFFFFFFF], dtype=np.uint32)
    b = np.array([0xFFFFFFFF], dtype=np.uint32)
    out, n = setops.intersect(_pad(a, 8), 2, _pad(b, 8), 1)
    np.testing.assert_array_equal(np.asarray(out)[: int(n)], [0xFFFFFFFF])
    out, n = setops.union(_pad(a, 8), 2, _pad(b, 8), 1)
    np.testing.assert_array_equal(np.asarray(out)[: int(n)], [5, 0xFFFFFFFF])
    out, n = setops.difference(_pad(a, 8), 2, _pad(b, 8), 1)
    np.testing.assert_array_equal(np.asarray(out)[: int(n)], [5])


def test_merge_sorted_kway():
    rng = np.random.default_rng(0)
    lists = [_mk(rng, n) for n in (50, 200, 0, 130, 1)]
    pad = 256
    L = np.stack([setops.pad_sorted(x, pad) for x in lists])
    lens = np.array([len(x) for x in lists], np.int32)
    out, n = setops.merge_sorted(jnp.asarray(L), jnp.asarray(lens))
    want = np.unique(np.concatenate(lists))
    np.testing.assert_array_equal(np.asarray(out)[: int(n)], want)


def test_intersect_many():
    rng = np.random.default_rng(1)
    base = _mk(rng, 400, hi=1 << 12)
    lists = [base]
    for _ in range(3):
        extra = _mk(rng, 300, hi=1 << 12)
        lists.append(np.union1d(base[::2], extra))
    pad = 1024
    L = np.stack([setops.pad_sorted(x, pad) for x in lists])
    lens = np.array([len(x) for x in lists], np.int32)
    out, n = setops.intersect_many(jnp.asarray(L), jnp.asarray(lens))
    want = lists[0]
    for x in lists[1:]:
        want = np.intersect1d(want, x, assume_unique=True)
    np.testing.assert_array_equal(np.asarray(out)[: int(n)], want)


def test_batched_vmap_matches_scalar():
    rng = np.random.default_rng(2)
    import jax

    pairs = [(_mk(rng, 100), _mk(rng, 300)) for _ in range(6)]
    pa = pb = 512
    A = np.stack([setops.pad_sorted(a, pa) for a, _ in pairs])
    B = np.stack([setops.pad_sorted(b, pb) for _, b in pairs])
    LA = np.array([len(a) for a, _ in pairs], np.int32)
    LB = np.array([len(b) for _, b in pairs], np.int32)
    out, n = jax.vmap(setops.intersect)(
        jnp.asarray(A), jnp.asarray(LA), jnp.asarray(B), jnp.asarray(LB)
    )
    for i, (a, b) in enumerate(pairs):
        want = np.intersect1d(a, b, assume_unique=True)
        np.testing.assert_array_equal(np.asarray(out[i])[: int(n[i])], want)


def test_pallas_membership_interpret():
    # semantics-equal to the XLA membership path (interpret mode on CPU)
    from dgraph_tpu.ops import pallas_setops

    rng = np.random.default_rng(3)
    b = _mk(rng, 5000)
    a = np.concatenate([b[::97][:40], _mk(rng, 30, hi=1 << 29)])
    a = np.unique(a)[:100]
    A = jnp.asarray(setops.pad_sorted(a, 128))
    B = jnp.asarray(setops.pad_sorted(b, 8192))
    got = np.asarray(
        pallas_setops.membership(A, len(a), B, len(b), interpret=True)
    )
    want = np.isin(a, b)
    np.testing.assert_array_equal(got[: len(a)], want)
    assert not got[len(a) :].any()
    # sentinel uid 0xFFFFFFFF is a valid value (validity by length)
    a2 = np.array([1, 0xFFFFFFFF], np.uint32)
    b2 = np.array([0xFFFFFFFF], np.uint32)
    got = np.asarray(
        pallas_setops.membership(
            jnp.asarray(setops.pad_sorted(a2, 8)), 2,
            jnp.asarray(setops.pad_sorted(b2, 8)), 1,
            interpret=True,
        )
    )
    np.testing.assert_array_equal(got[:2], [False, True])
    # zero-valued uid in b padding must not create false hits
    a3 = np.array([0], np.uint32)
    b3 = np.array([5], np.uint32)
    got = np.asarray(
        pallas_setops.membership(
            jnp.asarray(setops.pad_sorted(a3, 8)), 1,
            jnp.asarray(setops.pad_sorted(b3, 8)), 1,
            interpret=True,
        )
    )
    assert not got[0]

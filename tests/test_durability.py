"""Durable raft + cluster recovery (VERDICT r1 next-round #5).

- raft WAL: hardstate/log persist before responses; restart-safe votes
- snapshot/compaction: snap_req catch-up for lagging peers, truncated log
- kill-all cluster restart recovering all committed data
- commit-intent journal replay (no FATAL partial commits)
"""

import os

import numpy as np
import pytest

from dgraph_tpu.raft.raft import LEADER, RaftCluster, RaftNode, InProcNetwork
from dgraph_tpu.raft.wal import RaftWal
from dgraph_tpu.worker.groups import DistributedCluster, IntentLog


# ---------------------------------------------------------------------------
# RaftWal unit behavior
# ---------------------------------------------------------------------------


def test_raft_wal_roundtrip(tmp_path):
    w = RaftWal(str(tmp_path / "n1"))
    w.save_hard(3, 2, 0, 0)
    w.append_entry(1, ("delta", [1, 2]))
    w.append_entry(2, ("delta", [3]))
    w.truncate_from(2)
    w.append_entry(3, ("delta", [4]))
    w.flush()
    w.close()
    w2 = RaftWal(str(tmp_path / "n1"))
    assert w2.load_hard() == (3, 2, 0, 0)
    si, st, entries = w2.replay_log()
    assert (si, st) == (0, 0)
    assert entries == [(1, ("delta", [1, 2])), (3, ("delta", [4]))]


def test_raft_wal_compaction_rewrite(tmp_path):
    w = RaftWal(str(tmp_path / "n2"))
    for i in range(10):
        w.append_entry(1, i)
    w.flush()
    w.rewrite_log(7, 1, [(1, 7), (1, 8), (1, 9)])
    si, st, entries = w.replay_log()
    assert si == 7 and st == 1
    assert [d for _, d in entries] == [7, 8, 9]
    w.save_snapshot(b"snapdata")
    assert w.load_snapshot() == b"snapdata"


def test_raft_wal_torn_tail(tmp_path):
    w = RaftWal(str(tmp_path / "n3"))
    w.append_entry(1, "a")
    w.flush()
    w.close()
    with open(str(tmp_path / "n3" / "log.wal"), "ab") as f:
        f.write(b"\x01\x99")  # torn record
    w2 = RaftWal(str(tmp_path / "n3"))
    _, _, entries = w2.replay_log()
    assert entries == [(1, "a")]


# ---------------------------------------------------------------------------
# Raft node durability + snapshots
# ---------------------------------------------------------------------------


def test_raft_restart_remembers_vote_and_log(tmp_path):
    # durable cluster: one WAL dir per node
    net = InProcNetwork()
    applied = {i: [] for i in (1, 2, 3)}

    def mk(i):
        net.register(i)
        return RaftNode(
            i, [1, 2, 3], net,
            lambda idx, d, _i=i: applied[_i].append(d),
            seed=i,
            wal=RaftWal(str(tmp_path / f"r{i}")),
        )

    nodes = {i: mk(i) for i in (1, 2, 3)}
    now = 0
    while not any(n.is_leader() for n in nodes.values()):
        now += 10
        for n in nodes.values():
            n.tick(now)
    leader = next(n for n in nodes.values() if n.is_leader())
    assert leader.propose(("w", 1))
    for _ in range(30):
        now += 10
        for n in nodes.values():
            n.tick(now)
    assert all(("w", 1) in a for a in applied.values())

    # "crash" node 2 and restart from its WAL: term/vote/log survive
    n2 = nodes[2]
    term_before, log_before = n2.term, [e.data for e in n2.log]
    n2.wal.close()
    net2 = InProcNetwork()
    net2.register(2)
    restarted = RaftNode(
        2, [1, 2, 3], net2, lambda idx, d: None, seed=2,
        wal=RaftWal(str(tmp_path / "r2")),
    )
    assert restarted.term == term_before
    assert [e.data for e in restarted.log] == log_before


def test_snapshot_compaction_and_lagging_catchup(tmp_path):
    kvs = {i: [] for i in (1, 2, 3)}

    def cbs(i):
        def apply(idx, d):
            kvs[i].append(d)

        return apply

    c = RaftCluster(
        3,
        apply_cbs=[cbs(1), cbs(2), cbs(3)],
    )
    # wire snapshot callbacks manually (state machine = applied list)
    import pickle

    def mk_restore(i):
        def restore(data, idx):
            kvs[i].clear()
            kvs[i].extend(pickle.loads(data))

        return restore

    for i, nd in c.nodes.items():
        nd.snapshot_cb = lambda _i=i: pickle.dumps(kvs[_i])
        nd.restore_cb = mk_restore(i)

    leader = c.elect()
    # partition node 3 away, write a bunch, compact
    dead = [i for i in c.nodes if i != leader.id][0]
    c.net.down.add(dead)
    for k in range(20):
        assert leader.propose(("set", k))
        c.pump(10, 5)
    assert c.run_until(lambda: leader.last_applied >= 20)
    leader.take_snapshot()
    assert leader.snap_index >= 20
    assert len(leader.log) <= 1
    # node 3 rejoins: needs the compacted entries -> snapshot install
    c.net.down.discard(dead)
    assert c.run_until(lambda: c.nodes[dead].snap_index >= 20, max_ms=30_000)
    assert c.run_until(lambda: kvs[dead] == kvs[leader.id], max_ms=30_000)
    # and replication continues past the snapshot
    assert leader.propose(("set", 99))
    assert c.run_until(lambda: ("set", 99) in kvs[dead])


# ---------------------------------------------------------------------------
# Durable distributed cluster
# ---------------------------------------------------------------------------


def _query_names(cluster, uid):
    out = cluster.query("{ q(func: uid(%s)) { name } }" % hex(uid))
    return [x.get("name") for x in out["data"]["q"]]


def test_cluster_kill_all_restart_recovers(tmp_path):
    d = str(tmp_path / "cluster")
    c = DistributedCluster(n_groups=2, replicas=3, data_dir=d)
    c.alter("name: string @index(exact) .\nfollows: [uid] .")
    t = c.new_txn()
    t.mutate_rdf(
        set_rdf='<0x1> <name> "alice" .\n<0x2> <name> "bob" .\n'
        "<0x1> <follows> <0x2> .",
        commit_now=True,
    )
    before = c.query('{ q(func: eq(name, "alice")) { name follows { name } } }')
    c.close()

    # full restart from disk
    c2 = DistributedCluster(n_groups=2, replicas=3, data_dir=d)
    after = c2.query('{ q(func: eq(name, "alice")) { name follows { name } } }')
    assert after == before
    assert after["data"]["q"][0]["follows"][0]["name"] == "bob"
    # leases recovered: new uids/ts don't collide
    t2 = c2.new_txn()
    uids = t2.mutate_rdf(set_rdf='_:x <name> "carol" .', commit_now=True)
    out = c2.query('{ q(func: eq(name, "carol")) { name } }')
    assert out["data"]["q"][0]["name"] == "carol"
    c2.close()


def test_intent_log_replay(tmp_path):
    path = str(tmp_path / "intents.log")
    il = IntentLog(path)
    il.append_intent(10, {1: [(b"k1", 10, b"v")], 2: [(b"k2", 10, b"v")]})
    il.append_intent(11, {1: [(b"k3", 11, b"v")]})
    il.mark_done(10)
    il.close()
    il2 = IntentLog(path)
    pending = il2.pending()
    assert list(pending) == [11]
    assert pending[11] == {1: [(b"k3", 11, b"v")]}
    il2.close()


def test_cluster_completes_interrupted_commit_on_restart(tmp_path):
    """Simulate a crash after journaling the intent but before any group
    applied: restart must complete the commit."""
    d = str(tmp_path / "c2")
    c = DistributedCluster(n_groups=2, replicas=3, data_dir=d)
    c.alter("name: string @index(exact) .")
    # forge an interrupted commit: journal an intent by hand
    from dgraph_tpu.posting.pl import OP_SET, Posting, encode_delta
    from dgraph_tpu.x import keys as xkeys

    c.zero.should_serve("name")
    gid = c.zero.belongs_to("name")
    cts = c.zero.zero.next_ts(5) + 4
    key = xkeys.DataKey("name", 0x77)
    from dgraph_tpu.types.types import TypeID, Val, to_binary

    rec = encode_delta(
        [
            Posting(
                uid=(1 << 64) - 1,
                op=OP_SET,
                value=to_binary(Val(TypeID.STRING, "ghost")),
                value_type=TypeID.STRING,
            )
        ]
    )
    c.intents.append_intent(cts, {gid: [(key, cts, rec)]})
    c.close()

    c2 = DistributedCluster(n_groups=2, replicas=3, data_dir=d)
    got = c2.query("{ q(func: uid(0x77)) { name } }")
    assert got["data"]["q"][0]["name"] == "ghost"
    # intent is now done: no pending left
    assert c2.intents.pending() == {}
    c2.close()


def test_cluster_compaction_in_engine(tmp_path):
    d = str(tmp_path / "c3")
    c = DistributedCluster(n_groups=1, replicas=3, data_dir=d, compact_every=5)
    c.alter("name: string @index(exact) .")
    for i in range(12):
        c.new_txn().mutate_rdf(
            set_rdf=f'<0x{i+1:x}> <name> "n{i}" .', commit_now=True
        )
    # leader compacted: log window bounded
    import time

    deadline = time.time() + 10
    while time.time() < deadline:
        lead = c.groups[1].leader()
        if lead is not None and lead.raft.snap_index > 0:
            break
        time.sleep(0.05)
    lead = c.groups[1].leader()
    assert lead.raft.snap_index > 0
    assert len(lead.raft.log) < 12
    out = c.query('{ q(func: eq(name, "n11")) { name } }')
    assert out["data"]["q"][0]["name"] == "n11"
    c.close()

"""Multi-device sharding tests on the virtual 8-device CPU mesh."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dgraph_tpu.parallel import mesh as pmesh


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) >= 8, "conftest must force 8 cpu devices"
    return pmesh.make_mesh(8)


def test_sharded_membership_matches(mesh8):
    rng = np.random.default_rng(0)
    a = np.unique(rng.integers(0, 1 << 30, 4096, dtype=np.uint64)).astype(np.uint32)
    b = np.unique(rng.integers(0, 1 << 30, 2048, dtype=np.uint64)).astype(np.uint32)
    pa = 4096
    A = np.full((pa,), 0xFFFFFFFF, np.uint32)
    A[: len(a)] = a
    B = np.full((2048,), 0xFFFFFFFF, np.uint32)
    B[: len(b)] = b
    sh = NamedSharding(mesh8, P("data"))
    Ad = jax.device_put(jnp.asarray(A), sh)
    mask = np.asarray(
        pmesh.sharded_membership(mesh8, Ad, len(a), jnp.asarray(B), len(b))
    )
    want = np.isin(a, b)
    np.testing.assert_array_equal(mask[: len(a)], want)
    assert not mask[len(a) :].any()

    cnt = int(
        pmesh.sharded_intersect_count(
            mesh8, Ad, len(a), jnp.asarray(B), len(b)
        )
    )
    assert cnt == int(want.sum())


def test_sharded_topk_matches(mesh8):
    rng = np.random.default_rng(1)
    n, d, k = 1024, 16, 10
    V = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal(d).astype(np.float32)
    sh = NamedSharding(mesh8, P("data"))
    Vd = jax.device_put(jnp.asarray(V), sh)
    valid = jax.device_put(jnp.ones((n,), bool), sh)
    dists, idx = pmesh.sharded_topk(mesh8, Vd, valid, jnp.asarray(q), k)
    dists, idx = np.asarray(dists), np.asarray(idx)
    want = np.argsort(((V - q[None, :]) ** 2).sum(axis=1))[:k]
    np.testing.assert_array_equal(np.sort(idx), np.sort(want))


def test_sharded_kmeans_matches_single_device(mesh8):
    rng = np.random.default_rng(2)
    n, d, c = 800, 8, 10
    X = (
        rng.standard_normal((n, d)) + rng.integers(0, 5, (n, 1)) * 3.0
    ).astype(np.float32)

    cents = pmesh.sharded_ivf_train(mesh8, X, nlist=c, iters=5)

    # single-device reference Lloyd with identical init
    rng2 = np.random.default_rng(0)
    C = X[rng2.choice(n, c, replace=False)].copy()
    for _ in range(5):
        d2 = ((X[:, None, :] - C[None, :, :]) ** 2).sum(axis=2)
        a = d2.argmin(axis=1)
        for ci in range(c):
            sel = X[a == ci]
            if len(sel):
                C[ci] = sel.mean(axis=0)
    np.testing.assert_allclose(cents, C, rtol=1e-4, atol=1e-4)

"""HTTP endpoint tests (mirrors /root/reference/dgraph/cmd/alpha http tests)."""

import json
import urllib.request

import pytest

from dgraph_tpu.api.http_server import HTTPServer
from dgraph_tpu.api.server import Server


@pytest.fixture()
def http():
    engine = Server()
    engine.alter("name: string @index(exact) .\nfriend: [uid] .")
    srv = HTTPServer(engine, port=0).start()
    yield srv
    srv.stop()


def _post(srv, path, body, ctype="application/rdf"):
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}{path}",
        data=body.encode("utf-8"),
        headers={"Content-Type": ctype},
        method="POST",
    )
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def _get(srv, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{srv.port}{path}") as r:
        return r.read()


def test_mutate_query_roundtrip(http):
    out = _post(
        http,
        "/mutate?commitNow=true",
        '{ set { _:x <name> "Neo" . } }',
    )
    assert out["data"]["code"] == "Success"
    assert "x" in out["data"]["uids"]

    res = _post(http, "/query", '{ q(func: eq(name, "Neo")) { name } }')
    assert res["data"]["q"] == [{"name": "Neo"}]
    assert "server_latency" in res["extensions"]


def test_json_mutation(http):
    out = _post(
        http,
        "/mutate?commitNow=true",
        json.dumps({"set": {"uid": "_:a", "name": "Trin"}}),
        ctype="application/json",
    )
    assert out["data"]["code"] == "Success"
    res = _post(http, "/query", '{ q(func: eq(name, "Trin")) { name } }')
    assert res["data"]["q"] == [{"name": "Trin"}]


def test_txn_begin_then_commit(http):
    out = _post(http, "/mutate", '{ set { <0x9> <name> "Tank" . } }')
    ts = out["data"]["startTs"]
    # not yet visible
    res = _post(http, "/query", '{ q(func: eq(name, "Tank")) { uid } }')
    assert res["data"]["q"] == []
    out = _post(http, f"/commit?startTs={ts}", "")
    assert out["data"]["code"] == "Success"
    res = _post(http, "/query", '{ q(func: eq(name, "Tank")) { uid } }')
    assert res["data"]["q"] == [{"uid": "0x9"}]


def test_alter_and_admin_schema(http):
    out = _post(http, "/alter", "city: string @index(term) .")
    assert out["data"]["code"] == "Success"
    body = json.loads(_get(http, "/admin/schema"))
    assert "city: string @index(term) ." in body["data"]["schema"]


def test_health_state_metrics(http):
    h = json.loads(_get(http, "/health"))
    assert h[0]["status"] == "healthy"
    st = json.loads(_get(http, "/state"))
    assert "groups" in st
    _post(http, "/query", "{ q(func: has(name)) { uid } }")
    m = _get(http, "/debug/prometheus_metrics").decode()
    assert "dgraph_tpu_num_queries" in m


def test_error_shapes(http):
    req = urllib.request.Request(
        f"http://127.0.0.1:{http.port}/query",
        data=b"{ bad query",
        method="POST",
    )
    try:
        urllib.request.urlopen(req)
        assert False, "expected HTTPError"
    except urllib.error.HTTPError as e:
        body = json.loads(e.read())
        assert body["errors"][0]["message"]


def test_geojson_value_with_braces(http):
    _post(http, "/alter", "loc: geo @index(geo) .")
    out = _post(
        http,
        "/mutate?commitNow=true",
        '{ set { <0x1> <loc> "{\\"type\\":\\"Point\\",\\"coordinates\\":[1.0,2.0]}"^^<geo:geojson> . } }',
    )
    assert out["data"]["code"] == "Success"
    res = _post(http, "/query", "{ q(func: uid(0x1)) { loc } }")
    assert res["data"]["q"][0]["loc"]["type"] == "Point"


def test_graphql_endpoint(http):
    import urllib.request as ur

    sdl = "type City { id: ID! name: String! @search(by: [exact]) }"
    req = ur.Request(
        f"http://127.0.0.1:{http.port}/admin/schema/graphql",
        data=sdl.encode(),
        method="POST",
    )
    with ur.urlopen(req) as r:
        assert json.loads(r.read())["data"]["code"] == "Success"
    out = _post(
        http,
        "/graphql",
        json.dumps(
            {"query": 'mutation { addCity(input: [{name: "Oslo"}]) { numUids } }'}
        ),
        ctype="application/json",
    )
    assert out["data"]["addCity"]["numUids"] == 1
    out = _post(
        http,
        "/graphql",
        json.dumps({"query": "query { queryCity { name } }"}),
        ctype="application/json",
    )
    assert out["data"]["queryCity"] == [{"name": "Oslo"}]


def test_admin_graphql_endpoint(http):
    """/admin serves the ops GraphQL schema (ref graphql/admin/admin.go)."""
    import json as _json

    def admin(q, variables=None):
        return _post(
            http, "/admin", _json.dumps({"query": q}),
            ctype="application/json",
        )

    out = admin("{ health { instance status uptime } }")
    assert out["data"]["health"][0]["status"] == "healthy"
    out = admin("{ state }")
    assert out["data"]["state"]["counter"] >= 0
    out = admin('mutation { draining(enable: true) { response { code } } }')
    assert out["data"]["draining"]["response"]["code"] == "Success"
    out = admin('mutation { draining(enable: false) { response { code } } }')
    assert out["data"]["draining"]["response"]["code"] == "Success"
    out = admin(
        'mutation { updateGQLSchema(input: {set: {schema: "type T { id: ID! n: String }"}}) '
        "{ gqlSchema { schema } } }"
    )
    assert not out.get("errors"), out["errors"]
    assert "type T" in out["data"]["updateGQLSchema"]["gqlSchema"]["schema"]
    out = admin("{ getGQLSchema { schema } }")
    assert "type T" in out["data"]["getGQLSchema"]["schema"]


def test_query_timeout(http):
    """?timeout= bounds query execution (ref x/limits query timeout)."""
    import urllib.error

    # an impossible budget trips immediately with a 400-class error
    try:
        _post(http, "/query?timeout=0ms", "{ q(func: has(name)) { name } }")
        raised = False
    except urllib.error.HTTPError as e:
        raised = e.code == 400
    assert raised
    # a sane budget succeeds
    out = _post(http, "/query?timeout=5s", "{ q(func: has(name)) { uid } }")
    assert "q" in out["data"]


def test_admin_namespace_mutations(http):
    """addNamespace/deleteNamespace over the admin GraphQL
    (ref edgraph/multi_tenancy.go via graphql/admin)."""
    import json as _json

    def admin(q):
        return _post(http, "/admin", _json.dumps({"query": q}),
                     ctype="application/json")

    out = admin('mutation { addNamespace(input: {password: "pw"}) { namespaceId } }')
    ns = out["data"]["addNamespace"]["namespaceId"]
    assert ns >= 1
    out = admin(
        'mutation { deleteNamespace(input: {namespaceId: %d}) { message } }' % ns
    )
    assert "Deleted" in out["data"]["deleteNamespace"]["message"]

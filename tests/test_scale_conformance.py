"""Scale-suite conformance at test size: engine answers vs goldens
derived independently from the corpus model (VERDICT r1 next-round #4 —
goldens by reasoned derivation, not hand-typed).
"""

import sys


def test_scale_suite_conformance():
    sys.path.insert(0, "/root/repo")
    from benchmarks.movie_corpus import generate
    from benchmarks.scale_suite import load, run_suite

    corpus, server, _ = load(15_000)
    res = run_suite(corpus, server, repeat=1)
    bad = {k: v for k, v in res.items() if not v["ok"]}
    assert not bad, f"conformance failures: {bad}"
    # sanity: the corpus actually exercised non-trivial sizes
    assert res["films_of_genre"]["n"] > 50
    assert res["directors_of_genre_2hop"]["n"] > 20


def test_corpus_determinism():
    from benchmarks.movie_corpus import generate

    c1, rdf1 = generate(5000, seed=7)
    c2, rdf2 = generate(5000, seed=7)
    assert rdf1 == rdf2
    assert c1.film_rating == c2.film_rating

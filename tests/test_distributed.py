"""Distributed cluster tests: sharding, replication, failover, tablet moves.

The in-proc analog of the reference's dgraphtest docker clusters
(/root/reference/dgraphtest/local_cluster.go): real Raft groups, real
tablet routing, fault injection via the network layer.
"""

import pytest

from dgraph_tpu.worker.groups import DistributedCluster

SCHEMA = """
name: string @index(exact, term) .
age: int @index(int) .
friend: [uid] @reverse .
city: string @index(exact) .
"""

RDF = """
<0x1> <name> "Alice" .
<0x1> <age> "30"^^<xs:int> .
<0x1> <city> "Oslo" .
<0x1> <friend> <0x2> .
<0x2> <name> "Bob" .
<0x2> <age> "25"^^<xs:int> .
<0x2> <city> "Pune" .
"""


@pytest.fixture()
def cluster():
    c = DistributedCluster(n_groups=2, replicas=3)
    c.alter(SCHEMA)
    yield c
    c.close()


def test_predicates_sharded_across_groups(cluster):
    tablets = cluster.zero.tablets
    groups_used = set(tablets.values())
    assert groups_used == {1, 2}


def test_mutate_and_query_across_groups(cluster):
    t = cluster.new_txn()
    t.mutate_rdf(set_rdf=RDF, commit_now=True)
    res = cluster.query(
        '{ q(func: eq(name, "Alice")) { name age city friend { name city } } }'
    )["data"]
    assert res["q"] == [
        {
            "name": "Alice",
            "age": 30,
            "city": "Oslo",
            "friend": [{"name": "Bob", "city": "Pune"}],
        }
    ]


def test_replicas_converge(cluster):
    t = cluster.new_txn()
    t.mutate_rdf(set_rdf=RDF, commit_now=True)
    import time

    # all three replicas of each group converge to identical state
    for g in cluster.groups.values():
        deadline = time.time() + 5
        while time.time() < deadline:
            states = [
                sorted(
                    (k, tuple(n.kv.versions(k, 1 << 61)))
                    for k, _, _ in n.kv.iterate(b"", 1 << 61)
                )
                for n in g.nodes
            ]
            if states[0] == states[1] == states[2] and (
                states[0] or g.id not in set(cluster.zero.tablets.values())
            ):
                break
            time.sleep(0.05)
        assert states[0] == states[1] == states[2]


def test_leader_failure_cluster_still_serves(cluster):
    t = cluster.new_txn()
    t.mutate_rdf(set_rdf=RDF, commit_now=True)
    # kill every group's leader
    for g in cluster.groups.values():
        leader = g.leader()
        cluster.kill_node(leader.id)
    cluster._wait_for_leaders(timeout=15)
    # reads and writes still work
    res = cluster.query('{ q(func: eq(name, "Bob")) { name } }')["data"]
    assert res["q"] == [{"name": "Bob"}]
    t = cluster.new_txn()
    t.mutate_rdf(set_rdf='<0x3> <name> "Carl" .', commit_now=True)
    res = cluster.query('{ q(func: eq(name, "Carl")) { uid } }')["data"]
    assert res["q"] == [{"uid": "0x3"}]


def test_txn_conflict_across_cluster(cluster):
    from dgraph_tpu.zero.zero import TxnConflictError

    cluster.schema.get("name").upsert = True
    t1 = cluster.new_txn()
    t2 = cluster.new_txn()
    t1.mutate_rdf(set_rdf='<0x9> <name> "X" .')
    t2.mutate_rdf(set_rdf='<0x9> <name> "Y" .')
    t1.commit()
    with pytest.raises(TxnConflictError):
        t2.commit()


def test_tablet_move(cluster):
    t = cluster.new_txn()
    t.mutate_rdf(set_rdf=RDF, commit_now=True)
    pred = "name"
    src = cluster.zero.belongs_to(pred)
    dst = 2 if src == 1 else 1
    cluster.move_tablet(pred, dst)
    assert cluster.zero.belongs_to(pred) == dst
    # data still fully queryable after the move
    res = cluster.query('{ q(func: eq(name, "Alice")) { name age } }')["data"]
    assert res["q"] == [{"name": "Alice", "age": 30}]
    # source group dropped the tablet
    from dgraph_tpu.x import keys

    src_kv = cluster.groups[src].any_replica().kv
    assert not list(src_kv.iterate(keys.PredicatePrefix(pred), 1 << 61))


def test_rebalance(cluster):
    # force-skew: move everything to group 1, then rebalance
    for pred in list(cluster.zero.tablets):
        if cluster.zero.belongs_to(pred) != 1:
            cluster.move_tablet(pred, 1)
    before = len([p for p, g in cluster.zero.tablets.items() if g == 1])
    cluster.rebalance()
    after = len([p for p, g in cluster.zero.tablets.items() if g == 1])
    assert after == before - 1


def test_zero_state(cluster):
    st = cluster.zero.state()
    assert len(st["members"]) == 6
    assert st["maxTxnTs"] >= 0


def test_single_replica_groups_commit():
    """replicas=1: a one-voter raft group commits on its own match alone
    (no append responses ever arrive to advance the commit index)."""
    from dgraph_tpu.worker.facade import ClusterFacade
    from dgraph_tpu.worker.groups import DistributedCluster

    c = DistributedCluster(n_groups=2, replicas=1)
    f = ClusterFacade(c)
    c.alter("name: string @index(exact) .")
    t = f.new_txn()
    t.mutate_rdf(set_rdf='<0x1> <name> "solo" .', commit_now=True)
    got = f.query('{ q(func: eq(name, "solo")) { name } }')["data"]
    assert got == {"q": [{"name": "solo"}]}

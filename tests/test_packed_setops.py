"""Randomized equivalence suite for the compressed-domain set ops.

Packed (block-skip over UidPack, ops/packed_setops.py) intersect /
difference / membership must be element-exact against the decoded path
(ops/setops.py kernels / numpy exact ops) — including 32-bit segment
boundaries, UINT32_MAX as a legal UID, empty/singleton blocks, and
adversarial block-alignment cases.
"""

import numpy as np
import pytest

from dgraph_tpu.codec import uidpack
from dgraph_tpu.ops import packed_setops as ps
from dgraph_tpu.ops import setops
from dgraph_tpu.query.dispatch import PackedOperand, SetOpDispatcher


def _rand(rng, n, hi=1 << 33):
    return np.unique(rng.integers(1, hi, size=n, dtype=np.uint64))


def _check_all(a, b):
    """Packed results (array-vs-pack and pack-vs-pack) == numpy exact."""
    pa, pb = uidpack.encode(a), uidpack.encode(b)
    want_i = np.intersect1d(a, b, assume_unique=True)
    want_d = np.setdiff1d(a, b, assume_unique=True)
    np.testing.assert_array_equal(ps.intersect_packed(a, pb), want_i)
    np.testing.assert_array_equal(ps.intersect_packed(pa, pb), want_i)
    np.testing.assert_array_equal(ps.difference_packed(a, pb), want_d)
    np.testing.assert_array_equal(ps.difference_packed(pa, pb), want_d)
    np.testing.assert_array_equal(
        ps.membership_packed(a, pb),
        np.isin(a, b, assume_unique=True),
    )


@pytest.mark.parametrize("seed", range(8))
def test_randomized_equivalence(seed):
    rng = np.random.default_rng(seed)
    na = int(rng.integers(0, 3000))
    nb = int(rng.integers(0, 50000))
    hi = int(rng.choice([1 << 20, 1 << 32, 1 << 34, 1 << 45]))
    a, b = _rand(rng, na, hi), _rand(rng, nb, hi)
    if seed % 2 and len(b):
        # force heavy overlap so results are non-trivial
        a = np.unique(
            np.concatenate([a, rng.choice(b, min(len(b), 64), replace=False)])
        )
    _check_all(a, b)


def test_selective_case_skips_blocks():
    """10-vs-1M: candidate search must decode a tiny fraction of blocks."""
    rng = np.random.default_rng(42)
    b = _rand(rng, 1_100_000, hi=1 << 31)[:1_000_000]
    a = np.sort(rng.choice(b, 10, replace=False))
    pb = uidpack.encode(b)
    ps.reset_counters()
    np.testing.assert_array_equal(ps.intersect_packed(a, pb), a)
    c = ps.counters()
    assert c["decoded_bytes"] * 50 < c["full_decode_bytes"], c


def test_segment_boundaries_and_sentinels():
    """Hi-32 boundary straddles, UINT32_MAX-valued lo words, and the
    all-ones UID are all legal and exact (codec.go:117 split rule)."""
    m = 0xFFFFFFFF
    a = np.array(
        [1, m, (1 << 32), (1 << 32) | m, (2 << 32), (1 << 64) - 1],
        np.uint64,
    )
    b = np.array(
        [m, m + 1, (1 << 32) | m, (3 << 32) | 7, (1 << 64) - 1], np.uint64
    )
    _check_all(a, b)
    _check_all(b, a)
    # and against the decoded device kernels (per-segment uint32 space)
    seg_a = uidpack.split_segments(a)
    seg_b = uidpack.split_segments(b)
    got = ps.intersect_packed(a, uidpack.encode(b))
    dev = []
    for h in sorted(set(seg_a) & set(seg_b)):
        x, y = seg_a[h], seg_b[h]
        px, py = 8, 8
        out, n = setops.intersect(
            setops.pad_sorted(x, px), len(x), setops.pad_sorted(y, py), len(y)
        )
        lo = np.asarray(out)[: int(n)]
        dev.append((np.uint64(h) << np.uint64(32)) | lo.astype(np.uint64))
    want = np.concatenate(dev) if dev else np.zeros((0,), np.uint64)
    np.testing.assert_array_equal(got, want)


def test_empty_and_singleton_blocks():
    empty = np.zeros((0,), np.uint64)
    one = np.array([7], np.uint64)
    _check_all(empty, empty)
    _check_all(one, empty)
    _check_all(empty, one)
    _check_all(one, one)
    _check_all(one, np.array([8], np.uint64))


def test_adversarial_block_alignment():
    """Exact multiples of BLOCK_SIZE, ranges that touch at block borders,
    and interleaved disjoint runs (every block overlaps, nothing matches —
    the worst case for range-based skipping must still be exact)."""
    bs = uidpack.BLOCK_SIZE
    # b = dense run; a = exactly the block-boundary elements
    b = np.arange(1, 10 * bs + 1, dtype=np.uint64)
    a = b[::bs].copy()
    _check_all(a, b)
    # interleaved evens/odds: block ranges overlap, zero matches
    evens = np.arange(0, 4 * bs, 2, dtype=np.uint64)
    odds = np.arange(1, 4 * bs, 2, dtype=np.uint64)
    _check_all(evens, odds)
    # a touches only the first/last element of each b block
    starts = b.reshape(10, bs)[:, 0]
    ends = b.reshape(10, bs)[:, -1]
    _check_all(np.unique(np.concatenate([starts, ends])), b)


def test_block_metadata():
    rng = np.random.default_rng(5)
    u = _rand(rng, 3000, hi=1 << 40)
    p = uidpack.encode(u)
    maxes = uidpack.block_maxes(p)
    assert maxes.shape == (p.nblocks,)
    # ranges are disjoint ascending and tile the uid set
    assert np.all(p.bases <= maxes)
    assert np.all(maxes[:-1] < p.bases[1:])
    # partial decode of every block == full decode
    np.testing.assert_array_equal(
        uidpack.decode_blocks(p, np.arange(p.nblocks)), u
    )
    # arbitrary subset
    idxs = np.array([0, p.nblocks - 1], np.int64)
    want = np.concatenate(
        [
            u[: int(p.counts[0])],
            u[len(u) - int(p.counts[-1]) :],
        ]
    )
    np.testing.assert_array_equal(uidpack.decode_blocks(p, idxs), want)


def test_merge_packs_multipart():
    rng = np.random.default_rng(6)
    u = _rand(rng, 5000, hi=1 << 34)
    parts = [uidpack.encode(c) for c in np.array_split(u, 7)]
    merged = uidpack.merge_packs(parts)
    np.testing.assert_array_equal(uidpack.decode(merged), u)
    assert merged.num_uids == len(u)


# ---------------------------------------------------------------------------
# Dispatcher integration: packed operands through run_chain / run_pairs.
# ---------------------------------------------------------------------------


def test_dispatcher_packed_chain_and_pairs():
    rng = np.random.default_rng(9)
    b = _rand(rng, 200_000, hi=1 << 33)
    a = np.sort(rng.choice(b, 25, replace=False))
    pop = PackedOperand(uidpack.encode(b))
    d = SetOpDispatcher()
    np.testing.assert_array_equal(
        d.run_chain("intersect", [a, pop]),
        np.intersect1d(a, b, assume_unique=True),
    )
    np.testing.assert_array_equal(
        d.run_chain("union", [a, pop]), np.union1d(a, b)
    )
    got = d.run_pairs("difference", [(a, pop)])
    np.testing.assert_array_equal(
        got[0], np.setdiff1d(a, b, assume_unique=True)
    )
    # mixed chain: two packed + one dense
    c = _rand(rng, 150_000, hi=1 << 33)
    popc = PackedOperand(uidpack.encode(c))
    want = np.intersect1d(
        np.intersect1d(a, b, assume_unique=True), c, assume_unique=True
    )
    np.testing.assert_array_equal(
        d.run_chain("intersect", [pop, a, popc]), want
    )


def test_dispatcher_packed_fallback_below_crossover():
    """A dense (ratio ~1) ARRAY x pack pair must take the full-decode
    path — the packed counters stay at zero packed ops. (Pack x pack
    pairs have no such cliff: the per-block engine keeps both sides
    compressed at every ratio — tests/test_bitmap_setops.py
    test_dispatcher_dense_pair_stays_compressed.)"""
    rng = np.random.default_rng(10)
    a = _rand(rng, 5000, hi=1 << 30)
    b = _rand(rng, 5000, hi=1 << 30)
    pop = PackedOperand(uidpack.encode(b))
    d = SetOpDispatcher()
    ps.reset_counters()
    got = d.run_pairs("intersect", [(a, pop)])
    np.testing.assert_array_equal(
        got[0], np.intersect1d(a, b, assume_unique=True)
    )
    assert ps.counters()["packed_ops"] == 0


def test_dispatcher_prefers_dense_when_decode_is_sunk():
    """Once a packed operand's full decode is memoized (on the operand /
    owning PostingList), the dispatcher must take the free dense path
    instead of re-running block-skip every query."""
    rng = np.random.default_rng(13)
    b = _rand(rng, 200_000, hi=1 << 33)
    a = np.sort(rng.choice(b, 20, replace=False))
    pop = PackedOperand(uidpack.encode(b))
    d = SetOpDispatcher()
    ps.reset_counters()
    r1 = d.run_pairs("intersect", [(a, pop)])[0]
    assert ps.counters()["packed_ops"] == 1  # cold operand: packed path
    pop._uids = b  # decode cost now sunk
    r2 = d.run_pairs("intersect", [(a, pop)])[0]
    assert ps.counters()["packed_ops"] == 1  # memoized: dense path
    np.testing.assert_array_equal(r1, r2)


def test_posting_list_block_cache_and_packed_view():
    import dgraph_tpu.posting.pl as plmod
    from dgraph_tpu.posting.lists import LocalCache
    from dgraph_tpu.posting.pl import Posting, PostingList, rollup_writes
    from dgraph_tpu.storage.kv import MemKV

    from dgraph_tpu.x import keys

    rng = np.random.default_rng(11)
    uids = _rand(rng, 5000, hi=1 << 33)
    key = keys.DataKey("friend", 1)
    kv = MemKV()
    old = plmod.MAX_PART_UIDS
    plmod.MAX_PART_UIDS = 1000  # force a multi-part split
    try:
        for k, ts, rec in rollup_writes(key, uids, [], 5):
            kv.put(k, ts, rec)
    finally:
        plmod.MAX_PART_UIDS = old
    p = PostingList.from_versions(
        key, kv.versions(key, 10), kv=kv, read_ts=10
    )
    assert len(p.part_packs) > 1
    mp = p.merged_pack()
    np.testing.assert_array_equal(uidpack.decode(mp), uids)
    idxs = np.array([0, 2, mp.nblocks - 1], np.int64)
    first = p.decode_blocks(mp, idxs)
    np.testing.assert_array_equal(first, uidpack.decode_blocks(mp, idxs))
    assert len(p._block_cache) == 3  # cached for the next traversal
    np.testing.assert_array_equal(p.decode_blocks(mp, idxs), first)
    np.testing.assert_array_equal(p.uids(), uids)

    cache = LocalCache(kv, 10)
    pop = cache.packed_operand(key)
    assert pop is not None and len(pop) == len(uids)
    # a txn-local uid delta makes the packed view stale -> refused
    cache.add_delta(key, Posting(uid=123))
    assert cache.packed_operand(key) is None
    # value-only deltas keep the uid set exact -> still packed
    cache2 = LocalCache(kv, 10)
    cache2.add_delta(key, Posting(uid=(1 << 64) - 1, value=b"v"))
    assert cache2.packed_operand(key) is not None


def test_native_bulk_load_feeds_stats(tmp_path):
    """The C++ bulk path must emit index selectivity records and the
    loader must ingest them at load finish (NOTES_NEXT_ROUND §2 gap)."""
    from dgraph_tpu import native

    if not native.NATIVE_AVAILABLE:
        pytest.skip("native toolchain unavailable")
    from dgraph_tpu.api.server import Server
    from dgraph_tpu.loaders.bulk2 import ParallelBulkLoader

    s = Server()
    s.alter("name: string @index(exact) .")
    rdf = [f'<0x{i+1:x}> <name> "n{i % 5}" .' for i in range(200)]
    ld = ParallelBulkLoader(s, workdir=str(tmp_path / "w"), workers=1)
    assert ld._native_ok()
    ld.load_text("\n".join(rdf))
    for t in range(5):
        est = s.stats.estimate("name", b"\x02" + f"n{t}".encode())
        assert est >= 40, (t, est)

"""Superflags + backup/CDC URI handlers (ref x/flags.go,
worker/backup_handler.go, worker/sink_handler.go)."""

import json
import os

import pytest

from dgraph_tpu.admin.handlers import (
    FileHandler,
    FileSink,
    HandlerError,
    backup_to_uri,
    handler_for,
    sink_for,
)
from dgraph_tpu.x.flags import SuperFlag, SuperFlagError


def test_superflag_parse_defaults_and_types():
    sf = SuperFlag(
        "backend=lsm; memtable-mb=16",
        "backend=mem; encryption-key-file=; memtable-mb=8",
    )
    assert sf.get_string("backend") == "lsm"
    assert sf.get_int("memtable-mb") == 16
    assert sf.get_string("encryption-key-file") == ""
    # underscores normalize to dashes (reference behavior)
    assert sf.get_int("memtable_mb") == 16


def test_superflag_rejects_unknown_and_bad_values():
    with pytest.raises(SuperFlagError):
        SuperFlag("bogus=1", "known=2")
    with pytest.raises(SuperFlagError):
        SuperFlag("known", "known=2")
    sf = SuperFlag("flag=notbool", "flag=")
    with pytest.raises(SuperFlagError):
        sf.get_bool("flag")


def test_file_handler_roundtrip(tmp_path):
    h = handler_for(f"file://{tmp_path}/b")
    assert isinstance(h, FileHandler)
    h.put("x.bin", b"data")
    assert h.exists("x.bin") and h.get("x.bin") == b"data"
    assert h.ls() == ["x.bin"]


def test_s3_and_kafka_gated():
    with pytest.raises(HandlerError, match="boto3"):
        handler_for("s3://bucket/prefix")
    with pytest.raises(HandlerError, match="kafka-python"):
        sink_for("kafka://broker:9092/topic")
    with pytest.raises(HandlerError, match="scheme"):
        handler_for("ftp://nope")


def test_backup_to_file_uri_and_restore(tmp_path):
    from dgraph_tpu.admin.backup import restore
    from dgraph_tpu.api.server import Server

    s = Server()
    s.alter("name: string @index(exact) .")
    s.new_txn().mutate_rdf(set_rdf='_:a <name> "bk" .', commit_now=True)
    uri = f"file://{tmp_path}/bk"
    entry = backup_to_uri(s, uri)
    assert entry["records"] >= 1

    s2 = Server()
    restore(s2, str(tmp_path / "bk"))
    out = s2.query('{ q(func: eq(name, "bk")) { name } }')
    assert out["data"]["q"][0]["name"] == "bk"


def test_file_sink_cdc(tmp_path):
    path = str(tmp_path / "cdc.ndjson")
    sink = sink_for(path)
    assert isinstance(sink, FileSink)
    sink.send(b"k", json.dumps({"e": 1}).encode())
    sink.send(b"k", json.dumps({"e": 2}).encode())
    sink.close()
    lines = open(path).read().strip().splitlines()
    assert [json.loads(l)["e"] for l in lines] == [1, 2]

"""End-to-end query tests: RDF load -> DQL -> JSON.

Mirrors the shape of /root/reference/query/query0_test.go golden assertions
on a small social graph.
"""

import json

import numpy as np
import pytest

from dgraph_tpu.api.server import Server

SCHEMA = """
name: string @index(term, exact, trigram) @lang .
age: int @index(int) .
friend: [uid] @reverse @count .
alive: bool @index(bool) .
loc: geo @index(geo) .
dob: datetime @index(year) .
nick: string .
dgraph.type: [string] @index(exact) .

type Person {
  name
  age
  friend
}
"""

RDF = """
<0x1> <name> "Michonne" .
<0x1> <age> "38"^^<xs:int> .
<0x1> <alive> "true"^^<xs:boolean> .
<0x1> <dob> "1910-01-01"^^<xs:dateTime> .
<0x1> <dgraph.type> "Person" .
<0x1> <friend> <0x17> (since=2006-01-02) .
<0x1> <friend> <0x18> .
<0x1> <friend> <0x19> .
<0x1> <friend> <0x1f> .
<0x17> <name> "Rick Grimes" .
<0x17> <age> "15"^^<xs:int> .
<0x17> <dgraph.type> "Person" .
<0x17> <friend> <0x1> .
<0x18> <name> "Glenn Rhee" .
<0x18> <age> "15"^^<xs:int> .
<0x18> <dgraph.type> "Person" .
<0x19> <name> "Daryl Dixon" .
<0x19> <age> "17"^^<xs:int> .
<0x19> <dgraph.type> "Person" .
<0x1f> <name> "Andrea" .
<0x1f> <age> "19"^^<xs:int> .
<0x1f> <dgraph.type> "Person" .
<0x1f> <friend> <0x18> .
"""


@pytest.fixture(scope="module")
def server():
    s = Server()
    s.alter(SCHEMA)
    txn = s.new_txn()
    txn.mutate_rdf(set_rdf=RDF, commit_now=True)
    return s


def test_eq_root_with_children(server):
    res = server.query(
        """
        { me(func: eq(name, "Michonne")) {
            name age alive
            friend { name }
        } }
        """
    )["data"]
    assert res == {
        "me": [
            {
                "name": "Michonne",
                "age": 38,
                "alive": True,
                "friend": [
                    {"name": "Rick Grimes"},
                    {"name": "Glenn Rhee"},
                    {"name": "Daryl Dixon"},
                    {"name": "Andrea"},
                ],
            }
        ]
    }


def test_uid_func_and_uid_leaf(server):
    res = server.query("{ me(func: uid(0x17)) { uid name } }")["data"]
    assert res == {"me": [{"uid": "0x17", "name": "Rick Grimes"}]}


def test_filter_and_or_not(server):
    res = server.query(
        """
        { me(func: eq(name, "Michonne")) {
            friend @filter(gt(age, 14) AND NOT eq(name, "Andrea")) { name }
        } }
        """
    )["data"]
    names = {o["name"] for o in res["me"][0]["friend"]}
    assert names == {"Rick Grimes", "Glenn Rhee", "Daryl Dixon"}


def test_count_and_count_uid(server):
    res = server.query(
        """
        { me(func: has(friend)) {
            name
            c: count(friend)
          }
          total(func: has(name)) { count(uid) }
        }
        """
    )["data"]
    by_name = {o["name"]: o["c"] for o in res["me"]}
    assert by_name == {"Michonne": 4, "Rick Grimes": 1, "Andrea": 1}
    assert res["total"] == [{"count": 5}]


def test_pagination_and_order(server):
    res = server.query(
        """
        { q(func: has(age), orderasc: age, first: 2) { name age } }
        """
    )["data"]
    assert [o["age"] for o in res["q"]] == [15, 15]
    res = server.query(
        """
        { q(func: has(age), orderdesc: age, first: 2, offset: 1) { name age } }
        """
    )["data"]
    assert [o["age"] for o in res["q"]] == [19, 17]


def test_between_and_ge(server):
    res = server.query("{ q(func: between(age, 16, 19)) { age } }")["data"]
    assert sorted(o["age"] for o in res["q"]) == [17, 19]
    res = server.query("{ q(func: ge(age, 19)) { age } }")["data"]
    assert sorted(o["age"] for o in res["q"]) == [19, 38]


def test_anyofterms_allofterms(server):
    res = server.query(
        '{ q(func: anyofterms(name, "rick andrea")) { name } }'
    )["data"]
    assert {o["name"] for o in res["q"]} == {"Rick Grimes", "Andrea"}
    res = server.query(
        '{ q(func: allofterms(name, "rick grimes")) { name } }'
    )["data"]
    assert {o["name"] for o in res["q"]} == {"Rick Grimes"}


def test_regexp(server):
    res = server.query('{ q(func: regexp(name, /Gle.*/)) { name } }')["data"]
    assert {o["name"] for o in res["q"]} == {"Glenn Rhee"}


def test_reverse_edge(server):
    res = server.query(
        '{ q(func: eq(name, "Glenn Rhee")) { ~friend { name } } }'
    )["data"]
    assert {o["name"] for o in res["q"][0]["~friend"]} == {"Michonne", "Andrea"}


def test_type_func_and_expand(server):
    res = server.query('{ q(func: type(Person), orderasc: name, first: 1) { name } }')[
        "data"
    ]
    assert res["q"] == [{"name": "Andrea"}]
    res = server.query('{ q(func: uid(0x18)) { expand(_all_) } }')["data"]
    assert res["q"][0]["name"] == "Glenn Rhee"
    assert res["q"][0]["age"] == 15


def test_vars_and_aggregation(server):
    res = server.query(
        """
        {
          var(func: eq(name, "Michonne")) {
            f as friend { a as age }
          }
          friends(func: uid(f), orderasc: val(a)) {
            name
            val(a)
            }
          stats(func: uid(f)) {
            m: min(val(a))
            x: max(val(a))
            s: sum(val(a))
          }
        }
        """
    )["data"]
    assert [o["name"] for o in res["friends"]] == [
        "Rick Grimes",
        "Glenn Rhee",
        "Daryl Dixon",
        "Andrea",
    ]
    stats = {}
    for o in res["stats"]:
        stats.update(o)
    assert stats == {"m": 15, "x": 19, "s": 66}


def test_cascade(server):
    res = server.query(
        "{ q(func: type(Person)) @cascade { name friend { name } } }"
    )["data"]
    names = {o["name"] for o in res["q"]}
    assert names == {"Michonne", "Rick Grimes", "Andrea"}


def test_facets(server):
    res = server.query(
        '{ q(func: uid(0x1)) { friend @facets(since) { name } } }'
    )["data"]
    # facet values ride on the child objects keyed pred|facet
    rick = [o for o in res["q"][0]["friend"] if o.get("name") == "Rick Grimes"]
    assert rick  # facet itself is on the edge; round-1 exposes child values


def test_has_at_root(server):
    res = server.query("{ q(func: has(alive)) { name } }")["data"]
    assert {o["name"] for o in res["q"]} == {"Michonne"}


def test_shortest_path(server):
    res = server.query(
        """
        {
          path as shortest(from: 0x17, to: 0x18) { friend }
          names(func: uid(path)) { name }
        }
        """
    )["data"]
    # 0x17 -> 0x1 -> 0x18 (nested reference shape)
    p0 = res["_path_"][0]
    assert p0["uid"] == "0x17"
    assert p0["friend"]["uid"] == "0x1"
    assert p0["friend"]["friend"]["uid"] == "0x18"
    assert {o["name"] for o in res["names"]} == {
        "Rick Grimes",
        "Michonne",
        "Glenn Rhee",
    }


def test_recurse(server):
    res = server.query(
        """
        { q(func: uid(0x1f)) @recurse(depth: 3) { name friend } }
        """
    )["data"]
    # 0x1f -> 0x18 (no further friends)
    assert res["q"][0]["name"] == "Andrea"
    assert res["q"][0]["friend"][0]["name"] == "Glenn Rhee"


def test_normalize(server):
    res = server.query(
        """
        { q(func: uid(0x1)) @normalize {
            n: name
            friend { fn: name }
        } }
        """
    )["data"]
    assert {o["fn"] for o in res["q"]} == {
        "Rick Grimes",
        "Glenn Rhee",
        "Daryl Dixon",
        "Andrea",
    }
    assert all(o["n"] == "Michonne" for o in res["q"])


def test_mutation_delete(server):
    s = Server()
    s.alter("name: string @index(exact) .\nfriend: [uid] .")
    t = s.new_txn()
    t.mutate_rdf(
        set_rdf='<0x1> <name> "A" .\n<0x1> <friend> <0x2> .', commit_now=True
    )
    t = s.new_txn()
    t.mutate_rdf(del_rdf="<0x1> <friend> <0x2> .", commit_now=True)
    res = s.query('{ q(func: eq(name, "A")) { name friend { uid } } }')["data"]
    assert res["q"] == [{"name": "A"}]
    # S P * delete
    t = s.new_txn()
    t.mutate_rdf(del_rdf="<0x1> <name> * .", commit_now=True)
    res = s.query('{ q(func: has(name)) { name } }')["data"]
    assert res["q"] == []


def test_blank_nodes_and_json_mutation(server):
    s = Server()
    s.alter("name: string @index(exact) .\nfriend: [uid] .")
    t = s.new_txn()
    uids = t.mutate_json(
        set_obj={
            "uid": "_:alice",
            "name": "Alice",
            "friend": [{"uid": "_:bob", "name": "Bob"}],
        },
        commit_now=True,
    )
    assert "alice" in uids and "bob" in uids
    res = s.query('{ q(func: eq(name, "Alice")) { name friend { name } } }')[
        "data"
    ]
    assert res["q"][0]["friend"][0]["name"] == "Bob"


def test_multi_key_ordering(server):
    # ages tie at 15: name breaks the tie; then desc age primary
    res = server.query(
        "{ q(func: has(age), orderasc: age, orderasc: name) { name age } }"
    )["data"]
    assert [o["name"] for o in res["q"]][:2] == ["Glenn Rhee", "Rick Grimes"]
    res = server.query(
        "{ q(func: has(age), orderdesc: age, orderasc: name, first: 3) { age } }"
    )["data"]
    assert [o["age"] for o in res["q"]] == [38, 19, 17]


def test_ignorereflex(server):
    # Michonne <-> Rick are mutual friends; @ignorereflex drops the
    # back-edge to the parent
    res = server.query(
        "{ q(func: uid(0x1)) @ignorereflex { name friend { name friend { name } } } }"
    )["data"]
    rick = [f for f in res["q"][0]["friend"] if f["name"] == "Rick Grimes"][0]
    assert "friend" not in rick or all(
        g["name"] != "Michonne" for g in rick.get("friend", [])
    )


def test_ignorereflex_path_correctness():
    # review repros: shared child reached from two parents keeps the
    # non-ancestor edge on each path; self-loops pruned without losing
    # sibling subtrees; counts agree with pruned lists
    s = Server()
    s.alter("name: string @index(exact) .\nfriend: [uid] @count .")
    t = s.new_txn()
    t.mutate_rdf(set_rdf='''
    <0xa> <name> "A" . <0xb> <name> "B" . <0xc> <name> "C" .
    <0xa> <friend> <0xc> . <0xb> <friend> <0xc> .
    <0xc> <friend> <0xa> . <0xc> <friend> <0xb> .
    <0xd> <name> "D" . <0xe> <name> "E" . <0xf> <name> "F" .
    <0xd> <friend> <0xd> . <0xd> <friend> <0xe> . <0xe> <friend> <0xf> .
    ''', commit_now=True)
    res = s.query(
        "{ q(func: uid(0xa, 0xb)) @ignorereflex { name friend { name friend { name } } } }"
    )["data"]
    by = {o["name"]: o for o in res["q"]}
    # under A, C keeps friend B; under B, C keeps friend A
    assert [g["name"] for g in by["A"]["friend"][0]["friend"]] == ["B"]
    assert [g["name"] for g in by["B"]["friend"][0]["friend"]] == ["A"]
    # self-loop pruned, sibling subtree intact
    res = s.query(
        "{ q(func: uid(0xd)) @ignorereflex { name friend { name friend { name } } } }"
    )["data"]
    d = res["q"][0]
    assert [f["name"] for f in d["friend"]] == ["E"]
    assert [g["name"] for g in d["friend"][0]["friend"]] == ["F"]
    # count matches pruned list
    res = s.query(
        "{ q(func: uid(0xa)) @ignorereflex { friend { name c: count(friend) friend { name } } } }"
    )["data"]
    c_obj = res["q"][0]["friend"][0]
    assert c_obj["c"] == len(c_obj.get("friend", []))

"""Reference golden conformance: the Dgraph query suites as oracle.

Runs every case extracted from /root/reference/query/query{0..4}_test.go,
query_facets_test.go and math_test.go (tests/ref_golden/cases.json, built by
extract_goldens.py) against the ported common_test.go fixture
(tests/ref_golden/{schema.txt,triples.rdf,triples_facets.rdf}, built by
extract_fixture.py), comparing with testify-JSONEq semantics (exact
structure; Go numbers are float64).

This replaces self-derived goldens with the reference's own answers
(VERDICT r2 missing #1). Cases the engine doesn't match yet are tracked in
known_fails.json and xfail — shrinking that file is the conformance metric
(currently 444/535 exact).
"""

import json
import os

import pytest

HERE = os.path.join(os.path.dirname(os.path.abspath(__file__)), "ref_golden")

CASES = json.load(open(os.path.join(HERE, "cases.json")))
KNOWN_FAILS = set(json.load(open(os.path.join(HERE, "known_fails.json"))))


def _canon(x):
    if isinstance(x, dict):
        return {k: _canon(v) for k, v in x.items()}
    if isinstance(x, list):
        return [_canon(v) for v in x]
    if isinstance(x, bool):
        return x
    if isinstance(x, (int, float)):
        return float(x)
    return x


def _build(facets: bool):
    from dgraph_tpu.api.server import Server

    s = Server()
    s.alter(open(os.path.join(HERE, "schema.txt")).read())
    t = s.new_txn()
    t.mutate_rdf(
        set_rdf=open(os.path.join(HERE, "triples.rdf")).read(),
        commit_now=True,
    )
    if facets:
        t = s.new_txn()
        t.mutate_rdf(
            set_rdf=open(os.path.join(HERE, "triples_facets.rdf")).read(),
            commit_now=True,
        )
    return s


@pytest.fixture(scope="module")
def base_server():
    return _build(facets=False)


@pytest.fixture(scope="module")
def facets_server():
    return _build(facets=True)


@pytest.mark.parametrize(
    "case",
    [
        pytest.param(
            c,
            marks=(
                # strict: a tracked case that starts passing XPASSes and
                # fails the suite — known_fails.json cannot go stale
                [pytest.mark.xfail(strict=True, reason="tracked gap")]
                if c["id"] in KNOWN_FAILS
                else []
            ),
        )
        for c in CASES
    ],
    ids=[c["id"] for c in CASES],
)
def test_ref_golden(case, base_server, facets_server):
    s = (
        facets_server
        if case["file"] == "query_facets_test.go"
        else base_server
    )
    got = {"data": s.query(case["query"])["data"]}
    want = json.loads(case["expected"])
    assert _canon(got) == _canon(want)

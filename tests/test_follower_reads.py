"""Resilient read plane: watermark-verified follower reads, the
health-aware replica picker, retry budgets, and hedge-pool saturation.

Unit layer: ReplicaPicker eligibility under the watermark rule (floor
gating, TTL staleness, leader never locked out), the latency EWMA and
the closed/open/half-open breaker state machine, RetryBudget accounting
through `retrying_call`, the bounded hedge-slot pool (saturated =>
sequential fallback + counter, never queue-behind-pool), full-rotation
fallback after leader + hedge both fail, leaderless follower serving,
and the `leader_only` contract (move/backup streams NEVER touch a
follower, however slow the leader is).

Cluster layer (marked `chaos`): the fixed-seed sanity slice of
tools/chaos_soak.py — leader SIGKILL mid-workload with byte-identity
and ledger checks — runs as a subprocess, wiring the soak into tier-1.
"""

import os
import subprocess
import sys
import threading
import time
import types

import pytest

from dgraph_tpu.conn.messages import HealthInfo
from dgraph_tpu.conn.retry import RetryBudget, retrying_call
from dgraph_tpu.conn.rpc import RpcError, RpcPool, RpcServer
from dgraph_tpu.utils.observe import METRICS
from dgraph_tpu.worker import remote as remote_mod
from dgraph_tpu.worker.groups import AlphaGroup, GroupLeaderlessError
from dgraph_tpu.worker.remote import (
    ReadContext,
    RemoteGroup,
    RetryBudgetExhausted,
)
from dgraph_tpu.worker.replicapick import CLOSED, OPEN, ReplicaPicker

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

A1 = ("127.0.0.1", 7001)
A2 = ("127.0.0.1", 7002)
A3 = ("127.0.0.1", 7003)

_UP = lambda a: True  # noqa: E731  — transport circuit always closed


# ---------------------------------------------------------------------------
# ReplicaPicker: watermark eligibility
# ---------------------------------------------------------------------------


def test_picker_floor_gates_followers():
    p = ReplicaPicker(1, [A1, A2, A3])
    p.note_health(A2, applied=10, is_leader=False)
    p.note_health(A3, applied=4, is_leader=False)
    s0 = METRICS.value("follower_read_stale_skips_total")
    # floor 7: A2 (applied 10) qualifies, A3 (applied 4) is provably
    # behind the read watermark and must be skipped
    plan = p.plan([A1, A2, A3], leader=A1, floor=7, healthy=_UP)
    assert A2 in plan and A3 not in plan and plan[0] == A1
    assert METRICS.value("follower_read_stale_skips_total") == s0 + 1


def test_picker_unknown_health_is_stale():
    p = ReplicaPicker(1, [A1, A2])
    # no health row at all for A2 => not eligible, even at floor 0
    plan = p.plan([A1, A2], leader=A1, floor=0, healthy=_UP)
    assert plan == [A1]


def test_picker_unknown_floor_gates_all_followers():
    # floor=None (restarted coordinator): a TTL-fresh follower claiming
    # ANY applied index is ineligible — applied >= 0 would "cover"
    # pre-restart writes this process knows nothing about
    p = ReplicaPicker(1, [A1, A2])
    p.note_health(A2, applied=1 << 40, is_leader=False)
    u0 = METRICS.value("follower_read_floor_unknown_skips_total")
    assert p.plan([A1, A2], leader=A1, floor=None, healthy=_UP) == [A1]
    assert (
        METRICS.value("follower_read_floor_unknown_skips_total") == u0 + 1
    )
    # leaderless + unknown floor: nobody may serve
    assert p.plan([A1, A2], leader=None, floor=None, healthy=_UP) == []


def test_picker_ttl_expiry_skips_follower(monkeypatch):
    monkeypatch.setenv("DGRAPH_TPU_FOLLOWER_READ_TTL_S", "0.05")
    p = ReplicaPicker(1, [A1, A2])
    p.note_health(A2, applied=10, is_leader=False)
    assert A2 in p.plan([A1, A2], leader=A1, floor=0, healthy=_UP)
    time.sleep(0.08)
    assert p.plan([A1, A2], leader=A1, floor=0, healthy=_UP) == [A1]
    assert p.applied_of(A2, ttl=0.05) is None


def test_picker_leader_only_mode_and_leaderless():
    p = ReplicaPicker(1, [A1, A2])
    p.note_health(A2, applied=10, is_leader=False)
    assert p.plan([A1, A2], leader=A1, floor=0, healthy=_UP,
                  follower_ok=False) == [A1]
    # no leader at all: verified followers still serve
    assert p.plan([A1, A2], leader=None, floor=5, healthy=_UP) == [A2]


def test_picker_ewma_orders_fast_replica_first():
    p = ReplicaPicker(1, [A1, A2, A3])
    for a in (A2, A3):
        p.note_health(a, applied=10, is_leader=False)
    for _ in range(6):
        p.observe(A2, ok=True, lat_s=0.200)
        p.observe(A3, ok=True, lat_s=0.002)
    # leaderless: candidates sort by latency score, fast follower first
    assert p.plan([A1, A2, A3], leader=None, floor=0, healthy=_UP)[0] == A3
    # unknown EWMA (the leader here) sorts FIRST — exploration beats
    # a replica with a known-bad latency
    p.note_health(A1, applied=10, is_leader=True)
    plan = p.plan([A1, A2, A3], leader=A1, floor=0, healthy=_UP)
    assert plan[0] == A1


# ---------------------------------------------------------------------------
# ReplicaPicker: circuit breaker state machine
# ---------------------------------------------------------------------------


def test_breaker_opens_probes_and_closes(monkeypatch):
    monkeypatch.setenv("DGRAPH_TPU_READ_BREAKER_ERRORS", "3")
    monkeypatch.setenv("DGRAPH_TPU_READ_BREAKER_PROBE_S", "0.05")
    p = ReplicaPicker(1, [A1, A2])
    p.note_health(A2, applied=10, is_leader=False)
    o0 = METRICS.value("read_breaker_open_total")
    p.observe(A2, ok=False)
    p.observe(A2, ok=False)
    assert p._stat(A2).state == CLOSED  # two fails: still closed
    p.observe(A2, ok=False)
    assert p._stat(A2).state == OPEN
    assert METRICS.value("read_breaker_open_total") == o0 + 1
    # freshly OPEN: skipped outright (probe window not elapsed yet)
    assert A2 not in p.plan([A1, A2], leader=A1, floor=0, healthy=_UP)
    # window elapses: appended at the END as a half-open probe
    time.sleep(0.09)
    pr0 = METRICS.value("read_breaker_probe_total")
    plan = p.plan([A1, A2], leader=A1, floor=0, healthy=_UP)
    assert plan[-1] == A2 and plan[0] == A1
    assert METRICS.value("read_breaker_probe_total") == pr0 + 1
    # the probe window was CLAIMED: an immediate second plan skips it
    assert A2 not in p.plan([A1, A2], leader=A1, floor=0, healthy=_UP)
    # a successful probe closes the breaker
    c0 = METRICS.value("read_breaker_close_total")
    p.observe(A2, ok=True, lat_s=0.01)
    assert p._stat(A2).state == CLOSED
    assert METRICS.value("read_breaker_close_total") == c0 + 1


def test_breaker_failed_probe_pushes_window_out(monkeypatch):
    monkeypatch.setenv("DGRAPH_TPU_READ_BREAKER_ERRORS", "1")
    monkeypatch.setenv("DGRAPH_TPU_READ_BREAKER_PROBE_S", "0.01")
    p = ReplicaPicker(1, [A1, A2])
    p.observe(A2, ok=False)
    assert p._stat(A2).state == OPEN
    time.sleep(0.03)  # first jittered window (5-15ms) elapses
    # the failed half-open probe re-arms a FULL window at the current
    # knob — the replica must not be probe-eligible again immediately
    monkeypatch.setenv("DGRAPH_TPU_READ_BREAKER_PROBE_S", "60.0")
    p.observe(A2, ok=False)
    assert p._stat(A2).state == OPEN
    assert p._stat(A2).next_probe_at > time.monotonic() + 1.0
    assert A2 not in p.plan([A1, A2], leader=A1, floor=0, healthy=_UP)


def test_breaker_never_locks_out_leader(monkeypatch):
    monkeypatch.setenv("DGRAPH_TPU_READ_BREAKER_ERRORS", "1")
    monkeypatch.setenv("DGRAPH_TPU_READ_BREAKER_PROBE_S", "60.0")
    p = ReplicaPicker(1, [A1])
    p.observe(A1, ok=False)
    assert p._stat(A1).state == OPEN
    # picker-level: an OPEN leader outside its probe window yields an
    # empty plan; _read_once falls back to [leader] in that case
    assert p.plan([A1], leader=A1, floor=0, healthy=_UP) == []
    # a health reply (restart recovery path) does NOT close the
    # breaker — it goes half-open, immediately probe-eligible
    p.note_health(A1, applied=3, is_leader=True)
    assert p._stat(A1).state == OPEN
    assert p.plan([A1], leader=A1, floor=0, healthy=_UP) == [A1]
    # only the successful probe read closes it
    p.observe(A1, ok=True, lat_s=0.01)
    assert p._stat(A1).state == CLOSED


def test_breaker_health_reply_goes_half_open_not_closed(monkeypatch):
    # a replica that answers health RPCs but fails data reads (sick
    # disk, overloaded read path) must STAY routed around: health
    # replies arrive every TTL/2 sweep and used to force-close the
    # breaker within a quarter second of tripping
    monkeypatch.setenv("DGRAPH_TPU_READ_BREAKER_ERRORS", "2")
    monkeypatch.setenv("DGRAPH_TPU_READ_BREAKER_PROBE_S", "60.0")
    p = ReplicaPicker(1, [A1, A2])
    p.note_health(A2, applied=10, is_leader=False)
    p.observe(A2, ok=False)
    # a health reply between failures must not reset the consecutive
    # count (the sweep would otherwise outpace any flaky data path)
    p.note_health(A2, applied=10, is_leader=False)
    p.observe(A2, ok=False)
    assert p._stat(A2).state == OPEN
    # health keeps answering: breaker stays OPEN, but becomes
    # probe-eligible (half-open) — appended LAST in the plan
    p.note_health(A2, applied=11, is_leader=False)
    assert p._stat(A2).state == OPEN
    plan = p.plan([A1, A2], leader=A1, floor=0, healthy=_UP)
    assert plan == [A1, A2]
    # the probe read fails: a full window re-arms, skip it again
    p.observe(A2, ok=False)
    assert A2 not in p.plan([A1, A2], leader=A1, floor=0, healthy=_UP)
    # the next health reply re-opens the half-open window...
    p.note_health(A2, applied=12, is_leader=False)
    assert p.plan([A1, A2], leader=A1, floor=0, healthy=_UP)[-1] == A2
    # ...and only a SUCCESSFUL read finally closes the breaker
    p.observe(A2, ok=True, lat_s=0.01)
    assert p._stat(A2).state == CLOSED


def test_breaker_disabled_with_zero_threshold(monkeypatch):
    monkeypatch.setenv("DGRAPH_TPU_READ_BREAKER_ERRORS", "0")
    p = ReplicaPicker(1, [A1, A2])
    p.note_health(A2, applied=10, is_leader=False)
    for _ in range(10):
        p.observe(A2, ok=False)
    assert p._stat(A2).state == CLOSED
    assert A2 in p.plan([A1, A2], leader=A1, floor=0, healthy=_UP)


# ---------------------------------------------------------------------------
# RetryBudget
# ---------------------------------------------------------------------------


def test_retry_budget_accounting():
    b = RetryBudget(3)
    assert b.remaining() == 3
    assert b.try_spend() and b.try_spend(2)
    assert b.remaining() == 0
    assert not b.try_spend()
    assert b.remaining() == 0  # failed spend does not go negative


def test_retrying_call_spends_budget_per_retry():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        raise TimeoutError("nope")

    b = RetryBudget(2)
    with pytest.raises(TimeoutError):
        retrying_call(flaky, retryable=(TimeoutError,), budget=b)
    # first attempt free, then exactly `budget` retries
    assert calls["n"] == 3
    assert b.remaining() == 0


def test_read_context_without_budget_never_exhausts():
    ctx = ReadContext(budget=None)
    assert all(ctx.charge() for _ in range(100))
    ctx = ReadContext(budget=RetryBudget(1))
    assert ctx.charge() and not ctx.charge()


# ---------------------------------------------------------------------------
# RemoteGroup wiring: fake replica processes over real sockets
# ---------------------------------------------------------------------------


def _replica(is_leader, node, payload=None, delay=0.0, touched=None,
             fail=False, applied=100):
    srv = RpcServer().start()
    srv.register(
        "health",
        lambda a: HealthInfo(ok=True, is_leader=is_leader, node=node,
                             group=1, applied=applied),
    )

    def get(a):
        if touched is not None:
            touched.append(node)
        if delay:
            time.sleep(delay)
        if fail:
            raise RuntimeError(f"replica {node} read failure")
        return {"who": payload}

    srv.register("kv.get", get)
    return srv


def test_leaderless_group_serves_watermark_reads():
    f1 = _replica(False, 1, "f1")
    f2 = _replica(False, 2, "f2")
    pool = RpcPool(timeout=2.0)
    try:
        g = RemoteGroup(1, [f1.addr, f2.addr], pool)
        g.note_floor(50)  # both report applied=100 >= floor
        ll0 = METRICS.value("leaderless_reads_total")
        fr0 = METRICS.value("follower_reads_total")
        ctx = ReadContext()
        out = g.read("kv.get", {}, timeout=5.0, ctx=ctx)
        assert out["who"] in ("f1", "f2")
        assert METRICS.value("leaderless_reads_total") == ll0 + 1
        assert METRICS.value("follower_reads_total") == fr0 + 1
        assert ctx.leaderless_gids == {1}
        assert ctx.follower_reads == 1
    finally:
        pool.close()
        f1.close()
        f2.close()


def test_leaderless_group_with_stale_followers_errors():
    f1 = _replica(False, 1, "f1", applied=3)
    pool = RpcPool(timeout=1.0)
    try:
        g = RemoteGroup(1, [f1.addr], pool)
        g.note_floor(50)  # follower applied=3 < floor: NOT servable
        with pytest.raises(RpcError, match="watermark-verified"):
            g.read("kv.get", {}, timeout=1.2, ctx=ReadContext())
    finally:
        pool.close()
        f1.close()


def test_restarted_coordinator_unknown_floor_refuses_followers():
    # a fresh RemoteGroup models a coordinator restarted during a
    # leaderless window: its floor is UNKNOWN, and a TTL-fresh follower
    # claiming a huge applied index must NOT serve — at floor "0" it
    # would pass the check while possibly missing pre-restart writes
    f1 = _replica(False, 1, "f1", applied=1 << 40)
    pool = RpcPool(timeout=1.0)
    try:
        g = RemoteGroup(1, [f1.addr], pool)
        assert g.read_floor() is None
        with pytest.raises(RpcError, match="floor=unknown"):
            g.read("kv.get", {}, timeout=1.2, ctx=ReadContext())
        # a completed proposal (or leader health reply) re-establishes
        # the floor and turns follower serving back on
        g.note_floor(5)
        assert g.read_floor() == 5
        out = g.read("kv.get", {}, timeout=2.0, ctx=ReadContext())
        assert out["who"] == "f1"
    finally:
        pool.close()
        f1.close()


def test_read_rotates_past_leader_and_hedge_failures():
    # satellite (a): leader fails, first hedge fails, the LAST replica
    # must still be tried — the old code gave up after two
    lead = _replica(True, 1, fail=True)
    bad = _replica(False, 2, fail=True)
    good = _replica(False, 3, "good")
    pool = RpcPool(timeout=2.0)
    try:
        g = RemoteGroup(1, [lead.addr, bad.addr, good.addr], pool)
        out = g.read("kv.get", {}, hedge_after=0.02, timeout=8.0,
                     ctx=ReadContext())
        assert out["who"] == "good"
    finally:
        pool.close()
        for s in (lead, bad, good):
            s.close()


def test_leader_only_never_touches_follower():
    # satellite (c): move/backup streams pin to the leader — a SLOW
    # leader must not tempt the hedge onto a follower
    touched = []
    lead = _replica(True, 1, "leader", delay=0.25, touched=touched)
    fast = _replica(False, 2, "follower", touched=touched)
    pool = RpcPool(timeout=5.0)
    try:
        g = RemoteGroup(1, [lead.addr, fast.addr], pool)
        out = g.read("kv.get", {}, hedge_after=0.03, timeout=8.0,
                     leader_only=True, ctx=ReadContext())
        assert out["who"] == "leader"
        assert touched == [1]  # the follower handler NEVER ran
    finally:
        pool.close()
        lead.close()
        fast.close()


def test_leader_only_without_leader_raises():
    f1 = _replica(False, 1, "f1")
    pool = RpcPool(timeout=1.0)
    try:
        g = RemoteGroup(1, [f1.addr], pool)
        with pytest.raises(RpcError, match="leader-only"):
            g.read("kv.get", {}, timeout=1.2, leader_only=True)
    finally:
        pool.close()
        f1.close()


def test_read_budget_exhaustion_is_retryable_503_shape():
    # a live leader whose reads always fail: each outer retry spends a
    # budget token, and the dry budget surfaces as the retryable error
    sick = _replica(True, 1, fail=True)
    pool = RpcPool(timeout=2.0)
    try:
        g = RemoteGroup(1, [sick.addr], pool)
        e0 = METRICS.value("read_retry_budget_exhausted_total")
        ctx = ReadContext(budget=RetryBudget(1))
        with pytest.raises(RetryBudgetExhausted) as ei:
            g.read("kv.get", {}, timeout=10.0, ctx=ctx)
        assert ei.value.retryable is True
        assert ei.value.code == "retry_budget_exhausted"
        assert METRICS.value("read_retry_budget_exhausted_total") > e0
    finally:
        pool.close()
        sick.close()


def test_hedge_saturated_pool_skips_hedge_and_still_answers():
    # satellite (b): drain every hedge slot, the read must fall back to
    # the calling thread (sequential rotation) instead of queueing
    lead = _replica(True, 1, "leader", delay=0.05)
    fast = _replica(False, 2, "follower")
    pool = RpcPool(timeout=2.0)
    taken = 0
    try:
        while remote_mod._HEDGE_SLOTS.acquire(blocking=False):
            taken += 1
        assert taken == remote_mod._HEDGE_WORKERS
        s0 = METRICS.value("hedge_skipped_saturated_total")
        g = RemoteGroup(1, [lead.addr, fast.addr], pool)
        out = g.read("kv.get", {}, hedge_after=0.01, timeout=8.0,
                     ctx=ReadContext())
        assert out["who"] in ("leader", "follower")
        assert METRICS.value("hedge_skipped_saturated_total") > s0
    finally:
        for _ in range(taken):
            remote_mod._HEDGE_SLOTS.release()
        pool.close()
        lead.close()
        fast.close()


def test_hedge_wins_not_counted_for_failure_rotations():
    # the primary fails fast and the NEXT candidate answers — no hedge
    # timer ever fired, so hedge_wins must not move (it measures hedge
    # effectiveness: hedge_wins <= hedge_fired_total)
    lead = _replica(True, 1, fail=True)
    good = _replica(False, 2, "good")
    pool = RpcPool(timeout=2.0)
    try:
        g = RemoteGroup(1, [lead.addr, good.addr], pool)
        w0 = METRICS.value("hedge_wins")
        f0 = METRICS.value("hedge_fired_total")
        out = g.read("kv.get", {}, hedge_after=30.0, timeout=8.0,
                     ctx=ReadContext())
        assert out["who"] == "good"
        assert METRICS.value("hedge_fired_total") == f0
        assert METRICS.value("hedge_wins") == w0
    finally:
        pool.close()
        lead.close()
        good.close()


def test_hedge_wins_counted_when_timer_hedge_wins():
    # slow-but-healthy leader, fast follower: the hedge timer fires and
    # the hedge wins the race — exactly what hedge_wins measures
    lead = _replica(True, 1, "leader", delay=0.5)
    fast = _replica(False, 2, "fast")
    pool = RpcPool(timeout=5.0)
    try:
        g = RemoteGroup(1, [lead.addr, fast.addr], pool)
        w0 = METRICS.value("hedge_wins")
        f0 = METRICS.value("hedge_fired_total")
        out = g.read("kv.get", {}, hedge_after=0.03, timeout=8.0,
                     ctx=ReadContext())
        assert out["who"] == "fast"
        assert METRICS.value("hedge_fired_total") == f0 + 1
        assert METRICS.value("hedge_wins") == w0 + 1
    finally:
        pool.close()
        lead.close()
        fast.close()


# ---------------------------------------------------------------------------
# in-proc plane (AlphaGroup.read_replica): same stale-never-serves rule
# ---------------------------------------------------------------------------


def _stub_node(nid, applied, is_leader=False, term=1):
    return types.SimpleNamespace(
        id=nid,
        applied_index=applied,
        raft=types.SimpleNamespace(
            is_leader=lambda lead=is_leader: lead, term=term
        ),
    )


def _stub_group(nodes, down=()):
    g = AlphaGroup.__new__(AlphaGroup)
    g.id = 1
    g.net = types.SimpleNamespace(down=set(down))
    g.nodes = list(nodes)
    g.read_floor = 0
    g.floor_known = False
    return g


def test_inproc_leader_serve_establishes_floor_then_follower_serves():
    lead = _stub_node(1, applied=10, is_leader=True)
    fol = _stub_node(2, applied=10)
    g = _stub_group([lead, fol])
    # a leader-served read refreshes the floor (mirrors the remote
    # plane's leader health replies)
    assert g.read_replica() is lead
    assert g.floor_known and g.read_floor == 10
    # leaderless with a covering replica: serves, counted as a
    # follower + leaderless read
    g.net.down.add(1)
    fr0 = METRICS.value("follower_reads_total")
    ll0 = METRICS.value("leaderless_reads_total")
    assert g.read_replica() is fol
    assert METRICS.value("follower_reads_total") == fr0 + 1
    assert METRICS.value("leaderless_reads_total") == ll0 + 1


def test_inproc_read_replica_refuses_stale_and_unknown(monkeypatch):
    # behind the floor: refuse instead of silently serving stale bytes
    g = _stub_group(
        [_stub_node(1, applied=10, is_leader=True), _stub_node(2, applied=4)],
        down={1},
    )
    g.read_floor, g.floor_known = 7, True
    s0 = METRICS.value("follower_read_stale_skips_total")
    with pytest.raises(GroupLeaderlessError, match="floor=7"):
        g.read_replica()
    assert METRICS.value("follower_read_stale_skips_total") == s0 + 1
    # unknown floor: refuse even a caught-up-looking replica
    g2 = _stub_group([_stub_node(2, applied=1 << 40)])
    with pytest.raises(GroupLeaderlessError, match="floor=unknown"):
        g2.read_replica()
    # FOLLOWER_READS=0: strict leader-only — leaderless raises
    monkeypatch.setenv("DGRAPH_TPU_FOLLOWER_READS", "0")
    g3 = _stub_group(
        [_stub_node(1, applied=10, is_leader=True), _stub_node(2, applied=99)],
        down={1},
    )
    g3.read_floor, g3.floor_known = 5, True
    with pytest.raises(GroupLeaderlessError):
        g3.read_replica()


def test_follower_reads_flag_off_is_leader_first_legacy(monkeypatch):
    monkeypatch.setenv("DGRAPH_TPU_FOLLOWER_READS", "0")
    lead = _replica(True, 1, "leader")
    fast = _replica(False, 2, "follower")
    pool = RpcPool(timeout=2.0)
    try:
        g = RemoteGroup(1, [lead.addr, fast.addr], pool)
        fr0 = METRICS.value("follower_reads_total")
        out = g.read("kv.get", {}, timeout=5.0)
        assert out["who"] == "leader"
        assert METRICS.value("follower_reads_total") == fr0
    finally:
        pool.close()
        lead.close()
        fast.close()


# ---------------------------------------------------------------------------
# cluster chaos: the soak's fixed-seed sanity slice in tier-1
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_chaos_soak_sanity_slice():
    """tools/chaos_soak.py --sanity: ProcCluster bank + query mix with
    the group leader SIGKILLed mid-workload; asserts byte-identity of
    follower-served responses against a leader-routed control replay,
    ledger exactness, bounded availability gap, and that the kill
    window actually served follower/leaderless reads."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos_soak.py"),
         "--sanity"],
        capture_output=True, text=True, timeout=180, env=env, cwd=REPO,
    )
    assert out.returncode == 0, (
        f"chaos soak sanity failed:\n{out.stdout}\n{out.stderr}"
    )
    assert "chaos_soak: PASS" in out.stdout

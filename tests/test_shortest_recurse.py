"""Golden tests for weighted shortest paths and multi-predicate @recurse.

Semantics mirror /root/reference/query/shortest.go (facet edge costs,
numpaths, minweight/maxweight) and query/recurse.go:19 (ALL uid predicates
recurse, shared seen set).
"""

import pytest

from dgraph_tpu.api.server import Server

SCHEMA = """
name: string @index(exact) .
connects: [uid] @reverse .
rail: [uid] .
follow: [uid] .
"""

# weighted graph (facet w):
#   A(0x1) -2-> B(0x2) -2-> D(0x4)
#   A(0x1) -5-> C(0x3) -1-> D(0x4)
#   A(0x1) -10-> D(0x4)
RDF = """
<0x1> <name> "A" .
<0x2> <name> "B" .
<0x3> <name> "C" .
<0x4> <name> "D" .
<0x1> <connects> <0x2> (w=2) .
<0x1> <connects> <0x3> (w=5) .
<0x1> <connects> <0x4> (w=10) .
<0x2> <connects> <0x4> (w=2) .
<0x3> <connects> <0x4> (w=1) .
"""

# two-relation graph for multi-pred recurse:
#   1 -rail-> 2 ; 1 -follow-> 3 ; 2 -follow-> 4 ; 3 -rail-> 5
RECURSE_RDF = """
<0x11> <name> "n1" .
<0x12> <name> "n2" .
<0x13> <name> "n3" .
<0x14> <name> "n4" .
<0x15> <name> "n5" .
<0x11> <rail> <0x12> .
<0x11> <follow> <0x13> .
<0x12> <follow> <0x14> .
<0x13> <rail> <0x15> .
"""


@pytest.fixture(scope="module")
def server():
    s = Server()
    s.alter(SCHEMA)
    t = s.new_txn()
    t.mutate_rdf(set_rdf=RDF + RECURSE_RDF, commit_now=True)
    return s


def _path_uids(entry):
    # nested reference shape: {"uid": A, "<pred>": {"uid": B, ...}}
    out = []
    cur = entry
    while isinstance(cur, dict):
        out.append(cur["uid"])
        nxt = None
        for k, v in cur.items():
            if k not in ("uid", "_weight_") and "|" not in k and isinstance(
                v, dict
            ):
                nxt = v
        cur = nxt
    return out


def test_weighted_shortest_uses_facet_costs(server):
    out = server.query(
        """{
          path as shortest(from: 0x1, to: 0x4) {
            connects @facets(w)
          }
          path(func: uid(path)) { name }
        }"""
    )
    # cheapest route is A->B->D at cost 4 (not the 1-hop cost-10 edge)
    paths = out["data"]["_path_"]
    assert _path_uids(paths[0]) == ["0x1", "0x2", "0x4"]
    assert paths[0]["_weight_"] == 4.0
    names = [n["name"] for n in out["data"]["path"]]
    assert names == ["A", "B", "D"]


def test_numpaths_orders_by_cost(server):
    out = server.query(
        """{
          shortest(from: 0x1, to: 0x4, numpaths: 3) {
            connects @facets(w)
          }
        }"""
    )
    paths = out["data"]["_path_"]
    assert [p["_weight_"] for p in paths] == [4.0, 6.0, 10.0]
    assert _path_uids(paths[1]) == ["0x1", "0x3", "0x4"]
    assert _path_uids(paths[2]) == ["0x1", "0x4"]


def test_min_max_weight_bounds(server):
    out = server.query(
        """{
          shortest(from: 0x1, to: 0x4, numpaths: 3, minweight: 5, maxweight: 8) {
            connects @facets(w)
          }
        }"""
    )
    paths = out["data"]["_path_"]
    assert [p["_weight_"] for p in paths] == [6.0]


def test_unweighted_shortest_hop_count(server):
    out = server.query(
        """{
          shortest(from: 0x1, to: 0x4) { connects }
        }"""
    )
    paths = out["data"]["_path_"]
    assert _path_uids(paths[0]) == ["0x1", "0x4"]
    assert paths[0]["_weight_"] == 1.0


def test_recurse_expands_all_uid_preds(server):
    """Both rail and follow must recurse: n4 is only reachable via
    rail(1->2) then follow(2->4); n5 only via follow(1->3) then rail."""
    out = server.query(
        """{
          q(func: uid(0x11)) @recurse(depth: 4) {
            name
            rail
            follow
          }
        }"""
    )
    q = out["data"]["q"][0]
    rail_child = q["rail"][0]
    assert rail_child["name"] == "n2"
    assert rail_child["follow"][0]["name"] == "n4"
    follow_child = q["follow"][0]
    assert follow_child["name"] == "n3"
    assert follow_child["rail"][0]["name"] == "n5"


def test_shortest_with_node_filter(server):
    """The path predicate's @filter prunes intermediate nodes
    (ref shortest.go intermediate filtering); the destination always
    completes a path."""
    # block B (0x2): the only cheap route A->B->D is cut off by the
    # filter, so the path must go A->C->D (cost 6) or direct (10)
    out = server.query(
        """{
          shortest(from: 0x1, to: 0x4) {
            connects @filter(NOT uid(0x2)) @facets(w)
          }
        }"""
    )
    paths = out["data"]["_path_"]
    assert _path_uids(paths[0]) == ["0x1", "0x3", "0x4"]
    assert paths[0]["_weight_"] == 6.0

"""MemKV: MVCC versions, prefix iteration, WAL durability."""

import os

from dgraph_tpu.storage.kv import MemKV, open_kv


def test_put_get_mvcc():
    kv = MemKV()
    kv.put(b"k1", 5, b"v5")
    kv.put(b"k1", 10, b"v10")
    assert kv.get(b"k1", 4) is None
    assert kv.get(b"k1", 5) == (5, b"v5")
    assert kv.get(b"k1", 7) == (5, b"v5")
    assert kv.get(b"k1", 100) == (10, b"v10")


def test_versions_newest_first():
    kv = MemKV()
    for ts in (3, 7, 9):
        kv.put(b"k", ts, f"v{ts}".encode())
    assert kv.versions(b"k", 8) == [(7, b"v7"), (3, b"v3")]
    assert kv.versions(b"k", 100)[0] == (9, b"v9")


def test_iterate_prefix():
    kv = MemKV()
    kv.put(b"a/1", 1, b"x")
    kv.put(b"a/2", 1, b"y")
    kv.put(b"b/1", 1, b"z")
    got = list(kv.iterate(b"a/", 10))
    assert [k for k, _, _ in got] == [b"a/1", b"a/2"]


def test_out_of_order_ts_insert():
    kv = MemKV()
    kv.put(b"k", 10, b"v10")
    kv.put(b"k", 5, b"v5")  # late arrival of older version
    assert kv.get(b"k", 7) == (5, b"v5")
    assert kv.get(b"k", 10) == (10, b"v10")


def test_delete_below_and_drop_prefix():
    kv = MemKV()
    for ts in (1, 2, 3):
        kv.put(b"k", ts, b"v%d" % ts)
    kv.delete_below(b"k", 2)
    assert kv.get(b"k", 1) is None
    assert kv.get(b"k", 3) == (3, b"v3")
    kv.put(b"p/x", 1, b"1")
    kv.drop_prefix(b"p/")
    assert kv.get(b"p/x", 10) is None


def test_wal_replay(tmp_path):
    path = str(tmp_path / "store")
    kv = open_kv(path)
    kv.put(b"k1", 1, b"a")
    kv.put(b"k2", 2, b"b")
    kv.close()
    kv2 = open_kv(path)
    assert kv2.get(b"k1", 10) == (1, b"a")
    assert kv2.get(b"k2", 10) == (2, b"b")
    kv2.close()


def test_wal_torn_tail(tmp_path):
    path = str(tmp_path / "store")
    kv = open_kv(path)
    kv.put(b"k1", 1, b"a")
    kv.close()
    # append garbage partial record
    with open(os.path.join(path, "wal.log"), "ab") as f:
        f.write(b"\x10\x00\x00")
    kv2 = open_kv(path)
    assert kv2.get(b"k1", 10) == (1, b"a")
    kv2.close()

"""Device cache + batched chain dispatch + round-1 advisory fixes.

Covers VERDICT r1 next-round #2 (device-resident pack cache, true level
batching) and the ADVICE r1 findings (reindex aggregation, commit
visibility barrier, oracle GC, corrupt-record validation).
"""

import numpy as np
import pytest

from dgraph_tpu.api.server import Server
from dgraph_tpu.query import dispatch
from dgraph_tpu.query.dispatch import DISPATCHER, DeviceCache
from dgraph_tpu.zero.zero import ZeroLite


def _mk_sorted(rng, n, lim=1 << 40):
    return np.unique(rng.integers(1, lim, n, dtype=np.uint64))


def test_run_chain_intersect_matches_numpy():
    rng = np.random.default_rng(7)
    parts = [_mk_sorted(rng, 5000, 1 << 20) for _ in range(4)]
    want = parts[0]
    for p in parts[1:]:
        want = np.intersect1d(want, p, assume_unique=True)
    got = DISPATCHER.run_chain("intersect", parts)
    np.testing.assert_array_equal(got, want)


def test_run_chain_union_matches_numpy():
    rng = np.random.default_rng(8)
    parts = [_mk_sorted(rng, 3000, 1 << 20) for _ in range(5)]
    want = parts[0]
    for p in parts[1:]:
        want = np.union1d(want, p)
    got = DISPATCHER.run_chain("union", parts)
    np.testing.assert_array_equal(got, want)


def test_run_chain_small_host_path():
    a = np.array([1, 2, 3, 9], np.uint64)
    b = np.array([2, 3, 4], np.uint64)
    c = np.array([3, 2], np.uint64)  # unsorted tiny -> host path sorts? no:
    c.sort()
    np.testing.assert_array_equal(
        DISPATCHER.run_chain("intersect", [a, b, c]), [2, 3]
    )
    np.testing.assert_array_equal(DISPATCHER.run_chain("intersect", []), [])
    np.testing.assert_array_equal(DISPATCHER.run_chain("union", [a]), a)


def test_device_cache_hit_and_invalidate(monkeypatch):
    monkeypatch.setattr(dispatch, "_DEVICE_MIN_TOTAL", 1)
    monkeypatch.setattr(dispatch, "_FORCE_DEVICE", True)
    d = dispatch.SetOpDispatcher()
    rng = np.random.default_rng(3)
    rows = [_mk_sorted(rng, 200, 1 << 20) for _ in range(8)]
    toks = [(b"k%d" % i, 7) for i in range(8)]
    b = _mk_sorted(rng, 1000, 1 << 20)

    r1 = d.run_rows_vs_one("intersect", rows, b, row_tokens=toks, b_token=(b"big", 3))
    h0 = d.device_cache.hits
    r2 = d.run_rows_vs_one("intersect", rows, b, row_tokens=toks, b_token=(b"big", 3))
    assert d.device_cache.hits >= h0 + 2  # stacked rows + b both reused
    for x, y in zip(r1, r2):
        np.testing.assert_array_equal(x, y)
    # commit invalidation by key drops entries referencing it
    d.device_cache.invalidate([b"k3"])
    n_before = d.device_cache.stats()["entries"]
    r3 = d.run_rows_vs_one("intersect", rows, b, row_tokens=toks, b_token=(b"big", 3))
    for x, y in zip(r1, r3):
        np.testing.assert_array_equal(x, y)


def test_device_cache_lru_bound():
    c = DeviceCache(max_bytes=1000)
    for i in range(10):
        c.put(("t", i), [b"k%d" % i], ("arr",), 300)
    assert c.stats()["bytes"] <= 1000


def test_reindex_aggregates_shared_tokens():
    """ADVICE r1 high: alter() adding an index on a predicate where two
    entities share a value must index BOTH uids."""
    s = Server()
    s.alter(schema_text="name: string .")
    t = s.new_txn()
    t.mutate_rdf(set_rdf='_:a <name> "bob" .\n_:b <name> "bob" .', commit_now=True)
    s.alter(schema_text="name: string @index(exact) .")
    out = s.query('{ q(func: eq(name, "bob")) { count(uid) } }')
    assert out["data"]["q"][0]["count"] == 2


def test_zero_conflict_gc_bounded():
    z = ZeroLite()
    # overlapping registered txns: GC purges entries below the active floor
    for i in range(200):
        s1 = z.begin_txn()
        s2 = z.begin_txn()  # keeps _active non-empty at s1's commit
        z.commit(s1, [i])
        z.abort(s2)
    assert len(z._commits) < 200
    assert len(z._aborted) < 200


def test_read_ts_waits_for_applied():
    z = ZeroLite()
    s = z.begin_txn()
    cts = z.commit(s, [1], track=True)
    import threading, time

    got = []
    th = threading.Thread(target=lambda: got.append(z.read_ts()))
    th.start()
    time.sleep(0.05)
    assert not got  # reader parked until applied()
    z.applied(cts)
    th.join(timeout=5)
    assert got and got[0] > cts


def test_corrupt_record_raises():
    from dgraph_tpu.posting.pl import (
        CorruptRecordError,
        OP_SET,
        Posting,
        decode_record,
        encode_delta,
    )

    rec = encode_delta([Posting(uid=5, op=OP_SET)])
    decode_record(rec)  # sanity
    with pytest.raises(CorruptRecordError):
        decode_record(rec[: len(rec) - 3])
    with pytest.raises(CorruptRecordError):
        decode_record(b"\x07\x01\x00\x00\x00")


def test_cached_operands_transfer_zero_bytes_on_reuse(monkeypatch):
    """VERDICT r4 #2: with version tokens present, a repeat dispatch of
    the same operands must perform ZERO new host->device transfers —
    the padded uploads are HBM-resident in the DeviceCache."""
    import jax.numpy as jnp_mod

    rng = np.random.default_rng(11)
    rows = [_mk_sorted(rng, 4000, 1 << 20) for _ in range(8)]
    b = _mk_sorted(rng, 200_000, 1 << 20)
    row_tokens = [((b"rk%d" % i), 7) for i in range(len(rows))]
    b_token = (b"bk", 7)

    d = dispatch.SetOpDispatcher()
    monkeypatch.setattr(dispatch, "_DEVICE_MIN_TOTAL", 1)
    monkeypatch.setattr(dispatch, "_FORCE_DEVICE", True)

    transfers = {"n": 0}
    real_asarray = jnp_mod.asarray
    real_put = dispatch.jax.device_put

    def count_asarray(x, *a, **k):
        if isinstance(x, np.ndarray) and x.size > 16:
            transfers["n"] += 1
        return real_asarray(x, *a, **k)

    def count_put(x, *a, **k):
        transfers["n"] += 1
        return real_put(x, *a, **k)

    monkeypatch.setattr(dispatch.jnp, "asarray", count_asarray)
    monkeypatch.setattr(dispatch.jax, "device_put", count_put)

    want = [np.intersect1d(r, b, assume_unique=True) for r in rows]
    got = d.run_rows_vs_one(
        "intersect", rows, b, row_tokens=row_tokens, b_token=b_token
    )
    for g, w in zip(got, want):
        np.testing.assert_array_equal(np.asarray(g, np.uint64), w)
    warm = transfers["n"]
    assert warm > 0  # first call does upload

    transfers["n"] = 0
    got2 = d.run_rows_vs_one(
        "intersect", rows, b, row_tokens=row_tokens, b_token=b_token
    )
    for g, w in zip(got2, want):
        np.testing.assert_array_equal(np.asarray(g, np.uint64), w)
    assert transfers["n"] == 0, (
        f"cached operands re-uploaded: {transfers['n']} transfers"
    )

"""Serving front: cross-query micro-batching, plan cache, admission.

Byte-equality is the batcher's contract (the same one the worker pool
holds in test_parallel_exec.py): `DGRAPH_TPU_BATCH_WINDOW_US` is a pure
performance knob — the DQL golden smoke subset must serialize
identically at window 0 (the true off switch: the executor never sees
a batcher) and window 200, solo and under real cross-query
concurrency. Plan caching must keep correctness under concurrent
mutation (commit-epoch invalidation: no stale result ever), and
admission must shed with a retryable too_many_requests past the
in-flight budget and degrade — bounded, marked, partial — under a
seeded fault plan instead of queueing without bound.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from dgraph_tpu.utils.observe import METRICS

HERE = os.path.join(os.path.dirname(os.path.abspath(__file__)), "ref_golden")
CASES = json.load(open(os.path.join(HERE, "cases.json")))
SMOKE_CASES = CASES[::9]


@pytest.fixture(scope="module")
def golden_server():
    from dgraph_tpu.api.server import Server

    s = Server()
    s.alter(open(os.path.join(HERE, "schema.txt")).read())
    t = s.new_txn()
    t.mutate_rdf(
        set_rdf=open(os.path.join(HERE, "triples.rdf")).read(),
        commit_now=True,
    )
    t = s.new_txn()
    t.mutate_rdf(
        set_rdf=open(os.path.join(HERE, "triples_facets.rdf")).read(),
        commit_now=True,
    )
    return s


def _query_windows(server, q, windows=("0", "200")):
    """Run q at each batch window; return the byte-exact payloads (or
    identical error reprs)."""
    out = []
    for w in windows:
        os.environ["DGRAPH_TPU_BATCH_WINDOW_US"] = w
        try:
            got = json.dumps(server.query(q)["data"], sort_keys=False)
        except Exception as exc:
            got = f"{type(exc).__name__}: {exc}"
        out.append(got)
    os.environ.pop("DGRAPH_TPU_BATCH_WINDOW_US", None)
    return out


# ---------------------------------------------------------------------------
# Micro-batcher: byte-equality, off switch, coalescing
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "case", SMOKE_CASES, ids=[c["id"] for c in SMOKE_CASES]
)
def test_batch_window_smoke(golden_server, case):
    off, on = _query_windows(golden_server, case["query"])
    assert off == on


def test_window_zero_is_a_true_off_switch(golden_server, monkeypatch):
    """At window 0 the executor must take today's exact path — the
    batcher object is never consulted at all."""
    from dgraph_tpu.serving.microbatch import MicroBatcher

    def boom(*a, **kw):
        raise AssertionError("batcher engaged at BATCH_WINDOW_US=0")

    monkeypatch.setattr(MicroBatcher, "read_uids", boom)
    monkeypatch.setattr(MicroBatcher, "read_values", boom)
    monkeypatch.delenv("DGRAPH_TPU_BATCH_WINDOW_US", raising=False)
    q = SMOKE_CASES[0]["query"]
    golden_server.query(q)  # must not touch the batcher


def test_concurrent_queries_coalesce_and_stay_byte_identical(
    golden_server, monkeypatch
):
    q = """{ me(func: eq(name, "Michonne")) {
        name
        friend { name friend { name } }
        school { name }
    } }"""
    base = json.dumps(golden_server.query(q)["data"], sort_keys=False)
    # slow the level reads so same-shape arrivals reliably pile up
    # behind the in-flight dispatch (the coalescing trigger)
    real_read_many = golden_server.mem.read_many

    def slow_read_many(kv, keys_list, read_ts):
        time.sleep(0.002)
        return real_read_many(kv, keys_list, read_ts)

    monkeypatch.setattr(golden_server.mem, "read_many", slow_read_many)
    monkeypatch.setenv("DGRAPH_TPU_BATCH_WINDOW_US", "20000")
    before = METRICS.value("batch_coalesced_total")
    results = []
    lock = threading.Lock()
    barrier = threading.Barrier(4)

    def worker():
        barrier.wait()
        for _ in range(20):
            got = json.dumps(
                golden_server.query(q)["data"], sort_keys=False
            )
            with lock:
                results.append(got)

    ths = [threading.Thread(target=worker) for _ in range(4)]
    for th in ths:
        th.start()
    for th in ths:
        th.join()
    assert all(r == base for r in results)
    assert METRICS.value("batch_coalesced_total") > before, (
        "no cross-query coalescing happened under 4-way concurrency"
    )


def test_batcher_demux_slices_match_solo_reads():
    """Direct contract check: members arriving during an in-flight
    same-key dispatch form the next batch; its combined-read slices are
    byte-identical to each member's solo read, incl. duplicate keys."""
    from dgraph_tpu.serving.microbatch import MicroBatcher

    first_started = threading.Event()
    release_first = threading.Event()

    class StubCache:
        kv = object()
        mem = object()
        read_ts = 3
        calls = 0

        def uids_many(self, keys_list):
            StubCache.calls += 1
            if StubCache.calls == 1:
                first_started.set()
                release_first.wait(5)
            rows = [
                np.arange(int(k), dtype=np.uint64) for k in keys_list
            ]
            offs = np.zeros(len(rows) + 1, dtype=np.int64)
            offs[1:] = np.cumsum([len(r) for r in rows])
            flat = (
                np.concatenate(rows)
                if rows
                else np.zeros(0, np.uint64)
            )
            return flat, offs, [("tok", int(k)) for k in keys_list]

    cache = StubCache()
    b = MicroBatcher(inflight_fn=lambda: 4)
    os.environ["DGRAPH_TPU_BATCH_WINDOW_US"] = "1000000"
    before = METRICS.value("batch_coalesced_total")
    try:
        out = {}

        def member(name, keys):
            out[name] = b.read_uids("p", cache, keys)

        t0 = threading.Thread(target=member, args=("z", [1]))
        t1 = threading.Thread(target=member, args=("a", [3, 1]))
        t2 = threading.Thread(target=member, args=("b", [2, 3]))
        t0.start()  # dispatches immediately, blocks inside the read
        first_started.wait(5)
        t1.start()  # opens the next batch behind the runner
        time.sleep(0.05)
        t2.start()  # joins that batch
        time.sleep(0.05)
        release_first.set()
        for th in (t0, t1, t2):
            th.join(10)
    finally:
        os.environ.pop("DGRAPH_TPU_BATCH_WINDOW_US", None)
        release_first.set()
    assert METRICS.value("batch_coalesced_total") == before + 2
    for name, keys in (("z", [1]), ("a", [3, 1]), ("b", [2, 3])):
        flat, offs, toks = out[name]
        solo_flat, solo_offs, solo_toks = cache.uids_many(keys)
        assert np.array_equal(flat, solo_flat)
        assert np.array_equal(offs, solo_offs)
        assert list(toks) == list(solo_toks)


def test_batcher_snapshot_token_respects_commits(golden_server):
    """Two queries separated by a commit must never share a coalescing
    group key: the watermark moves with the commit."""
    b = golden_server.serving.batcher
    from dgraph_tpu.posting.lists import LocalCache

    c1 = LocalCache(
        golden_server.kv, golden_server.zero.read_ts(),
        mem=golden_server.mem,
    )
    t1 = b._snapshot_token(c1)
    tx = golden_server.new_txn()
    tx.mutate_rdf(
        set_rdf='<0x9999> <name> "snapshot-probe" .', commit_now=True
    )
    c2 = LocalCache(
        golden_server.kv, golden_server.zero.read_ts(),
        mem=golden_server.mem,
    )
    t2 = b._snapshot_token(c2)
    assert t1 != t2
    # and a pre-commit read_ts can never join the post-commit group
    assert b._snapshot_token(c1) != t2


# ---------------------------------------------------------------------------
# Plan cache
# ---------------------------------------------------------------------------


def test_normalize_strips_values_and_whitespace():
    from dgraph_tpu.serving.plancache import normalize

    a = normalize('{ q(func: eq(name, "Alice"), first: 5) { name } }')
    b = normalize(
        '{  q(func: eq(name,   "Bob"), first: 17) {\n name }\n }'
    )
    c = normalize('{ q(func: eq(age, 3)) { name } }')
    assert a is not None and b is not None and c is not None
    assert a[0] == b[0]  # same shape, different literals
    assert a[1] != b[1]
    assert a[0] != c[0]  # different shape
    assert normalize("{ q(func: \x01") is None or True  # lex errors -> None


def test_plan_cache_hit_and_variant_semantics(golden_server):
    pc = golden_server.serving.plan_cache
    q1 = '{ q(func: eq(name, "Michonne")) { name } }'
    q2 = '{ q(func: eq(name, "Rick Grimes")) { name } }'
    h0 = METRICS.value("plan_cache_hit_total")
    r1a = json.dumps(golden_server.query(q1)["data"])
    r1b = json.dumps(golden_server.query(q1)["data"])
    assert r1a == r1b
    assert METRICS.value("plan_cache_hit_total") > h0
    # same shape, different literal: correct (different) results
    r2 = json.dumps(golden_server.query(q2)["data"])
    assert "Rick" in r2 and r2 != r1a
    st = pc.stats()
    assert st["shapes"] >= 1 and st["hits"] >= 1


def test_plan_cache_reuse_is_execution_safe(golden_server):
    """The executor must not mutate cached parse trees: repeated
    cache-hit executions (incl. expand/recurse, which build child
    GraphQuerys at run time) stay byte-identical."""
    queries = [
        '{ q(func: eq(name, "Michonne")) { expand(_all_) } }',
        '{ q(func: eq(name, "Michonne")) @recurse(depth: 3) '
        "{ name friend } }",
        '{ q(func: eq(name, "Michonne")) { name friend @facets '
        "(first: 2) { name } } }",
    ]
    for q in queries:
        first = json.dumps(golden_server.query(q)["data"])
        for _ in range(3):
            assert json.dumps(golden_server.query(q)["data"]) == first


def test_plan_cache_epoch_invalidation_no_stale_plans():
    from dgraph_tpu.api.server import Server

    s = Server()
    s.alter("pname: string @index(exact) .")
    t = s.new_txn()
    t.mutate_rdf(set_rdf='<0x1> <pname> "v0" .', commit_now=True)
    q = '{ q(func: has(pname)) { pname } }'
    assert s.query(q)["data"]["q"][0]["pname"] == "v0"
    e0 = s.serving.plan_cache.epoch
    t = s.new_txn()
    t.mutate_rdf(set_rdf='<0x1> <pname> "v1" .', commit_now=True)
    assert s.serving.plan_cache.epoch > e0  # commit bumped the epoch
    assert s.query(q)["data"]["q"][0]["pname"] == "v1"  # never stale


def test_plan_cache_correct_under_concurrent_mutation():
    """Queries racing a mutator must always see a committed value —
    a cached plan may be reused, a stale RESULT may not exist."""
    from dgraph_tpu.api.server import Server

    s = Server()
    s.alter("cname: string @index(exact) .")
    t = s.new_txn()
    t.mutate_rdf(set_rdf='<0x1> <cname> "w0" .', commit_now=True)
    stop = threading.Event()
    versions = ["w0"]
    errs = []

    def mutator():
        for i in range(1, 25):
            # the value becomes legal BEFORE the commit lands (a reader
            # may observe it the instant the commit applies)
            versions.append(f"w{i}")
            tx = s.new_txn()
            tx.mutate_rdf(
                set_rdf=f'<0x1> <cname> "w{i}" .', commit_now=True
            )
            time.sleep(0.001)
        stop.set()

    def reader():
        while not stop.is_set():
            try:
                got = s.query('{ q(func: has(cname)) { cname } }')
                val = got["data"]["q"][0]["cname"]
                if val not in versions:
                    errs.append(val)
            except Exception as exc:  # pragma: no cover
                errs.append(repr(exc))

    ths = [threading.Thread(target=mutator)] + [
        threading.Thread(target=reader) for _ in range(2)
    ]
    for th in ths:
        th.start()
    for th in ths:
        th.join()
    assert not errs, errs
    # the final read must see the final committed value
    assert (
        s.query('{ q(func: has(cname)) { cname } }')["data"]["q"][0][
            "cname"
        ]
        == "w24"
    )


def test_plan_cache_lru_bound(monkeypatch):
    from dgraph_tpu.serving.plancache import PlanCache

    pc = PlanCache(size=4)
    for i in range(10):
        pc.put(f"shape{i}", ("x",), [i])
    assert pc.stats()["shapes"] <= 4
    assert pc.get("shape9", ("x",)) == [9]
    assert pc.get("shape0", ("x",)) is None  # evicted


# ---------------------------------------------------------------------------
# Admission control
# ---------------------------------------------------------------------------


def test_admission_sheds_over_budget_and_is_retryable(monkeypatch):
    from dgraph_tpu.serving import TooManyRequestsError
    from dgraph_tpu.serving.front import ServingFront

    monkeypatch.setenv("DGRAPH_TPU_ADMISSION", "1")
    monkeypatch.setenv("DGRAPH_TPU_MAX_INFLIGHT", "2")
    front = ServingFront()
    t1 = front.admit(None)
    t2 = front.admit(None)
    shed0 = METRICS.value("admission_shed_total")
    with pytest.raises(TooManyRequestsError) as exc:
        front.admit(None)
    assert exc.value.retryable and exc.value.code == "too_many_requests"
    assert METRICS.value("admission_shed_total") == shed0 + 1
    front.finish(t1, None, 1.0)
    t3 = front.admit(None)  # slot freed -> admitted again
    front.finish(t2, None, 1.0)
    front.finish(t3, None, 1.0)
    assert front.admission.inflight == 0


def test_admission_idle_server_always_admits_one(monkeypatch):
    """A single expensive query must be admitted on an idle server even
    when its estimated cost exceeds the whole budget."""
    from dgraph_tpu.serving.front import ServingFront

    monkeypatch.setenv("DGRAPH_TPU_ADMISSION", "1")
    monkeypatch.setenv("DGRAPH_TPU_MAX_INFLIGHT", "1")
    front = ServingFront()
    front.plan_cache.observe_cost("big", 10000.0)  # ~1000 tokens
    t = front.admit("big")
    assert t.cost > 1.0
    front.finish(t, "big", 5.0)


def test_admission_degrades_when_slow_query_signal_fires(monkeypatch):
    from dgraph_tpu.serving.front import ServingFront

    monkeypatch.setenv("DGRAPH_TPU_ADMISSION", "1")
    monkeypatch.setenv("DGRAPH_TPU_MAX_INFLIGHT", "64")
    front = ServingFront()
    d0 = METRICS.value("admission_degraded_total")
    for _ in range(6):  # cross the saturation threshold
        front.admission.note_slow()
    t = front.admit(None)
    assert t.degrade
    assert METRICS.value("admission_degraded_total") == d0 + 1
    front.finish(t, None, 1.0)


def test_http_429_with_retryable_code(monkeypatch):
    import urllib.error
    import urllib.request

    from dgraph_tpu.api.http_server import HTTPServer
    from dgraph_tpu.api.server import Server
    from dgraph_tpu.conn.retry import RetryPolicy, retrying_call

    monkeypatch.setenv("DGRAPH_TPU_ADMISSION", "1")
    monkeypatch.setenv("DGRAPH_TPU_MAX_INFLIGHT", "1")
    s = Server()
    s.alter("hname: string @index(exact) .")
    srv = HTTPServer(s, port=0).start()
    try:
        # hold the whole budget so the HTTP query sheds
        held = s.serving.admit(None)
        url = f"http://127.0.0.1:{srv.port}/query"

        def post():
            req = urllib.request.Request(
                url,
                data=b'{ q(func: has(hname)) { hname } }',
                method="POST",
            )
            return urllib.request.urlopen(req, timeout=10)

        with pytest.raises(urllib.error.HTTPError) as err:
            post()
        assert err.value.code == 429
        body = json.loads(err.value.read())
        ext = body["errors"][0]["extensions"]
        assert ext["code"] == "too_many_requests" and ext["retryable"]

        # retrying_call: release the budget from a timer; the retry
        # loop must then get through
        timer = threading.Timer(
            0.2, lambda: s.serving.finish(held, None, 1.0)
        )
        timer.start()

        def attempt():
            try:
                return post().read()
            except urllib.error.HTTPError as e:
                if e.code == 429:
                    e.retryable = True  # transport-level mapping
                raise

        got = retrying_call(
            attempt,
            policy=RetryPolicy(base=0.05, cap=0.2, max_attempts=50),
        )
        assert b'"data"' in got
        timer.join()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# Exec-pool backpressure (bounded submit + gauge)
# ---------------------------------------------------------------------------


def test_pool_bounded_submit_and_gauge(monkeypatch):
    from dgraph_tpu.query import subgraph

    # a full backlog refuses the submit (caller expands inline)
    monkeypatch.setattr(subgraph, "_POOL_QUEUED", 8)
    pool = subgraph._expand_pool(2)
    assert subgraph._submit_bounded(pool, 2, lambda: None) is None
    monkeypatch.setattr(subgraph, "_POOL_QUEUED", 0)
    fut = subgraph._submit_bounded(pool, 2, lambda: 41)
    assert fut is not None and fut.result() == 41
    queued, workers = subgraph.pool_backpressure()
    assert queued == 0


def test_pool_queue_depth_surfaces_in_profile(golden_server, monkeypatch):
    monkeypatch.setenv("DGRAPH_TPU_EXEC_WORKERS", "4")
    out = golden_server.query(
        """{ me(func: eq(name, "Michonne")) {
            friend { name } school { name } pet { name }
        } }"""
    )
    prof = out["extensions"]["profile"]
    assert "exec_pool" in prof
    assert prof["exec_pool"]["max_queue_depth"] >= 0


# ---------------------------------------------------------------------------
# Admission under a seeded fault plan (cluster, chaos marker)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_admission_shed_degrade_under_seeded_faults(monkeypatch):
    """Fixed-seed delay faults slow the cluster's RPC plane; a client
    flood against a tiny in-flight budget must shed fast (retryable),
    keep every accepted query bounded, and mark degraded-admission
    responses — never queue without bound."""
    from dgraph_tpu.conn import faults
    from dgraph_tpu.conn.faults import FaultPlan
    from dgraph_tpu.serving import TooManyRequestsError
    from dgraph_tpu.worker.harness import ProcCluster

    monkeypatch.setenv("DGRAPH_TPU_ADMISSION", "1")
    monkeypatch.setenv("DGRAPH_TPU_MAX_INFLIGHT", "2")
    monkeypatch.setenv("DGRAPH_TPU_SLOW_QUERY_MS", "25")
    c = ProcCluster(n_groups=1, replicas=3)
    try:
        c.alter("aname: string @index(exact) .")
        c.new_txn().mutate_rdf(
            set_rdf="\n".join(
                f'<0x{i:x}> <aname> "acct{i}" .' for i in range(1, 30)
            ),
            commit_now=True,
        )
        faults.install(
            FaultPlan(
                seed=1234,
                rules=[
                    dict(
                        point="send", action="delay", p=0.5,
                        delay_ms=30,
                    ),
                ],
            )
        )
        stats = {"ok": 0, "shed": 0, "degraded": 0, "slowest": 0.0}
        lock = threading.Lock()

        def client(i):
            for _ in range(6):
                t0 = time.monotonic()
                try:
                    out = c.query(
                        '{ q(func: eq(aname, "acct%d")) { aname } }'
                        % (i + 1),
                        timeout_s=10.0,
                    )
                    with lock:
                        stats["ok"] += 1
                        if out["extensions"].get("degraded_admission"):
                            stats["degraded"] += 1
                except TooManyRequestsError:
                    with lock:
                        stats["shed"] += 1
                finally:
                    took = time.monotonic() - t0
                    with lock:
                        stats["slowest"] = max(stats["slowest"], took)

        ths = [
            threading.Thread(target=client, args=(i,)) for i in range(8)
        ]
        for th in ths:
            th.start()
        for th in ths:
            th.join()
        assert stats["shed"] > 0, stats  # over-limit traffic shed
        assert stats["ok"] > 0, stats  # in-budget traffic served
        # bounded: nothing queued past its deadline + fault delays
        assert stats["slowest"] < 15.0, stats
        assert METRICS.value("admission_shed_total") > 0
    finally:
        faults.reset()
        c.close()

"""Tier-1 gate + self-tests for the project-invariant analyzer suite.

Two layers:

  1. The GATE: `analysis.run()` over the real package must come back
     clean — zero unallowlisted violations AND zero stale allowlist
     entries (every deliberate exception keeps matching something).

  2. SELF-TESTS: each checker is run against fixture sources seeding
     exactly the defect class it exists to catch (bad lock nesting,
     raw env read, truncated restype, naked retry sleep, np-in-jit),
     plus a clean fixture asserting no false positives. A checker that
     silently stops detecting its class fails here, not in production.

Also covers the x/config registry itself (types, defaults, precedence)
and the generated CONFIG.md sync.
"""

import ctypes
import os
import textwrap

import pytest

from dgraph_tpu import analysis
from dgraph_tpu.analysis import check_ctypes_abi
from dgraph_tpu.analysis.allowlist import ALLOWLIST
from dgraph_tpu.analysis.core import Allow
from dgraph_tpu.x import config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# the gate
# ---------------------------------------------------------------------------


def test_package_is_clean():
    rep = analysis.run()
    assert not rep.violations, "\n" + "\n".join(
        v.render() for v in rep.violations
    )
    assert not rep.unused_allows, (
        "stale allowlist entries (remove them): "
        + ", ".join(f"({a.checker}, {a.path})" for a in rep.unused_allows)
    )


def test_every_allowlist_entry_has_a_reason():
    for a in ALLOWLIST:
        assert a.reason and len(a.reason.split()) >= 5, (
            f"allowlist entry ({a.checker}, {a.path}, {a.match!r}) needs "
            f"a real reason, not a token"
        )


def test_cli_lint_contract():
    from dgraph_tpu import cli

    class Args:
        json = False
        checker = None

    assert cli.cmd_lint(Args()) == 0
    Args.checker = ["no-such-checker"]
    assert cli.cmd_lint(Args()) == 2


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------


def _run_fixture(tmp_path, rel, source, checkers):
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(source))
    return analysis.run(
        root=str(tmp_path), checkers=checkers, allows=[]
    )


CLEAN_FIXTURE = """
    import threading
    import time

    from dgraph_tpu.x import config

    _LOCK = threading.Lock()


    def good(counter):
        workers = config.get("EXEC_WORKERS")
        with _LOCK:
            counter += workers
        time.sleep(0.01)  # not in a loop, no lock held
        return counter
"""


def test_clean_fixture_no_false_positives(tmp_path):
    rep = _run_fixture(
        tmp_path, "conn/clean.py", CLEAN_FIXTURE, list(analysis.CHECKERS)
    )
    assert rep.violations == []


def test_config_checker_catches_raw_env_read(tmp_path):
    rep = _run_fixture(
        tmp_path,
        "worker/bad_env.py",
        """
        import os
        import os as _os
        from os import environ, getenv

        A = os.environ.get("DGRAPH_TPU_EXEC_WORKERS", "0")
        B = os.getenv("DGRAPH_TPU_LEVEL_BATCH")
        C = _os.environ["DGRAPH_TPU_STORAGE"]
        os.environ["DGRAPH_TPU_STORAGE"] = "lsm"
        D = environ.get("SOME_OTHER_VAR")
        E = dict(os.environ)
        F = environ["DGRAPH_TPU_PALLAS"]      # from-import bypass
        G = getenv("DGRAPH_TPU_PALLAS")       # bare getenv bypass
        """,
        ["config-registry"],
    )
    codes = [v.code for v in rep.violations]
    # A, B, C, the write, F, G — from-imported access must still
    # classify as the DGRAPH hard-violation class, not generic
    assert codes.count("raw-dgraph-env") == 6
    assert codes.count("raw-env-read") == 2  # D + dict(os.environ)


def test_config_checker_exempts_registry_itself(tmp_path):
    rep = _run_fixture(
        tmp_path,
        "x/config.py",
        """
        import os

        V = os.environ.get("DGRAPH_TPU_ANYTHING")
        """,
        ["config-registry"],
    )
    assert rep.violations == []


LOCK_FIXTURE = """
    import threading
    import threading as th
    import time
    import subprocess

    from dgraph_tpu.native import packs_decode_many

    A = th.Lock()  # aliased module import must still register
    B = threading.Lock()


    class Layer:
        def __init__(self):
            self._lock = threading.Lock()
            self._cv = threading.Condition(self._lock)

        def bad_sleep(self):
            with self._lock:
                time.sleep(0.5)

        def bad_native(self, packs):
            with self._lock:
                return packs_decode_many(packs)

        def good_wait(self):
            with self._cv:
                self._cv.wait(1.0)  # releases its own lock: fine

        def bad_wait(self):
            with A:
                with self._cv:
                    self._cv.wait(1.0)  # A stays held for the wait

        def bad_subprocess(self):
            with B:
                subprocess.run(["true"])


    def order_ab():
        with A:
            with B:
                pass


    def order_ba():
        with B:
            with A:
                pass
"""


def test_lock_checker_catches_seeded_violations(tmp_path):
    rep = _run_fixture(
        tmp_path, "posting/bad_locks.py", LOCK_FIXTURE, ["lock-discipline"]
    )
    codes = sorted(v.code for v in rep.violations)
    msgs = "\n".join(v.render() for v in rep.violations)
    assert codes.count("blocking-under-lock") == 2, msgs  # sleep + subprocess
    assert codes.count("native-call-under-lock") == 1, msgs
    assert codes.count("cv-wait-under-other-lock") == 1, msgs
    assert codes.count("lock-order-cycle") == 1, msgs
    # the good condition wait produced nothing
    assert "good_wait" not in msgs


def test_deadline_checker_catches_naked_sleep_and_settimeout(tmp_path):
    src = """
        import time
        from time import sleep


        def naked_retry(sock):
            sock.settimeout(5)
            while True:
                try:
                    return sock.recv(1)
                except OSError:
                    time.sleep(0.05)


        def also_naked():
            for _ in range(3):
                sleep(0.1)


        def fine_outside_loop():
            time.sleep(0.01)
    """
    rep = _run_fixture(
        tmp_path / "in_scope", "conn/bad_retry.py", src,
        ["deadline-hygiene"],
    )
    codes = sorted(v.code for v in rep.violations)
    assert codes.count("naked-sleep-in-loop") == 2
    assert codes.count("raw-settimeout-constant") == 1
    # same file OUTSIDE the cluster dirs: out of scope
    rep2 = _run_fixture(
        tmp_path / "out_of_scope", "query/bad_retry.py", src,
        ["deadline-hygiene"],
    )
    assert rep2.violations == []


def test_jax_checker_catches_np_in_jit(tmp_path):
    src = """
        import functools

        import jax
        import jax.numpy as jnp
        import numpy as np


        @jax.jit
        def bad(a):
            return np.sum(a)  # host numpy inside jit


        @functools.partial(jax.jit, static_argnames=("k",))
        def bad2(a, k):
            b = jnp.take(a, 0)
            return b.item()  # forced device->host sync


        def helper(a):
            return np.sum(a)  # NOT jitted: numpy is fine


        def wrapped(a):
            return np.asarray(a)


        wrapped = jax.jit(wrapped)
    """
    rep = _run_fixture(tmp_path, "ops/bad_jit.py", src, ["jax-hygiene"])
    codes = sorted(v.code for v in rep.violations)
    msgs = "\n".join(v.render() for v in rep.violations)
    assert codes.count("np-in-jit") == 1, msgs
    assert codes.count("host-sync-in-jit") == 2, msgs  # .item + np.asarray
    assert "helper" not in msgs


# ---------------------------------------------------------------------------
# ctypes ABI checker self-tests (synthetic C++ + synthetic DECLS)
# ---------------------------------------------------------------------------

_SYN_CPP = """
using i64 = int64_t;
using u64 = uint64_t;

extern "C" {

static i64 helper(i64 x) { return x; }

i64 truncated(const u64* a, i64 n) { return n; }

void takes_three(i64 a, i64 b, int c) {}

u64* returns_ptr(void* h) { return 0; }

int undeclared_fn(int x) { return x; }

}  // extern "C"
"""


def _syn_decls(**overrides):
    i64 = ctypes.c_int64
    u64p = ctypes.POINTER(ctypes.c_uint64)
    decls = {
        "truncated": (i64, [u64p, i64]),
        "takes_three": (None, [i64, i64, ctypes.c_int]),
        "returns_ptr": (u64p, [ctypes.c_void_p]),
        "undeclared_fn": (ctypes.c_int, [ctypes.c_int]),
    }
    decls.update(overrides)
    return decls


def _abi(decls):
    return check_ctypes_abi.check_abi(
        {"native/syn.cpp": _SYN_CPP}, decls, "native/__init__.py"
    )


def test_abi_clean_baseline():
    assert _abi(_syn_decls()) == []


def test_abi_catches_truncated_restype():
    # the headline defect class: int64_t return bound with default c_int
    decls = _syn_decls(
        truncated=(None, [ctypes.POINTER(ctypes.c_uint64), ctypes.c_int64])
    )
    out = _abi(decls)
    assert [v.code for v in out] == ["restype-mismatch"]
    assert "truncated" in out[0].message


def test_abi_catches_arity_and_width():
    i64 = ctypes.c_int64
    out = _abi(_syn_decls(takes_three=(None, [i64, i64])))
    assert [v.code for v in out] == ["arity-mismatch"]
    # int32 param declared as int64: width mismatch
    out = _abi(_syn_decls(takes_three=(None, [i64, i64, i64])))
    assert [v.code for v in out] == ["arg-type-mismatch"]
    # unsigned vs signed pointee
    out = _abi(_syn_decls(
        truncated=(i64, [ctypes.POINTER(ctypes.c_int64), i64])
    ))
    assert [v.code for v in out] == ["arg-type-mismatch"]


def test_abi_catches_undeclared_and_stale():
    decls = _syn_decls()
    del decls["undeclared_fn"]
    decls["ghost"] = (ctypes.c_int64, [])
    codes = sorted(v.code for v in _abi(decls))
    assert codes == ["stale-decl", "undeclared-export"]
    # static helper must NOT demand a declaration
    assert all("helper" not in v.message for v in _abi(decls))


_SYN_BITMAP_CPP = """
extern "C" {

int64_t bitmap_and_block(const uint64_t* a_words, const uint64_t* b_words,
                         int64_t nwords, int64_t bm_bits, uint64_t* out) {
    return 0;
}

}  // extern "C"
"""


def test_abi_catches_bitmap_kernel_width_mismatch():
    """Seeded violation for the adaptive-engine kernel class: a bitmap
    kernel whose word-count parameter is declared c_int32 against the
    C++ int64_t must be flagged (on a >2^31-bit operand the truncated
    width silently corrupts the word loop's bounds)."""
    i64 = ctypes.c_int64
    u64p = ctypes.POINTER(ctypes.c_uint64)
    good = {"bitmap_and_block": (i64, [u64p, u64p, i64, i64, u64p])}
    assert (
        check_ctypes_abi.check_abi(
            {"native/syn_bitmap.cpp": _SYN_BITMAP_CPP},
            good,
            "native/__init__.py",
        )
        == []
    )
    bad = {
        "bitmap_and_block": (
            i64,
            [u64p, u64p, ctypes.c_int32, i64, u64p],
        )
    }
    out = check_ctypes_abi.check_abi(
        {"native/syn_bitmap.cpp": _SYN_BITMAP_CPP},
        bad,
        "native/__init__.py",
    )
    assert [v.code for v in out] == ["arg-type-mismatch"]
    assert "bitmap_and_block" in out[0].message and "arg 2" in out[0].message


_SYN_ENCODER_CPP = """
extern "C" {

int64_t enc_uid_objs(const uint64_t* uids, int64_t n, const uint8_t* pre,
                     int64_t pre_len, const uint8_t* post, int64_t post_len,
                     uint8_t* out) {
    return 0;
}

}  // extern "C"
"""


def test_abi_catches_encoder_width_mismatch():
    """Seeded violation for the arena-encoder kernel class: the uid
    pointer declared c_uint32* against the C++ uint64_t* must be
    flagged (the kernel would read half-width uids and emit garbage
    hex — silently, since the call still 'works')."""
    i64 = ctypes.c_int64
    u64p = ctypes.POINTER(ctypes.c_uint64)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    good = {
        "enc_uid_objs": (i64, [u64p, i64, u8p, i64, u8p, i64, u8p])
    }
    assert (
        check_ctypes_abi.check_abi(
            {"native/syn_enc.cpp": _SYN_ENCODER_CPP},
            good,
            "native/__init__.py",
        )
        == []
    )
    bad = {
        "enc_uid_objs": (
            i64,
            [
                ctypes.POINTER(ctypes.c_uint32),
                i64, u8p, i64, u8p, i64, u8p,
            ],
        )
    }
    out = check_ctypes_abi.check_abi(
        {"native/syn_enc.cpp": _SYN_ENCODER_CPP},
        bad,
        "native/__init__.py",
    )
    assert [v.code for v in out] == ["arg-type-mismatch"]
    assert "enc_uid_objs" in out[0].message and "arg 0" in out[0].message
    # the length parameter truncated to c_int32 is the other silent
    # corruption class (a >2^31-row run would wrap negative)
    bad_n = {
        "enc_uid_objs": (
            i64,
            [u64p, ctypes.c_int32, u8p, i64, u8p, i64, u8p],
        )
    }
    out = check_ctypes_abi.check_abi(
        {"native/syn_enc.cpp": _SYN_ENCODER_CPP},
        bad_n,
        "native/__init__.py",
    )
    assert [v.code for v in out] == ["arg-type-mismatch"]


def test_abi_covers_encoder_exports():
    """The real arena-encoder entry points are parsed from codec.cpp and
    covered by DECLS (the ctypes-abi analyzer then enforces full
    width/signedness equality on every run)."""
    from dgraph_tpu import native

    with open(
        os.path.join(REPO, "dgraph_tpu", "native", "codec.cpp")
    ) as f:
        exports = check_ctypes_abi.parse_cpp_exports(f.read())
    for name in ("enc_uid_objs", "enc_int_objs"):
        assert name in exports, name
        assert name in native.DECLS, name
        assert len(exports[name][1]) == len(native.DECLS[name][1]), name


def test_abi_covers_mutation_kernel_exports():
    """The write-path mutation kernels are parsed from codec.cpp and
    covered by DECLS (regression guard: a missing restype on the
    int64-returning encoders is the memory-corruption class)."""
    from dgraph_tpu import native

    with open(
        os.path.join(REPO, "dgraph_tpu", "native", "codec.cpp")
    ) as f:
        exports = check_ctypes_abi.parse_cpp_exports(f.read())
    for name in (
        "enc_delta_records",
        "tok_terms_ascii",
        "batch_apply",
        "batch_apply_caps",
    ):
        assert name in exports, name
        assert name in native.DECLS, name
        assert len(exports[name][1]) == len(native.DECLS[name][1]), name


def test_abi_covers_adaptive_engine_exports():
    """The real adaptive-engine entry points are parsed from codec.cpp
    and covered by DECLS (regression guard for the new kernels)."""
    from dgraph_tpu import native

    with open(
        os.path.join(REPO, "dgraph_tpu", "native", "codec.cpp")
    ) as f:
        exports = check_ctypes_abi.parse_cpp_exports(f.read())
    for name in (
        "pack_build_bitmaps",
        "pack_pair_setop",
        "pack_stream_setop",
    ):
        assert name in exports, name
        assert name in native.DECLS, name
        # arity agrees (full width/signedness equality is the analyzer's
        # job — test_abi_real_package_is_clean keeps it at zero findings)
        assert len(exports[name][1]) == len(native.DECLS[name][1]), name


_SYN_VEC_CPP = """
extern "C" {

int64_t vec_qi8_topk_idx(const int8_t* codes, int64_t d,
                         const float* scales, const int32_t* rows,
                         int64_t nrows, float qscale, int metric,
                         int64_t k, int64_t* out_idx, float* out_dist) {
    return 0;
}

}  // extern "C"
"""

_SYN_VEC_LISTS_CPP = """
extern "C" {

int64_t vec_qi8_topk_lists(const int8_t* codes, int64_t d,
                           const int32_t* rows, const int64_t* begs,
                           const int64_t* ends, int64_t nq, int64_t k,
                           int64_t* out_idx, float* out_dist) {
    return 0;
}

}  // extern "C"
"""


def test_abi_catches_vector_kernel_width_mismatch():
    """Seeded violations for the quantized-vector kernel class: (a) the
    candidate row-id pointer declared c_int64* against the C++ int32_t*
    (the probe would stride double-width through the cell lists and
    score garbage rows — silently); (b) the code-matrix pointer widened
    to c_int16* (every dot product reads interleaved halves of two
    rows)."""
    i64 = ctypes.c_int64
    i8p = ctypes.POINTER(ctypes.c_int8)
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    f32 = ctypes.c_float
    f32p = ctypes.POINTER(ctypes.c_float)
    good = {
        "vec_qi8_topk_idx": (
            i64, [i8p, i64, f32p, i32p, i64, f32, ctypes.c_int, i64,
                  i64p, f32p],
        )
    }
    assert (
        check_ctypes_abi.check_abi(
            {"native/syn_vec.cpp": _SYN_VEC_CPP},
            good,
            "native/__init__.py",
        )
        == []
    )
    bad_rows = {
        "vec_qi8_topk_idx": (
            i64, [i8p, i64, f32p, i64p, i64, f32, ctypes.c_int, i64,
                  i64p, f32p],
        )
    }
    out = check_ctypes_abi.check_abi(
        {"native/syn_vec.cpp": _SYN_VEC_CPP}, bad_rows,
        "native/__init__.py",
    )
    assert [v.code for v in out] == ["arg-type-mismatch"]
    assert "vec_qi8_topk_idx" in out[0].message and "arg 3" in out[0].message
    bad_codes = {
        "vec_qi8_topk_idx": (
            i64, [ctypes.POINTER(ctypes.c_int16), i64, f32p, i32p, i64,
                  f32, ctypes.c_int, i64, i64p, f32p],
        )
    }
    out = check_ctypes_abi.check_abi(
        {"native/syn_vec.cpp": _SYN_VEC_CPP}, bad_codes,
        "native/__init__.py",
    )
    assert [v.code for v in out] == ["arg-type-mismatch"]
    assert "arg 0" in out[0].message


def test_abi_catches_lists_kernel_csr_width_mismatch():
    """Seeded violation for the batched CSR scan kernel: the begs/ends
    slice-bound pointers declared c_int32* against the C++ int64_t* —
    every query after the first would read garbage slice bounds and
    scan (or skip) the wrong candidates, silently."""
    i64 = ctypes.c_int64
    i8p = ctypes.POINTER(ctypes.c_int8)
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    f32p = ctypes.POINTER(ctypes.c_float)
    good = {
        "vec_qi8_topk_lists": (
            i64, [i8p, i64, i32p, i64p, i64p, i64, i64, i64p, f32p],
        )
    }
    assert (
        check_ctypes_abi.check_abi(
            {"native/syn_vec.cpp": _SYN_VEC_LISTS_CPP}, good,
            "native/__init__.py",
        )
        == []
    )
    bad_begs = {
        "vec_qi8_topk_lists": (
            i64, [i8p, i64, i32p, i32p, i64p, i64, i64, i64p, f32p],
        )
    }
    out = check_ctypes_abi.check_abi(
        {"native/syn_vec.cpp": _SYN_VEC_LISTS_CPP}, bad_begs,
        "native/__init__.py",
    )
    assert [v.code for v in out] == ["arg-type-mismatch"]
    assert (
        "vec_qi8_topk_lists" in out[0].message and "arg 3" in out[0].message
    )


def test_abi_covers_vector_exports():
    """The real quantized-vector entry points are parsed from codec.cpp
    and covered by DECLS (the analyzer then enforces full width and
    signedness equality on every run)."""
    from dgraph_tpu import native

    with open(
        os.path.join(REPO, "dgraph_tpu", "native", "codec.cpp")
    ) as f:
        exports = check_ctypes_abi.parse_cpp_exports(f.read())
    for name in (
        "vec_qi8_topk", "vec_qi8_topk_idx",
        "vec_qi8_topk_lists", "vec_qi8_quantize",
    ):
        assert name in exports, name
        assert name in native.DECLS, name
        assert len(exports[name][1]) == len(native.DECLS[name][1]), name


def test_abi_real_package_is_clean():
    # re-derive from the real sources; independent of the full gate so a
    # regression pinpoints here
    rep = analysis.run(checkers=["ctypes-abi"], allows=[])
    assert rep.violations == [], "\n".join(
        v.render() for v in rep.violations
    )
    # and the parser actually saw the real exports (not a silent no-op)
    from dgraph_tpu import native

    with open(
        os.path.join(REPO, "dgraph_tpu", "native", "codec.cpp")
    ) as f:
        exports = check_ctypes_abi.parse_cpp_exports(f.read())
    assert "merge_sorted_u64" in exports and "sst_scan" in exports
    assert set(exports) <= set(native.DECLS)


# ---------------------------------------------------------------------------
# x/config registry
# ---------------------------------------------------------------------------


def test_config_types_and_defaults(monkeypatch):
    monkeypatch.delenv("DGRAPH_TPU_EXEC_WORKERS", raising=False)
    assert config.get("EXEC_WORKERS") == 0
    monkeypatch.setenv("DGRAPH_TPU_EXEC_WORKERS", "4")
    assert config.get("EXEC_WORKERS") == 4
    # malformed values fall back instead of crashing server startup
    monkeypatch.setenv("DGRAPH_TPU_EXEC_WORKERS", "banana")
    assert config.get("EXEC_WORKERS") == 0
    monkeypatch.setenv("DGRAPH_TPU_LEVEL_BATCH", "0")
    assert config.get("LEVEL_BATCH") is False
    monkeypatch.setenv("DGRAPH_TPU_LEVEL_BATCH", "true")
    assert config.get("LEVEL_BATCH") is True
    monkeypatch.delenv("DGRAPH_TPU_DEVICE_MIN_TOTAL", raising=False)
    assert config.get("DEVICE_MIN_TOTAL") is None


def test_config_set_env_roundtrip(monkeypatch):
    monkeypatch.delenv("DGRAPH_TPU_STORAGE", raising=False)
    config.set_env("STORAGE", "lsm")
    assert os.environ["DGRAPH_TPU_STORAGE"] == "lsm"
    assert config.get("STORAGE") == "lsm"
    config.unset_env("STORAGE")
    assert config.get("STORAGE") == "mem"
    config.set_env("WIRE_COMPRESS", True)
    assert os.environ["DGRAPH_TPU_WIRE_COMPRESS"] == "1"
    config.unset_env("WIRE_COMPRESS")


def test_max_part_uids_single_default(monkeypatch):
    """Regression for the duplicated-default hazard: posting/pl.py and
    loaders/bulk2.py both size multi-part splits off MAX_PART_UIDS. The
    registry is now the one place the 1<<20 default lives; both call
    sites must agree with it."""
    monkeypatch.delenv("DGRAPH_TPU_MAX_PART_UIDS", raising=False)
    assert config.knob("MAX_PART_UIDS").default == 1 << 20
    assert config.get("MAX_PART_UIDS") == 1 << 20
    from dgraph_tpu.posting import pl

    # pl reads at import: its module constant equals the registry default
    assert pl.MAX_PART_UIDS == config.knob("MAX_PART_UIDS").default


def test_every_registered_knob_documented():
    for name, k in config.REGISTRY.items():
        assert k.doc and len(k.doc.split()) >= 5, name
        assert k.type in ("str", "int", "float", "bool"), name
        if k.default is not None and k.type == "bool":
            assert isinstance(k.default, bool), name


def test_config_md_in_sync():
    with open(os.path.join(REPO, "CONFIG.md")) as f:
        on_disk = f.read()
    assert on_disk == config.reference_table(), (
        "CONFIG.md is stale — regenerate with "
        "`python -m dgraph_tpu.cli config-ref -o CONFIG.md`"
    )


def test_no_unregistered_dgraph_env_vars_in_package():
    """Every DGRAPH_TPU_* string literal in the package must be a
    registered knob (catches a knob added ad hoc via config-checker
    bypass like indirection through a constant)."""
    import re

    known = {k.env for k in config.REGISTRY.values()}
    pkg = os.path.join(REPO, "dgraph_tpu")
    offenders = []
    for dirpath, _, files in os.walk(pkg):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path, encoding="utf-8") as f:
                for i, line in enumerate(f, 1):
                    for m in re.finditer(r"DGRAPH_TPU_[A-Z0-9_]+", line):
                        if m.group(0) not in known and m.group(0) != \
                                config.PREFIX.rstrip("_"):
                            offenders.append(
                                f"{path}:{i}: {m.group(0)}"
                            )
    assert not offenders, "\n".join(offenders)


# ---------------------------------------------------------------------------
# metrics-registry checker (PR 5): every METRICS name is declared
# ---------------------------------------------------------------------------


def test_metrics_registry_checker_flags_undeclared(tmp_path):
    rep = _run_fixture(
        tmp_path,
        "mod.py",
        """
        from dgraph_tpu.utils.observe import METRICS

        def f(x, name):
            METRICS.inc("tootally_bogus_counter")       # typo'd name
            METRICS.observe(f"span_{x}_oops", 1.0)      # unknown family
            METRICS.inc(name)                           # unresolvable
        """,
        ["metrics-registry"],
    )
    codes = sorted(v.code for v in rep.violations)
    assert codes == [
        "dynamic-metric-name",
        "dynamic-metric-name",
        "unregistered-metric",
    ], [v.render() for v in rep.violations]


def test_metrics_registry_checker_clean_fixture(tmp_path):
    rep = _run_fixture(
        tmp_path,
        "mod.py",
        """
        from dgraph_tpu.utils.observe import METRICS, Metrics

        def f(name):
            METRICS.inc("rpc_retries_total")
            METRICS.inc("level_task_uids", 5)
            METRICS.observe(f"span_{name}_seconds", 0.1)  # declared family
            METRICS.set_gauge("cache_point_reads", 1.0)
            with METRICS.timer("query_latency_seconds"):
                pass
            local = Metrics(prefix="t")
            local.inc("anything_goes")  # local registries are exempt
        """,
        ["metrics-registry"],
    )
    assert not rep.violations, [v.render() for v in rep.violations]


def test_metrics_md_in_sync():
    from dgraph_tpu.utils import observe

    with open(os.path.join(REPO, "METRICS.md")) as f:
        on_disk = f.read()
    assert on_disk == observe.metrics_reference(), (
        "METRICS.md is stale — regenerate with "
        "`python -m dgraph_tpu.cli metrics-ref -o METRICS.md`"
    )


def test_metric_declarations_are_documented():
    from dgraph_tpu.utils.observe import METRIC_DEFS

    for d in METRIC_DEFS.values():
        assert d.kind in ("counter", "gauge", "histogram"), d
        assert len(d.doc.split()) >= 4, f"{d.name} needs a real doc line"


# ---------------------------------------------------------------------------
# lock-order: cross-module acquisition graph + cycle detection
# ---------------------------------------------------------------------------

# an inversion neither half of which is visible intra-file: EngineX
# holds its instance lock while calling into the coalescer module,
# which elsewhere holds its queue lock while calling back into a
# (unique-name-resolved) EngineX method that takes the instance lock
_LO_ENGINE = """
    import threading

    from dgraph_tpu.worker import coalx


    class EngineX:
        def __init__(self):
            self._lock = threading.Lock()

        def flush_batches(self):
            with self._lock:
                coalx.drain_all()

        def apply_one_delta(self):
            with self._lock:
                return 1
"""

_LO_COAL = """
    import threading

    _QLOCK = threading.Lock()


    def drain_all():
        with _QLOCK:
            return []


    def requeue(engine):
        with _QLOCK:
            engine.apply_one_delta()
"""


def _write_fixture(tmp_path, rel, source):
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(source))


def test_lockorder_catches_cross_module_inversion(tmp_path):
    _write_fixture(tmp_path, "worker/enginex.py", _LO_ENGINE)
    _write_fixture(tmp_path, "worker/coalx.py", _LO_COAL)
    rep = analysis.run(
        root=str(tmp_path), checkers=["lock-order"], allows=[]
    )
    assert [v.code for v in rep.violations] == ["lock-order-cycle"], [
        v.render() for v in rep.violations
    ]
    msg = rep.violations[0].message
    assert "worker/enginex.py:EngineX._lock" in msg
    assert "worker/coalx.py:_QLOCK" in msg
    # each hop carries a concrete code location
    assert "worker/enginex.py:" in msg and "worker/coalx.py:" in msg


def test_lockorder_clean_when_callback_runs_unlocked(tmp_path):
    # same modules, but the coalescer calls back AFTER releasing its
    # queue lock — the classic fix — so the edge (and cycle) vanishes
    fixed = _LO_COAL.replace(
        """
    def requeue(engine):
        with _QLOCK:
            engine.apply_one_delta()
""",
        """
    def requeue(engine):
        with _QLOCK:
            pass
        engine.apply_one_delta()
""",
    )
    assert fixed != _LO_COAL  # the replace actually happened
    _write_fixture(tmp_path, "worker/enginex.py", _LO_ENGINE)
    _write_fixture(tmp_path, "worker/coalx.py", fixed)
    rep = analysis.run(
        root=str(tmp_path), checkers=["lock-order"], allows=[]
    )
    assert rep.violations == [], [v.render() for v in rep.violations]


_LO_NEST = """
    import threading

    A = threading.Lock()
    B = threading.Lock()
    C = threading.Lock()


    def ab():
        with A:
            with B:
                pass


    def bc():
        with B:
            with C:
                pass


    def ca():
        with C:
            with A:
                pass
"""


def test_lockorder_catches_three_lock_nest_cycle(tmp_path):
    # arbitrary-length cycles via lexical nesting alone — beyond the
    # pairwise inversion the lock-discipline checker already catches
    _write_fixture(tmp_path, "worker/ringlocks.py", _LO_NEST)
    rep = analysis.run(
        root=str(tmp_path), checkers=["lock-order"], allows=[]
    )
    assert [v.code for v in rep.violations] == ["lock-order-cycle"]
    msg = rep.violations[0].message
    for lock in ("ringlocks.py:A", "ringlocks.py:B", "ringlocks.py:C"):
        assert lock in msg, msg


def test_lockorder_real_graph_is_populated():
    # guard against the checker silently extracting nothing: the real
    # package must yield a non-trivial graph containing the known
    # commit-plane orderings (and, per the gate above, zero cycles)
    from dgraph_tpu.analysis import check_lockorder
    from dgraph_tpu.analysis.core import load_sources

    g = check_lockorder.lock_graph(load_sources(analysis.package_root()))
    nodes = {n for e in g for n in e}
    assert len(g) >= 12, sorted(g)
    for expected in (
        "worker/groupcommit.py:GroupCommit._lock",
        "worker/harness.py:ProcCluster._commit_lock",
        "worker/groups.py:DistributedCluster._commit_lock",
        "utils/observe.py:Metrics._lock",
        "models/vector.py:VectorIndex._lock",
    ):
        assert expected in nodes, sorted(nodes)
    # the commit lock is held across GroupCommit bookkeeping — the
    # ordering TSan/chaos runs exercise dynamically
    assert (
        "worker/harness.py:ProcCluster._commit_lock",
        "worker/groupcommit.py:GroupCommit._lock",
    ) in g


# ---------------------------------------------------------------------------
# shared-state: unguarded writes from thread-context functions
# ---------------------------------------------------------------------------

_SS_FIXTURE = """
    import threading

    _REGISTRY = {}
    _TOTAL = 0


    class Daemon:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0
            self.ok_count = 0
            self.noted = 0
            self._thread = threading.Thread(target=self._loop, daemon=True)

        def _loop(self):
            self.count += 1
            _REGISTRY["d"] = self
            with self._lock:
                self.ok_count += 1
            self.noted = 1  # race-ok: single-writer monotonic flag
            self.bare = 2  # race-ok


    def kick(pool):
        return pool.submit(_work)


    def _work():
        global _TOTAL
        _TOTAL += 1
"""


def test_shared_state_catches_seeded_races(tmp_path):
    rep = _run_fixture(
        tmp_path, "worker/daemon.py", _SS_FIXTURE, ["shared-state"]
    )
    codes = sorted(v.code for v in rep.violations)
    msgs = "\n".join(v.render() for v in rep.violations)
    # self.count, _REGISTRY["d"], and the pool-submitted global
    assert codes.count("unguarded-shared-write") == 3, msgs
    # bare `# race-ok` without an ownership reason still fails
    assert codes.count("race-ok-missing-reason") == 1, msgs
    # the lock-guarded write and the annotated write produced nothing
    assert "ok_count" not in msgs and "noted" not in msgs, msgs


def test_shared_state_accepts_preceding_comment_annotation(tmp_path):
    rep = _run_fixture(
        tmp_path,
        "worker/annotated.py",
        """
        import threading


        class D:
            def __init__(self):
                self.beat = 0
                threading.Thread(target=self._loop, daemon=True).start()

            def _loop(self):
                # race-ok: heartbeat counter, this thread is the only
                # writer and readers tolerate staleness
                self.beat += 1
        """,
        ["shared-state"],
    )
    assert rep.violations == [], [v.render() for v in rep.violations]


def test_shared_state_def_level_annotation_covers_body(tmp_path):
    rep = _run_fixture(
        tmp_path,
        "worker/owned.py",
        """
        import threading


        class D:
            def __init__(self):
                self.a = 0
                self.b = 0
                threading.Thread(target=self._loop, daemon=True).start()

            def _loop(self):  # race-ok: sole owner of a and b
                self.a += 1
                self.b += 1
        """,
        ["shared-state"],
    )
    assert rep.violations == [], [v.render() for v in rep.violations]


def test_shared_state_ignores_locals_and_main_thread_writes(tmp_path):
    rep = _run_fixture(
        tmp_path,
        "worker/clean.py",
        """
        import threading

        _STATE = {}


        class D:
            def __init__(self):
                self.total = 0  # main-thread write: not thread context

            def run_inline(self):
                self.total += 1  # never a thread target

            def spawn(self):
                threading.Thread(target=self._loop, daemon=True).start()

            def _loop(self):
                local = 0
                local += 1
                items = [x for x in range(3)]
                for x in items:
                    local = x
        """,
        ["shared-state"],
    )
    assert rep.violations == [], [v.render() for v in rep.violations]


def test_shared_state_sees_lambda_and_ctx_run_entries(tmp_path):
    rep = _run_fixture(
        tmp_path,
        "worker/wrapped.py",
        """
        import contextvars
        import threading

        _SINK = {}


        class H:
            def fire(self, pool):
                threading.Thread(
                    target=lambda: _SINK.update(a=1), daemon=True
                ).start()
                pool.submit(
                    contextvars.copy_context().run, self._timed, 1
                )

            def _timed(self, x):
                self.last = x
        """,
        ["shared-state"],
    )
    codes = [v.code for v in rep.violations]
    msgs = "\n".join(v.render() for v in rep.violations)
    # the ctx.run-wrapped method's self.last write is found; the
    # lambda's .update() method call is a documented limitation
    assert codes == ["unguarded-shared-write"], msgs
    assert "self.last" in msgs


def test_shared_state_real_package_is_clean():
    rep = analysis.run(checkers=["shared-state"], allows=[])
    assert rep.violations == [], "\n".join(
        v.render() for v in rep.violations
    )
    # and entry discovery actually saw the real daemons (not a no-op)
    from dgraph_tpu.analysis import check_shared_state
    from dgraph_tpu.analysis.core import load_sources

    entries = 0
    per_file = {}
    for src in load_sources(analysis.package_root()):
        if src.tree is None:
            continue
        found = check_shared_state._find_entries(src)
        entries += len(found)
        if found:
            per_file[src.rel] = len(found)
    assert entries >= 10, per_file
    for rel in (
        "posting/rollup.py", "worker/groups.py", "worker/remote.py",
        "utils/observe.py",
    ):
        assert rel in per_file, per_file


# ---------------------------------------------------------------------------
# DECLS drift: extern "C" prototypes vs ctypes decls, both directions
# ---------------------------------------------------------------------------


def _real_cpp_texts():
    out = {}
    native_dir = os.path.join(REPO, "dgraph_tpu", "native")
    for fn in sorted(os.listdir(native_dir)):
        if fn.endswith(".cpp"):
            with open(os.path.join(native_dir, fn)) as f:
                out[f"native/{fn}"] = f.read()
    return out


def test_decls_drift_name_and_arity_set_equality():
    # the drift invariant, asserted directly: the union of extern "C"
    # exports across every native .cpp equals DECLS exactly, name AND
    # arity — not just the subset direction the width checker implies
    from dgraph_tpu import native

    exports = {}
    for text in _real_cpp_texts().values():
        exports.update(check_ctypes_abi.parse_cpp_exports(text))
    assert set(exports) == set(native.DECLS), (
        sorted(set(exports) ^ set(native.DECLS))
    )
    for name, (_ret, params, _line) in exports.items():
        assert len(params) == len(native.DECLS[name][1]), (
            f"{name}: .cpp takes {len(params)} args, "
            f"DECLS declares {len(native.DECLS[name][1])}"
        )


def test_decls_drift_detected_on_mutated_real_source():
    # seed drift into the REAL codec.cpp text (proving the parser
    # handles the production file, not just synthetic fixtures):
    # 1. an extra parameter on a live kernel -> arity-mismatch
    from dgraph_tpu import native

    texts = _real_cpp_texts()
    cpp = texts["native/codec.cpp"]
    needle = "int64_t sst_scan("
    assert needle in cpp, "sst_scan prototype moved; update this test"
    mutated = dict(texts)
    mutated["native/codec.cpp"] = cpp.replace(
        needle, "int64_t sst_scan(int32_t extra_flag, ", 1
    )
    out = check_ctypes_abi.check_abi(
        mutated, native.DECLS, "native/__init__.py"
    )
    assert any(
        v.code in ("arity-mismatch", "arg-type-mismatch")
        and "sst_scan" in v.message
        for v in out
    ), [v.render() for v in out]

    # 2. a renamed export -> stale-decl (old name) + undeclared-export
    mutated["native/codec.cpp"] = cpp.replace(
        "int64_t sst_scan(", "int64_t sst_scan_v2(", 1
    )
    codes = sorted(
        v.code for v in check_ctypes_abi.check_abi(
            mutated, native.DECLS, "native/__init__.py"
        )
    )
    assert "stale-decl" in codes and "undeclared-export" in codes, codes

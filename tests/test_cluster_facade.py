"""HTTP + gRPC front-ends over the distributed cluster (ClusterFacade):
the same wire surface the single-node Server exposes, served by a
sharded, replicated engine."""

import json

import pytest

from dgraph_tpu.worker.facade import ClusterFacade
from dgraph_tpu.worker.groups import DistributedCluster


@pytest.fixture(scope="module")
def facade():
    c = DistributedCluster(n_groups=2, replicas=3)
    f = ClusterFacade(c)
    yield f
    c.close()


def test_facade_txn_roundtrip(facade):
    facade.alter("name: string @index(exact) .\nfriend: [uid] .")
    t = facade.new_txn()
    uids = t.mutate_rdf(
        set_rdf='_:a <name> "fc-alice" .\n_:a <friend> _:b .\n'
        '_:b <name> "fc-bob" .',
        commit_now=True,
    )
    assert "a" in uids
    out = facade.query('{ q(func: eq(name, "fc-alice")) { name friend { name } } }')
    assert out["data"]["q"][0]["friend"][0]["name"] == "fc-bob"


def test_http_over_cluster(facade):
    import urllib.request

    from dgraph_tpu.api.http_server import HTTPServer

    srv = HTTPServer(facade, port=0).start()
    try:
        def post(path, body, ctype="application/rdf"):
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}{path}",
                data=body.encode(),
                headers={"Content-Type": ctype},
                method="POST",
            )
            with urllib.request.urlopen(req) as r:
                return json.loads(r.read())

        out = post(
            "/mutate?commitNow=true", '{ set { _:x <name> "fc-neo" . } }'
        )
        assert out["data"]["code"] == "Success"
        res = post("/query", '{ q(func: eq(name, "fc-neo")) { name } }')
        assert res["data"]["q"] == [{"name": "fc-neo"}]
    finally:
        srv.stop()


def test_grpc_over_cluster(facade):
    import grpc

    from dgraph_tpu.api.grpc_server import pb, serve

    gs, port = serve(facade)
    try:
        ch = grpc.insecure_channel(f"127.0.0.1:{port}")
        q = ch.unary_unary(
            "/api.Dgraph/Query",
            request_serializer=pb.Request.SerializeToString,
            response_deserializer=pb.Response.FromString,
        )
        req = pb.Request(commit_now=True)
        m = req.mutations.add()
        m.set_nquads = b'_:g <name> "fc-grpc" .'
        resp = q(req)
        assert resp.txn.commit_ts > 0
        out = q(
            pb.Request(
                read_only=True,
                query='{ q(func: eq(name, "fc-grpc")) { name } }',
            )
        )
        assert json.loads(out.json)["q"][0]["name"] == "fc-grpc"
    finally:
        gs.stop(0)


def test_cluster_drop_attr_and_all(facade):
    facade.alter("tmp1: string @index(exact) .\nkeep: string @index(exact) .")
    t = facade.new_txn()
    t.mutate_rdf(
        set_rdf='_:a <tmp1> "gone" .\n_:b <keep> "stays" .', commit_now=True
    )
    facade.alter(drop_attr="tmp1")
    assert facade.schema.get("tmp1") is None
    out = facade.query('{ q(func: eq(keep, "stays")) { keep } }')
    assert out["data"]["q"][0]["keep"] == "stays"
    facade.alter(drop_all=True)
    assert facade.schema.get("keep") is None

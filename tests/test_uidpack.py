"""UidPack codec roundtrip tests (mirrors /root/reference/codec/codec_test.go)."""

import numpy as np
import pytest

from dgraph_tpu.codec import uidpack


def _rand_uids(rng, n, hi=1 << 34):
    return np.unique(rng.integers(1, hi, size=n, dtype=np.uint64))


@pytest.mark.parametrize("n", [0, 1, 255, 256, 257, 1000, 100_000])
def test_encode_decode_roundtrip(n):
    rng = np.random.default_rng(n)
    uids = _rand_uids(rng, n)
    pack = uidpack.encode(uids)
    assert pack.num_uids == len(uids)
    np.testing.assert_array_equal(uidpack.decode(pack), uids)


def test_hi32_boundary_split():
    # UIDs straddling a hi-32 boundary must land in different blocks
    # (offsets always fit uint32) — mirrors codec.go:117 split rule.
    uids = np.array(
        [1, 2, (1 << 32) - 1, 1 << 32, (1 << 32) + 5, (5 << 32) + 7],
        dtype=np.uint64,
    )
    pack = uidpack.encode(uids)
    np.testing.assert_array_equal(uidpack.decode(pack), uids)
    assert pack.nblocks >= 3


@pytest.mark.parametrize("n", [0, 1, 300, 5000])
def test_serialize_roundtrip(n):
    rng = np.random.default_rng(n + 99)
    uids = _rand_uids(rng, n)
    pack = uidpack.encode(uids)
    data = uidpack.serialize(pack)
    back = uidpack.deserialize(data)
    np.testing.assert_array_equal(uidpack.decode(back), uids)


def test_compression_clustered():
    # Clustered UIDs (the codec bench corpus shape, codec/benchmark) should
    # compress well below 8 bytes/uid.
    rng = np.random.default_rng(7)
    start = 0
    chunks = []
    for _ in range(1000):
        start += rng.integers(1, 1000)
        chunks.append(np.arange(start, start + 1000, dtype=np.uint64))
        start += 1000
    uids = np.concatenate(chunks)
    pack = uidpack.encode(uids)
    data = uidpack.serialize(pack)
    bytes_per_uid = len(data) / len(uids)
    assert bytes_per_uid < 2.5, bytes_per_uid
    back = uidpack.deserialize(data)
    np.testing.assert_array_equal(uidpack.decode(back), uids)


def test_split_join_segments():
    rng = np.random.default_rng(11)
    uids = _rand_uids(rng, 10_000, hi=1 << 36)
    segs = uidpack.split_segments(uids)
    np.testing.assert_array_equal(uidpack.join_segments(segs), uids)


def test_dispatcher_pairs():
    from dgraph_tpu.query.dispatch import SetOpDispatcher

    rng = np.random.default_rng(21)
    d = SetOpDispatcher()
    pairs = []
    for _ in range(9):
        a = _rand_uids(rng, int(rng.integers(0, 3000)), hi=1 << 33)
        b = _rand_uids(rng, int(rng.integers(0, 3000)), hi=1 << 33)
        pairs.append((a, b))
    for op, ref in [
        ("intersect", lambda a, b: np.intersect1d(a, b, assume_unique=True)),
        ("union", np.union1d),
        ("difference", lambda a, b: np.setdiff1d(a, b, assume_unique=True)),
    ]:
        got = d.run_pairs(op, pairs)
        for (a, b), g in zip(pairs, got):
            np.testing.assert_array_equal(
                np.asarray(g, np.uint64), ref(a, b), err_msg=op
            )


def test_dispatcher_forced_device(monkeypatch):
    import dgraph_tpu.query.dispatch as dispatch

    monkeypatch.setattr(dispatch, "_DEVICE_MIN_TOTAL", 1)
    rng = np.random.default_rng(22)
    d = dispatch.SetOpDispatcher()
    pairs = [
        (_rand_uids(rng, 50, hi=1 << 33), _rand_uids(rng, 70, hi=1 << 33))
        for _ in range(4)
    ]
    got = d.run_pairs("intersect", pairs)
    for (a, b), g in zip(pairs, got):
        np.testing.assert_array_equal(
            np.asarray(g, np.uint64), np.intersect1d(a, b, assume_unique=True)
        )


def test_native_layer():
    from dgraph_tpu import native

    rng = np.random.default_rng(5)
    a = _rand_uids(rng, 5000, hi=1 << 40)
    b = _rand_uids(rng, 300, hi=1 << 40)
    np.testing.assert_array_equal(
        native.intersect(a, b), np.intersect1d(a, b, assume_unique=True)
    )
    np.testing.assert_array_equal(native.union(a, b), np.union1d(a, b))
    np.testing.assert_array_equal(
        native.difference(a, b), np.setdiff1d(a, b, assume_unique=True)
    )
    vals = np.asarray(rng.integers(0, 1 << 17, 777), np.uint32)
    for w in (1, 7, 17, 32):
        vv = vals & ((1 << w) - 1) if w < 32 else vals
        packed = native.bitpack(vv, w)
        np.testing.assert_array_equal(native.bitunpack(packed, len(vv), w), vv)
    # native and python paths produce identical bytes
    if native.NATIVE_AVAILABLE:
        from dgraph_tpu.codec.uidpack import _bitpack_py

        assert native.bitpack(vv, 17) == _bitpack_py(vals & 0x1FFFF, 17)


def test_rows_vs_one_shared_operand(monkeypatch):
    import dgraph_tpu.query.dispatch as dispatch

    monkeypatch.setattr(dispatch, "_DEVICE_MIN_TOTAL", 1)
    rng = np.random.default_rng(31)
    d = dispatch.SetOpDispatcher()
    b = _rand_uids(rng, 2000, hi=1 << 31)
    rows = [_rand_uids(rng, int(n), hi=1 << 31) for n in (5, 120, 0, 700)]
    for op, ref in [
        ("intersect", lambda a: np.intersect1d(a, b, assume_unique=True)),
        ("difference", lambda a: np.setdiff1d(a, b, assume_unique=True)),
        ("union", lambda a: np.union1d(a, b)),
    ]:
        got = d.run_rows_vs_one(op, rows, b)
        for r, g in zip(rows, got):
            np.testing.assert_array_equal(np.asarray(g, np.uint64), ref(r), err_msg=op)

    # multi-segment operands fall back to the generic pair path correctly
    b2 = np.concatenate([b, (np.uint64(5) << np.uint64(32)) + np.arange(3, dtype=np.uint64)])
    got = d.run_rows_vs_one("intersect", rows, np.sort(b2))
    for r, g in zip(rows, got):
        np.testing.assert_array_equal(
            np.asarray(g, np.uint64), np.intersect1d(r, b2, assume_unique=True)
        )

"""LDBC SNB interactive-short-read conformance (systest/ldbc analog).

The reference asserts golden answers for IS01..IS07 over the real SNB
dataset (/root/reference/systest/ldbc/test_cases.yaml); the dataset is
CI-fetched and unavailable here, so these tests run the SAME query
shapes over benchmarks/ldbc_corpus.py's synthetic SNB-shaped graph and
assert against goldens derived from the corpus model, independent of
the engine.
"""

import json

import pytest

from benchmarks.ldbc_corpus import generate, SCHEMA
from dgraph_tpu.api.server import Server
from dgraph_tpu.loaders.bulk2 import ParallelBulkLoader


@pytest.fixture(scope="module")
def ldbc():
    corpus, rdf = generate(n_persons=120, n_posts=300, n_comments=450)
    s = Server()
    s.alter(SCHEMA)
    ld = ParallelBulkLoader(s, workers=1)
    ld.load_text("\n".join(rdf))
    return s, corpus


def _q(s, dql):
    out = s.query(dql)
    assert "errors" not in out, out
    return out["data"]


def test_is01_profile(ldbc):
    s, c = ldbc
    pu = next(iter(c.persons))
    p = c.persons[pu]
    data = _q(
        s,
        f'{{ q(func: eq(fqid, "person_{p.sid}")) {{ firstName lastName '
        "birthday locationIP browserUsed gender isLocatedIn { id name } } }",
    )
    row = data["q"][0]
    assert row["firstName"] == p.first
    assert row["lastName"] == p.last
    assert row["locationIP"] == p.ip
    assert row["browserUsed"] == p.browser
    assert row["gender"] == p.gender
    assert row["isLocatedIn"][0]["name"] == c.places[p.place]
    assert row["isLocatedIn"][0]["id"] == c.place_ids[p.place]


def test_is02_recent_messages(ldbc):
    """~hasCreator ordered newest-first with replyOf chain (IS02)."""
    s, c = ldbc
    # pick a person with >= 3 messages
    pu = max(c.persons, key=lambda u: len(c.messages_by(u)))
    p = c.persons[pu]
    data = _q(
        s,
        f'{{ q(func: eq(fqid, "person_{p.sid}")) {{ '
        "~hasCreator(orderdesc: creationDate, first: 10) { "
        "id content creationDate replyOf { id hasCreator { id } } } } }",
    )
    rows = data["q"][0]["~hasCreator"]
    mine = sorted(
        c.messages_by(pu),
        key=lambda mu: (-c.messages[mu].creation, mu),
    )[:10]
    assert [r["id"] for r in rows] == [c.messages[mu].sid for mu in mine]
    for r, mu in zip(rows, mine):
        m = c.messages[mu]
        if m.reply_of is not None:
            parent = c.messages[m.reply_of]
            assert r["replyOf"][0]["id"] == parent.sid
            assert r["replyOf"][0]["hasCreator"][0]["id"] == c.persons[
                parent.creator
            ].sid


def test_is03_friends_with_facet_order(ldbc):
    """knows @facets(orderdesc: creationDate) — friendship list newest
    first with the facet value surfaced (IS03)."""
    s, c = ldbc
    pu = max(c.persons, key=lambda u: len(c.knows_of(u)))
    p = c.persons[pu]
    data = _q(
        s,
        f'{{ q(func: eq(fqid, "person_{p.sid}")) {{ '
        "knows @facets(orderdesc: creationDate) { id firstName lastName } } }",
    )
    rows = data["q"][0]["knows"]
    want = sorted(c.knows_of(pu), key=lambda fm: (-fm[1], fm[0]))
    assert [r["id"] for r in rows] == [c.persons[f].sid for f, _ in want]
    # facet value present on each row (knows|creationDate)
    assert all("knows|creationDate" in r for r in rows)


def test_is04_message_content(ldbc):
    s, c = ldbc
    mu = next(u for u, m in c.messages.items() if m.kind == "post" and m.content)
    m = c.messages[mu]
    data = _q(
        s,
        f'{{ q(func: eq(fqid, "post_{m.sid}")) '
        "{ creationDate content imageFile } }",
    )
    row = data["q"][0]
    assert row["content"] == m.content


def test_is05_message_creator(ldbc):
    s, c = ldbc
    mu = next(u for u, m in c.messages.items() if m.kind == "post")
    m = c.messages[mu]
    data = _q(
        s,
        f'{{ q(func: eq(fqid, "post_{m.sid}")) '
        "{ hasCreator { id firstName lastName } } }",
    )
    row = data["q"][0]["hasCreator"][0]
    cr = c.persons[m.creator]
    assert row["id"] == cr.sid
    assert row["firstName"] == cr.first
    assert row["lastName"] == cr.last


def test_is06_forum_of_post(ldbc):
    s, c = ldbc
    fu, f = next(iter(c.forums.items()))
    post = f.posts[0]
    m = c.messages[post]
    data = _q(
        s,
        f'{{ q(func: eq(fqid, "post_{m.sid}")) {{ '
        "~containerOf { id title hasModerator { id firstName lastName } } } }",
    )
    row = data["q"][0]["~containerOf"][0]
    assert row["id"] == f.sid
    assert row["title"] == f.title
    assert row["hasModerator"][0]["id"] == c.persons[f.moderator].sid


def test_is07_replies_with_knows_filter(ldbc):
    """var block + uid() + ~replyOf + knows @filter(uid(c)) (IS07)."""
    s, c = ldbc
    # find a post with replies
    mu = next(
        u
        for u, m in c.messages.items()
        if m.kind == "post" and c.replies_to(u)
    )
    m = c.messages[mu]
    data = _q(
        s,
        f'{{ mid as var(func: eq(fqid, "post_{m.sid}")) {{ c as hasCreator }} '
        "q(func: uid(mid)) { ~replyOf(orderdesc: creationDate) { "
        "id content hasCreator { id knows @filter(uid(c)) { id } } } } }",
    )
    rows = data["q"][0]["~replyOf"]
    want = sorted(
        c.replies_to(mu), key=lambda u: (-c.messages[u].creation, u)
    )
    assert [r["id"] for r in rows] == [c.messages[u].sid for u in want]
    # knows-filter: replier's friendship with the original poster
    for r, ru in zip(rows, want):
        replier = c.messages[ru].creator
        friends = {f for f, _ in c.knows_of(replier)}
        if m.creator in friends:
            assert r["hasCreator"][0]["knows"][0]["id"] == c.persons[
                m.creator
            ].sid
        else:
            assert "knows" not in r["hasCreator"][0]


def test_fof_2hop_golden(ldbc):
    """The north-star traversal: 2-hop friends-of-friends via knows,
    asserted against the model (BASELINE.json LDBC 2-hop)."""
    s, c = ldbc
    pu = max(c.persons, key=lambda u: len(c.knows_of(u)))
    p = c.persons[pu]
    data = _q(
        s,
        f'{{ me as var(func: eq(fqid, "person_{p.sid}")) {{ '
        "f as knows } "
        "q(func: uid(f)) { fof as knows @filter(NOT uid(me) AND NOT uid(f)) } "
        "res(func: uid(fof)) { id } }",
    )
    got = sorted(r["id"] for r in data["res"])
    want = sorted(c.persons[u].sid for u in c.friends_of_friends(pu))
    assert got == want


def test_latency_budgets(ldbc):
    """The reference runs its whole LDBC suite under one 10-minute
    deadline (systest/ldbc/ldbc_test.go:47 context.WithTimeout); there
    are no per-query budgets in test_cases.yaml. We hold a much tighter
    line: on this small corpus every IS-style short read must finish in
    single-digit ms (warm), and the north-star FoF traversal under 5ms
    — the round-3 '113ms engine floor' was a bench-accounting artifact
    (benchmarks/ldbc_corpus.py knows_of was O(E) inside the timed loop)
    and must never creep back into the engine itself."""
    import time

    s, c = ldbc
    pu = max(c.persons, key=lambda u: len(c.knows_of(u)))
    p = c.persons[pu]
    fof = (
        f'{{ me as var(func: eq(fqid, "person_{p.sid}")) {{ f as knows }} '
        "q(func: uid(f)) { fof as knows @filter(NOT uid(me) AND NOT uid(f)) } "
        "res(func: uid(fof)) { count(uid) } }"
    )
    profile = (
        f'{{ q(func: eq(fqid, "person_{p.sid}")) {{ firstName lastName '
        "birthday locationIP browserUsed gender isLocatedIn { id name } } }"
    )
    for q, budget_ms, label in ((profile, 10, "IS01"), (fof, 5, "FoF")):
        s.query(q)  # warm
        t0 = time.perf_counter()
        n = 5
        for _ in range(n):
            s.query(q)
        ms = (time.perf_counter() - t0) / n * 1e3
        # generous 4x headroom over typical (~1-3ms) for CI-box noise
        assert ms < budget_ms * 4, f"{label} took {ms:.1f}ms (budget {budget_ms}ms x4)"

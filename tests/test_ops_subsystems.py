"""CDC, rollups, MCP, CLI subsystem tests."""

import io
import json
import subprocess
import sys

import pytest

from dgraph_tpu.api.server import Server
from dgraph_tpu.admin.cdc import CDC
from dgraph_tpu.posting.rollup import rollup_all
from dgraph_tpu.api.mcp_server import McpServer

SCHEMA = "name: string @index(exact) .\nfriend: [uid] ."


def test_cdc_events(tmp_path):
    path = str(tmp_path / "cdc.ndjson")
    s = Server()
    s.alter(SCHEMA)
    cdc = CDC(s, sink_path=path)
    t = s.new_txn()
    t.mutate_rdf(
        set_rdf='<0x1> <name> "A" .\n<0x1> <friend> <0x2> .', commit_now=True
    )
    t = s.new_txn()
    t.mutate_rdf(del_rdf='<0x1> <friend> <0x2> .', commit_now=True)
    cdc.close()
    events = [json.loads(l) for l in open(path)]
    ops = [(e["event"]["operation"], e["event"]["attr"]) for e in events]
    assert ("set", "name") in ops
    assert ("set", "friend") in ops
    assert ("del", "friend") in ops
    assert cdc.checkpoint > 0
    # commit_ts monotone
    ts = [e["meta"]["commit_ts"] for e in events]
    assert ts == sorted(ts)


def test_rollup_compacts_chains():
    from dgraph_tpu.posting.pl import KIND_DELTA
    from dgraph_tpu.x import keys

    s = Server()
    s.alter(SCHEMA)
    for i in range(5):
        t = s.new_txn()
        t.mutate_rdf(set_rdf=f'<0x1> <friend> <{hex(10 + i)}> .', commit_now=True)
    key = keys.DataKey("friend", 1)
    assert len(s.kv.versions(key, 1 << 61)) == 5
    n = rollup_all(s, min_deltas=2)
    assert n >= 1
    vers = s.kv.versions(key, 1 << 61)
    assert len(vers) == 1 and vers[0][1][0] != KIND_DELTA
    res = s.query("{ q(func: uid(0x1)) { friend { uid } } }")["data"]
    assert len(res["q"][0]["friend"]) == 5
    # reads at old timestamps still possible at/after the rollup ts
    res = s.query("{ q(func: uid(0x1)) { friend { uid } } }", read_ts=vers[0][0])[
        "data"
    ]
    assert len(res["q"][0]["friend"]) == 5


def test_mcp_protocol():
    s = Server()
    s.alter(SCHEMA)
    mcp = McpServer(s)
    r = mcp.handle({"jsonrpc": "2.0", "id": 1, "method": "initialize"})
    assert r["result"]["serverInfo"]["name"] == "dgraph-tpu-mcp"
    r = mcp.handle({"jsonrpc": "2.0", "id": 2, "method": "tools/list"})
    names = {t["name"] for t in r["result"]["tools"]}
    assert {"run_query", "run_mutation", "alter_schema", "get_schema"} <= names
    r = mcp.handle(
        {
            "jsonrpc": "2.0",
            "id": 3,
            "method": "tools/call",
            "params": {
                "name": "run_mutation",
                "arguments": {"set_rdf": '<0x1> <name> "M" .'},
            },
        }
    )
    assert "uids" in json.loads(r["result"]["content"][0]["text"])
    r = mcp.handle(
        {
            "jsonrpc": "2.0",
            "id": 4,
            "method": "tools/call",
            "params": {
                "name": "run_query",
                "arguments": {"query": '{ q(func: eq(name, "M")) { uid } }'},
            },
        }
    )
    out = json.loads(r["result"]["content"][0]["text"])
    assert out["data"]["q"] == [{"uid": "0x1"}]
    r = mcp.handle({"jsonrpc": "2.0", "id": 5, "method": "nope"})
    assert r["error"]["code"] == -32601


def test_mcp_stdio_loop():
    s = Server()
    s.alter(SCHEMA)
    mcp = McpServer(s)
    stdin = io.StringIO(
        json.dumps({"jsonrpc": "2.0", "id": 1, "method": "tools/list"}) + "\n"
    )
    stdout = io.StringIO()
    mcp.serve_stdio(stdin=stdin, stdout=stdout)
    resp = json.loads(stdout.getvalue())
    assert resp["id"] == 1 and "tools" in resp["result"]


def test_cli_bulk_export_debug_increment(tmp_path):
    rdf = tmp_path / "data.rdf"
    rdf.write_text('_:a <name> "CliUser" .\n')
    schema = tmp_path / "schema.txt"
    schema.write_text("name: string @index(exact) .\n")
    pdir = str(tmp_path / "p")

    from dgraph_tpu.cli import main

    # bulk load into a p-dir
    main(["bulk", "-p", pdir, "--schema", str(schema), str(rdf)])
    # debug histogram sees the predicate
    import contextlib, io as _io

    buf = _io.StringIO()
    with contextlib.redirect_stdout(buf):
        main(["debug", "-p", pdir])
    hist = json.loads(buf.getvalue())
    assert "name" in hist and hist["name"]["data"] == 1
    # export from the p-dir
    buf = _io.StringIO()
    with contextlib.redirect_stdout(buf):
        main(["export", "-p", pdir, "--out", str(tmp_path / "exp")])
    out = json.loads(buf.getvalue())
    assert out["nquads"] == 1
    # increment smoke test
    buf = _io.StringIO()
    with contextlib.redirect_stdout(buf):
        main(["increment", "-p", pdir, "--num", "3"])
    assert "counter: 3" in buf.getvalue()


def test_task_queue_serializes_ops(tmp_path):
    import time

    from dgraph_tpu.admin import tasks

    s = Server()
    s.alter(SCHEMA)
    t = s.new_txn()
    t.mutate_rdf(set_rdf='<0x1> <name> "T" .', commit_now=True)

    order = []
    tq = tasks._queue_of(s)

    def slow(tag):
        def run():
            order.append(("start", tag))
            time.sleep(0.05)
            order.append(("end", tag))
            return tag
        return run

    t1 = tq.enqueue(tasks.KIND_EXPORT, slow("a"))
    t2 = tq.enqueue(tasks.KIND_BACKUP, slow("b"))
    assert tq.wait(t1)["status"] == "Success"
    assert tq.wait(t2)["status"] == "Success"
    # strictly serialized: no interleaving
    assert order == [("start", "a"), ("end", "a"), ("start", "b"), ("end", "b")]

    # real ops through the queue
    tid = tasks.enqueue_backup(s, str(tmp_path / "b"))
    st = tq.wait(tid)
    assert st["status"] == "Success" and st["result"]["records"] > 0
    tid = tasks.enqueue_rollup(s, min_deltas=1)
    assert tq.wait(tid)["status"] == "Success"
    # failures recorded, queue survives
    tid = tq.enqueue(tasks.KIND_EXPORT, lambda: 1 / 0)
    st = tq.wait(tid)
    assert st["status"] == "Failed" and "division" in st["error"]
    assert len(tq.list()) == 5


def test_http_draining_and_task_status(tmp_path):
    import json as _json
    import urllib.request as ur
    import urllib.error

    from dgraph_tpu.api.http_server import HTTPServer

    s = Server()
    s.alter(SCHEMA)
    srv = HTTPServer(s, port=0).start()
    base = f"http://127.0.0.1:{srv.port}"

    def post(path, body=""):
        req = ur.Request(base + path, data=body.encode(), method="POST")
        try:
            with ur.urlopen(req) as r:
                return r.status, _json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, _json.loads(e.read())

    code, _ = post("/admin/draining?enable=true")
    assert code == 200
    code, body = post("/mutate?commitNow=true", '{ set { <0x1> <name> "X" . } }')
    assert code == 503
    post("/admin/draining?enable=false")
    code, _ = post("/mutate?commitNow=true", '{ set { <0x1> <name> "X" . } }')
    assert code == 200
    # async backup + task status poll
    code, body = post(f"/admin/backup?destination={tmp_path}/bk&wait=false")
    tid = body["data"]["taskId"]
    import time as _t

    deadline = _t.time() + 10
    while _t.time() < deadline:
        code, st = post(f"/admin/task?id={tid}")
        if st["data"]["status"] in ("Success", "Failed"):
            break
        _t.sleep(0.05)
    assert st["data"]["status"] == "Success"
    srv.stop()


def test_count_min_sketch():
    import numpy as np

    from dgraph_tpu.utils.cmsketch import CountMinSketch, StatsHolder

    cms = CountMinSketch(epsilon=0.001, delta=0.01)
    rng = np.random.default_rng(0)
    truth = {}
    for i in range(200):
        key = f"tok{i}".encode()
        n = int(rng.integers(1, 500))
        truth[key] = n
        cms.add(key, n)
    # estimates never underestimate; overestimate bounded by eps * total
    slack = int(0.001 * cms.count * 3)
    for key, n in truth.items():
        est = cms.estimate(key)
        assert est >= n
        assert est <= n + slack
    # merging folds another sketch's counts into this one
    cms2 = CountMinSketch(epsilon=0.001, delta=0.01)
    cms2.add(b"tok0", 7)
    cms.merge(cms2)
    assert cms.estimate(b"tok0") >= truth[b"tok0"] + 7

    st = StatsHolder()
    st.record("name", b"a", 100)
    st.record("name", b"b", 5)
    st.record("name", b"c", 50)
    assert st.plan_eq_order("name", [b"a", b"b", b"c"]) == [b"b", b"c", b"a"]


def test_stats_auto_fed_and_planning():
    """cm-sketch selectivity stats are fed by commits/bulk and order
    allofterms scans rarest-token-first (ref worker/task.go
    planForEqFilter)."""
    from dgraph_tpu.api.server import Server

    s = Server()
    s.alter("bio: string @index(term) .")
    t = s.new_txn()
    rdf = []
    # 'common' appears in 50 docs, 'rare' in 2
    for i in range(1, 51):
        extra = " rare" if i <= 2 else ""
        rdf.append(f'<0x{i:x}> <bio> "common{extra} filler{i}" .')
    t.mutate_rdf(set_rdf="\n".join(rdf), commit_now=True)
    # stats recorded per token
    common_est = s.stats.estimate("bio", b"\x01common")
    rare_est = s.stats.estimate("bio", b"\x01rare")
    assert common_est > rare_est >= 2
    # plan orders rare first
    order = s.stats.plan_eq_order("bio", [b"\x01common", b"\x01rare"])
    assert order[0] == b"\x01rare"
    # and the query is correct
    out = s.query('{ q(func: allofterms(bio, "common rare")) { uid } }')
    assert len(out["data"]["q"]) == 2

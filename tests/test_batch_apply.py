"""Columnar batch-apply write path: byte-equality fuzz + invariants.

The columnar path (posting/colwrite + native.batch_apply) must be a
pure performance substitution: for any mutation workload, the KV bytes
it writes are identical to the per-edge serial loop's, and the
predicate-sharded residual apply must preserve the serial path's
outcome under concurrency. This suite drives a seeded mixed corpus
(flat scalars, uid lists, lang values, deletes — the slow shapes
exercise the fallback ladder) through both arms and asserts the full
store dumps match byte-for-byte, across shard widths {1, 2, 8}.
"""

import threading

import numpy as np
import pytest

from dgraph_tpu import native
from dgraph_tpu.api.server import Server
from dgraph_tpu.utils.observe import METRICS
from dgraph_tpu.x import config
from dgraph_tpu.zero.zero import TxnConflictError

requires_native = pytest.mark.skipif(
    not native.NATIVE_AVAILABLE, reason="native codec library not built"
)

SCHEMA = (
    "name: string @index(exact) .\n"
    "age: int @index(int) .\n"
    "bio: string @index(term) .\n"
    "city: string .\n"
    "alias: string @lang .\n"
    "alive: bool @index(bool) .\n"
    "knows: [uid] @reverse ."
)


def _set_knobs(**knobs):
    for name, value in knobs.items():
        config.set_env(name, value)


def _unset_knobs(*names):
    for name in names:
        config.unset_env(name)


def _run_corpus(seed: int, n_txns: int = 30):
    """Apply a seeded mixed workload to a fresh Server; return the full
    KV dump. Deterministic: uid assignment, txn order, and rng draws
    depend only on the seed, so both arms replay the same edges."""
    rng = np.random.default_rng(seed)
    s = Server()
    s.alter(SCHEMA)
    written_rdf = []  # (subj_hex, pred, literal) for later deletes
    auto = 0
    for _ in range(n_txns):
        t = s.new_txn()
        shape = int(rng.integers(0, 10))
        if shape < 5:
            # flat scalar objects + uid refs: the columnar fast path
            objs = []
            for _ in range(int(rng.integers(1, 5))):
                auto += 1
                objs.append(
                    {
                        "uid": f"_:n{auto}",
                        "name": f"user{int(rng.integers(0, 40))}",
                        "age": int(rng.integers(0, 90)),
                        "bio": f"likes topic{int(rng.integers(0, 8))} a lot",
                        "city": f"city{int(rng.integers(0, 6))}",
                        "alive": bool(rng.integers(0, 2)),
                        "knows": [{"uid": hex(int(rng.integers(1, 32)))}],
                    }
                )
            t.mutate_json(set_obj=objs, commit_now=True)
        elif shape < 7:
            # @lang values: fallback reason "lang"
            subj = int(rng.integers(1, 32))
            lang = ["en", "fr", "it"][int(rng.integers(0, 3))]
            t.mutate_rdf(
                set_rdf=f'<0x{subj:x}> <alias> "al{subj}"@{lang} .',
                commit_now=True,
            )
        elif shape < 9:
            # overwrite + remember for a later delete
            subj = int(rng.integers(1, 32))
            val = f"city{int(rng.integers(0, 6))}"
            t.mutate_rdf(
                set_rdf=f'<0x{subj:x}> <city> "{val}" .', commit_now=True
            )
            written_rdf.append((subj, "city", val))
        else:
            # delete shape: fallback reason "delete"
            if written_rdf:
                subj, pred, val = written_rdf[
                    int(rng.integers(0, len(written_rdf)))
                ]
                t.mutate_rdf(
                    del_rdf=f'<0x{subj:x}> <{pred}> "{val}" .',
                    commit_now=True,
                )
            else:
                t.discard()
    return {k: list(v) for k, v in s.kv._data.items()}


@requires_native
@pytest.mark.parametrize("shards", [1, 2, 8])
@pytest.mark.parametrize("seed", [7, 1234])
def test_batch_apply_byte_equality(shards, seed):
    """Native columnar arm vs per-edge serial arm: identical KV bytes
    for the same seeded corpus, at every forced shard width. The native
    arm must actually take the kernel (counter nonzero), and the serial
    arm must never touch it."""
    before = dict(METRICS.snapshot())
    _set_knobs(
        BATCH_APPLY=1,
        APPLY_SHARDS=shards,
        APPLY_SHARD_MIN_EDGES=1,
        EXEC_WORKERS=4,
    )
    try:
        native_dump = _run_corpus(seed)
        mid = dict(METRICS.snapshot())
        config.set_env("BATCH_APPLY", 0)
        serial_dump = _run_corpus(seed)
        after = dict(METRICS.snapshot())
    finally:
        _unset_knobs(
            "BATCH_APPLY",
            "APPLY_SHARDS",
            "APPLY_SHARD_MIN_EDGES",
            "EXEC_WORKERS",
        )
    diff = {
        k
        for k in native_dump.keys() | serial_dump.keys()
        if native_dump.get(k) != serial_dump.get(k)
    }
    assert not diff, f"{len(diff)} divergent keys, e.g. {sorted(diff)[:3]}"
    key = "mutation_batch_apply_total"
    assert mid.get(key, 0) > before.get(key, 0), "native arm skipped kernel"
    assert after.get(key, 0) == mid.get(key, 0), "serial arm hit kernel"
    if shards > 1:
        skey = "mutation_sharded_apply_total"
        assert after.get(skey, 0) > before.get(skey, 0), (
            "forced shard width never engaged the sharded apply"
        )


@requires_native
def test_fallback_reason_labels():
    """The slow shapes land on the per-reason fallback counters with
    the labels METRICS.md documents, while flat scalars stay native."""
    before = dict(METRICS.snapshot())
    _set_knobs(BATCH_APPLY=1)
    try:
        s = Server()
        s.alter(SCHEMA)
        t = s.new_txn()
        t.mutate_rdf(set_rdf='<0x1> <alias> "bob"@en .', commit_now=True)
        t = s.new_txn()
        t.mutate_rdf(set_rdf='<0x2> <city> "rome" .', commit_now=True)
        t = s.new_txn()
        t.mutate_rdf(del_rdf='<0x2> <city> "rome" .', commit_now=True)
    finally:
        _unset_knobs("BATCH_APPLY")
    after = dict(METRICS.snapshot())

    def delta(name):
        return after.get(name, 0) - before.get(name, 0)

    assert delta('mutation_native_fallback_total{reason="lang"}') >= 1
    assert delta('mutation_native_fallback_total{reason="delete"}') >= 1
    assert delta("mutation_batch_apply_total") >= 1  # the city SET stayed native
    total = delta("mutation_native_fallback_total")
    assert total >= 2


@requires_native
def test_read_your_writes_materializes_columns():
    """A query inside the writing txn must see column-collected edges:
    the read hook materializes them back into Python deltas (reason
    "read") and the commit still lands every edge."""
    before = dict(METRICS.snapshot())
    _set_knobs(BATCH_APPLY=1)
    try:
        s = Server()
        s.alter(SCHEMA)
        t = s.new_txn()
        t.mutate_json(
            set_obj=[{"uid": "_:a", "name": "ada", "age": 36}],
        )
        r = t.query('{ q(func: eq(name, "ada")) { uid age } }')
        assert r["data"]["q"] and r["data"]["q"][0]["age"] == 36
        t.commit()
        r2 = s.query('{ q(func: eq(name, "ada")) { uid age } }')
        assert r2["data"]["q"] and r2["data"]["q"][0]["age"] == 36
    finally:
        _unset_knobs("BATCH_APPLY")
    after = dict(METRICS.snapshot())
    assert after.get(
        'mutation_native_fallback_total{reason="read"}', 0
    ) > before.get('mutation_native_fallback_total{reason="read"}', 0)


# ---------------------------------------------------------------------------
# sharded residual apply under concurrency (bank invariants)
# ---------------------------------------------------------------------------

N_ACCOUNTS = 8
START_BAL = 100


def _bank_server():
    s = Server()
    s.alter(
        "bal: int @upsert .\n"
        "acct: string @index(exact) @upsert .\n"
        "last: string ."
    )
    rdf = []
    for i in range(1, N_ACCOUNTS + 1):
        rdf.append(f'<0x{i:x}> <acct> "a{i}" .')
        rdf.append(f'<0x{i:x}> <bal> "{START_BAL}"^^<xs:int> .')
    s.new_txn().mutate_rdf(set_rdf="\n".join(rdf), commit_now=True)
    return s


def test_sharded_apply_concurrent_bank():
    """Concurrent conflicting transfers through the Python apply path
    with sharding forced on (two predicates per transfer so the shard
    planner engages): SSI aborts still fire, committed transfers apply
    exactly once, and the balance sum is conserved."""
    _set_knobs(
        BATCH_APPLY=0,  # force the residual Python path the shards split
        APPLY_SHARDS=2,
        APPLY_SHARD_MIN_EDGES=1,
        EXEC_WORKERS=4,
    )
    before = dict(METRICS.snapshot())
    try:
        s = _bank_server()
        lock = threading.Lock()
        committed = []

        def worker(widx):
            rng = np.random.default_rng(1000 + widx)
            for step in range(12):
                frm, to = (
                    int(x) + 1
                    for x in rng.choice(N_ACCOUNTS, 2, replace=False)
                )
                amt = int(rng.integers(1, 15))
                t = s.new_txn()
                try:
                    got = t.query(
                        "{ a(func: uid(0x%x)) { bal } "
                        "b(func: uid(0x%x)) { bal } }" % (frm, to)
                    )
                    a_bal = got["data"]["a"][0]["bal"]
                    b_bal = got["data"]["b"][0]["bal"]
                    if a_bal < amt:
                        t.discard()
                        continue
                    t.mutate_rdf(
                        set_rdf=(
                            f'<0x{frm:x}> <bal> "{a_bal - amt}"'
                            f"^^<xs:int> .\n"
                            f'<0x{frm:x}> <last> "w{widx}s{step}" .\n'
                            f'<0x{to:x}> <bal> "{b_bal + amt}"'
                            f"^^<xs:int> .\n"
                            f'<0x{to:x}> <last> "w{widx}s{step}" .'
                        ),
                    )
                    t.commit()
                    with lock:
                        committed.append((frm, to, amt))
                except TxnConflictError:
                    pass

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(4)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()

        out = s.query("{ q(func: has(bal)) { uid bal } }")
        bals = {int(x["uid"], 16): x["bal"] for x in out["data"]["q"]}
        assert sum(bals.values()) == N_ACCOUNTS * START_BAL, bals
        ledger = {i: START_BAL for i in range(1, N_ACCOUNTS + 1)}
        for frm, to, amt in committed:
            ledger[frm] -= amt
            ledger[to] += amt
        assert bals == ledger, (bals, ledger)
        assert committed, "no transfer ever committed"
    finally:
        _unset_knobs(
            "BATCH_APPLY",
            "APPLY_SHARDS",
            "APPLY_SHARD_MIN_EDGES",
            "EXEC_WORKERS",
        )
    after = dict(METRICS.snapshot())
    assert after.get("mutation_sharded_apply_total", 0) > before.get(
        "mutation_sharded_apply_total", 0
    ), "sharded apply never engaged"


@pytest.mark.chaos
def test_chaos_bank_sharded_apply():
    """Chaos bank with the sharded apply forced on across the cluster
    (env knobs are inherited by spawned replicas): seeded drop/delay
    faults, ledger stays exact and the balance sum is conserved."""
    from dgraph_tpu.conn import faults
    from dgraph_tpu.conn.faults import FaultPlan
    from dgraph_tpu.worker.harness import ProcCluster

    _set_knobs(
        APPLY_SHARDS=2,
        APPLY_SHARD_MIN_EDGES=1,
        EXEC_WORKERS=4,
    )
    c = None
    try:
        c = ProcCluster(n_groups=1, replicas=3)
        c.alter(
            "bal: int @upsert .\n"
            "acct: string @index(exact) @upsert .\n"
            "last: string ."
        )
        rdf = []
        for i in range(1, N_ACCOUNTS + 1):
            rdf.append(f'<0x{i:x}> <acct> "a{i}" .')
            rdf.append(f'<0x{i:x}> <bal> "{START_BAL}"^^<xs:int> .')
        c.new_txn().mutate_rdf(set_rdf="\n".join(rdf), commit_now=True)

        faults.install(
            FaultPlan(
                seed=99,
                rules=[
                    dict(point="send", action="drop", p=0.04),
                    dict(point="send", action="delay", p=0.10, delay_ms=3),
                ],
            )
        )
        rng = np.random.default_rng(5)
        ledger = {i: START_BAL for i in range(1, N_ACCOUNTS + 1)}
        ambiguous = 0
        for step in range(8):
            frm, to = (
                int(x) + 1 for x in rng.choice(N_ACCOUNTS, 2, replace=False)
            )
            amt = int(rng.integers(1, 20))
            t = c.new_txn()
            try:
                t.mutate_rdf(
                    set_rdf=(
                        f'<0x{frm:x}> <bal> "{ledger[frm] - amt}"'
                        f"^^<xs:int> .\n"
                        f'<0x{frm:x}> <last> "s{step}" .\n'
                        f'<0x{to:x}> <bal> "{ledger[to] + amt}"'
                        f"^^<xs:int> .\n"
                        f'<0x{to:x}> <last> "s{step}" .'
                    ),
                    commit_now=True,
                )
                ledger[frm] -= amt
                ledger[to] += amt
            except TimeoutError:
                ambiguous += 1  # may or may not have applied
        faults.reset()
        out = c.query("{ q(func: has(bal)) { uid bal } }")
        bals = {int(x["uid"], 16): x["bal"] for x in out["data"]["q"]}
        assert sum(bals.values()) == N_ACCOUNTS * START_BAL, bals
        if ambiguous == 0:
            assert bals == ledger, (bals, ledger)
    finally:
        from dgraph_tpu.conn import faults as _f

        _f.reset()
        _unset_knobs("APPLY_SHARDS", "APPLY_SHARD_MIN_EDGES", "EXEC_WORKERS")
        if c is not None:
            c.close()


# ---------------------------------------------------------------------------
# multi-process apply plane (worker/applyshard)
# ---------------------------------------------------------------------------


@requires_native
@pytest.mark.parametrize("shards", [1, 2, 8])
@pytest.mark.parametrize("procs", [0, 1, 2])
def test_proc_shard_byte_equality(procs, shards):
    """The multi-process apply plane is a pure transport substitution:
    for every APPLY_PROCS width the full KV dump must match the serial
    per-edge arm byte-for-byte, the shard-process kernel counter must
    move iff procs > 0 (and the in-process path must run iff procs == 0),
    and no batch may fall back. APPLY_SHARDS varies independently so the
    thread-sharded residual path and the proc plane are exercised in
    every combination."""
    from dgraph_tpu.worker import applyshard

    before = dict(METRICS.snapshot())
    _set_knobs(
        BATCH_APPLY=1,
        APPLY_PROCS=procs,
        APPLY_SHARDS=shards,
        APPLY_SHARD_MIN_EDGES=1,
        EXEC_WORKERS=4,
    )
    try:
        native_dump = _run_corpus(7)
        mid = dict(METRICS.snapshot())
        config.set_env("BATCH_APPLY", 0)
        serial_dump = _run_corpus(7)
    finally:
        _unset_knobs(
            "BATCH_APPLY",
            "APPLY_PROCS",
            "APPLY_SHARDS",
            "APPLY_SHARD_MIN_EDGES",
            "EXEC_WORKERS",
        )
        applyshard.shutdown()
    diff = {
        k
        for k in native_dump.keys() | serial_dump.keys()
        if native_dump.get(k) != serial_dump.get(k)
    }
    assert not diff, f"{len(diff)} divergent keys, e.g. {sorted(diff)[:3]}"

    def delta(name):
        return mid.get(name, 0) - before.get(name, 0)

    assert delta("mutation_batch_apply_total") > 0, "kernel never ran"
    if procs > 0:
        assert delta("apply_shard_batches_total") > 0, (
            "proc plane never took a batch"
        )
        assert delta("apply_shard_fallback_total") == 0, (
            "proc plane fell back during a healthy run"
        )
    else:
        assert delta("apply_shard_batches_total") == 0, (
            "APPLY_PROCS=0 escape hatch still dispatched to processes"
        )


@requires_native
@pytest.mark.chaos
def test_chaos_proc_shard_sigkill_bank():
    """SIGKILL an apply-shard worker between bank transfers: the dead
    shard surfaces as a crash fallback, the batch replays through the
    serial in-process kernel (so the ledger stays exact — 0 lost, 0
    duplicated edges), the worker is respawned, and later batches flow
    through the pool again."""
    import os
    import signal
    import time

    from dgraph_tpu.worker import applyshard

    _set_knobs(
        BATCH_APPLY=1,
        APPLY_PROCS=2,
        APPLY_SHARD_MIN_EDGES=1,
    )
    before = dict(METRICS.snapshot())
    try:
        s = _bank_server()
        ledger = {i: START_BAL for i in range(1, N_ACCOUNTS + 1)}
        rng = np.random.default_rng(17)

        def transfer(step):
            frm, to = (
                int(x) + 1
                for x in rng.choice(N_ACCOUNTS, 2, replace=False)
            )
            amt = int(rng.integers(1, 15))
            t = s.new_txn()
            t.mutate_rdf(
                set_rdf=(
                    f'<0x{frm:x}> <bal> "{ledger[frm] - amt}"'
                    f"^^<xs:int> .\n"
                    f'<0x{frm:x}> <last> "s{step}" .\n'
                    f'<0x{to:x}> <bal> "{ledger[to] + amt}"'
                    f"^^<xs:int> .\n"
                    f'<0x{to:x}> <last> "s{step}" .'
                ),
                commit_now=True,
            )
            ledger[frm] -= amt
            ledger[to] += amt

        for step in range(6):
            transfer(step)
        pool = applyshard.maybe_pool()
        assert pool is not None, "pool never came up"
        victim = pool.worker_pids()[0]
        os.kill(victim, signal.SIGKILL)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            try:
                os.kill(victim, 0)
            except ProcessLookupError:
                break
            time.sleep(0.01)
        for step in range(6, 12):
            transfer(step)

        out = s.query("{ q(func: has(bal)) { uid bal } }")
        bals = {int(x["uid"], 16): x["bal"] for x in out["data"]["q"]}
        assert sum(bals.values()) == N_ACCOUNTS * START_BAL, bals
        assert bals == ledger, (bals, ledger)

        after = dict(METRICS.snapshot())

        def delta(name):
            return after.get(name, 0) - before.get(name, 0)

        assert delta("apply_shard_fallback_total") >= 1, (
            "killed worker never surfaced as a fallback"
        )
        assert delta('apply_shard_fallback_total{reason="crash"}') >= 1
        # respawned: the pool is healthy again and took post-kill batches
        pool = applyshard.maybe_pool()
        assert pool is not None and pool.disabled is None
        assert victim not in pool.worker_pids()
        for pid in pool.worker_pids():
            os.kill(pid, 0)  # raises if the respawn died
    finally:
        _unset_knobs("BATCH_APPLY", "APPLY_PROCS", "APPLY_SHARD_MIN_EDGES")
        applyshard.shutdown()

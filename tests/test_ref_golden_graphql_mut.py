"""GraphQL *mutation* conformance against the reference's rewriter
oracles (VERDICT r4 #3).

Cases: tests/ref_golden_graphql/mutation_cases.json, extracted from
/root/reference/graphql/resolve/{add,update,delete,validate}_mutation_test.yaml
(driven there by mutation_test.go TestMutationRewriting).

Execution-equivalence (see mutation_support.py): both sides run against
OUR engine on identical seeded worlds — our GraphQL layer on store A,
the reference-blessed plan (dgquery/dgquerysec + setjson/deletejson/
@if conds via Txn.upsert_json) on store B — and the resulting graphs
must match modulo uid renaming. Error cases must error on side A too.

Failures are tracked in known_fails_mut.json (strict xfail — a fixed
case must be removed); shrinking it is the metric.
"""

import json
import os
import sys

import pytest

# the reference YAMLs freeze $now (@default) at this instant
os.environ.setdefault("DGRAPH_TPU_FAKE_NOW", "2000-01-01T00:00:00.00Z")

HERE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "ref_golden_graphql"
)
sys.path.insert(0, HERE)

CASES = json.load(open(os.path.join(HERE, "mutation_cases.json")))
SCHEMA = open(os.path.join(HERE, "resolve_schema.graphql")).read()


def _load(name):
    p = os.path.join(HERE, name)
    return set(json.load(open(p))) if os.path.exists(p) else set()


KNOWN = _load("known_fails_mut.json")


@pytest.mark.parametrize(
    "case",
    [
        pytest.param(
            c,
            marks=(
                [pytest.mark.xfail(strict=True, reason="tracked gap")]
                if c["id"] in KNOWN
                else []
            ),
        )
        for c in CASES
    ],
    ids=[c["id"] for c in CASES],
)
def test_graphql_mutation_equiv(case):
    import mutation_support as ms

    types = __import__(
        "dgraph_tpu.graphql.sdl", fromlist=["parse_sdl"]
    ).parse_sdl(SCHEMA)
    seeds, max_uid = ms.seed_objects(case, types)

    # --- side A: our GraphQL layer -------------------------------------
    sa, gql = ms.make_server(SCHEMA, max_uid)
    ms.apply_seed(sa, seeds)
    res = gql.execute(
        case["gqlmutation"], variables=case.get("gqlvariables")
    )
    errored = bool(res.get("errors"))

    wants_error = any(
        k in case for k in ("error", "error2", "validationerror")
    )
    if wants_error:
        assert errored, (
            f"reference rejects this mutation "
            f"({case.get('error') or case.get('error2') or case.get('validationerror')!r}) "
            f"but ours succeeded: {res}"
        )
        return
    assert not errored, res["errors"]

    # --- side B: reference plan through our engine ---------------------
    sb, _ = ms.make_server(SCHEMA, max_uid)
    ms.apply_seed(sb, seeds)
    query = case.get("dgquerysec") or ""
    if case["kind"] == "delete":
        query = case.get("dgquery") or query
    txn = sb.new_txn()
    txn.upsert_json(query, case.get("dgmutations", []), commit_now=True)
    if case.get("dgmutationssec"):
        txn2 = sb.new_txn()
        txn2.upsert_json(
            query, case["dgmutationssec"], commit_now=True
        )

    got = ms.canonicalize(ms.dump_triples(sa))
    want = ms.canonicalize(ms.dump_triples(sb))
    assert got == want, _diff(got, want)


def _diff(got, want):
    gs, ws = set(map(repr, got)), set(map(repr, want))
    extra = sorted(gs - ws)[:12]
    missing = sorted(ws - gs)[:12]
    return (
        f"state mismatch\n  ours-only ({len(gs - ws)}): {extra}\n"
        f"  ref-only ({len(ws - gs)}): {missing}"
    )

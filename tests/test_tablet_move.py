"""Crash-safe live tablet moves: phased migration + durable move journal.

Layers:
  - pure units: the deterministic size-based rebalance picker over
    adversarial distributions; MoveJournal torn-tail recovery at every
    byte boundary (test_wal_crash.py-style).
  - in-process DistributedCluster: chunked multi-proposal moves, the
    bounded Phase-2 fence (commits on other predicates flow during
    Phase 1; fenced commits bounce RETRYABLE), selective MemoryLayer
    invalidation (an unrelated predicate's cache survives a move),
    coordinator-crash recovery at every journaled phase boundary
    (named `crash` fault points), durable journal recovery across a
    full cluster restart, replicated-Zero journaling, auto-rebalance.
  - multi-process ProcCluster chaos smoke (`chaos` marker, fixed seed):
    the bank workload runs while the move coordinator is killed at
    every phase boundary and the destination group is partitioned —
    after recovery the cluster heals to exactly-once placement with
    ledger-exact balances and exact edge counts.
"""

import threading
import time

import pytest

from dgraph_tpu.conn import faults
from dgraph_tpu.conn.faults import FaultPlan, InjectedCrash
from dgraph_tpu.conn.retry import Deadline, RetryPolicy, deadline_scope, retrying_call
from dgraph_tpu.utils.observe import METRICS
from dgraph_tpu.worker.groups import DistributedCluster
from dgraph_tpu.worker.tabletmove import (
    MoveJournal,
    TabletFencedError,
    pick_rebalance_move,
)
from dgraph_tpu.x import keys

CRASH_POINTS = (
    "move.begin", "move.copy", "move.fence",
    "move.delta", "move.flip", "move.drop",
)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _crash_plan(point: str) -> FaultPlan:
    return FaultPlan(
        seed=7, rules=[dict(point=point, action="crash", p=1.0, max=1)]
    )


def _group_holding(c, pred):
    """Group ids whose KV physically holds any key of the tablet."""
    prefix = keys.PredicatePrefix(pred)
    return sorted(
        g for g in c.groups
        if list(c.groups[g].any_replica().kv.iterate(prefix, 1 << 61))
    )


# ---------------------------------------------------------------------------
# pure units
# ---------------------------------------------------------------------------


def test_pick_rebalance_move_adversarial_and_deterministic():
    # balanced -> no move
    assert pick_rebalance_move(
        {"a": 100, "b": 100}, {"a": 1, "b": 2}, [1, 2], 1) is None
    # simple skew -> biggest tablet moves to the empty group
    assert pick_rebalance_move(
        {"a": 300, "b": 100}, {"a": 1, "b": 1}, [1, 2], 1) == ("a", 2)
    # one giant tablet that would merely flip the imbalance is skipped
    # in favor of the next-smaller tablet that narrows the gap
    assert pick_rebalance_move(
        {"big": 1000, "s1": 10, "s2": 10},
        {"big": 1, "s1": 1, "s2": 1}, [1, 2], 1,
    ) == ("big", 2)  # |(1023-1001) - 1001| = 979 < 1020: still narrows
    assert pick_rebalance_move(
        {"big": 1000}, {"big": 1}, [1, 2], 1) is None  # pure flip: refuse
    # byte-empty tablets still spread by count (+1 weight per tablet)
    got = pick_rebalance_move(
        {"a": 0, "b": 0, "c": 0, "d": 0},
        {"a": 1, "b": 1, "c": 1, "d": 1}, [1, 2], 1)
    assert got == ("a", 2)  # equal weights tie-break lexicographically
    # but an empty skew stays put under a byte-scale min_move threshold
    assert pick_rebalance_move(
        {"a": 0, "b": 0, "c": 0, "d": 0},
        {"a": 1, "b": 1, "c": 1, "d": 1}, [1, 2], 1 << 10) is None
    # gap below min_move_bytes -> no move
    assert pick_rebalance_move(
        {"a": 120, "b": 100}, {"a": 1, "b": 2}, [1, 2], 1 << 10) is None
    # group-load tie (two equally loaded donors): smallest gid donates;
    # tablet-weight tie inside the donor breaks lexicographically; and
    # the choice is stable across dict insertion orders
    s1 = {"x": 50, "x2": 50, "y": 50, "y2": 50, "z": 0}
    t1 = {"x": 1, "x2": 1, "y": 2, "y2": 2, "z": 3}
    s2 = dict(reversed(list(s1.items())))
    t2 = dict(reversed(list(t1.items())))
    assert (
        pick_rebalance_move(s1, t1, [1, 2, 3], 1)
        == pick_rebalance_move(s2, t2, [3, 2, 1], 1)
        == ("x", 3)
    )
    # a move that would merely widen the spread is refused outright
    assert pick_rebalance_move(
        {"x": 50, "y": 50, "z": 0},
        {"x": 1, "y": 2, "z": 3}, [1, 2, 3], 1) is None
    # no groups at all
    assert pick_rebalance_move({}, {}, [], 1) is None


def test_move_journal_roundtrip_and_clear(tmp_path):
    j = MoveJournal(str(tmp_path / "moves.journal"))
    j.record("p1", {"src": 1, "dst": 2, "phase": "copy", "read_ts": 9})
    j.record("p2", {"src": 2, "dst": 1, "phase": "copy", "read_ts": 11})
    j.record("p1", {"src": 1, "dst": 2, "phase": "fence", "read_ts": 9})
    j.clear("p2")
    j.close()
    got = MoveJournal(str(tmp_path / "moves.journal")).pending()
    assert got == {"p1": {"src": 1, "dst": 2, "phase": "fence", "read_ts": 9}}


def test_move_journal_torn_tail_every_byte_boundary(tmp_path):
    """A crash mid-append leaves a torn tail: recovery folds to the
    last COMPLETE record and physically truncates the garbage so later
    appends land on a clean boundary (the WAL-crash contract)."""
    import os

    seed = tmp_path / "seed.journal"
    j = MoveJournal(str(seed))
    j.record("p1", {"src": 1, "dst": 2, "phase": "copy", "read_ts": 5})
    j.record("p1", {"src": 1, "dst": 2, "phase": "fence", "read_ts": 5})
    j.record("p1", {"src": 1, "dst": 2, "phase": "drop", "read_ts": 5})
    j.close()
    blob = seed.read_bytes()
    # locate the last record's start
    offsets, pos = [], 0
    while pos < len(blob):
        _, plen = MoveJournal._HDR.unpack_from(blob, pos)
        offsets.append(pos)
        pos += MoveJournal._HDR.size + plen
    assert pos == len(blob) and len(offsets) == 3
    last = offsets[-1]
    for cut in range(last, len(blob)):
        p = tmp_path / f"cut_{cut}.journal"
        p.write_bytes(blob[:cut])
        jr = MoveJournal(str(p))
        assert jr.pending()["p1"]["phase"] == "fence", cut
        assert os.path.getsize(p) == last, cut  # tail truncated
        # appends after repair continue cleanly
        jr.clear("p1")
        jr.close()
        assert MoveJournal(str(p)).pending() == {}, cut


# ---------------------------------------------------------------------------
# in-process cluster: phases, fence, chunking, caches
# ---------------------------------------------------------------------------

N_EDGES = 64


def _seed_cluster(c, n=N_EDGES, val_pad=0):
    c.alter("mv: string @index(exact) .\nother: string @index(exact) .")
    pad = "x" * val_pad
    rdf = [f'<0x{i:x}> <mv> "m{i}{pad}" .' for i in range(1, n + 1)]
    rdf += [f'<0x{i:x}> <other> "o{i}" .' for i in range(1, 9)]
    c.new_txn().mutate_rdf(set_rdf="\n".join(rdf), commit_now=True)


def _counts(c):
    mv = len(c.query("{ q(func: has(mv)) { uid } }")["data"]["q"])
    other = len(c.query("{ q(func: has(other)) { uid } }")["data"]["q"])
    return mv, other


def test_chunked_move_and_unrelated_cache_survives(monkeypatch):
    monkeypatch.setenv("DGRAPH_TPU_MOVE_CHUNK_BYTES", "1024")
    c = DistributedCluster(n_groups=2, replicas=1, pump_ms=2)
    try:
        _seed_cluster(c, val_pad=64)
        src = c.zero.belongs_to("mv")
        dst = 2 if src == 1 else 1
        # populate the shared decoded-list cache for BOTH predicates
        assert _counts(c) == (N_EDGES, 8)
        other_keys = [
            k for k in c.mem._cache
            if k.startswith(keys.PredicatePrefix("other"))
        ]
        assert other_keys, "cache should hold the unrelated predicate"
        chunks0 = METRICS.value("tablet_move_chunks_total")
        assert c.move_tablet("mv", dst) is True
        # bounded proposals: the tablet shipped in multiple chunks
        assert METRICS.value("tablet_move_chunks_total") >= chunks0 + 3
        # placement flipped, exactly-once: only dst holds the tablet
        assert c.zero.belongs_to("mv") == dst
        assert _group_holding(c, "mv") == [dst]
        # the unrelated predicate's cache entries SURVIVED the move
        # (the old mover cleared the whole MemoryLayer) ...
        assert all(k in c.mem._cache for k in other_keys)
        # ... while the moved tablet's entries were invalidated
        assert not any(
            k.startswith(keys.PredicatePrefix("mv")) for k in c.mem._cache
        )
        # data exact after the move
        assert _counts(c) == (N_EDGES, 8)
        out = c.query('{ q(func: eq(mv, "m1x%s")) { mv } }' % ("x" * 63))
        assert len(out["data"]["q"]) == 1
        # writes land on the new owner
        c.new_txn().mutate_rdf(
            set_rdf='<0xfff> <mv> "post-move" .', commit_now=True
        )
        out = c.query('{ q(func: eq(mv, "post-move")) { uid } }')
        assert out["data"]["q"] == [{"uid": "0xfff"}]
    finally:
        c.close()


def test_phase1_does_not_block_other_commits(monkeypatch):
    """The acceptance check: a multi-chunk move under concurrent
    writes holds the global commit lock only for the bounded Phase-2
    fence — commits on a non-moving predicate complete DURING Phase 1
    (the old mover was stop-the-world for the whole copy), and writes
    to the MOVING predicate during Phase 1 survive via the delta."""
    monkeypatch.setenv("DGRAPH_TPU_MOVE_CHUNK_BYTES", "1024")
    c = DistributedCluster(n_groups=2, replicas=1, pump_ms=2)
    try:
        _seed_cluster(c, n=96, val_pad=128)  # tens of chunks
        src = c.zero.belongs_to("mv")
        dst = 2 if src == 1 else 1
        # stretch phase 1 deterministically: 15ms per chunk flush
        faults.install(FaultPlan(seed=3, rules=[
            dict(point="move.chunk", action="delay", p=1.0, delay_ms=15),
        ]))
        done = threading.Event()
        moved = []

        def run_move():
            try:
                moved.append(c.move_tablet("mv", dst))
            finally:
                done.set()

        th = threading.Thread(target=run_move)
        t0 = time.perf_counter()
        th.start()
        lat_max = 0.0
        i = 0
        while not done.is_set():
            i += 1
            t1 = time.perf_counter()
            # non-moving predicate: must not block on the copy
            c.new_txn().mutate_rdf(
                set_rdf=f'<0x{0x500 + i:x}> <other> "d{i}" .',
                commit_now=True,
            )
            lat_max = max(lat_max, time.perf_counter() - t1)
            # moving predicate: keeps accepting writes in phase 1; a
            # fence bounce is retryable and the write still lands
            try:
                c.new_txn().mutate_rdf(
                    set_rdf=f'<0x{0x600 + i:x}> <mv> "live{i}" .',
                    commit_now=True,
                )
            except TabletFencedError:
                retrying_call(
                    lambda i=i: c.new_txn().mutate_rdf(
                        set_rdf=f'<0x{0x600 + i:x}> <mv> "live{i}" .',
                        commit_now=True,
                    ),
                    policy=RetryPolicy(base=0.01, cap=0.1, max_attempts=50),
                    retryable=(TabletFencedError,),
                )
            time.sleep(0.005)
        th.join(timeout=30)
        move_s = time.perf_counter() - t0
        assert moved == [True]
        # commits flowed while the move was in flight, each far faster
        # than the move itself
        assert i >= 3, (i, move_s)
        assert lat_max < max(1.0, move_s / 2), (lat_max, move_s)
        # every acked write to the moving tablet survived the move
        out = c.query("{ q(func: has(mv)) { uid } }")
        assert len(out["data"]["q"]) == 96 + i
        faults.reset()
        # fence duration was observed and bounded
        assert METRICS.value("tablet_move_chunks_total") > 0
    finally:
        faults.reset()
        c.close()


def test_recover_moves_skips_an_in_flight_move(monkeypatch):
    """recover_moves() (e.g. an auto-rebalance tick) must NEVER treat a
    LIVE move's journal entry as a crashed one: a concurrent rollback
    would clear the journal under the mover, its flip would no-op, and
    the source drop would destroy the only copy of the tablet."""
    monkeypatch.setenv("DGRAPH_TPU_MOVE_CHUNK_BYTES", "1024")
    c = DistributedCluster(n_groups=2, replicas=1, pump_ms=2)
    try:
        _seed_cluster(c, n=96, val_pad=128)
        src = c.zero.belongs_to("mv")
        dst = 2 if src == 1 else 1
        faults.install(FaultPlan(seed=3, rules=[
            dict(point="move.chunk", action="delay", p=1.0, delay_ms=20),
        ]))
        done = threading.Event()
        moved = []

        def run_move():
            try:
                moved.append(c.move_tablet("mv", dst))
            finally:
                done.set()

        th = threading.Thread(target=run_move)
        th.start()
        recovered = 0
        while not done.is_set():
            recovered += c.recover_moves()  # concurrent healing ticks
            time.sleep(0.01)
        th.join(timeout=30)
        assert moved == [True]
        assert recovered == 0  # the live move was never "recovered"
        assert c.zero.moves() == {}
        assert _group_holding(c, "mv") == [dst]
        assert _counts(c)[0] == 96  # nothing lost
    finally:
        faults.reset()
        c.close()


def test_crash_at_every_phase_boundary_recovers():
    """Kill the move coordinator at each journaled phase boundary: the
    journal + recover_moves() always heal to exactly-once placement —
    copy/fence roll back, drop rolls forward — with exact data."""
    c = DistributedCluster(n_groups=2, replicas=1, pump_ms=2)
    try:
        _seed_cluster(c)
        recovered0 = METRICS.value("tablet_move_recovered_total")
        for point in CRASH_POINTS:
            src = c.zero.belongs_to("mv")
            dst = 2 if src == 1 else 1
            faults.install(_crash_plan(point))
            with pytest.raises(InjectedCrash):
                c.move_tablet("mv", dst)
            faults.reset()
            assert c.zero.moves(), point  # journal survived the crash
            c.recover_moves()
            # journal drained; placement is exactly-once
            assert c.zero.moves() == {}, point
            where = c.zero.belongs_to("mv")
            assert where in (src, dst), point
            assert _group_holding(c, "mv") == [where], point
            # data exact, queries correct
            assert _counts(c)[0] == N_EDGES, point
            out = c.query('{ q(func: eq(mv, "m7")) { mv } }')
            assert out["data"]["q"] == [{"mv": "m7"}], point
            # crashes at/after the flip recover FORWARD
            if point in ("move.flip", "move.drop"):
                assert where == dst, point
            else:
                assert where == src, point
        assert (
            METRICS.value("tablet_move_recovered_total")
            >= recovered0 + len(CRASH_POINTS)
        )
        # the cluster is fully functional: a clean move completes
        src = c.zero.belongs_to("mv")
        dst = 2 if src == 1 else 1
        assert c.move_tablet("mv", dst) is True
        assert _group_holding(c, "mv") == [dst]
    finally:
        faults.reset()
        c.close()


def test_stale_fence_bounces_retryable_until_recovered():
    c = DistributedCluster(n_groups=2, replicas=1, pump_ms=2)
    try:
        _seed_cluster(c)
        src = c.zero.belongs_to("mv")
        dst = 2 if src == 1 else 1
        faults.install(_crash_plan("move.fence"))
        with pytest.raises(InjectedCrash):
            c.move_tablet("mv", dst)
        faults.reset()
        # the dead coordinator left the fence up: commits to the moving
        # tablet bounce RETRYABLE (never wrong data) ...
        rej0 = METRICS.value("tablet_fence_rejected_total")
        with pytest.raises(TabletFencedError) as ei:
            c.new_txn().mutate_rdf(
                set_rdf='<0x200> <mv> "nope" .', commit_now=True
            )
        assert getattr(ei.value, "retryable", False) is True
        assert METRICS.value("tablet_fence_rejected_total") == rej0 + 1
        # ... drop_attr of the moving tablet is refused the same way ...
        with pytest.raises(TabletFencedError):
            c.drop_attr("mv")
        # ... commits on other predicates are unaffected ...
        c.new_txn().mutate_rdf(
            set_rdf='<0x201> <other> "fine" .', commit_now=True
        )
        # ... and reads keep serving from the source throughout
        assert _counts(c)[0] == N_EDGES
        # recovery lifts the fence (rollback) and writes flow again
        c.recover_moves()
        c.new_txn().mutate_rdf(
            set_rdf='<0x200> <mv> "now-ok" .', commit_now=True
        )
        assert _counts(c)[0] == N_EDGES + 1
        assert c.zero.belongs_to("mv") == src
    finally:
        faults.reset()
        c.close()


def test_durable_journal_recovery_across_restart(tmp_path):
    """Coordinator death at a phase boundary, then a FULL cluster
    restart from disk: startup recovery resolves the journaled move —
    fence rolls back, drop rolls forward — before serving."""
    d = str(tmp_path / "dc")
    # -- crash after the flip: restart completes the move forward
    c = DistributedCluster(n_groups=2, replicas=1, pump_ms=2, data_dir=d)
    _seed_cluster(c)
    src = c.zero.belongs_to("mv")
    dst = 2 if src == 1 else 1
    faults.install(_crash_plan("move.flip"))
    with pytest.raises(InjectedCrash):
        c.move_tablet("mv", dst)
    faults.reset()
    c.close()
    c = DistributedCluster(n_groups=2, replicas=1, pump_ms=2, data_dir=d)
    try:
        assert c.zero.moves() == {}  # startup recovery drained it
        assert c.zero.belongs_to("mv") == dst
        assert _group_holding(c, "mv") == [dst]
        assert _counts(c)[0] == N_EDGES
        # -- crash mid-fence: restart rolls the move back
        faults.install(_crash_plan("move.delta"))
        with pytest.raises(InjectedCrash):
            c.move_tablet("mv", src)
        faults.reset()
    finally:
        c.close()
    c = DistributedCluster(n_groups=2, replicas=1, pump_ms=2, data_dir=d)
    try:
        assert c.zero.moves() == {}
        assert c.zero.belongs_to("mv") == dst  # rollback: still at dst
        assert _group_holding(c, "mv") == [dst]
        assert _counts(c)[0] == N_EDGES
        # and a clean move works after both recoveries
        assert c.move_tablet("mv", src) is True
        assert _group_holding(c, "mv") == [src]
        # hard crash right after a COMPLETED move (no clean close, no
        # later commit): the flip was persisted at flip time — BEFORE
        # the journal cleared — so restart must not route the tablet
        # to the already-dropped old owner
        c._save_zero_state = lambda: None  # close() persists nothing
    finally:
        faults.reset()
        c.close()
    c = DistributedCluster(n_groups=2, replicas=1, pump_ms=2, data_dir=d)
    try:
        assert c.zero.moves() == {}
        assert c.zero.belongs_to("mv") == src
        assert _group_holding(c, "mv") == [src]
        assert _counts(c)[0] == N_EDGES
    finally:
        c.close()


def test_replicated_zero_journals_moves_in_state_machine():
    """With a raft-backed Zero the journal lives in the replicated
    state machine (snapshot-inclusive), not a coordinator file."""
    c = DistributedCluster(
        n_groups=2, replicas=1, pump_ms=2,
        replicated_zero=True, zero_replicas=3,
    )
    try:
        _seed_cluster(c, n=16)
        src = c.zero.belongs_to("mv")
        dst = 2 if src == 1 else 1
        faults.install(_crash_plan("move.delta"))
        with pytest.raises(InjectedCrash):
            c.move_tablet("mv", dst)
        faults.reset()
        # every zero replica journals the fence phase through raft
        # (followers apply asynchronously — poll for convergence)
        deadline = time.time() + 10
        while time.time() < deadline:
            phases = [
                z.sm.moves.get("mv", {}).get("phase") for z in c.zero_nodes
            ]
            if phases == ["fence"] * len(c.zero_nodes):
                break
            time.sleep(0.05)
        assert phases == ["fence"] * len(c.zero_nodes), phases
        # state-machine snapshot round-trips the journal
        blob = c.zero_nodes[0].sm.dump()
        from dgraph_tpu.zero.replicated import ZeroStateMachine

        sm2 = ZeroStateMachine()
        sm2.load(blob)
        assert sm2.moves == c.zero_nodes[0].sm.moves
        c.recover_moves()
        assert c.zero.moves() == {}
        assert c.zero.belongs_to("mv") == src
        assert _counts(c)[0] == 16
        assert c.move_tablet("mv", dst) is True
        assert c.zero.belongs_to("mv") == dst
        assert _counts(c)[0] == 16
    finally:
        faults.reset()
        c.close()


def test_auto_rebalance_loop_moves_skewed_tablets():
    c = DistributedCluster(n_groups=2, replicas=1, pump_ms=2)
    try:
        _seed_cluster(c, val_pad=32)
        # force-skew everything onto group 1
        for pred in list(c.zero.tablets):
            if c.zero.belongs_to(pred) != 1:
                c.move_tablet(pred, 1)
        c.enable_auto_rebalance(interval_s=0.05)
        deadline = time.time() + 15
        while time.time() < deadline:
            if any(g == 2 for g in c.zero.tablets.values()):
                break
            time.sleep(0.05)
        assert any(g == 2 for g in c.zero.tablets.values()), dict(
            c.zero.tablets
        )
        assert _counts(c)[0] == N_EDGES  # data intact after the move
    finally:
        c.close()


# ---------------------------------------------------------------------------
# multi-process chaos smoke (fixed seed, tier-1)
# ---------------------------------------------------------------------------

N_ACCOUNTS = 6
START_BAL = 100


@pytest.mark.chaos
def test_move_chaos_bank_crash_every_phase_and_partition(monkeypatch):
    """The acceptance scenario on a real multi-process cluster: the
    bank workload runs while the 'bal' tablet is moved between groups
    with the coordinator killed at EVERY journaled phase boundary and
    the destination group partitioned mid-move. After each recovery:
    placement is exactly-once, balances are ledger-exact (sum always
    conserved), edge counts exact, and the fence only ever produced
    retryable errors — never wrong data."""
    from dgraph_tpu.worker.harness import ProcCluster

    monkeypatch.setenv("DGRAPH_TPU_MOVE_CHUNK_BYTES", "2048")
    c = ProcCluster(n_groups=2, replicas=1)
    stop = threading.Event()
    stats = {"ok": 0, "fence_retries": 0, "ambiguous": 0}
    ledger = {i: START_BAL for i in range(1, N_ACCOUNTS + 1)}
    lock = threading.Lock()
    try:
        c.alter("bal: int @upsert .\nacct: string @index(exact) @upsert .")
        rdf = []
        for i in range(1, N_ACCOUNTS + 1):
            rdf.append(f'<0x{i:x}> <acct> "a{i}" .')
            rdf.append(f'<0x{i:x}> <bal> "{START_BAL}"^^<xs:int> .')
        c.new_txn().mutate_rdf(set_rdf="\n".join(rdf), commit_now=True)

        import numpy as np

        def writer():
            rng = np.random.default_rng(42)
            while not stop.is_set():
                frm, to = (
                    int(x) + 1
                    for x in rng.choice(N_ACCOUNTS, 2, replace=False)
                )
                amt = int(rng.integers(1, 10))
                rdf = (
                    f'<0x{frm:x}> <bal> "{ledger[frm] - amt}"^^<xs:int> .\n'
                    f'<0x{to:x}> <bal> "{ledger[to] + amt}"^^<xs:int> .'
                )
                try:
                    try:
                        c.new_txn().mutate_rdf(set_rdf=rdf, commit_now=True)
                    except TabletFencedError:
                        # the serving contract: fence errors are
                        # retryable through conn/retry backoff
                        with lock:
                            stats["fence_retries"] += 1
                        retrying_call(
                            lambda: c.new_txn().mutate_rdf(
                                set_rdf=rdf, commit_now=True
                            ),
                            policy=RetryPolicy(
                                base=0.02, cap=0.2, max_attempts=60
                            ),
                            retryable=(TabletFencedError,),
                        )
                    with lock:
                        ledger[frm] -= amt
                        ledger[to] += amt
                        stats["ok"] += 1
                except Exception:
                    with lock:
                        stats["ambiguous"] += 1
                time.sleep(0.01)

        th = threading.Thread(target=writer)
        th.start()

        def check(tag):
            out = c.query("{ q(func: has(bal)) { uid bal } }")
            assert not out["extensions"].get("degraded"), tag
            bals = {int(x["uid"], 16): x["bal"] for x in out["data"]["q"]}
            assert len(bals) == N_ACCOUNTS, (tag, bals)
            assert sum(bals.values()) == N_ACCOUNTS * START_BAL, (tag, bals)
            with lock:
                amb = stats["ambiguous"]
                snap = dict(ledger)
            if amb == 0:
                # ledger-exact: every acked transfer applied exactly
                # once (sample a stable account read)
                out2 = c.query('{ q(func: eq(acct, "a1")) { bal } }')
                assert out2["data"]["q"], tag
            assert c.zero.moves() == {}, tag
            return bals

        # kill the coordinator at every journaled phase boundary
        for point in CRASH_POINTS:
            src = c.zero.belongs_to("bal")
            dst = 2 if src == 1 else 1
            faults.install(_crash_plan(point))
            with pytest.raises(InjectedCrash):
                c.move_tablet("bal", dst)
            faults.reset()
            assert c.zero.moves(), point
            c.recover_moves()
            check(point)
            where = c.zero.belongs_to("bal")
            assert where == (dst if point in ("move.flip", "move.drop")
                             else src), point

        # partition the DESTINATION group mid-copy: the move fails
        # bounded, the journal survives, recovery rolls it back
        src = c.zero.belongs_to("bal")
        dst = 2 if src == 1 else 1
        plan = faults.install(FaultPlan(seed=99))
        for addr in c.remote_groups[dst].addrs:
            plan.partition(addr)
        with deadline_scope(Deadline.after(3.0)):
            with pytest.raises(Exception):
                c.move_tablet("bal", dst)
        assert c.zero.moves(), "journal must survive a failed rollback"
        plan.heal()
        faults.reset()
        deadline = time.time() + 10
        while time.time() < deadline:
            try:
                c.recover_moves()
                break
            except Exception:
                time.sleep(0.3)
        check("partition-rollback")
        assert c.zero.belongs_to("bal") == src

        # and a clean live move completes under the same traffic
        assert c.move_tablet("bal", dst) is True
        check("clean-move")
        assert c.zero.belongs_to("bal") == dst

        stop.set()
        th.join(timeout=30)
        bals = check("final")
        with lock:
            if stats["ambiguous"] == 0:
                assert bals == ledger, stats
        assert stats["ok"] > 0, stats
    finally:
        stop.set()
        faults.reset()
        c.close()


# ---------------------------------------------------------------------------
# long randomized schedule (out of tier-1)
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.chaos
def test_move_chaos_long_randomized_schedule(monkeypatch):
    """Randomized (seeded) schedule: repeated moves under the bank
    workload with coordinator crashes at random phase boundaries,
    random partitions of source/destination, and RPC-plane noise —
    invariants checked after every healing round."""
    import numpy as np

    from dgraph_tpu.worker.harness import ProcCluster

    monkeypatch.setenv("DGRAPH_TPU_MOVE_CHUNK_BYTES", "4096")
    c = ProcCluster(n_groups=2, replicas=3)
    rng = np.random.default_rng(20260803)

    def wait_healthy(timeout=15.0):
        # healed partitions reopen through the heartbeat's half-open
        # probes; wait for every circuit before the next clean round
        deadline = time.time() + timeout
        while time.time() < deadline:
            if all(
                c.pool.healthy(a)
                for g in c.remote_groups.values()
                for a in g.addrs
            ):
                return
            time.sleep(0.2)

    try:
        c.alter("bal: int @upsert .\nacct: string @index(exact) @upsert .")
        rdf = []
        for i in range(1, N_ACCOUNTS + 1):
            rdf.append(f'<0x{i:x}> <acct> "a{i}" .')
            rdf.append(f'<0x{i:x}> <bal> "{START_BAL}"^^<xs:int> .')
        c.new_txn().mutate_rdf(set_rdf="\n".join(rdf), commit_now=True)
        ledger = {i: START_BAL for i in range(1, N_ACCOUNTS + 1)}
        ambiguous = 0
        for round_ in range(12):
            # a few transfers
            for _ in range(4):
                frm, to = (
                    int(x) + 1
                    for x in rng.choice(N_ACCOUNTS, 2, replace=False)
                )
                amt = int(rng.integers(1, 10))
                try:
                    retrying_call(
                        lambda: c.new_txn().mutate_rdf(
                            set_rdf=(
                                f'<0x{frm:x}> <bal> '
                                f'"{ledger[frm] - amt}"^^<xs:int> .\n'
                                f'<0x{to:x}> <bal> '
                                f'"{ledger[to] + amt}"^^<xs:int> .'
                            ),
                            commit_now=True,
                        ),
                        policy=RetryPolicy(base=0.02, cap=0.3,
                                           max_attempts=40),
                        retryable=(TabletFencedError,),
                    )
                    ledger[frm] -= amt
                    ledger[to] += amt
                except Exception:
                    ambiguous += 1
            # a move, possibly killed at a random boundary
            src = c.zero.belongs_to("bal")
            dst = 2 if src == 1 else 1
            mode = int(rng.integers(0, 3))
            if mode == 0:
                wait_healthy()
                c.move_tablet("bal", dst)
            elif mode == 1:
                point = CRASH_POINTS[int(rng.integers(len(CRASH_POINTS)))]
                faults.install(_crash_plan(point))
                with pytest.raises(InjectedCrash):
                    c.move_tablet("bal", dst)
                faults.reset()
                c.recover_moves()
            else:
                plan = faults.install(FaultPlan(seed=int(rng.integers(1e6))))
                victim = dst if rng.integers(2) else src
                for addr in c.remote_groups[victim].addrs:
                    plan.partition(addr)
                with deadline_scope(Deadline.after(3.0)):
                    try:
                        c.move_tablet("bal", dst)
                    except Exception:
                        pass
                plan.heal()
                faults.reset()
                wait_healthy()
                deadline = time.time() + 15
                while c.zero.moves() and time.time() < deadline:
                    try:
                        c.recover_moves()
                    except Exception:
                        time.sleep(0.3)
            assert c.zero.moves() == {}
            out = c.query("{ q(func: has(bal)) { uid bal } }")
            if out["extensions"].get("degraded"):
                continue
            bals = {int(x["uid"], 16): x["bal"] for x in out["data"]["q"]}
            assert sum(bals.values()) == N_ACCOUNTS * START_BAL, (
                round_, bals,
            )
            assert len(bals) == N_ACCOUNTS, (round_, bals)
        if ambiguous == 0:
            out = c.query("{ q(func: has(bal)) { uid bal } }")
            bals = {int(x["uid"], 16): x["bal"] for x in out["data"]["q"]}
            assert bals == ledger
    finally:
        faults.reset()
        c.close()

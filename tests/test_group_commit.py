"""Group-commit write pipeline tests (worker/groupcommit.py).

Unit layer: batched oracle verdicts (per-txn isolation, serial-order
equivalence, idempotent replay under resend), native delta-encode and
bulk-tokenizer byte-equality against the Python encoders, batched
apply_edges equivalence against the per-edge path, the
DGRAPH_TPU_GROUP_COMMIT=0 escape hatch restoring the serial commit
path byte-for-byte through the public commit API, watermark
monotonicity under concurrent pipelined commits, per-member fence
bounces, and write admission costing.

Cluster layer (marked `chaos`): a fixed-seed drop+delay+disconnect
schedule plus a replica crash while concurrent committers drive the
bank workload through group commit on a real multi-process cluster —
balances stay ledger-exact, an aborted batch member never aborts its
batchmates, and acked transfers apply exactly once.
"""

import random
import threading
import time

import numpy as np
import pytest

from dgraph_tpu.conn import faults
from dgraph_tpu.conn.faults import FaultPlan
from dgraph_tpu.posting.pl import (
    OP_DEL,
    OP_SET,
    Posting,
    encode_delta,
    encode_deltas,
)
from dgraph_tpu.types.types import TypeID, Val
from dgraph_tpu.utils.observe import METRICS
from dgraph_tpu.x import config
from dgraph_tpu.zero.zero import TxnConflictError, ZeroLite


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


# ---------------------------------------------------------------------------
# batched oracle verdicts
# ---------------------------------------------------------------------------


def test_zerolite_commit_batch_verdicts_match_serial_order():
    """Batch members decide in list order — exactly what back-to-back
    commit() calls produce: a later same-key member whose start_ts
    predates an earlier member's commit aborts; disjoint keys commit."""
    z = ZeroLite()
    t1, t2, t3 = z.begin_txn(), z.begin_txn(), z.begin_txn()
    v = z.commit_batch([(t1, {0xA}), (t2, {0xA}), (t3, {0xB})], track=True)
    assert v[0][0] == "commit" and v[2][0] == "commit"
    assert v[1] == ("abort", v[0][1])  # isolated: batchmates unharmed
    assert v[2][1] == v[0][1] + 1  # consecutive commit timestamps
    # tracked members are pending until applied
    for verdict in (v[0], v[2]):
        z.applied(verdict[1])


def test_zero_sm_commit_batch_is_idempotent_on_replay():
    """A batch re-proposed with a fresh request id (lost ack), or one
    member re-proposed SOLO through the plain commit op, replays the
    recorded verdicts instead of re-running conflict detection."""
    from dgraph_tpu.zero.replicated import ZeroStateMachine

    sm = ZeroStateMachine()
    sm.max_ts = 7  # starts 5/6/7 were leased
    batch = {"b": [[5, [10]], [6, [10]], [7, [11]]]}
    out = sm.apply(("commit_batch", 1, 1, batch))
    assert [o[0] for o in out] == ["commit", "abort", "commit"]
    # same batch, fresh req id: identical verdicts, no new timestamps
    out2 = sm.apply(("commit_batch", 1, 2, batch))
    assert [tuple(v) for v in out2] == [tuple(v) for v in out]
    # solo replay of one member through the old op: recorded verdict
    assert sm.apply(("commit", 1, 3, 6, [10])) == tuple(out[1])
    assert sm.apply(("commit", 1, 4, 5, [10])) == tuple(out[0])


def test_zero_commit_batch_wire_roundtrip():
    """The typed ZeroCommitBatch body survives the zero.exec encode/
    decode — u64 conflict fingerprints intact."""
    from dgraph_tpu.conn.messages import (
        ZeroCommitBatch,
        ZeroCommitReq,
        ZeroExec,
    )

    big = (1 << 64) - 3
    e = ZeroExec(
        op="commit_batch",
        args_json=b"{}",
        commit_batch=ZeroCommitBatch(
            txns=[
                ZeroCommitReq(start_ts=9, cks=[1, big]),
                ZeroCommitReq(start_ts=10, cks=[]),
            ]
        ),
    )
    d = ZeroExec.decode(e.encode())
    assert d.op == "commit_batch"
    assert d.commit_batch.txns[0].start_ts == 9
    assert d.commit_batch.txns[0].cks == [1, big]
    assert d.commit_batch.txns[1].start_ts == 10


# ---------------------------------------------------------------------------
# native mutation kernels: byte-equality
# ---------------------------------------------------------------------------


def _random_posting(rng):
    if rng.random() < 0.5:
        return Posting(
            uid=rng.getrandbits(64) or 1,
            op=rng.choice([OP_SET, OP_DEL]),
        )
    return Posting(
        uid=rng.getrandbits(64) or 1,
        op=rng.choice([OP_SET, OP_DEL]),
        value=bytes(rng.getrandbits(8) for _ in range(rng.randint(0, 48))),
        value_type=TypeID(rng.choice([0, 1, 2, 9])),
    )


def test_native_delta_encode_byte_equality_randomized():
    """encode_deltas (ONE native enc_delta_records call for the whole
    write set) is byte-identical to per-key encode_delta over a
    randomized corpus; rich shapes (lang/facets) fall back per key."""
    rng = random.Random(1234)
    deltas = {}
    for k in range(300):
        deltas[b"key%d" % k] = [
            _random_posting(rng) for _ in range(rng.randint(1, 7))
        ]
    got = dict(encode_deltas(deltas))
    want = {k: encode_delta(p) for k, p in deltas.items()}
    assert got == want
    # rich shapes: the whole set falls back, still byte-identical
    deltas[b"lang"] = [
        Posting(uid=3, lang="en", value=b"x", value_type=TypeID(9))
    ]
    deltas[b"facets"] = [
        Posting(uid=4, facets={"f": b"1"}, facet_types={"f": TypeID(1)})
    ]
    got = dict(encode_deltas(deltas))
    assert got == {k: encode_delta(p) for k, p in deltas.items()}
    # edge shapes: empty value vs no value are distinct records
    deltas2 = {b"e": [Posting(uid=1, value=b"", value_type=TypeID(9))]}
    assert dict(encode_deltas(deltas2)) == {
        b"e": encode_delta(deltas2[b"e"])
    }


def test_native_term_tokens_byte_equality_randomized():
    """tok_terms_ascii matches the Python TermTokenizer byte-for-byte
    over adversarial ASCII input (case, digits, quotes, underscores,
    duplicates, empties, punctuation runs)."""
    from dgraph_tpu import native
    from dgraph_tpu.tok.tok import get_tokenizer

    if not native.NATIVE_AVAILABLE:
        pytest.skip("native library unavailable")
    term = get_tokenizer("term")
    rng = random.Random(99)
    import string

    alpha = string.ascii_letters + string.digits + "_' .,;:-!?@#\t\r\n"
    vals = [
        "".join(rng.choice(alpha) for _ in range(rng.randint(0, 80)))
        for _ in range(400)
    ]
    vals += ["", " ", "A A a", "don't STOP Don't", "__x__ 'y' z9"]
    got = native.tok_terms_ascii(
        [v.encode() for v in vals], term.identifier
    )
    for v, toks in zip(vals, got):
        assert toks == term.tokens(Val(TypeID.STRING, v)), v


# ---------------------------------------------------------------------------
# batched apply_edges equivalence
# ---------------------------------------------------------------------------

_APPLY_SCHEMA = (
    "name: string @index(exact, term) .\n"
    "age: int @index(int) .\n"
    "city: string .\n"
    "tag: [string] @index(exact) .\n"
    "knows: [uid] @reverse .\n"
    "boss: uid @reverse .\n"
    "bio: string @index(fulltext) @lang .\n"
    "upname: string @index(exact) @upsert .\n"
)


def _random_edges(rng, n):
    from dgraph_tpu.posting.mutation import DirectedEdge

    edges = []
    for _ in range(n):
        ent = rng.randint(1, 12)
        kind = rng.random()
        if kind < 0.35:
            edges.append(
                DirectedEdge(
                    ent, rng.choice(["name", "city", "upname"]),
                    value=Val(
                        TypeID.STRING,
                        f"Val {rng.randint(0, 6)} x{rng.randint(0, 3)}",
                    ),
                    op=OP_SET,
                    fresh=bool(rng.random() < 0.3),
                )
            )
        elif kind < 0.5:
            edges.append(
                DirectedEdge(
                    ent, "age", value=Val(TypeID.INT, rng.randint(0, 90)),
                    op=OP_SET,
                )
            )
        elif kind < 0.65:
            edges.append(
                DirectedEdge(
                    ent, rng.choice(["knows", "boss"]),
                    value_id=rng.randint(1, 12), op=OP_SET,
                )
            )
        elif kind < 0.75:
            edges.append(
                DirectedEdge(
                    ent, "tag",
                    value=Val(TypeID.STRING, f"t{rng.randint(0, 4)}"),
                    op=rng.choice([OP_SET, OP_DEL]),
                )
            )
        elif kind < 0.85:
            edges.append(
                DirectedEdge(
                    ent, "bio",
                    value=Val(TypeID.STRING, "some Bio text here"),
                    lang=rng.choice(["", "en"]), op=OP_SET,
                )
            )
        else:
            edges.append(
                DirectedEdge(
                    ent, "name",
                    value=Val(TypeID.STRING, f"Val {rng.randint(0, 6)}"),
                    op=OP_DEL,
                )
            )
    return edges


def test_apply_edges_equivalent_to_per_edge_loop():
    """apply_edges (fast classes + bulk reads + native tokens) produces
    a store byte-identical to the per-edge apply_edge loop, over
    randomized mixed batches including shared keys, deletes, langs,
    list values, uid/reverse edges and upsert preds."""
    from dgraph_tpu.api.server import Server
    from dgraph_tpu.posting.mutation import apply_edge, apply_edges

    rng = random.Random(4242)
    for round_ in range(6):
        edges_spec = _random_edges(rng, rng.randint(2, 24))
        dumps = []
        for mode in ("batched", "per_edge"):
            s = Server()
            s.alter(_APPLY_SCHEMA)
            t = s.new_txn()
            if mode == "batched":
                apply_edges(t.txn, s.schema, edges_spec)
            else:
                for e in edges_spec:
                    apply_edge(t.txn, s.schema, e)
            # per-key delta postings must MERGE identically; record
            # bytes can differ only in intra-key ordering where the
            # batch reorders commute — compare the merged visible state
            t.commit()
            q = s.query(
                '{ q(func: has(name)) { uid name age city tag '
                "knows { uid } boss { uid } bio } }"
            )
            dumps.append(q["data"])
        assert dumps[0] == dumps[1], f"round {round_}: {edges_spec}"


# ---------------------------------------------------------------------------
# group commit through the public API
# ---------------------------------------------------------------------------


def _mk_server():
    from dgraph_tpu.api.server import Server

    s = Server()
    s.alter(
        "name: string @index(exact) .\n"
        "bal: int @upsert .\n"
        "knows: [uid] @reverse .\n"
    )
    return s


def test_concurrent_committers_coalesce_and_commit():
    s = _mk_server()
    base_batches = METRICS.value("group_commit_total")
    base_txns = METRICS.value("group_commit_txns_total")
    errs = []

    def w(i):
        try:
            t = s.new_txn()
            t.mutate_json(
                set_obj={"uid": "_:x", "name": f"gc{i}",
                         "knows": [{"uid": "0x1"}]},
                commit_now=True,
            )
        except Exception as e:  # pragma: no cover - failure detail
            errs.append(e)

    ths = [threading.Thread(target=w, args=(i,)) for i in range(32)]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    assert not errs
    out = s.query('{ q(func: has(name)) { name } }')
    assert len(out["data"]["q"]) == 32
    assert METRICS.value("group_commit_txns_total") - base_txns >= 32
    assert METRICS.value("group_commit_total") - base_batches >= 1
    # pipeline fully drained: no outstanding barrier
    assert METRICS.value("commit_pipeline_depth") == 0
    s._group_commit.drain()  # returns immediately when drained


def test_batch_conflict_aborts_only_the_loser():
    """Two txns racing the same @upsert key through group commit: one
    commits, the other gets TxnConflictError — and an unrelated txn in
    the same window always commits (per-member verdict isolation)."""
    s = _mk_server()
    t0 = s.new_txn()
    t0.mutate_json(set_obj={"uid": "0x100", "bal": 5}, commit_now=True)
    results = []
    start = threading.Barrier(3)

    def contender(v):
        t = s.new_txn()
        t.mutate_json(set_obj={"uid": "0x100", "bal": v})
        start.wait()
        try:
            t.commit()
            results.append("ok")
        except TxnConflictError:
            results.append("abort")

    def bystander():
        t = s.new_txn()
        t.mutate_json(set_obj={"uid": "0x200", "name": "safe"})
        start.wait()
        t.commit()
        results.append("bystander_ok")

    ths = [
        threading.Thread(target=contender, args=(1,)),
        threading.Thread(target=contender, args=(2,)),
        threading.Thread(target=bystander),
    ]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    assert sorted(results) == ["abort", "bystander_ok", "ok"], results
    out = s.query('{ q(func: eq(name, "safe")) { name } }')
    assert out["data"]["q"] == [{"name": "safe"}]


def test_escape_hatch_restores_serial_path_byte_for_byte(monkeypatch):
    """DGRAPH_TPU_GROUP_COMMIT=0 through the public commit API: the
    coalescer is never even constructed, and the stored KV bytes match
    a group-commit engine's byte-for-byte for the same single-threaded
    mutation sequence."""
    import dgraph_tpu.worker.groupcommit as gcmod

    def run(mode):
        config.set_env("GROUP_COMMIT", mode)
        try:
            s = _mk_server()
            for i in range(12):
                t = s.new_txn()
                t.mutate_json(
                    set_obj={
                        "uid": f"_:n{i}",
                        "name": f"user{i}",
                        "knows": [{"uid": "0x1"}],
                    },
                    commit_now=True,
                )
            try:
                t = s.new_txn()
                t.mutate_json(set_obj={"uid": "0x100", "bal": 1})
                t2 = s.new_txn()
                t2.mutate_json(set_obj={"uid": "0x100", "bal": 2})
                t.commit()
                t2.commit()
            except TxnConflictError:
                pass  # same conflict either way
            return s.kv.dump_bytes()
        finally:
            config.unset_env("GROUP_COMMIT")

    on = run(1)

    def _boom(*a, **k):  # the serial path must never touch the coalescer
        raise AssertionError("GroupCommit constructed with hatch off")

    monkeypatch.setattr(gcmod.GroupCommit, "__init__", _boom)
    off = run(0)
    assert on == off


def test_watermark_advances_in_commit_ts_order():
    """Under concurrent pipelined commits the snapshot watermark only
    ever advances (the micro-batcher's snapshot-grouping proof depends
    on monotonicity)."""
    s = _mk_server()
    stop = threading.Event()
    samples = [0]
    bad = []

    def sampler():
        last = 0
        while not stop.is_set():
            cur = s._snapshot_ts
            if cur < last:
                bad.append((last, cur))
            last = cur
            samples[0] += 1
            time.sleep(0.0005)

    def writer(base):
        for i in range(40):
            t = s.new_txn()
            t.mutate_json(
                set_obj={"uid": "_:w", "name": f"w{base}-{i}"},
                commit_now=True,
            )

    sam = threading.Thread(target=sampler)
    ws = [threading.Thread(target=writer, args=(b,)) for b in range(4)]
    sam.start()
    for w in ws:
        w.start()
    for w in ws:
        w.join()
    stop.set()
    sam.join()
    assert not bad, f"watermark went backwards: {bad[:3]}"
    assert samples[0] > 0
    # every commit is visible at the final watermark
    out = s.query('{ q(func: has(name)) { name } }')
    assert len(out["data"]["q"]) == 160


def test_fence_bounce_is_per_member_and_retryable():
    """A batch member touching a fenced (moving) tablet bounces with
    the retryable TabletFencedError BEFORE the oracle; its batchmates
    commit normally."""
    from dgraph_tpu.worker.groups import DistributedCluster
    from dgraph_tpu.worker.tabletmove import TabletFencedError

    c = DistributedCluster(n_groups=1, replicas=1)
    try:
        c.alter("pa: string @index(exact) .\npb: string @index(exact) .")
        c.zero._fenced.add("pa")
        start = threading.Barrier(2)
        out = {}

        def fenced_writer():
            t = c.new_txn()
            t.mutate_rdf(set_rdf='<0x1> <pa> "x" .')
            start.wait()
            try:
                t.commit()
                out["fenced"] = "committed"
            except TabletFencedError as e:
                out["fenced"] = ("bounced", getattr(e, "retryable", None))

        def clean_writer():
            t = c.new_txn()
            t.mutate_rdf(set_rdf='<0x2> <pb> "y" .')
            start.wait()
            out["clean"] = t.commit()

        ths = [
            threading.Thread(target=fenced_writer),
            threading.Thread(target=clean_writer),
        ]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        assert out["fenced"] == ("bounced", True)
        assert isinstance(out["clean"], int)
        got = c.query('{ q(func: eq(pb, "y")) { pb } }')
        assert got["data"]["q"] == [{"pb": "y"}]
        # the fence lifted: the bounced member's retry succeeds
        c.zero._fenced.discard("pa")
        t = c.new_txn()
        t.mutate_rdf(set_rdf='<0x1> <pa> "x" .', commit_now=True)
    finally:
        c.close()


def test_admission_costs_writes():
    """With admission on and the budget consumed, a commit sheds with
    the retryable TooManyRequestsError; releasing the budget lets the
    retry through (the write-side half of the admission contract)."""
    from dgraph_tpu.serving import TooManyRequestsError

    s = _mk_server()
    config.set_env("ADMISSION", 1)
    config.set_env("MAX_INFLIGHT", 4)
    try:
        hog = s.serving.admit_write(10_000)  # swallows the budget
        t = s.new_txn()
        t.mutate_json(set_obj={"uid": "_:a", "name": "shedme"})
        with pytest.raises(TooManyRequestsError):
            t.commit()
        s.serving.release_write(hog)
        t2 = s.new_txn()
        t2.mutate_json(
            set_obj={"uid": "_:a", "name": "shedme"}, commit_now=True
        )
        out = s.query('{ q(func: eq(name, "shedme")) { name } }')
        assert out["data"]["q"] == [{"name": "shedme"}]
    finally:
        config.unset_env("ADMISSION")
        config.unset_env("MAX_INFLIGHT")


# ---------------------------------------------------------------------------
# chaos: concurrent committers through group commit under faults
# ---------------------------------------------------------------------------

N_ACCOUNTS = 8
START_BAL = 100


@pytest.mark.chaos
def test_chaos_group_commit_bank_fixed_seed():
    """Fixed-seed drop+delay+disconnect across the RPC plane plus a
    replica crash+restart while FOUR concurrent committers drive bank
    transfers through group commit on a real multi-process cluster:

      - balances stay ledger-exact (sum conserved at every check);
      - an acked transfer applies exactly once (idempotent replay
        under resend — proposals ride idem keys, verdicts are
        recorded per txn);
      - a conflict abort never takes down batchmates (the other
        writers' acked transfers all land);
      - TimeoutError acks are AMBIGUOUS (may or may not have applied)
        and are excluded from the exact-ledger claim, like the
        serial-path chaos bank.

    Deflake (PR 15): under full-suite load the 1-core box schedules
    three replica interpreters + four writer threads + the test runner
    against everything else in tier-1 — the default 20s/15s
    commit/query deadlines and the startup election waits tripped once
    in the PR 11/12 runs (fixed seed, passes solo). The deadlines are
    widened HERE (and the harness election waits globally) so a slow
    box reads as slow, not broken; the ledger/idempotency claims are
    untouched.
    """
    from dgraph_tpu.worker.harness import ProcCluster

    config.set_env("COMMIT_DEADLINE_S", 90)
    config.set_env("QUERY_DEADLINE_S", 60)
    c = None
    plan = None
    try:
        c = ProcCluster(n_groups=1, replicas=3)
        c.alter("bal: int @upsert .")
        rdf = []
        for i in range(1, N_ACCOUNTS + 1):
            rdf.append(f'<0x{i:x}> <bal> "{START_BAL}"^^<xs:int> .')
        c.new_txn().mutate_rdf(set_rdf="\n".join(rdf), commit_now=True)
        plan = faults.install(
            FaultPlan(
                seed=777,
                rules=[
                    dict(point="send", action="drop", p=0.04),
                    dict(point="send", action="delay", p=0.10, delay_ms=4),
                    dict(point="send", action="disconnect", p=0.02),
                ],
            )
        )
        applied_lock = threading.Lock()
        applied = []  # (frm, to, amt) for every ACKED transfer
        ambiguous = [0]

        def reader_balance(uid):
            out = c.query("{ q(func: has(bal)) { uid bal } }")
            for row in out["data"]["q"]:
                if int(row["uid"], 16) == uid:
                    return row["bal"]
            return None

        def worker(seed):
            rng = np.random.default_rng(seed)
            for _ in range(6):
                frm, to = (
                    int(x) + 1
                    for x in rng.choice(N_ACCOUNTS, 2, replace=False)
                )
                amt = int(rng.integers(1, 9))
                for _attempt in range(6):
                    t = c.new_txn()
                    try:
                        # read-modify-write on @upsert keys: real
                        # conflicts under concurrency
                        bf = t.txn.cache.value(
                            _bal_key(frm)
                        )
                        bt = t.txn.cache.value(_bal_key(to))
                        bfv = int(bf.value) if bf else START_BAL
                        btv = int(bt.value) if bt else START_BAL
                        t.mutate_rdf(
                            set_rdf=(
                                f'<0x{frm:x}> <bal> "{bfv - amt}"'
                                f"^^<xs:int> .\n"
                                f'<0x{to:x}> <bal> "{btv + amt}"'
                                f"^^<xs:int> ."
                            ),
                        )
                        t.commit()
                        with applied_lock:
                            applied.append((frm, to, amt))
                        break
                    except TxnConflictError:
                        continue  # not applied: retry cleanly
                    except TimeoutError:
                        ambiguous[0] += 1
                        break

        def _bal_key(uid):
            from dgraph_tpu.x import keys as _k

            return _k.DataKey("bal", uid)

        ths = [
            threading.Thread(target=worker, args=(s,)) for s in range(4)
        ]
        for t in ths:
            t.start()
        # crash one replica mid-traffic and bring it back (process
        # SIGKILL — the group's raft quorum keeps serving)
        time.sleep(0.4)
        victim = next(iter(c.procs))
        c.kill(victim)
        time.sleep(0.3)
        c.restart(victim)
        for t in ths:
            t.join()
        faults.reset()
        out = c.query("{ q(func: has(bal)) { uid bal } }")
        bals = {
            int(x["uid"], 16): x["bal"] for x in out["data"]["q"]
        }
        assert sum(bals.values()) == N_ACCOUNTS * START_BAL, (
            bals, applied, ambiguous,
        )
        assert METRICS.value("group_commit_txns_total") > 0
    finally:
        faults.reset()
        if plan is not None:
            plan.heal()
        if c is not None:
            c.close()
        config.unset_env("COMMIT_DEADLINE_S")
        config.unset_env("QUERY_DEADLINE_S")

"""Test configuration: force a virtual 8-device CPU mesh.

The real bench runs on TPU; tests exercise the same code paths on a CPU
backend with 8 virtual devices so multi-chip sharding is validated without
TPU hardware (mirrors the reference's docker-on-one-host integration
strategy, /root/reference TESTING.md).

The environment's sitecustomize registers a remote-TPU PJRT plugin at
interpreter start (when PALLAS_AXON_POOL_IPS is set) and pins
JAX_PLATFORMS=axon; every test process would then dial the TPU tunnel —
and hang whenever the tunnel is busy or down. sitecustomize has already
imported jax by the time conftest runs, so env vars are too late; instead
we unregister the axon backend factory and flip the platform config to
cpu before any backend is initialized.
"""

import os

# XLA_FLAGS is read at CPU client creation (first backend init), which
# happens after conftest — still in time to set it here.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# XLA/TSL C++ logging writes to RAW stderr (bypassing pytest capture):
# a cold compile-cache INFO mid-run splices into the progress-dot lines
# and corrupts dot-counting harnesses. Level 2 keeps ERROR visible.
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")

# Persistent XLA compile cache: this box is 1-core, each compile is seconds.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/dgraph_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")

import jax  # noqa: E402
from jax._src import xla_bridge as _xb  # noqa: E402

_xb._backend_factories.pop("axon", None)
jax.config.update("jax_platforms", "cpu")


# GIL-fuzz race harness (DGRAPH_TPU_RACE_FUZZ / check.sh --race-sanity):
# a ~1µs switch interval forces a thread switch roughly every bytecode,
# so a read-modify-write race that needs an unlucky preemption between
# LOAD and STORE hits on nearly every iteration instead of once a month
# under full-suite load. Env read is raw on purpose — conftest runs
# before dgraph_tpu imports are safe, and tests/ is outside the
# config-registry analyzer's scan root.
if os.environ.get("DGRAPH_TPU_RACE_FUZZ", "").strip().lower() in (
    "1", "true", "yes", "on"
):
    import sys as _sys

    _sys.setswitchinterval(1e-6)


def pytest_configure(config):
    # tier-1 runs `-m 'not slow'`: the parallel-executor smoke subset
    # (test_parallel_exec.py, DGRAPH_TPU_EXEC_WORKERS=4 over sampled DQL
    # goldens) stays in tier-1 to keep thread-safety regressions out of
    # main; the full 535-case corpus sweep and other large passes carry
    # this marker so the 1-core box stays fast.
    config.addinivalue_line(
        "markers",
        "slow: full-corpus / large-scale passes excluded from tier-1",
    )
    # chaos: fault-injection suites (tests/test_chaos.py). The fixed-seed
    # smoke schedules stay in tier-1 (<30s); long randomized schedules
    # carry `slow` as well and run out-of-band.
    config.addinivalue_line(
        "markers",
        "chaos: fault-injection schedules against the cluster stack",
    )

"""Vector index tests: brute-force exactness, IVF recall, similar_to e2e.

Mirrors /root/reference/tok/hnsw/persistent_hnsw_test.go and
ef_recall_test.go intent: correctness + recall against exact scan.
"""

import numpy as np
import pytest

from dgraph_tpu.models.vector import VectorIndex


def _exact_topk(V, uids, q, k, metric="euclidean"):
    if metric == "euclidean":
        d = ((V - q[None, :]) ** 2).sum(axis=1)
    elif metric == "cosine":
        d = 1 - (V @ q) / (
            np.linalg.norm(V, axis=1) * np.linalg.norm(q) + 1e-12
        )
    else:
        d = -(V @ q)
    idx = np.argsort(d, kind="stable")[:k]
    return [int(uids[i]) for i in idx]


@pytest.mark.parametrize("metric", ["euclidean", "cosine", "dotproduct"])
def test_brute_force_exact(metric):
    rng = np.random.default_rng(0)
    n, d = 500, 32
    V = rng.standard_normal((n, d)).astype(np.float32)
    uids = np.arange(1, n + 1)
    idx = VectorIndex("emb", metric=metric)
    for u, v in zip(uids, V):
        idx.insert(int(u), v)
    q = rng.standard_normal(d).astype(np.float32)
    got = list(idx.search(q, 10))
    want = _exact_topk(V, uids, q, 10, metric)
    assert got == want


def test_insert_update_remove():
    idx = VectorIndex("emb")
    idx.insert(1, [0.0, 0.0])
    idx.insert(2, [1.0, 1.0])
    idx.insert(3, [5.0, 5.0])
    assert list(idx.search([0.1, 0.1], 2)) == [1, 2]
    idx.insert(1, [10.0, 10.0])  # update moves uid 1 away
    assert list(idx.search([0.1, 0.1], 2)) == [2, 3]
    idx.remove(2)
    assert list(idx.search([0.1, 0.1], 3)) == [3, 1]
    assert len(idx) == 2


def test_filtered_search_and_threshold():
    idx = VectorIndex("emb")
    for u in range(1, 11):
        idx.insert(u, [float(u), 0.0])
    got = list(idx.search([0.0, 0.0], 3, allowed=np.array([4, 5, 6], np.uint64)))
    assert got == [4, 5, 6]
    got = list(idx.search([0.0, 0.0], 10, distance_threshold=9.1))
    assert got == [1, 2, 3]  # squared euclidean <= 9.1


def test_search_with_uid():
    idx = VectorIndex("emb")
    for u in range(1, 6):
        idx.insert(u, [float(u), 0.0])
    assert list(idx.search_with_uid(3, 2)) == [2, 4]


def test_ivf_recall():
    rng = np.random.default_rng(1)
    n, d, k = 4000, 16, 10
    V = rng.standard_normal((n, d)).astype(np.float32)
    uids = np.arange(1, n + 1)
    idx = VectorIndex("emb", ivf_threshold=1000, nprobe=16)
    for u, v in zip(uids, V):
        idx.insert(int(u), v)
    idx._sync_device()
    assert idx._ivf is not None
    hits = total = 0
    for _ in range(20):
        q = rng.standard_normal(d).astype(np.float32)
        got = set(int(u) for u in idx.search(q, k))
        want = set(_exact_topk(V, uids, q, k))
        hits += len(got & want)
        total += k
    recall = hits / total
    assert recall >= 0.90, recall


def test_similar_to_e2e():
    from dgraph_tpu.api.server import Server

    s = Server()
    s.alter(
        "embedding: float32vector @index(hnsw(metric:\"euclidean\")) .\n"
        "name: string @index(exact) ."
    )
    t = s.new_txn()
    t.mutate_rdf(
        set_rdf="\n".join(
            [
                '<0x1> <name> "a" .',
                '<0x1> <embedding> "[1.0, 0.0]"^^<float32vector> .',
                '<0x2> <name> "b" .',
                '<0x2> <embedding> "[0.9, 0.1]"^^<float32vector> .',
                '<0x3> <name> "c" .',
                '<0x3> <embedding> "[-1.0, 0.5]"^^<float32vector> .',
            ]
        ),
        commit_now=True,
    )
    res = s.query(
        '{ v(func: similar_to(embedding, 2, "[1.0, 0.05]")) { name } }'
    )["data"]
    assert [o["name"] for o in res["v"]] == ["a", "b"]

    # by-uid form (result order is uid-ascending, ref worker/task.go:407)
    res = s.query('{ v(func: similar_to(embedding, 2, 0x3)) { name } }')[
        "data"
    ]
    assert {o["name"] for o in res["v"]} == {"b", "c"}

    # vector roundtrip in output
    res = s.query('{ v(func: uid(0x1)) { embedding } }')["data"]
    assert res["v"][0]["embedding"] == [1.0, 0.0]

    # update vector then delete entity removes from index
    t = s.new_txn()
    t.mutate_rdf(del_rdf="<0x1> <embedding> * .", commit_now=True)
    res = s.query(
        '{ v(func: similar_to(embedding, 3, "[1.0, 0.05]")) { name } }'
    )["data"]
    assert [o["name"] for o in res["v"]] == ["b", "c"]


def test_mesh_sharded_engine_search(monkeypatch):
    """DGRAPH_TPU_SHARD_VECTORS=1 routes engine vector search through the
    row-sharded mesh top-k (runs on the virtual 8-device CPU mesh —
    the distributed data plane for 1M×768-class corpora)."""
    import numpy as np

    import jax

    if len(jax.devices()) < 2:
        import pytest as _pytest

        _pytest.skip("needs multi-device mesh")
    monkeypatch.setenv("DGRAPH_TPU_SHARD_VECTORS", "1")
    from dgraph_tpu.models.vector import VectorIndex

    rng = np.random.default_rng(4)
    n, d = 3000, 32
    V = rng.standard_normal((n, d)).astype(np.float32)
    idx = VectorIndex("m", ivf_threshold=1 << 62)
    for i in range(n):
        idx.insert(i + 1, V[i])
    q = V[17] + 0.001 * rng.standard_normal(d).astype(np.float32)
    got = idx.search(q, 5)
    assert idx._mesh is not None  # actually sharded
    # exact result parity with the single-device brute force
    monkeypatch.delenv("DGRAPH_TPU_SHARD_VECTORS")
    idx2 = VectorIndex("m2", ivf_threshold=1 << 62)
    for i in range(n):
        idx2.insert(i + 1, V[i])
    want = idx2.search(q, 5)
    np.testing.assert_array_equal(got, want)
    assert got[0] == 18  # uid of the perturbed row

    # engine-level similar_to through the sharded path
    monkeypatch.setenv("DGRAPH_TPU_SHARD_VECTORS", "1")
    from dgraph_tpu.api.server import Server

    s = Server()
    s.alter(
        'emb: float32vector @index(hnsw(metric:"euclidean")) .\n'
        "name: string @index(exact) ."
    )
    t = s.new_txn()
    objs = [
        {"uid": f"0x{i+1:x}", "name": f"v{i+1}", "emb": V[i].tolist()}
        for i in range(50)
    ]
    t.mutate_json(set_obj=objs, commit_now=True)
    vec_str = "[" + ", ".join(f"{x:.6f}" for x in V[7]) + "]"
    out = s.query(
        '{ q(func: similar_to(emb, 3, "%s")) { name } }' % vec_str
    )
    assert out["data"]["q"][0]["name"] == "v8"

"""GraphQL @auth + introspection (VERDICT r1 missing #8; ref
graphql/schema/auth.go, resolve/query_rewriter auth injection,
schema/introspection.go).
"""

import json

import pytest

from dgraph_tpu.acl import jwt as jwtlib
from dgraph_tpu.api.server import Server
from dgraph_tpu.graphql.resolve import GraphQLServer

SDL = r'''
type Todo @auth(
  query: { or: [
    { rule: "{$ROLE: { eq: \"ADMIN\" } }" },
    { rule: """query($USER: String!) { queryTodo(filter: { owner: { eq: $USER } }) { __typename } }""" }
  ]},
  add: { or: [
    { rule: "{$ROLE: { eq: \"ADMIN\" } }" },
    { rule: """query($USER: String!) { queryTodo(filter: { owner: { eq: $USER } }) { __typename } }""" }
  ]},
  delete: { rule: "{$ROLE: { eq: \"ADMIN\" } }" }
) {
  id: ID!
  owner: String @search(by: [exact])
  text: String @search(by: [term])
}

type Public {
  id: ID!
  name: String @search(by: [exact])
}

# Dgraph.Authorization {"VerificationKey":"secret-key","Header":"X-App-Auth","Namespace":"","Algo":"HS256"}
'''


def _token(claims):
    return jwtlib.encode(claims, b"secret-key")


@pytest.fixture()
def gql():
    engine = Server()
    g = GraphQLServer(engine, SDL)
    g.execute(
        'mutation { addTodo(input: [{owner: "alice", text: "a1"}, '
        '{owner: "bob", text: "b1"}]) { numUids } }',
        claims={"USER": "system", "ROLE": "ADMIN"},
    )
    return g


def test_auth_config_parsed(gql):
    assert gql.auth_config is not None
    assert gql.auth_config.header == "X-App-Auth"


def test_query_rule_filters_by_owner(gql):
    out = gql.execute(
        "{ queryTodo { owner text } }", jwt_token=_token({"USER": "alice"})
    )
    todos = out["data"]["queryTodo"]
    assert [t["owner"] for t in todos] == ["alice"]


def test_rbac_admin_sees_all(gql):
    out = gql.execute(
        "{ queryTodo { owner } }",
        jwt_token=_token({"USER": "nobody", "ROLE": "ADMIN"}),
    )
    assert len(out["data"]["queryTodo"]) == 2


def test_no_token_denied_but_unprotected_type_open(gql):
    out = gql.execute("{ queryTodo { owner } }")
    # no claims: the or-rule needs $USER -> error surfaces in envelope
    assert out.get("errors") or out["data"]["queryTodo"] == []
    out = gql.execute("{ queryPublic { name } }")
    assert out["data"]["queryPublic"] == []  # open type, just empty


def test_add_rule_enforced(gql):
    # bob may only add todos he owns
    out = gql.execute(
        'mutation { addTodo(input: [{owner: "bob", text: "ok"}]) { numUids } }',
        jwt_token=_token({"USER": "bob"}),
    )
    assert out["data"]["addTodo"]["numUids"] == 1
    out = gql.execute(
        'mutation { addTodo(input: [{owner: "eve", text: "nope"}]) { numUids } }',
        jwt_token=_token({"USER": "bob"}),
    )
    # ref resolver wording: post-insert auth check failed
    assert out["data"] is None and "authorization failed" in (
        out["errors"][0]["message"]
    )


def test_delete_rbac(gql):
    out = gql.execute(
        'mutation { deleteTodo(filter: {owner: {eq: "alice"}}) { numUids } }',
        jwt_token=_token({"USER": "alice"}),  # not ADMIN
    )
    # a denied delete matches nothing — empty payload, NOT an error
    # (ref auth_delete_test "top level RBAC false": `x as deleteLog()`)
    assert not out.get("errors"), out
    assert out["data"]["deleteTodo"]["numUids"] == 0
    out = gql.execute(
        'mutation { deleteTodo(filter: {owner: {eq: "alice"}}) { numUids } }',
        jwt_token=_token({"ROLE": "ADMIN"}),
    )
    assert out["data"]["deleteTodo"]["numUids"] == 1


def test_bad_signature_rejected(gql):
    bad = jwtlib.encode({"USER": "alice"}, b"wrong-key")
    out = gql.execute("{ queryTodo { owner } }", jwt_token=bad)
    assert out.get("errors")


def test_typename_injection(gql):
    out = gql.execute(
        "{ queryTodo { __typename owner } }",
        jwt_token=_token({"ROLE": "ADMIN", "USER": "x"}),
    )
    assert all(t["__typename"] == "Todo" for t in out["data"]["queryTodo"])


def test_introspection_schema(gql):
    out = gql.execute(
        """{ __schema {
             queryType { name }
             mutationType { name }
             types { name kind }
           } }"""
    )
    sch = out["data"]["__schema"]
    assert sch["queryType"]["name"] == "Query"
    names = {t["name"] for t in sch["types"]}
    assert {"Todo", "Public", "Query", "Mutation", "String"} <= names


def test_introspection_type_fields(gql):
    out = gql.execute(
        '{ __type(name: "Todo") { name kind fields { name type { kind name ofType { name } } } } }'
    )
    t = out["data"]["__type"]
    assert t["name"] == "Todo" and t["kind"] == "OBJECT"
    fields = {f["name"] for f in t["fields"]}
    assert {"id", "owner", "text"} <= fields


def test_introspection_query_fields(gql):
    out = gql.execute(
        '{ __type(name: "Query") { fields { name } } }'
    )
    names = {f["name"] for f in out["data"]["__type"]["fields"]}
    assert {"getTodo", "queryTodo", "aggregateTodo", "queryPublic"} <= names

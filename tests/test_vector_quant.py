"""Quantized vector engine: recall, exact equivalence, incremental IVF.

The quantized engine's contract (models/vector.py): the int8 scan may
only ever *narrow* the candidate pool — the float32 rerank re-scores
survivors exactly, so the final top-k ordering is float-exact whenever
the true neighbors survive the scan. These tests drive that contract
through adversarial row scales, duplicate vectors, and tombstones; pin
the incremental-IVF "no full rebuild on mutation" invariant against
fresh builds; pin the per-call brute-vs-IVF crossover on both sides of
the r5 inversion (VECTOR_1M_CPU.json: batched IVF 5.8 qps losing to
brute 12.2); and hold the solo == batch-row identity the serving-front
coalescing of similar_to (serving/microbatch.read_similar) relies on.

This module is part of the UBSan corpus (test_native_san.py): the
native kernels vec_qi8_topk / vec_qi8_topk_idx / vec_qi8_topk_lists /
vec_qi8_quantize run every case here under -fsanitize=undefined in
that gate.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from dgraph_tpu.models import vector
from dgraph_tpu.models.vector import VectorIndex


@pytest.fixture(autouse=True)
def _quant_on_small_corpora(monkeypatch):
    """The quantized engine only engages above _QUANT_MIN live rows
    (below it the jitted float scan is already sub-ms and exact);
    force it on for test-sized corpora."""
    monkeypatch.setattr(vector, "_QUANT_MIN", 1)


def _exact_topk(V, uids, q, k, metric="euclidean"):
    if metric == "euclidean":
        d = ((V - q[None, :]) ** 2).sum(axis=1)
    elif metric == "cosine":
        d = 1 - (V @ q) / (
            np.linalg.norm(V, axis=1) * np.linalg.norm(q) + 1e-12
        )
    else:
        d = -(V @ q)
    idx = np.argsort(d, kind="stable")[:k]
    return [int(uids[i]) for i in idx]


def _mk(V, uids=None, metric="euclidean", **kw):
    if uids is None:
        uids = np.arange(1, len(V) + 1, dtype=np.uint64)
    idx = VectorIndex("emb", metric=metric, **kw)
    idx.bulk_load(np.asarray(uids, np.uint64), np.ascontiguousarray(V))
    return idx


# ---------------------------------------------------------------------------
# Quantized-vs-float: exact equivalence and recall
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("metric", ["euclidean", "cosine", "dotproduct"])
def test_quant_brute_matches_exact_float(metric):
    """Quantized brute tier == exact float ordering: the int8 scan keeps
    VEC_RERANK*k candidates and the rerank is float-exact, so on
    well-separated data the full top-k matches the exact scan."""
    rng = np.random.default_rng(0)
    n, d, k = 6000, 48, 10
    V = rng.standard_normal((n, d)).astype(np.float32)
    uids = np.arange(1, n + 1, dtype=np.uint64)
    idx = _mk(V, uids, metric=metric, ivf_threshold=1 << 62)
    assert idx._use_quant(), "quantized engine must engage"
    for qi in range(8):
        q = rng.standard_normal(d).astype(np.float32)
        got = [int(u) for u in idx.search(q, k)]
        assert got == _exact_topk(V, uids, q, k, metric), f"query {qi}"
    assert vector.counters()["path_quant_brute"] > 0


@pytest.mark.parametrize("quant_env", ["1", "0"])
def test_quant_vs_float_escape_hatch_same_results(monkeypatch, quant_env):
    """DGRAPH_TPU_VEC_QUANT is a pure A/B switch: both engines return
    the same top-k on the same corpus (both exact on the brute tier)."""
    monkeypatch.setenv("DGRAPH_TPU_VEC_QUANT", quant_env)
    rng = np.random.default_rng(1)
    n, d, k = 4000, 32, 10
    V = rng.standard_normal((n, d)).astype(np.float32)
    uids = np.arange(1, n + 1, dtype=np.uint64)
    idx = _mk(V, uids, ivf_threshold=1 << 62)
    assert idx._use_quant() == (quant_env == "1")
    q = rng.standard_normal(d).astype(np.float32)
    got = [int(u) for u in idx.search(q, k)]
    assert got == _exact_topk(V, uids, q, k)


def test_quant_adversarial_row_scales():
    """Per-row asymmetric quantization is scale-invariant per row: rows
    spanning 12 orders of magnitude, constant rows, and all-zero rows
    must neither crash nor displace the true neighbors."""
    rng = np.random.default_rng(2)
    n, d, k = 3000, 24, 10
    V = rng.standard_normal((n, d)).astype(np.float32)
    mags = (10.0 ** rng.uniform(-6, 6, size=n)).astype(np.float32)
    V *= mags[:, None]
    V[100] = 0.0                       # all-zero row
    V[101] = 3.25                      # constant row
    V[102] = np.float32(1e-30)         # denormal-scale row
    uids = np.arange(1, n + 1, dtype=np.uint64)
    idx = _mk(V, uids, ivf_threshold=1 << 62)
    assert idx._use_quant()
    hits = total = 0
    for _ in range(16):
        q = rng.standard_normal(d).astype(np.float32) * float(
            10.0 ** rng.uniform(-3, 3)
        )
        got = set(int(u) for u in idx.search(q, k))
        want = set(_exact_topk(V, uids, q, k))
        hits += len(got & want)
        total += k
    assert hits / total >= 0.95, hits / total
    # the degenerate rows themselves are findable exactly
    assert int(idx.search(np.zeros(d, np.float32), 1)[0]) == 101


def test_quant_duplicate_vectors_deterministic():
    """Duplicate vectors tie exactly (same codes -> same integer dot ->
    same float32 distance); the kernels pin the tie-break to the LOWER
    row index, so repeated searches — native or numpy mirror — return
    the identical uid list (what solo-vs-coalesced byte-identity needs
    for duplicate corpora)."""
    rng = np.random.default_rng(3)
    n, d, k = 2000, 16, 12
    base = rng.standard_normal((50, d)).astype(np.float32)
    V = base[rng.integers(0, 50, n)]  # every vector duplicated ~40x
    uids = np.arange(1, n + 1, dtype=np.uint64)
    idx = _mk(V, uids, ivf_threshold=1 << 62)
    q = base[7] + np.float32(1e-3)
    first = [int(u) for u in idx.search(q, k)]
    for _ in range(3):
        assert [int(u) for u in idx.search(q, k)] == first
    # numpy mirror agrees with the native kernel on the tie-break
    view = idx._quant_view()
    qc, qs, qo, qcs, qstat = vector._quantize_queries(
        q.reshape(1, -1), "euclidean"
    )
    rows_py, _ = vector._qi8_scan_py(
        view["codes"], view["scales"], view["offsets"], view["csums"],
        view["sqnorms"], view["valid"], qc[0], qs[0], qo[0], qcs[0],
        qstat[0], "euclidean", k,
    )
    from dgraph_tpu import native

    if native.NATIVE_AVAILABLE:
        got = native.vec_qi8_topk(
            view["codes"], view["scales"], view["offsets"],
            view["csums"], view["sqnorms"], view["valid"],
            qc, qs, qo, qcs, qstat, 0, k,
        )
        assert got is not None
        np.testing.assert_array_equal(got[0][0], rows_py)


def test_quant_tombstones_never_surface():
    """Removed uids must never appear in results, and the survivors'
    ordering must match a fresh index built from only the survivors
    (both brute tiers are exact)."""
    rng = np.random.default_rng(4)
    n, d, k = 3000, 24, 15
    V = rng.standard_normal((n, d)).astype(np.float32)
    uids = np.arange(1, n + 1, dtype=np.uint64)
    idx = _mk(V, uids, ivf_threshold=1 << 62)
    dead = set(range(1, n + 1, 3))  # remove every third uid
    for u in dead:
        idx.remove(u)
    keep = np.array([u for u in uids if int(u) not in dead], np.uint64)
    fresh = _mk(V[[int(u) - 1 for u in keep]], keep, ivf_threshold=1 << 62)
    for _ in range(6):
        q = rng.standard_normal(d).astype(np.float32)
        got = [int(u) for u in idx.search(q, k)]
        assert not (set(got) & dead), "tombstoned uid surfaced"
        assert got == [int(u) for u in fresh.search(q, k)]


def test_quant_ivf_recall_clustered():
    """IVF tier recall on clustered data (the embedding-corpus regime
    the index contract assumes): recall@10 >= 0.95 vs exact scan."""
    rng = np.random.default_rng(5)
    nclust, per, d, k = 64, 120, 32, 10
    cents = 12.0 * rng.standard_normal((nclust, d)).astype(np.float32)
    V = (
        cents[np.repeat(np.arange(nclust), per)]
        + rng.standard_normal((nclust * per, d)).astype(np.float32)
    )
    n = len(V)
    uids = np.arange(1, n + 1, dtype=np.uint64)
    idx = _mk(V, uids, ivf_threshold=1000)
    queries = (
        cents[rng.integers(0, nclust, 30)]
        + rng.standard_normal((30, d)).astype(np.float32)
    )
    got = idx.search_batch(queries, k)
    assert vector.counters()["path_quant_ivf"] > 0, "IVF tier not engaged"
    hits = total = 0
    for i, q in enumerate(queries):
        want = set(_exact_topk(V, uids, q, k))
        hits += len(set(int(u) for u in got[i]) & want)
        total += k
    assert hits / total >= 0.95, hits / total


def test_native_quantize_matches_numpy_mirror():
    """vec_qi8_quantize == the numpy _quantize mirror bit-for-bit on
    codes/scales/offsets/csums (same f32 op order, rintf == np.rint
    under round-to-nearest-even), across adversarial row scales,
    constant rows, and zero rows; sqnorms agree to accumulation-order
    float32 tolerance."""
    from dgraph_tpu import native

    if not native.NATIVE_AVAILABLE:
        pytest.skip("native lib unavailable")
    rng = np.random.default_rng(20)
    n, d = 1500, 67  # odd dim: exercises the SIMD tail loop
    V = rng.standard_normal((n, d)).astype(np.float32)
    V *= (10.0 ** rng.uniform(-6, 6, size=n)).astype(np.float32)[:, None]
    V[7] = 0.0
    V[8] = -2.5
    codes, scales, offsets, csums = vector._quantize(V)
    sqn = (V * V).sum(axis=1, dtype=np.float32)
    for nt in (1, 3):
        got = native.vec_qi8_quantize(V, nt)
        assert got is not None
        nc, ns, no, ncs, nsq = got
        np.testing.assert_array_equal(nc, codes)
        np.testing.assert_array_equal(ns, scales)
        np.testing.assert_array_equal(no, offsets)
        np.testing.assert_array_equal(ncs, csums)
        np.testing.assert_allclose(nsq, sqn, rtol=1e-5)


def test_lists_kernel_rows_match_solo_idx_kernel():
    """Every row of a vec_qi8_topk_lists batch is byte-identical to the
    solo vec_qi8_topk_idx call on the same candidate slice — the kernel-
    level form of the solo == coalesced contract — across metrics,
    thread counts, empty slices, aliased slices, and tombstones."""
    from dgraph_tpu import native

    if not native.NATIVE_AVAILABLE:
        pytest.skip("native lib unavailable")
    rng = np.random.default_rng(21)
    n, d, nq, k = 4000, 32, 9, 8
    V = rng.standard_normal((n, d)).astype(np.float32)
    codes, scales, offsets, csums = vector._quantize(V)
    sqn = (V * V).sum(axis=1, dtype=np.float32)
    valid = np.ones((n,), np.uint8)
    valid[rng.choice(n, 400, replace=False)] = 0
    Q = rng.standard_normal((nq, d)).astype(np.float32)
    cand = [
        np.sort(
            rng.choice(n, int(rng.integers(1, 700)), replace=False)
        ).astype(np.int32)
        for _ in range(nq)
    ]
    cand[4] = np.zeros((0,), np.int32)   # empty slice
    cand[6] = cand[2]                     # aliased slice
    lens = np.array([c.size for c in cand], np.int64)
    ends = np.cumsum(lens)
    begs = ends - lens
    cat = np.concatenate(cand)
    for metric in ("euclidean", "cosine", "dotproduct"):
        qc, qs, qo, qcs, qstat = vector._quantize_queries(Q, metric)
        mid = vector._METRIC_ID[metric]
        for nt in (1, 2):
            got = native.vec_qi8_topk_lists(
                codes, scales, offsets, csums, sqn, valid,
                cat, begs, ends, qc, qs, qo, qcs, qstat, mid, k, nt,
            )
            assert got is not None
            li, ld, _scanned = got
            for i in range(nq):
                si, sd, _w = native.vec_qi8_topk_idx(
                    codes, scales, offsets, csums, sqn, valid,
                    cand[i], qc[i], qs[i], qo[i], qcs[i], qstat[i],
                    mid, k,
                )
                np.testing.assert_array_equal(li[i], si, err_msg=metric)
                np.testing.assert_array_equal(ld[i], sd, err_msg=metric)


def test_native_assignment_path_serves_same_recall(monkeypatch):
    """The int8 coarse-to-fine cell assignment (the 1Mx768 build path,
    forced here by zeroing its MAC threshold) must serve the same
    recall class as the exact numpy assignment, and keep the
    incremental no-rebuild invariant."""
    from dgraph_tpu import native

    if not native.NATIVE_AVAILABLE:
        pytest.skip("native lib unavailable")
    rng = np.random.default_rng(22)
    nclust, per, d, k = 32, 120, 24, 10
    cents = 10.0 * rng.standard_normal((nclust, d)).astype(np.float32)
    V = (
        cents[np.repeat(np.arange(nclust), per)]
        + rng.standard_normal((nclust * per, d)).astype(np.float32)
    )
    uids = np.arange(1, len(V) + 1, dtype=np.uint64)
    queries = (
        cents[rng.integers(0, nclust, 25)]
        + rng.standard_normal((25, d)).astype(np.float32)
    )

    def recall(ix):
        hits = 0
        for q in queries:
            want = set(_exact_topk(V, uids, q, k))
            hits += len(set(int(u) for u in ix.search(q, k)) & want)
        return hits / (25 * k)

    monkeypatch.setattr(vector, "_ASSIGN_NATIVE_MIN_MACS", 0)
    nat = _mk(V, uids, ivf_threshold=500)
    nat.search(cents[0], k)
    monkeypatch.setattr(vector, "_ASSIGN_NATIVE_MIN_MACS", float("inf"))
    ref = _mk(V, uids, ivf_threshold=500)
    ref.search(cents[0], k)
    r_nat, r_ref = recall(nat), recall(ref)
    assert r_nat >= r_ref - 0.03, (r_nat, r_ref)

    # incremental growth through the native path: no rebuild, inserted
    # vectors findable, assignment stays deterministic
    monkeypatch.setattr(vector, "_ASSIGN_NATIVE_MIN_MACS", 0)
    for j in range(40):
        u = len(V) + 1 + j
        v = cents[int(rng.integers(0, nclust))] + rng.standard_normal(
            d
        ).astype(np.float32)
        nat.insert(u, v)
        assert int(nat.search(v, 1)[0]) == u
    assert nat.build_count == 1 and nat.repartition_count == 0


# ---------------------------------------------------------------------------
# Incremental IVF: mutations never rebuild
# ---------------------------------------------------------------------------


def test_incremental_insert_remove_no_rebuild():
    """Inserts append to nearest cells, removes tombstone in place:
    after heavy mutation the centroids have NOT retrained
    (build_count pinned), no repartition ran below the thresholds, and
    served results are correct — inserted vectors findable, removed
    uids gone (equivalence vs exact scan on the mutated corpus)."""
    rng = np.random.default_rng(6)
    nclust, per, d, k = 32, 100, 24, 10
    cents = 10.0 * rng.standard_normal((nclust, d)).astype(np.float32)
    V = (
        cents[np.repeat(np.arange(nclust), per)]
        + rng.standard_normal((nclust * per, d)).astype(np.float32)
    )
    n = len(V)
    uids = np.arange(1, n + 1, dtype=np.uint64)
    idx = _mk(V, uids, ivf_threshold=500)
    idx.search(cents[0], k)  # trigger the initial build
    assert idx.build_count == 1 and idx.repartition_count == 0

    # mutate: 200 inserts near existing clusters, 150 removes
    new_uids, new_vecs = [], []
    for j in range(200):
        u = n + 1 + j
        v = cents[int(rng.integers(0, nclust))] + rng.standard_normal(
            d
        ).astype(np.float32)
        idx.insert(u, v)
        new_uids.append(u)
        new_vecs.append(v)
    removed = set(int(u) for u in rng.choice(uids, 150, replace=False))
    for u in removed:
        idx.remove(u)

    res = idx.search_batch(np.stack(new_vecs[:20]), k)
    assert idx.build_count == 1, "mutation triggered a centroid retrain"
    assert idx.repartition_count == 0, "mutation triggered a repartition"
    for j in range(20):
        assert int(res[j][0]) == new_uids[j], "inserted vector not nearest"
    got = idx.search(cents[1], 2 * k)
    assert not (set(int(u) for u in got) & removed)


def test_repartition_triggers_on_garbage_and_stays_correct(monkeypatch):
    """Tombstone garbage past live/4 triggers ONE deferred repartition
    (cells reassigned, centroids kept — build_count still 1) and the
    probe stops scanning dead rows."""
    rng = np.random.default_rng(7)
    n, d, k = 4000, 16, 5
    V = rng.standard_normal((n, d)).astype(np.float32) + 5.0
    uids = np.arange(1, n + 1, dtype=np.uint64)
    idx = _mk(V, uids, ivf_threshold=500)
    idx.search(V[0], k)
    assert idx.build_count == 1
    for u in range(1, n // 2):  # ~50% garbage >> live/4
        idx.remove(u)
    got = idx.search(V[n - 1], k)
    assert idx.repartition_count == 1
    assert idx.build_count == 1, "repartition must keep centroids"
    assert int(got[0]) == n
    assert all(int(u) >= n // 2 for u in got)


def test_incremental_matches_fresh_build_recall():
    """An index grown incrementally to corpus X serves the same recall
    class as one built fresh on X (the layout differs; the answers must
    not degrade): recall gap vs exact <= 3 points over 20 queries."""
    rng = np.random.default_rng(8)
    nclust, per, d, k = 24, 80, 16, 10
    cents = 8.0 * rng.standard_normal((nclust, d)).astype(np.float32)
    V = (
        cents[np.repeat(np.arange(nclust), per)]
        + rng.standard_normal((nclust * per, d)).astype(np.float32)
    )
    half = len(V) // 2
    uids = np.arange(1, len(V) + 1, dtype=np.uint64)

    inc = _mk(V[:half], uids[:half], ivf_threshold=400)
    inc.search(cents[0], k)  # build on the first half
    for i in range(half, len(V)):  # grow incrementally to full X
        inc.insert(int(uids[i]), V[i])
    fresh = _mk(V, uids, ivf_threshold=400)

    def recall(ix):
        hits = 0
        for qi in range(20):
            q = cents[qi % nclust] + rng.standard_normal(d).astype(
                np.float32
            )
            want = set(_exact_topk(V, uids, q, k))
            hits += len(set(int(u) for u in ix.search(q, k)) & want)
        return hits / (20 * k)

    r_inc, r_fresh = recall(inc), recall(fresh)
    assert inc.build_count == 1, "incremental growth retrained"
    assert r_inc >= r_fresh - 0.03, (r_inc, r_fresh)


# ---------------------------------------------------------------------------
# Per-call brute-vs-IVF crossover (the r5 inversion, both sides)
# ---------------------------------------------------------------------------


def test_ivf_pick_both_sides_of_the_crossover():
    pick = VectorIndex._ivf_pick
    n = 1_000_000
    # r5 inversion regression (VECTOR_1M_CPU.json): a batched jit probe
    # pooling ~3% of the corpus STILL loses to brute at batch 64 —
    # the old static choice picked IVF here and lost 5.8-vs-12.2 qps
    assert pick(64, 30_000, n, quant=False) is False
    # ...while a single query at the same pool picks IVF
    assert pick(1, 30_000, n, quant=False) is True
    # jit single-query crossover flips when the probe nears corpus/3
    assert pick(1, n // 2, n, quant=False) is False
    # quantized engine: probe and brute share the scan kernel, so the
    # pick flips right around probed ~ corpus (10/13 ratio)
    assert pick(8, int(n * 0.5), n, quant=True) is True
    assert pick(8, int(n * 0.9), n, quant=True) is False
    # a probe covering the corpus can never win
    assert pick(1, n, n, quant=True) is False
    assert pick(1, n, n, quant=False) is False


def test_crossover_routes_real_searches(monkeypatch):
    """Integration: the same quantized index routes batched searches
    brute (pool ~ corpus after multi-assignment) or IVF per CALL as
    nprobe moves the estimated pool across the crossover."""
    rng = np.random.default_rng(9)
    n, d, k = 5000, 16, 5
    V = rng.standard_normal((n, d)).astype(np.float32)
    idx = _mk(V, ivf_threshold=500, nlist=64, nprobe=2)
    Q = rng.standard_normal((4, d)).astype(np.float32)
    vector.reset_counters()
    idx.search_batch(Q, k)  # nprobe 2/64 -> tiny pool -> IVF
    assert vector.counters()["path_quant_ivf"] == 4
    idx2 = _mk(V, ivf_threshold=500, nlist=64, nprobe=64)
    vector.reset_counters()
    idx2.search_batch(Q, k)  # full probe: pool ~ 2x corpus -> brute
    assert vector.counters()["path_quant_brute"] == 4


# ---------------------------------------------------------------------------
# Solo == batch row (the coalescing identity) + serving integration
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("quant_env", ["1", "0"])
def test_search_one_is_batch_row(monkeypatch, quant_env):
    monkeypatch.setenv("DGRAPH_TPU_VEC_QUANT", quant_env)
    rng = np.random.default_rng(10)
    n, d, k = 3000, 24, 7
    V = rng.standard_normal((n, d)).astype(np.float32)
    for thr in (1 << 62, 400):  # brute tier and IVF tier
        idx = _mk(V, ivf_threshold=thr)
        Q = rng.standard_normal((5, d)).astype(np.float32)
        batch = idx.search_batch(Q, k)
        for i in range(len(Q)):
            np.testing.assert_array_equal(
                idx.search_one(Q[i], k), batch[i]
            )


def test_read_similar_coalesces_and_demuxes_identically():
    """Concurrent plain similar_to tasks coalesce into ONE search_batch
    dispatch through the micro-batcher; every member's row is byte-
    identical to its solo search, and the batch_dispatch span links
    every member's trace."""
    from dgraph_tpu.serving.microbatch import MicroBatcher
    from dgraph_tpu.utils.observe import METRICS, TRACER, parse_traceparent

    rng = np.random.default_rng(11)
    n, d, k = 4000, 16, 6
    V = rng.standard_normal((n, d)).astype(np.float32)
    idx = _mk(V, ivf_threshold=1 << 62)

    first_started = threading.Event()
    release_first = threading.Event()
    calls = []
    real_batch = idx.search_batch

    def gated_batch(Q, kk):
        calls.append(len(Q))
        if len(calls) == 1:
            first_started.set()
            release_first.wait(5)
        return real_batch(Q, kk)

    idx.search_batch = gated_batch

    class StubCache:
        kv = object()
        mem = object()
        read_ts = 11

    cache = StubCache()
    b = MicroBatcher(inflight_fn=lambda: 3)
    os.environ["DGRAPH_TPU_BATCH_WINDOW_US"] = "1000000"
    queries = rng.standard_normal((3, d)).astype(np.float32)
    solo = [real_batch(q.reshape(1, -1), k)[0] for q in queries]
    results = {}
    trace_ids = {}
    before = METRICS.value("batch_coalesced_total")
    try:

        def member(i):
            with TRACER.span("query") as root:
                trace_ids[i] = root.trace_id
                results[i] = b.read_similar(
                    "emb", cache, idx, queries[i], k
                )

        t0 = threading.Thread(target=member, args=(0,))
        t0.start()
        first_started.wait(5)
        t1 = threading.Thread(target=member, args=(1,))
        t2 = threading.Thread(target=member, args=(2,))
        t1.start()
        time.sleep(0.05)
        t2.start()
        time.sleep(0.05)
        release_first.set()
        for th in (t0, t1, t2):
            th.join(10)
    finally:
        os.environ.pop("DGRAPH_TPU_BATCH_WINDOW_US", None)
        release_first.set()
        idx.search_batch = real_batch

    # members 1+2 coalesced into ONE combined dispatch of 2 rows
    assert sorted(calls) == [1, 2], calls
    assert METRICS.value("batch_coalesced_total") == before + 2
    for i in range(3):
        np.testing.assert_array_equal(results[i], solo[i])
    batch = [
        s for s in TRACER.recent(50) if s["name"] == "batch_dispatch"
    ]
    assert batch, "no batch_dispatch span for the coalesced search"
    links = [
        parse_traceparent(v).trace_id
        for s in batch
        for a, v in s["attrs"].items()
        if a.startswith("link.")
    ]
    assert {trace_ids[1], trace_ids[2]} <= set(links)


def _vector_server(n=300, d=8, seed=12):
    from dgraph_tpu.api.server import Server

    rng = np.random.default_rng(seed)
    V = rng.standard_normal((n, d)).astype(np.float32)
    s = Server()
    s.alter(
        'emb: float32vector @index(hnsw(metric:"euclidean")) .\n'
        "name: string @index(exact) ."
    )
    t = s.new_txn()
    objs = [
        {"uid": f"0x{i+1:x}", "name": f"v{i+1}", "emb": V[i].tolist()}
        for i in range(n)
    ]
    t.mutate_json(set_obj=objs, commit_now=True)
    return s, V


def test_similar_to_coalesced_golden_equivalence(monkeypatch):
    """End-to-end: concurrent similar_to queries through the server
    coalesce (batch_coalesced_total moves) and serve byte-identical
    payloads to the solo baseline, at window 0 and window on, with
    VEC_COALESCE=0 as the per-feature escape hatch."""
    from dgraph_tpu.utils.observe import METRICS

    s, V = _vector_server()
    qs = [
        "{ q(func: similar_to(emb, 3, \"%s\")) { name } }"
        % ("[" + ", ".join(f"{x:.6f}" for x in V[i]) + "]")
        for i in range(6)
    ]
    base = [json.dumps(s.query(q)["data"], sort_keys=False) for q in qs]

    # slow the index's batch search so concurrent arrivals pile up
    # behind the in-flight dispatch (the coalescing trigger)
    idx = s.vector_indexes["emb"]
    real_batch = idx.search_batch

    def slow_batch(Q, kk):
        time.sleep(0.002)
        return real_batch(Q, kk)

    monkeypatch.setattr(idx, "search_batch", slow_batch)
    monkeypatch.setenv("DGRAPH_TPU_BATCH_WINDOW_US", "20000")
    before = METRICS.value("batch_coalesced_total")
    results = []
    lock = threading.Lock()
    barrier = threading.Barrier(4)

    def worker(wid):
        barrier.wait()
        for r in range(10):
            qi = (wid + r) % len(qs)
            got = json.dumps(s.query(qs[qi])["data"], sort_keys=False)
            with lock:
                results.append((qi, got))

    ths = [threading.Thread(target=worker, args=(w,)) for w in range(4)]
    for th in ths:
        th.start()
    for th in ths:
        th.join()
    assert all(got == base[qi] for qi, got in results)
    assert METRICS.value("batch_coalesced_total") > before, (
        "no similar_to coalescing under 4-way concurrency"
    )

    # escape hatch: VEC_COALESCE=0 must keep results identical and
    # never consult the batcher for vector searches
    from dgraph_tpu.serving.microbatch import MicroBatcher

    monkeypatch.setenv("DGRAPH_TPU_VEC_COALESCE", "0")

    def boom(*a, **kw):
        raise AssertionError("read_similar engaged at VEC_COALESCE=0")

    monkeypatch.setattr(MicroBatcher, "read_similar", boom)
    assert json.dumps(s.query(qs[0])["data"], sort_keys=False) == base[0]


def test_similar_to_filtered_paths_unchanged(monkeypatch):
    """ef / distance_threshold / filtered similar_to must never route
    through the batcher (only plain top-k coalesces)."""
    from dgraph_tpu.serving.microbatch import MicroBatcher

    s, V = _vector_server(n=50)
    monkeypatch.setenv("DGRAPH_TPU_BATCH_WINDOW_US", "200")

    def boom(*a, **kw):
        raise AssertionError("filtered similar_to reached read_similar")

    monkeypatch.setattr(MicroBatcher, "read_similar", boom)
    vec = "[" + ", ".join(f"{x:.6f}" for x in V[3]) + "]"
    out = s.query(
        '{ q(func: similar_to(emb, 2, "%s", ef: 8)) { name } }' % vec
    )
    assert out["data"]["q"][0]["name"] == "v4"


# ---------------------------------------------------------------------------
# Observability: metrics + per-query profile attribution
# ---------------------------------------------------------------------------


def test_vector_metrics_and_profile(monkeypatch):
    from dgraph_tpu.utils.observe import METRICS, profile_scope

    s, V = _vector_server(n=100)
    monkeypatch.setattr(vector, "_QUANT_MIN", 1)
    before = METRICS.value("vector_search_total")
    vec = "[" + ", ".join(f"{x:.6f}" for x in V[0]) + "]"
    with profile_scope() as prof:
        s.query('{ q(func: similar_to(emb, 3, "%s")) { name } }' % vec)
    assert METRICS.value("vector_search_total") == before + 1
    vec_keys = [k for k in prof.kernel if k.startswith("vec_")]
    assert "vec_searches" in vec_keys, prof.kernel


# ---------------------------------------------------------------------------
# Mutation-lifecycle hardening (post-review regressions)
# ---------------------------------------------------------------------------


def test_empty_bulk_load_then_insert():
    """A zero-row bulk_load (an empty loader shard) leaves a (0, d)
    store; the next insert must grow from cap 0 instead of hanging."""
    idx = VectorIndex("emb")
    idx.bulk_load(
        np.zeros((0,), np.uint64), np.zeros((0, 8), np.float32)
    )
    idx.insert(1, np.ones(8, np.float32))
    q = np.ones(8, np.float32)
    assert [int(u) for u in idx.search(q, 1)] == [1]


def test_compaction_bounds_store_growth_and_stays_correct():
    """Update-heavy workload: every write is tombstone + append, so the
    host store must compact back to O(live) instead of growing with
    total writes — and answers must stay float-exact across the row
    renumbering."""
    rng = np.random.default_rng(3)
    n, d = 400, 16
    V = rng.standard_normal((n, d)).astype(np.float32)
    idx = _mk(V)
    for _ in range(6):
        for i in range(n):
            V[i] = rng.standard_normal(d).astype(np.float32)
            idx.insert(i + 1, V[i])
        # a search is the sync point that may compact
        idx.search(V[0], 3)
    assert idx._n == n, (idx._n, n)
    assert len(idx) == n
    q = V[17]
    got = [int(u) for u in idx.search(q, 5)]
    assert got == _exact_topk(V, np.arange(1, n + 1), q, 5)
    # uid identity survived the renumbering
    assert [int(u) for u in idx.search_with_uid(17 + 1, 2)][:1] != [18]


def test_ivf_maintained_below_build_threshold(monkeypatch):
    """ivf_threshold gates BUILDING only: once an index exists, rows
    inserted while live sits below the threshold must still be assigned
    to cells — before the fix they were categorically unreachable
    through the probe path until live re-crossed the threshold."""
    rng = np.random.default_rng(4)
    n, d = 300, 12
    V = rng.standard_normal((n, d)).astype(np.float32)
    idx = _mk(V, ivf_threshold=n, nlist=16, nprobe=16)
    idx._quant_view()
    assert idx._qivf is not None
    for u in range(1, 20):  # live dips below the build threshold
        idx.remove(u)
    newv = rng.standard_normal(d).astype(np.float32)
    idx.insert(1000, newv)
    view = idx._quant_view()
    assert view["ivf"] is not None
    assert view["ivf"]["assigned"] == idx._n, "fresh row left unassigned"
    # pin the probe path and assert the fresh row is actually served
    monkeypatch.setattr(
        VectorIndex, "_ivf_pick", staticmethod(lambda *a, **kw: True)
    )
    assert [int(u) for u in idx.search(newv, 1)] == [1000]


def test_filtered_search_widens_ivf_probe(monkeypatch):
    """The widening loop must widen the PROBE, not just the kept pool:
    an allowed set whose uids all live outside the query's top-nprobe
    cells is unreachable at any pool width unless the probe escalates
    (the quant analog of the jitted path's pool-scaled _probe_plan)."""
    rng = np.random.default_rng(9)
    d = 8
    A = rng.standard_normal((200, d)).astype(np.float32) * 0.05
    B = rng.standard_normal((200, d)).astype(np.float32) * 0.05 + 50.0
    V = np.vstack([A, B])
    idx = _mk(V, ivf_threshold=100, nlist=8, nprobe=1)
    idx._quant_view()
    assert idx._qivf is not None
    monkeypatch.setattr(
        VectorIndex, "_ivf_pick", staticmethod(lambda *a, **kw: True)
    )
    q = A[0]  # query sits in cluster A; only cluster-B uids allowed
    allowed = np.arange(201, 401, dtype=np.uint64)
    got = [int(u) for u in idx.search(q, 3, allowed=allowed)]
    assert got == _exact_topk(B, np.arange(201, 401), q, 3)

"""Extract the reference query-suite dataset into checked-in data files.

The reference's query tests run against a fixed fixture defined in
/root/reference/query/common_test.go (testSchema + populateCluster): a
self-contained ~700-triple graph whose golden answers appear in
query0..4_test.go et al. This script mechanically extracts that fixture —
the schema string, every addTriplesToCluster block, the geo helper calls,
and the regex-pattern loop — into:

    tests/ref_golden/schema.txt   (DQL schema, verbatim)
    tests/ref_golden/triples.rdf  (N-Quads, verbatim + synthesized geo/regex)

Run from the repo root:  python tests/ref_golden/extract_fixture.py
Both outputs are checked in so the conformance suite is self-contained.
"""

import os
import re

REF = "/root/reference/query/common_test.go"
OUT_DIR = os.path.dirname(os.path.abspath(__file__))


def main():
    src = open(REF, encoding="utf-8").read()

    # -- schema ---------------------------------------------------------------
    m = re.search(r"var testSchema = `(.*?)`", src, re.S)
    schema = m.group(1)

    # -- raw triples blocks ---------------------------------------------------
    blocks = re.findall(r"addTriplesToCluster\(`(.*?)`\)", src, re.S)
    # skip the per-pattern loop template (contains %d/%s placeholders)
    blocks = [b for b in blocks if "%d" not in b]

    out = []
    for b in blocks:
        out.append(b)

    # -- geo helpers (addGeoPointToCluster etc.) ------------------------------
    for mm in re.finditer(
        r'addGeoPointToCluster\((\d+),\s*"(\w+)",\s*\[\]float64\{([^}]*)\}\)', src
    ):
        uid, pred, coords = mm.group(1), mm.group(2), mm.group(3)
        out.append(
            f"<{uid}> <{pred}> \"{{'type':'Point', 'coordinates':[{coords}]}}\"^^<geo:geojson> ."
        )

    def fmt_ring(ring_src):
        pts = re.findall(r"\{([-\d.]+),\s*([-\d.]+)\}", ring_src)
        return "[" + ",".join(f"[{x}, {y}]" for x, y in pts) + "]"

    for mm in re.finditer(
        r'addGeoPolygonToCluster\((\d+),\s*"(\w+)",\s*\[\]\[\]\[\]float64\{\s*\{(.*?)\}\s*,?\s*\}\)\)',
        src,
        re.S,
    ):
        uid, pred, body = mm.group(1), mm.group(2), mm.group(3)
        coords = "[" + fmt_ring(body) + "]"
        out.append(
            f"<{uid}> <{pred}> \"{{'type':'Polygon', 'coordinates': {coords}}}\"^^<geo:geojson> ."
        )

    mm = re.search(
        r"addGeoMultiPolygonToCluster\((\d+),\s*\[\]\[\]\[\]\[\]float64\{(.*?)\}\)\)\s*\n",
        src,
        re.S,
    )
    if mm:
        uid, body = mm.group(1), mm.group(2)
        polys = []
        for poly_src in re.findall(r"\{\{\{(.*?)\}\}\}", src[mm.start() : mm.end()], re.S):
            polys.append("[" + fmt_ring(poly_src) + "]")
        coords = "[" + ",".join(polys) + "]"
        out.append(
            f"<{uid}> <geometry> \"{{'type':'MultiPolygon', 'coordinates': {coords}}}\"^^<geo:geojson> ."
        )

    # -- regex pattern loop ---------------------------------------------------
    mm = re.search(r"patterns := \[\]string\{(.*?)\}", src, re.S)
    patterns = re.findall(r'"([^"]+)"', mm.group(1))
    next_id = 0x2000
    for p in patterns:
        out.append(f'<{next_id}> <value> "{p}" .')
        out.append(f"<0x1234> <pattern> <{next_id}> .")
        next_id += 1

    # -- facets fixture (query_facets_test.go populateClusterWithFacets) ------
    fsrc = open(
        "/root/reference/query/query_facets_test.go", encoding="utf-8"
    ).read()
    mfn = re.search(
        r"func populateClusterWithFacets\(\) error \{(.*?)\n\}", fsrc, re.S
    )
    body = mfn.group(1)
    fout = []
    mm = re.search(r"triples := `(.*?)`", body, re.S)
    fout.append(mm.group(1))
    # fmt.Sprintf expansion: resolve `name := "(...)"` vars then templates
    fvars = {
        m.group(1): m.group(2).replace('\\"', '"')
        for m in re.finditer(r"(\w+) := \"(\(.*?\))\"", body)
    }
    for m in re.finditer(
        r'triples \+= fmt\.Sprintf\("(.*?)(?:\\n)?",\s*(\w+)\)', body
    ):
        tmpl, var = m.group(1), m.group(2)
        fout.append(
            tmpl.replace("%s", fvars[var]).replace('\\"', '"')
        )
    with open(
        os.path.join(OUT_DIR, "triples_facets.rdf"), "w", encoding="utf-8"
    ) as f:
        f.write("\n".join(fout) + "\n")

    with open(os.path.join(OUT_DIR, "schema.txt"), "w", encoding="utf-8") as f:
        f.write(schema.strip() + "\n")
    with open(os.path.join(OUT_DIR, "triples.rdf"), "w", encoding="utf-8") as f:
        f.write("\n".join(out) + "\n")
    n = sum(
        1
        for ln in "\n".join(out).splitlines()
        if ln.strip() and not ln.strip().startswith("#")
    )
    print(f"schema.txt + triples.rdf written ({n} triples)")


if __name__ == "__main__":
    main()

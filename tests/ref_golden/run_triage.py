"""Triage driver: run every extracted golden case, bucket failures.

Usage: python tests/ref_golden/run_triage.py [substr-filter]
"""

import json
import os
import sys
import traceback

sys.path.insert(0, "/root/repo")

HERE = os.path.dirname(os.path.abspath(__file__))


def canon(x):
    """JSONEq semantics: exact structure incl. array order; Go unmarshals all
    numbers to float64, so normalize ints to floats."""
    if isinstance(x, dict):
        return {k: canon(v) for k, v in x.items()}
    if isinstance(x, list):
        return [canon(v) for v in x]
    if isinstance(x, bool):
        return x
    if isinstance(x, (int, float)):
        return float(x)
    return x


def canon_unordered(x):
    if isinstance(x, dict):
        return {k: canon_unordered(v) for k, v in x.items()}
    if isinstance(x, list):
        return sorted(
            (canon_unordered(v) for v in x),
            key=lambda v: json.dumps(v, sort_keys=True, default=str),
        )
    if isinstance(x, bool):
        return x
    if isinstance(x, (int, float)):
        return float(x)
    return x


def build_server(facets=False):
    from dgraph_tpu.api.server import Server

    s = Server()
    s.alter(open(os.path.join(HERE, "schema.txt")).read())
    t = s.new_txn()
    t.mutate_rdf(
        set_rdf=open(os.path.join(HERE, "triples.rdf")).read(), commit_now=True
    )
    if facets:
        # query_facets_test.go cases run with populateClusterWithFacets
        # applied on top of the base fixture
        t = s.new_txn()
        t.mutate_rdf(
            set_rdf=open(os.path.join(HERE, "triples_facets.rdf")).read(),
            commit_now=True,
        )
    return s


def main():
    filt = sys.argv[1] if len(sys.argv) > 1 else ""
    cases = json.load(open(os.path.join(HERE, "cases.json")))
    if filt:
        cases = [c for c in cases if filt in c["id"]]
    s = build_server()
    sf = build_server(facets=True)
    ok = okuo = 0
    errors, wrong = [], []
    for c in cases:
        eng = sf if c["file"] == "query_facets_test.go" else s
        try:
            got = {"data": eng.query(c["query"])["data"]}
        except Exception as e:
            errors.append((c["id"], f"{type(e).__name__}: {e}"))
            continue
        try:
            want = json.loads(c["expected"])
        except Exception:
            errors.append((c["id"], "unparseable expected"))
            continue
        if canon(got) == canon(want):
            ok += 1
        elif canon_unordered(got) == canon_unordered(want):
            okuo += 1
            wrong.append((c["id"], "ORDER-ONLY", None, None))
        else:
            wrong.append(
                (
                    c["id"],
                    "VALUE",
                    json.dumps(want, default=str)[:200],
                    json.dumps(got, default=str)[:200],
                )
            )
    print(f"\n=== {ok} exact, {okuo} order-only, "
          f"{len(wrong)-okuo} wrong, {len(errors)} errors / {len(cases)}")
    with open("/tmp/golden_triage.json", "w") as f:
        json.dump({"errors": errors, "wrong": wrong}, f, indent=1, default=str)
    from collections import Counter

    print("\n-- error types --")
    for msg, cnt in Counter(e[1].split(":")[0] for e in errors).most_common():
        print(f"  {cnt:4d}  {msg}")
    print("\n-- first errors --")
    for eid, msg in errors[:15]:
        print(f"  {eid}: {msg[:140]}")
    print("\n-- first wrong --")
    for w in wrong[:10]:
        print(f"  {w[0]} [{w[1]}]")
        if w[2]:
            print(f"    want: {w[2]}")
            print(f"    got : {w[3]}")


if __name__ == "__main__":
    main()

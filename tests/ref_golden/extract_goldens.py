"""Extract golden (query, expected-JSON) cases from the reference query suites.

The reference asserts ~600 golden answers over the common_test.go fixture in
/root/reference/query/query{0..4}_test.go, query_facets_test.go, etc., in a
mechanical shape:

    query := `...`
    js := processQueryNoErr(t, query)
    require.JSONEq(t, `{"data": {...}}`, js)

This script walks those files and extracts every such triple into
tests/ref_golden/cases.json. Functions that mutate shared cluster state
(addTriplesToCluster / setSchema / dropPredicate / deleteTriplesInCluster)
are excluded — their goldens depend on in-test mutations, not the fixture.
Sprintf-built queries and var-based queries are skipped (not extractable
statically).

Run from the repo root:  python tests/ref_golden/extract_goldens.py
cases.json is checked in so the conformance suite is self-contained.
"""

import json
import os
import re

REF_DIR = "/root/reference/query"
FILES = [
    "query0_test.go",
    "query1_test.go",
    "query2_test.go",
    "query3_test.go",
    "query4_test.go",
    "query_facets_test.go",
    "math_test.go",
]
OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "cases.json")

MUTATORS = (
    "addTriplesToCluster",
    "deleteTriplesInCluster",
    "setSchema",
    "dropPredicate",
    "addGeoPointToCluster",
    "addGeoPolygonToCluster",
    "client.Alter",
    "txn.Mutate",
)


def split_functions(src):
    """Yield (name, body) for each top-level test func."""
    for m in re.finditer(r"func (Test\w+)\(t \*testing\.T\) \{", src):
        start = m.end()
        depth = 1
        i = start
        in_raw = False
        in_str = False
        while i < len(src) and depth:
            c = src[i]
            if in_raw:
                if c == "`":
                    in_raw = False
            elif in_str:
                if c == "\\":
                    i += 1
                elif c == '"':
                    in_str = False
            elif c == "`":
                in_raw = True
            elif c == '"':
                in_str = True
            elif c == "{":
                depth += 1
            elif c == "}":
                depth -= 1
            i += 1
        yield m.group(1), src[start : i - 1]


# one statement shapes we recognize (raw strings only — Sprintf etc. skipped)
RE_ASSIGN = re.compile(r"(\w+)\s*:?=\s*`", re.S)
RE_EXEC = re.compile(r"(\w+)\s*:?=\s*processQueryNoErr\(t,\s*(\w+)\)")
RE_JSONEQ = re.compile(r"require\.JSONEq\(t,\s*", re.S)


def read_raw(src, i):
    """src[i] == '`' — return (string content, index after closing tick)."""
    j = src.index("`", i + 1)
    return src[i + 1 : j], j + 1


def extract_from_body(name, body, fname):
    cases = []
    svars = {}  # var name -> raw string value
    jsvars = {}  # js var name -> query text it holds results of
    i = 0
    n = len(body)
    k = 0
    while i < n:
        # next interesting token
        m_assign = RE_ASSIGN.search(body, i)
        m_exec = RE_EXEC.search(body, i)
        m_eq = RE_JSONEQ.search(body, i)
        starts = [
            (m.start(), kind, m)
            for kind, m in (("assign", m_assign), ("exec", m_exec), ("eq", m_eq))
            if m
        ]
        if not starts:
            break
        starts.sort()
        _, kind, m = starts[0]
        if kind == "assign":
            raw, after = read_raw(body, body.index("`", m.start()))
            svars[m.group(1)] = raw
            i = after
        elif kind == "exec":
            jsvars[m.group(1)] = svars.get(m.group(2))
            i = m.end()
        else:  # require.JSONEq(t, <expected>, <jsvar>)
            j = m.end()
            # expected: raw string, quoted string, or a var naming one
            if body[j] == "`":
                expected, after = read_raw(body, j)
            elif body[j] == '"':
                # quoted Go string — decode escapes via json tricks
                mm = re.match(r'"((?:[^"\\]|\\.)*)"', body[j:])
                if not mm:
                    i = j
                    continue
                expected = json.loads('"' + mm.group(1) + '"')
                after = j + mm.end()
            else:
                mm = re.match(r"(\w+)", body[j:])
                expected = svars.get(mm.group(1)) if mm else None
                after = j + (mm.end() if mm else 0)
            if expected is None:
                i = after
                continue
            # the actual arg after expected
            mm = re.match(r"\s*,\s*(\w+)\s*\)", body[after:])
            i = after
            if not mm:
                continue
            qtext = jsvars.get(mm.group(1))
            if qtext is None:
                continue
            # Go-side string concatenation (query := `...` + poly + `...`)
            # leaves an unbalanced fragment — not statically extractable
            if qtext.count("{") != qtext.count("}"):
                continue
            # fully commented-out test bodies leave junk goldens
            try:
                json.loads(expected)
            except ValueError:
                continue
            stripped = re.sub(r"//[^\n]*", "", qtext)
            if stripped.count("{") != stripped.count("}"):
                continue
            cases.append(
                {
                    "id": f"{name}/{k}",
                    "file": fname,
                    "query": qtext,
                    "expected": expected,
                }
            )
            k += 1
    return cases


def main():
    all_cases = []
    skipped_mutating = 0
    for fname in FILES:
        src = open(os.path.join(REF_DIR, fname), encoding="utf-8").read()
        for name, body in split_functions(src):
            if any(mu in body for mu in MUTATORS):
                skipped_mutating += 1
                continue
            all_cases.extend(extract_from_body(name, body, fname))
    with open(OUT, "w", encoding="utf-8") as f:
        json.dump(all_cases, f, indent=1)
    print(f"{len(all_cases)} cases extracted; {skipped_mutating} mutating funcs skipped")


if __name__ == "__main__":
    main()

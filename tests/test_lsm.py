"""LsmKV: spill-to-disk storage engine (VERDICT r1 missing #9; ref
BadgerDB's role at worker/server_state.go:95).
"""

import numpy as np
import pytest

from dgraph_tpu.storage.kv import MemKV
from dgraph_tpu.storage.lsm import LsmKV


def test_basic_mvcc_roundtrip(tmp_path):
    kv = LsmKV(str(tmp_path / "l"))
    kv.put(b"a", 5, b"v5")
    kv.put(b"a", 9, b"v9")
    kv.put(b"b", 3, b"w")
    assert kv.get(b"a", 4) is None
    assert kv.get(b"a", 5) == (5, b"v5")
    assert kv.get(b"a", 100) == (9, b"v9")
    assert kv.versions(b"a", 100) == [(9, b"v9"), (5, b"v5")]
    assert [k for k, _, _ in kv.iterate(b"", 100)] == [b"a", b"b"]
    kv.close()


def test_flush_and_reopen(tmp_path):
    d = str(tmp_path / "l")
    kv = LsmKV(d)
    for i in range(100):
        kv.put(b"k%03d" % i, i + 1, b"v%d" % i)
    kv.flush()
    kv.put(b"late", 500, b"mem-only")
    kv.close()
    kv2 = LsmKV(d)
    assert kv2.get(b"k042", 1000) == (43, b"v42")
    assert kv2.get(b"late", 1000) == (500, b"mem-only")  # WAL replay
    assert len(list(kv2.iterate(b"k", 1000))) == 100
    kv2.close()


def test_spill_under_small_memtable(tmp_path):
    kv = LsmKV(str(tmp_path / "l"), memtable_bytes=2048)
    for i in range(500):
        kv.put(b"key%05d" % i, i + 1, b"x" * 50)
    assert len(kv._tables) >= 1  # spilled
    assert kv._mem_size < 500 * 74  # memory bounded
    for i in (0, 123, 499):
        assert kv.get(b"key%05d" % i, 1 << 40) == (i + 1, b"x" * 50)
    kv.close()


def test_drop_prefix_across_flush(tmp_path):
    kv = LsmKV(str(tmp_path / "l"))
    kv.put(b"p/a", 1, b"1")
    kv.put(b"p/b", 2, b"2")
    kv.put(b"q/c", 3, b"3")
    kv.flush()
    kv.drop_prefix(b"p/")
    assert kv.get(b"p/a", 100) is None
    assert kv.get(b"q/c", 100) == (3, b"3")
    # a write AFTER the drop is visible
    kv.put(b"p/a", 10, b"new")
    assert kv.get(b"p/a", 100) == (10, b"new")
    kv.compact()
    assert kv.get(b"p/a", 100) == (10, b"new")
    assert kv.get(b"p/b", 100) is None
    kv.close()


def test_delete_below_gc(tmp_path):
    kv = LsmKV(str(tmp_path / "l"))
    for ts in (1, 5, 9):
        kv.put(b"k", ts, b"v%d" % ts)
    kv.flush()
    kv.delete_below(b"k", 9)
    assert kv.versions(b"k", 100) == [(9, b"v9")]
    kv.compact()
    assert kv.versions(b"k", 100) == [(9, b"v9")]
    kv.close()


def test_compaction_collapses_tables(tmp_path):
    kv = LsmKV(str(tmp_path / "l"), memtable_bytes=512, compact_at=3)
    for i in range(400):
        kv.put(b"c%04d" % i, i + 1, b"y" * 20)
    kv.flush()
    assert len(kv._tables) < 3  # auto-compaction kept the count bounded
    assert kv.get(b"c0000", 1 << 40) == (1, b"y" * 20)
    assert kv.get(b"c0399", 1 << 40) == (400, b"y" * 20)
    kv.close()


def test_parity_with_memkv_random_ops(tmp_path):
    rng = np.random.default_rng(0)
    lsm = LsmKV(str(tmp_path / "l"), memtable_bytes=1024)
    mem = MemKV()
    keys = [b"k%d" % i for i in range(30)]
    ts = 0
    for _ in range(600):
        ts += 1
        op = rng.integers(0, 10)
        k = keys[int(rng.integers(0, len(keys)))]
        if op < 8:
            v = b"v%d" % ts
            lsm.put(k, ts, v)
            mem.put(k, ts, v)
        elif op == 8:
            lsm.delete_below(k, max(1, ts - 20))
            mem.delete_below(k, max(1, ts - 20))
        else:
            lsm.flush()
    for k in keys:
        assert lsm.versions(k, ts) == mem.versions(k, ts), k
    got = [(k, t, v) for k, t, v in lsm.iterate(b"k", ts)]
    want = [(k, t, v) for k, t, v in mem.iterate(b"k", ts)]
    assert got == want
    lsm.close()


def test_engine_runs_on_lsm(tmp_path, monkeypatch):
    monkeypatch.setenv("DGRAPH_TPU_STORAGE", "lsm")
    from dgraph_tpu.api.server import Server

    s = Server(data_dir=str(tmp_path / "p"))
    s.alter("name: string @index(exact) .\nfriend: [uid] .")
    t = s.new_txn()
    t.mutate_rdf(
        set_rdf='<0x1> <name> "lsm-alice" .\n<0x1> <friend> <0x2> .\n'
        '<0x2> <name> "lsm-bob" .',
        commit_now=True,
    )
    out = s.query('{ q(func: eq(name, "lsm-alice")) { name friend { name } } }')
    assert out["data"]["q"][0]["friend"][0]["name"] == "lsm-bob"
    s.kv.close()
    # restart from disk
    s2 = Server(data_dir=str(tmp_path / "p"))
    out = s2.query('{ q(func: eq(name, "lsm-alice")) { name } }')
    assert out["data"]["q"][0]["name"] == "lsm-alice"
    s2.kv.close()


def test_compaction_same_ts_newest_seq_wins(tmp_path):
    """ADVICE r2 (high): rollup rewrites a key at the SAME ts as the latest
    version; compaction must keep the newest seq for a (key, ts) group, like
    the read path, or the rollup silently reverts to the pre-rollup value."""
    kv = LsmKV(str(tmp_path / "l"))
    kv.put(b"k", 5, b"old")
    kv.flush()
    kv.put(b"k", 5, b"ROLLUP")
    kv.compact()
    assert kv.get(b"k", 100) == (5, b"ROLLUP")
    # and it survives reopen
    kv.close()
    kv2 = LsmKV(str(tmp_path / "l"))
    assert kv2.get(b"k", 100) == (5, b"ROLLUP")
    kv2.close()


def test_iterate_survives_concurrent_compaction(tmp_path):
    """ADVICE r2 (medium): a live single-table iterator must not crash when
    a concurrent flush+compact unlinks the table it is scanning."""
    kv = LsmKV(str(tmp_path / "l"), compact_at=2)
    for i in range(500):
        kv.put(b"k%04d" % i, 1, b"v%d" % i)
    kv.compact()  # single table, no memtable: iterate takes the fast path
    it = kv.iterate(b"k", 10)
    got = [next(it) for _ in range(10)]  # iterator now mid-table
    # trigger flush + compaction, which closes+unlinks the old table
    for i in range(500):
        kv.put(b"j%04d" % i, 2, b"w%d" % i)
    kv.flush()
    kv.compact()
    rest = list(it)  # must finish cleanly on the retained mmap
    assert len(got) + len(rest) == 500
    assert rest[-1][0] == b"k0499"
    kv.close()

"""Crash-consistent online ops plane: backup/restore + replicated CDC.

Layers:
  - pure/unit: manifest-chain gap/overlap detection; torn-backup-file
    rejection at every record boundary (test_wal_crash.py-style) plus
    bit-flip CRC coverage; legacy v1 truncation detection.
  - single-engine: chunked v2 backup/restore roundtrips, incremental
    chains, until= cuts.
  - distributed: the journaled backup coordinator crash-tested at
    EVERY journaled boundary (backup.begin/group/manifest) while the
    bank workload runs and a tablet move is in flight — restore must
    be ledger-exact (0 lost / 0 duplicated edges); resume and abort;
    online restore with watermark visibility + idempotent re-run.
  - CDC: strict commit-ts ordering across group-commit batches, the
    rfc3339 datetime golden (round-trips through the RDF parser),
    sink-failure retry + bounded-queue backpressure, sink crash +
    coordinator failover healed by replay-from-checkpoint, and the
    apply-equivalence gate: replaying the event stream into a fresh
    engine reproduces identical query results.
"""

import gzip
import hashlib
import json
import os
import threading
import time

import pytest

from dgraph_tpu.admin import backup as bk
from dgraph_tpu.admin.backup import (
    BackupWriter,
    ManifestChainError,
    TornBackupError,
    backup,
    backup_engine,
    restore,
    restore_to_cluster,
)
from dgraph_tpu.admin.cdc import CDC, events_for
from dgraph_tpu.api.server import Server
from dgraph_tpu.conn import faults
from dgraph_tpu.conn.faults import FaultPlan, InjectedCrash
from dgraph_tpu.conn.retry import RetryPolicy, retrying_call
from dgraph_tpu.utils.observe import METRICS
from dgraph_tpu.worker.backupdriver import BackupCoordinator
from dgraph_tpu.worker.groups import DistributedCluster
from dgraph_tpu.worker.tabletmove import TabletFencedError

SCHEMA = "name: string @index(exact) .\nage: int .\nfriend: [uid] ."


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _seed_server(n=12):
    s = Server()
    s.alter(SCHEMA)
    rdf = [f'<0x{i:x}> <name> "n{i}" .' for i in range(1, n + 1)]
    rdf += [f'<0x{i:x}> <age> "{i}"^^<xs:int> .' for i in range(1, n + 1)]
    s.new_txn().mutate_rdf(set_rdf="\n".join(rdf), commit_now=True)
    return s


# ---------------------------------------------------------------------------
# manifest chain validation
# ---------------------------------------------------------------------------


def _entry(since, read_ts, **kw):
    return dict(
        since=since, read_ts=read_ts, records=1,
        type="full" if since == 0 else "incremental", files=[], **kw,
    )


def test_manifest_chain_gap_overlap_detection():
    ok = {"backups": [_entry(0, 10), _entry(10, 20), _entry(20, 30)]}
    assert len(bk.validate_chain(ok)) == 3
    # a later full backup restarts the chain; restore replays from it
    refull = {"backups": [_entry(0, 10), _entry(0, 25), _entry(25, 30)]}
    got = bk.validate_chain(refull)
    assert [e["since"] for e in got] == [0, 25]
    with pytest.raises(ManifestChainError, match="gap"):
        bk.validate_chain({"backups": [_entry(0, 10), _entry(15, 20)]})
    with pytest.raises(ManifestChainError, match="overlap"):
        bk.validate_chain({"backups": [_entry(0, 10), _entry(5, 20)]})
    with pytest.raises(ManifestChainError, match="incremental"):
        bk.validate_chain({"backups": [_entry(5, 10)]})
    with pytest.raises(ManifestChainError, match="inverted"):
        bk.validate_chain({"backups": [_entry(0, 10), _entry(10, 10)]})


def test_restore_refuses_gapped_chain(tmp_path):
    bdir = str(tmp_path / "b")
    s = _seed_server()
    backup(s, bdir)
    s.new_txn().mutate_rdf(set_rdf='<0x40> <name> "x" .', commit_now=True)
    backup(s, bdir)
    man = bk.load_manifest(bdir)
    man["backups"][1]["since"] += 3  # tear a hole in the chain
    bk.save_manifest(bdir, man)
    with pytest.raises(ManifestChainError):
        restore(Server(), bdir)


# ---------------------------------------------------------------------------
# torn/corrupt backup files
# ---------------------------------------------------------------------------


def _record_offsets(payload: bytes):
    offsets, pos = [], 0
    while pos < len(payload):
        klen, _ts, vlen, _crc = bk._REC2.unpack_from(payload, pos)
        offsets.append(pos)
        pos += bk._REC2.size + klen + vlen
    assert pos == len(payload)
    return offsets


def test_torn_backup_file_rejected_at_every_record_boundary(tmp_path):
    """Truncate the chunk file's payload at every record boundary AND
    every byte of the last record: restore must refuse each cut as a
    torn backup, never replay it as a silent hole."""
    bdir = str(tmp_path / "b")
    s = _seed_server(n=6)
    entry = backup(s, bdir)
    assert entry["files"], entry
    fmeta = entry["files"][0]
    path = os.path.join(bdir, fmeta["name"])
    payload = gzip.decompress(open(path, "rb").read())
    offsets = _record_offsets(payload)
    assert len(offsets) >= 3
    cuts = offsets[1:] + list(range(offsets[-1] + 1, len(payload)))
    for cut in cuts:
        with open(path, "wb") as f:
            f.write(gzip.compress(payload[:cut]))
        with pytest.raises(TornBackupError):
            list(bk.iter_file_records(bdir, fmeta))
        with pytest.raises(TornBackupError):
            restore(Server(), bdir)
    # a flipped bit inside a record body trips the per-record CRC even
    # when the length structure stays intact
    flipped = bytearray(payload)
    flipped[offsets[1] + bk._REC2.size + 2] ^= 0x40
    with open(path, "wb") as f:
        f.write(gzip.compress(bytes(flipped)))
    with pytest.raises(TornBackupError):
        restore(Server(), bdir)
    # raw garbage (not even gzip) is refused, not crashed on
    with open(path, "wb") as f:
        f.write(b"\x00garbage")
    with pytest.raises(TornBackupError):
        restore(Server(), bdir)
    # the pristine payload restores fine (control)
    with open(path, "wb") as f:
        f.write(gzip.compress(payload))
    assert restore(Server(), bdir) == entry["records"]


def test_legacy_v1_entry_restores_and_detects_truncation(tmp_path):
    bdir = str(tmp_path / "legacy")
    os.makedirs(bdir)
    s = _seed_server(n=4)
    # hand-write a v1 single-file backup (pre-CRC format)
    recs = []
    n = 0
    for key, vers in s.kv.iterate_versions(b"", 1 << 62):
        for ts, val in vers:
            recs.append(bk._REC.pack(len(key), ts, len(val)) + key + val)
            n += 1
    blob = b"".join(recs)
    with gzip.open(os.path.join(bdir, "backup-0001-0-9.gz"), "wb") as f:
        f.write(blob)
    bk.save_manifest(bdir, {"backups": [{
        "path": "backup-0001-0-9.gz", "since": 0,
        "read_ts": s.zero.max_assigned, "records": n, "type": "full",
    }]})
    s2 = Server()
    assert restore(s2, bdir) == n
    assert len(s2.query('{ q(func: has(name)) { uid } }')["data"]["q"]) == 4
    # truncated legacy file: record-count verification refuses it
    with gzip.open(os.path.join(bdir, "backup-0001-0-9.gz"), "wb") as f:
        f.write(blob[: len(blob) // 2])
    with pytest.raises(TornBackupError):
        restore(Server(), bdir)


def test_uncommitted_chunk_files_are_invisible(tmp_path):
    """Files the manifest never names (a crashed coordinator's
    partials) are ignored by restore — a torn backup is detectably
    incomplete, never silently short OR long."""
    bdir = str(tmp_path / "b")
    s = _seed_server(n=3)
    entry = backup(s, bdir)
    stray = BackupWriter(bdir, 99, 0, 1 << 20)
    stray.add(b"\x00junkkey", 999999, b"junkval")
    stray.finish()
    s2 = Server()
    assert restore(s2, bdir) == entry["records"]
    assert s2.kv.get(b"\x00junkkey", 1 << 62) is None


# ---------------------------------------------------------------------------
# single-engine roundtrips
# ---------------------------------------------------------------------------


def test_chunked_backup_roundtrip_and_until(tmp_path, monkeypatch):
    monkeypatch.setenv("DGRAPH_TPU_BACKUP_CHUNK_BYTES", "1")  # floor: 64KiB
    bdir = str(tmp_path / "b")
    s = _seed_server(n=10)
    e1 = backup(s, bdir)
    assert e1["type"] == "full" and len(e1["files"]) >= 1
    cut_ts = s.zero.max_assigned
    s.new_txn().mutate_rdf(set_rdf='<0x60> <name> "late" .', commit_now=True)
    e2 = backup(s, bdir)
    assert e2["type"] == "incremental" and e2["since"] == e1["read_ts"]
    s2 = Server()
    restore(s2, bdir)
    q = '{ q(func: has(name), orderasc: name) { name } }'
    assert s2.query(q)["data"] == s.query(q)["data"]
    # until= cuts inside the chain: the late write is excluded
    s3 = Server()
    restore(s3, bdir, until=cut_ts)
    assert s3.query('{ q(func: eq(name, "late")) { uid } }')["data"]["q"] == []
    assert len(s3.query('{ q(func: has(name)) { uid } }')["data"]["q"]) == 10


# ---------------------------------------------------------------------------
# distributed coordinator: crash at every journaled boundary under load
# ---------------------------------------------------------------------------

N_ACCOUNTS = 6
START_BAL = 100
BACKUP_CRASH_POINTS = ("backup.begin", "backup.group", "backup.manifest")


def _seed_bank(c):
    c.alter(
        "bal: int @upsert .\nacct: string @index(exact) @upsert .\n"
        "pad: string ."
    )
    rdf = []
    for i in range(1, N_ACCOUNTS + 1):
        rdf.append(f'<0x{i:x}> <acct> "a{i}" .')
        rdf.append(f'<0x{i:x}> <bal> "{START_BAL}"^^<xs:int> .')
    # a second, padded tablet so moves/backups have real bytes to chew
    rdf += [f'<0x{0x100 + i:x}> <pad> "p{i}{"x" * 64}" .' for i in range(48)]
    c.new_txn().mutate_rdf(set_rdf="\n".join(rdf), commit_now=True)


def _bank_writer(c, stop, ledger, lock, stats):
    import numpy as np

    rng = np.random.default_rng(42)
    while not stop.is_set():
        frm, to = (
            int(x) + 1 for x in rng.choice(N_ACCOUNTS, 2, replace=False)
        )
        amt = int(rng.integers(1, 10))
        with lock:
            rdf = (
                f'<0x{frm:x}> <bal> "{ledger[frm] - amt}"^^<xs:int> .\n'
                f'<0x{to:x}> <bal> "{ledger[to] + amt}"^^<xs:int> .'
            )
        try:
            retrying_call(
                lambda: c.new_txn().mutate_rdf(set_rdf=rdf, commit_now=True),
                policy=RetryPolicy(base=0.02, cap=0.2, max_attempts=60),
                retryable=(TabletFencedError,),
            )
            with lock:
                ledger[frm] -= amt
                ledger[to] += amt
                stats["ok"] += 1
        except Exception:
            with lock:
                stats["ambiguous"] += 1
        time.sleep(0.005)


@pytest.mark.chaos
def test_backup_crash_every_boundary_under_bank_and_move(
    tmp_path, monkeypatch
):
    """The acceptance scenario: the bank workload runs, a tablet move
    is in flight, and the backup coordinator is crashed at EVERY
    journaled boundary. Each resumed backup restores to a LEDGER-EXACT
    state: balances sum to exactly N*START (transfers conserve the sum
    at every commit, so any complete snapshot does too), every account
    exists exactly once (0 lost / 0 duplicated edges)."""
    monkeypatch.setenv("DGRAPH_TPU_MOVE_CHUNK_BYTES", "1024")
    c = DistributedCluster(n_groups=2, replicas=1, pump_ms=2)
    stop = threading.Event()
    lock = threading.Lock()
    ledger = {i: START_BAL for i in range(1, N_ACCOUNTS + 1)}
    stats = {"ok": 0, "ambiguous": 0}
    writer = threading.Thread(
        target=_bank_writer, args=(c, stop, ledger, lock, stats)
    )
    try:
        _seed_bank(c)
        writer.start()
        for round_, point in enumerate(BACKUP_CRASH_POINTS):
            bdir = str(tmp_path / f"bk_{round_}")
            # a tablet move in flight while the backup runs: stretch
            # its chunk flushes so it overlaps the capture window
            src = c.zero.belongs_to("pad")
            dst = 2 if src == 1 else 1
            faults.install(FaultPlan(seed=3, rules=[
                dict(point="move.chunk", action="delay", p=1.0,
                     delay_ms=10),
                dict(point=point, action="crash", p=1.0, max=1),
            ]))
            mv_done = threading.Event()

            def run_move():
                try:
                    c.move_tablet("pad", dst)
                finally:
                    mv_done.set()

            mv = threading.Thread(target=run_move)
            mv.start()
            with pytest.raises(InjectedCrash):
                BackupCoordinator(c, bdir).backup()
            mv.join(timeout=30)
            faults.reset()
            entry = BackupCoordinator(c, bdir).resume()
            assert entry is not None, point
            # a fresh journal has nothing pending after the resume
            assert BackupCoordinator(c, bdir).resume() is None, point

            s2 = Server()
            restore(s2, bdir)
            out = s2.query("{ q(func: has(bal)) { uid bal } }")["data"]["q"]
            bals = {int(x["uid"], 16): x["bal"] for x in out}
            assert len(bals) == N_ACCOUNTS, (point, bals)  # 0 lost/dup
            assert sum(bals.values()) == N_ACCOUNTS * START_BAL, (
                point, bals,
            )  # ledger-exact
            pads = s2.query("{ q(func: has(pad)) { uid } }")["data"]["q"]
            assert len(pads) == 48, (point, len(pads))  # exactly once
        assert METRICS.value("backup_resumed_total") >= len(
            BACKUP_CRASH_POINTS
        )
        stop.set()
        writer.join(timeout=30)
        assert stats["ok"] > 0, stats
        # final live state is itself ledger-exact (the workload's own
        # invariant — the backups above snapshotted consistent cuts)
        out = c.query("{ q(func: has(bal)) { uid bal } }")["data"]["q"]
        assert sum(x["bal"] for x in out) == N_ACCOUNTS * START_BAL
        if stats["ambiguous"] == 0:
            with lock:
                want = dict(ledger)
            assert {int(x["uid"], 16): x["bal"] for x in out} == want
    finally:
        stop.set()
        faults.reset()
        if writer.is_alive():
            writer.join(timeout=30)
        c.close()


def test_backup_waits_out_in_flight_move(monkeypatch, tmp_path):
    """A predicate mid-move is drained, not captured mid-fence: the
    backup still lands exactly one copy of every edge."""
    monkeypatch.setenv("DGRAPH_TPU_MOVE_CHUNK_BYTES", "1024")
    c = DistributedCluster(n_groups=2, replicas=1, pump_ms=2)
    try:
        _seed_bank(c)
        src = c.zero.belongs_to("pad")
        dst = 2 if src == 1 else 1
        faults.install(FaultPlan(seed=3, rules=[
            dict(point="move.chunk", action="delay", p=1.0, delay_ms=15),
        ]))
        waited0 = METRICS.value("backup_moves_waited_total")
        done = threading.Event()

        def run_move():
            try:
                c.move_tablet("pad", dst)
            finally:
                done.set()

        th = threading.Thread(target=run_move)
        th.start()
        time.sleep(0.05)  # let the move enter its chunked copy
        bdir = str(tmp_path / "bk")
        entry = BackupCoordinator(c, bdir).backup()
        th.join(timeout=30)
        faults.reset()
        assert done.is_set()
        s2 = Server()
        restore(s2, bdir)
        pads = s2.query("{ q(func: has(pad)) { uid } }")["data"]["q"]
        assert len(pads) == 48
        assert (
            METRICS.value("backup_moves_waited_total") > waited0
            or entry["records"] > 0
        )
    finally:
        faults.reset()
        c.close()


def test_backup_after_crash_finishes_pending_then_takes_fresh(tmp_path):
    """backup() over a crashed journal finishes the stale snapshot
    (chain stays gapless) AND then takes the backup the caller asked
    for as a fresh snapshot — writes committed after the crash land in
    the new entry, not silently outside any backup."""
    c = DistributedCluster(n_groups=2, replicas=1, pump_ms=2)
    try:
        _seed_bank(c)
        bdir = str(tmp_path / "bk")
        faults.install(FaultPlan(seed=7, rules=[
            dict(point="backup.group", action="crash", p=1.0, max=1),
        ]))
        with pytest.raises(InjectedCrash):
            BackupCoordinator(c, bdir).backup()
        faults.reset()
        # commits after the crash, before the operator retries
        c.new_txn().mutate_rdf(
            set_rdf='<0x700> <acct> "post-crash" .', commit_now=True
        )
        entry = BackupCoordinator(c, bdir).backup()
        man = bk.load_manifest(bdir)
        assert len(man["backups"]) == 2  # resumed stale + fresh
        assert entry is man["backups"][-1] or entry == man["backups"][-1]
        assert entry["since"] == man["backups"][0]["read_ts"]
        s2 = Server()
        restore(s2, bdir)
        out = s2.query('{ q(func: eq(acct, "post-crash")) { uid } }')
        assert out["data"]["q"], "post-crash write missing from backup"
    finally:
        faults.reset()
        c.close()


def test_full_backup_recovers_a_broken_chain(tmp_path):
    """A gapped manifest blocks incrementals (correct) but must NOT
    block a full backup — since=0 restarts the chain and never replays
    the broken prefix; `--full` is exactly the recovery tool."""
    bdir = str(tmp_path / "b")
    s = _seed_server(n=4)
    backup(s, bdir)
    s.new_txn().mutate_rdf(set_rdf='<0x70> <name> "x" .', commit_now=True)
    backup(s, bdir)
    man = bk.load_manifest(bdir)
    man["backups"][1]["since"] += 5  # break the chain
    bk.save_manifest(bdir, man)
    with pytest.raises(ManifestChainError):
        backup(s, bdir)  # incremental: refused
    e = backup(s, bdir, incremental=False)  # full: recovers
    assert e["since"] == 0
    s2 = Server()
    restore(s2, bdir)  # chain now replays from the new full entry
    assert len(s2.query('{ q(func: has(name)) { uid } }')["data"]["q"]) == 5


def test_backup_abort_drops_partials(tmp_path):
    c = DistributedCluster(n_groups=2, replicas=1, pump_ms=2)
    try:
        _seed_bank(c)
        bdir = str(tmp_path / "bk")
        faults.install(FaultPlan(seed=7, rules=[
            dict(point="backup.group", action="crash", p=1.0, max=1),
        ]))
        with pytest.raises(InjectedCrash):
            BackupCoordinator(c, bdir).backup()
        faults.reset()
        assert BackupCoordinator(c, bdir).abort() is True
        assert not [f for f in os.listdir(bdir) if f.endswith(".gz")]
        assert bk.load_manifest(bdir)["backups"] == []
        # and a clean backup afterwards works
        entry = BackupCoordinator(c, bdir).backup()
        assert entry["records"] > 0
    finally:
        faults.reset()
        c.close()


def test_online_restore_idempotent_rerun_and_journal(tmp_path):
    """restore_to_cluster journals applied chunks (resume skips them),
    and clears the journal on success — a LATER restore into the same
    data_dir must re-apply, not silently skip and report success."""
    from dgraph_tpu.worker.backupdriver import RestoreJournal

    src = _seed_server(n=8)
    bdir = str(tmp_path / "bk")
    backup(src, bdir)
    d = str(tmp_path / "dc")
    jpath = os.path.join(d, "restore.journal")
    c = DistributedCluster(n_groups=2, replicas=1, pump_ms=2, data_dir=d)
    try:
        # an interrupted restore's journal makes the resume skip its
        # applied chunks: pre-journal one real token and verify the
        # corresponding chunk is NOT re-proposed
        entry = bk.load_manifest(bdir)["backups"][0]
        os.makedirs(d, exist_ok=True)
        j = RestoreJournal(jpath)
        j.mark(f"{entry['since']}-{entry['read_ts']}-uall:1:0")
        j.close()
        q = '{ q(func: has(name), orderasc: name) { name age } }'
        src_data = src.query(q)["data"]
        n1 = restore_to_cluster(c, bdir)
        assert n1 > 0
        # the pre-journaled chunk was SKIPPED (resume semantics): the
        # first restore is visibly partial
        partial = c.query(q)["data"]
        assert partial != src_data
        # success clears the journal (it exists only to resume the
        # crashed restore it belongs to) ...
        assert not os.path.exists(jpath)
        # ... so the NEXT restore re-applies everything — the stale
        # journal can no longer suppress it into a silent no-op
        restore_to_cluster(c, bdir)
        assert c.query(q)["data"] == src_data
        assert len(src_data["q"]) == 8
        assert not os.path.exists(jpath)
    finally:
        c.close()


# ---------------------------------------------------------------------------
# multi-process cluster: online backup + watermark-visible restore + CDC
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_proc_cluster_online_backup_restore_watermark_and_cdc(tmp_path):
    """The ops plane on a real multi-process cluster: an online backup
    (paged leader-only RPC reads) while writes keep flowing, an online
    restore into a SECOND live cluster whose snapshot-watermark reads
    must see the restored data immediately (the regression:
    restore_to_cluster used to clear `mem` without advancing the
    watermark, so restored rows stayed invisible until the next live
    commit), and CDC with its checkpoint proposed through the group
    raft log."""
    from dgraph_tpu.worker.harness import ProcCluster

    bdir = str(tmp_path / "bk")
    c = ProcCluster(n_groups=2, replicas=1)
    try:
        c.alter(SCHEMA)
        rdf = [f'<0x{i:x}> <name> "p{i}" .' for i in range(1, 25)]
        c.new_txn().mutate_rdf(set_rdf="\n".join(rdf), commit_now=True)
        stop = threading.Event()

        def writer():
            i = 0
            while not stop.is_set():
                i += 1
                c.new_txn().mutate_rdf(
                    set_rdf=f'<0x{0x200 + i:x}> <name> "live{i}" .',
                    commit_now=True,
                )
                time.sleep(0.005)

        th = threading.Thread(target=writer)
        th.start()
        try:
            entry = backup_engine(c, bdir)
        finally:
            stop.set()
            th.join(timeout=30)
        assert entry["records"] >= 24
        # CDC over the proc cluster: checkpoint rides a raft proposal
        sink = []
        cdc = CDC(c, sink_fn=sink.append)
        try:
            c.new_txn().mutate_rdf(
                set_rdf='<0x500> <name> "cdc-proc" .', commit_now=True
            )
            assert cdc.flush()
            assert any(
                e["event"]["value"] == "cdc-proc" for e in sink
            )
            assert cdc.checkpoint > 0
        finally:
            cdc.close()
    finally:
        c.close()

    c2 = ProcCluster(n_groups=2, replicas=1)
    try:
        # a live commit first, so the watermark is nonzero and queries
        # take the watermark read path
        c2.alter("seed: int .")
        c2.new_txn().mutate_rdf(
            set_rdf='<0x900> <seed> "1"^^<xs:int> .', commit_now=True
        )
        wm0 = c2._snapshot_ts
        n = restore_to_cluster(c2, bdir)
        assert n >= entry["records"]
        # watermark advanced past the restored timestamps...
        assert c2._snapshot_ts > wm0
        # ...so a watermark read sees the restored rows IMMEDIATELY
        out = c2.query("{ q(func: has(name)) { uid } }")
        assert len(out["data"]["q"]) >= 24
        out = c2.query('{ q(func: eq(name, "p7")) { name } }')
        assert out["data"]["q"] == [{"name": "p7"}]
    finally:
        c2.close()


# ---------------------------------------------------------------------------
# CDC
# ---------------------------------------------------------------------------


def test_cdc_group_commit_ordering_and_dedup_ids():
    """Concurrent committers through the group-commit pipeline: the
    sink sees events strictly in commit-ts order with unique
    (commit_ts, seq) ids."""
    s = Server()
    s.alter("v: int .")
    got = []
    cdc = CDC(s, sink_fn=got.append)
    try:
        def w(i):
            for j in range(5):
                s.new_txn().mutate_rdf(
                    set_rdf=f'<0x{i:x}> <v> "{j}"^^<xs:int> .',
                    commit_now=True,
                )

        ths = [
            threading.Thread(target=w, args=(i,)) for i in range(1, 9)
        ]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        assert cdc.flush()
        ts = [e["meta"]["commit_ts"] for e in got]
        assert ts == sorted(ts)
        assert len(got) == 40
        ids = {(e["meta"]["commit_ts"], e["meta"]["seq"]) for e in got}
        assert len(ids) == 40
        assert cdc.checkpoint == max(ts)
    finally:
        cdc.close()


def test_cdc_datetime_rfc3339_golden(tmp_path):
    """CDC events carry RFC3339 datetimes (shared query/valuefmt.py
    formatter) that round-trip through the RDF/live-loader parse path
    — the bare isoformat() regression golden."""
    from dgraph_tpu.types.types import parse_datetime

    path = str(tmp_path / "cdc.ndjson")
    s = Server()
    s.alter("when: datetime .")
    cdc = CDC(s, sink_path=path)
    try:
        s.new_txn().mutate_rdf(
            set_rdf='<0x1> <when> "2022-10-12T07:20:50.52Z"'
            "^^<xs:dateTime> .",
            commit_now=True,
        )
        assert cdc.flush()
    finally:
        cdc.close()
    events = [json.loads(l) for l in open(path)]
    vals = [
        e["event"]["value"] for e in events if e["event"]["attr"] == "when"
    ]
    # golden: the Z-suffixed RFC3339 form, not a naive isoformat()
    assert vals == ["2022-10-12T07:20:50.520000Z"]
    # round-trip: the emitted literal parses back to the same instant
    got = parse_datetime(vals[0])
    want = parse_datetime("2022-10-12T07:20:50.52Z")
    assert got == want
    # and it re-ingests through the RDF mutation path unchanged
    s2 = Server()
    s2.alter("when: datetime .")
    s2.new_txn().mutate_rdf(
        set_rdf=f'<0x1> <when> "{vals[0]}"^^<xs:dateTime> .',
        commit_now=True,
    )
    assert (
        s2.query("{ q(func: has(when)) { when } }")["data"]
        == s.query("{ q(func: has(when)) { when } }")["data"]
    )


def test_cdc_sink_retry_and_backpressure():
    """A flaky sink is retried with backoff (no event lost, dupes
    allowed); a bounded queue blocks committers instead of dropping."""
    s = Server()
    s.alter("v: int .")
    delivered = []
    fails = {"n": 0}

    def flaky(ev):
        if fails["n"] < 3:
            fails["n"] += 1
            raise IOError("sink down")
        delivered.append(ev)

    retries0 = METRICS.value("cdc_sink_retries_total")
    cdc = CDC(
        s, sink_fn=flaky, queue_max=2,
        retry=RetryPolicy(base=0.005, cap=0.02),
    )
    try:
        for j in range(6):
            s.new_txn().mutate_rdf(
                set_rdf=f'<0x1> <v> "{j}"^^<xs:int> .', commit_now=True
            )
        assert cdc.flush()
        # every committed event arrived despite the sink failures
        seen = {
            (e["meta"]["commit_ts"], e["meta"]["seq"]) for e in delivered
        }
        assert len(seen) == 6
        assert METRICS.value("cdc_sink_retries_total") >= retries0 + 3
        assert cdc.checkpoint > 0
    finally:
        cdc.close()


def test_cdc_cluster_sink_crash_failover_replay_apply_equivalence():
    """The cluster CDC acceptance chain: a replicated checkpoint, a
    sink crash losing the in-flight window, a coordinator-failover
    handoff whose replay-from-checkpoint recovers every event — and
    the recovered stream, applied to a FRESH engine, reproduces
    identical query results (apply equivalence)."""
    c = DistributedCluster(n_groups=2, replicas=3, pump_ms=2)
    sink1, sink2 = [], []
    cdc2 = None
    try:
        c.alter(SCHEMA + "\nwhen: datetime .")
        cdc1 = CDC(c, sink_fn=sink1.append)
        c.new_txn().mutate_rdf(
            set_rdf='<0x1> <name> "alice" .\n<0x2> <name> "bob" .\n'
            "<0x1> <friend> <0x2> .",
            commit_now=True,
        )
        c.new_txn().mutate_rdf(
            set_rdf='<0x1> <age> "30"^^<xs:int> .\n'
            '<0x1> <when> "2024-05-06T07:08:09Z"^^<xs:dateTime> .',
            commit_now=True,
        )
        assert cdc1.flush()
        ck = cdc1.checkpoint
        assert ck > 0
        # the checkpoint is REPLICATED: every replica of the journal
        # group holds it, so any future coordinator can resume
        from dgraph_tpu.admin.cdc import CDC_CHECKPOINT_KEY

        gid = min(c.groups)
        for node in c.groups[gid].nodes:
            assert node.kv.get(CDC_CHECKPOINT_KEY, 1 << 62) is not None
        # sink crash: the emitter dies mid-window; commits keep flowing
        faults.install(FaultPlan(seed=1, rules=[
            dict(point="cdc.emit", action="crash", p=1.0, max=1),
        ]))
        c.new_txn().mutate_rdf(
            set_rdf='<0x3> <name> "carol" .', commit_now=True
        )
        c.new_txn().mutate_rdf(
            set_rdf='<0x2> <age> "41"^^<xs:int> .', commit_now=True
        )
        deadline = time.time() + 10
        while cdc1.dead is None and time.time() < deadline:
            time.sleep(0.05)
        faults.reset()
        assert cdc1.dead is not None  # the sink-crash window is open
        cdc1.close()
        # failover: a fresh CDC (the new coordinator) replays from the
        # replicated checkpoint — the lost window is recovered
        cdc2 = CDC(c, sink_fn=sink2.append)
        assert cdc2.flush()
        replayed = {
            (e["meta"]["commit_ts"], e["meta"]["seq"]) for e in sink2
        }
        assert replayed, "failover replay emitted nothing"
        assert min(ts for ts, _ in replayed) > ck
        # no event lost across the crash: dedup the union on
        # (commit_ts, seq) and apply it to a FRESH engine
        merged = {}
        for ev in sink1 + sink2:
            merged[(ev["meta"]["commit_ts"], ev["meta"]["seq"])] = ev
        fresh = Server()
        fresh.alter(SCHEMA + "\nwhen: datetime .")
        _apply_events(fresh, [merged[k] for k in sorted(merged)])
        for q in (
            '{ q(func: has(name), orderasc: name) { name age when } }',
            '{ q(func: eq(name, "alice")) { name friend { name } } }',
            '{ q(func: has(age), orderasc: age) { age } }',
        ):
            assert fresh.query(q)["data"] == c.query(q)["data"], q
    finally:
        faults.reset()
        if cdc2 is not None:
            cdc2.close()
        c.close()


def _apply_events(server, events):
    """Replay a CDC event stream through the normal mutation path (the
    live-loader seam): the apply-equivalence consumer."""
    for ev in events:
        e = ev["event"]
        subj = f"<0x{e['uid']:x}>"
        pred = f"<{e['attr']}>"
        if e["operation"] == "set":
            if "value_uid" in e:
                rdf = f"{subj} {pred} <0x{e['value_uid']:x}> ."
            else:
                v = e["value"]
                if isinstance(v, bool):
                    rdf = f'{subj} {pred} "{v}"^^<xs:boolean> .'
                elif isinstance(v, int):
                    rdf = f'{subj} {pred} "{v}"^^<xs:int> .'
                elif isinstance(v, float):
                    rdf = f'{subj} {pred} "{v}"^^<xs:float> .'
                else:
                    sv = str(v).replace("\\", "\\\\").replace('"', '\\"')
                    rdf = f'{subj} {pred} "{sv}" .'
            server.new_txn().mutate_rdf(set_rdf=rdf, commit_now=True)
        else:
            if "value_uid" in e:
                rdf = f"{subj} {pred} <0x{e['value_uid']:x}> ."
            else:
                rdf = f"{subj} {pred} * ."
            server.new_txn().mutate_rdf(del_rdf=rdf, commit_now=True)


def test_cdc_apply_equivalence_single_engine_with_deletes():
    """Replay the full event stream (sets, uid edges, deletes) into a
    fresh server: query results must be identical — the CDC events are
    a complete, typed description of the committed mutations."""
    s = Server()
    s.alter(SCHEMA + "\nwhen: datetime .\nscore: float .")
    got = []
    cdc = CDC(s, sink_fn=got.append)
    try:
        s.new_txn().mutate_rdf(
            set_rdf='<0x1> <name> "ann" .\n<0x2> <name> "ben" .\n'
            '<0x1> <friend> <0x2> .\n<0x1> <age> "7"^^<xs:int> .\n'
            '<0x2> <score> "2.5"^^<xs:float> .\n'
            '<0x2> <when> "2023-01-02T03:04:05.6Z"^^<xs:dateTime> .',
            commit_now=True,
        )
        s.new_txn().mutate_rdf(
            del_rdf="<0x1> <friend> <0x2> .", commit_now=True
        )
        s.new_txn().mutate_rdf(
            set_rdf='<0x1> <age> "8"^^<xs:int> .', commit_now=True
        )
        assert cdc.flush()
    finally:
        cdc.close()
    fresh = Server()
    fresh.alter(SCHEMA + "\nwhen: datetime .\nscore: float .")
    _apply_events(fresh, got)
    for q in (
        '{ q(func: has(name), orderasc: name) { name age score when } }',
        '{ q(func: eq(name, "ann")) { friend { name } age } }',
    ):
        assert fresh.query(q)["data"] == s.query(q)["data"], q


def test_cdc_replay_covers_checkpoint_gap_exactly():
    """Replay from an arbitrary checkpoint: only versions above it
    re-emit, with ids identical to the live emission (dedup-stable)."""
    s = Server()
    s.alter("v: int .\nname: string @index(exact) .")
    live = []
    cdc = CDC(s, sink_fn=live.append)
    try:
        for j in range(4):
            s.new_txn().mutate_rdf(
                set_rdf=f'<0x{j + 1:x}> <name> "r{j}" .', commit_now=True
            )
        assert cdc.flush()
    finally:
        cdc.close()
    # rewind the checkpoint to the 2nd commit and replay (the override
    # must land as the NEWEST checkpoint version to be read back)
    import struct

    from dgraph_tpu.admin.cdc import CDC_CHECKPOINT_KEY

    mid = sorted({e["meta"]["commit_ts"] for e in live})[1]
    s.kv.put(CDC_CHECKPOINT_KEY, 1 << 61, struct.pack("<Q", mid))
    replayed = []
    cdc2 = CDC(s, sink_fn=replayed.append, replay=True)
    try:
        assert cdc2.flush()
    finally:
        cdc2.close()
    live_ids = {
        (e["meta"]["commit_ts"], e["meta"]["seq"]): e["event"]
        for e in live
        if e["meta"]["commit_ts"] > mid
    }
    replay_ids = {
        (e["meta"]["commit_ts"], e["meta"]["seq"]): e["event"]
        for e in replayed
    }
    assert replay_ids == live_ids  # byte-stable ids AND bodies
    # the checkpoint re-advanced monotonically past the replayed
    # window (read the emitter's own cursor: the rewind hack above
    # shadows KV reads with its artificial high-ts version)
    assert cdc2._ckpt_saved == max(ts for ts, _ in live_ids)

"""Posting-list layering semantics (mirrors /root/reference/posting/list_test.go):
rollup + committed deltas + in-txn deltas, value postings, conflicts."""

import numpy as np
import pytest

from dgraph_tpu.posting.pl import (
    OP_DEL,
    OP_SET,
    Posting,
    PostingList,
    decode_record,
    encode_delta,
    encode_rollup,
    lang_uid,
)
from dgraph_tpu.posting.lists import LocalCache, Txn
from dgraph_tpu.posting.mutation import DirectedEdge, apply_edge
from dgraph_tpu.schema.schema import State, parse_schema
from dgraph_tpu.storage.kv import MemKV
from dgraph_tpu.types.types import TypeID, Val
from dgraph_tpu.x import keys
from dgraph_tpu.zero.zero import TxnConflictError, ZeroLite
from dgraph_tpu.codec import uidpack


def test_record_roundtrip():
    pack = uidpack.encode(np.array([1, 5, 9], np.uint64))
    posts = [
        Posting(uid=lang_uid(""), value=b"hello", value_type=TypeID.STRING),
        Posting(
            uid=7,
            facets={"since": b"2006"},
            facet_types={"since": TypeID.DEFAULT},
        ),
    ]
    kind, pk, ps, _ = decode_record(encode_rollup(pack, posts))
    assert kind == 0
    np.testing.assert_array_equal(uidpack.decode(pk), [1, 5, 9])
    assert ps[0].value == b"hello"
    assert ps[1].facets["since"] == b"2006"

    kind, _, ps, _ = decode_record(encode_delta([Posting(uid=3, op=OP_DEL)]))
    assert kind == 1 and ps[0].op == OP_DEL


def test_layered_uids():
    kv = MemKV()
    key = b"testkey"
    pack = uidpack.encode(np.array([10, 20, 30], np.uint64))
    kv.put(key, 5, encode_rollup(pack, []))
    kv.put(key, 8, encode_delta([Posting(uid=40, op=OP_SET)]))
    kv.put(key, 12, encode_delta([Posting(uid=20, op=OP_DEL)]))

    pl = PostingList.from_versions(key, kv.versions(key, 9))
    np.testing.assert_array_equal(pl.uids(), [10, 20, 30, 40])

    pl = PostingList.from_versions(key, kv.versions(key, 12))
    np.testing.assert_array_equal(pl.uids(), [10, 30, 40])

    # read below rollup+deltas sees only what was there
    pl = PostingList.from_versions(key, kv.versions(key, 5))
    np.testing.assert_array_equal(pl.uids(), [10, 20, 30])


def test_rollup_compacts():
    kv = MemKV()
    key = b"k"
    kv.put(key, 1, encode_rollup(uidpack.encode(np.array([1, 2], np.uint64)), []))
    kv.put(key, 3, encode_delta([Posting(uid=9, op=OP_SET)]))
    pl = PostingList.from_versions(key, kv.versions(key, 10))
    rec, ts, _parts = pl.rollup()
    assert ts == 3
    kv.put(key, ts, rec)  # same-ts overwrite (idempotent)
    pl2 = PostingList.from_versions(key, kv.versions(key, 10))
    assert not pl2.deltas
    np.testing.assert_array_equal(pl2.uids(), [1, 2, 9])


SCHEMA = """
name: string @index(term, exact) .
age: int @index(int) .
friend: [uid] @reverse @count .
"""


def _state():
    st = State()
    preds, _ = parse_schema(SCHEMA)
    for su in preds:
        st.set(su)
    return st


def test_apply_edges_and_read():
    kv = MemKV()
    zero = ZeroLite()
    st = _state()

    txn = Txn(kv, zero.next_ts())
    apply_edge(txn, st, DirectedEdge(1, "name", value=Val(TypeID.STRING, "Alice")))
    apply_edge(txn, st, DirectedEdge(1, "friend", value_id=2))
    apply_edge(txn, st, DirectedEdge(1, "friend", value_id=3))
    commit_ts = zero.commit(txn.start_ts, txn.conflict_keys)
    txn.write_deltas(kv, commit_ts)

    read = LocalCache(kv, zero.read_ts())
    np.testing.assert_array_equal(
        read.uids(keys.DataKey("friend", 1)), [2, 3]
    )
    assert read.value(keys.DataKey("name", 1)).value == "Alice"
    # reverse edges
    np.testing.assert_array_equal(read.uids(keys.ReverseKey("friend", 2)), [1])
    # term index
    tok = b"\x01" + b"alice"
    np.testing.assert_array_equal(
        read.uids(keys.IndexKey("name", tok)), [1]
    )
    # exact index
    tok = b"\x02" + b"Alice"
    np.testing.assert_array_equal(read.uids(keys.IndexKey("name", tok)), [1])


def test_value_overwrite_reindexes():
    kv = MemKV()
    zero = ZeroLite()
    st = _state()

    t1 = Txn(kv, zero.next_ts())
    apply_edge(t1, st, DirectedEdge(1, "name", value=Val(TypeID.STRING, "Bob")))
    t1.write_deltas(kv, zero.commit(t1.start_ts, t1.conflict_keys))

    t2 = Txn(kv, zero.next_ts())
    apply_edge(t2, st, DirectedEdge(1, "name", value=Val(TypeID.STRING, "Carol")))
    t2.write_deltas(kv, zero.commit(t2.start_ts, t2.conflict_keys))

    read = LocalCache(kv, zero.read_ts())
    assert read.value(keys.DataKey("name", 1)).value == "Carol"
    assert len(read.uids(keys.IndexKey("name", b"\x01bob"))) == 0
    np.testing.assert_array_equal(read.uids(keys.IndexKey("name", b"\x01carol")), [1])


def test_txn_conflict():
    kv = MemKV()
    zero = ZeroLite()
    st = _state()
    st.get("name").upsert = True  # conflict at entity granularity

    t1 = Txn(kv, zero.next_ts())
    t2 = Txn(kv, zero.next_ts())
    apply_edge(t1, st, DirectedEdge(1, "name", value=Val(TypeID.STRING, "A")))
    apply_edge(t2, st, DirectedEdge(1, "name", value=Val(TypeID.STRING, "B")))
    t1.write_deltas(kv, zero.commit(t1.start_ts, t1.conflict_keys))
    with pytest.raises(TxnConflictError):
        zero.commit(t2.start_ts, t2.conflict_keys)


def test_uncommitted_visible_to_own_txn_only():
    kv = MemKV()
    zero = ZeroLite()
    st = _state()

    txn = Txn(kv, zero.next_ts())
    apply_edge(txn, st, DirectedEdge(7, "friend", value_id=8))
    np.testing.assert_array_equal(
        txn.cache.uids(keys.DataKey("friend", 7)), [8]
    )
    other = LocalCache(kv, zero.read_ts())
    assert len(other.uids(keys.DataKey("friend", 7))) == 0


def test_int_index_tokens_sortable():
    kv = MemKV()
    zero = ZeroLite()
    st = _state()
    for uid, age in [(1, 25), (2, 30), (3, 19)]:
        t = Txn(kv, zero.next_ts())
        apply_edge(t, st, DirectedEdge(uid, "age", value=Val(TypeID.INT, age)))
        t.write_deltas(kv, zero.commit(t.start_ts, t.conflict_keys))
    read = LocalCache(kv, zero.read_ts())
    # iterate int index in order -> ages ascending
    got = []
    for k, _, _ in read.kv.iterate(keys.IndexPrefix("age"), read.read_ts):
        pk = keys.parse_key(k)
        uids = read.uids(k)
        got.extend([(pk.term, int(u)) for u in uids])
    assert [u for _, u in got] == [3, 1, 2]

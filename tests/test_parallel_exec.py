"""Serial-vs-parallel executor equivalence (level-batched task fan-out).

The sibling-expansion worker pool (subgraph._expand_children,
DGRAPH_TPU_EXEC_WORKERS) must be a pure performance knob: byte-identical
JSON against the serial executor on every query — the DQL golden corpus,
randomized multi-level queries, and var-binding queries (uid_vars /
val_vars are shared executor state and must stay race-free).

Tier-1 runs the smoke subset; the full 535-case corpus sweep is
slow-marked (one pass keeps thread-safety regressions out of main without
stalling the 1-core box).
"""

import json
import os

import numpy as np
import pytest

HERE = os.path.join(os.path.dirname(os.path.abspath(__file__)), "ref_golden")
CASES = json.load(open(os.path.join(HERE, "cases.json")))


def _query_both(server, q):
    """Run q with the serial and the 4-worker executor; return the two
    byte-exact JSON payloads (or the error reprs when the query fails —
    both modes must fail identically)."""
    out = []
    for workers in ("1", "4"):
        os.environ["DGRAPH_TPU_EXEC_WORKERS"] = workers
        try:
            got = json.dumps(server.query(q)["data"], sort_keys=False)
        except Exception as exc:  # must fail the same way serially
            got = f"{type(exc).__name__}: {exc}"
        out.append(got)
    os.environ.pop("DGRAPH_TPU_EXEC_WORKERS", None)
    return out


@pytest.fixture(scope="module")
def golden_server():
    from dgraph_tpu.api.server import Server

    s = Server()
    s.alter(open(os.path.join(HERE, "schema.txt")).read())
    t = s.new_txn()
    t.mutate_rdf(
        set_rdf=open(os.path.join(HERE, "triples.rdf")).read(),
        commit_now=True,
    )
    t = s.new_txn()
    t.mutate_rdf(
        set_rdf=open(os.path.join(HERE, "triples_facets.rdf")).read(),
        commit_now=True,
    )
    return s


# every ~9th case: wide coverage across the query0..4/facets/math suites
# without stalling tier-1 on the 1-core box
SMOKE_CASES = CASES[::9]


@pytest.mark.parametrize(
    "case", SMOKE_CASES, ids=[c["id"] for c in SMOKE_CASES]
)
def test_exec_workers_smoke(golden_server, case):
    serial, parallel = _query_both(golden_server, case["query"])
    assert serial == parallel


@pytest.mark.slow
@pytest.mark.parametrize("case", CASES, ids=[c["id"] for c in CASES])
def test_exec_workers_full_corpus(golden_server, case):
    serial, parallel = _query_both(golden_server, case["query"])
    assert serial == parallel


# ---------------------------------------------------------------------------
# Var-binding equivalence: vars are shared executor state; the classifier
# must serialize every var-touching sibling, in declaration order.
# ---------------------------------------------------------------------------

VAR_QUERIES = [
    # count-var consumed by a sibling math node
    """{ me(func: eq(name, "Michonne")) {
        name
        c as count(friend)
        friend { name }
        score: math(c + 1)
    } }""",
    # value var defined at one level, aggregated above
    """{ var(func: has(friend)) { friend { a as age } }
        me(func: has(friend)) {
            name
            mn: min(val(a))
            friend { name age }
        } }""",
    # uid var from one block, consumed as a sibling filter
    """{ f as var(func: eq(name, "Michonne")) { fr as friend }
        me(func: uid(f)) {
            name
            friend @filter(uid(fr)) { name }
            dgraph.type
        } }""",
    # facet var + per-parent propagation
    """{ me(func: eq(name, "Michonne")) {
        name
        friend @facets(w as since) { name }
        sum: math(w + 0)
    } }""",
    # val(x) as a comparison ARGUMENT (("valarg", x) in fn.args, not
    # fn.val_var) — the classifier must serialize this sibling AFTER the
    # `x as age` definition or the filter sees an unbound var
    """{ me(func: eq(name, "Michonne")) {
        x as age
        friend @filter(le(age, val(x))) { name age }
    } }""",
]


@pytest.mark.parametrize("q", VAR_QUERIES, ids=range(len(VAR_QUERIES)))
def test_exec_workers_var_binding(golden_server, q):
    serial, parallel = _query_both(golden_server, q)
    assert serial == parallel


# ---------------------------------------------------------------------------
# Span parenting: executor-pool workers must inherit the query's trace
# context (contextvars copy), not start orphan traces.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("workers", ["1", "4"])
def test_level_task_spans_share_query_trace(golden_server, workers):
    from dgraph_tpu.utils.observe import TRACER

    q = """{ me(func: eq(name, "Michonne")) {
        name
        friend { name friend { name } }
        school { name }
        pet { name }
    } }"""
    os.environ["DGRAPH_TPU_EXEC_WORKERS"] = workers
    try:
        golden_server.query(q)
    finally:
        os.environ.pop("DGRAPH_TPU_EXEC_WORKERS", None)
    spans = TRACER.recent(400)
    qspan = [s for s in spans if s["name"] == "query"][-1]
    level = [
        s
        for s in spans
        if s["name"] == "level_task" and s["start"] >= qspan["start"]
    ]
    assert len(level) >= 3, "expected level tasks across levels"
    for s in level:
        assert s["trace_id"] == qspan["trace_id"], s
        assert s["parent_id"] is not None, f"orphan level_task: {s}"


# ---------------------------------------------------------------------------
# Randomized multi-level fuzz: random graph, random query shapes.
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def fuzz_server():
    from dgraph_tpu.api.server import Server

    rng = np.random.default_rng(42)
    n = 120
    s = Server()
    s.alter(
        "name: string @index(exact, term) .\n"
        "age: int @index(int) .\n"
        "knows: [uid] @reverse @count .\n"
        "likes: [uid] @reverse .\n"
        "boss: uid .\n"
    )
    lines = []
    for u in range(1, n + 1):
        lines.append(f'<{hex(u)}> <name> "node{u}" .')
        lines.append(f'<{hex(u)}> <age> "{u % 60}"^^<xs:int> .')
        for v in rng.integers(1, n + 1, 6):
            if int(v) != u:
                lines.append(f"<{hex(u)}> <knows> <{hex(int(v))}> .")
        for v in rng.integers(1, n + 1, 3):
            lines.append(f"<{hex(u)}> <likes> <{hex(int(v))}> .")
        lines.append(f"<{hex(u)}> <boss> <{hex(int(rng.integers(1, n + 1)))}> .")
    t = s.new_txn()
    t.mutate_rdf(set_rdf="\n".join(lines), commit_now=True)
    return s


def _rand_query(rng) -> str:
    preds = ["knows", "likes", "~knows", "~likes", "boss"]

    def block(depth: int) -> str:
        fields = ["name"]
        if rng.random() < 0.5:
            fields.append("age")
        if rng.random() < 0.3:
            fields.append("cnt: count(knows)")
        k = 1 if depth >= 2 else int(rng.integers(1, 3))
        for p in rng.choice(preds, size=k, replace=False):
            mods = ""
            if rng.random() < 0.4:
                mods += " @filter(lt(age, %d))" % int(rng.integers(10, 60))
            page = ""
            if rng.random() < 0.4:
                page = " (first: %d, offset: %d)" % (
                    int(rng.integers(1, 6)),
                    int(rng.integers(0, 3)),
                )
            if depth < 3:
                mods = f"{page}{mods} {{ {block(depth + 1)} }}"
            else:
                mods = f"{page}{mods} {{ name }}"
            fields.append(f"{p}{mods}")
        return " ".join(fields)

    root = int(rng.integers(1, 120))
    return "{ q(func: uid(%s)) { %s } }" % (hex(root), block(1))


def test_exec_workers_fuzz(fuzz_server):
    rng = np.random.default_rng(1234)
    for _ in range(25):
        q = _rand_query(rng)
        serial, parallel = _query_both(fuzz_server, q)
        assert serial == parallel, q

"""Bank-transfer consistency suite (ref systest/bank/bank_test.go; the
jepsen-class invariant check): N accounts, concurrent conflicting
transfers under SSI — the total balance is invariant at every snapshot,
and lost updates are impossible (conflicting txns abort and retry).
"""

import threading

import numpy as np
import pytest

from dgraph_tpu.api.server import Server
from dgraph_tpu.zero.zero import TxnConflictError

N_ACCOUNTS = 10
START_BALANCE = 100
TOTAL = N_ACCOUNTS * START_BALANCE


@pytest.fixture()
def bank():
    s = Server()
    s.alter("bal: int @upsert .\nacct: string @index(exact) @upsert .")
    t = s.new_txn()
    rdf = []
    for i in range(1, N_ACCOUNTS + 1):
        rdf.append(f'<0x{i:x}> <acct> "a{i}" .')
        rdf.append(f'<0x{i:x}> <bal> "{START_BALANCE}"^^<xs:int> .')
    t.mutate_rdf(set_rdf="\n".join(rdf), commit_now=True)
    return s


def _balances(s, ts=None):
    out = s.query("{ q(func: has(bal)) { uid bal } }", read_ts=ts)
    return {int(x["uid"], 16): x["bal"] for x in out["data"]["q"]}


def _transfer(s, frm, to, amount, rng):
    """One read-modify-write transfer txn; returns True if committed."""
    t = s.new_txn()
    try:
        got = t.query(
            "{ a(func: uid(0x%x)) { bal } b(func: uid(0x%x)) { bal } }"
            % (frm, to)
        )
        a_bal = got["data"]["a"][0]["bal"]
        b_bal = got["data"]["b"][0]["bal"]
        if a_bal < amount:
            t.discard()
            return False
        # widen the read->write window so writers actually interleave
        # (a whole txn otherwise fits inside one GIL slice)
        import time as _time

        _time.sleep(0.001)
        t.mutate_rdf(
            set_rdf=(
                f'<0x{frm:x}> <bal> "{a_bal - amount}"^^<xs:int> .\n'
                f'<0x{to:x}> <bal> "{b_bal + amount}"^^<xs:int> .'
            )
        )
        t.commit()
        return True
    except TxnConflictError:
        return False
    except RuntimeError:
        return False


def test_concurrent_transfers_preserve_total(bank):
    rng = np.random.default_rng(0)
    stop = threading.Event()
    stats = {"ok": 0, "aborts": 0}
    lock = threading.Lock()

    def worker(seed):
        r = np.random.default_rng(seed)
        while not stop.is_set():
            frm, to = r.choice(N_ACCOUNTS, 2, replace=False) + 1
            ok = _transfer(bank, int(frm), int(to), int(r.integers(1, 20)), r)
            with lock:
                stats["ok" if ok else "aborts"] += 1

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    for th in threads:
        th.start()
    # check the invariant at many concurrent snapshots while running
    import time as _time

    for _ in range(25):
        bals = _balances(bank)
        assert sum(bals.values()) == TOTAL, bals
        _time.sleep(0.02)
    stop.set()
    for th in threads:
        th.join(timeout=10)
    # final state: invariant holds; work actually happened; SSI aborted
    # at least some conflicting pairs (4 writers over 10 accounts)
    bals = _balances(bank)
    assert sum(bals.values()) == TOTAL
    assert stats["ok"] > 20
    assert stats["aborts"] > 0


def test_snapshot_reads_are_stable(bank):
    """A fixed read_ts sees a frozen balance vector even while transfers
    commit after it (MVCC snapshot isolation)."""
    ts = bank.zero.read_ts()
    before = _balances(bank, ts)
    rng = np.random.default_rng(1)
    for _ in range(20):
        _transfer(bank, 1, 2, 5, rng)
    after_same_ts = _balances(bank, ts)
    assert after_same_ts == before
    assert sum(_balances(bank).values()) == TOTAL

"""Extract the reference's @auth-rewriting oracles into auth_cases.json.

Source YAMLs (graphql/resolve/, driven by auth_test.go over
graphql/e2e/auth/schema.graphql — copied here as auth_schema.graphql):
  auth_query_test.yaml   — query rewriting with JWT claims → dgquery
  auth_delete_test.yaml  — delete rewriting → dgquery + dgmutations
  auth_add_test.yaml     — add + post-mutation auth checks (error cases)
  auth_update_test.yaml  — update + auth filters (error cases)
  auth_closed_by_default_*.yaml — no-JWT rejections (closed mode)

The conformance test runs both sides through OUR engine on the same
seeded world: GraphQL-with-claims on side A, the reference-blessed
dgquery/dgmutations on side B (query cases compare responses Tier-B
style; delete cases compare resulting stores). Add/update cases with
`error` assert rejection; success cases assert acceptance.

Run from repo root: python tests/ref_golden_graphql/extract_auth.py
"""

import json
import os

import yaml

REF = "/root/reference/graphql/resolve"
OUT = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "auth_cases.json"
)

FILES = [
    ("query", "auth_query_test.yaml", False),
    ("add", "auth_add_test.yaml", False),
    ("update", "auth_update_test.yaml", False),
    ("delete", "auth_delete_test.yaml", False),
    ("query", "auth_closed_by_default_query_test.yaml", True),
    ("add", "auth_closed_by_default_add_test.yaml", True),
    ("update", "auth_closed_by_default_update_test.yaml", True),
    ("delete", "auth_closed_by_default_delete_test.yaml", True),
]


def _mutations(raw):
    out = []
    for m in raw or []:
        entry = {}
        if m.get("setjson"):
            entry["set"] = json.loads(m["setjson"])
        if m.get("deletejson"):
            entry["delete"] = json.loads(m["deletejson"])
        if m.get("cond"):
            entry["cond"] = m["cond"]
        out.append(entry)
    return out


def main():
    cases = []
    for kind, fname, closed in FILES:
        raw = yaml.safe_load(open(os.path.join(REF, fname)))
        stem = fname.replace("_test.yaml", "").replace("auth_", "")
        for i, c in enumerate(raw):
            case = {
                "id": f"auth/{stem}/{i:03d}",
                "kind": kind,
                "closed": closed,
                "name": c["name"],
                "gqlquery": c["gqlquery"],
            }
            jwt = c.get("jwtvar") or c.get("jwtVar")
            if jwt:
                case["jwtvar"] = jwt
            for vk in ("variables", "dgvars"):
                if c.get(vk):
                    v = c[vk]
                    case[vk] = json.loads(v) if isinstance(v, str) else v
            for k in ("dgquery", "dgquerysec", "authquery", "error"):
                if c.get(k):
                    case[k] = (
                        c[k]["message"]
                        if isinstance(c[k], dict)
                        else c[k]
                    )
            if c.get("dgmutations"):
                case["dgmutations"] = _mutations(c["dgmutations"])
            if c.get("uids"):
                case["uids"] = json.loads(c["uids"])
            cases.append(case)
    with open(OUT, "w") as f:
        json.dump(cases, f, indent=1)
    print(f"wrote {len(cases)} cases to {OUT}")


if __name__ == "__main__":
    main()

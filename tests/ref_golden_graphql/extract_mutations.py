"""Extract the reference's GraphQL *mutation*-rewriting oracles into
mutation_cases.json.

Source YAMLs (graphql/resolve/, driven by mutation_test.go
TestMutationRewriting):
  add_mutation_test.yaml      — NewAddRewriter cases
  update_mutation_test.yaml   — NewUpdateRewriter cases
  delete_mutation_test.yaml   — NewDeleteRewriter cases
  validate_mutation_test.yaml — schema-validation rejections

Each case pairs a GraphQL mutation with the reference-blessed execution
plan: `dgquery` (existence / delete-target queries), `dgquerysec` (the
upsert's query block), `dgmutations` (setjson/deletejson + @if conds),
and `qnametouid` (which referenced xids/uids the plan assumed to exist).

The conformance test (test_ref_golden_graphql_mut.py) runs both sides
through OUR engine against the same seeded world — our GraphQL layer on
one store, the reference's plan (via Txn.upsert_json) on another — and
compares the resulting graphs modulo uid renaming. Mutation *semantics*
are therefore checked against the reference without requiring our
internals to emit byte-identical rewrites.

Run from repo root: python tests/ref_golden_graphql/extract_mutations.py
mutation_cases.json is checked in so the suite is self-contained.
"""

import json
import os

import yaml

REF = "/root/reference/graphql/resolve"
OUT = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "mutation_cases.json"
)

FILES = [
    ("add", "add_mutation_test.yaml"),
    ("update", "update_mutation_test.yaml"),
    ("delete", "delete_mutation_test.yaml"),
    ("validate", "validate_mutation_test.yaml"),
]


def _mutations(raw):
    out = []
    for m in raw or []:
        entry = {}
        if m.get("setjson"):
            entry["set"] = json.loads(m["setjson"])
        if m.get("deletejson"):
            entry["delete"] = json.loads(m["deletejson"])
        if m.get("cond"):
            entry["cond"] = m["cond"]
        out.append(entry)
    return out


def main():
    cases = []
    for kind, fname in FILES:
        raw = yaml.safe_load(open(os.path.join(REF, fname)))
        for i, c in enumerate(raw):
            case = {
                "id": f"mut/{kind}/{i:03d}",
                "kind": kind,
                "name": c["name"],
                "gqlmutation": c["gqlmutation"],
            }
            if c.get("gqlvariables"):
                case["gqlvariables"] = json.loads(c["gqlvariables"])
            qn = (c.get("qnametouid") or "").strip()
            if qn:
                case["qnametouid"] = json.loads(qn)
            for k in ("dgquery", "dgquerysec"):
                if c.get(k):
                    case[k] = c[k]
            if c.get("dgmutations"):
                case["dgmutations"] = _mutations(c["dgmutations"])
            if c.get("dgmutationssec"):
                case["dgmutationssec"] = _mutations(c["dgmutationssec"])
            for k in ("error", "error2", "validationerror"):
                if c.get(k):
                    case[k] = (
                        c[k]["message"] if isinstance(c[k], dict) else c[k]
                    )
            cases.append(case)
    with open(OUT, "w") as f:
        json.dump(cases, f, indent=1)
    print(f"wrote {len(cases)} cases to {OUT}")


if __name__ == "__main__":
    main()

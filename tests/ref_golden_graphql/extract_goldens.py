"""Extract golden (GraphQL query, variables, expected-JSON) cases from
the reference's GraphQL e2e suites.

The reference runs ~200 e2e assertions over the normal/directives
fixture (schema.graphql + test_data.json loaded once per suite,
/root/reference/graphql/e2e/common/common.go RunAll) in two mechanical
shapes:

    params := &GraphQLParams{Query: `...`, Variables: map[...]{...}}
    gqlResponse := params.ExecuteAsPost(t, GraphqlURL)
    expected := `...`
    require.JSONEq(t, expected, string(gqlResponse.Data))
      (or testutil.CompareJSON — array-order-insensitive)

and table-driven:

    tcases := []struct{...}{{name: ..., query: `...`, respData: `...`}}

This script extracts every statically-resolvable case from functions
that do NOT mutate cluster state (helpers like addAuthor/deleteCountry
make a function's goldens depend on in-test data, not the fixture).
Queries needing Go-side Sprintf/concatenation or non-literal variables
are skipped.

Run from the repo root:  python tests/ref_golden_graphql/extract_goldens.py
cases.json is checked in so the conformance suite is self-contained.
"""

import json
import os
import re

REF = "/root/reference/graphql/e2e/common/query.go"
OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "cases.json")

# any of these in a function body => the function mutates shared state
# (or depends on data added in-test) and its goldens are not
# fixture-derived
MUTATORS = (
    "add",  # addCountry/addAuthor/addStarship/addMultipleAuthorFromRef…
    "delete",
    "update",
    "cleanup",
    "DeleteGql",
    "mutation",
    "Mutation",
    "dgo.",
    "RunQuery(",  # direct dgo side-channel
)


def has_mutator(body: str) -> bool:
    for mu in MUTATORS:
        if mu in body:
            return True
    return False


def split_functions(src):
    """Yield (name, body) for each top-level func taking *testing.T."""
    for m in re.finditer(r"func (\w+)\(t \*testing\.T[^)]*\) \{", src):
        start = m.end()
        depth = 1
        i = start
        in_raw = in_str = False
        while i < len(src) and depth:
            c = src[i]
            if in_raw:
                if c == "`":
                    in_raw = False
            elif in_str:
                if c == "\\":
                    i += 1
                elif c == '"':
                    in_str = False
            elif c == "`":
                in_raw = True
            elif c == '"':
                in_str = True
            elif c == "{":
                depth += 1
            elif c == "}":
                depth -= 1
            i += 1
        yield m.group(1), src[start : i - 1]


def read_raw(src, i):
    """src[i] == '`' — (content, index after closing tick)."""
    j = src.index("`", i + 1)
    return src[i + 1 : j], j + 1


# ---------------------------------------------------------------------------
# Go literal sub-parser (Variables maps). Returns (value, end) or raises.
# ---------------------------------------------------------------------------


class Unextractable(Exception):
    pass


WS = re.compile(r"[\s,]+")


def _skip_ws(s, i):
    m = WS.match(s, i)
    return m.end() if m else i


def parse_go_value(s, i):
    i = _skip_ws(s, i)
    if s.startswith("map[string]interface{}{", i):
        return parse_go_map(s, i + len("map[string]interface{}{"))
    if s.startswith("[]interface{}{", i):
        return parse_go_list(s, i + len("[]interface{}{"))
    m = re.match(r"\[\]string\{", s[i:])
    if m:
        return parse_go_list(s, i + m.end())
    if s[i] == '"':
        m = re.match(r'"((?:[^"\\]|\\.)*)"', s[i:])
        if not m:
            raise Unextractable("bad string")
        return json.loads('"' + m.group(1) + '"'), i + m.end()
    if s[i] == "`":
        v, j = read_raw(s, i)
        return v, j
    m = re.match(r"(true|false)\b", s[i:])
    if m:
        return m.group(1) == "true", i + m.end()
    m = re.match(r"-?\d+\.\d+", s[i:])
    if m:
        return float(m.group(0)), i + m.end()
    m = re.match(r"-?\d+", s[i:])
    if m:
        return int(m.group(0)), i + m.end()
    raise Unextractable(f"unsupported Go literal at {s[i:i+40]!r}")


def parse_go_map(s, i):
    out = {}
    while True:
        i = _skip_ws(s, i)
        if s[i] == "}":
            return out, i + 1
        m = re.match(r'"((?:[^"\\]|\\.)*)"\s*:', s[i:])
        if not m:
            raise Unextractable(f"bad map key at {s[i:i+40]!r}")
        key = json.loads('"' + m.group(1) + '"')
        v, i = parse_go_value(s, i + m.end())
        out[key] = v


def parse_go_list(s, i):
    out = []
    while True:
        i = _skip_ws(s, i)
        if s[i] == "}":
            return out, i + 1
        v, i = parse_go_value(s, i)
        out.append(v)


# ---------------------------------------------------------------------------
# Case extraction
# ---------------------------------------------------------------------------

RE_QUERY = re.compile(r"Query:\s*`")
RE_VARS = re.compile(r"Variables:\s*")
RE_EXPECT_ASSIGN = re.compile(r"(\w+)\s*:?=\s*`")
RE_COMPARE = re.compile(
    r"(require\.JSONEq|testutil\.CompareJSON|JSONEqGraphQL)\(t,\s*"
)
RE_TCASE_FIELD = re.compile(r"\b(name|query|variables|respData)\s*:\s*")


def balanced_query(q: str) -> bool:
    stripped = re.sub(r"#[^\n]*", "", q)
    return stripped.count("{") == stripped.count("}") and "%s" not in q


def extract_simple(name, body, fname):
    """Sequential scan: remember the last Query/Variables literal; a
    JSONEq/CompareJSON with a literal (or raw-string var) expected
    emits a case."""
    cases = []
    svars = {}
    cur_q = None
    cur_vars = None
    i, k = 0, 0
    n = len(body)
    while i < n:
        hits = []
        for kind, rx in (
            ("q", RE_QUERY),
            ("v", RE_VARS),
            ("a", RE_EXPECT_ASSIGN),
            ("c", RE_COMPARE),
        ):
            m = rx.search(body, i)
            if m:
                hits.append((m.start(), kind, m))
        if not hits:
            break
        hits.sort(key=lambda h: h[0])
        _, kind, m = hits[0]
        if kind == "q":
            cur_q, i = read_raw(body, body.index("`", m.start()))
            cur_vars = None
        elif kind == "v":
            try:
                cur_vars, i = parse_go_value(body, m.end())
                if not isinstance(cur_vars, dict):
                    cur_vars = None
            except (Unextractable, IndexError):
                cur_vars, i = "UNEXTRACTABLE", m.end()
        elif kind == "a":
            raw, i = read_raw(body, body.index("`", m.start()))
            svars[m.group(1)] = raw
        else:  # compare
            j = m.end()
            unordered = "CompareJSON" in m.group(1)
            if body[j] == "`":
                expected, j = read_raw(body, j)
            elif body[j] == '"':
                mm = re.match(r'"((?:[^"\\]|\\.)*)"', body[j:])
                if not mm:
                    i = j
                    continue
                expected = json.loads('"' + mm.group(1) + '"')
                j += mm.end()
            else:
                mm = re.match(r"(\w+)", body[j:])
                expected = svars.get(mm.group(1)) if mm else None
                j += mm.end() if mm else 0
            i = j
            if expected is None or cur_q is None:
                continue
            if cur_vars == "UNEXTRACTABLE" or not balanced_query(cur_q):
                cur_q = None
                continue
            try:
                json.loads(expected)
            except ValueError:
                continue
            cases.append(
                {
                    "id": f"{name}/{k}",
                    "file": fname,
                    "query": cur_q,
                    "variables": cur_vars,
                    "expected": expected,
                    "unordered": unordered,
                }
            )
            k += 1
            cur_q = None
    return cases


def extract_tables(name, body, fname):
    """Table-driven: {name: "...", query: `...`, [variables: ...,]
    respData: `...`} entries, compared via tcase.respData."""
    if "tcase.respData" not in body and "tcase.expected" not in body:
        return []
    unordered = "CompareJSON" in body
    cases = []
    i, k = 0, 0
    cur = {}
    while True:
        m = RE_TCASE_FIELD.search(body, i)
        if not m:
            break
        field = m.group(1)
        j = m.end()
        try:
            if body[j] == "`":
                val, j = read_raw(body, j)
            elif body[j] == '"':
                mm = re.match(r'"((?:[^"\\]|\\.)*)"', body[j:])
                if not mm:
                    i = j
                    continue
                val = json.loads('"' + mm.group(1) + '"')
                j += mm.end()
            elif field == "variables":
                val, j = parse_go_value(body, j)
            else:
                i = j
                continue
        except (Unextractable, IndexError, ValueError):
            i = j
            cur = {}
            continue
        i = j
        if field == "name":
            cur = {"name": val}
        else:
            cur[field] = val
        if "query" in cur and "respData" in cur:
            q = cur["query"]
            exp = cur["respData"]
            ok = balanced_query(q)
            try:
                json.loads(exp)
            except ValueError:
                ok = False
            v = cur.get("variables")
            if isinstance(v, str):
                try:
                    v = json.loads(v) if v.strip() else None
                except ValueError:
                    ok = False
            if ok:
                cases.append(
                    {
                        "id": f"{name}/t{k}",
                        "file": fname,
                        "case": cur.get("name", ""),
                        "query": q,
                        "variables": v,
                        "expected": exp,
                        "unordered": unordered,
                    }
                )
                k += 1
            cur = {}
    return cases


def main():
    src = open(REF, encoding="utf-8").read()
    fname = os.path.basename(REF)
    all_cases = []
    skipped = 0
    for name, body in split_functions(src):
        if has_mutator(body):
            skipped += 1
            continue
        if "tcases" in body or "tcase." in body:
            all_cases.extend(extract_tables(name, body, fname))
        else:
            all_cases.extend(extract_simple(name, body, fname))
    with open(OUT, "w", encoding="utf-8") as f:
        json.dump(all_cases, f, indent=1)
    print(
        f"{len(all_cases)} cases extracted; {skipped} mutating funcs skipped"
    )


if __name__ == "__main__":
    main()

"""Support machinery for the GraphQL mutation-rewrite conformance suite.

The reference's mutation YAMLs assert rewriter *output* (setjson /
deletejson / upsert queries). Our architecture executes mutations
directly, so the suite checks *execution equivalence* instead: seed two
stores with the identical world the case presumes (qnametouid nodes,
filter targets, child edges named by the plan's var blocks), run our
GraphQL mutation on store A and the reference-blessed plan on store B
(through Txn.upsert_json, against our 535/535-conformant DQL engine),
then compare the resulting graphs modulo uid renaming (WL-style
canonicalization).
"""

import json
import re

from dgraph_tpu.posting.lists import LocalCache
from dgraph_tpu.types.types import TypeID
from dgraph_tpu.x import keys

# --------------------------------------------------------------------------
# Case introspection
# --------------------------------------------------------------------------

_MUT_RE = re.compile(r"\b(add|update|delete)(\w+)\s*\(")
# `Post_2 as Author.posts` / `x as updateHotel(func: ...)` var bindings
_VARBLOCK_RE = re.compile(r"(\w+)\s+as\s+([A-Z]\w*)\.(\w+)")
_UIDFUNC_RE = re.compile(r"func:\s*uid\(([^)]*)\)")


def mutation_root(case):
    """(op, TypeName) from the gql mutation text."""
    m = _MUT_RE.search(case["gqlmutation"])
    if not m:
        raise ValueError(f"no mutation field in {case['id']}")
    return m.group(1), m.group(2)


def parse_args(case):
    """Parsed root-field args via our GraphQL parser (variables folded)."""
    from dgraph_tpu.graphql.parser import parse_operation

    op = parse_operation(
        case["gqlmutation"], variables=case.get("gqlvariables")
    )
    return op.selections[0].args


# --------------------------------------------------------------------------
# Seeding
# --------------------------------------------------------------------------


def _walk_identity_objects(types, tname, obj, out):
    """Collect (TypeName, obj) for every input object in document order —
    the traversal order the reference's existence-query variable counter
    follows (mutation_rewriter.go RewriteQueries)."""
    t = types.get(tname)
    if t is None or not isinstance(obj, dict):
        return
    out.append((tname, obj))
    for k, v in obj.items():
        f = t.fields.get(k)
        if f is None or f.is_scalar:
            continue
        ct = types.get(f.type_name)
        if ct is None:
            continue
        if ct.kind == "union":
            for item in v if isinstance(v, list) else [v]:
                if isinstance(item, dict) and len(item) == 1:
                    refk, sub = next(iter(item.items()))
                    mname = refk[:-3]
                    mname = mname[0].upper() + mname[1:]
                    _walk_identity_objects(types, mname, sub, out)
            continue
        for item in v if isinstance(v, list) else [v]:
            _walk_identity_objects(types, f.type_name, item, out)


def _identity(types, tname, obj):
    """The object's external identity: {'uid': u} | {'xids': {fname: v}}
    | None (a brand-new node)."""
    t = types[tname]
    xf0 = t.xid_field()
    if (
        set(obj.keys()) == {"id"}
        and (xf0 is None or xf0.name != "id")
        and isinstance(obj.get("id"), str)
    ):
        return {"uid": obj["id"]}
    if "id" in obj and (xf0 is None or xf0.name != "id"):
        # {id: 0x1, more...}: reference-with-patch (update semantics)
        return {"uid": obj["id"]}
    xids = {
        f.name: obj[f.name]
        for f in t.fields.values()
        if f.is_id and f.name in obj
    }
    return {"xids": xids} if xids else None


def seed_objects(case, types):
    """Build the seed world (JSON set objects with explicit uids) both
    stores start from, plus the max uid used."""
    seeds = {}  # uid-int -> seed dict
    max_uid = [0x1000]

    def node(uid_hex, tname):
        u = int(uid_hex, 16)
        max_uid[0] = max(max_uid[0], u)
        if u not in seeds:
            t = types.get(tname)
            dts = [tname, *(t.interfaces if t else [])]
            seeds[u] = {"uid": uid_hex, "dgraph.type": dts}
        return seeds[u]

    op, root = mutation_root(case)
    try:
        args = parse_args(case)
    except Exception:
        args = {}

    # 1. qnametouid — referenced ids/xids the plan assumed to exist.
    # Existence-query eq vars carry (pred, value) directly; when an
    # interface-wide @id is checked the rewriter emits the SAME eq twice
    # (type-scope var then interface-scope var) — the interface var
    # alone appearing in qnametouid means the node lives in ANOTHER
    # implementing type (mutation_rewriter.go RewriteQueries).
    qn = case.get("qnametouid") or {}
    eqvars = {}
    for qk in ("dgquery",):
        for vm in re.finditer(
            r'(\w+)\(func: eq\(([\w.]+), "([^"]*)"\)\)',
            case.get(qk, ""),
        ):
            eqvars[vm.group(1)] = (vm.group(2), vm.group(3))
    handled = set()
    for qname, uid_hex in qn.items():
        if qname not in eqvars:
            continue
        pred, val = eqvars[qname]
        pre, _, num = qname.rpartition("_")
        # the rewriter emits the same eq twice for interface-wide @ids:
        # type-scope var first, interface-scope var second. This node is
        # an OTHER-implementing-type hit when (a) the twin var is absent
        # from qnametouid, or (b) both are present mapping to DIFFERENT
        # uids and this is the higher (interface) var.
        twins = [
            v2
            for v2, pv in eqvars.items()
            if pv == eqvars[qname] and v2 != qname
        ]
        other = any(
            v2 not in qn
            or (qn[v2] != uid_hex and int(num) > int(v2.rpartition("_")[2]))
            for v2 in twins
        )
        tname = pre
        if other:
            owner = pred.split(".", 1)[0]
            ot = types.get(owner)
            impls = [m for m in (ot.implementers if ot else []) if m != pre]
            if impls:
                tname = impls[0]
        nd = node(uid_hex, tname)
        nd[pred] = val
        handled.add(qname)
    qn = {k: v for k, v in qn.items() if k not in handled}
    if qn:
        inputs = []
        if op == "add":
            inputs = [
                x for x in _as_list(args.get("input")) if isinstance(x, dict)
            ]
        elif op == "update":
            # update patches are root-typed field-maps; walk them so
            # nested references get identity-matched like add inputs
            inp = args.get("input") or {}
            inputs = [
                p
                for p in (inp.get("set"), inp.get("remove"))
                if isinstance(p, dict)
            ]
        walk = []
        for obj in inputs:
            _walk_identity_objects(types, root, obj, walk)
        if op == "update":
            # the patch dicts themselves aren't input objects
            walk = [(tn, o) for tn, o in walk if o not in inputs]
        # per-type document-order lists of identity-bearing objects
        by_type = {}
        for tname, obj in walk:
            ident = _identity(types, tname, obj)
            if ident is not None:
                by_type.setdefault(tname, []).append(ident)
        # qnames per type, ordered by their numeric suffix
        qn_by_type = {}
        for qname, uid_hex in qn.items():
            pre, _, n = qname.rpartition("_")
            qn_by_type.setdefault(pre, []).append((int(n), uid_hex))
        for pre, entries in qn_by_type.items():
            entries.sort()
            idents = by_type.get(pre, [])
            for i, (_, uid_hex) in enumerate(entries):
                nd = node(uid_hex, pre)
                # attach the matching identity's xid values so the
                # existence semantics hold in the seeded world
                matched = None
                if i < len(idents):
                    matched = idents[i]
                elif idents:
                    matched = idents[-1]
                if matched and "xids" in matched:
                    t = types[pre]
                    for fn, v in matched["xids"].items():
                        nd[t.pred(fn)] = v

    # 2. filter targets (update/delete): uid lists + matching values
    uid_hexes = set()
    for qk in ("dgquery", "dgquerysec"):
        for m in _UIDFUNC_RE.finditer(case.get(qk, "")):
            for tok in m.group(1).split(","):
                tok = tok.strip()
                if tok.startswith("0x"):
                    uid_hexes.add(tok)
    fobj = None
    if op in ("update", "delete"):
        fobj = (
            (args.get("input") or {}).get("filter")
            if op == "update"
            else args.get("filter")
        )
        for u in _as_list((fobj or {}).get("id")):
            if isinstance(u, str) and u.startswith("0x"):
                uid_hexes.add(u)
    root_nodes = [node(u, root) for u in sorted(uid_hexes)]
    # make scalar filters match so the case is non-vacuous
    if fobj:
        t = types.get(root)
        for fn, spec in fobj.items():
            if fn in ("id", "and", "or", "not") or t is None:
                continue
            f = t.fields.get(fn)
            if f is None or not f.is_scalar:
                continue
            val = _filter_match_value(spec)
            if val is not None:
                for nd in root_nodes:
                    nd.setdefault(t.pred(fn), val)

    # 3. child edges named by the plan's var blocks — seed one child per
    # root so reference-cleanup deletes have something to clean
    childvars = {}
    for qk in ("dgquery", "dgquerysec"):
        for vname, tname, fname in _VARBLOCK_RE.findall(case.get(qk, "")):
            t = types.get(tname)
            f = t.fields.get(fname) if t else None
            if f is None or f.is_scalar or tname != root:
                continue
            childvars[vname] = (tname, fname, f.type_name)
    # inverse preds the plan removes from those children
    inv_preds = {}
    for m in case.get("dgmutations", []) + case.get("dgmutationssec", []):
        for entry in _as_list(m.get("delete")):
            if not isinstance(entry, dict):
                continue
            uref = entry.get("uid", "")
            if isinstance(uref, str) and uref.startswith("uid("):
                var = uref[4:-1]
                if var in childvars:
                    inv_preds.setdefault(var, []).extend(
                        k
                        for k, v in entry.items()
                        if k != "uid" and isinstance(v, dict)
                    )
    ci = 0
    for vname, (tname, fname, ctype) in childvars.items():
        t = types[tname]
        ct = types.get(ctype)
        for nd in list(root_nodes):
            ci += 1
            cu = 0x2000 + ci
            max_uid[0] = max(max_uid[0], cu)
            child = {
                "uid": hex(cu),
                "dgraph.type": [ctype, *(ct.interfaces if ct else [])],
            }
            for p in inv_preds.get(vname, []):
                child[p] = {"uid": nd["uid"]}
            seeds[cu] = child
            nd.setdefault(t.pred(fname), []).append({"uid": hex(cu)})

    return list(seeds.values()), max_uid[0]


def _filter_match_value(spec):
    if not isinstance(spec, dict):
        return None
    for k in ("eq", "le", "ge", "lt", "gt"):
        if k in spec and not isinstance(spec[k], (dict, list)):
            return spec[k]
    if "in" in spec and isinstance(spec["in"], list) and spec["in"]:
        return spec["in"][0]
    for k in ("anyofterms", "allofterms", "anyoftext", "alloftext"):
        if k in spec:
            return spec[k]
    if "between" in spec and isinstance(spec["between"], dict):
        return spec["between"].get("min")
    return None


def _as_list(x):
    if x is None:
        return []
    return x if isinstance(x, list) else [x]


def make_server(schema_sdl, max_uid=0):
    from dgraph_tpu.api.server import Server
    from dgraph_tpu.graphql import GraphQLServer

    s = Server()
    gql = GraphQLServer(s, schema_sdl)
    if max_uid:
        s.zero._max_uid = max(s.zero._max_uid, max_uid)
    return s, gql


def apply_seed(s, seeds):
    if not seeds:
        return
    t = s.new_txn()
    t.upsert_json("", [{"set": seeds}], commit_now=True)


# --------------------------------------------------------------------------
# Auth-case world builder
# --------------------------------------------------------------------------

_TYPEFUNC_RE = re.compile(r"type\((\w+)\)")
_EQ_RE = re.compile(r'eq\((\w+)\.(\w+),\s*("[^"]*"|[\w.]+)\)')
_EDGE_RE = re.compile(r"\b(\w+)\.(\w+)\b")


def _parse_lit(tok):
    if tok.startswith('"'):
        return tok[1:-1]
    if tok in ("true", "false"):
        return tok == "true"
    try:
        return int(tok)
    except ValueError:
        try:
            return float(tok)
        except ValueError:
            return tok


def auth_seed_objects(case, types):
    """A small discriminating world for an @auth golden: 2 nodes per
    referenced type; node0 carries the dgquery's eq values (rule
    matches), node1 carries mismatching values; parents link to
    children asymmetrically so auth filtering is observable."""
    text = "\n".join(
        case.get(k) or ""
        for k in ("dgquery", "dgquerysec", "authquery")
    )
    tnames = set(_TYPEFUNC_RE.findall(text))
    eqs = {}  # (type, field) -> [distinct values]
    for tn, fn, lit in _EQ_RE.findall(text):
        v = _parse_lit(lit)
        vals = eqs.setdefault((tn, fn), [])
        if v not in vals:
            vals.append(v)
    edges = set()
    for tn, fn in _EDGE_RE.findall(text):
        t = types.get(tn)
        f = t.fields.get(fn) if t else None
        if f is not None and not f.is_scalar:
            edges.add((tn, fn, f.type_name))
        if f is not None:
            tnames.add(tn)
    for tn, fn in list(eqs):
        tnames.add(tn)
    # the queried root type too
    op = case.get("gqlquery", "")
    m = re.search(r"\b(?:query|get|add|update|delete)(\w+)\s*[({]", op)
    if m and m.group(1) in types:
        tnames.add(m.group(1))
    # interfaces: include implementers so type(Interface) matches
    for tn in list(tnames):
        t = types.get(tn)
        if t is not None and t.kind == "interface":
            tnames.update(t.implementers[:1])
    nodes = {}  # (tname, idx) -> seed dict
    uid = [0x100]

    def node(tn, idx):
        if (tn, idx) not in nodes:
            t = types[tn]
            uid[0] += 1
            nd = {
                "uid": hex(uid[0]),
                "dgraph.type": [tn, *t.interfaces],
            }
            # scalar fill so cascade doesn't prune on selected fields
            for f in t.fields.values():
                if not f.is_scalar or f.type_name == "ID" or f.is_secret:
                    continue
                key = t.pred(f.name).split("@", 1)[0]
                if f.type_name == "String":
                    nd[key] = f"{f.name}_{idx}"
                elif f.type_name == "Int":
                    nd[key] = idx
                elif f.type_name == "Float":
                    nd[key] = idx + 0.5
                elif f.type_name == "Boolean":
                    nd[key] = idx == 0
                elif f.type_name == "DateTime":
                    nd[key] = f"202{idx}-01-01T00:00:00Z"
                elif f.is_enum:
                    nd.pop(key, None)
            nodes[(tn, idx)] = nd
        return nodes[(tn, idx)]

    for tn in tnames:
        if tn in types and types[tn].kind in ("type", "interface"):
            if types[tn].kind == "interface":
                continue
            node(tn, 0)
            node(tn, 1)
    # eq values: node0 matches the first value, node1 differs; each
    # EXTRA distinct value gets its own matching node (idx 10+j) so
    # rules requiring different values (EDIT vs ADMIN) both find one
    for (tn, fn), vals in eqs.items():
        t = types.get(tn)
        if t is None:
            continue
        targets = (
            t.implementers if t.kind == "interface" else [tn]
        )
        for ct in targets:
            if (ct, 0) not in nodes and ct in types:
                node(ct, 0), node(ct, 1)
            if (ct, 0) not in nodes:
                continue
            pred = f"{tn}.{fn}"
            val = vals[0]
            nodes[(ct, 0)][pred] = val
            if isinstance(val, bool):
                nodes[(ct, 1)][pred] = not val
            elif isinstance(val, str):
                nodes[(ct, 1)][pred] = "not_" + val
            else:
                nodes[(ct, 1)][pred] = val + 1
            for j, v2 in enumerate(vals[1:]):
                nd = node(ct, 10 + j)
                nd[pred] = v2
    # edges first (so literal-uid clones inherit them):
    # parent0 -> child0(+child1 for lists), parent1 -> child1
    for tn, fn, ctype in sorted(edges):
        ct = types.get(ctype)
        if ct is None or ct.kind == "union":
            continue
        f = types[tn].fields.get(fn)
        ctargets = ct.implementers if ct.kind == "interface" else [ctype]
        for cname in ctargets[:1]:
            if (cname, 0) not in nodes:
                if cname not in types:
                    continue
                node(cname, 0), node(cname, 1)
            extra = sorted(
                k for (cn2, k) in nodes if cn2 == cname and 10 <= k < 100
            )
            # extra-value PARENT nodes (idx 10+) link like parent0
            pextra = sorted(
                k for (pn2, k) in nodes if pn2 == tn and 10 <= k < 100
            )
            plan = (
                ((0, [0, 1] + extra), (1, [1]))
                if (f is not None and f.is_list)
                else ((0, [0]), (1, [1]))
            )
            plan = plan + tuple((pk, [0]) for pk in pextra)
            for idx, kids in plan:
                if (tn, idx) not in nodes:
                    continue
                pred = types[tn].pred(fn)
                for k in kids:
                    nodes[(tn, idx)].setdefault(pred, []).append(
                        {"uid": nodes[(cname, k)]["uid"]}
                    )
    # literal root uids: an existence var names the type
    # (`Project_1(func: uid(0x123))`); otherwise fall back to the
    # queried root type, carrying node0's (rule-matching) values
    root = m.group(1) if m and m.group(1) in types else None
    for um in re.finditer(
        r"(?:(\w+)_\d+(?:\s+as\s+\w+)?\()?func: uid\((0x[0-9a-fA-F, x]*)\)",
        text,
    ):
        tname2 = um.group(1) if um.group(1) in types else root
        for tok in um.group(2).split(","):
            tok = tok.strip()
            if not tok.startswith("0x") or tname2 is None:
                continue
            u = int(tok, 16)
            uid[0] = max(uid[0], u)
            if not any(nd["uid"] == tok for nd in nodes.values()):
                proto = dict(node(tname2, 0))
                proto["uid"] = tok
                nodes[(tname2, 100 + u)] = proto
    # uid references inside the case variables ({colID: "0x456"}):
    # id-field names that are unique to one type identify the node type
    idfield_owner = {}
    for tn2, t2 in types.items():
        idf = t2.id_field()
        if idf is None:
            continue
        idfield_owner.setdefault(idf.name, []).append(tn2)

    def scan_vars(v):
        if isinstance(v, dict):
            for k, x in v.items():
                owners = idfield_owner.get(k, [])
                if (
                    len(owners) == 1
                    and isinstance(x, str)
                    and x.startswith("0x")
                ):
                    u = int(x, 16)
                    uid[0] = max(uid[0], u)
                    if not any(
                        nd["uid"] == x for nd in nodes.values()
                    ):
                        tn3 = owners[0]
                        proto = dict(node(tn3, 0))
                        proto["uid"] = x
                        nodes[(tn3, 200 + u)] = proto
                scan_vars(x)
        elif isinstance(v, list):
            for x in v:
                scan_vars(x)

    scan_vars(case.get("variables") or {})
    # per-case world overrides for goldens whose reference fixture
    # mocked a specific intermediate state (e.g. "additional delete
    # fails auth": the relinked node's OLD owner must fail its rule)
    for parent, pred, child in AUTH_SEED_OVERRIDES.get(case["id"], []):
        pn = (
            next(nd for nd in nodes.values() if nd["uid"] == parent)
            if isinstance(parent, str)
            else node(*parent)
        )
        cn = (
            next(nd for nd in nodes.values() if nd["uid"] == child)
            if isinstance(child, str)
            else node(*child)
        )
        pn[pred] = [{"uid": cn["uid"]}]
    return list(nodes.values()), uid[0]


# world tweaks for mock-encoded auth cases: (case id) -> list of
# (parent node-spec, predicate, child node-spec); node-spec is a seed
# uid hex or a (Type, idx) pair — idx 0 passes the case's auth rule,
# idx 1 fails it.
AUTH_SEED_OVERRIDES = {
    # additional-delete SUCCEEDS: 0x789's old column passes auth
    "auth/update/003": [("0x789", "Ticket.onColumn", ("Column", 0))],
    # additional-delete FAILS: old column fails auth
    "auth/update/004": [("0x789", "Ticket.onColumn", ("Column", 1))],
    # single-edge variant: old column of ticket 0x123
    "auth/update/005": [("0x123", "Ticket.onColumn", ("Column", 0))],
    "auth/update/006": [("0x123", "Ticket.onColumn", ("Column", 1))],
}


# --------------------------------------------------------------------------
# State dump + canonical compare
# --------------------------------------------------------------------------


def dump_triples(s):
    """All (subj, pred, obj) in the store. obj is ('u', uid) for edges,
    ('v', typeid, normalized-value, lang) for values."""
    ts = s.zero.read_ts()
    cache = LocalCache(s.kv, ts, mem=getattr(s, "mem", None))
    out = []
    for pred in s.schema.predicates():
        su = s.schema.get(pred)
        for k, _, _ in s.kv.iterate(keys.DataPrefix(pred), ts):
            pk = keys.parse_key(k)
            if su.value_type == TypeID.UID:
                for tgt in cache.uids(k):
                    out.append((pk.uid, pred, ("u", int(tgt))))
            for p in cache.values(k):
                val = p.val()
                out.append(
                    (pk.uid, pred, ("v", int(val.tid), _norm_val(val), p.lang))
                )
    return out


def _norm_val(val):
    v = val.value
    if val.tid == TypeID.PASSWORD:
        return "<pwd>"  # salted hashes differ across stores
    if val.tid == TypeID.DATETIME:
        return getattr(v, "isoformat", lambda: str(v))()
    if isinstance(v, dict):
        return json.dumps(v, sort_keys=True)
    if isinstance(v, float):
        return f"{v:.9g}"
    if hasattr(v, "tolist"):  # vectors
        return json.dumps(
            [round(float(x), 6) for x in v.tolist()]
        )
    return str(v)


def canonicalize(triples):
    """Rewrite uids to WL-canonical labels so two isomorphic stores
    produce identical sorted triple lists."""
    nodes = set()
    for sj, _, obj in triples:
        nodes.add(sj)
        if obj[0] == "u":
            nodes.add(obj[1])
    sig = {}
    for n in nodes:
        scalars = sorted(
            (p, o[1], o[2], o[3])
            for sj, p, o in triples
            if sj == n and o[0] == "v"
        )
        sig[n] = hash(tuple(scalars))
    for _ in range(4):
        nsig = {}
        for n in nodes:
            outs = sorted(
                (p, sig[o[1]])
                for sj, p, o in triples
                if sj == n and o[0] == "u"
            )
            ins = sorted(
                (p, sig[sj])
                for sj, p, o in triples
                if o[0] == "u" and o[1] == n
            )
            nsig[n] = hash((sig[n], tuple(outs), tuple(ins)))
        sig = nsig
    order = sorted(nodes, key=lambda n: (sig[n], n))
    canon = {n: f"n{i}" for i, n in enumerate(order)}
    out = []
    for sj, p, o in triples:
        if o[0] == "u":
            out.append((canon[sj], p, ("u", canon[o[1]])))
        else:
            out.append((canon[sj], p, o))
    out.sort(key=repr)
    return out

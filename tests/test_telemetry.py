"""The telemetry plane: per-tablet traffic accounting, the traffic-
driven rebalancer, trace exemplars (OpenMetrics round-trip + slow-log
embedding), the health/SLO rollup, and degraded-scrape robustness
(partial merges + unreachable_instances with an alpha down).
"""

import json
import re
import threading
import time

import pytest

from dgraph_tpu.utils import observe
from dgraph_tpu.utils.observe import (
    METRICS,
    TABLETS,
    Metrics,
    SloWindows,
    TabletTraffic,
    parse_openmetrics_exemplars,
)


# ---------------------------------------------------------------------------
# traffic accumulator
# ---------------------------------------------------------------------------


def test_traffic_accumulator_fields_and_merge_of_shards():
    t = TabletTraffic()
    t.note_read(0, "name", 1, 100, 800, 0, 2.0)
    t.note_read(0, "name", 1, 50, 400, 0, 4.0)
    t.note_result(0, "name", 256)
    t.note_write(0, "name", 7)
    t.note_read(5, "name", 1, 1, 8, 0, 1.0)  # other namespace: own row
    t.note_write(0, "friend", 3)
    rows = {(r["ns"], r["predicate"]): r for r in t.snapshot()}
    r = rows[(0, "name")]
    assert r["reads"] == 2 and r["read_uids"] == 150
    assert r["decoded_bytes"] == 1200 and r["result_bytes"] == 256
    assert r["mutation_edges"] == 7
    # EWMA: 2.0 then +0.2*(4.0-2.0) = 2.4
    assert abs(r["lat_ewma_ms"] - 2.4) < 1e-9
    assert rows[(5, "name")]["reads"] == 1
    assert rows[(0, "friend")]["mutation_edges"] == 3
    t.clear()
    assert t.snapshot() == []


def test_query_and_mutation_feed_the_global_accumulator():
    from dgraph_tpu.api.server import Server

    TABLETS.clear()
    s = Server()
    s.alter("tname: string @index(exact) .\ntfriend: [uid] .")
    t = s.new_txn()
    t.mutate_rdf(
        set_rdf=(
            '<0x1> <tname> "A" .\n<0x2> <tname> "B" .\n'
            "<0x1> <tfriend> <0x2> ."
        ),
        commit_now=True,
    )
    s.query("{ q(func: has(tname)) { tname tfriend { uid } } }")
    rows = {r["predicate"]: r for r in TABLETS.snapshot()}
    assert rows["tname"]["mutation_edges"] >= 2
    assert rows["tfriend"]["mutation_edges"] >= 1
    assert rows["tname"]["reads"] >= 1
    assert rows["tfriend"]["reads"] >= 1
    assert rows["tfriend"]["decoded_bytes"] > 0
    assert rows["tfriend"]["result_bytes"] > 0
    assert rows["tname"]["lat_ewma_ms"] >= 0


def test_traffic_knob_off_disables_capture(monkeypatch):
    from dgraph_tpu.api.server import Server

    monkeypatch.setenv("DGRAPH_TPU_TABLET_TRAFFIC", "0")
    TABLETS.clear()
    s = Server()
    s.alter("oname: string .")
    s.new_txn().mutate_rdf(
        set_rdf='<0x1> <oname> "A" .', commit_now=True
    )
    s.query("{ q(func: has(oname)) { oname } }")
    assert TABLETS.snapshot() == []


def test_merge_tablet_rows_weighted_ewma():
    from dgraph_tpu.worker.harness import merge_tablet_rows

    a = [{"ns": 0, "predicate": "p", "reads": 9, "read_uids": 90,
          "mutation_edges": 1, "decoded_bytes": 900, "result_bytes": 90,
          "lat_ewma_ms": 1.0}]
    b = [{"ns": 0, "predicate": "p", "reads": 1, "read_uids": 10,
          "mutation_edges": 2, "decoded_bytes": 100, "result_bytes": 10,
          "lat_ewma_ms": 11.0},
         {"ns": 0, "predicate": "q", "reads": 0, "read_uids": 0,
          "mutation_edges": 5, "decoded_bytes": 0, "result_bytes": 0,
          "lat_ewma_ms": 0.0}]
    merged = {r["predicate"]: r for r in merge_tablet_rows([a, b])}
    p = merged["p"]
    assert p["reads"] == 10 and p["decoded_bytes"] == 1000
    assert p["mutation_edges"] == 3
    # read-weighted: (9*1.0 + 1*11.0) / 10 = 2.0
    assert abs(p["lat_ewma_ms"] - 2.0) < 1e-9
    assert merged["q"]["mutation_edges"] == 5
    assert merged["q"]["lat_ewma_ms"] == 0.0


# ---------------------------------------------------------------------------
# traffic-driven rebalance picking (pure, adversarial distributions)
# ---------------------------------------------------------------------------


def test_pick_by_traffic_hot_small_beats_cold_giant():
    from dgraph_tpu.worker.tabletmove import (
        pick_rebalance_move,
        pick_rebalance_move_by_traffic,
    )

    sizes = {"giant": 10_000_000, "hot": 1_000}
    tablets = {"giant": 1, "hot": 1}
    # size-based would move the giant
    assert pick_rebalance_move(sizes, tablets, [1, 2], 1) == ("giant", 2)
    traffic = {
        "hot": {"decoded_bytes": 50_000_000, "result_bytes": 5_000_000,
                "mutation_edges": 100_000},
    }
    # traffic-weighted: the hot tiny tablet carries the real load
    assert pick_rebalance_move_by_traffic(
        sizes, traffic, tablets, [1, 2], 1
    ) == ("hot", 2)


def test_pick_by_traffic_cold_cluster_degenerates_to_size():
    from dgraph_tpu.worker.tabletmove import (
        pick_rebalance_move,
        pick_rebalance_move_by_traffic,
    )

    sizes = {"a": 5000, "b": 100, "c": 40}
    tablets = {"a": 1, "b": 1, "c": 2}
    assert pick_rebalance_move_by_traffic(
        sizes, {}, tablets, [1, 2], 1
    ) == pick_rebalance_move(sizes, tablets, [1, 2], 1)


def test_pick_by_traffic_deterministic_and_balanced_noop():
    from dgraph_tpu.worker.tabletmove import pick_rebalance_move_by_traffic

    sizes = {"a": 100, "b": 100}
    tablets = {"a": 1, "b": 2}
    traffic = {
        "a": {"decoded_bytes": 1000, "result_bytes": 0,
              "mutation_edges": 0},
        "b": {"decoded_bytes": 1000, "result_bytes": 0,
              "mutation_edges": 0},
    }
    # balanced: no move; and repeat calls agree (determinism)
    for _ in range(3):
        assert pick_rebalance_move_by_traffic(
            sizes, traffic, tablets, [1, 2], 1
        ) is None


def test_traffic_window_diffs_between_rebalance_steps():
    """The rebalancer scores traffic accrued SINCE the last step, not
    lifetime totals — an old hotspot gone idle must stop out-scoring
    currently-hot tablets on later ticks."""
    from dgraph_tpu.worker.tabletmove import _traffic_window

    class FakeCluster:
        def __init__(self):
            self.rows = []

        def merged_tablets(self):
            return {"tablets": self.rows}

    c = FakeCluster()
    c.rows = [{"ns": 0, "predicate": "old_hot", "reads": 100,
               "decoded_bytes": 10_000, "result_bytes": 1000,
               "mutation_edges": 50}]
    first = _traffic_window(c)
    assert first["old_hot"]["decoded_bytes"] == 10_000  # bootstrap
    # old_hot goes idle; new_hot starts serving
    c.rows = [
        {"ns": 0, "predicate": "old_hot", "reads": 100,
         "decoded_bytes": 10_000, "result_bytes": 1000,
         "mutation_edges": 50},
        {"ns": 0, "predicate": "new_hot", "reads": 10,
         "decoded_bytes": 4_000, "result_bytes": 400,
         "mutation_edges": 0},
    ]
    second = _traffic_window(c)
    assert second["old_hot"] == {
        "decoded_bytes": 0, "result_bytes": 0, "mutation_edges": 0,
        "reads": 0,
    }
    assert second["new_hot"]["decoded_bytes"] == 4_000


def test_run_rebalance_honors_traffic_knob(monkeypatch):
    """End-to-end on the in-process cluster: a hot small tablet moves
    ahead of a cold giant one when traffic scoring is on."""
    from dgraph_tpu.worker.groups import DistributedCluster

    TABLETS.clear()
    c = DistributedCluster(n_groups=2, replicas=1, pump_ms=2)
    try:
        c.alter("hot: string @index(exact) .\ncold: string .")
        # giant cold tablet, small hot tablet — both land on group 1
        rdf = ['<0x%x> <cold> "%s" .' % (i, "x" * 256) for i in
               range(1, 120)]
        rdf.append('<0x1> <hot> "a" .')
        c.new_txn().mutate_rdf(set_rdf="\n".join(rdf), commit_now=True)
        for pred in list(c.zero.tablets):
            if c.zero.belongs_to(pred) != 1:
                c.move_tablet(pred, 1)
        TABLETS.clear()  # mutation traffic above is setup, not signal
        for _ in range(50):
            c.query("{ q(func: has(hot)) { hot } }")
        sizes = {
            "hot": c.tablet_size_bytes("hot"),
            "cold": c.tablet_size_bytes("cold"),
        }
        assert sizes["cold"] > sizes["hot"] * 10  # genuinely adversarial
        # drive reads until hot's traffic score outweighs cold's bytes
        deadline = time.time() + 30
        while time.time() < deadline:
            row = next(
                r for r in TABLETS.snapshot() if r["predicate"] == "hot"
            )
            if row["decoded_bytes"] + row["result_bytes"] > sizes["cold"]:
                break
            c.query("{ q(func: has(hot)) { hot } }")
        # size-based scoring would pick the giant...
        from dgraph_tpu.worker.tabletmove import pick_rebalance_move

        assert pick_rebalance_move(
            sizes, dict(c.zero.tablets), [1, 2], 1
        )[0] == "cold"
        # ...the traffic-driven step moves the HOT tablet instead; and
        # the knob routes run_rebalance the same way
        monkeypatch.setenv("DGRAPH_TPU_REBALANCE_BY_TRAFFIC", "1")
        from dgraph_tpu.worker.tabletmove import run_rebalance

        moved = run_rebalance(c)
        assert moved == "hot"
        assert c.zero.belongs_to("hot") == 2
    finally:
        c.close()


# ---------------------------------------------------------------------------
# exemplars
# ---------------------------------------------------------------------------


def test_exemplars_bounded_and_roundtrip():
    m = Metrics(prefix="t")
    with observe.TRACER.span("query") as sp:
        for v in (0.0004, 0.03, 0.03, 7.0, 42.0):
            m.observe("lat_seconds", v)
    text = m.render_openmetrics()
    assert text.rstrip().endswith("# EOF")
    ex = parse_openmetrics_exemplars(text)
    # one exemplar per touched bucket, all carrying OUR trace id
    assert len(ex) == 4  # 0.0005, 0.05, 10.0 and +Inf buckets
    tid = f"{sp.trace_id:032x}"
    for rec in ex.values():
        assert rec["trace_id"] == tid
        assert rec["ts"] is not None
    inf = ex['t_lat_seconds_bucket{le="+Inf"}']
    assert inf["value"] == 42.0
    # exemplar lines match the OpenMetrics grammar
    for line in text.splitlines():
        if " # " in line:
            assert re.match(
                r'^\S+\{le="[^"]+"\} \d+(\.\d+)? # '
                r'\{trace_id="[0-9a-f]{32}"\} \S+ \d+\.\d+$',
                line,
            ), line
    # bounded: the ring is one slot per bucket, repeat observations
    # replace rather than grow
    h = m._hists["lat_seconds"]
    assert len(h.exemplars) == len(h.buckets) + 1


def test_exemplars_knob_off(monkeypatch):
    monkeypatch.setenv("DGRAPH_TPU_EXEMPLARS", "0")
    m = Metrics(prefix="t2")
    with observe.TRACER.span("query"):
        m.observe("lat_seconds", 0.03)
    assert parse_openmetrics_exemplars(m.render_openmetrics()) == {}


def test_exemplars_absent_without_trace_context():
    m = Metrics(prefix="t3")
    m.observe("lat_seconds", 0.03)  # no active span
    assert parse_openmetrics_exemplars(m.render_openmetrics()) == {}


def test_slow_query_log_embeds_exemplars(tmp_path, monkeypatch):
    from dgraph_tpu.api.server import Server

    log = tmp_path / "slow.jsonl"
    monkeypatch.setenv("DGRAPH_TPU_SLOW_QUERY_LOG", str(log))
    monkeypatch.setenv("DGRAPH_TPU_SLOW_QUERY_MS", "0.0")
    s = Server()
    s.alter("sname: string .")
    s.new_txn().mutate_rdf(
        set_rdf='<0x1> <sname> "A" .', commit_now=True
    )
    s.query("{ q(func: has(sname)) { sname } }")
    rec = json.loads(log.read_text().splitlines()[-1])
    assert "exemplars" in rec
    assert rec["exemplars"], rec
    for ex in rec["exemplars"]:
        assert set(ex) == {"le", "value", "trace_id", "ts"}
        assert re.fullmatch(r"[0-9a-f]{32}", ex["trace_id"])
    # the slow query's own trace id is among the anchored buckets
    # (it was just observed into the histogram)
    assert any(
        ex["trace_id"] == rec["trace_id"] for ex in rec["exemplars"]
    )


# ---------------------------------------------------------------------------
# health / SLO
# ---------------------------------------------------------------------------


def test_slo_windows_burn_rate(monkeypatch):
    monkeypatch.setenv("DGRAPH_TPU_SLO_QUERY_MS", "100")
    monkeypatch.setenv("DGRAPH_TPU_SLO_TARGET", "0.9")
    w = SloWindows()
    for _ in range(8):
        w.note(0.05)  # good
    for _ in range(2):
        w.note(0.5)  # bad
    rep = w.report()
    assert rep["threshold_ms"] == 100.0
    m = rep["windows"]["60s"]
    assert m["total"] == 10 and m["bad"] == 2
    assert abs(m["error_rate"] - 0.2) < 1e-9
    # budget = 0.1 -> burn = 0.2 / 0.1 = 2.0
    assert abs(m["burn_rate"] - 2.0) < 1e-9
    # every window sees the same fresh data
    assert rep["windows"]["3600s"]["total"] == 10


def test_healthz_shape_and_sources():
    observe.register_health("test_source", lambda: {"x": 1})
    observe.register_health(
        "broken_source", lambda: (_ for _ in ()).throw(ValueError("boom"))
    )
    try:
        h = observe.healthz("me")
        assert h["instance"] == "me" and h["status"] == "healthy"
        assert {"admission", "commit_pipeline_depth", "slo"} <= set(h)
        assert h["sources"]["test_source"] == {"x": 1}
        assert "ValueError" in h["sources"]["broken_source"]["error"]
    finally:
        observe._HEALTH_SOURCES.pop("test_source", None)
        observe._HEALTH_SOURCES.pop("broken_source", None)


def test_distributed_cluster_health_and_tablets():
    from dgraph_tpu.worker.groups import DistributedCluster

    c = DistributedCluster(n_groups=2, replicas=3, pump_ms=2)
    try:
        c.alter("hname: string @index(exact) .")
        c.new_txn().mutate_rdf(
            set_rdf='<0x1> <hname> "A" .', commit_now=True
        )
        c.query("{ q(func: has(hname)) { hname } }")
        h = c.health()
        assert h["status"] == "healthy"
        assert set(h["groups"]) == {"1", "2"}
        for g in h["groups"].values():
            assert g["healthy"] and g["leader"] is not None
            assert len(g["replicas"]) == 3
            for r in g["replicas"].values():
                assert r["ok"] and r["applied_lag"] >= 0
        assert any(
            r["is_leader"] for r in h["groups"]["1"]["replicas"].values()
        )
        tabs = c.merged_tablets()
        assert tabs["unreachable_instances"] == []
        assert any(
            r["predicate"] == "hname" for r in tabs["tablets"]
        )
        # kill a follower: group stays healthy, replica reports down
        g1 = c.groups[1]
        lead = g1.leader()
        follower = next(n for n in g1.nodes if n.id != lead.id)
        c.kill_node(follower.id)
        h2 = c.health()
        assert h2["groups"]["1"]["replicas"][str(follower.id)]["ok"] is False
        assert h2["groups"]["1"]["healthy"]
    finally:
        c.close()


# ---------------------------------------------------------------------------
# cluster scrape: degraded-scrape robustness + merged tablets + health
# (one ProcCluster shared across the checks — spawn cost dominates)
# ---------------------------------------------------------------------------


def test_proc_cluster_telemetry_and_degraded_scrape():
    from dgraph_tpu.worker.harness import ProcCluster

    c = ProcCluster(n_groups=1, replicas=2)
    try:
        c.alter("pname: string @index(exact) .")
        c.new_txn().mutate_rdf(
            set_rdf='<0x1> <pname> "A" .\n<0x2> <pname> "B" .',
            commit_now=True,
        )
        c.query("{ q(func: has(pname)) { pname } }")

        # healthy-path: full merge, nothing unreachable
        text, unreachable = c.merged_metrics(with_meta=True)
        assert unreachable == []
        assert "dgraph_tpu_num_queries" in text
        tabs = c.merged_tablets()
        assert tabs["unreachable_instances"] == []
        assert any(r["predicate"] == "pname" for r in tabs["tablets"])
        h = c.health()
        assert h["groups"]["1"]["healthy"]
        assert h["status"] == "healthy"
        assert h["snapshot_watermark"] > 0
        assert "watermark_lag" in h
        assert h["processes"]  # per-replica healthz via debug.health
        for ph in h["processes"].values():
            assert "slo" in ph and "uptime_s" in ph
        assert "tenant_traffic" in h  # per-namespace cluster rollup

        # flight recorder, healthy path: merged digests whose call
        # counts equal the sum of the per-process scrapes (the
        # `dgraph-tpu top` contract), plus a merged history window
        dg = c.merged_digests()
        assert dg["unreachable_instances"] == []
        assert dg["digests"], "no digest rows after a live query"
        replies, unreach = c._scrape_all("debug.digests")
        assert unreach == []
        from dgraph_tpu.serving.digest import DIGESTS as _DG
        per_scrape = sum(
            r["calls"]
            for reply in replies.values()
            for r in reply.get("digests", [])
        ) + sum(r["calls"] for r in _DG.snapshot())
        assert sum(r["calls"] for r in dg["digests"]) == per_scrape
        hist = c.merged_history(window_s=600.0)
        assert hist["unreachable_instances"] == []
        assert "client" in hist["history"]
        assert set(replies) <= set(hist["history"])

        # kill one alpha mid-scrape: PARTIAL merge + the dead instance
        # named — never an exception out of the aggregation path
        victims = [
            nid for nid, cfg in c._cfgs.items()
            if not cfg.get("_module", "").endswith("zero_process")
        ]
        dead = victims[-1]
        c.kill(dead)
        text, unreachable = c.merged_metrics(with_meta=True)
        assert unreachable == [f"alpha-{dead}"]
        assert "dgraph_tpu_num_queries" in text  # partial merge intact
        spans, unreachable2 = c.merged_traces(n=50, with_meta=True)
        assert unreachable2 == [f"alpha-{dead}"]
        assert isinstance(spans, list)
        tabs = c.merged_tablets()
        assert tabs["unreachable_instances"] == [f"alpha-{dead}"]
        h2 = c.health()
        assert f"alpha-{dead}" in h2["unreachable_instances"]
        assert h2["status"] == "degraded"
        # legacy no-meta signatures still return the bare merge
        assert isinstance(c.merged_metrics(), str)
        assert isinstance(c.merged_traces(10), list)

        # flight recorder, degraded path: digests/history/bundle all
        # stay PARTIAL merges naming the dead instance — never a raise
        dg2 = c.merged_digests()
        assert dg2["unreachable_instances"] == [f"alpha-{dead}"]
        assert dg2["digests"]  # surviving rows still merged
        hist2 = c.merged_history(window_s=600.0)
        assert hist2["unreachable_instances"] == [f"alpha-{dead}"]
        assert f"alpha-{dead}" not in hist2["history"]
        bundle = c.debug_bundle(window_s=600.0)
        assert f"alpha-{dead}" in bundle["unreachable_instances"]
        assert bundle["digests"]["digests"]
        assert "dgraph_tpu_num_queries" in bundle["metrics"]
        assert bundle["health"]["status"] == "degraded"
        assert bundle["lock_graph"] and "error" not in bundle[
            "lock_graph"
        ][0]
        assert bundle["config"]["DIGEST"]["env"] == "DGRAPH_TPU_DIGEST"
    finally:
        c.close()


# ---------------------------------------------------------------------------
# traffic-driven move, end-to-end under the chaos bank
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_traffic_driven_move_under_chaos_bank():
    """The PR 10 chaos bank exercising a TRAFFIC-driven move: bank
    transfers hammer the small 'bal' tablet while a byte-giant cold
    tablet sits beside it; with drop/delay faults on the RPC plane,
    rebalance_by_traffic must move the HOT tablet (not the giant) and
    the ledger must stay exact through the move."""
    from dgraph_tpu.conn import faults
    from dgraph_tpu.conn.faults import FaultPlan
    from dgraph_tpu.conn.retry import RetryPolicy, retrying_call
    from dgraph_tpu.worker.harness import ProcCluster
    from dgraph_tpu.worker.tabletmove import (
        TabletFencedError,
        cluster_traffic_by_pred,
        pick_rebalance_move,
    )

    N_ACCOUNTS, START_BAL = 6, 100
    TABLETS.clear()
    c = ProcCluster(n_groups=2, replicas=1)
    stop = threading.Event()
    ledger = {i: START_BAL for i in range(1, N_ACCOUNTS + 1)}
    lock = threading.Lock()
    stats = {"ok": 0, "ambiguous": 0}
    try:
        c.alter("bal: int @upsert .\nblob: string .")
        rdf = [
            f'<0x{i:x}> <bal> "{START_BAL}"^^<xs:int> .'
            for i in range(1, N_ACCOUNTS + 1)
        ]
        # the cold giant: lots of bytes, no traffic after load
        rdf += [
            '<0x%x> <blob> "%s" .' % (i + 100, "z" * 512)
            for i in range(1, 200)
        ]
        c.new_txn().mutate_rdf(set_rdf="\n".join(rdf), commit_now=True)
        for pred in ("bal", "blob"):
            if c.zero.belongs_to(pred) != 1:
                c.move_tablet(pred, 1)
        TABLETS.clear()  # setup traffic is not signal

        faults.install(
            FaultPlan(
                seed=321,
                rules=[
                    dict(point="send", action="drop", p=0.02),
                    dict(point="send", action="delay", p=0.05, delay_ms=3),
                ],
            )
        )

        import numpy as np

        def writer():
            rng = np.random.default_rng(7)
            while not stop.is_set():
                frm, to = (
                    int(x) + 1
                    for x in rng.choice(N_ACCOUNTS, 2, replace=False)
                )
                amt = int(rng.integers(1, 10))
                rdf = (
                    f'<0x{frm:x}> <bal> "{ledger[frm] - amt}"^^<xs:int> .\n'
                    f'<0x{to:x}> <bal> "{ledger[to] + amt}"^^<xs:int> .'
                )
                try:
                    retrying_call(
                        lambda: c.new_txn().mutate_rdf(
                            set_rdf=rdf, commit_now=True
                        ),
                        policy=RetryPolicy(
                            base=0.02, cap=0.2, max_attempts=60
                        ),
                        retryable=(TabletFencedError,),
                    )
                    with lock:
                        ledger[frm] -= amt
                        ledger[to] += amt
                        stats["ok"] += 1
                except Exception:
                    with lock:
                        stats["ambiguous"] += 1
                time.sleep(0.005)

        th = threading.Thread(target=writer)
        th.start()
        # accumulate hot-tablet traffic: reads + the writer's mutations
        deadline = time.time() + 20
        sizes = {
            "bal": c.tablet_size_bytes("bal"),
            "blob": c.tablet_size_bytes("blob"),
        }
        assert sizes["blob"] > sizes["bal"] * 5
        while time.time() < deadline:
            c.query("{ q(func: has(bal)) { uid bal } }")
            traffic = cluster_traffic_by_pred(c)
            bal = traffic.get("bal", {})
            hot_score = (
                bal.get("decoded_bytes", 0)
                + bal.get("result_bytes", 0)
                + bal.get("mutation_edges", 0) * 64
            )
            if hot_score > sizes["blob"]:
                break
        assert hot_score > sizes["blob"], (traffic, sizes)
        # size-based scoring would move the giant...
        tablets = dict(c.zero.tablets)
        assert pick_rebalance_move(
            {p: c.tablet_size_bytes(p) for p in tablets}, tablets,
            [1, 2], 1,
        )[0] == "blob"
        # ...the traffic-driven step moves the HOT tablet instead
        moved = c.rebalance_by_traffic()
        assert moved == "bal", moved
        assert c.zero.belongs_to("bal") == 2
        assert c.zero.moves() == {}  # journal drained
        stop.set()
        th.join(timeout=30)
        faults.reset()
        out = c.query("{ q(func: has(bal)) { uid bal } }")
        bals = {int(x["uid"], 16): x["bal"] for x in out["data"]["q"]}
        assert sum(bals.values()) == N_ACCOUNTS * START_BAL, bals
        with lock:
            if stats["ambiguous"] == 0:
                assert bals == ledger, stats  # ledger-exact
        assert stats["ok"] > 0
    finally:
        stop.set()
        faults.reset()
        c.close()

"""Sanitizer builds of the native kernels (DGRAPH_TPU_NATIVE_SAN).

The randomized packed-setops equivalence corpus is the best UB probe we
have for the C++ hot paths (block-skip intersect, partial decode,
bulk reduce): it drives adversarial block alignments, UINT32_MAX uids
and empty/singleton blocks through the same ctypes bindings production
uses. Here it re-runs in a subprocess whose native .so is compiled
with -fsanitize=undefined -fno-sanitize-recover=all, so ANY signed
overflow / misaligned access / OOB shift aborts the interpreter and
fails the test. slow-marked: it recompiles the library and re-runs a
whole test module.
"""

import os
import shutil
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _san_env(mode: str) -> dict:
    env = dict(os.environ)
    env["DGRAPH_TPU_NATIVE_SAN"] = mode
    env["JAX_PLATFORMS"] = "cpu"
    env["UBSAN_OPTIONS"] = "print_stacktrace=1:halt_on_error=1"
    return env


def _native_available(env: dict) -> bool:
    r = subprocess.run(
        [
            sys.executable, "-c",
            "from dgraph_tpu import native; "
            "print(int(native.NATIVE_AVAILABLE))",
        ],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    return r.returncode == 0 and r.stdout.strip() == "1"


def test_ubsan_build_is_separate_cache_entry(tmp_path):
    if shutil.which("g++") is None:
        pytest.skip("no g++ in this environment")
    env = _san_env("ubsan")
    env["DGRAPH_TPU_NATIVE_CACHE"] = str(tmp_path)
    if not _native_available(env):
        pytest.skip("ubsan build unavailable (toolchain lacks libubsan)")
    names = os.listdir(tmp_path)
    assert any(n.endswith("-ubsan.so") for n in names), names
    assert not any(
        n.endswith(".so") and "-ubsan" not in n for n in names
    ), f"plain and sanitized builds share a cache key: {names}"


def test_packed_setops_corpus_under_ubsan():
    if shutil.which("g++") is None:
        pytest.skip("no g++ in this environment")
    env = _san_env("ubsan")  # default cache dir: reuses the -ubsan .so
    if not _native_available(env):
        pytest.skip("ubsan build unavailable (toolchain lacks libubsan)")
    r = subprocess.run(
        [
            sys.executable, "-m", "pytest",
            # test_bitmap_setops drives the adaptive-engine kernels
            # (bitmap AND/ANDNOT windows, probes, galloping merges)
            # through the same adversarial corpus; test_stream_encoder
            # covers the arena encoder entry points (enc_uid_objs /
            # enc_int_objs) incl. the INT64_MIN negation and 0xfff...
            # hex edge values; test_vector_quant drives the quantized
            # vector kernels (vec_qi8_topk / vec_qi8_topk_idx, the
            # threaded vec_qi8_topk_lists CSR scan, and the
            # vec_qi8_quantize row quantizer) through adversarial
            # scales, duplicates, tombstones, empty/aliased slices
            # test_group_commit drives the mutation write-path kernels
            # (enc_delta_records batched record serialization over the
            # randomized posting corpus incl. 0-length and max-u64
            # values, tok_terms_ascii over adversarial ASCII) through
            # their byte-equality suites; test_batch_apply drives the
            # columnar batch_apply/batch_apply_caps kernels (fused
            # tokenize + index-key emission + record encode) through
            # the randomized mixed-shape A/B byte-equality corpus
            "tests/test_packed_setops.py", "tests/test_uidpack.py",
            "tests/test_bitmap_setops.py", "tests/test_stream_encoder.py",
            "tests/test_vector_quant.py", "tests/test_group_commit.py",
            "tests/test_batch_apply.py",
            "-q", "-m", "not slow", "-p", "no:cacheprovider",
        ],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=900,
    )
    assert r.returncode == 0, (
        "packed-setops corpus failed under UBSan:\n"
        + r.stdout[-4000:] + r.stderr[-4000:]
    )

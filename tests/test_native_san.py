"""Sanitizer matrix for the native kernels (DGRAPH_TPU_NATIVE_SAN).

Three instrumented builds of codec.cpp/bulkload.cpp, each re-running
the byte-equality corpora through the same ctypes bindings production
uses:

  ubsan  -fsanitize=undefined -fno-sanitize-recover=all — any signed
         overflow / misaligned access / OOB shift aborts;
  asan   -fsanitize=address — heap/stack OOB and use-after-free in the
         kernels abort (leak checking off: the interpreter itself is
         not instrumented);
  tsan   -fsanitize=thread — data races inside the std::thread
         fan-outs (vec_qi8_topk_lists, vec_qi8_quantize, batch_apply)
         abort; the GIL is released for the whole native call, so this
         is the only tool that can see them. Runs the threaded stress
         corpus (test_native_threads.py) plus the kernels' own suites.

asan/tsan instrument a .so loaded into an UNinstrumented python, so
the matching runtime must be LD_PRELOADed; `_preload_env` resolves it
via `g++ -print-file-name=...` and the tests skip when the toolchain
lacks it. Each mode also carries a seeded-defect proof: a deliberately
racy / overflowing mini-library built the same way must make the run
FAIL — the matrix is demonstrably able to detect its defect class,
not just green by silence. All slow-marked: each mode recompiles the
library and re-runs whole test modules (tools/check.sh --san-matrix).
"""

import os
import shutil
import subprocess
import sys
import textwrap

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the byte-equality corpus: test_bitmap_setops drives the adaptive-
# engine kernels (bitmap AND/ANDNOT windows, probes, galloping merges)
# through the adversarial corpus; test_stream_encoder covers the arena
# encoder entry points (enc_uid_objs / enc_int_objs) incl. the
# INT64_MIN negation and 0xfff... hex edge values; test_vector_quant
# drives the quantized vector kernels (vec_qi8_topk / vec_qi8_topk_idx,
# the threaded vec_qi8_topk_lists CSR scan, the vec_qi8_quantize row
# quantizer) through adversarial scales, duplicates, tombstones,
# empty/aliased slices; test_group_commit drives the mutation
# write-path kernels (enc_delta_records over the randomized posting
# corpus incl. 0-length and max-u64 values, tok_terms_ascii) through
# their byte-equality suites; test_batch_apply drives the columnar
# batch_apply/batch_apply_caps kernels (fused tokenize + index-key
# emission + record encode) through the randomized mixed-shape A/B
# corpus; test_native_threads hammers the -pthread kernels from many
# Python threads at once (the TSan target shape).
_FULL_CORPUS = [
    "tests/test_packed_setops.py", "tests/test_uidpack.py",
    "tests/test_bitmap_setops.py", "tests/test_stream_encoder.py",
    "tests/test_vector_quant.py", "tests/test_group_commit.py",
    "tests/test_batch_apply.py", "tests/test_native_threads.py",
]
# tsan runs 5-20x slower, so its slice is the threaded kernels only —
# races in the single-threaded kernels are impossible by construction
# (no threads), and ubsan/asan already cover their memory behaviour
_THREADED_CORPUS = [
    "tests/test_native_threads.py", "tests/test_vector_quant.py",
    "tests/test_batch_apply.py",
]


def _runtime_lib(name: str):
    """Absolute path of the sanitizer runtime, or None if the
    toolchain doesn't ship it."""
    try:
        r = subprocess.run(
            ["g++", f"-print-file-name={name}"],
            capture_output=True, text=True, timeout=30,
        )
    except Exception:
        return None
    path = r.stdout.strip()
    return path if os.path.isabs(path) and os.path.exists(path) else None


def _san_env(mode: str) -> dict:
    env = dict(os.environ)
    env["DGRAPH_TPU_NATIVE_SAN"] = mode
    env["JAX_PLATFORMS"] = "cpu"
    env["UBSAN_OPTIONS"] = "print_stacktrace=1:halt_on_error=1"
    # the interpreter is uninstrumented: intercepted allocations can't
    # be leak-tracked meaningfully, and halt_on_error is the contract
    env["ASAN_OPTIONS"] = "detect_leaks=0:halt_on_error=1"
    # suppressions: only our .so is instrumented — XLA's uninstrumented
    # internal synchronization is invisible to TSan and reports as
    # races the moment a test dispatches real XLA work (tools/tsan.supp)
    supp = os.path.join(REPO, "tools", "tsan.supp")
    env["TSAN_OPTIONS"] = f"halt_on_error=1:suppressions={supp}"
    if mode in ("asan", "tsan"):
        lib = _runtime_lib(f"lib{mode}.so")
        if lib is None:
            pytest.skip(f"toolchain lacks lib{mode}.so")
        # co-preload libstdc++: python itself doesn't link it, so the
        # sanitizer's __cxa_throw interceptor would find no real fn at
        # init and CHECK-fail the first time jax's MLIR bindings throw
        stdcpp = _runtime_lib("libstdc++.so.6") or _runtime_lib(
            "libstdc++.so"
        )
        env["LD_PRELOAD"] = f"{lib} {stdcpp}" if stdcpp else lib
    return env


def _native_available(env: dict) -> bool:
    r = subprocess.run(
        [
            sys.executable, "-c",
            "from dgraph_tpu import native; "
            "print(int(native.NATIVE_AVAILABLE))",
        ],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    return r.returncode == 0 and r.stdout.strip() == "1"


def _require_toolchain():
    if shutil.which("g++") is None:
        pytest.skip("no g++ in this environment")


def _run_corpus(mode: str, modules, timeout=1800):
    _require_toolchain()
    env = _san_env(mode)
    if not _native_available(env):
        pytest.skip(f"{mode} build unavailable in this toolchain")
    r = subprocess.run(
        [
            sys.executable, "-m", "pytest", *modules,
            "-q", "-m", "not slow", "-p", "no:cacheprovider",
        ],
        env=env, cwd=REPO, capture_output=True, text=True,
        timeout=timeout,
    )
    assert r.returncode == 0, (
        f"corpus failed under {mode}:\n"
        + r.stdout[-4000:] + r.stderr[-4000:]
    )


def test_ubsan_build_is_separate_cache_entry(tmp_path):
    _require_toolchain()
    env = _san_env("ubsan")
    env["DGRAPH_TPU_NATIVE_CACHE"] = str(tmp_path)
    if not _native_available(env):
        pytest.skip("ubsan build unavailable (toolchain lacks libubsan)")
    names = os.listdir(tmp_path)
    assert any(n.endswith("-ubsan.so") for n in names), names
    assert not any(
        n.endswith(".so") and "-ubsan" not in n for n in names
    ), f"plain and sanitized builds share a cache key: {names}"


def test_packed_setops_corpus_under_ubsan():
    _run_corpus("ubsan", _FULL_CORPUS, timeout=900)


def test_corpus_under_asan():
    _run_corpus("asan", _FULL_CORPUS, timeout=1800)


def test_threaded_corpus_under_tsan():
    _run_corpus("tsan", _THREADED_CORPUS, timeout=1800)


# ---------------------------------------------------------------------------
# seeded-defect proofs: the matrix must DETECT, not just stay green
# ---------------------------------------------------------------------------

_RACY_CPP = """
#include <cstdint>
#include <thread>

extern "C" int64_t racy_count(int64_t iters) {
    int64_t shared = 0;  // unsynchronized: both threads hammer it
    auto body = [&]() {
        for (int64_t i = 0; i < iters; i++) shared++;
    };
    std::thread a(body), b(body);
    a.join();
    b.join();
    return shared;
}
"""

_OOB_CPP = """
#include <cstdint>

extern "C" int64_t oob_read(int64_t n) {
    int64_t* buf = new int64_t[n];
    for (int64_t i = 0; i < n; i++) buf[i] = i;
    int64_t got = buf[n];  // one past the end
    delete[] buf;
    return got;
}
"""


def _seeded_defect_run(tmp_path, mode: str, cpp: str, fn: str, arg: int):
    """Build a mini .so the exact way native/_build_and_load does
    (same flags, uninstrumented python + LD_PRELOAD), call the seeded
    function through ctypes, and return the subprocess result."""
    _require_toolchain()
    env = _san_env(mode)
    src = tmp_path / "seeded.cpp"
    so = tmp_path / "seeded.so"
    src.write_text(textwrap.dedent(cpp))
    flags = {
        "tsan": ["-fsanitize=thread"],
        "asan": ["-fsanitize=address"],
    }[mode]
    r = subprocess.run(
        [
            "g++", "-O1", "-shared", "-fPIC", "-std=c++17", "-pthread",
            *flags, "-o", str(so), str(src),
        ],
        capture_output=True, text=True, timeout=120,
    )
    if r.returncode != 0:
        pytest.skip(f"{mode} compile unavailable: {r.stderr[-500:]}")
    return subprocess.run(
        [
            sys.executable, "-c",
            "import ctypes, sys; "
            f"lib = ctypes.CDLL({str(so)!r}); "
            f"lib.{fn}.restype = ctypes.c_int64; "
            f"lib.{fn}.argtypes = [ctypes.c_int64]; "
            f"print(lib.{fn}({arg}))",
        ],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=300,
    )


def test_tsan_detects_seeded_race(tmp_path):
    r = _seeded_defect_run(tmp_path, "tsan", _RACY_CPP,
                           "racy_count", 200000)
    assert r.returncode != 0, (
        "TSan missed a seeded data race — the matrix is blind:\n"
        + r.stdout[-2000:] + r.stderr[-2000:]
    )
    assert "data race" in (r.stdout + r.stderr).lower()


def test_asan_detects_seeded_overflow(tmp_path):
    r = _seeded_defect_run(tmp_path, "asan", _OOB_CPP, "oob_read", 64)
    assert r.returncode != 0, (
        "ASan missed a seeded heap overflow — the matrix is blind:\n"
        + r.stdout[-2000:] + r.stderr[-2000:]
    )
    assert "heap-buffer-overflow" in (r.stdout + r.stderr)

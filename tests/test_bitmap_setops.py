"""Randomized equivalence suite for the adaptive set-representation
engine (bitmap/packed hybrid containers).

The per-block-pair kernels (native/codec.cpp pack_pair_setop /
pack_stream_setop) must be byte-identical to the decoded reference path
across every container mix: bitmap ^ bitmap (word-wise AND/ANDNOT),
bitmap x packed probes, and packed x packed galloping merges — including
32-bit segment boundaries, UINT32_MAX as a legal UID, all-dense blocks,
adversarial packed/bitmap mixes inside one operand, and container
conversion round-trips (in-memory sidecar and on-disk bitset form).
Mirrors tests/test_packed_setops.py for the bitmap paths; re-run under
UBSan by tests/test_native_san.py.
"""

import numpy as np
import pytest

from dgraph_tpu.codec import uidpack
from dgraph_tpu.ops import packed_setops as ps
from dgraph_tpu.query.dispatch import PackedOperand, SetOpDispatcher


def _dense_run(rng, hi, max_len=3000):
    start = int(rng.integers(0, max(1, hi - max_len - 1)))
    n = int(rng.integers(1, max_len))
    run = np.arange(start, start + n, dtype=np.uint64)
    if rng.integers(0, 2):
        # punch random holes: still dense enough for bitmap eligibility
        keep = rng.random(n) > 0.3
        run = run[keep] if keep.any() else run[:1]
    return run


def _sparse(rng, hi, n):
    return np.unique(rng.integers(1, hi, size=max(1, n), dtype=np.uint64))


def _mixed(rng, hi, n):
    """Adversarial operand: dense runs (bitmap blocks) interleaved with
    sparse spans (packed blocks) in ONE uid set."""
    parts = [_sparse(rng, hi, n)]
    for _ in range(int(rng.integers(1, 4))):
        parts.append(_dense_run(rng, hi))
    return np.unique(np.concatenate(parts))


def _check_all(a, b):
    """Engine results (pack x pack, array x pack, both ops + membership)
    == numpy exact, regardless of which per-block kernels fire."""
    pa, pb = uidpack.encode(a), uidpack.encode(b)
    want_i = np.intersect1d(a, b, assume_unique=True)
    want_d = np.setdiff1d(a, b, assume_unique=True)
    np.testing.assert_array_equal(ps.intersect_packed(a, pb), want_i)
    np.testing.assert_array_equal(ps.intersect_packed(pa, pb), want_i)
    np.testing.assert_array_equal(ps.difference_packed(a, pb), want_d)
    np.testing.assert_array_equal(ps.difference_packed(pa, pb), want_d)
    np.testing.assert_array_equal(
        ps.membership_packed(a, pb), np.isin(a, b, assume_unique=True)
    )
    if ps.engine_available():
        # drive the pair/stream engines directly too: the public entry
        # points take the small-frontier path for tiny operands, which
        # would leave the block kernels uncovered on small inputs
        got = ps._pair_engine(0, pa, pb)
        np.testing.assert_array_equal(got, want_i)
        np.testing.assert_array_equal(ps._pair_engine(1, pa, pb), want_d)
        np.testing.assert_array_equal(ps._stream_engine(0, a, pb), want_i)
        np.testing.assert_array_equal(ps._stream_engine(1, a, pb), want_d)


@pytest.mark.parametrize("seed", range(12))
def test_randomized_container_mixes(seed):
    rng = np.random.default_rng(seed)
    hi = int(rng.choice([1 << 14, 1 << 20, 1 << 32, 1 << 34, 1 << 45]))
    a = _mixed(rng, hi, int(rng.integers(0, 20000)))
    b = _mixed(rng, hi, int(rng.integers(0, 20000)))
    if seed % 2 and len(b):
        # force heavy overlap so results are non-trivial
        a = np.unique(
            np.concatenate(
                [a, rng.choice(b, min(len(b), 500), replace=False)]
            )
        )
    _check_all(a, b)


def test_all_dense_blocks_use_bitmap_kernel():
    """Two fully dense operands: every overlapping block pair must run
    the bitmap AND kernel — zero decoded bytes, zero gallop merges."""
    rng = np.random.default_rng(42)
    base = 7 << 32
    pool = np.arange(base, base + 100_000, dtype=np.uint64)
    a = np.sort(rng.choice(pool, 80_000, replace=False))
    b = np.sort(rng.choice(pool, 75_000, replace=False))
    pa, pb = uidpack.encode(a), uidpack.encode(b)
    assert uidpack.bitmap_eligible(pa).all()
    assert uidpack.bitmap_eligible(pb).all()
    if not ps.engine_available():
        pytest.skip("native engine unavailable")
    ps.reset_counters()
    got = ps.intersect_packed(pa, pb)
    np.testing.assert_array_equal(
        got, np.intersect1d(a, b, assume_unique=True)
    )
    c = ps.counters()
    assert c["bitmap_pairs"] > 0 and c["gallop_pairs"] == 0, c
    assert c["decoded_bytes"] == 0, c
    # ANDNOT: same pairs, difference op
    ps.reset_counters()
    got = ps.difference_packed(pa, pb)
    np.testing.assert_array_equal(
        got, np.setdiff1d(a, b, assume_unique=True)
    )
    assert ps.counters()["bitmap_pairs"] > 0


def test_sparse_blocks_use_gallop_kernel():
    rng = np.random.default_rng(43)
    a = _sparse(rng, 1 << 33, 50_000)
    b = _sparse(rng, 1 << 33, 60_000)
    pa, pb = uidpack.encode(a), uidpack.encode(b)
    assert not uidpack.bitmap_eligible(pb).any()
    if not ps.engine_available():
        pytest.skip("native engine unavailable")
    ps.reset_counters()
    got = ps.intersect_packed(pa, pb)
    np.testing.assert_array_equal(
        got, np.intersect1d(a, b, assume_unique=True)
    )
    c = ps.counters()
    assert c["gallop_pairs"] > 0 and c["bitmap_pairs"] == 0, c
    assert c["decoded_bytes"] == 0, c


def test_mixed_operand_runs_probe_kernel():
    """Dense operand vs sparse operand over the same range: overlapping
    pairs mix containers, so the bitmap-probe kernel must fire."""
    rng = np.random.default_rng(44)
    dense = np.arange(1 << 20, (1 << 20) + 60_000, dtype=np.uint64)
    sparse = np.unique(
        rng.integers(1 << 20, (1 << 20) + 60_000, 2000, dtype=np.uint64)
    )
    pd, psp = uidpack.encode(dense), uidpack.encode(sparse)
    if not ps.engine_available():
        pytest.skip("native engine unavailable")
    ps.reset_counters()
    got = ps._pair_engine(0, psp, pd)
    np.testing.assert_array_equal(
        got, np.intersect1d(sparse, dense, assume_unique=True)
    )
    assert ps.counters()["probe_pairs"] > 0, ps.counters()


def test_segment_boundaries_and_sentinels():
    """Hi-32 boundary straddles, UINT32_MAX lo words, the all-ones UID,
    and dense runs hugging those boundaries are all exact."""
    m = 0xFFFFFFFF
    edge = np.array(
        [1, m, 1 << 32, (1 << 32) | m, 2 << 32, (1 << 64) - 1], np.uint64
    )
    run_at_boundary = np.arange(
        (1 << 32) - 1000, (1 << 32) + 1000, dtype=np.uint64
    )
    top_run = np.arange(
        (1 << 64) - 2000, (1 << 64) - 1, dtype=np.uint64
    )
    a = np.unique(np.concatenate([edge, run_at_boundary]))
    b = np.unique(np.concatenate([run_at_boundary[::2], top_run, edge[:3]]))
    _check_all(a, b)
    _check_all(b, a)
    _check_all(top_run, np.unique(np.concatenate([top_run[::3], edge])))


def test_empty_singleton_and_disjoint():
    empty = np.zeros((0,), np.uint64)
    one = np.array([7], np.uint64)
    run = np.arange(100, 400, dtype=np.uint64)
    _check_all(empty, run)
    _check_all(run, empty)
    _check_all(one, run)
    _check_all(run, one)
    # fully disjoint dense runs: block ranges never overlap -> pure skip
    _check_all(run, run + np.uint64(10_000))


def test_adversarial_block_alignment():
    """Block-boundary elements, interleaved evens/odds (every block
    overlaps, nothing matches), and runs that straddle the bitmap
    eligibility threshold exactly."""
    bs = uidpack.BLOCK_SIZE
    b = np.arange(1, 10 * bs + 1, dtype=np.uint64)
    _check_all(b[::bs].copy(), b)
    evens = np.arange(0, 4 * bs, 2, dtype=np.uint64)
    odds = np.arange(1, 4 * bs, 2, dtype=np.uint64)
    _check_all(evens, odds)
    if uidpack.BITMAP_BITS:
        # stride exactly at the eligibility edge: range == BITMAP_BITS-1
        # (eligible) vs range == BITMAP_BITS (not)
        step = max(1, (uidpack.BITMAP_BITS - 1) // (bs - 1))
        at_edge = np.arange(0, bs, dtype=np.uint64) * np.uint64(step)
        over_edge = at_edge.copy()
        over_edge[-1] = np.uint64(uidpack.BITMAP_BITS)
        _check_all(at_edge, over_edge)


def test_sidecar_conversion_roundtrip():
    """block_bitmaps <-> offsets conversions are exact, the sidecar is
    cached on the pack, and the compact layout only pays for eligible
    blocks."""
    rng = np.random.default_rng(45)
    u = _mixed(rng, 1 << 34, 5000)
    p = uidpack.encode(u)
    words, rows, ok = uidpack.block_bitmaps(p)
    assert uidpack.block_bitmaps(p) is p._bm  # cached
    np.testing.assert_array_equal(ok, uidpack.bitmap_eligible(p))
    if words is None:
        assert rows is None and not ok.any()
        return
    # compact: one row per eligible block, indirection covers the rest
    assert words.shape == (int(ok.sum()), uidpack.BITMAP_WORDS)
    np.testing.assert_array_equal(rows >= 0, ok)
    for bi in np.flatnonzero(ok):
        c = int(p.counts[bi])
        offs = p.offsets[bi, :c]
        row = words[int(rows[bi])]
        np.testing.assert_array_equal(
            uidpack.bitmap_to_offsets(row, uidpack.BITMAP_BITS), offs
        )
        np.testing.assert_array_equal(
            uidpack.offsets_to_bitmap(offs, uidpack.BITMAP_BITS), row
        )


@pytest.mark.parametrize("seed", range(6))
def test_serialized_bitmap_container_roundtrip(seed):
    """Dense blocks serialize as raw bitsets (smaller than bit-packed
    offsets) and deserialize byte-exactly; sparse blocks keep the packed
    form in the same record."""
    rng = np.random.default_rng(seed + 77)
    u = _mixed(rng, 1 << 34, 4000)
    p = uidpack.encode(u)
    data = uidpack.serialize(p)
    back = uidpack.deserialize(data)
    np.testing.assert_array_equal(uidpack.decode(back), u)
    # a fully dense list must beat the packed-only encoding clearly
    dense = np.arange(1 << 20, (1 << 20) + 10_000, dtype=np.uint64)
    blob = uidpack.serialize(uidpack.encode(dense))
    if uidpack.BITMAP_BITS:
        assert len(blob) < len(dense)  # < 1 byte/uid (packed form is >= 1)
    np.testing.assert_array_equal(
        uidpack.decode(uidpack.deserialize(blob)), dense
    )
    # serialize_uids stays wire-compatible for the single-block fast path
    small_dense = np.arange(500, 700, dtype=np.uint64)
    np.testing.assert_array_equal(
        uidpack.decode(
            uidpack.deserialize(uidpack.serialize_uids(small_dense))
        ),
        small_dense,
    )


def test_deserialize_rejects_corrupt_bitmap_block():
    dense = np.arange(0, 2000, dtype=np.uint64)
    data = bytearray(uidpack.serialize(uidpack.encode(dense)))
    # flip a payload bit: popcount no longer matches the block count
    data[-1] ^= 0x01
    with pytest.raises(ValueError):
        uidpack.deserialize(bytes(data))


def test_python_fallback_equivalence(monkeypatch):
    """With the native lib masked out, the packed ops fall back to the
    candidate-block decode path (and the numpy sidecar builder) and stay
    element-exact."""
    from dgraph_tpu import native

    rng = np.random.default_rng(46)
    a = _mixed(rng, 1 << 33, 3000)
    b = _mixed(rng, 1 << 33, 8000)
    monkeypatch.setattr(native, "_LIB", None)
    monkeypatch.setattr(native, "NATIVE_AVAILABLE", False)
    assert not ps.engine_available()
    _check_all(a, b)
    # numpy bitmap builder matches the native one bit-for-bit
    p = uidpack.encode(np.arange(10, 3000, 3, dtype=np.uint64))
    words_py, rows_py, ok_py = uidpack.block_bitmaps(p)
    monkeypatch.undo()
    p2 = uidpack.encode(np.arange(10, 3000, 3, dtype=np.uint64))
    words_nat, rows_nat, ok_nat = uidpack.block_bitmaps(p2)
    np.testing.assert_array_equal(ok_py, ok_nat)
    if words_py is not None:
        np.testing.assert_array_equal(rows_py, rows_nat)
        np.testing.assert_array_equal(words_py, words_nat)


def test_dispatcher_dense_pair_stays_compressed():
    """The old whole-operand PACKED_MIN_RATIO cliff is gone for
    pack x pack pairs: a ratio~1 dense pair runs the per-block engine
    with ZERO decoded bytes instead of falling back to full decode."""
    if not ps.engine_available():
        pytest.skip("native engine unavailable")
    rng = np.random.default_rng(47)
    base = 3 << 33
    pool = np.arange(base, base + 200_000, dtype=np.uint64)
    a = np.sort(rng.choice(pool, 90_000, replace=False))
    b = np.sort(rng.choice(pool, 100_000, replace=False))
    d = SetOpDispatcher()
    for op, want in (
        ("intersect", np.intersect1d(a, b, assume_unique=True)),
        ("difference", np.setdiff1d(a, b, assume_unique=True)),
    ):
        ps.reset_counters()
        got = d.run_pairs(
            op,
            [(PackedOperand(uidpack.encode(a)), PackedOperand(uidpack.encode(b)))],
        )[0]
        np.testing.assert_array_equal(got, want)
        c = ps.counters()
        assert c["packed_ops"] == 1 and c["decoded_bytes"] == 0, (op, c)
        assert c["bitmap_pairs"] > 0, (op, c)

"""DQL parser tests (mirrors a subset of /root/reference/dql/parser_test.go)."""

import pytest

from dgraph_tpu.dql.parser import ParseError, parse


def test_basic_block():
    q = """
    {
      people(func: eq(name, "Alice"), first: 10, offset: 2) {
        name
        age
      }
    }
    """
    blocks = parse(q)
    assert len(blocks) == 1
    b = blocks[0]
    assert b.attr == "people"
    assert b.func.name == "eq"
    assert b.func.attr == "name"
    assert b.func.args == ["Alice"]
    assert b.first == 10 and b.offset == 2
    assert [c.attr for c in b.children] == ["name", "age"]


def test_filter_tree():
    q = """
    {
      q(func: has(name)) @filter((gt(age, 18) OR has(friend)) AND NOT eq(name, "X")) {
        name
      }
    }
    """
    b = parse(q)[0]
    t = b.filter
    assert t.op == "and"
    assert t.children[0].op == "or"
    assert t.children[1].op == "not"
    assert t.children[1].children[0].func.name == "eq"


def test_nested_children_alias_pagination():
    q = """
    {
      q(func: uid(0x1)) {
        buddies: friend (first: 5, orderasc: name) @filter(lt(age, 30)) {
          name
          uid
        }
        c: count(friend)
        total: count(uid)
      }
    }
    """
    b = parse(q)[0]
    assert b.func.name == "uid" and b.func.args == [1]
    f = b.children[0]
    assert f.alias == "buddies" and f.attr == "friend"
    assert f.first == 5 and f.order[0].attr == "name" and not f.order[0].desc
    assert f.filter.func.name == "lt"
    assert f.children[1].is_uid
    c = b.children[1]
    assert c.is_count and c.attr == "friend" and c.alias == "c"
    t = b.children[2]
    assert t.is_count and t.attr == "uid" and t.alias == "total"


def test_vars_and_val():
    q = """
    {
      var(func: has(age)) {
        a as age
        f as friend
      }
      q(func: uid(f), orderdesc: val(a)) {
        name
        val(a)
        total: sum(val(a))
      }
    }
    """
    blocks = parse(q)
    assert blocks[0].is_var_block
    assert blocks[0].children[0].var_name == "a"
    assert blocks[1].func.uid_var == "f"
    assert blocks[1].order[0].val_var == "a"
    assert blocks[1].children[1].val_var == "a"
    assert blocks[1].children[2].aggregator == "sum"


def test_similar_to_options():
    q = """
    {
      v(func: similar_to(embedding, 5, "[0.1, 0.2]", ef: 20)) { uid }
    }
    """
    b = parse(q)[0]
    fn = b.func
    assert fn.name == "similar_to"
    assert fn.attr == "embedding"
    assert fn.args[0] == 5
    assert fn.options.get("ef") == 20


def test_between_regexp_terms():
    q = """
    {
      a(func: between(age, 18, 30)) { uid }
      b(func: regexp(name, /ali.*/i)) { uid }
      c(func: anyofterms(name, "alice bob")) { uid }
      d(func: type(Person)) { uid }
    }
    """
    blocks = parse(q)
    assert blocks[0].func.args == [18, 30]
    assert blocks[1].func.args == [("regex", "ali.*", "i")]
    assert blocks[2].func.args == ["alice bob"]
    assert blocks[3].func.attr == "Person"


def test_recurse_cascade_facets():
    q = """
    {
      q(func: uid(1)) @recurse(depth: 3, loop: true) @cascade {
        name
        friend @facets(since) @facets(orderasc: weight)
      }
    }
    """
    b = parse(q)[0]
    assert b.recurse and b.recurse_depth == 3 and b.recurse_loop
    assert b.cascade
    f = b.children[1]
    assert f.facets and "since" in f.facet_names
    assert f.facet_order == "weight"


def test_shortest_path_block():
    q = """
    {
      path as shortest(from: 0x1, to: 0x2, numpaths: 2) {
        friend
      }
      sp(func: uid(path)) { name }
    }
    """
    blocks = parse(q)
    assert blocks[0].attr == "shortest"
    assert blocks[0].shortest_from == 1
    assert blocks[0].shortest_to == 2
    assert blocks[0].num_paths == 2
    assert blocks[0].var_name == "path"


def test_lang_tag_and_expand():
    q = """
    {
      q(func: eq(name@en, "Alice")) {
        name@en
        expand(_all_) { name }
      }
    }
    """
    b = parse(q)[0]
    assert b.func.lang == "en"
    assert b.children[0].lang == "en"
    assert b.children[1].expand == "_all_"


def test_errors():
    with pytest.raises(ParseError):
        parse("{ q(func: eq(name, ) { } }")
    with pytest.raises(ParseError):
        parse("not a query")
    with pytest.raises(ParseError):
        parse("{ q(func: frobnicate(name)) { uid } } trailing")

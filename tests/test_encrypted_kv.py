"""At-rest KV encryption (ref enc/util.go + badger encryption plumbing)."""

import os

import pytest

# the encryption stack needs the optional cryptography module; a box
# without it SKIPS these tests instead of erroring at collection
pytest.importorskip("cryptography")

from dgraph_tpu.storage.encrypted import EncryptedKV
from dgraph_tpu.storage.kv import MemKV

KEY = b"0123456789abcdef"  # AES-128


def test_values_sealed_in_backing_store(tmp_path):
    inner = MemKV(wal_path=str(tmp_path / "wal.log"))
    kv = EncryptedKV(inner, KEY)
    kv.put(b"k", 5, b"super-secret-posting")
    # plaintext round-trips through the wrapper
    assert kv.get(b"k", 10) == (5, b"super-secret-posting")
    assert kv.versions(b"k", 10)[0][1] == b"super-secret-posting"
    # ...but the backing store and its WAL never see it
    raw = inner.get(b"k", 10)[1]
    assert b"super-secret" not in raw
    kv.sync()
    wal = (tmp_path / "wal.log").read_bytes()
    assert b"super-secret" not in wal
    # distinct IVs: same value twice -> different ciphertexts
    kv.put(b"k2", 5, b"super-secret-posting")
    assert inner.get(b"k2", 10)[1] != raw


def test_engine_on_encrypted_lsm(tmp_path, monkeypatch):
    """lsm + enc_key: nothing — values OR index tokens — on disk in
    plaintext, across WAL, SSTables, and restart."""
    monkeypatch.setenv("DGRAPH_TPU_STORAGE", "lsm")
    from dgraph_tpu.api.server import Server

    d = str(tmp_path / "p")
    s = Server(data_dir=d, encryption_key=KEY)
    s.alter("name: string @index(exact) .")
    s.new_txn().mutate_rdf(set_rdf='_:a <name> "enc-alice" .', commit_now=True)
    out = s.query('{ q(func: eq(name, "enc-alice")) { name } }')
    assert out["data"]["q"][0]["name"] == "enc-alice"
    s.kv.flush()
    s.kv.close()
    for root, _, files in os.walk(d):
        for fn in files:
            blob = open(os.path.join(root, fn), "rb").read()
            assert b"enc-alice" not in blob, fn
            if fn != "MANIFEST":
                assert b"name" not in blob, fn  # predicate names sealed too
    s2 = Server(data_dir=d, encryption_key=KEY)
    out = s2.query('{ q(func: eq(name, "enc-alice")) { name } }')
    assert out["data"]["q"][0]["name"] == "enc-alice"
    s2.kv.close()


def test_wrong_key_size_rejected():
    with pytest.raises(ValueError):
        EncryptedKV(MemKV(), b"short")

"""GraphQL layer tests: SDL schema gen, generated API, mutations, filters.

Mirrors the shape of /root/reference/graphql/resolve tests and e2e suites.
"""

import pytest

from dgraph_tpu.api.server import Server
from dgraph_tpu.graphql import GraphQLServer

SDL = """
type Author {
  id: ID!
  name: String! @search(by: [term, exact])
  email: String @id
  age: Int @search
  posts: [Post] @hasInverse(field: "author")
}

type Post {
  id: ID!
  title: String! @search(by: [term])
  score: Float @search
  published: Boolean @search
  author: Author
}
"""


@pytest.fixture()
def gql():
    return GraphQLServer(Server(), SDL)


def test_sdl_to_dql_schema(gql):
    su = gql.engine.schema.get("Author.name")
    assert su is not None and su.tokenizers == ["term", "exact"]
    assert gql.engine.schema.get("Author.email").upsert
    assert gql.engine.schema.get("Author.posts").is_list
    assert gql.engine.schema.get("Post.author") is not None
    tu = gql.engine.schema.get_type("Author")
    assert "Author.name" in tu.fields


def test_add_and_query(gql):
    res = gql.execute(
        """
        mutation {
          addAuthor(input: [
            {name: "Jane", age: 40, posts: [{title: "Hello world"}]},
            {name: "Bob", age: 20}
          ]) {
            numUids
            author { name age posts { title } }
          }
        }
        """
    )
    assert "errors" not in res, res
    out = res["data"]["addAuthor"]
    # numUids counts nested creates too (2 authors + 1 post)
    assert out["numUids"] == 3
    janes = [a for a in out["author"] if a["name"] == "Jane"]
    assert janes[0]["posts"][0]["title"] == "Hello world"

    res = gql.execute(
        """
        query {
          queryAuthor(filter: {name: {anyofterms: "jane"}}) {
            name
            age
            posts { title author { name } }
          }
        }
        """
    )
    q = res["data"]["queryAuthor"]
    assert q[0]["name"] == "Jane"
    # @hasInverse wired both directions
    # non-list field `author: Author` returns an object (ref GraphQL shape)
    assert q[0]["posts"][0]["author"]["name"] == "Jane"


def test_filters_order_pagination(gql):
    gql.execute(
        """
        mutation {
          addAuthor(input: [
            {name: "A1", age: 10}, {name: "A2", age: 20},
            {name: "A3", age: 30}, {name: "A4", age: 40}
          ]) { numUids }
        }
        """
    )
    res = gql.execute(
        """
        query {
          queryAuthor(
            filter: {age: {ge: 20}},
            order: {desc: age}, first: 2
          ) { name age }
        }
        """
    )
    assert [a["age"] for a in res["data"]["queryAuthor"]] == [40, 30]
    res = gql.execute(
        """
        query {
          queryAuthor(filter: {and: [{age: {gt: 15}}, {age: {lt: 35}}]}) {
            age
          }
        }
        """
    )
    assert sorted(a["age"] for a in res["data"]["queryAuthor"]) == [20, 30]


def test_get_by_id_and_xid(gql):
    res = gql.execute(
        'mutation { addAuthor(input: [{name: "X", email: "x@y.z"}]) '
        "{ author { id } } }"
    )
    uid = res["data"]["addAuthor"]["author"][0]["id"]
    res = gql.execute(f'query {{ getAuthor(id: "{uid}") {{ name }} }}')
    assert res["data"]["getAuthor"]["name"] == "X"
    res = gql.execute('query { getAuthor(email: "x@y.z") { name } }')
    assert res["data"]["getAuthor"]["name"] == "X"


def test_update_and_delete(gql):
    gql.execute(
        'mutation { addAuthor(input: [{name: "U", age: 1}]) { numUids } }'
    )
    res = gql.execute(
        """
        mutation {
          updateAuthor(input: {
            filter: {name: {eq: "U"}}, set: {age: 99}
          }) { numUids author { name age } }
        }
        """
    )
    assert res["data"]["updateAuthor"]["author"][0]["age"] == 99
    res = gql.execute(
        'mutation { deleteAuthor(filter: {name: {eq: "U"}}) { msg numUids } }'
    )
    assert res["data"]["deleteAuthor"]["numUids"] == 1
    res = gql.execute('query { queryAuthor(filter: {name: {eq: "U"}}) { name } }')
    assert res["data"]["queryAuthor"] == []


def test_aggregate_and_variables(gql):
    gql.execute(
        'mutation { addAuthor(input: [{name: "V1"}, {name: "V2"}]) { numUids } }'
    )
    res = gql.execute("query { aggregateAuthor { count } }")
    assert res["data"]["aggregateAuthor"]["count"] >= 2
    res = gql.execute(
        "query q($n: String!) { queryAuthor(filter: {name: {eq: $n}}) { name } }",
        variables={"n": "V1"},
    )
    assert res["data"]["queryAuthor"] == [{"name": "V1"}]


def test_xid_dedup_on_add(gql):
    gql.execute(
        'mutation { addAuthor(input: [{name: "D", email: "d@d"}]) { numUids } }'
    )
    # a second add with the same @id errors (ref mutation_rewriter.go
    # "id ... already exists") unless upsert: true, which updates
    res = gql.execute(
        'mutation { addAuthor(input: [{name: "D2", email: "d@d"}]) { numUids } }'
    )
    assert res.get("errors"), res
    gql.execute(
        'mutation { addAuthor(input: [{name: "D2", email: "d@d"}], '
        "upsert: true) { numUids } }"
    )
    res = gql.execute('query { queryAuthor(filter: {has: ["email"]}) { name } }')
    names = [a["name"] for a in res["data"]["queryAuthor"]]
    assert names == ["D2"]  # upsert updated the same node


def test_add_rejects_explicit_null_for_required_field(gql):
    res = gql.execute(
        'mutation { addAuthor(input: [{name: null, email: "n@n"}]) '
        "{ numUids } }"
    )
    assert res.get("errors"), res


def test_union_remove_does_not_create():
    gql = GraphQLServer(
        Server(),
        """
        type Dog { dname: String! @id }
        type Cat { cname: String! @id }
        union Pet = Dog | Cat
        type Person {
          id: ID!
          pname: String
          pet: Pet
        }
        """,
    )
    res = gql.execute(
        "mutation { updatePerson(input: {filter: {}, "
        'remove: {pet: {dogRef: {dname: "Ghost"}}}}) { numUids } }'
    )
    # removing a non-existent union member must not create it
    q = gql.execute("query { queryDog { dname } }")
    assert not (q["data"] or {}).get("queryDog"), (res, q)


def test_error_envelope(gql):
    res = gql.execute("query { queryNope { x } }")
    assert res["errors"][0]["message"]


def test_vector_embedding_sdl():
    sdl = """
    type Product {
      id: ID!
      name: String! @search(by: [exact])
      vec: [Float!] @embedding @search(by: ["hnsw(metric: euclidean)"])
    }
    """
    g = GraphQLServer(Server(), sdl)
    su = g.engine.schema.get("Product.vec")
    assert su.vector_specs
    g.execute(
        """
        mutation {
          addProduct(input: [
            {name: "p1", vec: [1.0, 0.0]},
            {name: "p2", vec: [0.0, 1.0]}
          ]) { numUids }
        }
        """
    )
    res = g.execute(
        """
        query {
          querySimilarProductByEmbedding(by: "vec", topK: 1, vector: [0.9, 0.1]) {
            name
          }
        }
        """
    )
    assert res["data"]["querySimilarProductByEmbedding"][0]["name"] == "p1"


def test_aggregate_fields(gql):
    gql.execute(
        'mutation { addAuthor(input: [{name: "G1", age: 10}, '
        '{name: "G2", age: 30}]) { numUids } }'
    )
    res = gql.execute(
        "query { aggregateAuthor(filter: {name: {anyofterms: \"g1 g2\"}}) "
        "{ count ageMin ageMax ageSum ageAvg } }"
    )
    agg = res["data"]["aggregateAuthor"]
    assert agg["count"] == 2
    assert agg["ageMin"] == 10 and agg["ageMax"] == 30
    assert agg["ageSum"] == 40 and agg["ageAvg"] == 20.0


def test_aggregate_aliased_count(gql):
    gql.execute('mutation { addAuthor(input: [{name: "AC"}]) { numUids } }')
    res = gql.execute("query { aggregateAuthor { c: count } }")
    assert res["data"]["aggregateAuthor"]["c"] >= 1


def test_fragment_cycle_rejected(gql):
    res = gql.execute(
        "query { queryAuthor { ...A } } "
        "fragment A on Author { ...B } fragment B on Author { ...A }"
    )
    assert res.get("errors") and "cycle" in res["errors"][0]["message"]


def test_inline_fragment_without_type_condition(gql):
    gql.execute('mutation { addAuthor(input: [{name: "Zed", age: 9}]) { numUids } }')
    res = gql.execute("query { queryAuthor { ... { name age } } }")
    assert not res.get("errors"), res
    assert any(a["name"] == "Zed" and a["age"] == 9 for a in res["data"]["queryAuthor"])


def test_decimal_and_hex_ids():
    from dgraph_tpu.graphql.resolve import _parse_uid

    assert _parse_uid("17") == 17
    assert _parse_uid("0x11") == 17
    assert _parse_uid("alice") is None
    # ParseUint accepts 0 (uid 0 just matches nothing) — ref convertIDs
    assert _parse_uid("0") == 0
    assert _parse_uid("0x0") == 0
    assert _parse_uid(str(1 << 65)) is None


def test_fragment_with_directives_parses():
    from dgraph_tpu.graphql.parser import parse_operation

    op = parse_operation(
        "fragment F on Person @include(if: true) { name }\n"
        "query { queryPerson { ...F } }"
    )
    assert op.selections[0].name == "queryPerson"
    op2 = parse_operation(
        "query { queryPerson { ...G } }\n"
        "fragment G on Person @cacheControl(maxAge: 5) { name }"
    )
    assert op2.selections[0].name == "queryPerson"


def test_ngram_shingle_cutoff_is_utf8_bytes():
    from dgraph_tpu.tok.tok import NGramTokenizer

    sh = NGramTokenizer._shingle
    # 29 chars ASCII = 29 bytes: raw
    assert sh("a" * 29) == b"a" * 29
    # 29 chars of 2-byte UTF-8 = 58 bytes: hashed (ref tok.go byte compare)
    assert len(sh("é" * 29)) == 32
    assert sh("a" * 30) != b"a" * 30


def test_mutation_payload_shapes_typename_and_aggregates(gql):
    res = gql.execute(
        """mutation {
          addAuthor(input: [{name: "Shape", posts: [{title: "a"}, {title: "b"}]}]) {
            author { __typename name postsAggregate { count } }
          }
        }"""
    )
    assert not res.get("errors"), res
    a = [x for x in res["data"]["addAuthor"]["author"] if x["name"] == "Shape"][0]
    assert a["__typename"] == "Author"
    assert a["postsAggregate"] == {"count": 2}


def test_leading_fragment_with_operation_variables(gql):
    gql.execute('mutation { addAuthor(input: [{name: "Lead", age: 3}]) { numUids } }')
    res = gql.execute(
        "fragment F on Author { name age @include(if: $v) } "
        'query Q($v: Boolean = true) { queryAuthor(filter: {name: {eq: "Lead"}}) { ...F } }'
    )
    assert not res.get("errors"), res
    assert res["data"]["queryAuthor"][0] == {"name": "Lead", "age": 3}


def test_aggregate_not_clobbered_by_fragment_overlap(gql):
    gql.execute(
        'mutation { addAuthor(input: [{name: "Aggy", posts: [{title: "x"}, {title: "y"}]}]) { numUids } }'
    )
    res = gql.execute(
        'query { queryAuthor(filter: {name: {eq: "Aggy"}}) '
        "{ postsAggregate { count } ... { postsAggregate { count } } } }"
    )
    assert not res.get("errors"), res
    assert res["data"]["queryAuthor"][0]["postsAggregate"] == {"count": 2}

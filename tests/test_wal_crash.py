"""Raft WAL crash recovery: torn tails at every byte boundary.

A crash mid-write leaves log.wal truncated at an arbitrary byte. Replay
must recover to the last COMPLETE record (raft safety: an entry whose
bytes never fully hit disk was never acked) and physically truncate the
torn tail so subsequent appends start from a clean boundary.
"""

import os
import struct

from dgraph_tpu.raft.wal import RaftWal, _REC

_HDR = struct.Struct("<BI")


def _record_offsets(blob: bytes):
    """Byte offsets where each WAL record begins."""
    offsets = []
    pos = 0
    while pos + _REC.size <= len(blob):
        _, plen = _REC.unpack_from(blob, pos)
        offsets.append(pos)
        pos += _REC.size + plen
    assert pos == len(blob), "seed log itself must parse cleanly"
    return offsets


def _write_wal(dirpath, entries):
    w = RaftWal(str(dirpath))
    for term, data in entries:
        w.append_entry(term, data)
    w.flush()
    w.close()
    with open(os.path.join(str(dirpath), "log.wal"), "rb") as f:
        return f.read()


def test_torn_tail_recovers_at_every_byte_boundary(tmp_path):
    entries = [
        (1, {"op": "set", "k": f"key{i}", "blob": b"x" * (7 * i)})
        for i in range(1, 6)
    ]
    blob = _write_wal(tmp_path / "seed", entries)
    offsets = _record_offsets(blob)
    last_start = offsets[-1]

    # cut the LAST record at every byte boundary: mid-header, mid-length,
    # and every prefix of the pickled payload
    for cut in range(last_start, len(blob)):
        d = tmp_path / f"cut_{cut}"
        os.makedirs(d)
        with open(d / "log.wal", "wb") as f:
            f.write(blob[:cut])
        w = RaftWal(str(d))
        snap_index, snap_term, got = w.replay_log()
        assert (snap_index, snap_term) == (0, 0)
        assert got == entries[:-1], f"cut at byte {cut}"
        # the torn tail was physically truncated to the valid boundary
        assert os.path.getsize(d / "log.wal") == last_start, cut
        w.close()

    # the untruncated log replays fully (control)
    w = RaftWal(str(tmp_path / "seed"))
    assert w.replay_log()[2] == entries
    w.close()


def test_torn_tail_then_append_continues_cleanly(tmp_path):
    entries = [(1, i) for i in range(4)]
    blob = _write_wal(tmp_path / "w", entries)
    offsets = _record_offsets(blob)
    # tear halfway into the last record
    cut = offsets[-1] + (len(blob) - offsets[-1]) // 2
    with open(tmp_path / "w" / "log.wal", "wb") as f:
        f.write(blob[:cut])
    w = RaftWal(str(tmp_path / "w"))
    assert w.replay_log()[2] == entries[:-1]
    # appends after recovery land on the clean boundary and replay
    w.append_entry(2, "post-crash")
    w.flush()
    w.close()
    w2 = RaftWal(str(tmp_path / "w"))
    assert w2.replay_log()[2] == entries[:-1] + [(2, "post-crash")]
    w2.close()


def test_torn_trunc_and_compact_records(tmp_path):
    """Crash mid-TRUNC / mid-COMPACT: the control records are recovered
    or dropped whole, never half-applied."""
    w = RaftWal(str(tmp_path / "w"))
    for i in range(3):
        w.append_entry(1, i)
    w.truncate_from(3)  # drops entry index 3 (the third append)
    w.compact(1, 1)     # snapshot covers global index 1
    w.flush()
    w.close()
    with open(tmp_path / "w" / "log.wal", "rb") as f:
        blob = f.read()
    offsets = _record_offsets(blob)
    # full replay: 3 appends, minus trunc'd tail, minus compacted head
    full = RaftWal(str(tmp_path / "w")).replay_log()
    assert full == (1, 1, [(1, 1)])
    # tear the COMPACT record at each byte: replay sees the TRUNC but
    # not the compact
    for cut in range(offsets[-1], len(blob)):
        d = tmp_path / f"c_{cut}"
        os.makedirs(d)
        with open(d / "log.wal", "wb") as f:
            f.write(blob[:cut])
        got = RaftWal(str(d)).replay_log()
        assert got == (0, 0, [(1, 0), (1, 1)]), cut
    # tear the TRUNC record at each byte: all three appends survive
    for cut in range(offsets[-2], offsets[-1]):
        d = tmp_path / f"t_{cut}"
        os.makedirs(d)
        with open(d / "log.wal", "wb") as f:
            f.write(blob[:cut])
        got = RaftWal(str(d)).replay_log()
        assert got == (0, 0, [(1, 0), (1, 1), (1, 2)]), cut

"""Real multi-OS-process cluster tests (VERDICT r1 next-round #6).

Spawns alpha replicas as separate python processes (ref
dgraphtest/local_cluster.go): cross-process raft over TCP, RPC reads with
hedging, leader-routed proposals, process-kill fault injection, durable
restart.
"""

import time

import pytest

from dgraph_tpu.conn.rpc import RpcPool, RpcServer
from dgraph_tpu.worker.harness import ProcCluster


# ---------------------------------------------------------------------------
# RPC layer
# ---------------------------------------------------------------------------


def test_rpc_roundtrip_and_errors():
    srv = RpcServer().start()
    srv.register("echo", lambda a: {"got": a})
    srv.register("boom", lambda a: 1 / 0)
    pool = RpcPool(timeout=3.0)
    out = pool.call(srv.addr, "echo", {"x": 1, "b": b"\x00\xff"})
    assert out["got"]["x"] == 1 and bytes(out["got"]["b"]) == b"\x00\xff"
    from dgraph_tpu.conn.rpc import RpcError

    with pytest.raises(RpcError):
        pool.call(srv.addr, "boom")
    with pytest.raises(RpcError):
        pool.call(srv.addr, "nope")
    assert pool.healthy(srv.addr)
    srv.close()
    pool.close()


def test_rpc_pool_health_marks_dead_peer():
    srv = RpcServer().start()
    pool = RpcPool(timeout=0.3, heartbeat_s=0.1, max_misses=2)
    pool.call(srv.addr, "ping")
    addr = srv.addr
    srv.close()
    # drop the pooled socket: the listener is gone, reconnects must fail
    # (an established handler thread would otherwise keep answering)
    pool.get(addr).close_conn()
    from dgraph_tpu.conn.rpc import RpcError

    for _ in range(3):
        try:
            pool.call(addr, "ping", timeout=0.3)
        except RpcError:
            pass
    assert not pool.healthy(addr)
    pool.close()


# ---------------------------------------------------------------------------
# Process cluster
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def cluster():
    c = ProcCluster(n_groups=2, replicas=3)
    yield c
    c.close()


SCHEMA = "name: string @index(exact) .\nfollows: [uid] .\nage: int @index(int) ."


def test_proc_cluster_end_to_end(cluster):
    cluster.alter(SCHEMA)
    t = cluster.new_txn()
    t.mutate_rdf(
        set_rdf=(
            '<0x1> <name> "alice" .\n'
            '<0x2> <name> "bob" .\n'
            '<0x1> <age> "30"^^<xs:int> .\n'
            "<0x1> <follows> <0x2> .\n"
        ),
        commit_now=True,
    )
    out = cluster.query(
        '{ q(func: eq(name, "alice")) { name age follows { name } } }'
    )
    q = out["data"]["q"][0]
    assert q["name"] == "alice" and q["age"] == 30
    assert q["follows"][0]["name"] == "bob"


def test_proc_cluster_survives_follower_kill(cluster):
    g = cluster.remote_groups[1]
    leader = g.leader_addr()
    victim = None
    for nid, cfg in cluster._cfgs.items():
        addr = tuple(cfg["rpc_addr"])
        if cfg["group_id"] == 1 and addr != leader:
            victim = nid
            break
    cluster.kill(victim)
    t = cluster.new_txn()
    t.mutate_rdf(set_rdf='<0x3> <name> "carol" .', commit_now=True)
    out = cluster.query('{ q(func: eq(name, "carol")) { name } }')
    assert out["data"]["q"][0]["name"] == "carol"
    cluster.restart(victim)
    time.sleep(0.5)


def test_proc_cluster_survives_leader_kill(cluster):
    g = cluster.remote_groups[1]
    leader = g.leader_addr()
    victim = None
    for nid, cfg in cluster._cfgs.items():
        if tuple(cfg["rpc_addr"]) == tuple(leader):
            victim = nid
            break
    cluster.kill(victim)
    # remaining two re-elect; commits keep working
    t = cluster.new_txn()
    t.mutate_rdf(set_rdf='<0x4> <name> "dave" .', commit_now=True)
    out = cluster.query('{ q(func: eq(name, "dave")) { name } }')
    assert out["data"]["q"][0]["name"] == "dave"
    cluster.restart(victim)
    time.sleep(0.5)


def test_proc_cluster_durable_restart(tmp_path):
    d = str(tmp_path / "pc")
    c = ProcCluster(n_groups=1, replicas=3, data_dir=d)
    try:
        c.alter("name: string @index(exact) .")
        c.new_txn().mutate_rdf(set_rdf='<0x9> <name> "zoe" .', commit_now=True)
        out = c.query('{ q(func: eq(name, "zoe")) { name } }')
        assert out["data"]["q"][0]["name"] == "zoe"
        # kill ALL replicas, respawn from disk
        for nid in list(c.procs):
            c.kill(nid)
        for nid in list(c.procs):
            c._spawn(nid)
        c._wait_healthy()
        out = c.query('{ q(func: eq(name, "zoe")) { name } }')
        assert out["data"]["q"][0]["name"] == "zoe"
    finally:
        c.close()


def test_proc_cluster_with_zero_quorum_processes(tmp_path):
    """Full cross-process topology: alphas AND the Zero quorum as OS
    processes (ref dgraph/cmd/zero run.go); leases/commits/tablets via
    zero.exec RPC; zero-leader kill tolerated."""
    c = ProcCluster(
        n_groups=1, replicas=3, replicated_zero=True, zero_replicas=3
    )
    try:
        c.alter("name: string @index(exact) .")
        t = c.new_txn()
        t.mutate_rdf(set_rdf='<0x1> <name> "zq-alice" .', commit_now=True)
        out = c.query('{ q(func: eq(name, "zq-alice")) { name } }')
        assert out["data"]["q"][0]["name"] == "zq-alice"
        # tablets decided by the zero quorum
        assert c.zero.belongs_to("name") == 1
        # kill the zero leader process: remaining two re-elect
        lead_addr = c.zero.zero._leader
        victim = next(
            nid
            for nid, cfg in c._cfgs.items()
            if cfg.get("_module", "").endswith("zero_process")
            and tuple(cfg["rpc_addr"]) == tuple(lead_addr)
        )
        c.kill(victim)
        t2 = c.new_txn()
        t2.mutate_rdf(set_rdf='<0x2> <name> "zq-bob" .', commit_now=True)
        out = c.query('{ q(func: eq(name, "zq-bob")) { name } }')
        assert out["data"]["q"][0]["name"] == "zq-bob"
    finally:
        c.close()


def test_proc_cluster_move_recovery_at_each_journaled_phase(cluster):
    """Coordinator death mid-move at every journaled phase: the move
    journal + recover_moves() resolve to exactly-once placement (the
    in-process analog restarts the whole cluster; here the same
    coordinator recovers after a simulated crash at the boundary)."""
    import pytest as _pytest

    from dgraph_tpu.conn import faults
    from dgraph_tpu.conn.faults import FaultPlan, InjectedCrash

    cluster.alter("crashy: string @index(exact) .")
    cluster.new_txn().mutate_rdf(
        set_rdf="\n".join(
            f'<0x{i:x}> <crashy> "c{i}" .' for i in range(0x80, 0x8c)
        ),
        commit_now=True,
    )
    try:
        for point in (
            "move.begin", "move.copy", "move.fence",
            "move.delta", "move.flip", "move.drop",
        ):
            src = cluster.zero.belongs_to("crashy")
            dst = next(g for g in cluster.remote_groups if g != src)
            faults.install(FaultPlan(seed=5, rules=[
                dict(point=point, action="crash", p=1.0, max=1)
            ]))
            with _pytest.raises(InjectedCrash):
                cluster.move_tablet("crashy", dst)
            faults.reset()
            assert cluster.zero.moves(), point  # journal survived
            cluster.recover_moves()
            assert cluster.zero.moves() == {}, point
            where = cluster.zero.belongs_to("crashy")
            # copy/fence phases roll back; post-flip phases roll forward
            assert where == (
                dst if point in ("move.flip", "move.drop") else src
            ), point
            out = cluster.query("{ q(func: has(crashy)) { uid } }")
            assert len(out["data"]["q"]) == 12, point
            out = cluster.query('{ q(func: eq(crashy, "c130")) { crashy } }')
            assert out["data"]["q"] == [{"crashy": "c130"}], point
    finally:
        faults.reset()


def test_proc_cluster_chunked_move_larger_than_frame_chunk(
    cluster, monkeypatch
):
    """A tablet bigger than one chunk streams in multiple bounded
    ('delta', chunk) proposals and paged source reads — the old mover
    shipped ONE proposal and hard-failed at the frame cap."""
    from dgraph_tpu.utils.observe import METRICS

    monkeypatch.setenv("DGRAPH_TPU_MOVE_CHUNK_BYTES", "4096")
    cluster.alter("bigmv: string @index(exact) .")
    pad = "y" * 180
    cluster.new_txn().mutate_rdf(
        set_rdf="\n".join(
            f'<0x{0x900 + i:x}> <bigmv> "b{i}{pad}" .' for i in range(120)
        ),
        commit_now=True,
    )
    src = cluster.zero.belongs_to("bigmv")
    dst = next(g for g in cluster.remote_groups if g != src)
    chunks0 = METRICS.value("tablet_move_chunks_total")
    assert cluster.move_tablet("bigmv", dst) is True
    assert METRICS.value("tablet_move_chunks_total") >= chunks0 + 3
    assert cluster.zero.belongs_to("bigmv") == dst
    out = cluster.query("{ q(func: has(bigmv)) { uid } }")
    assert len(out["data"]["q"]) == 120
    out = cluster.query(f'{{ q(func: eq(bigmv, "b7{pad}")) {{ uid }} }}')
    assert len(out["data"]["q"]) == 1


def test_proc_cluster_predicate_move(cluster):
    """Cross-process tablet move: stream out of the source group's
    replicas, raft-propose into the destination, flip, drop
    (ref worker/predicate_move.go)."""
    cluster.alter("movable: string @index(exact) .")
    t = cluster.new_txn()
    t.mutate_rdf(
        set_rdf="\n".join(
            f'<0x{i:x}> <movable> "m{i}" .' for i in range(0x60, 0x70)
        ),
        commit_now=True,
    )
    src = cluster.zero.belongs_to("movable")
    dst = next(g for g in cluster.remote_groups if g != src)
    cluster.move_tablet("movable", dst)
    assert cluster.zero.belongs_to("movable") == dst
    out = cluster.query('{ q(func: eq(movable, "m97")) { movable } }')
    assert out["data"]["q"][0]["movable"] == "m97"
    out = cluster.query("{ q(func: has(movable)) { uid } }")
    assert len(out["data"]["q"]) == 16
    # and writes keep landing on the new owner
    cluster.new_txn().mutate_rdf(
        set_rdf='<0x70> <movable> "m112" .', commit_now=True
    )
    out = cluster.query('{ q(func: eq(movable, "m112")) { movable } }')
    assert out["data"]["q"][0]["movable"] == "m112"

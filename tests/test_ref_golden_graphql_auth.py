"""GraphQL @auth conformance against the reference's rewriter oracles
(VERDICT r4 #3, auth half).

Cases: tests/ref_golden_graphql/auth_cases.json, extracted from
/root/reference/graphql/resolve/auth_*_test.yaml (driven there by
auth_test.go over graphql/e2e/auth/schema.graphql — copied here as
auth_schema.graphql).

Execution equivalence on a discriminating seeded world (two nodes per
type: one matching the case's auth-rule values, one not — see
mutation_support.auth_seed_objects):
  query  — our GraphQL layer with JWT claims vs the reference dgquery
           through our DQL engine; responses must agree (Tier-B
           normalization).
  delete — both sides mutate sibling stores; final graphs must match
           modulo uid renaming.
  add/update — error cases must error; success cases must succeed.

Failures tracked in known_fails_auth.json (strict xfail)."""

import json
import os
import re
import sys

import pytest

HERE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "ref_golden_graphql"
)
sys.path.insert(0, HERE)

CASES = json.load(open(os.path.join(HERE, "auth_cases.json")))
SCHEMA = open(os.path.join(HERE, "auth_schema.graphql")).read()


def _load(name):
    p = os.path.join(HERE, name)
    return set(json.load(open(p))) if os.path.exists(p) else set()


KNOWN = _load("known_fails_auth.json")

_EMPTY_DGQ = re.compile(r"^\s*query\s*\{\s*(\w+)\(\)\s*\}\s*$")


def _types():
    from dgraph_tpu.graphql.sdl import parse_sdl

    return parse_sdl(SCHEMA)


@pytest.mark.parametrize(
    "case",
    [
        pytest.param(
            c,
            marks=(
                [pytest.mark.xfail(strict=True, reason="tracked gap")]
                if c["id"] in KNOWN
                else []
            ),
        )
        for c in CASES
    ],
    ids=[c["id"] for c in CASES],
)
def test_graphql_auth_equiv(case):
    import mutation_support as ms
    from test_ref_golden_graphql import (
        _canon,
        _normalize_pair,
        _sorted_lists,
    )

    types = _types()
    seeds, max_uid = ms.auth_seed_objects(case, types)
    claims = dict(case.get("jwtvar") or {})

    sa, gql = ms.make_server(SCHEMA, max_uid)
    if case.get("closed"):
        gql.closed_by_default = True
    ms.apply_seed(sa, seeds)
    res = gql.execute(
        case["gqlquery"],
        variables=case.get("variables"),
        claims=claims or None,
    )
    errored = bool(res.get("errors"))

    if case["kind"] in ("add", "update") or (
        case.get("closed") and case.get("error")
    ):
        if case.get("error"):
            assert errored, (
                f"reference rejects ({case['error']!r}) but ours "
                f"succeeded: {res}"
            )
        else:
            assert not errored, res["errors"]
        return

    assert not errored, res["errors"]

    if case["kind"] == "delete":
        sb, _ = ms.make_server(SCHEMA, max_uid)
        ms.apply_seed(sb, seeds)
        txn = sb.new_txn()
        txn.upsert_json(
            case.get("dgquery") or "",
            case.get("dgmutations", []),
            commit_now=True,
        )
        got = ms.canonicalize(ms.dump_triples(sa))
        want = ms.canonicalize(ms.dump_triples(sb))
        assert got == want, _mdiff(got, want)
        return

    # query equivalence
    dgq = case.get("dgquery") or ""
    m = _EMPTY_DGQ.match(dgq)
    if m:
        # rewriter denied outright: our response must be empty
        for v in (res.get("data") or {}).values():
            assert v in (None, [], {}), res
        return
    ref = sa.query(dgq, variables=case.get("dgvars"))["data"]
    got, want = _normalize_pair(res["data"], ref)
    assert _canon(_sorted_lists(got)) == _canon(_sorted_lists(want))


def _mdiff(got, want):
    gs, ws = set(map(repr, got)), set(map(repr, want))
    return (
        f"state mismatch\n  ours-only ({len(gs - ws)}): "
        f"{sorted(gs - ws)[:10]}\n  ref-only ({len(ws - gs)}): "
        f"{sorted(ws - gs)[:10]}"
    )

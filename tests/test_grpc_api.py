"""gRPC api.Dgraph wire-protocol smoke tests (VERDICT r1 next-round #8).

Drives the server exactly the way stock pydgraph/dgo do: raw gRPC calls
on the /api.Dgraph/* method paths with the public proto messages —
login-txn-query-mutate-commit, upsert blocks, JSON mutations, aborts.
(pydgraph itself isn't installable in this image; these stubs use the
identical method paths + serialized messages, which IS the protocol.)
"""

import json

import grpc
import pytest

from dgraph_tpu.api.grpc_server import pb, serve
from dgraph_tpu.api.server import Server


class MiniDgraphClient:
    """The exact call surface pydgraph's DgraphClientStub builds."""

    def __init__(self, addr):
        self.channel = grpc.insecure_channel(addr)
        u = self.channel.unary_unary
        self.login = u(
            "/api.Dgraph/Login",
            request_serializer=pb.LoginRequest.SerializeToString,
            response_deserializer=pb.Response.FromString,
        )
        self.query = u(
            "/api.Dgraph/Query",
            request_serializer=pb.Request.SerializeToString,
            response_deserializer=pb.Response.FromString,
        )
        self.alter = u(
            "/api.Dgraph/Alter",
            request_serializer=pb.Operation.SerializeToString,
            response_deserializer=pb.Payload.FromString,
        )
        self.commit_or_abort = u(
            "/api.Dgraph/CommitOrAbort",
            request_serializer=pb.TxnContext.SerializeToString,
            response_deserializer=pb.TxnContext.FromString,
        )
        self.check_version = u(
            "/api.Dgraph/CheckVersion",
            request_serializer=pb.Check.SerializeToString,
            response_deserializer=pb.Version.FromString,
        )


@pytest.fixture(scope="module")
def client():
    engine = Server()
    server, port = serve(engine)
    c = MiniDgraphClient(f"127.0.0.1:{port}")
    yield c
    server.stop(0)


def test_check_version(client):
    v = client.check_version(pb.Check())
    assert v.tag == "dgraph-tpu"


def test_alter_and_mutate_commit_now(client):
    client.alter(pb.Operation(schema="name: string @index(exact) ."))
    req = pb.Request(commit_now=True)
    m = req.mutations.add()
    m.set_nquads = b'_:a <name> "grpc-alice" .'
    resp = client.query(req)
    assert resp.txn.commit_ts > 0
    assert "a" in dict(resp.uids)

    q = pb.Request(
        query='{ q(func: eq(name, "grpc-alice")) { name } }', read_only=True
    )
    out = json.loads(client.query(q).json)
    assert out["q"][0]["name"] == "grpc-alice"


def test_txn_query_mutate_commit(client):
    # open a txn with the first query (start_ts=0 -> server assigns)
    r1 = client.query(pb.Request(query="{ q(func: has(name)) { uid } }"))
    ts = r1.txn.start_ts
    assert ts > 0
    # mutate inside the txn
    req = pb.Request(start_ts=ts)
    m = req.mutations.add()
    m.set_nquads = b'_:b <name> "grpc-bob" .'
    r2 = client.query(req)
    assert r2.txn.commit_ts == 0  # not committed yet
    # uncommitted write visible inside the txn
    r3 = client.query(
        pb.Request(
            start_ts=ts, query='{ q(func: eq(name, "grpc-bob")) { name } }'
        )
    )
    assert json.loads(r3.json)["q"][0]["name"] == "grpc-bob"
    # not visible outside
    r4 = client.query(
        pb.Request(
            read_only=True, query='{ q(func: eq(name, "grpc-bob")) { name } }'
        )
    )
    assert json.loads(r4.json)["q"] == []
    # commit, then visible
    ctx = client.commit_or_abort(pb.TxnContext(start_ts=ts))
    assert ctx.commit_ts > 0
    r5 = client.query(
        pb.Request(
            read_only=True, query='{ q(func: eq(name, "grpc-bob")) { name } }'
        )
    )
    assert json.loads(r5.json)["q"][0]["name"] == "grpc-bob"


def test_txn_abort_discards(client):
    r1 = client.query(pb.Request(query="{ q(func: has(name)) { uid } }"))
    ts = r1.txn.start_ts
    req = pb.Request(start_ts=ts)
    m = req.mutations.add()
    m.set_nquads = b'_:c <name> "grpc-ghost" .'
    client.query(req)
    ctx = client.commit_or_abort(pb.TxnContext(start_ts=ts, aborted=True))
    assert ctx.aborted
    r = client.query(
        pb.Request(
            read_only=True, query='{ q(func: eq(name, "grpc-ghost")) { uid } }'
        )
    )
    assert json.loads(r.json)["q"] == []


def test_json_mutation(client):
    req = pb.Request(commit_now=True)
    m = req.mutations.add()
    m.set_json = json.dumps(
        {"uid": "_:x", "name": "grpc-json", "age": 7}
    ).encode()
    client.query(req)
    r = client.query(
        pb.Request(
            read_only=True,
            query='{ q(func: eq(name, "grpc-json")) { name age } }',
        )
    )
    got = json.loads(r.json)["q"][0]
    assert got["name"] == "grpc-json" and got["age"] == 7


def test_upsert_block(client):
    req = pb.Request(
        commit_now=True,
        query='{ u as var(func: eq(name, "grpc-alice")) }',
    )
    m = req.mutations.add()
    m.set_nquads = b'uid(u) <name> "grpc-alice-renamed" .'
    client.query(req)
    r = client.query(
        pb.Request(
            read_only=True,
            query='{ q(func: eq(name, "grpc-alice-renamed")) { name } }',
        )
    )
    assert len(json.loads(r.json)["q"]) == 1


def test_conflict_aborts_with_grpc_status(client):
    client.alter(pb.Operation(schema="counter: int @upsert ."))
    client.query(_commit_now_nquads(b'<0x500> <counter> "1"^^<xs:int> .'))
    r1 = client.query(pb.Request(query="{ q(func: uid(0x500)) { counter } }"))
    r2 = client.query(pb.Request(query="{ q(func: uid(0x500)) { counter } }"))
    for ts, val in ((r1.txn.start_ts, b"2"), (r2.txn.start_ts, b"3")):
        req = pb.Request(start_ts=ts)
        m = req.mutations.add()
        m.set_nquads = b'<0x500> <counter> "%s"^^<xs:int> .' % val
        client.query(req)
    assert client.commit_or_abort(
        pb.TxnContext(start_ts=r1.txn.start_ts)
    ).commit_ts > 0
    with pytest.raises(grpc.RpcError) as ei:
        client.commit_or_abort(pb.TxnContext(start_ts=r2.txn.start_ts))
    assert ei.value.code() == grpc.StatusCode.ABORTED


def _commit_now_nquads(nq: bytes) -> "pb.Request":
    req = pb.Request(commit_now=True)
    m = req.mutations.add()
    m.set_nquads = nq
    return req


def test_grpc_login_with_acl():
    """Login over gRPC against an ACL-enabled engine returns working
    JWTs (ref edgraph/access_ee login flow)."""
    import json as _json

    engine = Server()
    engine.enable_acl(groot_password="secret123")
    server, port = serve(engine)
    try:
        c = MiniDgraphClient(f"127.0.0.1:{port}")
        resp = c.login(pb.LoginRequest(userid="groot", password="secret123"))
        jwt = _json.loads(resp.json)
        assert jwt["accessJwt"]
        # wrong password -> UNAUTHENTICATED
        import grpc as _grpc

        with pytest.raises(_grpc.RpcError) as ei:
            c.login(pb.LoginRequest(userid="groot", password="nope"))
        assert ei.value.code() == _grpc.StatusCode.UNAUTHENTICATED
    finally:
        server.stop(0)

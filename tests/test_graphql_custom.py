"""GraphQL @custom HTTP resolvers (ref graphql/schema/remote.go,
resolve/http.go: custom queries/mutations/fields hitting external
endpoints with $arg substitution).
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from dgraph_tpu.api.server import Server
from dgraph_tpu.graphql.resolve import GraphQLServer


class _Api(BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass

    def _send(self, obj):
        data = json.dumps(obj).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        if self.path.startswith("/weather"):
            city = self.path.split("city=")[1]
            self._send({"city": city, "temp": 21.5})
        else:
            self._send(None)

    def do_POST(self):
        n = int(self.headers.get("Content-Length", 0))
        body = json.loads(self.rfile.read(n))
        self._send({"echoed": body, "ok": True})


@pytest.fixture(scope="module")
def api_port():
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _Api)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield srv.server_address[1]
    srv.shutdown()


@pytest.fixture()
def gql(api_port):
    sdl = f'''
type Person {{
  id: ID!
  name: String @search(by: [exact])
}}
type Query {{
  getWeather(city: String!): WeatherPayload @custom(http: {{url: "http://127.0.0.1:{api_port}/weather?city=$city", method: GET}})
}}
type Mutation {{
  notify(msg: String!): NotifyPayload @custom(http: {{url: "http://127.0.0.1:{api_port}/notify", method: POST, body: "{{message: $msg}}"}})
}}
type WeatherPayload {{ city: String temp: Float }}
type NotifyPayload {{ ok: Boolean }}
'''
    return GraphQLServer(Server(), sdl)


def test_custom_query_get(gql):
    out = gql.execute('{ getWeather(city: "lisbon") { city temp } }')
    assert out["data"]["getWeather"] == {"city": "lisbon", "temp": 21.5}


def test_custom_mutation_post(gql):
    out = gql.execute('mutation { notify(msg: "hi") { ok } }')
    assert out["data"]["notify"]["ok"] is True


def test_custom_does_not_create_predicates(gql):
    # Query/Mutation virtual roots + custom fields generate no schema
    preds = gql.engine.schema.predicates()
    assert not any(p.startswith("Query.") for p in preds)
    assert not any(p.startswith("Mutation.") for p in preds)
    # and the regular generated API still works alongside
    out = gql.execute('mutation { addPerson(input: [{name: "pc"}]) { numUids } }')
    assert out["data"]["addPerson"]["numUids"] == 1


def test_custom_error_surfaces(gql):
    bad = GraphQLServer(
        Server(),
        'type Q2 { id: ID! }\n'
        'type Query { broken: Q2 @custom(http: {url: "http://127.0.0.1:1/x", method: GET}) }',
    )
    out = bad.execute("{ broken { id } }")
    assert out["errors"] and "http call failed" in out["errors"][0]["message"]


def _stub_remote(schema_types, resolver):
    """Local stub GraphQL server: answers introspection + one op."""
    import http.server
    import json as _json
    import threading

    class H(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers["Content-Length"])
            q = _json.loads(self.rfile.read(n))["query"]
            if "__schema" in q:
                body = {"data": {"__schema": schema_types}}
            else:
                body = resolver(q)
            out = _json.dumps(body).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            self.wfile.write(out)

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


_REMOTE_SCHEMA = {
    "queryType": {"name": "Query"},
    "mutationType": None,
    "types": [
        {
            "kind": "OBJECT",
            "name": "Query",
            "fields": [
                {
                    "name": "getWeather",
                    "args": [
                        {
                            "name": "city",
                            "type": {
                                "kind": "NON_NULL",
                                "name": None,
                                "ofType": {"kind": "SCALAR", "name": "String"},
                            },
                        }
                    ],
                    "type": {"kind": "OBJECT", "name": "Weather"},
                }
            ],
        },
        {
            "kind": "OBJECT",
            "name": "Weather",
            "fields": [
                {"name": "city", "args": [], "type": {"kind": "SCALAR", "name": "String"}},
                {"name": "tempC", "args": [], "type": {"kind": "SCALAR", "name": "Int"}},
            ],
        },
        {"kind": "SCALAR", "name": "String", "fields": None},
        {"kind": "SCALAR", "name": "Int", "fields": None},
    ],
}


def test_custom_graphql_remote_introspection_validates_and_resolves():
    """@custom graphql mode: the remote is introspected at schema load
    (ref graphql/schema/remote.go validateRemoteGraphql) and the op is
    executed via POST {query} at request time."""
    from dgraph_tpu.api.server import Server
    from dgraph_tpu.graphql import GraphQLServer

    srv = _stub_remote(
        _REMOTE_SCHEMA,
        lambda q: {
            "data": {"getWeather": {"city": "Pune", "tempC": 31}}
        },
    )
    try:
        url = f"http://127.0.0.1:{srv.server_port}/graphql"
        sdl = f'''
        type Weather @remote {{
          city: String
          tempC: Int
        }}
        type Query {{
          weather(city: String!): Weather @custom(http: {{
            url: "{url}",
            method: "POST",
            graphql: "query {{ getWeather(city: $city) }}"
          }})
        }}
        '''
        gql = GraphQLServer(Server(), sdl)
        res = gql.execute('query { weather(city: "Pune") { city tempC } }')
        assert not res.get("errors"), res
        assert res["data"]["weather"] == {"city": "Pune", "tempC": 31}
    finally:
        srv.shutdown()


def test_custom_graphql_remote_rejects_unknown_op():
    """A @custom graphql op the remote does not serve is rejected at
    schema-update time, like the reference."""
    import pytest

    from dgraph_tpu.api.server import Server
    from dgraph_tpu.graphql import GraphQLServer

    srv = _stub_remote(_REMOTE_SCHEMA, lambda q: {"data": {}})
    try:
        url = f"http://127.0.0.1:{srv.server_port}/graphql"
        sdl = f'''
        type Weather @remote {{
          city: String
          tempC: Int
        }}
        type Query {{
          weather(city: String!): Weather @custom(http: {{
            url: "{url}",
            method: "POST",
            graphql: "query {{ getForecast(city: $city) }}"
          }})
        }}
        '''
        from dgraph_tpu.graphql.resolve import GraphQLError

        with pytest.raises(GraphQLError, match="not present in remote"):
            GraphQLServer(Server(), sdl)
    finally:
        srv.shutdown()


def test_custom_graphql_remote_rejects_missing_required_arg():
    import pytest

    from dgraph_tpu.api.server import Server
    from dgraph_tpu.graphql import GraphQLServer
    from dgraph_tpu.graphql.resolve import GraphQLError

    srv = _stub_remote(_REMOTE_SCHEMA, lambda q: {"data": {}})
    try:
        url = f"http://127.0.0.1:{srv.server_port}/graphql"
        sdl = f'''
        type Weather @remote {{
          city: String
          tempC: Int
        }}
        type Query {{
          weather: Weather @custom(http: {{
            url: "{url}",
            method: "POST",
            graphql: "query {{ getWeather }}"
          }})
        }}
        '''
        with pytest.raises(GraphQLError, match="required by remote"):
            GraphQLServer(Server(), sdl)
    finally:
        srv.shutdown()

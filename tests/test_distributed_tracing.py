"""End-to-end distributed observability over a multi-process cluster.

One query against a ProcCluster (alpha replicas AND the Zero quorum as
OS processes) must yield ONE trace: the client's root query span, the
alphas' rpc_server spans, and the zero's rpc_server spans all share a
single 128-bit trace id with correct parent links, each process writing
its own JSONL sink (DGRAPH_TPU_TRACE_SINK). The response carries
reference-shaped extensions.server_latency and the per-query profile
assembled from child-server fragments. The cluster metrics surface
merges every process's /debug/prometheus_metrics (counters summed,
per-instance labels), served behind the facade HTTP endpoint and the
`dgraph-tpu metrics` CLI.
"""

import json
import glob
import os
import urllib.request

import pytest

from dgraph_tpu.utils import observe
from dgraph_tpu.worker.harness import ProcCluster


@pytest.fixture(scope="module")
def traced_cluster(tmp_path_factory):
    sink_dir = str(tmp_path_factory.mktemp("trace_sinks"))
    os.environ["DGRAPH_TPU_TRACE_SINK"] = sink_dir
    os.environ["DGRAPH_TPU_TRACE_SAMPLE"] = "1"
    c = ProcCluster(
        n_groups=1, replicas=3, replicated_zero=True, zero_replicas=3
    )
    try:
        c.alter("name: string @index(exact) .\nfollows: [uid] .")
        t = c.new_txn()
        t.mutate_rdf(
            set_rdf=(
                '<0x1> <name> "tr-alice" .\n'
                '<0x2> <name> "tr-bob" .\n'
                "<0x1> <follows> <0x2> .\n"
            ),
            commit_now=True,
        )
        yield c, sink_dir
    finally:
        c.close()
        os.environ.pop("DGRAPH_TPU_TRACE_SINK", None)
        os.environ.pop("DGRAPH_TPU_TRACE_SAMPLE", None)
        observe.TRACER.set_sink(None)


def _sink_spans(sink_dir):
    """{filename: [span dicts]} across every per-process sink file."""
    out = {}
    for path in glob.glob(os.path.join(sink_dir, "spans-*.jsonl")):
        with open(path) as f:
            out[os.path.basename(path)] = [
                json.loads(line) for line in f if line.strip()
            ]
    return out


def test_one_query_one_trace_across_client_alpha_zero(traced_cluster):
    c, sink_dir = traced_cluster
    # force the cached ts-lease block to exhaust so THIS query's read_ts
    # makes a real zero.exec RPC inside the root span
    c.zero.zero.TS_BLOCK = 1
    c.zero.zero._ts_end = -1
    out = c.query(
        '{ q(func: eq(name, "tr-alice")) { name follows { name } } }'
    )
    assert out["data"]["q"][0]["follows"][0]["name"] == "tr-bob"
    tid = int(out["extensions"]["trace_id"], 16)
    assert tid > 1 << 64  # random 128-bit, not a sequential counter

    by_file = _sink_spans(sink_dir)
    in_client = [
        f for f, spans in by_file.items()
        if f"pid{os.getpid()}" in f
        and any(s["trace_id"] == tid for s in spans)
    ]
    in_alpha = [
        f for f, spans in by_file.items()
        if "alpha-" in f and any(s["trace_id"] == tid for s in spans)
    ]
    in_zero = [
        f for f, spans in by_file.items()
        if "zero-" in f and any(s["trace_id"] == tid for s in spans)
    ]
    assert in_client, f"trace missing from client sink: {list(by_file)}"
    assert in_alpha, f"trace missing from alpha sinks: {list(by_file)}"
    assert in_zero, f"trace missing from zero sinks: {list(by_file)}"

    # parent links: exactly one root, and every other span's parent is a
    # span of the same trace (cross-process links resolve)
    trace = [
        s for spans in by_file.values() for s in spans
        if s["trace_id"] == tid
    ]
    ids = {s["span_id"] for s in trace}
    roots = [s for s in trace if s["parent_id"] is None]
    assert len(roots) == 1 and roots[0]["name"] == "query"
    for s in trace:
        if s["parent_id"] is not None:
            assert s["parent_id"] in ids, s
    names = {s["name"] for s in trace}
    assert "rpc_server" in names and "level_task" in names


def test_traced_commit_marks_raft_replication_hop(traced_cluster):
    """A traced commit's proposal rides the raft TCP envelope: the
    append broadcast that replicates it carries the proposer's
    traceparent, and each follower emits a raft_recv span joined to the
    same trace."""
    import time as _t

    c, sink_dir = traced_cluster
    c.new_txn().mutate_rdf(
        set_rdf='<0x3> <name> "tr-carol" .', commit_now=True
    )
    raft = []
    deadline = _t.time() + 10
    while _t.time() < deadline and not raft:
        by_file = _sink_spans(sink_dir)
        raft = [
            s
            for spans in by_file.values()
            for s in spans
            if s["name"] == "raft_recv"
        ]
        if not raft:
            _t.sleep(0.2)
    assert raft, "no raft_recv spans reached any sink"
    commit_tids = {
        s["trace_id"]
        for spans in by_file.values()
        for s in spans
        if s["name"] in ("commit", "rpc_server")
    }
    joined = [s for s in raft if s["trace_id"] in commit_tids]
    assert joined, "raft_recv spans did not join any traced proposal"
    assert all(s["parent_id"] is not None for s in joined)


def test_server_latency_and_profile_are_consistent(traced_cluster):
    c, _ = traced_cluster
    out = c.query(
        '{ q(func: eq(name, "tr-alice")) { name follows { name } } }'
    )
    lat = out["extensions"]["server_latency"]
    parts = (
        lat["parsing_ns"] + lat["assign_timestamp_ns"]
        + lat["processing_ns"] + lat["encoding_ns"]
    )
    assert lat["total_ns"] > 0
    assert lat["processing_ns"] > 0
    assert 0 < parts <= lat["total_ns"]
    prof = out["extensions"]["profile"]
    assert prof["level_tasks"], prof
    for lt in prof["level_tasks"]:
        assert lt["ms"] >= 0 and lt["parents"] >= 1 and lt["level"] >= 1
    levels = {(lt["attr"], lt["level"]) for lt in prof["level_tasks"]}
    assert ("follows", 1) in levels and ("name", 2) in levels
    # child-server fragments piggybacked on the read RPCs
    assert prof["rpc"], prof
    assert any(r["instance"].startswith("alpha-") for r in prof["rpc"])
    assert all(r["ms"] >= 0 and r["calls"] >= 1 for r in prof["rpc"])


def test_merged_metrics_equal_sum_of_per_process_scrapes(traced_cluster):
    c, _ = traced_cluster
    from dgraph_tpu.utils.observe import METRICS

    # per-process scrape over each replica's own debug HTTP listener
    texts = {"client": METRICS.render()}
    for label, addr in c.instance_labels().items():
        info = c.pool.call(addr, "debug.info", timeout=2.0)
        assert info["instance"] == label
        port = info["debug_http_port"]
        assert port > 0
        texts[label] = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/prometheus_metrics",
            timeout=5,
        ).read().decode()

    merged = observe.parse_exposition(c.merged_metrics())
    # counters that only move when queries run (stable between scrapes)
    for name in (
        "dgraph_tpu_num_queries",
        "dgraph_tpu_level_tasks_started",
        "dgraph_tpu_rpc_server_requests_total",
    ):
        expected = sum(
            observe.parse_exposition(t)["counter"].get(name, 0.0)
            for t in texts.values()
        )
        assert merged["counter"].get(name, 0.0) == expected, name
    assert merged["counter"]["dgraph_tpu_num_queries"] >= 1
    assert merged["counter"]["dgraph_tpu_rpc_server_requests_total"] >= 1
    # per-instance series survive the merge
    assert any(
        k.startswith('dgraph_tpu_rpc_server_requests_total{instance="')
        for k in merged["counter"]
    )


def test_cli_metrics_against_running_cluster(traced_cluster, capsys):
    c, _ = traced_cluster
    from dgraph_tpu import cli
    from dgraph_tpu.api.http_server import HTTPServer

    srv = HTTPServer(c, port=0).start()
    try:
        rc = cli.main(
            [
                "metrics",
                "--addr", f"http://127.0.0.1:{srv.port}",
                "--json",
            ]
        )
        assert rc == 0
        got = json.loads(capsys.readouterr().out)
        assert got["counters"]["dgraph_tpu_num_queries"] >= 1
        # merged value equals the sum of the per-instance series the
        # same scrape carries
        per_inst = sum(
            v
            for k, v in got["counters"].items()
            if k.startswith("dgraph_tpu_num_queries{")
        )
        assert got["counters"]["dgraph_tpu_num_queries"] == per_inst
        # text mode exposes the raw exposition
        rc = cli.main(
            ["metrics", "--addr", f"http://127.0.0.1:{srv.port}"]
        )
        assert rc == 0
        assert "dgraph_tpu_num_queries" in capsys.readouterr().out
        # merged /debug/traces spans carry their instance
        body = json.loads(
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/traces", timeout=5
            ).read()
        )
        assert {s.get("instance") for s in body["spans"]} >= {"client"}
    finally:
        srv.stop()

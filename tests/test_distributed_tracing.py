"""End-to-end distributed observability over a multi-process cluster.

One query against a ProcCluster (alpha replicas AND the Zero quorum as
OS processes) must yield ONE trace: the client's root query span, the
alphas' rpc_server spans, and the zero's rpc_server spans all share a
single 128-bit trace id with correct parent links, each process writing
its own JSONL sink (DGRAPH_TPU_TRACE_SINK). The response carries
reference-shaped extensions.server_latency and the per-query profile
assembled from child-server fragments. The cluster metrics surface
merges every process's /debug/prometheus_metrics (counters summed,
per-instance labels), served behind the facade HTTP endpoint and the
`dgraph-tpu metrics` CLI.
"""

import json
import glob
import os
import urllib.request

import pytest

from dgraph_tpu.utils import observe
from dgraph_tpu.worker.harness import ProcCluster


@pytest.fixture(scope="module")
def traced_cluster(tmp_path_factory):
    sink_dir = str(tmp_path_factory.mktemp("trace_sinks"))
    os.environ["DGRAPH_TPU_TRACE_SINK"] = sink_dir
    os.environ["DGRAPH_TPU_TRACE_SAMPLE"] = "1"
    c = ProcCluster(
        n_groups=1, replicas=3, replicated_zero=True, zero_replicas=3
    )
    try:
        c.alter("name: string @index(exact) .\nfollows: [uid] .")
        t = c.new_txn()
        t.mutate_rdf(
            set_rdf=(
                '<0x1> <name> "tr-alice" .\n'
                '<0x2> <name> "tr-bob" .\n'
                "<0x1> <follows> <0x2> .\n"
            ),
            commit_now=True,
        )
        yield c, sink_dir
    finally:
        c.close()
        os.environ.pop("DGRAPH_TPU_TRACE_SINK", None)
        os.environ.pop("DGRAPH_TPU_TRACE_SAMPLE", None)
        observe.TRACER.set_sink(None)


def _sink_spans(sink_dir):
    """{filename: [span dicts]} across every per-process sink file."""
    out = {}
    for path in glob.glob(os.path.join(sink_dir, "spans-*.jsonl")):
        with open(path) as f:
            out[os.path.basename(path)] = [
                json.loads(line) for line in f if line.strip()
            ]
    return out


def test_one_query_one_trace_across_client_alpha_zero(traced_cluster):
    c, sink_dir = traced_cluster
    # force the cached ts-lease block to exhaust so THIS query's read_ts
    # makes a real zero.exec RPC inside the root span
    c.zero.zero.TS_BLOCK = 1
    c.zero.zero._ts_end = -1
    out = c.query(
        '{ q(func: eq(name, "tr-alice")) { name follows { name } } }'
    )
    assert out["data"]["q"][0]["follows"][0]["name"] == "tr-bob"
    tid = int(out["extensions"]["trace_id"], 16)
    assert tid > 1 << 64  # random 128-bit, not a sequential counter

    by_file = _sink_spans(sink_dir)
    in_client = [
        f for f, spans in by_file.items()
        if f"pid{os.getpid()}" in f
        and any(s["trace_id"] == tid for s in spans)
    ]
    in_alpha = [
        f for f, spans in by_file.items()
        if "alpha-" in f and any(s["trace_id"] == tid for s in spans)
    ]
    in_zero = [
        f for f, spans in by_file.items()
        if "zero-" in f and any(s["trace_id"] == tid for s in spans)
    ]
    assert in_client, f"trace missing from client sink: {list(by_file)}"
    assert in_alpha, f"trace missing from alpha sinks: {list(by_file)}"
    assert in_zero, f"trace missing from zero sinks: {list(by_file)}"

    # parent links: exactly one root, and every other span's parent is a
    # span of the same trace (cross-process links resolve)
    trace = [
        s for spans in by_file.values() for s in spans
        if s["trace_id"] == tid
    ]
    ids = {s["span_id"] for s in trace}
    roots = [s for s in trace if s["parent_id"] is None]
    assert len(roots) == 1 and roots[0]["name"] == "query"
    for s in trace:
        if s["parent_id"] is not None:
            assert s["parent_id"] in ids, s
    names = {s["name"] for s in trace}
    assert "rpc_server" in names and "level_task" in names


def test_traced_commit_marks_raft_replication_hop(traced_cluster):
    """A traced commit's proposal rides the raft TCP envelope: the
    append broadcast that replicates it carries the proposer's
    traceparent, and each follower emits a raft_recv span joined to the
    same trace."""
    import time as _t

    c, sink_dir = traced_cluster
    c.new_txn().mutate_rdf(
        set_rdf='<0x3> <name> "tr-carol" .', commit_now=True
    )
    raft = []
    deadline = _t.time() + 10
    while _t.time() < deadline and not raft:
        by_file = _sink_spans(sink_dir)
        raft = [
            s
            for spans in by_file.values()
            for s in spans
            if s["name"] == "raft_recv"
        ]
        if not raft:
            _t.sleep(0.2)
    assert raft, "no raft_recv spans reached any sink"
    commit_tids = {
        s["trace_id"]
        for spans in by_file.values()
        for s in spans
        if s["name"] in ("commit", "rpc_server")
    }
    joined = [s for s in raft if s["trace_id"] in commit_tids]
    assert joined, "raft_recv spans did not join any traced proposal"
    assert all(s["parent_id"] is not None for s in joined)


def test_server_latency_and_profile_are_consistent(traced_cluster):
    c, _ = traced_cluster
    out = c.query(
        '{ q(func: eq(name, "tr-alice")) { name follows { name } } }'
    )
    lat = out["extensions"]["server_latency"]
    parts = (
        lat["parsing_ns"] + lat["assign_timestamp_ns"]
        + lat["processing_ns"] + lat["encoding_ns"]
    )
    assert lat["total_ns"] > 0
    assert lat["processing_ns"] > 0
    assert 0 < parts <= lat["total_ns"]
    prof = out["extensions"]["profile"]
    assert prof["level_tasks"], prof
    for lt in prof["level_tasks"]:
        assert lt["ms"] >= 0 and lt["parents"] >= 1 and lt["level"] >= 1
    levels = {(lt["attr"], lt["level"]) for lt in prof["level_tasks"]}
    assert ("follows", 1) in levels and ("name", 2) in levels
    # child-server fragments piggybacked on the read RPCs
    assert prof["rpc"], prof
    assert any(r["instance"].startswith("alpha-") for r in prof["rpc"])
    assert all(r["ms"] >= 0 and r["calls"] >= 1 for r in prof["rpc"])


def test_merged_metrics_equal_sum_of_per_process_scrapes(traced_cluster):
    c, _ = traced_cluster
    from dgraph_tpu.utils.observe import METRICS

    # per-process scrape over each replica's own debug HTTP listener
    texts = {"client": METRICS.render()}
    for label, addr in c.instance_labels().items():
        info = c.pool.call(addr, "debug.info", timeout=2.0)
        assert info["instance"] == label
        port = info["debug_http_port"]
        assert port > 0
        texts[label] = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/prometheus_metrics",
            timeout=5,
        ).read().decode()

    merged = observe.parse_exposition(c.merged_metrics())
    # counters that only move when queries run (stable between scrapes)
    for name in (
        "dgraph_tpu_num_queries",
        "dgraph_tpu_level_tasks_started",
        "dgraph_tpu_rpc_server_requests_total",
    ):
        expected = sum(
            observe.parse_exposition(t)["counter"].get(name, 0.0)
            for t in texts.values()
        )
        assert merged["counter"].get(name, 0.0) == expected, name
    assert merged["counter"]["dgraph_tpu_num_queries"] >= 1
    assert merged["counter"]["dgraph_tpu_rpc_server_requests_total"] >= 1
    # per-instance series survive the merge
    assert any(
        k.startswith('dgraph_tpu_rpc_server_requests_total{instance="')
        for k in merged["counter"]
    )


def test_microbatch_links_member_traces_one_trace_per_query():
    """PR 5's one-trace-per-query invariant must survive cross-query
    micro-batching: each member query keeps its own single trace (its
    level_task spans parent under its own root), and the coalesced
    batch_dispatch span carries every member's traceparent as span
    links — the attribution seam between the per-query traces and the
    shared dispatch."""
    import threading
    import time as _t

    from dgraph_tpu.serving.microbatch import MicroBatcher
    from dgraph_tpu.utils.observe import TRACER, parse_traceparent

    first_started = threading.Event()
    release_first = threading.Event()

    class StubCache:
        kv = object()
        mem = object()
        read_ts = 11
        calls = 0

        def uids_many(self, keys_list):
            import numpy as np

            StubCache.calls += 1
            if StubCache.calls == 1:
                first_started.set()
                release_first.wait(5)
            rows = [np.arange(3, dtype=np.uint64) for _ in keys_list]
            offs = np.zeros(len(rows) + 1, dtype=np.int64)
            offs[1:] = np.cumsum([len(r) for r in rows])
            return np.concatenate(rows), offs, [None] * len(rows)

    cache = StubCache()
    b = MicroBatcher(inflight_fn=lambda: 3)
    os.environ["DGRAPH_TPU_BATCH_WINDOW_US"] = "1000000"
    trace_ids = {}
    try:

        def member(name):
            # each member is its own query: its own root span/trace
            with TRACER.span("query") as root:
                trace_ids[name] = root.trace_id
                with TRACER.span("level_task", attr="knows"):
                    b.read_uids("knows", cache, [b"k1", b"k2"])

        # member z dispatches immediately and blocks in the read;
        # a and b pile up behind it and coalesce into the next batch
        t0 = threading.Thread(target=member, args=("z",))
        t1 = threading.Thread(target=member, args=("a",))
        t2 = threading.Thread(target=member, args=("b",))
        t0.start()
        first_started.wait(5)
        t1.start()
        _t.sleep(0.05)
        t2.start()
        _t.sleep(0.05)
        release_first.set()
        for th in (t0, t1, t2):
            th.join(10)
    finally:
        os.environ.pop("DGRAPH_TPU_BATCH_WINDOW_US", None)
        release_first.set()

    spans = TRACER.recent(50)
    assert trace_ids["a"] != trace_ids["b"], "queries must not share a trace"
    # every member's level_task stays inside its own query's trace
    for name in ("a", "b"):
        lt = [
            s
            for s in spans
            if s["name"] == "level_task"
            and s["trace_id"] == trace_ids[name]
        ]
        assert lt, f"member {name} lost its level_task span"
        assert all(s["parent_id"] is not None for s in lt)
    # the coalesced dispatch links BOTH members via traceparent attrs
    batch = [s for s in spans if s["name"] == "batch_dispatch"]
    assert batch, "no batch_dispatch span for the coalesced read"
    links = [
        parse_traceparent(v).trace_id
        for s in batch
        for k, v in s["attrs"].items()
        if k.startswith("link.")
    ]
    assert {trace_ids["a"], trace_ids["b"]} <= set(links)


def test_cli_metrics_against_running_cluster(traced_cluster, capsys):
    c, _ = traced_cluster
    from dgraph_tpu import cli
    from dgraph_tpu.api.http_server import HTTPServer

    srv = HTTPServer(c, port=0).start()
    try:
        rc = cli.main(
            [
                "metrics",
                "--addr", f"http://127.0.0.1:{srv.port}",
                "--json",
            ]
        )
        assert rc == 0
        got = json.loads(capsys.readouterr().out)
        assert got["counters"]["dgraph_tpu_num_queries"] >= 1
        # merged value equals the sum of the per-instance series the
        # same scrape carries
        per_inst = sum(
            v
            for k, v in got["counters"].items()
            if k.startswith("dgraph_tpu_num_queries{")
        )
        assert got["counters"]["dgraph_tpu_num_queries"] == per_inst
        # text mode exposes the raw exposition
        rc = cli.main(
            ["metrics", "--addr", f"http://127.0.0.1:{srv.port}"]
        )
        assert rc == 0
        assert "dgraph_tpu_num_queries" in capsys.readouterr().out
        # merged /debug/traces spans carry their instance
        body = json.loads(
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/traces", timeout=5
            ).read()
        )
        assert {s.get("instance") for s in body["spans"]} >= {"client"}
    finally:
        srv.stop()

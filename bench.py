"""Headline benchmark: batched sorted-UID intersect on the device.

Mirrors the reference's flagship checked-in number — IntersectCompressedWithBin
10-vs-1M at ~2.43us/op on CPU (/root/reference/algo/benchmarks:45). We run
the same shape as a *batch*: 256 independent 10-vs-1M intersections in one
vmapped dispatch (the way the query engine issues them), and report the
per-op amortized latency.

Also reports the compressed-domain path (ops/packed_setops.py — the
direct analog of IntersectCompressedWithBin, which never fully decodes):

  intersect_packed_10v1M_batch256  ns/op for 256 block-skip intersects
  decode_bytes_per_query           decoded vs full-decode bytes across the
                                   selectivity ratio ladder, both operands
                                   compressed; every rung reports which
                                   block kernels ran (bitmap/probe/gallop
                                   — the adaptive set-representation
                                   engine keeps even the dense rungs at
                                   zero decode)

Prints one JSON line per metric:
  {"metric": ..., "value": N, "unit": "ns/op", "vs_baseline": N}
vs_baseline > 1.0 means faster than the reference's 2430 ns/op.
The packed metrics are also stamped into BENCH_PACKED.json via
benchmarks/stamp.guarded_write (a cpu_fallback run cannot overwrite a TPU
capture).
"""

import json
import signal
import sys
import time

import numpy as np

REF_NS_PER_OP = 2430.0  # algo/benchmarks:45 IntersectCompressedWithBin/ratio=100000
BATCH = 256
SMALL, BIG = 10, 1_000_000
PAD_SMALL = 16
PAD_BIG = 1 << 20


def _watchdog(seconds):
    def handler(signum, frame):
        print(
            json.dumps(
                {
                    "metric": "intersect_10v1M_batch256",
                    "value": None,
                    "unit": "ns/op",
                    "vs_baseline": 0.0,
                    "error": f"device init exceeded {seconds}s (tunnel down?)",
                }
            )
        )
        sys.stdout.flush()
        import os

        os._exit(2)

    signal.signal(signal.SIGALRM, handler)
    signal.alarm(seconds)


def _probe_device(timeout_s: int = 240) -> bool:
    """Check the accelerator backend initializes, in a SUBPROCESS — a dead
    remote-TPU tunnel hangs init un-interruptibly in-process. Returns True
    when the real device is usable."""
    import subprocess

    try:
        got = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s,
            capture_output=True,
        )
        return got.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def _build_fanout_graph(fanout=100, pool=200_000):
    """The 3-level 1 -> f -> f^2 -> f^3 traversal graph (~1.01M edges at
    f=100) shared by the fan-out and observability benchmarks. Returns
    (server, query, edges, load_seconds)."""
    from dgraph_tpu.api.server import Server
    from dgraph_tpu.loaders.bulk2 import ParallelBulkLoader

    f = fanout
    rng = np.random.default_rng(7)
    l1 = [0x100 + i for i in range(f)]
    l2 = [0x10000 + i for i in range(f * f)]
    base3 = 0x1000000
    lines = [f"<0x1> <child> <{hex(v)}> ." for v in l1]
    for i, v in enumerate(l2):
        lines.append(f"<{hex(l1[i // f])}> <child> <{hex(v)}> .")
    tgts = rng.integers(base3, base3 + pool, size=len(l2) * f)
    for i, v in enumerate(l2):
        hv = hex(v)
        for t in tgts[i * f : (i + 1) * f]:
            lines.append(f"<{hv}> <child> <{hex(int(t))}> .")
    edges = len(lines)

    s = Server()
    s.alter("child: [uid] .")
    t0 = time.perf_counter()
    ParallelBulkLoader(s).load_text("\n".join(lines))
    load_s = time.perf_counter() - t0
    print(f"fanout graph: {edges} edges loaded in {load_s:.1f}s",
          file=sys.stderr)
    q = "{ q(func: uid(0x1)) { child { child { c: count(child) } } } }"
    return s, q, edges, load_s


def _bench_fanout(platform, fanout=100, pool=200_000):
    """Level-batched fan-out headline (BENCH_FANOUT.json):

      fanout_3level_1M        3-level traversal latency over ~1.01M edges
                              (1 -> 100 -> 10k -> 1M), batched level tasks
                              vs the per-uid baseline
                              (DGRAPH_TPU_LEVEL_BATCH=0), both warm
      level_batch_read_calls  cache round-trips per query in each mode —
                              the batched executor issues ONE uids_many
                              per (predicate, level) instead of one
                              uids_tok per parent uid
    """
    import os

    from benchmarks import stamp
    from dgraph_tpu.posting.lists import READ_COUNTERS

    f = fanout
    s, q, edges, load_s = _build_fanout_graph(fanout, pool)

    def run_mode(batch: bool):
        os.environ["DGRAPH_TPU_LEVEL_BATCH"] = "1" if batch else "0"
        s.query(q)  # warm the decoded-list caches
        p0 = READ_COUNTERS.point_reads
        b0 = READ_COUNTERS.batch_reads
        best = float("inf")
        reps = 3
        for _ in range(reps):
            t0 = time.perf_counter()
            out = s.query(q)
            best = min(best, time.perf_counter() - t0)
        trips = (
            (READ_COUNTERS.point_reads - p0)
            + (READ_COUNTERS.batch_reads - b0)
        ) / reps
        n2 = sum(
            len(c1.get("child", []))
            for c1 in out["data"]["q"][0]["child"]
        )
        return best * 1e3, trips, n2

    per_uid_ms, per_uid_trips, n2 = run_mode(batch=False)
    batched_ms, batched_trips, n2b = run_mode(batch=True)
    os.environ.pop("DGRAPH_TPU_LEVEL_BATCH", None)
    assert n2 == n2b, (n2, n2b)
    reduction = per_uid_trips / max(1.0, batched_trips)
    print(
        json.dumps(
            {
                "metric": "fanout_3level_1M",
                "value": round(batched_ms, 2),
                "unit": "ms",
                "per_uid_baseline_ms": round(per_uid_ms, 2),
                "speedup_x": round(per_uid_ms / batched_ms, 2),
                "platform": platform,
            }
        )
    )
    print(
        json.dumps(
            {
                "metric": "level_batch_read_calls",
                "value": batched_trips,
                "unit": "round-trips/query",
                "per_uid_baseline": per_uid_trips,
                "reduction_x": round(reduction, 1),
                "platform": platform,
            }
        )
    )
    stamp.guarded_write(
        "BENCH_FANOUT.json",
        {
            "fanout_3level_1M_ms": {
                "batched": round(batched_ms, 2),
                "per_uid_baseline": round(per_uid_ms, 2),
                "speedup_x": round(per_uid_ms / batched_ms, 2),
            },
            "level_batch_read_calls": {
                "batched": batched_trips,
                "per_uid_baseline": per_uid_trips,
                "reduction_x": round(reduction, 1),
            },
            "graph": {
                "edges": edges,
                "levels": 3,
                "fanout": f,
                "l2_parents": f * f,
                "l3_rows": int(n2),
                "load_seconds": round(load_s, 1),
            },
        },
        platform,
    )


def main():
    _watchdog(900)
    platform_note = ""
    if not _probe_device():
        # tunnel down: a labeled CPU number beats a null (the engine makes
        # the same call at runtime via dispatch._device_ready)
        print(
            "device probe failed (tunnel down?) — CPU fallback",
            file=sys.stderr,
        )
        from dgraph_tpu.devsetup import force_cpu

        force_cpu()
        platform_note = "_fallback"
    import jax
    import jax.numpy as jnp

    from dgraph_tpu.ops import setops

    devs = jax.devices()
    platform = devs[0].platform + platform_note
    print(f"bench device: {devs[0]}", file=sys.stderr)

    rng = np.random.default_rng(0)
    big = np.unique(
        rng.integers(0, 1 << 31, BIG + BIG // 8, dtype=np.uint64)
    ).astype(np.uint32)[:BIG]
    B = np.full((PAD_BIG,), 0xFFFFFFFF, np.uint32)
    B[:BIG] = big

    A = np.full((BATCH, PAD_SMALL), 0xFFFFFFFF, np.uint32)
    LA = np.zeros((BATCH,), np.int32)
    for i in range(BATCH):
        # half the small lists are drawn from big (hits), half random
        if i % 2 == 0:
            a = np.sort(rng.choice(big, SMALL, replace=False))
        else:
            a = np.unique(rng.integers(0, 1 << 31, SMALL, dtype=np.uint64)).astype(
                np.uint32
            )[:SMALL]
        A[i, : len(a)] = a
        LA[i] = len(a)

    fn = jax.jit(
        jax.vmap(setops.intersect, in_axes=(0, 0, None, None)),
        static_argnums=(),
    )
    Ad, LAd = jnp.asarray(A), jnp.asarray(LA)
    Bd, LBd = jnp.asarray(B), jnp.asarray(np.int32(BIG))

    # warmup/compile
    out = fn(Ad, LAd, Bd, LBd)
    jax.block_until_ready(out)

    times = []
    for _ in range(10):
        t0 = time.perf_counter()
        out = fn(Ad, LAd, Bd, LBd)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)

    # ratio sweep (stderr only): mirrors algo/benchmarks:2-46 ratio ladder —
    # small side fixed at 10, big side 10*ratio, batch 256
    for ratio in (1, 10, 100, 1000, 10000):
        big_n = SMALL * ratio
        pad = max(16, 1 << (big_n - 1).bit_length())
        Bs = np.full((pad,), 0xFFFFFFFF, np.uint32)
        Bs[:big_n] = np.sort(rng.choice(big, big_n, replace=False))
        f2 = jax.jit(jax.vmap(setops.intersect, in_axes=(0, 0, None, None)))
        o = f2(Ad, LAd, jnp.asarray(Bs), jnp.asarray(np.int32(big_n)))
        jax.block_until_ready(o)
        t0 = time.perf_counter()
        for _ in range(5):
            o = f2(Ad, LAd, jnp.asarray(Bs), jnp.asarray(np.int32(big_n)))
            jax.block_until_ready(o)
        dt = (time.perf_counter() - t0) / 5
        print(
            f"sweep ratio={ratio}: {dt/BATCH*1e9:.1f} ns/op "
            f"(batch {BATCH} in {dt*1e3:.3f} ms)",
            file=sys.stderr,
        )
    signal.alarm(0)

    per_op_ns = (np.median(times) / BATCH) * 1e9
    result = {
        "metric": "intersect_10v1M_batch256",
        "value": round(per_op_ns, 1),
        "unit": "ns/op",
        "vs_baseline": round(REF_NS_PER_OP / per_op_ns, 3),
        "platform": platform,
    }
    print(
        f"platform={platform} median_batch_ms={np.median(times)*1e3:.3f} "
        f"hits={int(np.asarray(out[1]).sum())}",
        file=sys.stderr,
    )
    print(json.dumps(result))
    _bench_packed(rng, big, platform)
    _bench_fanout(platform)
    _bench_obs(platform)
    _bench_chaos(platform)


def _bench_packed(rng, big, platform):
    """Compressed-domain headline: 256 block-skip 10-vs-1M intersects with
    the big side kept packed (the shape IntersectCompressedWithBin times in
    the reference), plus the decoded-bytes ladder across selectivity
    ratios."""
    from benchmarks import stamp
    from dgraph_tpu.codec import uidpack
    from dgraph_tpu.ops import packed_setops

    b64 = big.astype(np.uint64)
    pack = uidpack.encode(b64)
    smalls = []
    for i in range(BATCH):
        if i % 2 == 0:
            a = np.sort(rng.choice(b64, SMALL, replace=False))
        else:
            a = np.unique(
                rng.integers(0, 1 << 31, SMALL, dtype=np.uint64)
            )[:SMALL]
        smalls.append(a)

    # warm (first-touch candidate metadata: block_maxes builds once)
    packed_setops.intersect_packed(smalls[0], pack)
    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        for a in smalls:
            packed_setops.intersect_packed(a, pack)
        times.append(time.perf_counter() - t0)
    per_op_ns = (np.median(times) / BATCH) * 1e9
    print(
        json.dumps(
            {
                "metric": "intersect_packed_10v1M_batch256",
                "value": round(per_op_ns, 1),
                "unit": "ns/op",
                "vs_baseline": round(REF_NS_PER_OP / per_op_ns, 3),
                "platform": platform,
            }
        )
    )

    # decoded-bytes ladder: per-query decode cost across the selectivity
    # ratio ladder, BOTH operands offered compressed (the posting-list vs
    # posting-list shape every traversal sees). The adaptive per-block
    # engine keeps every rung compressed-domain — bitmap AND on dense
    # block pairs, galloping merge on sparse ones, bitmap probes on mixed
    # — so even the dense rungs (ratio 1/100, which used to fall back to
    # an 8-16 MB full decode) materialize nothing. Each rung reports the
    # per-representation kernel counts alongside the byte accounting.
    from dgraph_tpu.query.dispatch import PackedOperand, SetOpDispatcher

    disp = SetOpDispatcher()
    ladder = []
    for ratio in (1, 100, 1000, 100000):
        n_small = max(10, len(b64) // ratio)
        a = np.sort(rng.choice(b64, n_small, replace=False))
        pack_a = uidpack.encode(a)
        packed_setops.reset_counters()
        got = disp.run_pairs(
            "intersect", [(PackedOperand(pack_a), PackedOperand(pack))]
        )[0]
        c = packed_setops.counters()
        full = (pack.num_uids + pack_a.num_uids) * 8
        decoded = c["decoded_bytes"] if c["packed_ops"] else full
        ladder.append(
            {
                "ratio": ratio,
                "packed_path": bool(c["packed_ops"]),
                "kernels": {
                    "bitmap": int(c["bitmap_pairs"]),
                    "probe": int(c["probe_pairs"]),
                    "gallop": int(c["gallop_pairs"]),
                },
                "streamed_bytes": int(c["streamed_uids"]) * 8,
                "decoded_bytes_per_query": decoded,
                "full_decode_bytes": full,
                "reduction_x": round(full / max(1, decoded), 1),
                "result_n": int(len(got)),
            }
        )
        print(
            f"packed ladder ratio={ratio}: packed={bool(c['packed_ops'])} "
            f"kernels(b/p/g)={int(c['bitmap_pairs'])}/"
            f"{int(c['probe_pairs'])}/{int(c['gallop_pairs'])} "
            f"decoded={decoded}B streamed={int(c['streamed_uids'])*8}B "
            f"full={full}B reduction={full/max(1,decoded):.1f}x",
            file=sys.stderr,
        )
    headline = ladder[-1]  # the 10-vs-1M (most selective) row
    print(
        json.dumps(
            {
                "metric": "decode_bytes_per_query",
                "value": headline["decoded_bytes_per_query"],
                "unit": "bytes",
                "reduction_x": headline["reduction_x"],
                "ladder": ladder,
                "platform": platform,
            }
        )
    )
    stamp.guarded_write(
        "BENCH_PACKED.json",
        {
            "intersect_packed_10v1M_batch256_ns": round(per_op_ns, 1),
            "decode_bytes_ladder": ladder,
        },
        platform,
    )


def _bench_obs(platform, fanout=100, pool=200_000):
    """Tracing overhead (BENCH_OBS.json): the fanout_3level_1M warm
    query under three modes — tracing OFF (DGRAPH_TPU_TRACE=0),
    enabled-but-UNSAMPLED (the production default posture: context
    propagates, histograms fill, nothing exported), and FULLY SAMPLED
    with every span written to a JSONL sink — plus the sink's raw
    spans/s throughput. The acceptance bar: enabled-unsampled must stay
    within 5% of off, proving instrumentation is off the hot path."""
    import os
    import tempfile

    from benchmarks import stamp
    from dgraph_tpu.utils import observe
    from dgraph_tpu.x import config

    s, q, edges, load_s = _build_fanout_graph(fanout, pool)

    def run_mode(trace: bool, sample: float, sink: str = "", env=None,
                 reps: int = 5):
        config.set_env("TRACE", trace)
        config.set_env("TRACE_SAMPLE", sample)
        for k, v in (env or {}).items():
            config.set_env(k, v)
        observe.TRACER.set_sink(sink or None)
        try:
            s.query(q)  # warm caches under the mode's settings
            best = float("inf")
            for _ in range(reps):
                t0 = time.perf_counter()
                s.query(q)
                best = min(best, time.perf_counter() - t0)
            return best * 1e3
        finally:
            observe.TRACER.set_sink(None)
            config.unset_env("TRACE")
            config.unset_env("TRACE_SAMPLE")
            for k in env or {}:
                config.unset_env(k)

    sink_path = os.path.join(
        tempfile.mkdtemp(prefix="dgraph_obs_bench_"), "spans.jsonl"
    )
    off_ms = run_mode(trace=False, sample=0.0)
    unsampled_ms = run_mode(trace=True, sample=0.0)
    sampled_ms = run_mode(trace=True, sample=1.0, sink=sink_path)
    overhead_pct = (unsampled_ms - off_ms) / off_ms * 100.0

    # always-on accounting A/B: per-tablet traffic + exemplars + query
    # digests + metrics history (all on by default).  The flight-recorder
    # gate requires the always-on arm within 1% of accounting-off,
    # asserted in-capture — interleaved best-of-9 pairs so minute-scale
    # box drift cancels
    from dgraph_tpu.serving.digest import DIGESTS

    observe.TABLETS.clear()
    DIGESTS.reset()
    _obs_off = {"TABLET_TRAFFIC": 0, "EXEMPLARS": 0,
                "DIGEST": 0, "HISTORY": 0}
    _obs_on = {"TABLET_TRAFFIC": 1, "EXEMPLARS": 1,
               "DIGEST": 1, "HISTORY": 1}
    acct_off_ms = float("inf")
    acct_on_ms = float("inf")
    for _ in range(9):
        acct_off_ms = min(acct_off_ms, run_mode(
            trace=True, sample=0.0, env=_obs_off, reps=1,
        ))
        acct_on_ms = min(acct_on_ms, run_mode(
            trace=True, sample=0.0, env=_obs_on, reps=1,
        ))
    assert observe.TABLETS.snapshot(), "accounting arm recorded nothing"
    assert DIGESTS.snapshot(), "digest arm recorded nothing"
    acct_overhead_pct = (acct_on_ms - acct_off_ms) / acct_off_ms * 100.0
    assert acct_overhead_pct <= 1.0, (
        f"always-on accounting (traffic + exemplars + digests + "
        f"history) cost {acct_overhead_pct:.2f}% on fanout_3level_1M "
        f"(on {acct_on_ms:.2f}ms vs off {acct_off_ms:.2f}ms); "
        f"the flight-recorder gate requires <= 1%"
    )

    # profiler-armed leg, reported separately (sampling is an opt-in,
    # bounded capture — not part of the always-on <=1% contract): the
    # same query timed while a wall-clock capture is actively walking
    # sys._current_frames() at PROFILE_HZ
    import threading as _threading

    from dgraph_tpu.utils.profiler import PROFILER

    prof_base_ms = run_mode(trace=True, sample=0.0, reps=3)
    capture_s = min(5.0, max(0.5, 10 * prof_base_ms / 1e3))
    folded_box = {}
    cap = _threading.Thread(
        target=lambda: folded_box.setdefault(
            "folded", PROFILER.profile(capture_s)
        ),
        daemon=True,
    )
    cap.start()
    prof_armed_ms = run_mode(trace=True, sample=0.0, reps=3)
    cap.join()
    assert folded_box.get("folded"), "profiler capture saw no stacks"
    prof_overhead_pct = (
        (prof_armed_ms - prof_base_ms) / prof_base_ms * 100.0
    )

    # raw JSONL sink throughput: how many spans/s the exporter absorbs
    n_spans = 20_000
    tr = observe.Tracer(capacity=16, sink_path=sink_path + ".tput")
    t0 = time.perf_counter()
    for _ in range(n_spans):
        with tr.span("bench"):
            pass
    sink_spans_per_s = n_spans / (time.perf_counter() - t0)

    for metric, value, extra in (
        (
            "fanout_3level_1M_traced",
            round(unsampled_ms, 2),
            {
                "unit": "ms",
                "tracing_off_ms": round(off_ms, 2),
                "fully_sampled_ms": round(sampled_ms, 2),
                "unsampled_overhead_pct": round(overhead_pct, 2),
            },
        ),
        (
            "fanout_3level_1M_accounting",
            round(acct_on_ms, 2),
            {
                "unit": "ms",
                "accounting_off_ms": round(acct_off_ms, 2),
                "overhead_pct": round(acct_overhead_pct, 2),
                "digest_shapes": len(DIGESTS.snapshot()),
            },
        ),
        (
            "fanout_3level_1M_profiler_armed",
            round(prof_armed_ms, 2),
            {
                "unit": "ms",
                "unarmed_ms": round(prof_base_ms, 2),
                "overhead_pct": round(prof_overhead_pct, 2),
            },
        ),
        (
            "trace_sink_throughput",
            round(sink_spans_per_s),
            {"unit": "spans/s"},
        ),
    ):
        print(
            json.dumps(
                {"metric": metric, "value": value, **extra,
                 "platform": platform}
            )
        )
    stamp.guarded_write(
        "BENCH_OBS.json",
        {
            "fanout_3level_1M_ms": {
                "tracing_off": round(off_ms, 2),
                "enabled_unsampled": round(unsampled_ms, 2),
                "fully_sampled_jsonl": round(sampled_ms, 2),
            },
            "unsampled_overhead_pct": round(overhead_pct, 2),
            "traffic_accounting_ms": {
                "accounting_off": round(acct_off_ms, 2),
                "accounting_on": round(acct_on_ms, 2),
                "overhead_pct": round(acct_overhead_pct, 2),
            },
            "profiler_armed_ms": {
                "unarmed": round(prof_base_ms, 2),
                "armed": round(prof_armed_ms, 2),
                "overhead_pct": round(prof_overhead_pct, 2),
            },
            "jsonl_sink_spans_per_s": round(sink_spans_per_s),
            "graph": {"edges": edges, "load_seconds": round(load_s, 1)},
        },
        platform,
    )


def _build_flat_graph(n=1_000_000):
    """One hub with n uid-pred followers — the result-size ladder for
    the encoder bench (pagination slices the SAME level buffers, so
    every rung measures encoding over identical executor work)."""
    from dgraph_tpu.api.server import Server
    from dgraph_tpu.loaders.bulk2 import ParallelBulkLoader

    s = Server()
    s.alter("follow: [uid] .")
    lines = [f"<0x1> <follow> <{hex(0x10 + i)}> ." for i in range(n)]
    t0 = time.perf_counter()
    ParallelBulkLoader(s).load_text("\n".join(lines))
    load_s = time.perf_counter() - t0
    print(f"flat graph: {n} edges loaded in {load_s:.1f}s", file=sys.stderr)
    return s, load_s


def _encode_rung(s, q, reps=3):
    """Best-of-reps (encoding_ns, total_ns, bytes, share) for q through
    the PUBLIC query path with `want='raw'` (the serving surface — no
    dict parse-back in the loop)."""
    s.query(q, want="raw")  # warm decoded-list caches + plan cache
    best = None
    for _ in range(reps):
        res = s.query(q, want="raw")
        lat = res["extensions"]["server_latency"]
        enc = res["extensions"]["profile"]["encode"]
        row = (
            int(lat["encoding_ns"]),
            int(lat["total_ns"]),
            int(enc["bytes"]),
            float(enc.get("share", 0.0)),
        )
        if best is None or row[0] < best[0]:
            best = row
    return best


def _bench_encode(platform, sanity=False):
    """Streaming arena encoder ladder (BENCH_ENCODE.json):

      encode_share_ladder   encoding_ns (from extensions.server_latency)
                            and encode share of total at 1k/100k/1M-uid
                            results, dict encoder
                            (DGRAPH_TPU_STREAM_ENCODER=0) vs streaming
                            arena (=1) over the same warm server — the
                            A/B rides the registered escape hatch, both
                            paths producing the SAME wire bytes

    --encode-sanity: one small rung, assert byte-identity + print the
    numbers, no stamping (the tools/check.sh smoke gate).
    """
    import os

    from benchmarks import stamp

    n_max = 100_000 if sanity else 1_000_000
    rungs = [100_000] if sanity else [1_000, 100_000, 1_000_000]
    s, load_s = _build_flat_graph(n_max)

    ladder = []
    for n in rungs:
        q = "{ q(func: uid(0x1)) { follow(first: %d) { uid } } }" % n
        row = {"uids": n}
        raws = {}
        for flag, key in (("0", "dict"), ("1", "stream")):
            os.environ["DGRAPH_TPU_STREAM_ENCODER"] = flag
            enc_ns, total_ns, nbytes, share = _encode_rung(
                s, q, reps=1 if sanity else 3
            )
            raws[key] = s.query(q, want="raw")["data"].raw
            row[key] = {
                "encoding_ns": enc_ns,
                "total_ns": total_ns,
                "bytes": nbytes,
                "encode_share": round(share, 4),
            }
        os.environ.pop("DGRAPH_TPU_STREAM_ENCODER", None)
        assert raws["dict"] == raws["stream"], (
            f"byte-identity violated at {n} uids"
        )
        row["reduction_x"] = round(
            row["dict"]["encoding_ns"]
            / max(1, row["stream"]["encoding_ns"]),
            2,
        )
        ladder.append(row)
        print(
            json.dumps(
                {
                    "metric": "encoding_ns",
                    "uids": n,
                    "dict": row["dict"]["encoding_ns"],
                    "stream": row["stream"]["encoding_ns"],
                    "reduction_x": row["reduction_x"],
                    "encode_share_dict": row["dict"]["encode_share"],
                    "encode_share_stream": row["stream"]["encode_share"],
                    "platform": platform,
                }
            )
        )
    if sanity:
        print("encode sanity: byte-identity + ladder ok", file=sys.stderr)
        return
    stamp.guarded_write(
        "BENCH_ENCODE.json",
        {
            "encode_share_ladder": ladder,
            "graph": {"edges": n_max, "load_seconds": round(load_s, 1)},
        },
        platform,
    )


def _bench_vector(platform, sanity=False):
    """Quantized vector engine A/B (BENCH_VECTOR.json, ISSUE 9):

      float_brute        the jitted float32 batched scan, forced via the
                         DGRAPH_TPU_VEC_QUANT=0 escape hatch — the exact
                         baseline AND the recall ground truth
      quant_brute        the int8 scan kernels, full corpus (exact after
                         the float32 rerank — recall should be ~1.0)
      quant_ivf          the incremental quantized IVF tier (sampled
                         mini-batch k-means + top-2 cell assignment);
                         reports build seconds vs the r5 255s sync train
      incremental        inserts + removes against the built IVF index:
                         asserts NO rebuild ran and results stay correct

    All tiers run in the SAME process over the SAME corpus (same-run
    A/B). --vector-sanity shrinks the corpus to a ~5s gate that asserts
    exact A/B top-k equality + recall floors, and stamps nothing.
    """
    import gc
    import os

    from benchmarks import stamp
    from dgraph_tpu.models import vector as vecmod
    from dgraph_tpu.models.vector import VectorIndex

    n, d = (20_000, 64) if sanity else (1_000_000, 768)
    k, qb = 10, 64
    nq = 64 if sanity else 256
    if sanity:
        # the quantized engine normally wants >= _QUANT_MIN live rows
        vecmod._QUANT_MIN = 1
    rng = np.random.default_rng(1)
    # mixture-of-gaussians corpus: real embedding sets cluster; pure
    # isotropic gaussian is IVF's pathological worst case (distance
    # concentration) and misrepresents production recall
    n_clusters = 256
    centers = rng.standard_normal((n_clusters, d)).astype(np.float32) * 4.0
    V = (
        centers[rng.integers(0, n_clusters, n)]
        + rng.standard_normal((n, d)).astype(np.float32)
    )
    Qs = (
        centers[rng.integers(0, n_clusters, nq)]
        + rng.standard_normal((nq, d))
    ).astype(np.float32)
    uids = np.arange(1, n + 1, dtype=np.uint64)

    def timed_batches(ix):
        ix.search_batch(Qs[:qb], k)  # warm (compile / quantize view)
        t0 = time.perf_counter()
        rows = [
            ix.search_batch(Qs[i : i + qb], k) for i in range(0, nq, qb)
        ]
        dt = time.perf_counter() - t0
        return np.concatenate(rows, axis=0), nq / dt

    def recall(got, exact):
        hits = sum(
            len(set(map(int, got[i])) & set(map(int, exact[i])))
            for i in range(nq)
        )
        return hits / (nq * k)

    out = {"n_vectors": n, "dim": d, "query_batch": qb, "k": k}

    # -- A: float32 jit brute (escape hatch) — baseline + ground truth
    os.environ["DGRAPH_TPU_VEC_QUANT"] = "0"
    idx = VectorIndex("emb", ivf_threshold=1 << 62)
    idx.bulk_load(uids, V)
    exact, float_qps = timed_batches(idx)
    assert not idx._use_quant()
    idx._device = None
    del idx
    gc.collect()
    out["float_brute_qps"] = round(float_qps, 1)

    # -- B: quantized int8 brute (native kernels + float32 rerank)
    os.environ["DGRAPH_TPU_VEC_QUANT"] = "1"
    idxq = VectorIndex("emb", ivf_threshold=1 << 62)
    idxq.bulk_load(uids, V)
    t0 = time.perf_counter()
    idxq._quant_view()  # quantize the corpus (no IVF at this threshold)
    out["quantize_seconds"] = round(time.perf_counter() - t0, 1)
    assert idxq._use_quant(), "quantized engine must engage for the A/B"
    qgot, quant_qps = timed_batches(idxq)
    out["quant_brute_qps"] = round(quant_qps, 1)
    out["quant_brute_recall_at_10"] = round(recall(qgot, exact), 3)
    del idxq
    gc.collect()

    # -- C: quantized incremental IVF (build + serve)
    idx2 = VectorIndex("emb2", ivf_threshold=1)
    idx2.bulk_load(uids, V)
    t0 = time.perf_counter()
    idx2._quant_view()  # quantize + centroid train + cell assignment
    build_s = time.perf_counter() - t0
    out["ivf_build_seconds"] = round(build_s, 1)
    igot, ivf_qps = timed_batches(idx2)
    out["quant_ivf_qps"] = round(ivf_qps, 1)
    out["quant_ivf_recall_at_10"] = round(recall(igot, exact), 3)

    idx2.search(Qs[0], k)  # warm the single-query path
    t0 = time.perf_counter()
    for q in Qs[:10]:
        idx2.search(q, k)
    out["ivf_latency_ms_single"] = round(
        (time.perf_counter() - t0) / 10 * 1e3, 2
    )

    # -- D: incremental mutations serve correct results, NO rebuild
    builds_before = (idx2.build_count, idx2.repartition_count)
    new_vecs = centers[rng.integers(0, n_clusters, 64)] + rng.standard_normal(
        (64, d)
    ).astype(np.float32)
    t0 = time.perf_counter()
    for j in range(64):
        idx2.insert(n + 1 + j, new_vecs[j])
    for u in rng.choice(uids, 64, replace=False):
        idx2.remove(int(u))
    res = idx2.search_batch(new_vecs[:16], k)
    mut_ms = (time.perf_counter() - t0) * 1e3
    assert (idx2.build_count, idx2.repartition_count) == builds_before, (
        "mutation triggered a rebuild/repartition"
    )
    assert all(int(res[j][0]) == n + 1 + j for j in range(16)), (
        "inserted vectors not served as their own nearest neighbor"
    )
    out["incremental_64ins_64del_plus_16q_ms"] = round(mut_ms, 1)

    best_qps = max(out["quant_brute_qps"], out["quant_ivf_qps"])
    out["speedup_x_vs_float_brute"] = round(best_qps / max(float_qps, 1e-9), 1)
    out["build_speedup_x_vs_r5_sync"] = round(255.0 / max(build_s, 1e-9), 1)
    out["native_kernels"] = __import__(
        "dgraph_tpu.native", fromlist=["NATIVE_AVAILABLE"]
    ).NATIVE_AVAILABLE
    os.environ.pop("DGRAPH_TPU_VEC_QUANT", None)

    for metric in (
        "float_brute_qps", "quant_brute_qps", "quant_ivf_qps",
        "quant_brute_recall_at_10", "quant_ivf_recall_at_10",
        "ivf_build_seconds", "speedup_x_vs_float_brute",
    ):
        print(
            json.dumps(
                {"metric": metric, "value": out[metric],
                 "platform": platform}
            )
        )

    if sanity:
        # exact A/B identity: both brute tiers are exact, so each row's
        # top-k SET must match (ordering of ulp-close neighbors may
        # differ between the XLA matmul and the rerank dot)
        assert np.array_equal(np.sort(qgot, 1), np.sort(exact, 1)), (
            "quant/float brute A/B differ"
        )
        assert out["quant_ivf_recall_at_10"] >= 0.95, out
        print("vector sanity: A/B identity + recall + no-rebuild ok",
              file=sys.stderr)
        return
    stamp.guarded_write("BENCH_VECTOR.json", out, platform)


def _bench_chaos(platform):
    """Retry-storm visibility (BENCH_CHAOS.json): a fixed-seed fault
    schedule (drops + delays + disconnects + lost acks) over an
    in-process RPC pair, with idempotent retries. Stamps wall time and
    the fault/retry/idempotency counters so a regression that turns
    recoverable faults into retry storms — or worse, double-applies —
    shows up as a diff in the artifact."""
    import time as _t

    from benchmarks import stamp
    from dgraph_tpu.conn import faults as _faults
    from dgraph_tpu.conn.faults import FaultPlan
    from dgraph_tpu.conn.retry import Deadline
    from dgraph_tpu.conn.rpc import RpcClient, RpcServer
    from dgraph_tpu.utils.observe import METRICS

    N = 400
    srv = RpcServer().start()
    applied = []
    srv.register("apply", lambda a: applied.append(a["v"]) or {"ok": True})
    keys = (
        "rpc_retries_total", "rpc_giveups_total", "faults_injected_total",
        "fault_drop_total", "fault_delay_total", "fault_disconnect_total",
        "idem_hits_total",
    )
    before = {k: METRICS.value(k) for k in keys}
    _faults.install(
        FaultPlan(
            seed=2024,
            rules=[
                {"point": "send", "action": "drop", "p": 0.06},
                {"point": "send", "action": "delay", "p": 0.10,
                 "delay_ms": 2},
                {"point": "send", "action": "disconnect", "p": 0.04},
                {"point": "resp", "action": "drop", "p": 0.04},
            ],
        )
    )
    try:
        c = RpcClient(srv.addr, timeout=0.1)
        t0 = _t.perf_counter()
        for i in range(N):
            c.call("apply", {"v": i}, timeout=0.1,
                   deadline=Deadline.after(10.0), idem=True)
        wall = _t.perf_counter() - t0
    finally:
        _faults.reset()
        srv.close()
    delta = {k: METRICS.value(k) - before[k] for k in keys}
    lost = N - len(set(applied))
    dupes = len(applied) - len(set(applied))
    result = {
        "metric": "chaos_rpc_400calls",
        "value": round(wall, 3),
        "unit": "s",
        "retries_per_100_calls": round(delta["rpc_retries_total"] / N * 100, 1),
        "faults_injected": delta["faults_injected_total"],
        "idem_hits": delta["idem_hits_total"],
        "lost_applies": lost,
        "double_applies": dupes,
        "platform": platform,
    }
    print(json.dumps(result))
    assert lost == 0 and dupes == 0, (lost, dupes)
    stamp.guarded_write(
        "BENCH_CHAOS.json",
        {
            "chaos_rpc_400calls_s": round(wall, 3),
            "seed": 2024,
            "counters": {k: delta[k] for k in keys},
            "retries_per_100_calls": result["retries_per_100_calls"],
            "lost_applies": lost,
            "double_applies": dupes,
        },
        platform,
    )


def _explain_sanity():
    """The ~5s CI gate for the EXPLAIN surface (tools/check.sh
    --explain-sanity): debug on/off byte-equality over the DQL golden
    smoke subset, schema validation of every captured plan, and one
    rendered-plan snapshot through the CLI renderer."""
    import os as _os

    from dgraph_tpu.api.server import Server
    from dgraph_tpu.cli import render_plan

    here = _os.path.join(
        _os.path.dirname(_os.path.abspath(__file__)), "tests", "ref_golden"
    )
    cases = json.load(open(_os.path.join(here, "cases.json")))[::9]
    s = Server()
    s.alter(open(_os.path.join(here, "schema.txt")).read())
    for rdf in ("triples.rdf", "triples_facets.rdf"):
        t = s.new_txn()
        t.mutate_rdf(
            set_rdf=open(_os.path.join(here, rdf)).read(),
            commit_now=True,
        )

    def data_bytes(d):
        raw = getattr(d, "raw", None)
        return (
            bytes(raw)
            if raw is not None
            else json.dumps(d, sort_keys=True).encode()
        )

    checked = planned = 0
    for case in cases:
        q = case["query"]
        try:
            plain = data_bytes(s.query(q, want="raw")["data"])
        except Exception:
            continue  # error queries covered by tests/test_explain.py
        res = s.query(q, want="raw", debug=True)
        assert data_bytes(res["data"]) == plain, case["id"]
        plan = res["extensions"]["plan"]
        assert isinstance(plan["nodes"], list), case["id"]
        checked += 1
        planned += bool(plan["nodes"])
    assert checked >= 30, f"only {checked} smoke cases executed"
    # one rendered-plan snapshot: the renderer's contract lines
    res = s.query(
        "{ q(func: has(name)) { name friend { uid } } }", debug=True
    )
    out = render_plan(res["extensions"]["plan"])
    assert out.startswith("Query plan (wall "), out
    assert "\n  plan cache: " in out and "\n  admission: " in out, out
    assert "friend level=1 [batched]" in out, out
    print(
        json.dumps(
            {
                "explain_sanity": "OK",
                "cases_checked": checked,
                "cases_with_plan_nodes": planned,
            }
        )
    )


def _plan_sanity():
    """The ~5s CI gate for the cost-based planner + result cache
    (tools/check.sh --plan-sanity): planner on/off AND result-cache
    off/miss/hit byte-equality over the DQL golden smoke subset, with
    the decision counters asserted live."""
    import os as _os

    from dgraph_tpu.api.server import Server
    from dgraph_tpu.utils.observe import METRICS

    here = _os.path.join(
        _os.path.dirname(_os.path.abspath(__file__)), "tests", "ref_golden"
    )
    cases = json.load(open(_os.path.join(here, "cases.json")))[::9]
    s = Server()
    s.alter(open(_os.path.join(here, "schema.txt")).read())
    for rdf in ("triples.rdf", "triples_facets.rdf"):
        t = s.new_txn()
        t.mutate_rdf(
            set_rdf=open(_os.path.join(here, rdf)).read(),
            commit_now=True,
        )

    def run(q):
        try:
            d = s.query(q, want="raw")["data"]
            raw = getattr(d, "raw", None)
            return (
                bytes(raw)
                if raw is not None
                else json.dumps(d, sort_keys=True).encode()
            )
        except Exception as exc:
            return f"{type(exc).__name__}: {exc}"

    def with_env(q, **env):
        from dgraph_tpu.x import config as _config

        saved = {k: _config.get_raw(k) for k in env}
        for k, v in env.items():
            _config.set_env(k, v)
        try:
            return run(q)
        finally:
            for k, old in saved.items():
                if old is None:
                    _config.unset_env(k)
                else:
                    _config.set_env(k, old)

    r0 = METRICS.value("planner_reorders_total")
    h0 = METRICS.value("result_cache_hit_total")
    checked = 0
    for case in cases:
        q = case["query"]
        base = with_env(q, QUERY_PLANNER=0, RESULT_CACHE_SIZE=0)
        planner_on = with_env(q, QUERY_PLANNER=1, RESULT_CACHE_SIZE=0)
        assert planner_on == base, f"planner changed bytes: {case['id']}"
        first = with_env(q, RESULT_CACHE_SIZE=4096)
        second = with_env(q, RESULT_CACHE_SIZE=4096)  # the HIT
        assert first == base and second == base, (
            f"result cache changed bytes: {case['id']}"
        )
        checked += 1
    assert checked >= 30, f"only {checked} smoke cases executed"
    reorders = METRICS.value("planner_reorders_total") - r0
    hits = METRICS.value("result_cache_hit_total") - h0
    assert reorders > 0, "planner never reordered over the smoke subset"
    assert hits > 0, "result cache never hit over the smoke subset"
    print(
        json.dumps(
            {
                "plan_sanity": "OK",
                "cases_checked": checked,
                "planner_reorders": int(reorders),
                "result_cache_hits": int(hits),
            }
        )
    )


def _obs_sanity():
    """The ~5s CI gate for the flight recorder (tools/check.sh
    --obs-sanity): recorder on/off byte-equality over the DQL golden
    smoke subset, with the digest store and metrics history asserted
    live on the recorder-on arm."""
    import os as _os

    from dgraph_tpu.api.server import Server
    from dgraph_tpu.serving.digest import DIGESTS
    from dgraph_tpu.utils import observe
    from dgraph_tpu.x import config as _config

    here = _os.path.join(
        _os.path.dirname(_os.path.abspath(__file__)), "tests", "ref_golden"
    )
    cases = json.load(open(_os.path.join(here, "cases.json")))[::9]
    s = Server()
    s.alter(open(_os.path.join(here, "schema.txt")).read())
    for rdf in ("triples.rdf", "triples_facets.rdf"):
        t = s.new_txn()
        t.mutate_rdf(
            set_rdf=open(_os.path.join(here, rdf)).read(),
            commit_now=True,
        )

    def run(q):
        try:
            d = s.query(q, want="raw")["data"]
            raw = getattr(d, "raw", None)
            return (
                bytes(raw)
                if raw is not None
                else json.dumps(d, sort_keys=True).encode()
            )
        except Exception as exc:
            return f"{type(exc).__name__}: {exc}"

    def with_env(q, **env):
        saved = {k: _config.get_raw(k) for k in env}
        for k, v in env.items():
            _config.set_env(k, v)
        try:
            return run(q)
        finally:
            for k, old in saved.items():
                if old is None:
                    _config.unset_env(k)
                else:
                    _config.set_env(k, old)

    DIGESTS.reset()
    observe.HISTORY.reset()
    checked = 0
    for case in cases:
        q = case["query"]
        off = with_env(q, DIGEST=0, HISTORY=0)
        on = with_env(q, DIGEST=1, HISTORY=1)
        assert on == off, f"flight recorder changed bytes: {case['id']}"
        checked += 1
    assert checked >= 30, f"only {checked} smoke cases executed"
    digests = DIGESTS.snapshot()
    assert digests, "recorder-on arm recorded no digests"
    calls = sum(r["calls"] for r in digests)
    # one history snapshot on demand proves the ring's record path works
    # without waiting out the sampler interval
    saved = _config.get_raw("HISTORY")
    _config.set_env("HISTORY", 1)
    try:
        observe.HISTORY.record_now()
        observe.HISTORY.record_now()
    finally:
        if saved is None:
            _config.unset_env("HISTORY")
        else:
            _config.set_env("HISTORY", saved)
    hist = observe.HISTORY.report(window_s=60.0)
    assert hist["samples"] >= 2, hist
    print(
        json.dumps(
            {
                "obs_sanity": "OK",
                "cases_checked": checked,
                "digest_shapes": len(digests),
                "digest_calls": int(calls),
                "history_samples": hist["samples"],
            }
        )
    )


if __name__ == "__main__":
    if "--explain-sanity" in sys.argv:
        _explain_sanity()
    elif "--plan-sanity" in sys.argv:
        _plan_sanity()
    elif "--obs-sanity" in sys.argv:
        _obs_sanity()
    elif "--write-sanity" in sys.argv:
        # mixed read/write smoke incl. the columnar batch-apply arm
        # check (delegates to the loadgen's gate; host-path only)
        from dgraph_tpu.devsetup import maybe_force_cpu

        maybe_force_cpu()
        from benchmarks import qps_loadgen

        sys.exit(qps_loadgen.main(["--write-sanity"]))
    elif "--chaos-only" in sys.argv:
        # host-only capture: no device involved in the RPC plane
        _bench_chaos("cpu")
    elif "--fanout-only" in sys.argv:
        # query-engine-only capture: no device probe (the executor's
        # dispatcher handles backend fallback itself)
        from dgraph_tpu.devsetup import maybe_force_cpu

        maybe_force_cpu()
        import jax as _jax

        _bench_fanout(_jax.default_backend())
    elif "--encode-only" in sys.argv or "--encode-sanity" in sys.argv:
        # encoder-path capture (BENCH_ENCODE.json); host-path only
        from dgraph_tpu.devsetup import maybe_force_cpu

        maybe_force_cpu()
        import jax as _jax

        _bench_encode(
            _jax.default_backend(),
            sanity="--encode-sanity" in sys.argv,
        )
    elif "--vector-only" in sys.argv or "--vector-sanity" in sys.argv:
        # quantized-vector-engine capture (BENCH_VECTOR.json); host-path
        from dgraph_tpu.devsetup import maybe_force_cpu

        maybe_force_cpu()
        import jax as _jax

        _bench_vector(
            _jax.default_backend(),
            sanity="--vector-sanity" in sys.argv,
        )
    elif "--obs-only" in sys.argv:
        # tracing-overhead capture (BENCH_OBS.json); host-path only
        from dgraph_tpu.devsetup import maybe_force_cpu

        maybe_force_cpu()
        import jax as _jax

        _bench_obs(_jax.default_backend())
    else:
        main()

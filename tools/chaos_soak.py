#!/usr/bin/env python
"""Cluster chaos soak for the resilient read plane.

Runs a SEEDED randomized fault schedule against a real multi-OS-process
ProcCluster under the bank + query mix and asserts the three promises
the read plane makes:

  correctness    every response sampled from the default (follower-
                 routed) path is byte-identical to a leader-routed
                 control replay of the same query at the same pinned
                 read_ts (DGRAPH_TPU_FOLLOWER_READS=0), and the bank
                 ledger is exact — sum conserved always, per-account
                 equality when no transfer ack was ambiguous.

  availability   with the group leader SIGKILLed mid-workload,
                 watermark reads keep answering (served by verified
                 followers during the leaderless window); the gap until
                 the first successful read is measured and bounded.

  honesty        nothing surfaces as a non-retryable error: every
                 failure seen by the driver is a timeout, a retryable
                 RPC error, or a degraded-but-correct response.

Fault phases (long mode): baseline, leader SIGKILL + respawn, an
asymmetric partition (coordinator->follower blocked, raft plane up),
a delay-lagged follower (the EWMA routes around it), and a live tablet
move under traffic. Sanity mode trims to baseline + leader kill +
recovery and finishes in seconds — tier-1 and `tools/check.sh
--read-chaos-sanity` run exactly that slice.

    python tools/chaos_soak.py --sanity          # fixed-seed CI slice
    python tools/chaos_soak.py --long            # full schedule,
                                                 # stamps BENCH_CHAOS.json

Every per-phase row carries the follower-read / breaker / retry-budget
counters, so a regression in routing shows up as a counter delta even
when the asserts still pass.
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from dgraph_tpu.conn import faults  # noqa: E402
from dgraph_tpu.conn.faults import FaultPlan  # noqa: E402
from dgraph_tpu.utils.observe import METRICS  # noqa: E402

N_ACCOUNTS = 8
START_BAL = 100

# the counters every phase row reports (acceptance: follower-read /
# breaker / retry-budget counters in every row)
ROW_COUNTERS = (
    "follower_reads_total",
    "leaderless_reads_total",
    "follower_read_stale_skips_total",
    "read_breaker_open_total",
    "read_breaker_close_total",
    "read_breaker_probe_total",
    "read_retry_budget_exhausted_total",
    "hedge_fired_total",
    "hedge_skipped_saturated_total",
    "degraded_queries_total",
)

RETRYABLE = (TimeoutError,)


def _retryable(exc) -> bool:
    """The honesty gate: an error the driver sees must be one a client
    is allowed to retry."""
    from dgraph_tpu.conn.rpc import RpcError

    if isinstance(exc, RETRYABLE):
        return True
    if getattr(exc, "retryable", False):
        return True
    # group-unavailable / exhausted-rotation reads are retryable by
    # contract: the response would have been degraded, never wrong
    return isinstance(exc, RpcError)


def _counters():
    return {k: int(METRICS.value(k)) for k in ROW_COUNTERS}


def _delta(before, after):
    return {k: after[k] - before[k] for k in before}


class Soak:
    def __init__(self, seed: int, sanity: bool):
        import numpy as np

        from dgraph_tpu.worker.harness import ProcCluster

        self.seed = seed
        self.sanity = sanity
        self.rng = np.random.default_rng(seed)
        self.n_groups = 1 if sanity else 2
        self.cluster = ProcCluster(
            n_groups=self.n_groups, replicas=3,
            replicated_zero=False,
        )
        self.ledger = {}
        self.ambiguous = 0
        self.transfers_ok = 0
        self.queries_ok = 0
        self.queries_degraded = 0
        self.queries_failed = 0
        self.identity_checked = 0
        self.deferred = []  # (query, ts, baseline_bytes) awaiting control
        self.rows = []
        self.failures = []

    # -- workload ---------------------------------------------------------

    def seed_data(self):
        c = self.cluster
        c.alter(
            "bal: int @upsert .\n"
            "acct: string @index(exact) @upsert .\n"
            "mv: string @index(exact) ."
        )
        rdf = []
        for i in range(1, N_ACCOUNTS + 1):
            rdf.append(f'<0x{i:x}> <acct> "a{i}" .')
            rdf.append(f'<0x{i:x}> <bal> "{START_BAL}"^^<xs:int> .')
            rdf.append(f'<0x{i:x}> <mv> "m{i}" .')
        c.new_txn().mutate_rdf(set_rdf="\n".join(rdf), commit_now=True)
        self.ledger = {i: START_BAL for i in range(1, N_ACCOUNTS + 1)}

    def transfer(self):
        frm, to = (
            int(x) + 1
            for x in self.rng.choice(N_ACCOUNTS, 2, replace=False)
        )
        amt = int(self.rng.integers(1, 20))
        t = self.cluster.new_txn()
        try:
            t.mutate_rdf(
                set_rdf=(
                    f'<0x{frm:x}> <bal> "{self.ledger[frm] - amt}"'
                    f"^^<xs:int> .\n"
                    f'<0x{to:x}> <bal> "{self.ledger[to] + amt}"'
                    f"^^<xs:int> ."
                ),
                commit_now=True,
            )
            self.ledger[frm] -= amt
            self.ledger[to] += amt
            self.transfers_ok += 1
        except Exception as e:
            if not _retryable(e):
                self.failures.append(
                    f"non-retryable transfer error: {type(e).__name__}: {e}"
                )
            self.ambiguous += 1  # may or may not have applied

    QUERIES = (
        "{ q(func: has(bal)) { uid bal } }",
        '{ q(func: eq(acct, "a3")) { acct bal } }',
        "{ q(func: has(mv)) { uid mv } }",
    )

    def query_once(self, identity: bool, timeout_s: float = 8.0):
        """One read at the pinned snapshot watermark. With `identity`,
        the response is also queued for a leader-routed control replay
        at the SAME ts (byte-identity proof obligation)."""
        c = self.cluster
        q = self.QUERIES[int(self.rng.integers(0, len(self.QUERIES)))]
        wm = c._snapshot_ts
        try:
            out = c.query(q, read_ts=wm, timeout_s=timeout_s)
        except Exception as e:
            if not _retryable(e):
                self.failures.append(
                    f"non-retryable query error: {type(e).__name__}: {e}"
                )
            self.queries_failed += 1
            return None
        ext = out.get("extensions", {})
        if ext.get("degraded"):
            self.queries_degraded += 1
            # degraded=True means PARTIAL (unreachable group) — never
            # identity-check those; "leaderless" responses are complete
            # and must pass the identity check like any other
            if ext["degraded"] is True:
                return out
        self.queries_ok += 1
        if identity:
            blob = json.dumps(out["data"], sort_keys=True)
            self.deferred.append((q, wm, blob))
        return out

    def replay_controls(self):
        """Leader-routed control replay of every deferred sample: same
        query, same pinned ts, FOLLOWER_READS off — the bytes must
        match what the default path served earlier. Run while the
        cluster is healthy (controls need a leader)."""
        c = self.cluster
        pending, self.deferred = self.deferred, []
        os.environ["DGRAPH_TPU_FOLLOWER_READS"] = "0"
        try:
            for q, ts, blob in pending:
                control = c.query(q, read_ts=ts, timeout_s=15.0)
                cblob = json.dumps(control["data"], sort_keys=True)
                if cblob != blob:
                    self.failures.append(
                        f"BYTE MISMATCH at ts={ts} for {q!r}:\n"
                        f"  default: {blob[:400]}\n"
                        f"  control: {cblob[:400]}"
                    )
                self.identity_checked += 1
        finally:
            os.environ["DGRAPH_TPU_FOLLOWER_READS"] = "1"

    def check_ledger(self):
        out = self.cluster.query("{ q(func: has(bal)) { uid bal } }",
                                 timeout_s=20.0)
        ext = out.get("extensions", {})
        if ext.get("degraded") is True:
            return  # partial view: sum check would be vacuous
        bals = {int(x["uid"], 16): x["bal"] for x in out["data"]["q"]}
        total = sum(bals.values())
        if total != N_ACCOUNTS * START_BAL:
            self.failures.append(
                f"LEDGER SUM BROKEN: {total} != {N_ACCOUNTS * START_BAL} "
                f"({bals})"
            )
        if self.ambiguous == 0 and bals != self.ledger:
            self.failures.append(
                f"LEDGER DRIFT with zero ambiguous acks: "
                f"{bals} != {self.ledger}"
            )

    # -- phases -----------------------------------------------------------

    def run_phase(self, name, steps, setup=None, teardown=None,
                  extra=None):
        t0 = time.perf_counter()
        before = _counters()
        info = {}
        if setup is not None:
            info.update(setup() or {})
        try:
            for step in range(steps):
                self.transfer()
                self.query_once(identity=(step % 2 == 0))
                if step % 5 == 4:
                    self.check_ledger()
        finally:
            if teardown is not None:
                info.update(teardown() or {})
        if extra is not None:
            info.update(extra() or {})
        row = {
            "phase": name,
            "steps": steps,
            "wall_s": round(time.perf_counter() - t0, 3),
            "counters": _delta(before, _counters()),
            **info,
        }
        self.rows.append(row)
        print(f"  [{name}] {json.dumps(row['counters'])}", flush=True)
        return row

    def _group1_leader_nid(self):
        c = self.cluster
        g = c.remote_groups[1]
        lead = g.leader_addr(timeout=10.0)
        if lead is None:
            return None
        for nid, cfg in c._cfgs.items():
            if tuple(cfg["rpc_addr"]) == tuple(lead):
                return nid
        return None

    def phase_leader_kill(self, steps):
        """SIGKILL group 1's leader mid-workload; watermark reads must
        keep answering from verified followers, and the first-success
        gap is bounded (breaker probe + discovery, plus CI slack)."""
        c = self.cluster
        killed = {"nid": None, "gap_s": None}

        def setup():
            nid = self._group1_leader_nid()
            assert nid is not None, "no leader to kill"
            # quiesce writes briefly: leader heartbeats carry the commit
            # index, so after ~2 rounds the followers have APPLIED the
            # floor and a health sweep proves it — only then can the
            # election window itself be follower-served
            time.sleep(0.7)
            self.query_once(identity=False)  # warms picker health rows
            c.kill(nid)
            killed["nid"] = nid
            # availability gap: time to the first successful read after
            # the kill (leaderless window included — followers serve)
            t0 = time.perf_counter()
            deadline = t0 + 30.0
            while time.perf_counter() < deadline:
                out = self.query_once(identity=False, timeout_s=5.0)
                if out is not None:
                    killed["gap_s"] = round(time.perf_counter() - t0, 3)
                    break
            if killed["gap_s"] is None:
                self.failures.append(
                    "reads never recovered within 30s of leader SIGKILL"
                )
            return {"killed_nid": killed["nid"]}

        def teardown():
            c.restart(killed["nid"])
            c._wait_healthy(timeout=90.0)
            return {"availability_gap_s": killed["gap_s"]}

        row = self.run_phase("leader_kill", steps, setup, teardown)
        # correctness obligation: the window actually exercised the
        # follower path (otherwise this phase proved nothing)
        served = (row["counters"]["follower_reads_total"]
                  + row["counters"]["leaderless_reads_total"])
        if served <= 0:
            self.failures.append(
                "leader_kill phase served no follower/leaderless reads "
                f"— counters: {row['counters']}"
            )
        return row

    def phase_asym_partition(self, steps):
        """Block coordinator->follower traffic for ONE follower of
        group 1 (its raft plane stays up, so it keeps applying). The
        breaker must open and route reads around it."""
        c = self.cluster
        g = c.remote_groups[1]
        state = {}

        def setup():
            lead = g.leader_addr(timeout=10.0)
            followers = [a for a in g.addrs if a != lead]
            victim = followers[0]
            plan = faults.active() or faults.install(
                FaultPlan(seed=self.seed)
            )
            plan.partition(victim, direction="to")
            state["victim"] = victim
            return {"partitioned": f"{victim[0]}:{victim[1]}"}

        def teardown():
            plan = faults.active()
            if plan is not None:
                plan.heal()
            return {}

        return self.run_phase("asym_partition", steps, setup, teardown)

    def phase_lagged_follower(self, steps):
        """Delay every RPC to one follower of group 1 by ~40ms: the
        latency EWMA must steer reads to the healthy replicas (the
        hedge pays the lag at most once per plan)."""
        c = self.cluster
        g = c.remote_groups[1]

        def setup():
            lead = g.leader_addr(timeout=10.0)
            followers = [a for a in g.addrs if a != lead]
            victim = followers[-1]
            faults.reset()
            faults.install(FaultPlan(seed=self.seed + 1, rules=[
                dict(point="send", action="delay", p=1.0, delay_ms=40,
                     peer=victim),
            ]))
            return {"lagged": f"{victim[0]}:{victim[1]}"}

        def teardown():
            faults.reset()
            return {}

        return self.run_phase("lagged_follower", steps, setup, teardown)

    def phase_live_move(self, steps):
        """Move the `mv` tablet to the other group mid-workload: the
        copy/delta stream is leader-only by contract; queries keep
        answering through the fence + flip."""
        c = self.cluster
        src = c.zero.belongs_to("mv")
        dst = 2 if src == 1 else 1
        state = {}

        def setup():
            import threading

            def mover():
                try:
                    c.move_tablet("mv", dst)
                    state["moved"] = True
                except Exception as e:
                    state["move_error"] = f"{type(e).__name__}: {e}"

            th = threading.Thread(target=mover, daemon=True)
            th.start()
            state["thread"] = th
            return {"move": f"mv: g{src} -> g{dst}"}

        def teardown():
            state["thread"].join(timeout=60.0)
            if state["thread"].is_alive():
                self.failures.append("tablet move hung past 60s")
            elif "move_error" in state:
                self.failures.append(
                    f"tablet move failed: {state['move_error']}"
                )
            elif c.zero.belongs_to("mv") != dst:
                self.failures.append("tablet map never flipped to dst")
            return {"move_done": state.get("moved", False)}

        return self.run_phase("live_move", steps, setup, teardown)

    # -- driver -----------------------------------------------------------

    def run(self):
        c = self.cluster
        try:
            self.seed_data()
            base_steps = 6 if self.sanity else 25
            self.run_phase("baseline", base_steps)
            self.replay_controls()

            self.phase_leader_kill(4 if self.sanity else 20)
            self.replay_controls()  # healthy again: controls valid now

            if not self.sanity:
                self.phase_asym_partition(20)
                self.replay_controls()
                self.phase_lagged_follower(20)
                self.replay_controls()
                self.phase_live_move(25)
                self.replay_controls()

            self.run_phase("recovery", 4 if self.sanity else 10)
            self.replay_controls()
            self.check_ledger()
        finally:
            faults.reset()
            c.close()
        if self.identity_checked == 0:
            self.failures.append("identity check never ran")
        return {
            "seed": self.seed,
            "mode": "sanity" if self.sanity else "long",
            "groups": self.n_groups,
            "replicas": 3,
            "phases": self.rows,
            "transfers_ok": self.transfers_ok,
            "transfers_ambiguous": self.ambiguous,
            "queries_ok": self.queries_ok,
            "queries_degraded": self.queries_degraded,
            "queries_failed": self.queries_failed,
            "identity_checked": self.identity_checked,
            "failures": self.failures,
        }


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--sanity", action="store_true",
                    help="short fixed-seed slice (tier-1 / check.sh)")
    ap.add_argument("--long", action="store_true",
                    help="full schedule, stamps BENCH_CHAOS.json")
    ap.add_argument("--seed", type=int, default=1234)
    ap.add_argument("--out", default=os.path.join(REPO, "BENCH_CHAOS.json"))
    args = ap.parse_args()
    if not (args.sanity or args.long):
        args.sanity = True

    # the soak drives follower routing explicitly; pin the knobs so the
    # run is self-describing regardless of ambient env
    os.environ["DGRAPH_TPU_FOLLOWER_READS"] = "1"

    t0 = time.perf_counter()
    result = Soak(args.seed, sanity=args.sanity).run()
    result["wall_s"] = round(time.perf_counter() - t0, 2)

    print(json.dumps(
        {k: v for k, v in result.items() if k != "phases"}, indent=2
    ))
    if args.long:
        from benchmarks import stamp

        try:
            existing = json.load(open(args.out))
            existing.pop("provenance", None)
        except Exception:
            existing = {}
        existing["soak"] = result
        wrote = stamp.guarded_write(args.out, existing, "cpu")
        print(f"chaos_soak: stamped {wrote}")

    if result["failures"]:
        print("chaos_soak: FAILURES:", file=sys.stderr)
        for f in result["failures"]:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print(
        f"chaos_soak: PASS ({result['mode']}, "
        f"{result['identity_checked']} identity checks, "
        f"{result['queries_ok']} queries, "
        f"{result['transfers_ok']} transfers, "
        f"{result['wall_s']}s)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env bash
# One-shot verification gate: static analysis + tests.
#
#   tools/check.sh          lint + analyzer/registry tests + smoke subset
#   tools/check.sh --full   lint + the FULL tier-1 suite (same command the
#                           ROADMAP pins for tier-1 verify)
#   tools/check.sh --ops-sanity
#                           the ~5s ops-plane gate alone: backup/restore
#                           crash-consistency + CDC ordering/replay
#                           (tests/test_ops_plane.py)
#   tools/check.sh --plan-sanity
#                           the ~5s planner/result-reuse gate alone:
#                           planner on/off + result-cache off/miss/hit
#                           byte-equality over the golden smoke subset
#                           (bench.py --plan-sanity)
#   tools/check.sh --obs-sanity
#                           the ~5s flight-recorder gate alone: digest +
#                           history on/off byte-equality over the golden
#                           smoke subset, digest store and history ring
#                           asserted live (bench.py --obs-sanity)
#   tools/check.sh --read-chaos-sanity
#                           the read-plane chaos gate alone: fixed-seed
#                           chaos soak slice — leader SIGKILL under the
#                           bank + query mix, follower-served responses
#                           byte-checked against a leader-routed control
#                           replay (tools/chaos_soak.py --sanity)
#   tools/check.sh --race-sanity
#                           GIL-fuzz race slice (~30s): re-runs the
#                           fixed-seed concurrency suites (group commit,
#                           apply shards, follower reads, serving front,
#                           native-thread stress) with
#                           DGRAPH_TPU_RACE_FUZZ=1, which pins
#                           sys.setswitchinterval(1e-6) so latent
#                           Python-level races surface deterministically
#   tools/check.sh --san-matrix
#                           the full sanitizer matrix (SLOW: recompiles
#                           the native library 3x and re-runs whole
#                           corpora): UBSan + ASan over the byte-equality
#                           corpus, TSan over the threaded kernel stress
#                           corpus, plus the seeded-defect proofs that
#                           each sanitizer actually detects its class
#                           (tests/test_native_san.py)
#
# Exit code is nonzero on the first failing stage, so CI can consume it
# directly. JAX is pinned to CPU: the gate must never dial an accelerator.

set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

if [[ "${1:-}" == "--ops-sanity" ]]; then
    echo "== ops-plane sanity (~5s): backup/restore crash consistency + CDC =="
    python -m pytest tests/test_ops_plane.py -q -p no:cacheprovider
    echo "check.sh: ops-sanity passed"
    exit 0
fi

if [[ "${1:-}" == "--plan-sanity" ]]; then
    echo "== planner/result-reuse sanity (~5s): A/B byte-equality =="
    python bench.py --plan-sanity
    echo "check.sh: plan-sanity passed"
    exit 0
fi

if [[ "${1:-}" == "--obs-sanity" ]]; then
    echo "== flight-recorder sanity (~5s): digest/history A/B byte-equality =="
    python bench.py --obs-sanity
    echo "check.sh: obs-sanity passed"
    exit 0
fi

if [[ "${1:-}" == "--read-chaos-sanity" ]]; then
    echo "== read-plane chaos sanity: leader kill + byte-identity replay =="
    python tools/chaos_soak.py --sanity
    echo "check.sh: read-chaos-sanity passed"
    exit 0
fi

if [[ "${1:-}" == "--race-sanity" ]]; then
    echo "== GIL-fuzz race slice (~30s): switchinterval=1e-6 concurrency suites =="
    DGRAPH_TPU_RACE_FUZZ=1 python -m pytest \
        tests/test_group_commit.py tests/test_batch_apply.py \
        tests/test_follower_reads.py tests/test_serving_front.py \
        tests/test_native_threads.py \
        -q -m 'not slow' -p no:cacheprovider
    echo "check.sh: race-sanity passed"
    exit 0
fi

if [[ "${1:-}" == "--san-matrix" ]]; then
    echo "== sanitizer matrix (slow): ubsan + asan corpus, tsan threaded =="
    python -m pytest tests/test_native_san.py -q -m slow \
        -p no:cacheprovider
    echo "check.sh: san-matrix passed"
    exit 0
fi

# analyzers FIRST: a registry violation (undeclared metric/config, new
# allowlist entry) must fail in seconds, before lint and long before the
# smoke subset or the ~5s sanity gates get a chance to run
echo "== analyzer + config-registry self-tests =="
python -m pytest tests/test_static_analysis.py -q -p no:cacheprovider

echo "== dgraph-tpu lint =="
python -m dgraph_tpu.cli lint

if [[ "${1:-}" == "--full" ]]; then
    echo "== full tier-1 suite =="
    python -m pytest tests/ -q -m 'not slow' \
        --continue-on-collection-errors \
        -p no:cacheprovider -p no:xdist -p no:randomly
else
    echo "== tier-1 smoke subset =="
    python -m pytest \
        tests/test_setops.py tests/test_uidpack.py \
        tests/test_packed_setops.py tests/test_bitmap_setops.py \
        tests/test_posting.py \
        tests/test_storage.py tests/test_raft.py \
        tests/test_replicated_zero.py tests/test_cluster_facade.py \
        tests/test_tablet_move.py \
        tests/test_observability.py tests/test_distributed_tracing.py \
        tests/test_serving_front.py \
        tests/test_stream_encoder.py \
        tests/test_vector_quant.py \
        tests/test_group_commit.py \
        tests/test_batch_apply.py \
        tests/test_explain.py tests/test_telemetry.py \
        tests/test_planner.py \
        tests/test_ops_plane.py \
        tests/test_follower_reads.py \
        -q -p no:cacheprovider

    echo "== proc-shard chaos smoke: worker SIGKILL + respawn, ledger exact =="
    python -m pytest tests/test_batch_apply.py -q -m chaos \
        -p no:cacheprovider

    echo "== read-plane chaos sanity: leader kill + byte-identity replay =="
    python tools/chaos_soak.py --sanity

    echo "== explain sanity (~5s) =="
    python bench.py --explain-sanity

    echo "== planner/result-reuse sanity (~5s) =="
    python bench.py --plan-sanity

    echo "== flight-recorder sanity (~5s) =="
    python bench.py --obs-sanity

    echo "== qps loadgen sanity (~5s) =="
    python benchmarks/qps_loadgen.py --sanity

    echo "== qps loadgen write sanity (~5s) =="
    python benchmarks/qps_loadgen.py --write-sanity

    echo "== encode microbench sanity (~5s) =="
    python bench.py --encode-sanity

    echo "== vector engine sanity (~5s) =="
    python bench.py --vector-sanity
fi

echo "check.sh: all stages passed"
